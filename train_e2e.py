"""Fed-path end-to-end training artifact driver (``TRAIN_E2E_r{N}.json``).

The one composition ``bench.py`` never proves: the FULL ``Trainer`` —
``workloads/imagenet.main``, the reference's flagship path
(``TensorFlow_imagenet/src/resnet_main.py:282-307``) — fed from a REAL
record pipeline at bench batch size, with eval every epoch, a mid-run
checkpoint+resume (fit is invoked twice; the second run must continue from
the first's checkpoint, not restart), and the per-epoch metrics JSONL.

Data is the deterministic 4096-image synthetic-JPEG TFRecord shard set
(``data/bench_data.py``, reference converter schema) consumed through the
decode-once uint8 raw cache (``data/raw_cache.py``) — the input pipeline
that actually feeds a v5e from a weak host (``BENCH_DATA_r04.json``).

Prints ONE JSON line and writes it to ``TRAIN_E2E_r{round}.json``:
fed images/sec per epoch, the staged-consume ceiling it should approach on
a real TPU-VM, final train/eval metrics, and the resume evidence.

Labels are synthetic (1 + i mod 1000 over random JPEGs), so accuracy only
measures that the label plumbing learns SOMETHING (train top-1 must move
off the 0.001 floor by memorization); convergence quality is
``tests/test_convergence.py``'s job on real 3-class data.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--round", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--epochs", type=int, default=3,
                    help="total epochs; the first runs in invocation 1, "
                    "the rest resume in invocation 2")
    ap.add_argument("--train-images", type=int, default=4096)
    ap.add_argument("--val-images", type=int, default=512)
    ap.add_argument("--data-dir", default=None,
                    help="shard location (default: ~/.cache/ddlt/bench-shards)")
    ap.add_argument("--out", default=None,
                    help="artifact path (default TRAIN_E2E_r{round:02d}.json)")
    ap.add_argument("--keep-workdir", action="store_true")
    args = ap.parse_args()

    from distributeddeeplearning_tpu.data.bench_data import (
        ensure_bench_shards,
        generate_bench_shards,
    )
    from distributeddeeplearning_tpu.workloads.imagenet import main as train_main

    train_dir = ensure_bench_shards(
        args.data_dir, num_images=args.train_images, num_shards=8
    )
    val_dir = os.path.join(os.path.dirname(train_dir), "bench-shards-val")
    generate_bench_shards(
        val_dir, num_images=args.val_images, num_shards=2, split="validation"
    )

    work = tempfile.mkdtemp(prefix="ddlt-e2e-")
    ckpt = os.path.join(work, "ckpt")
    jsonl = os.path.join(work, "metrics.jsonl")
    steps_per_epoch = args.train_images // args.batch_size
    common = dict(
        model="resnet50",
        data_format="tfrecords",
        input_pipeline="raw",
        training_data_path=train_dir,
        validation_data_path=val_dir,
        batch_size=args.batch_size,
        train_images=args.train_images,
        steps_per_epoch=steps_per_epoch,
        warmup_epochs=1,
        save_filepath=ckpt,
        metrics_path=jsonl,
        checkpoint_every_steps=max(steps_per_epoch // 2, 1),  # mid-epoch saves
        seed=42,
    )

    # Invocation 1: first epoch, then "the job dies".
    state1, fit1 = train_main(epochs=1, resume=False, **common)
    steps_after_1 = int(state1.step)

    # Invocation 2: same config, more epochs — MUST resume, not restart.
    state2, fit2 = train_main(epochs=args.epochs, resume=True, **common)
    steps_after_2 = int(state2.step)
    resumed = steps_after_2 == args.epochs * steps_per_epoch and (
        fit2.epochs_run == args.epochs - 1
    )

    rows = []
    with open(jsonl) as f:
        for line in f:
            if line.strip():
                rows.append(json.loads(line))
    epoch_rows = [r for r in rows if "images_per_second" in r]
    steady = [
        r["images_per_second"]
        for r in epoch_rows
        if not r.get("includes_compile")
    ] or [r["images_per_second"] for r in epoch_rows]
    fed_img_sec = sorted(steady)[len(steady) // 2]

    result = {
        "metric": "resnet50_e2e_fed_train_img_sec",
        "value": round(fed_img_sec, 1),
        "unit": "img/sec",
        "vs_baseline": None,
        "round": args.round,
        "harness": (
            "python train_e2e.py — full Trainer.fit (workloads/imagenet.main),"
            " tfrecords->raw-cache pipeline, eval every epoch, two invocations"
            " with checkpoint+resume between them"
        ),
        "batch_size": args.batch_size,
        "steps_per_epoch": steps_per_epoch,
        "epochs_total": args.epochs,
        "resume_proof": {
            "steps_after_first_invocation": steps_after_1,
            "steps_after_second_invocation": steps_after_2,
            "epochs_run_in_second_invocation": fit2.epochs_run,
            "resumed_not_restarted": resumed,
        },
        "final_train_metrics": {
            k: float(v) for k, v in (fit2.final_train_metrics or {}).items()
        },
        "final_eval_metrics": {
            k: float(v) for k, v in (fit2.final_eval_metrics or {}).items()
        },
        "per_epoch_images_per_second": [
            round(r["images_per_second"], 1) for r in epoch_rows
        ],
        "staged_consume_ceiling_note": (
            "BENCH_DATA r04/r05: the same step consumes pre-staged raw-cache "
            "batches at ~2,500 img/s/chip and the host produces at ~4,700; "
            "on this dev box the fed rate is additionally throttled by the "
            "tunneled TPU backend serializing H2D transfers with queued "
            "compute (~10x step blowup, measured r4) — on a real TPU-VM "
            "(local PCIe DMA) the host produce rate is the binding limit"
        ),
        "labels_note": "synthetic labels (1+i mod 1000); accuracy proves "
        "plumbing/memorization, not convergence (see tests/test_convergence)",
    }
    if not resumed:
        result["error"] = "second invocation did not resume from checkpoint"
    out = args.out or f"TRAIN_E2E_r{args.round:02d}.json"
    with open(out, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))
    if not args.keep_workdir:
        shutil.rmtree(work, ignore_errors=True)
    return 0 if resumed else 1


if __name__ == "__main__":
    sys.exit(main())
