#!/usr/bin/env bash
# Per-worker TPU-VM environment setup.
#
# The role of the reference's AML image build (conda env from
# environment_gpu.yml + base MPI/CUDA image, aml_compute.py:354-393): turn a
# fresh TPU VM into a worker that can run ddlt workloads.  Invoked on every
# worker by `ddlt tpu ssh --worker all 'bash ~/ddlt/envs/setup-tpu-vm.sh'`
# or automatically after `ddlt tpu bootstrap`.
set -euo pipefail

DDLT_DIR="${DDLT_DIR:-$HOME/ddlt}"

python3 -m pip install -q --upgrade pip
python3 -m pip install -q -r "$DDLT_DIR/envs/requirements-tpu.txt"
python3 -m pip install -q -e "$DDLT_DIR"

# Sanity: every worker must see its local TPU chips.
python3 - <<'EOF'
import jax
print(f"worker {jax.process_index()}/{jax.process_count()}: "
      f"{jax.local_device_count()} local device(s): "
      f"{jax.local_devices()[0].device_kind}")
EOF
