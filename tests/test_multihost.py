"""Two-process ``jax.distributed`` CPU test of the multi-host seam.

CI's virtual 8-device mesh is single-process, so ``shard_batch``'s
``make_array_from_process_local_data`` branch (``parallel/sharding.py``),
``distributed.initialize``'s rendezvous branch (``parallel/distributed.py``),
and ``input_fn``'s per-host shard defaulting (``data/tfrecords.py``) never
execute there.  This test launches two real OS processes that rendezvous on
a local coordinator port and run those paths — the JAX-native analogue of a
2-rank mpirun (SURVEY.md §7 "Hard parts" (a)).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

WORKER = Path(__file__).parent / "multihost_worker.py"
WNIDS = ["n01440764", "n01443537", "n02102040"]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def tfrecord_dir(tmp_path_factory):
    from PIL import Image

    from distributeddeeplearning_tpu.data import convert_tfrecords

    root = tmp_path_factory.mktemp("mh-imagenet") / "train"
    rng = np.random.default_rng(0)
    for wnid in WNIDS:
        d = root / wnid
        d.mkdir(parents=True)
        for i in range(4):
            arr = rng.integers(0, 255, (48, 56, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"{wnid}_{i}.JPEG", quality=95)
    out = tmp_path_factory.mktemp("mh-tfrecords")
    n = convert_tfrecords.convert_dataset(str(root), str(out), "validation", 4)
    assert n == 12
    return out


@pytest.mark.slow
def test_two_process_rendezvous_shard_batch_and_file_sharding(tfrecord_dir):
    port = _free_port()
    nprocs, local_devices = 2, 2
    env = dict(os.environ)
    # The worker forces the CPU platform itself (jax.config) and appends its
    # own device-count flag; scrub any conflicting inherited setting.
    env.pop("JAX_PLATFORMS", None)
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    repo_root = str(Path(__file__).parent.parent)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo_root, env.get("PYTHONPATH", "")) if p
    )

    procs = [
        subprocess.Popen(
            [
                sys.executable,
                str(WORKER),
                str(port),
                str(pid),
                str(nprocs),
                str(local_devices),
                str(tfrecord_dir),
            ],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(nprocs)
    ]
    outputs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outputs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        for stage in ("rendezvous OK", "shard_batch OK", "host_file_sharding OK"):
            assert stage in out, f"worker {pid} missing stage {stage!r}:\n{out}"
    # Both processes assembled the identical global batch.
    fp = [
        line.split("fingerprint=")[1]
        for out in outputs
        for line in out.splitlines()
        if "fingerprint=" in line
    ]
    assert len(fp) == 2 and fp[0] == fp[1]
