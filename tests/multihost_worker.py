"""Subprocess worker for the two-process multi-host seam test.

Run as: python multihost_worker.py <coord_port> <process_id> <num_processes>
        <local_device_count> [tfrecord_dir]

Exercises, under a REAL two-process ``jax.distributed`` rendezvous on the
CPU backend (the regime CI's single-process virtual mesh cannot reach):

1. ``parallel.distributed.initialize``'s explicit-rendezvous branch;
2. ``parallel.sharding.shard_batch``'s
   ``jax.make_array_from_process_local_data`` path, with a position-weighted
   fingerprint so a wrong global row order fails, not just wrong values;
3. ``data.tfrecords.input_fn``'s shard defaulting from the process topology
   (the TPU-native ``dataset.shard(hvd.size(), hvd.rank())``): the two
   hosts' label multisets must be disjoint and union to the full dataset.

Prints one line per passed stage; the parent asserts on them.
"""

from __future__ import annotations

import os
import sys


def main() -> int:
    coord_port, pid, nprocs, local_devices = (
        sys.argv[1],
        int(sys.argv[2]),
        int(sys.argv[3]),
        int(sys.argv[4]),
    )
    tfrecord_dir = sys.argv[5] if len(sys.argv) > 5 else None

    flags = os.environ.get("XLA_FLAGS", "")
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={local_devices}".strip()
    )
    import jax

    # Env vars alone cannot unpin a site-configured hardware plugin; flip
    # the platform before the first backend query (tests/conftest.py recipe).
    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from distributeddeeplearning_tpu.parallel import MeshSpec, create_mesh
    from distributeddeeplearning_tpu.parallel.distributed import initialize
    from distributeddeeplearning_tpu.parallel.sharding import (
        replicated,
        shard_batch,
    )

    ctx = initialize(
        coordinator_address=f"127.0.0.1:{coord_port}",
        num_processes=nprocs,
        process_id=pid,
        force=True,
    )
    assert ctx.process_count == nprocs, ctx
    assert ctx.local_device_count == local_devices, ctx
    print(f"WORKER {pid} STAGE rendezvous OK", flush=True)

    mesh = create_mesh(MeshSpec())
    n_global = mesh.devices.size
    assert n_global == nprocs * local_devices
    global_batch = 2 * n_global
    full = np.arange(global_batch * 3, dtype=np.float32).reshape(global_batch, 3)
    per_host = global_batch // nprocs
    local = full[pid * per_host : (pid + 1) * per_host]

    batch = shard_batch(mesh, {"x": local})
    leaf = batch["x"]
    assert leaf.shape == (global_batch, 3), leaf.shape

    import jax.numpy as jnp

    def fingerprint(b):
        # position-dependent weights: permuted global row order changes the sum
        w = (jnp.arange(global_batch, dtype=jnp.float32) + 1.0)[:, None]
        return (b["x"] * w).sum()

    got = float(jax.jit(fingerprint, out_shardings=replicated(mesh))(batch))
    expected = float(
        (full * (np.arange(global_batch, dtype=np.float32) + 1.0)[:, None]).sum()
    )
    assert abs(got - expected) <= 1e-3 * abs(expected), (got, expected)
    print(f"WORKER {pid} STAGE shard_batch OK fingerprint={got}", flush=True)

    if tfrecord_dir:
        from jax.experimental import multihost_utils

        from distributeddeeplearning_tpu.data import tfrecords

        # No explicit shard_count/shard_index: must default to the process
        # topology (data/tfrecords.py input_fn).
        labels = np.concatenate(
            [
                b["label"]
                for b in tfrecords.input_fn(
                    tfrecord_dir,
                    False,
                    batch_size=2,
                    num_shards=4,
                    image_size=32,
                    repeat=False,
                )
            ]
        )
        # Fixed-size exchange: each host's shard is 2 of 4 files = 6 records.
        assert labels.shape == (6,), labels.shape
        gathered = multihost_utils.process_allgather(labels)
        combined = sorted(np.asarray(gathered).reshape(-1).tolist())
        assert combined == sorted([1, 1, 1, 1, 2, 2, 2, 2, 3, 3, 3, 3]), combined
        mine = sorted(labels.tolist())
        other = sorted(
            np.asarray(gathered).reshape(nprocs, -1)[1 - pid].tolist()
        )
        assert mine != other or len(set(combined)) == 1
        print(f"WORKER {pid} STAGE host_file_sharding OK", flush=True)

    print(f"WORKER {pid} DONE", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
