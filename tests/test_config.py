import os

from distributeddeeplearning_tpu.config import (
    DEFAULTS,
    load_config,
    load_env,
    parse_env,
    set_key,
    str_to_bool,
    unset_key,
    write_env_template,
)


def test_parse_env_basics():
    text = """
# comment
FOO=bar
export BAZ=qux
QUOTED="hello world"
SINGLE='x y'
EMPTY=
SPACED =  padded
"""
    env = parse_env(text)
    assert env["FOO"] == "bar"
    assert env["BAZ"] == "qux"
    assert env["QUOTED"] == "hello world"
    assert env["SINGLE"] == "x y"
    assert env["EMPTY"] == ""
    assert env["SPACED"] == "padded"


def test_set_key_roundtrip(tmp_env):
    set_key(tmp_env, "A", "1")
    set_key(tmp_env, "B", "two words")
    set_key(tmp_env, "A", "2")
    env = load_env(tmp_env)
    assert env == {"A": "2", "B": "two words"}
    # In-place edit: file has exactly two assignments.
    assert tmp_env.read_text().count("=") == 2


def test_unset_key(tmp_env):
    set_key(tmp_env, "A", "1")
    set_key(tmp_env, "B", "2")
    unset_key(tmp_env, "A")
    assert load_env(tmp_env) == {"B": "2"}


def test_load_config_layering(tmp_env, monkeypatch):
    set_key(tmp_env, "TPU_NAME", "from-file")
    set_key(tmp_env, "GCS_BUCKET", "file-bucket")
    monkeypatch.setenv("GCS_BUCKET", "env-bucket")
    cfg = load_config(tmp_env, overrides={"epochs": 3})
    assert cfg.TPU_NAME == "from-file"  # file beats default
    assert cfg.GCS_BUCKET == "env-bucket"  # process env beats file
    assert cfg.get_int("EPOCHS") == 3  # override beats everything
    assert cfg.TPU_TYPE == DEFAULTS["TPU_TYPE"]  # default survives


def test_settings_persist_writes_back(tmp_env):
    cfg = load_config(tmp_env)
    cfg.persist("GCS_BUCKET", "discovered-bucket")
    assert load_env(tmp_env)["GCS_BUCKET"] == "discovered-bucket"
    cfg2 = load_config(tmp_env)
    assert cfg2.GCS_BUCKET == "discovered-bucket"


def test_write_env_template(tmp_path):
    path = tmp_path / ".env"
    write_env_template(path, gcp_project="proj-x")
    env = load_env(path)
    assert env["GCP_PROJECT"] == "proj-x"
    assert "TPU_TYPE" in env


def test_str_to_bool():
    assert str_to_bool("True") and str_to_bool("yes") and str_to_bool("1")
    assert not (str_to_bool("false") or str_to_bool("N") or str_to_bool("0"))
    try:
        str_to_bool("maybe")
        assert False
    except ValueError:
        pass


def test_get_bool_and_int_defaults(tmp_env):
    cfg = load_config(tmp_env)
    assert cfg.get_bool("DISTRIBUTED", default=False) is False
    assert cfg.get_int("FAKE_DATA_LENGTH", default=128) == 128


def test_quoted_value_roundtrip(tmp_env):
    # Backslashes and quotes must survive a save/load cycle unchanged.
    from distributeddeeplearning_tpu.config.env import load_env, set_key

    tricky = 'pa"ss\\word with spaces'
    set_key(tmp_env, "SECRET", tricky)
    assert load_env(tmp_env)["SECRET"] == tricky
    set_key(tmp_env, "SECRET", tricky)  # idempotent second save
    assert load_env(tmp_env)["SECRET"] == tricky


def test_persist_without_existing_env(tmp_path, monkeypatch):
    # persist() must write back even when no .env existed at load time.
    monkeypatch.chdir(tmp_path)
    from distributeddeeplearning_tpu.config import load_config, load_env

    cfg = load_config()
    cfg.persist("GCS_BUCKET", "fresh-bucket")
    assert load_env(tmp_path / ".env")["GCS_BUCKET"] == "fresh-bucket"
