"""Fleet-scale observability (ISSUE 11): distributed tracing, mergeable
metrics, the crash flight recorder and the SLO layer.

Covers the OBS_FLEET contract:

- ``Histogram.merge`` is EXACT: merged percentiles property-tested
  against numpy on the concatenated raw samples across skewed
  distributions, bucket-identical to a single histogram over the
  concatenation, and merge-order invariant;
- registry states round-trip/merge (counters add, gauges resolve by
  freshness) and every snapshot row carries process identity;
- trace shards export with per-process pids and merge onto the router
  clock within tolerance under synthetic skew, with the failover chain
  ORDERED in the merged timeline;
- the flight recorder stays on with the tracer disabled, is bounded,
  and dumps on quarantine / watchdog / (fleet test) replica death;
- SLO parse/evaluate pass + violation cases;
- OBS_FLEET schema rejection cases (anonymous per-replica rows, missing
  failover evidence);
- one real 2-replica chaos fleet end-to-end through
  ``obs.fleet.observe_fleet`` — the in-process half of the bench gate.
"""

import itertools
import json
import time

import numpy as np
import pytest

from distributeddeeplearning_tpu.obs import fleet as obs_fleet
from distributeddeeplearning_tpu.obs import recorder as recorder_mod
from distributeddeeplearning_tpu.obs.recorder import FlightRecorder
from distributeddeeplearning_tpu.obs.registry import (
    Histogram,
    MetricsRegistry,
    merge_states,
)
from distributeddeeplearning_tpu.obs.trace import Tracer


@pytest.fixture()
def fresh_recorder():
    """Isolate the process flight recorder; restore afterwards."""
    prior = recorder_mod._RECORDER
    rec = recorder_mod.set_recorder(FlightRecorder(capacity=64))
    yield rec
    recorder_mod.set_recorder(prior)


# --------------------------------------------------------------------------
# Histogram.merge: exactness, numpy property tests, order invariance
# --------------------------------------------------------------------------


_DISTRIBUTIONS = {
    "lognormal_heavy": np.random.default_rng(0).lognormal(0.0, 2.0, 6000),
    "uniform": np.random.default_rng(1).uniform(1e-4, 50.0, 6000),
    "bimodal_skew": np.concatenate([
        np.random.default_rng(2).exponential(0.001, 5000),
        np.random.default_rng(3).normal(100.0, 5.0, 200).clip(min=1.0),
    ]),
    "constant": np.full(777, 0.125),
}


@pytest.mark.parametrize("name", sorted(_DISTRIBUTIONS))
def test_merged_percentiles_match_numpy_on_concatenated_samples(name):
    """The property the fleet depends on: shard the samples across
    'workers', merge the sketches, and the percentiles must match numpy
    over the CONCATENATED raw samples as well as a single unsharded
    sketch does — merging loses nothing."""
    samples = _DISTRIBUTIONS[name]
    shards = np.array_split(samples, 5)
    merged = Histogram()
    for shard in shards:
        h = Histogram()
        h.record_many(shard)
        merged.merge(h)
    single = Histogram()
    single.record_many(samples)
    for q in (50, 90, 99):
        want = float(np.percentile(samples, q))
        got = merged.percentile(q)
        # the sketch's own 1% bound + interpolation-convention slack —
        # identical to what the UNSHARDED sketch is held to
        assert got == pytest.approx(want, rel=0.03), (name, q, got, want)
        assert got == single.percentile(q), (name, q)
    assert merged.count == single.count == len(samples)
    assert merged.max == pytest.approx(float(samples.max()))
    assert merged.mean == pytest.approx(float(samples.mean()), rel=1e-9)


def test_merge_is_bucket_exact_and_order_invariant():
    rng = np.random.default_rng(7)
    parts = [rng.lognormal(0.0, 1.5, 400) for _ in range(4)]
    hists = []
    for part in parts:
        h = Histogram()
        h.record_many(part)
        hists.append(h)
    single = Histogram()
    single.record_many(np.concatenate(parts))
    summaries = set()
    for perm in itertools.permutations(range(4)):
        merged = Histogram()
        for i in perm:
            merged.merge(hists[i])
        assert merged._buckets == single._buckets  # bucket-for-bucket
        summaries.add(json.dumps(merged.summary(), sort_keys=True))
    assert len(summaries) == 1  # every merge order: identical answer


def test_merge_refuses_mismatched_error_bounds():
    a, b = Histogram(max_rel_err=0.01), Histogram(max_rel_err=0.05)
    b.record(1.0)
    with pytest.raises(ValueError, match="error bounds"):
        a.merge(b)


def test_histogram_state_roundtrip_preserves_buckets_exactly():
    h = Histogram("ttft", max_rel_err=0.02)
    h.record_many([0.0, 1e-6, 0.5, 0.5, 3.25, 100.0])
    clone = Histogram.from_state(
        json.loads(json.dumps(h.state()))  # through the JSON wire
    )
    assert clone._buckets == h._buckets
    assert clone.summary() == h.summary()
    assert (clone.count, clone.total, clone.min, clone.max) == (
        h.count, h.total, h.min, h.max,
    )


def test_empty_histogram_state_roundtrip():
    clone = Histogram.from_state(Histogram("empty").state())
    assert clone.count == 0 and clone.summary()["p99"] == 0.0


# --------------------------------------------------------------------------
# registry: identity on rows, mergeable states
# --------------------------------------------------------------------------


def test_snapshot_rows_carry_process_identity(tmp_path):
    """The satellite: fleet JSONL streams must be attributable — every
    row carries pid, and replica identity once stamped."""
    import os

    reg = MetricsRegistry()
    reg.counter("c").inc()
    row = reg.snapshot()
    assert row["pid"] == os.getpid()
    assert "replica_id" not in row  # unstamped single-process registry
    reg.set_identity(replica_id=3, process_name="replica-3")
    path = str(tmp_path / "obs.jsonl")
    assert reg.write_snapshot(path)
    written = json.loads(open(path).read())
    assert written["pid"] == os.getpid()
    assert written["replica_id"] == 3
    assert written["process"] == "replica-3"


def test_registry_states_merge_counters_gauges_histograms():
    a = MetricsRegistry(replica_id=0)
    b = MetricsRegistry(replica_id=1)
    a.counter("serve.requests").inc(3)
    b.counter("serve.requests").inc(5)
    a.gauge("occ").set(0.25)
    time.sleep(0.01)
    b.gauge("occ").set(0.75)  # fresher: must win either merge order
    a.histogram("serve.ttft_s").record_many([0.1, 0.2])
    b.histogram("serve.ttft_s").record_many([0.3, 0.4])
    for states in ([a.state(), b.state()], [b.state(), a.state()]):
        merged = merge_states(states)
        assert merged.counter("serve.requests").value == 8
        assert merged.gauge("occ").value == 0.75
        assert merged.histogram("serve.ttft_s").count == 4
        assert merged.histogram("serve.ttft_s").max == 0.4


def test_fleet_latency_reads_bucket_merged_histograms():
    # a fast busy replica and a small slow one: the merged p99 must see
    # the slow replica's tail (sorted rank 103 of 104 lands in the 9.0
    # block), where averaging per-replica p99s would answer ~4.5 — the
    # construction distinguishes bucket-merging from averaging
    a = MetricsRegistry()
    a.histogram(obs_fleet.TTFT_HISTOGRAM).record_many([0.1] * 100)
    b = MetricsRegistry()
    b.histogram(obs_fleet.TTFT_HISTOGRAM).record_many([9.0] * 4)
    merged = merge_states([a.state(), b.state()])
    lat = obs_fleet.fleet_latency(merged)
    assert lat["ttft_samples"] == 104
    assert lat["ttft_s"]["p99"] == pytest.approx(9.0, rel=0.05)
    assert lat["ttft_s"]["p50"] == pytest.approx(0.1, rel=0.05)


# --------------------------------------------------------------------------
# trace shards: derived pids, skew alignment, chain ordering
# --------------------------------------------------------------------------


def test_tracer_derives_pid_and_accepts_replica_naming():
    import os

    t = Tracer(enabled=True, annotate=False)
    assert t.pid == os.getpid()
    named = Tracer(
        enabled=True, annotate=False, pid=4242, process_name="replica-7",
    )
    with named.span("x"):
        pass
    exported = named.to_chrome_trace()
    meta = [e for e in exported["traceEvents"] if e.get("ph") == "M"]
    assert meta[0]["pid"] == 4242
    assert meta[0]["args"]["name"] == "replica-7"
    assert exported["metadata"]["host_pids"] == [4242]
    assert all(
        e["pid"] == 4242
        for e in exported["traceEvents"]
        if e.get("ph") == "X"
    )


def test_tracer_context_stamps_every_span_and_event():
    t = Tracer(enabled=True, annotate=False).set_context(replica=3)
    with t.span("s", uid="r1"):
        pass
    t.event("e")
    for ev in t.events:
        assert ev["args"]["replica"] == 3
    assert t.events[0]["args"]["uid"] == "r1"  # explicit args kept


def _synthetic_shard(pid, name, epoch_unix_s, events):
    return {
        "traceEvents": [
            {"ph": "M", "name": "process_name", "pid": pid,
             "args": {"name": name}},
            *events,
        ],
        "metadata": {
            "tracer_epoch_unix_s": epoch_unix_s,
            "host_pids": [pid],
            "process_name": name,
        },
    }


def test_shards_with_known_skew_land_on_router_clock():
    """The satellite pin: worker shards whose perf-counter epochs are
    skewed by known amounts must land within tolerance on the router
    clock after the merge (epoch alignment), and a handshake offset
    must override the epoch estimate when provided."""
    router = _synthetic_shard(10, "router", 1000.0, [
        {"ph": "i", "s": "t", "name": "fleet/drain_begin", "pid": 10,
         "tid": 1, "ts": 0.0, "args": {}},
    ])
    # worker epoch 2.5s after the router's: a local ts of 1000µs is
    # really at router-time 2.501s
    w1 = _synthetic_shard(20, "replica-0", 1002.5, [
        {"ph": "X", "name": "serve/decode_step", "pid": 20, "tid": 1,
         "ts": 1000.0, "dur": 5.0, "args": {}},
    ])
    merged = obs_fleet.merge_fleet_trace(router, [w1])
    ev = next(
        e for e in merged["traceEvents"]
        if e.get("name") == "serve/decode_step"
    )
    assert ev["ts"] == pytest.approx(2.5e6 + 1000.0, abs=1.0)
    assert merged["metadata"]["shards"][0]["offset_source"] == "epoch"
    # explicit handshake estimate wins over the epoch difference
    merged2 = obs_fleet.merge_fleet_trace(
        router, [w1], offsets_us={20: 7.0e6},
    )
    ev2 = next(
        e for e in merged2["traceEvents"]
        if e.get("name") == "serve/decode_step"
    )
    assert ev2["ts"] == pytest.approx(7.0e6 + 1000.0, abs=1.0)
    assert merged2["metadata"]["shards"][0]["offset_source"] == "handshake"


def test_colliding_shard_pids_are_remapped_to_distinct_tracks():
    """The satellite fix: two exports sharing a pid must NOT interleave
    into one track after the merge."""
    router = _synthetic_shard(10, "router", 1000.0, [])
    w1 = _synthetic_shard(10, "replica-0", 1000.0, [  # colliding pid!
        {"ph": "X", "name": "serve/a", "pid": 10, "tid": 1,
         "ts": 1.0, "dur": 1.0, "args": {}},
    ])
    w2 = _synthetic_shard(10, "replica-1", 1000.0, [
        {"ph": "X", "name": "serve/b", "pid": 10, "tid": 1,
         "ts": 1.0, "dur": 1.0, "args": {}},
    ])
    merged = obs_fleet.merge_fleet_trace(router, [w1, w2])
    a = next(e for e in merged["traceEvents"] if e.get("name") == "serve/a")
    b = next(e for e in merged["traceEvents"] if e.get("name") == "serve/b")
    assert a["pid"] != 10 and b["pid"] != 10  # neither stole the router's
    assert a["pid"] != b["pid"]               # nor each other's
    assert len(set(merged["metadata"]["host_pids"])) == 3


def test_failover_chain_appears_ordered_after_alignment():
    """Worker clocks skewed such that RAW timestamps would order the
    survivor's completion BEFORE the death — after alignment the chain
    reads admit -> died -> requeued -> completion, and the checker
    recognizes the full failover shape."""
    tid = "tr0003"
    router = _synthetic_shard(10, "router", 1000.0, [
        {"ph": "i", "s": "t", "name": "fleet/replica_died", "pid": 10,
         "tid": 1, "ts": 3.0e6, "args": {"trace_ids": [tid]}},
        {"ph": "i", "s": "t", "name": "fleet/request_requeued", "pid": 10,
         "tid": 1, "ts": 3.1e6, "args": {"trace": tid}},
    ])
    # dying replica: served the request 1.5s in (router clock) — its
    # local ts is only 0.5e6 because its epoch is 1s later
    dying = _synthetic_shard(20, "replica-0", 1001.0, [
        {"ph": "X", "name": "serve/admit", "pid": 20, "tid": 1,
         "ts": 0.5e6, "dur": 10.0, "args": {"trace": tid}},
    ])
    # survivor: completes at router-time 3.5s; raw local ts 1.0e6 would
    # sort BEFORE the death without alignment
    survivor = _synthetic_shard(30, "replica-1", 1002.5, [
        {"ph": "i", "s": "t", "name": "serve/request_complete", "pid": 30,
         "tid": 1, "ts": 1.0e6, "args": {"trace": tid}},
    ])
    merged = obs_fleet.merge_fleet_trace(router, [dying, survivor])
    chains = obs_fleet.failover_chains(merged, [tid])
    chain = chains[tid]
    assert [e["name"] for e in chain] == [
        "serve/admit", "fleet/replica_died", "fleet/request_requeued",
        "serve/request_complete",
    ]
    verdict = obs_fleet.check_failover_chain(chain)
    assert verdict["ok"]
    assert verdict["served_on_pid_before_death"] == [20]
    assert verdict["completed_on_pid"] == 30
    # and the negative: without the death the shape is NOT a failover
    no_death = [e for e in chain if e["name"] != "fleet/replica_died"]
    assert not obs_fleet.check_failover_chain(no_death)["ok"]


# --------------------------------------------------------------------------
# flight recorder
# --------------------------------------------------------------------------


def test_recorder_is_bounded_and_survives_disabled_tracer(fresh_recorder):
    tracer = Tracer(
        enabled=False, annotate=False, recorder=fresh_recorder,
    )
    for i in range(200):  # capacity is 64: the ring must stay bounded
        with tracer.span("serve/decode_step", step=i):
            pass
    tracer.event("serve/request_complete", uid="r1")
    assert tracer.events == []  # the TRACER recorded nothing...
    assert len(fresh_recorder) == 64  # ...the black box everything recent
    entries = fresh_recorder.entries()
    assert entries[-1]["name"] == "serve/request_complete"
    assert entries[-1]["kind"] == "event"
    assert all(e["kind"] in ("span", "event") for e in entries)
    assert fresh_recorder.records_total == 201


def test_recorder_captures_metric_deltas(fresh_recorder):
    reg = MetricsRegistry()
    reg.counter("serve.errors").inc()
    reg.gauge("serve.tokens_per_sec").set(42.0)
    kinds = [(e["kind"], e["name"]) for e in fresh_recorder.entries()]
    assert ("metric", "serve.errors") in kinds
    assert ("metric", "serve.tokens_per_sec") in kinds
    metric = [
        e for e in fresh_recorder.entries()
        if e["name"] == "serve.tokens_per_sec"
    ][0]
    assert metric["value"] == 42.0


def test_recorder_dump_freezes_ring_and_attaches_metrics(fresh_recorder):
    reg = MetricsRegistry()
    reg.counter("c").inc(2)
    fresh_recorder.record_event("serve/request_quarantined", "serve")
    dump = fresh_recorder.dump("decode_quarantine", registry=reg, uid="r9")
    assert dump["reason"] == "decode_quarantine"
    assert dump["uid"] == "r9"
    assert dump["metrics"]["counters"]["c"] == 2
    assert any(
        e["name"] == "serve/request_quarantined" for e in dump["entries"]
    )
    assert fresh_recorder.dumps == [dump]
    drained = fresh_recorder.drain_dumps()
    assert drained == [dump] and fresh_recorder.dumps == []


def test_scheduler_feeds_latency_histograms_per_completion():
    """The registry's TTFT/TPOT buckets are written as each request
    finishes — NOT in an end-of-run rollup — so a fleet worker killed
    mid-run has already recorded (and shipped) every completion.  Exactly
    one sample per completed request: a second end-of-run pass would
    double-count."""
    from distributeddeeplearning_tpu.obs import registry as registry_mod
    from distributeddeeplearning_tpu.serve.scheduler import (
        ContinuousBatchingScheduler,
        Request,
    )

    class _Engine:
        batch_slots = 2
        max_seq = 64
        chunked_prefill = False
        prefill_compiles = 0

        def prefill(self, slot, prompt):
            return 1

        def decode(self, tokens, pos):
            return np.full(2, 2, np.int32)

    prior = registry_mod.get_registry()
    reg = registry_mod.set_registry(registry_mod.MetricsRegistry())
    try:
        reqs = [Request(uid=f"r{i}", prompt=[1, 2]) for i in range(5)]
        ContinuousBatchingScheduler(_Engine(), max_new_tokens=4).run(reqs)
        assert reg.histogram("serve.ttft_s").count == 5
        assert reg.histogram("serve.tpot_s").count == 5
    finally:
        registry_mod.set_registry(prior)


def test_quarantine_triggers_recorder_dump(fresh_recorder):
    """The scheduler's NaN quarantine is a flight-recorder trigger: the
    dump lands even with the tracer fully disabled."""
    from distributeddeeplearning_tpu.serve.scheduler import (
        ContinuousBatchingScheduler,
        Request,
    )

    class _NanEngine:
        batch_slots = 2
        max_seq = 64
        chunked_prefill = False
        prefill_compiles = 0

        def __init__(self):
            self.steps = 0
            self.last_finite = np.ones(2, bool)

        def prefill(self, slot, prompt):
            return 1

        def decode(self, tokens, pos):
            self.steps += 1
            self.last_finite = (
                np.array([False, True])
                if self.steps == 2 else np.ones(2, bool)
            )
            return np.full(2, 2, np.int32)

    reqs = [Request(uid=f"r{i}", prompt=[1, 2]) for i in range(2)]
    results, report = ContinuousBatchingScheduler(
        _NanEngine(), max_new_tokens=4
    ).run(reqs)
    assert report.quarantined == 1
    dumps = [
        d for d in fresh_recorder.dumps
        if d["reason"] == "decode_quarantine"
    ]
    assert len(dumps) == 1
    assert dumps[0]["step"] == 2


def test_watchdog_fire_triggers_recorder_dump(fresh_recorder):
    from distributeddeeplearning_tpu.train.resilience import StepWatchdog

    import io

    fired = []
    wd = StepWatchdog(
        0.1, on_timeout=lambda: fired.append(True), poll_s=0.02,
        stream=io.StringIO(),
    ).start()
    try:
        wd.tick(7)
        deadline = time.monotonic() + 5.0
        while not fired and time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        wd.stop()
    assert fired
    dumps = [
        d for d in fresh_recorder.dumps if d["reason"] == "watchdog_fired"
    ]
    assert len(dumps) == 1
    assert dumps[0]["step"] == 7


def test_injected_faults_land_in_recorder_ring(fresh_recorder):
    from distributeddeeplearning_tpu.utils import faults as faults_mod

    plan = faults_mod.FaultPlan(faults_mod.parse_spec("decode_stall@1:secs=0"))
    assert plan.take_decode_stall(1) == 0.0
    names = [e["name"] for e in fresh_recorder.entries()]
    assert "fault/decode_stall" in names


# --------------------------------------------------------------------------
# SLO spec
# --------------------------------------------------------------------------


def test_slo_parse_roundtrip_and_rejects_unknown_keys():
    slo = obs_fleet.SLOSpec.parse(
        "ttft_p99_s=2.0,tpot_p99_s=0.5,max_error_rate=0.01,"
        "max_lost_requests=0"
    )
    assert slo.ttft_p99_s == 2.0 and slo.max_lost_requests == 0
    assert obs_fleet.SLOSpec.parse(slo.describe()) == slo
    with pytest.raises(ValueError, match="unknown SLO key"):
        obs_fleet.SLOSpec.parse("p99=1.0")
    with pytest.raises(ValueError, match="key=value"):
        obs_fleet.SLOSpec.parse("ttft_p99_s")


def test_slo_evaluate_pass_and_violations():
    slo = obs_fleet.SLOSpec(
        ttft_p99_s=1.0, tpot_p99_s=0.2, max_error_rate=0.0,
        max_lost_requests=0,
    )
    latency = {
        "ttft_s": {"p99": 0.8}, "tpot_s": {"p99": 0.1},
        "ttft_samples": 10, "tpot_samples": 10,
    }
    good = slo.evaluate(
        fleet_report={"requests": 10, "errors": 0, "lost_requests": 0},
        latency=latency,
    )
    assert good["pass"] and all(
        c["ok"] for c in good["criteria"].values()
    )
    assert set(good["criteria"]) == {
        "ttft_p99_s", "tpot_p99_s", "max_error_rate", "max_lost_requests",
    }
    # a latency breach, an error, a lost request: each flips its criterion
    bad = slo.evaluate(
        fleet_report={"requests": 10, "errors": 1, "lost_requests": 2},
        latency={**latency, "ttft_s": {"p99": 3.0}},
    )
    assert not bad["pass"]
    assert not bad["criteria"]["ttft_p99_s"]["ok"]
    assert not bad["criteria"]["max_error_rate"]["ok"]
    assert not bad["criteria"]["max_lost_requests"]["ok"]
    assert bad["criteria"]["tpot_p99_s"]["ok"]


def test_slo_with_no_samples_fails_latency_criteria_loudly():
    """Zero merged samples means the metric shipping broke — an SLO over
    a silent fleet must not read as met."""
    slo = obs_fleet.SLOSpec(ttft_p99_s=10.0)
    out = slo.evaluate(
        fleet_report={"requests": 5, "errors": 0, "lost_requests": 0},
        latency={"ttft_s": {"p99": 0.0}, "tpot_s": {}, "ttft_samples": 0,
                 "tpot_samples": 0},
    )
    assert not out["criteria"]["ttft_p99_s"]["ok"]


# --------------------------------------------------------------------------
# OBS_FLEET schema
# --------------------------------------------------------------------------


def test_obs_fleet_schema_rejects_anonymous_rows_and_missing_failover():
    from distributeddeeplearning_tpu.obs.schema import (
        SchemaError,
        validate_obs_fleet_payload,
    )

    with pytest.raises(SchemaError) as exc:
        validate_obs_fleet_payload({})
    assert "failover" in str(exc.value)

    base = json.load(open("OBS_FLEET_r14.json"))
    anonymous = json.loads(json.dumps(base))
    anonymous["per_replica_metrics"][0].pop("replica_id")
    with pytest.raises(SchemaError, match="ANONYMOUS"):
        validate_obs_fleet_payload(anonymous)

    no_chain = json.loads(json.dumps(base))
    for c in no_chain["failover"].values():
        c["ok"] = False
    with pytest.raises(SchemaError, match="no failover chain"):
        validate_obs_fleet_payload(no_chain)

    peaceful = json.loads(json.dumps(base))
    peaceful["fleet_report"]["replica_deaths"] = 0
    with pytest.raises(SchemaError, match="chaos run"):
        validate_obs_fleet_payload(peaceful)


def test_committed_obs_fleet_artifact_passes_merge_exactness():
    """Acceptance (b), against the COMMITTED artifact: the fleet
    percentile blocks must be exactly reproducible by re-merging the
    committed per-replica histogram buckets, in reversed order."""
    d = json.load(open("OBS_FLEET_r14.json"))
    recomputed = obs_fleet.fleet_latency(
        merge_states(list(reversed(d["per_replica_metrics"])))
    )
    assert recomputed == d["fleet_latency"]
    assert d["fleet_latency"]["ttft_samples"] > 0
    assert all(d["gates"].values())


# --------------------------------------------------------------------------
# lint registration (the CI/tooling satellite)
# --------------------------------------------------------------------------


def test_recorder_and_metric_ship_paths_are_registered_hot_regions():
    from distributeddeeplearning_tpu.analysis import host_sync
    from distributeddeeplearning_tpu.analysis.regions import get_region

    for name in (
        "obs-recorder-record",
        "obs-recorder-span-enter",
        "obs-recorder-span-exit",
        "fleet-worker-metrics-ship",
    ):
        region = get_region(name)
        assert region.sync_budget == 0  # zero DESIGNED syncs, enforced
        findings = host_sync.check_region(region)
        assert not findings, (name, findings)


# --------------------------------------------------------------------------
# the real thing: a 2-replica chaos fleet, observed end to end
# --------------------------------------------------------------------------


FLEET_MODEL = dict(num_layers=1, d_model=16, num_heads=2, d_ff=32,
                   vocab_size=97, max_len=32)


@pytest.mark.timeout(280)
def test_observe_fleet_end_to_end_chaos(tmp_path):
    """ISSUE 11 acceptance (test half): a 2-replica fleet through
    ``replica_death@3`` with tracing on — worker shards exported
    (including by the DYING replica), merged onto the router clock, the
    failover traceable under one trace id, fleet TTFT/TPOT bucket-merged
    with samples, per-replica states attributable, and flight-recorder
    dumps attached to the report."""
    import glob
    import os

    from distributeddeeplearning_tpu.serve import (
        ReplicaSpec,
        synthetic_requests,
    )

    spec = ReplicaSpec(
        model=FLEET_MODEL, seed=0, num_heads=2, batch_slots=2,
        max_seq=32, kv_layout="paged", page_size=8, prefill_chunk=8,
        max_new_tokens=8,
    )
    reqs = synthetic_requests(
        8, vocab_size=FLEET_MODEL["vocab_size"], max_prompt=10,
        rng=np.random.default_rng(0),
    )
    trace_dir = str(tmp_path / "fleet-trace")
    slo = obs_fleet.SLOSpec.parse(
        "ttft_p99_s=120,tpot_p99_s=30,max_error_rate=0,"
        "max_lost_requests=0"
    )
    view = obs_fleet.observe_fleet(
        spec, reqs, replicas=2, trace_dir=trace_dir,
        faults="replica_death@3", slo=slo,
    )
    report = view["fleet_report"]
    assert report.replica_deaths == 1
    assert report.lost_requests == 0
    assert sorted(r.uid for r in view["results"]) == sorted(
        r.uid for r in reqs
    )

    # every uid got a distinct trace id, minted at the router
    assert sorted(report.trace_ids) == sorted(r.uid for r in reqs)
    assert len(set(report.trace_ids.values())) == len(reqs)

    # shards: one per worker incarnation, INCLUDING the injected death's
    shards = glob.glob(os.path.join(trace_dir, "replica*.trace.json"))
    assert len(shards) >= 2
    assert os.path.exists(view["merged_trace_path"])

    # the failover is traceable end-to-end under one trace id
    assert view["failover"], "no requeued trace ids found"
    ok_chains = [t for t, c in view["failover"].items() if c["ok"]]
    assert ok_chains, view["failover"]
    chain = view["failover"][ok_chains[0]]["chain"]
    names = [e["name"] for e in chain]
    assert names.index("fleet/replica_died") < names.index(
        "fleet/request_requeued"
    ) < len(names) - 1 - names[::-1].index("serve/request_complete")

    # mergeable metrics: bucket-merged fleet latency with real samples,
    # exactly reproducible from the attributable per-replica states
    assert view["fleet_latency"]["ttft_samples"] == len(reqs)
    for row in view["per_replica_metrics"]:
        assert isinstance(row["pid"], int)
        assert isinstance(row["replica_id"], int)
    recomputed = obs_fleet.fleet_latency(
        merge_states(list(reversed(view["per_replica_metrics"])))
    )
    assert recomputed == view["fleet_latency"]
    assert report.fleet_latency == view["fleet_latency"]

    # flight recorder: the death dumped on BOTH sides of the boundary
    reasons = {d["reason"] for d in view["flight_recorder_dumps"]}
    assert "replica_death" in reasons            # router observed it
    assert "replica_death (injected)" in reasons  # worker froze its ring

    # SLO evaluated over the merged view
    assert view["slo"]["pass"], view["slo"]
    assert set(view["slo"]["criteria"]) == {
        "ttft_p99_s", "tpot_p99_s", "max_error_rate", "max_lost_requests",
    }


@pytest.mark.slow
@pytest.mark.timeout(280)
def test_bench_obs_fleet_smoke(tmp_path):
    """``bench.py --obs-fleet --small`` end to end: schema-valid
    OBS_FLEET artifact, all gates green, merged fleet trace on disk."""
    import os
    import subprocess
    import sys as _sys

    from distributeddeeplearning_tpu.obs.schema import validate_artifact

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    report = tmp_path / "OBS_FLEET_r98.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DDLT_FAULTS", None)
    proc = subprocess.run(
        [
            _sys.executable, os.path.join(repo, "bench.py"),
            "--obs-fleet", "--small",
            "--obs-fleet-requests", "8",
            "--obs-fleet-new-tokens", "6",
            "--report", str(report),
            "--trace-dir", str(tmp_path / "trace"),
        ],
        cwd=repo, env=env, capture_output=True, text=True, timeout=260,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    line = validate_artifact(str(report))
    assert line["bench_revision"] >= 14
    assert all(line["gates"].values())
    assert os.path.exists(tmp_path / "trace" / "fleet.trace.json")
