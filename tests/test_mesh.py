"""Mesh construction and geometry inference on the virtual 8-device pod."""

import jax
import pytest

from distributeddeeplearning_tpu.parallel.mesh import (
    AXIS_ORDER,
    MeshSpec,
    create_mesh,
    data_parallel_size,
    world_size,
)


def test_default_spec_is_full_data_parallel():
    mesh = create_mesh()
    assert mesh.shape["data"] == 8
    assert all(mesh.shape[a] == 1 for a in AXIS_ORDER if a != "data")


def test_world_size_matches_devices():
    mesh = create_mesh()
    assert world_size(mesh) == 8 == jax.device_count()


def test_explicit_axes():
    mesh = create_mesh(MeshSpec(data=2, tensor=4))
    assert mesh.shape["data"] == 2
    assert mesh.shape["tensor"] == 4
    assert data_parallel_size(mesh) == 2


def test_inferred_axis_absorbs_remainder():
    mesh = create_mesh(MeshSpec(tensor=2))  # data=None absorbs 4
    assert mesh.shape["data"] == 4
    assert mesh.shape["tensor"] == 2


def test_fsdp_counts_as_data_parallel():
    mesh = create_mesh(MeshSpec(data=2, fsdp=4))
    assert data_parallel_size(mesh) == 8


def test_mismatched_product_raises():
    with pytest.raises(ValueError):
        create_mesh(MeshSpec(data=3, tensor=4))


def test_two_free_axes_raise():
    with pytest.raises(ValueError):
        MeshSpec(data=None, fsdp=None).sizes(8)


def test_subset_of_devices():
    mesh = create_mesh(devices=jax.devices()[:4])
    assert world_size(mesh) == 4


class TestMultiSlice:
    """Multi-slice (DCN) mesh: data parallelism spans slices, everything
    else stays on each slice's ICI (the scaling-book multi-slice recipe)."""

    def test_slice_boundary_outermost_on_data(self):
        import jax
        import numpy as np

        from distributeddeeplearning_tpu.parallel.mesh import (
            AXIS_ORDER,
            MeshSpec,
            create_mesh,
        )

        mesh = create_mesh(MeshSpec(tensor=2), num_slices=2)
        assert mesh.shape["data"] == 4 and mesh.shape["tensor"] == 2
        devs = jax.devices()
        arr = mesh.devices
        data_pos = AXIS_ORDER.index("data")
        # first half of the data axis = slice 0's devices, second = slice 1
        first = set(
            d.id for d in np.take(arr, range(2), axis=data_pos).ravel()
        )
        second = set(
            d.id for d in np.take(arr, range(2, 4), axis=data_pos).ravel()
        )
        assert first == {d.id for d in devs[:4]}
        assert second == {d.id for d in devs[4:]}

    def test_training_step_runs_on_multislice_mesh(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        import optax

        from distributeddeeplearning_tpu.data.synthetic import synthetic_batch
        from distributeddeeplearning_tpu.models import get_model
        from distributeddeeplearning_tpu.parallel import (
            MeshSpec,
            create_mesh,
            shard_batch,
        )
        from distributeddeeplearning_tpu.train.state import (
            create_train_state,
            sgd_momentum,
        )
        from distributeddeeplearning_tpu.train.step import build_train_step

        mesh = create_mesh(MeshSpec(), num_slices=2)
        model = get_model("resnet18", num_classes=5, dtype=jnp.float32)
        tx = sgd_momentum(optax.constant_schedule(0.1))
        state = create_train_state(jax.random.key(0), model, (8, 32, 32, 3), tx)
        step = build_train_step(mesh, state, compute_dtype=jnp.float32)
        batch = shard_batch(mesh, synthetic_batch(16, (32, 32, 3), 5))
        state, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))

    def test_indivisible_data_axis_rejected(self):
        import pytest as _pytest

        from distributeddeeplearning_tpu.parallel.mesh import (
            MeshSpec,
            create_mesh,
        )

        with _pytest.raises(ValueError, match="num_slices"):
            create_mesh(MeshSpec(tensor=8), num_slices=2)  # data axis = 1
