"""Mesh construction and geometry inference on the virtual 8-device pod."""

import jax
import pytest

from distributeddeeplearning_tpu.parallel.mesh import (
    AXIS_ORDER,
    MeshSpec,
    create_mesh,
    data_parallel_size,
    world_size,
)


def test_default_spec_is_full_data_parallel():
    mesh = create_mesh()
    assert mesh.shape["data"] == 8
    assert all(mesh.shape[a] == 1 for a in AXIS_ORDER if a != "data")


def test_world_size_matches_devices():
    mesh = create_mesh()
    assert world_size(mesh) == 8 == jax.device_count()


def test_explicit_axes():
    mesh = create_mesh(MeshSpec(data=2, tensor=4))
    assert mesh.shape["data"] == 2
    assert mesh.shape["tensor"] == 4
    assert data_parallel_size(mesh) == 2


def test_inferred_axis_absorbs_remainder():
    mesh = create_mesh(MeshSpec(tensor=2))  # data=None absorbs 4
    assert mesh.shape["data"] == 4
    assert mesh.shape["tensor"] == 2


def test_fsdp_counts_as_data_parallel():
    mesh = create_mesh(MeshSpec(data=2, fsdp=4))
    assert data_parallel_size(mesh) == 8


def test_mismatched_product_raises():
    with pytest.raises(ValueError):
        create_mesh(MeshSpec(data=3, tensor=4))


def test_two_free_axes_raise():
    with pytest.raises(ValueError):
        MeshSpec(data=None, fsdp=None).sizes(8)


def test_subset_of_devices():
    mesh = create_mesh(devices=jax.devices()[:4])
    assert world_size(mesh) == 4
