"""Compiled-HLO collective signatures for every sharded mode.

VERDICT r03 #4: the multichip dryrun proves each sharded train step runs and
its loss decreases, but says nothing about the communication XLA actually
inserted — a sharding regression that silently replicates params (or
all-gathers activations every layer) still produces finite, decreasing loss
while multiplying ICI traffic.  These tests lower each mode's train step on
the 8-device CPU mesh (the partitioner is platform-independent), read the
compiled module's HLO, and pin the expected collective signature:

  DP          grad all-reduce(s) carrying >= the model's parameter bytes;
              no all-gather / reduce-scatter / all-to-all
  FSDP        param all-gather(s) in fwd/bwd + grad reduce-scatter(s)
  TP          activation all-reduces (row-parallel matmul outputs)
  ring SP     collective-permute k/v rotation (inside the scan while-loop)
  Ulysses SP  all-to-all head<->sequence re-sharding
  MoE EP      all-to-all expert dispatch/combine
  pipeline    collective-permute stage rotation

This is the strongest multi-chip evidence obtainable without hardware: the
communication *pattern* is compile-time; only its wall-clock cost needs real
ICI.  Complements ``__graft_entry__.dryrun_multichip`` (execution) and
``MULTICHIP_r*.json``.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearning_tpu.models import get_model
from distributeddeeplearning_tpu.parallel import MeshSpec, create_mesh, shard_batch
from distributeddeeplearning_tpu.parallel.sharding import (
    RULES_EP,
    RULES_FSDP,
    RULES_TP,
    model_logical_axes,
)
from distributeddeeplearning_tpu.train.state import create_train_state
from distributeddeeplearning_tpu.train.step import build_train_step

# ---------------------------------------------------------------------------
# HLO inspection helpers
# ---------------------------------------------------------------------------

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8": 1,
    "s64": 8, "s32": 4, "s16": 2, "s8": 1, "u64": 8, "u32": 4,
    "u16": 2, "u8": 1, "pred": 1,
}


def compiled_hlo(step, state, batch) -> str:
    return step.lower(state, batch).compile().as_text()


def _shape_bytes(shape: str) -> int:
    """Bytes of one HLO shape literal like ``f32[128,1001]`` or ``bf16[]``."""
    m = re.match(r"([a-z0-9]+)\[([\d,]*)\]", shape)
    if not m:
        return 0
    dtype, dims = m.groups()
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


def collective_ops(hlo: str, name: str):
    """All occurrences of a collective op with their result shapes.

    Matches both plain results (``f32[...] all-reduce(...)``) and tuple
    results (``(f32[...], f32[...]) all-reduce-start(...)``); returns a list
    of per-op byte counts.
    """
    out = []
    # op applications are " = <shape> opname(" in HLO text
    for m in re.finditer(
        rf"= (\([^)]*\)|[a-z0-9]+\[[\d,]*\](?:\{{[^}}]*\}})?) {name}[.\d]*\(",
        hlo,
    ):
        shapes = re.findall(r"[a-z0-9]+\[[\d,]*\]", m.group(1))
        out.append(sum(_shape_bytes(s) for s in shapes))
    return out


def param_bytes(state) -> int:
    return sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(state.params)
    )


# ---------------------------------------------------------------------------
# Mode builders (tiny shapes; mirror __graft_entry__.dryrun_multichip legs)
# ---------------------------------------------------------------------------

N_DEV = 8


def _resnet_leg(rules, mesh_spec):
    mesh = create_mesh(mesh_spec, devices=jax.devices()[:N_DEV])
    model = get_model("resnet18", num_classes=101, dtype=jnp.float32)
    tx = optax.sgd(0.1)
    state = create_train_state(jax.random.key(0), model, (2, 32, 32, 3), tx)
    step = build_train_step(
        mesh, state, compute_dtype=jnp.float32, rules=rules
    )
    rng = np.random.default_rng(0)
    batch = shard_batch(
        mesh,
        {
            "image": rng.standard_normal((2 * N_DEV, 32, 32, 3)).astype(
                np.float32
            ),
            "label": rng.integers(0, 101, (2 * N_DEV,)).astype(np.int32),
        },
    )
    return step, state, batch, mesh


def _bert_leg(mesh_spec, rules, *, attention_fn=None, num_experts=None,
              batch_rows=None):
    mesh = create_mesh(mesh_spec, devices=jax.devices()[:N_DEV])
    kwargs = dict(
        num_layers=2, hidden_size=64, num_heads=4, intermediate_size=128,
        vocab_size=211, num_classes=5, max_position_embeddings=32,
        dropout_rate=0.0, dtype=jnp.float32,
    )
    if attention_fn is not None:
        kwargs["attention_fn"] = attention_fn
    if num_experts is not None:
        kwargs["num_experts"] = num_experts
    model = get_model("bert-base", **kwargs)
    rows = batch_rows if batch_rows is not None else 2 * N_DEV
    tx = optax.sgd(0.1)
    axes = model_logical_axes(
        model, jax.random.key(0), np.zeros((rows, 16), np.int32), train=False
    )
    state = create_train_state(
        jax.random.key(0), model, (rows, 16), tx, input_dtype=jnp.int32
    )
    step = build_train_step(
        mesh, state, compute_dtype=jnp.float32, rules=rules, logical_axes=axes
    )
    rng = np.random.default_rng(0)
    batch = shard_batch(
        mesh,
        {
            "input": rng.integers(0, 211, (rows, 16)).astype(np.int32),
            "label": rng.integers(0, 5, (rows,)).astype(np.int32),
        },
    )
    return step, state, batch, mesh


# ---------------------------------------------------------------------------
# Signatures
# ---------------------------------------------------------------------------


def test_dp_emits_grad_allreduce_and_nothing_else():
    """Pure DP = Horovod semantics: the ONLY communication is the gradient
    (+metrics) all-reduce.  Its payload must cover every parameter byte —
    fewer means some grads never synchronized."""
    step, state, batch, _ = _resnet_leg([], MeshSpec())
    hlo = compiled_hlo(step, state, batch)
    ar = collective_ops(hlo, "all-reduce") + collective_ops(
        hlo, "all-reduce-start"
    )
    assert ar, "DP step compiled without any all-reduce"
    assert sum(ar) >= param_bytes(state), (
        f"all-reduce payload {sum(ar)} < param bytes {param_bytes(state)}"
    )
    # The partitioner may gather metric-sized tensors (e.g. the [B, classes]
    # logits, ~6KB) to compute replicated scalars — fine.  A PARAMETER-scale
    # all-gather would mean params were actually sharded: that is the
    # regression this test exists to catch.
    big_gathers = [
        b for b in collective_ops(hlo, "all-gather")
        if b > 0.01 * param_bytes(state)
    ]
    assert not big_gathers, (
        f"parameter-scale all-gather in DP step: {big_gathers} bytes"
    )
    assert not collective_ops(hlo, "reduce-scatter"), (
        "unexpected reduce-scatter in DP"
    )
    assert not collective_ops(hlo, "all-to-all"), "unexpected all-to-all in DP"


def test_fsdp_emits_allgather_and_sharded_grad_reduction():
    """ZeRO-3 layout: forward/backward all-gather the sharded params, and
    the gradient reduction keeps only each shard's slice.  The TPU backend
    emits that as ``reduce-scatter``; the CPU partitioner (this test's
    backend) lowers the SAME pattern as all-reduce + dynamic-slice — accept
    either spelling, require the pattern."""
    step, state, batch, _ = _resnet_leg(RULES_FSDP, MeshSpec(fsdp=N_DEV))
    hlo = compiled_hlo(step, state, batch)
    ag = collective_ops(hlo, "all-gather") + collective_ops(
        hlo, "all-gather-start"
    )
    assert ag, "FSDP step compiled without param all-gathers"
    rs = collective_ops(hlo, "reduce-scatter")
    ar = collective_ops(hlo, "all-reduce") + collective_ops(
        hlo, "all-reduce-start"
    )
    ds = collective_ops(hlo, "dynamic-slice")
    assert rs or (ar and ds), (
        "FSDP step compiled without a sharded gradient reduction "
        "(neither reduce-scatter nor all-reduce+dynamic-slice)"
    )


def test_tp_emits_activation_allreduces():
    """Megatron row-parallel outputs all-reduce activations per layer (fwd)
    and per layer again in bwd — strictly more all-reduce SITES than pure
    DP's single fused grad reduction."""
    step, state, batch, _ = _bert_leg(MeshSpec(tensor=N_DEV), RULES_TP)
    hlo = compiled_hlo(step, state, batch)
    ar = collective_ops(hlo, "all-reduce") + collective_ops(
        hlo, "all-reduce-start"
    )
    assert len(ar) >= 2, f"TP step emitted {len(ar)} all-reduce sites"


def test_ring_attention_emits_collective_permutes():
    """Ring SP rotates k/v via ppermute inside the scan loop."""
    from distributeddeeplearning_tpu.ops import make_ring_attention

    mesh = create_mesh(MeshSpec(seq=2), devices=jax.devices()[:N_DEV])
    step, state, batch, _ = _bert_leg(
        MeshSpec(seq=2), [],
        attention_fn=make_ring_attention(mesh), batch_rows=2 * (N_DEV // 2),
    )
    hlo = compiled_hlo(step, state, batch)
    cp = collective_ops(hlo, "collective-permute") + collective_ops(
        hlo, "collective-permute-start"
    )
    assert cp, "ring attention compiled without collective-permute"


def test_ulysses_emits_all_to_all():
    from distributeddeeplearning_tpu.ops import make_ulysses_attention

    mesh = create_mesh(MeshSpec(seq=2), devices=jax.devices()[:N_DEV])
    step, state, batch, _ = _bert_leg(
        MeshSpec(seq=2), [],
        attention_fn=make_ulysses_attention(mesh),
        batch_rows=2 * (N_DEV // 2),
    )
    hlo = compiled_hlo(step, state, batch)
    assert collective_ops(hlo, "all-to-all"), (
        "Ulysses attention compiled without all-to-all"
    )


def test_moe_expert_sharding_emits_cross_expert_collectives():
    """The MoE layer is GShard/Switch DENSE dispatch (one-hot einsums,
    ``models/moe.py``), so expert parallelism deliberately lowers to
    gather/reduce collectives over the ``expert`` axis rather than the
    gather-scatter all-to-all of token-routing implementations — assert
    that signature: all-gathers (expert-sharded weights / token resharding)
    plus strictly more all-reduce sites than the pure-DP single fused grad
    reduction.  (Explicit a2a coverage is Ulysses' test above.)"""
    step, state, batch, _ = _bert_leg(
        MeshSpec(expert=2), list(RULES_TP) + list(RULES_EP), num_experts=2,
        batch_rows=2 * (N_DEV // 2),
    )
    hlo = compiled_hlo(step, state, batch)
    ag = collective_ops(hlo, "all-gather")
    ar = collective_ops(hlo, "all-reduce") + collective_ops(
        hlo, "all-reduce-start"
    )
    assert ag, "expert-parallel MoE compiled without all-gathers"
    assert len(ar) >= 2, (
        f"expert-parallel MoE emitted only {len(ar)} all-reduce sites"
    )


def test_pipeline_emits_collective_permutes():
    """GPipe stage rotation moves microbatch activations with ppermute."""
    from distributeddeeplearning_tpu.ops.pipeline import pipeline_apply

    mesh = create_mesh(MeshSpec(pipe=2), devices=jax.devices()[:N_DEV])
    rng = np.random.default_rng(1)
    params = {
        "w": jnp.asarray(rng.standard_normal((2, 8, 8)), jnp.float32),
        "b": jnp.asarray(rng.standard_normal((2, 8)), jnp.float32),
    }
    x = jnp.asarray(rng.standard_normal((2 * N_DEV, 8)), jnp.float32)

    def stage(p, h):
        return h + jnp.tanh(h @ p["w"] + p["b"])

    fn = jax.jit(
        lambda p, h: pipeline_apply(stage, p, h, mesh=mesh, num_microbatches=2)
    )
    hlo = fn.lower(params, x).compile().as_text()
    cp = collective_ops(hlo, "collective-permute") + collective_ops(
        hlo, "collective-permute-start"
    )
    assert cp, "pipeline compiled without collective-permute rotation"


def test_causal_ring_lm_emits_collective_permutes():
    """The causal sequence-parallel decoder (round 4): the LM train step
    with ring attention (causal=True) must still compile to the ppermute
    k/v rotation — causality is masking, not a different communication
    pattern."""
    from distributeddeeplearning_tpu.models.pipelined_transformer import (
        forward,
        init_params,
        next_token_loss,
    )
    from distributeddeeplearning_tpu.ops import make_ring_attention
    from distributeddeeplearning_tpu.train.state import TrainState

    mesh = create_mesh(MeshSpec(seq=2), devices=jax.devices()[:N_DEV])
    ring_fn = make_ring_attention(mesh, causal=True)
    params = init_params(
        jax.random.key(0), num_layers=2, d_model=32, num_heads=2, d_ff=64,
        vocab_size=64, max_len=16,
    )

    def apply_fn(variables, tokens, train=True, mutable=None, rngs=None):
        logits = forward(
            variables["params"], tokens, num_heads=2, attention_fn=ring_fn
        )
        if mutable is not None:
            return logits, {}
        return logits

    tx = optax.sgd(0.1)
    state = TrainState(
        step=jnp.zeros((), jnp.int32), params=params,
        opt_state=tx.init(params), batch_stats={}, apply_fn=apply_fn, tx=tx,
    )
    step = build_train_step(
        mesh, state, compute_dtype=jnp.float32,
        loss_fn=lambda lg, lb, label_smoothing=0.0: next_token_loss(lg, lb),
        metrics_fn=lambda lg, lb, loss: {"loss": loss.astype(jnp.float32)},
    )
    rows = 2 * (N_DEV // 2)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 64, (rows, 16)).astype(np.int32)
    batch = shard_batch(mesh, {"input": toks, "label": toks})
    hlo = compiled_hlo(step, state, batch)
    cp = collective_ops(hlo, "collective-permute") + collective_ops(
        hlo, "collective-permute-start"
    )
    assert cp, "causal ring LM compiled without collective-permute"



_LM_LOGICAL_AXES = {
    "embed": ("vocab", None),
    "pos": None,
    "head": (None, "vocab"),
    "blocks": {
        "qkv": ("layers", None, "width"),
        "proj": ("layers", "width", None),
        "w_in": ("layers", None, "width"),
        "w_out": ("layers", "width", None),
        "ln1": ("layers", None),
        "ln2": ("layers", None),
    },
}


def _lm_step_hlo(mesh, forward_fn):
    """Compiled HLO of a full LM train step: shared scaffolding for the
    LM collective-signature tests (one copy of the TrainState / rules /
    logical-axes boilerplate; ``forward_fn(params, tokens)`` decides the
    parallel forward under test)."""
    from distributeddeeplearning_tpu.models.pipelined_transformer import (
        init_params,
        next_token_loss,
    )
    from distributeddeeplearning_tpu.train.state import TrainState

    params = init_params(
        jax.random.key(0), num_layers=2, d_model=32, num_heads=2, d_ff=64,
        vocab_size=64, max_len=16,
    )

    def apply_fn(variables, tokens, train=True, mutable=None, rngs=None):
        logits = forward_fn(variables["params"], tokens)
        if mutable is not None:
            return logits, {}
        return logits

    tx = optax.sgd(0.1)
    state = TrainState(
        step=jnp.zeros((), jnp.int32), params=params,
        opt_state=tx.init(params), batch_stats={}, apply_fn=apply_fn, tx=tx,
    )
    step = build_train_step(
        mesh, state, compute_dtype=jnp.float32,
        rules=[("layers", "pipe"), ("vocab", "fsdp"), ("width", "fsdp")],
        logical_axes=_LM_LOGICAL_AXES,
        loss_fn=lambda lg, lb, label_smoothing=0.0: next_token_loss(lg, lb),
        metrics_fn=lambda lg, lb, loss: {"loss": loss.astype(jnp.float32)},
    )
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 64, (2 * N_DEV, 16)).astype(np.int32)
    batch = shard_batch(mesh, {"input": toks, "label": toks})
    return compiled_hlo(step, state, batch)


def test_fsdp_lm_emits_param_allgathers():
    """--fsdp on the LM workload: sharded embed/head/FF params must be
    all-gathered for compute (ZeRO-3 signature) rather than silently
    replicated."""
    from distributeddeeplearning_tpu.models.pipelined_transformer import (
        forward,
    )

    mesh = create_mesh(MeshSpec(fsdp=N_DEV), devices=jax.devices()[:N_DEV])
    hlo = _lm_step_hlo(mesh, lambda p, t: forward(p, t, num_heads=2))
    ag = collective_ops(hlo, "all-gather") + collective_ops(
        hlo, "all-gather-start"
    )
    assert ag, "fsdp LM compiled without any param all-gather"


def test_zero3_pipeline_lm_emits_per_tick_gathers_and_grad_scatter():
    """pipe×fsdp with zero3_axis: the compiled step must contain weight
    all-gathers and the gather-transpose gradient reduce-scatter.  (A
    pipe×fsdp step WITHOUT zero3_axis also gathers at the shard_map
    boundary, so presence alone does not prove the per-tick path — the
    in-stage wiring itself is pinned by
    tests/test_pipelined_transformer.py::test_zero3_wires_param_partition
    and the math by ...::test_zero3_pipelined_matches_sequential; this
    test pins the end-to-end collective signature of the full train
    step.)"""
    from distributeddeeplearning_tpu.models.pipelined_transformer import (
        forward_pipelined,
    )

    mesh = create_mesh(
        MeshSpec(pipe=2, fsdp=2), devices=jax.devices()[:N_DEV]
    )
    hlo = _lm_step_hlo(
        mesh,
        lambda p, t: forward_pipelined(
            p, t, num_heads=2, mesh=mesh, num_microbatches=2,
            zero3_axis="fsdp",
        ),
    )
    ag = collective_ops(hlo, "all-gather") + collective_ops(
        hlo, "all-gather-start"
    )
    assert ag, "zero3 pipeline compiled without weight all-gathers"
    rs = collective_ops(hlo, "reduce-scatter") + collective_ops(
        hlo, "reduce-scatter-start"
    )
    assert rs, (
        "zero3 pipeline compiled without a gradient reduce-scatter "
        "(the all-gather transpose)"
    )
