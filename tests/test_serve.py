"""Serving subsystem (serve/): KV cache, engine, continuous batching.

The load-bearing guarantee is decode correctness: token-t logits from the
KV-cached decode path must match a fresh full-sequence forward at position
t — bit-for-bit the same math, different dataflow.  Everything else
(slot release/reuse, EOS, sharding) is exercised against that oracle.
"""

from __future__ import annotations

import io
import json
import sys
import types
from contextlib import redirect_stdout
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.models.pipelined_transformer import (
    forward,
    forward_decode,
    forward_prefill,
    init_params,
)
from distributeddeeplearning_tpu.parallel import MeshSpec, create_mesh
from distributeddeeplearning_tpu.serve import (
    ContinuousBatchingScheduler,
    InferenceEngine,
    Request,
    cache_bytes,
    init_cache,
    insert_sequence,
)

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

CFG = dict(num_layers=3, d_model=32, num_heads=4, d_ff=64, vocab_size=61,
           max_len=32)
HEADS = CFG["num_heads"]
HEAD_DIM = CFG["d_model"] // HEADS


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.key(0), **CFG)


@pytest.fixture(scope="module")
def tokens():
    return jnp.asarray(
        np.random.default_rng(0).integers(1, CFG["vocab_size"], (2, 12)),
        jnp.int32,
    )


def _naive_greedy(params, prompt, n):
    """Oracle: greedy generation by full-forward recompute every step."""
    toks = list(prompt)
    for _ in range(n):
        logits = forward(params, jnp.asarray([toks], jnp.int32),
                         num_heads=HEADS)
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


def test_prefill_matches_forward(params, tokens):
    """forward_prefill is forward + captured per-layer K/V."""
    want = forward(params, tokens, num_heads=HEADS)
    logits, k, v = forward_prefill(params, tokens, num_heads=HEADS)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(want),
                               atol=1e-6)
    b, s = tokens.shape
    assert k.shape == (b, CFG["num_layers"], s, HEADS, HEAD_DIM)
    assert v.shape == k.shape


def test_decode_matches_full_forward_at_every_position(params, tokens):
    """Acceptance pin: decode-step-t logits == full forward at position t,
    for every t, starting from an empty cache."""
    b, s = tokens.shape
    full = np.asarray(forward(params, tokens, num_heads=HEADS))
    cache = init_cache(
        batch_slots=b, num_layers=CFG["num_layers"], max_seq=16,
        num_heads=HEADS, head_dim=HEAD_DIM,
    )
    for t in range(s):
        logits, cache = forward_decode(
            params, tokens[:, t], cache, jnp.full((b,), t, jnp.int32),
            num_heads=HEADS,
        )
        np.testing.assert_allclose(
            np.asarray(logits), full[:, t], atol=1e-5,
            err_msg=f"decode diverged from full forward at position {t}",
        )


def test_prefill_then_decode_matches_full_forward(params, tokens):
    """The serving dataflow: prefill a prompt prefix into cache slots,
    decode the rest token-by-token; every step matches the full forward."""
    b, s = tokens.shape
    split = 6
    full = np.asarray(forward(params, tokens, num_heads=HEADS))
    _, k, v = forward_prefill(params, tokens[:, :split], num_heads=HEADS)
    cache = init_cache(
        batch_slots=b, num_layers=CFG["num_layers"], max_seq=16,
        num_heads=HEADS, head_dim=HEAD_DIM,
    )
    for slot in range(b):
        cache = insert_sequence(cache, k[slot], v[slot], slot)
    for t in range(split, s):
        logits, cache = forward_decode(
            params, tokens[:, t], cache, jnp.full((b,), t, jnp.int32),
            num_heads=HEADS,
        )
        np.testing.assert_allclose(np.asarray(logits), full[:, t], atol=1e-5)


def test_cache_bytes_and_shapes():
    cache = init_cache(batch_slots=4, num_layers=2, max_seq=8, num_heads=2,
                       head_dim=4, dtype=jnp.bfloat16)
    assert cache["k"].shape == (4, 2, 8, 2, 4)
    assert cache_bytes(cache) == 2 * 4 * 2 * 8 * 2 * 4 * 2  # k+v, bf16


def test_engine_greedy_matches_oracle(params):
    """Engine-level prefill+decode greedy generation == full-forward
    greedy, with the flash prompt pass (the serving default)."""
    prompt = [5, 17, 3, 42, 8]
    engine = InferenceEngine(
        params, num_heads=HEADS, batch_slots=2, max_seq=24,
        prefill_attention="flash",
    )
    first = engine.prefill(0, prompt)
    got = [first]
    pos = np.array([len(prompt), 0], np.int32)
    toks = np.array([first, 0], np.int32)
    for _ in range(4):
        out = engine.decode(toks, pos)
        got.append(int(out[0]))
        toks[0] = out[0]
        pos[0] += 1
    assert got == _naive_greedy(params, prompt, 5)


def test_engine_validates_inputs(params):
    engine = InferenceEngine(params, num_heads=HEADS, batch_slots=2,
                             max_seq=16)
    with pytest.raises(ValueError, match="empty prompt"):
        engine.prefill(0, [])
    with pytest.raises(ValueError, match="no room"):
        engine.prefill(0, list(range(1, 17)))
    with pytest.raises(ValueError, match="slot"):
        engine.prefill(5, [1, 2])
    with pytest.raises(ValueError, match="max_seq"):
        InferenceEngine(params, num_heads=HEADS, batch_slots=2,
                        max_seq=CFG["max_len"] + 1)
    with pytest.raises(ValueError, match="top_k"):
        InferenceEngine(params, num_heads=HEADS, batch_slots=2,
                        max_seq=16, temperature=1.0, top_k=0)


def test_continuous_batching_slot_release_and_reuse(params):
    """More requests than slots: finished sequences release their slot
    mid-flight, newcomers take it, and EVERY completion still matches the
    full-forward greedy oracle (slot reuse must not leak stale K/V)."""
    rng = np.random.default_rng(1)
    prompts = {
        f"r{i}": rng.integers(1, CFG["vocab_size"], rng.integers(2, 9)).tolist()
        for i in range(7)
    }
    engine = InferenceEngine(params, num_heads=HEADS, batch_slots=2,
                             max_seq=24, prefill_attention="dense")
    sched = ContinuousBatchingScheduler(engine, max_new_tokens=4)
    results, report = sched.run(
        [Request(uid=uid, prompt=p) for uid, p in prompts.items()]
    )
    assert len(results) == 7
    for r in results:
        assert r.finish_reason == "length"
        assert r.tokens == _naive_greedy(params, prompts[r.uid], 4), r.uid
        assert r.ttft_s >= 0
    assert report.generated_tokens == 7 * 4
    assert report.requests == 7
    # 7 requests through 2 slots requires >= ceil(7/2)*4 decode... at least
    # more steps than one static batch would take, and occupancy recorded
    assert report.decode_steps >= 4
    assert 0 < report.slot_occupancy_mean <= 1
    assert report.tokens_per_sec > 0
    assert report.ttft_s["p99"] >= report.ttft_s["p50"]


def test_eos_releases_slot_early(params):
    """EOS mid-generation finishes the request with reason 'eos' and frees
    the slot for the queue.  The EOS id is discovered from a dry run so the
    test is robust to the random weights."""
    prompt = [7, 3, 11]
    dry = _naive_greedy(params, prompt, 4)
    eos = dry[1]  # second generated token becomes the EOS id
    engine = InferenceEngine(params, num_heads=HEADS, batch_slots=1,
                             max_seq=16, prefill_attention="dense")
    sched = ContinuousBatchingScheduler(engine, eos_id=eos,
                                        max_new_tokens=8)
    results, report = sched.run(
        [Request(uid="a", prompt=prompt), Request(uid="b", prompt=prompt)]
    )
    assert len(results) == 2
    for r in results:
        assert r.finish_reason == "eos"
        assert r.tokens == dry[:2]  # stops AT the eos token, includes it
    assert report.finish_reasons == {"eos": 2}


def test_per_request_token_budget(params):
    engine = InferenceEngine(params, num_heads=HEADS, batch_slots=2,
                             max_seq=16, prefill_attention="dense")
    sched = ContinuousBatchingScheduler(engine, max_new_tokens=6)
    results, _ = sched.run([
        Request(uid="short", prompt=[4, 9], max_new_tokens=2),
        Request(uid="default", prompt=[4, 9]),
    ])
    by_uid = {r.uid: r for r in results}
    assert len(by_uid["short"].tokens) == 2
    assert len(by_uid["default"].tokens) == 6
    # a zero budget is rejected per-request (not silently promoted to the
    # default, and not raised — in live/fleet mode a raise out of run()
    # would kill the whole worker over one malformed client request)
    results, report = sched.run(
        [Request(uid="zero", prompt=[4, 9], max_new_tokens=0)]
    )
    (res,) = results
    assert res.finish_reason == "error"
    assert "max_new_tokens" in res.error
    assert report.errors == 1


def test_sharded_cache_smoke(params):
    """2-virtual-device mesh: slots shard over the data axes, the run
    completes, and greedy outputs equal the single-device engine's."""
    rng = np.random.default_rng(2)
    prompts = {
        f"r{i}": rng.integers(1, CFG["vocab_size"], rng.integers(2, 7)).tolist()
        for i in range(6)
    }
    requests = [Request(uid=uid, prompt=p) for uid, p in prompts.items()]
    mesh = create_mesh(MeshSpec(), devices=jax.devices()[:2])
    engine = InferenceEngine(params, num_heads=HEADS, batch_slots=4,
                             max_seq=24, mesh=mesh,
                             prefill_attention="dense")
    spec = engine.cache["k"].sharding.spec
    assert spec[0] == ("data", "fsdp")  # slot axis over the data axes
    results, report = ContinuousBatchingScheduler(
        engine, max_new_tokens=3
    ).run(requests)
    assert len(results) == 6
    for r in results:
        assert r.tokens == _naive_greedy(params, prompts[r.uid], 3), r.uid
    # the cache stayed sharded through donated decode steps
    assert engine.cache["k"].sharding.spec[0] == ("data", "fsdp")
    assert report.slot_occupancy_mean > 0

    with pytest.raises(ValueError, match="not divisible"):
        InferenceEngine(params, num_heads=HEADS, batch_slots=3, max_seq=16,
                        mesh=mesh)


def test_top_k_mask_keeps_exactly_k_under_ties():
    """Tie-heavy regression: with many logits equal to the k-th value, a
    threshold mask (`logits < kth`) lets every tied candidate through and
    samples from more than k; the exact-k mask must only ever emit the k
    deterministically-chosen (lowest-index) winners."""
    from distributeddeeplearning_tpu.serve.engine import sample_logits

    vocab = 32
    logits = np.zeros((1, vocab), np.float32)  # ALL tied at the top
    logits[0, 7] = 1.0  # one clear winner + 31 tied at 0.0
    k = 4
    seen = set()
    for step in range(200):
        tok = sample_logits(
            jnp.asarray(logits), jax.random.key(step),
            temperature=1.0, top_k=k,
        )
        seen.add(int(tok[0]))
    # winners are index 7 plus the first k-1 tied indices (0, 1, 2) —
    # lax.top_k breaks ties lowest-index-first
    assert seen <= {7, 0, 1, 2}, f"sampled outside the exact top-{k}: {seen}"
    assert len(seen) > 1  # the draw really is stochastic across steps

    # batched shape: the mask must be per-row, not global
    two = np.stack([logits[0], np.roll(logits[0], 16)])
    toks = sample_logits(
        jnp.asarray(two), jax.random.key(0), temperature=1.0, top_k=1
    )
    assert toks.tolist() == [7, 23]  # top-1 == per-row argmax


def test_temperature_sampling_reproducible(params):
    """Step-folded RNG: same seed -> same stochastic sample stream; a
    different seed decorrelates (train/step.py convention)."""
    def run(seed):
        engine = InferenceEngine(
            params, num_heads=HEADS, batch_slots=1, max_seq=16,
            temperature=1.5, rng=jax.random.key(seed),
            prefill_attention="dense",
        )
        results, _ = ContinuousBatchingScheduler(
            engine, max_new_tokens=6
        ).run([Request(uid="x", prompt=[3, 1, 4])])
        return results[0].tokens

    a, b, c = run(7), run(7), run(8)
    assert a == b
    assert a != c  # 61-way categorical over 6 draws: collision ~impossible


def test_checkpoint_restore_params_roundtrip(params, tmp_path):
    """serve's checkpoint loading: restore_params returns the params
    subtree without needing an optimizer/TrainState template."""
    from distributeddeeplearning_tpu.train.checkpoint import Checkpointer

    state = types.SimpleNamespace(
        step=jnp.zeros((), jnp.int32), params=params, opt_state={},
        batch_stats={},
    )
    ckpt = Checkpointer(str(tmp_path / "ckpt"), async_save=False)
    assert ckpt.save(0, state)
    ckpt.wait()
    ckpt.close()
    # restore through a FRESH manager — the serve flow runs in a process
    # that never saved (a same-instance restore hides missing handler args)
    fresh = Checkpointer(str(tmp_path / "ckpt"), async_save=False)
    restored, step = fresh.restore_params()
    fresh.close()
    assert step == 0
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b)),
        params, restored,
    )

    empty = Checkpointer(str(tmp_path / "none"), async_save=False)
    assert empty.restore_params() == (None, None)
    empty.close()


def test_cli_serve_synthetic(tmp_path, capsys):
    """ddlt serve --synthetic: continuous-batching run (requests > slots)
    on the virtual pod, SERVE artifact written with the full schema."""
    from distributeddeeplearning_tpu.cli.main import main

    report_path = tmp_path / "SERVE_test.json"
    rc = main([
        "serve", "--synthetic", "--requests", "5", "--batch-slots", "2",
        "--max-new-tokens", "3", "--prompt-len", "6",
        "--num-layers", "2", "--d-model", "32", "--num-heads", "4",
        "--d-ff", "64", "--vocab-size", "61",
        "--prefill-attention", "dense", "--report", str(report_path),
    ])
    assert rc == 0
    stats = json.loads(report_path.read_text())
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line == stats
    assert stats["requests"] == 5
    assert stats["batch_slots"] == 2
    assert stats["generated_tokens"] == 15
    assert stats["tokens_per_sec"] > 0
    assert {"p50", "p99", "mean", "max"} <= set(stats["ttft_s"])
    assert {"p50", "p99"} <= set(stats["decode_step_s"])
    assert 0 < stats["slot_occupancy_mean"] <= 1
    assert stats["platform"] == "cpu"
    assert stats["virtual_pod"] is True  # conftest forces the 8-CPU pod


def test_cli_serve_prompt_file(tmp_path, capsys):
    """Token-id prompt lines in, uid<TAB>completion lines out."""
    from distributeddeeplearning_tpu.cli.main import main

    pf = tmp_path / "prompts.txt"
    pf.write_text("5 17 3\n# comment\n\n9 2\n")
    rc = main([
        "serve", "--prompt-file", str(pf), "--batch-slots", "2",
        "--max-new-tokens", "2", "--num-layers", "2", "--d-model", "32",
        "--num-heads", "4", "--d-ff", "64", "--vocab-size", "61",
        "--prefill-attention", "dense",
    ])
    assert rc == 0
    out_lines = capsys.readouterr().out.strip().splitlines()
    got = dict(line.split("\t") for line in out_lines)
    assert set(got) == {"line1", "line4"}
    for toks in got.values():
        assert len(toks.split()) == 2


def test_cli_serve_rejects_too_long_prompt(tmp_path, capsys):
    """A prompt that cannot fit the cache fails loudly BEFORE the run —
    an engine error mid-run would discard finished completions."""
    from distributeddeeplearning_tpu.cli.main import main

    pf = tmp_path / "prompts.txt"
    pf.write_text(" ".join(["3"] * 12) + "\n")
    rc = main([
        "serve", "--prompt-file", str(pf), "--max-seq", "8",
        "--num-layers", "2", "--d-model", "32", "--num-heads", "4",
        "--d-ff", "64", "--vocab-size", "61",
    ])
    assert rc == 1
    assert "no room to generate" in capsys.readouterr().err


def test_cli_serve_checkpoint_requires_explicit_heads(tmp_path, capsys):
    """--checkpoint-dir without --num-heads must refuse: a wrong-but-
    dividing default head count would decode garbage silently."""
    from distributeddeeplearning_tpu.cli.main import main

    rc = main([
        "serve", "--synthetic", "--requests", "2",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
    ])
    assert rc == 1
    assert "--num-heads" in capsys.readouterr().err


def test_cli_serve_rejects_zero_requests(capsys):
    from distributeddeeplearning_tpu.cli.main import main

    assert main(["serve", "--synthetic", "--requests", "0"]) == 1
    assert "--requests" in capsys.readouterr().err


def test_cli_serve_rejects_out_of_vocab_prompt(tmp_path, capsys):
    """Out-of-range token ids would be clamped silently by jit's gather
    and decode a plausible completion from a wrong prompt — refuse."""
    from distributeddeeplearning_tpu.cli.main import main

    pf = tmp_path / "prompts.txt"
    pf.write_text("99999 5\n")
    rc = main([
        "serve", "--prompt-file", str(pf), "--num-layers", "2",
        "--d-model", "32", "--num-heads", "4", "--d-ff", "64",
        "--vocab-size", "61",
    ])
    assert rc == 1
    assert "outside the model vocab" in capsys.readouterr().err


def test_bench_serve_conflicts_with_devices():
    import subprocess

    proc = subprocess.run(
        [sys.executable, "bench.py", "--serve", "--devices", "1,2"],
        capture_output=True, text=True,
        cwd=str(Path(__file__).resolve().parent.parent),
    )
    assert proc.returncode == 2
    assert "mutually exclusive" in proc.stderr


def test_cli_serve_dry_run(capsys):
    from distributeddeeplearning_tpu.cli.main import main

    assert main(["serve", "--synthetic", "--requests", "9", "--dry-run"]) == 0
    assert "9 request(s)" in capsys.readouterr().out


def test_bench_serve_mode():
    """bench.py --serve emits the SERVE artifact line with provenance."""
    import bench

    args = types.SimpleNamespace(
        small=True, seq_len=8, batch_slots=2, serve_requests=5,
        max_new_tokens=3, serve_temperature=0.0, attention="default",
        kv_layout="dense", page_size=8, prefill_chunk=8, kv_pages=None,
        steps_cap=None, report=None,
    )
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bench._run_serve(args)
    assert rc == 0
    line = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert line["metric"] == "lm_serve_default_tok_sec"
    assert line["unit"] == "tok/sec"
    assert line["value"] > 0
    assert line["requests"] == 5
    assert line["generated_tokens"] == 15
    # the README-documented ServeReport schema (same as ddlt serve
    # --report) plus the ms-denominated conveniences
    assert {"p50", "p99", "mean", "max"} <= set(line["ttft_s"])
    assert line["finish_reasons"] == {"length": 5}
    assert line["wall_s"] > 0
    assert {"p50", "p99"} <= set(line["ttft_ms"])
    assert {"p50", "p99"} <= set(line["decode_step_ms"])
    assert 0 < line["slot_occupancy_mean"] <= 1
    assert line["platform"] == "cpu"
    assert line["virtual_pod"] is True
    assert line["kv_cache_mb"] > 0
    # satellites: queue wait has its own percentile block, and warmup
    # drove every prefill bucket compile out of the benchmarked phase
    assert {"p50", "p99", "mean", "max"} <= set(line["queue_wait_s"])
    assert line["prefill_compiles"] == 0
    assert line["kv_layout"] == "dense"
    assert line["kv_bytes_peak"] == line["kv_bytes"] > 0  # dense: all reserved
