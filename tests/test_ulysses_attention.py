"""Ulysses all-to-all sequence parallelism (ops/ulysses_attention.py):
parity with dense attention, gradients, masking, and the head constraint."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.models.bert import dot_product_attention
from distributeddeeplearning_tpu.ops import ulysses_attention
from distributeddeeplearning_tpu.parallel import MeshSpec, create_mesh

B, S, H, D = 4, 32, 4, 8


@pytest.fixture(scope="module")
def mesh_sp2():
    return create_mesh(MeshSpec(seq=2))


def _inputs(seed=0):
    rng = np.random.default_rng(seed)
    shape = (B, S, H, D)
    q = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    k = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    v = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    lengths = rng.integers(S // 2, S + 1, B)
    mask = jnp.asarray(
        (np.arange(S)[None, :] < lengths[:, None])[:, None, None, :]
    )
    return q, k, v, mask


def test_matches_dense_reference(mesh_sp2):
    q, k, v, mask = _inputs()
    got = ulysses_attention(q, k, v, mask, mesh=mesh_sp2, dtype=jnp.float32)
    want = dot_product_attention(q, k, v, mask, dtype=jnp.float32)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )


def test_no_mask_and_gradients(mesh_sp2):
    q, k, v, _ = _inputs(1)

    def loss_u(q, k, v):
        o = ulysses_attention(q, k, v, None, mesh=mesh_sp2, dtype=jnp.float32)
        return (o ** 2).sum()

    def loss_ref(q, k, v):
        o = dot_product_attention(q, k, v, None, dtype=jnp.float32)
        return (o ** 2).sum()

    g_u = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_u, g_r):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4
        )


def test_head_divisibility_rejected():
    mesh = create_mesh(MeshSpec(seq=8))
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((8, 16, 4, 8)), jnp.float32)  # 4 heads
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, q, q, None, mesh=mesh, dtype=jnp.float32)


def test_seq1_falls_back_to_dense():
    mesh = create_mesh(MeshSpec())
    q, k, v, mask = _inputs(2)
    got = ulysses_attention(q, k, v, mask, mesh=mesh, dtype=jnp.float32)
    want = dot_product_attention(q, k, v, mask, dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_bert_workload_ulysses_trains():
    from distributeddeeplearning_tpu.workloads.bert import main

    state, fit = main(
        epochs=1,
        batch_size=2,
        seq_len=16,
        num_classes=3,
        vocab_size=64,
        num_layers=2,
        hidden_size=32,
        num_heads=2,
        intermediate_size=64,
        max_position_embeddings=16,
        train_examples=32,
        steps_per_epoch=2,
        seq=2,
        attention="ulysses",
        dropout_rate=0.0,
        compute_dtype="float32",
        resume=False,
        distributed=False,
    )
    assert np.isfinite(fit.final_train_metrics["loss"])


# ---------------------------------------------------------------------------
# Causal Ulysses (round 4): after the tokens->heads all-to-all each device
# holds the full sequence, so causality is a local tril over the gathered
# mask.  Oracle: dense attention over the combined padding & tril mask.
# ---------------------------------------------------------------------------


def _dense_causal(q, k, v, mask):
    s = q.shape[1]
    tril = jnp.tril(jnp.ones((s, s), bool))[None, None]
    full = tril if mask is None else jnp.logical_and(mask, tril)
    return dot_product_attention(q, k, v, full, dtype=jnp.float32)


@pytest.mark.parametrize("n", [2, 4])  # heads=4 caps the seq axis
def test_causal_matches_dense(n):
    q, k, v, mask = _inputs(3)
    mesh = create_mesh(MeshSpec(seq=n))
    dense = _dense_causal(q, k, v, mask)
    out = ulysses_attention(
        q, k, v, mask, mesh=mesh, dtype=jnp.float32, causal=True
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense), atol=2e-5, rtol=2e-5
    )


def test_causal_no_mask_and_gradients():
    q, k, v, _ = _inputs(4)
    mesh = create_mesh(MeshSpec(seq=4))
    dense = _dense_causal(q, k, v, None)
    out = ulysses_attention(
        q, k, v, None, mesh=mesh, dtype=jnp.float32, causal=True
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense), atol=2e-5, rtol=2e-5
    )

    def dense_loss(q):
        return (_dense_causal(q, k, v, None) ** 2).sum()

    def uly_loss(q):
        return (
            ulysses_attention(
                q, k, v, None, mesh=mesh, dtype=jnp.float32, causal=True
            )
            ** 2
        ).sum()

    np.testing.assert_allclose(
        np.asarray(jax.grad(uly_loss)(q)),
        np.asarray(jax.grad(dense_loss)(q)),
        atol=5e-4, rtol=5e-4,
    )


def test_causal_seq_axis_one_falls_back_to_dense():
    q, k, v, mask = _inputs(5)
    mesh = create_mesh(MeshSpec())  # seq=1
    dense = _dense_causal(q, k, v, mask)
    out = ulysses_attention(
        q, k, v, mask, mesh=mesh, dtype=jnp.float32, causal=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense), atol=1e-6)


# ---------------------------------------------------------------------------
# Ulysses × flash (round 4): the local per-device attention runs through
# the Pallas kernel (interpret mode on CPU).  Must match the dense oracle
# with and without the causal triangle, fwd and grads.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("causal", [False, True])
def test_flash_composition_matches_dense(causal):
    q, k, v, mask = _inputs(6)
    mesh = create_mesh(MeshSpec(seq=2))
    want = (
        _dense_causal(q, k, v, mask)
        if causal
        else dot_product_attention(q, k, v, mask, dtype=jnp.float32)
    )
    got = ulysses_attention(
        q, k, v, mask, mesh=mesh, dtype=jnp.float32, causal=causal,
        use_flash=True,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )


def test_flash_composition_gradients():
    q, k, v, _ = _inputs(7)
    mesh = create_mesh(MeshSpec(seq=2))

    def dense_loss(q):
        return (_dense_causal(q, k, v, None) ** 2).sum()

    def flash_loss(q):
        return (
            ulysses_attention(
                q, k, v, None, mesh=mesh, dtype=jnp.float32, causal=True,
                use_flash=True,
            )
            ** 2
        ).sum()

    np.testing.assert_allclose(
        np.asarray(jax.grad(flash_loss)(q)),
        np.asarray(jax.grad(dense_loss)(q)),
        atol=5e-4, rtol=5e-4,
    )


def test_flash_composition_seq1_fallback():
    q, k, v, mask = _inputs(8)
    mesh = create_mesh(MeshSpec())  # seq=1
    want = _dense_causal(q, k, v, mask)
    got = ulysses_attention(
        q, k, v, mask, mesh=mesh, dtype=jnp.float32, causal=True,
        use_flash=True,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )
