"""Host->device prefetch (utils/prefetch.py) and its Trainer wiring."""

import numpy as np
import pytest

from distributeddeeplearning_tpu.parallel import MeshSpec, create_mesh
from distributeddeeplearning_tpu.parallel.sharding import batch_sharding
from distributeddeeplearning_tpu.utils.prefetch import prefetch_to_device


@pytest.fixture(scope="module")
def mesh8():
    return create_mesh(MeshSpec())


def _host_batches(n):
    for i in range(n):
        yield {
            "image": np.full((16, 4), i, np.float32),
            "label": np.full((16,), i, np.int32),
        }


def test_prefetch_preserves_order_and_places_on_mesh(mesh8):
    out = list(prefetch_to_device(_host_batches(5), mesh8, size=2))
    assert len(out) == 5
    expected = batch_sharding(mesh8)
    for i, batch in enumerate(out):
        assert batch["image"].sharding == expected
        assert float(batch["image"][0, 0]) == i  # order preserved
        assert int(batch["label"][0]) == i


def test_prefetch_propagates_worker_exception(mesh8):
    def bad():
        yield {"image": np.zeros((16, 4), np.float32)}
        raise RuntimeError("decoder exploded")

    it = prefetch_to_device(bad(), mesh8, size=2)
    next(it)
    with pytest.raises(RuntimeError, match="decoder exploded"):
        next(it)


def test_prefetch_rejects_zero_size(mesh8):
    with pytest.raises(ValueError, match="size"):
        next(prefetch_to_device(_host_batches(1), mesh8, size=0))


def test_trainer_prefetch_matches_synchronous(mesh8):
    """Same data, prefetch on vs off: identical final params."""
    import itertools

    import jax
    import jax.numpy as jnp
    import optax

    from distributeddeeplearning_tpu.data.synthetic import synthetic_batch
    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.train.loop import Trainer, TrainerConfig
    from distributeddeeplearning_tpu.train.state import (
        create_train_state,
        sgd_momentum,
    )
    from distributeddeeplearning_tpu.train.step import build_train_step

    model = get_model("resnet18", num_classes=5, dtype=jnp.float32)
    tx = sgd_momentum(optax.constant_schedule(0.05))

    def run(prefetch):
        state = create_train_state(
            jax.random.key(0), model, (8, 32, 32, 3), tx
        )
        step = build_train_step(mesh8, state, compute_dtype=jnp.float32)
        batches = (
            synthetic_batch(16, (32, 32, 3), 5, seed=s) for s in itertools.count()
        )
        trainer = Trainer(
            mesh8,
            step,
            config=TrainerConfig(
                epochs=1, steps_per_epoch=4, global_batch_size=16,
                log_every=10**9, prefetch=prefetch,
            ),
        )
        final_state, _ = trainer.fit(state, batches)
        return final_state

    s_sync = run(0)
    s_pre = run(2)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        ),
        s_sync.params,
        s_pre.params,
    )


def test_prefetch_close_reaps_worker_blocked_on_put(mesh8):
    """Regression (ISSUE 2): close() must REAP the worker even when it sits
    blocked in q.put — the old single get_nowait could unblock one put and
    then leave the thread blocked forever on the next (e.g. the sentinel
    going into a re-filled queue)."""
    import itertools

    def endless():
        for i in itertools.count():
            yield {"image": np.full((16, 4), i, np.float32)}

    it = prefetch_to_device(endless(), mesh8, size=1)
    next(it)  # worker now blocked in q.put with a full queue behind it
    it.close()
    assert not it.thread.is_alive()  # thread actually reaped, not leaked
    with pytest.raises(RuntimeError, match="close"):
        next(it)


def test_prefetch_close_after_exhaustion_is_noop(mesh8):
    it = prefetch_to_device(_host_batches(2), mesh8, size=2)
    assert len(list(it)) == 2
    it.close()
    assert not it.thread.is_alive()


def test_prefetch_close_reaps_worker_blocked_on_sentinel_put(mesh8):
    """The exact leak shape from the issue: a finite source whose SENTINEL
    put lands in a queue the consumer has stopped draining."""
    it = prefetch_to_device(_host_batches(3), mesh8, size=1)
    next(it)  # queue refills immediately; worker heads toward the sentinel
    it.close()
    assert not it.thread.is_alive()


def test_prefetch_close_stops_worker_overconsumption(mesh8):
    """Closing the wrapper (Trainer.fit's finally) must stop the worker; it
    may stage at most the queue depth + 1 ahead of what was consumed."""
    import itertools
    import time

    pulled = []

    def source():
        for i in itertools.count():
            pulled.append(i)
            yield {"image": np.full((16, 4), i, np.float32)}

    it = prefetch_to_device(source(), mesh8, size=2)
    next(it)
    next(it)
    it.close()
    time.sleep(0.2)  # let a racing worker (if any) run
    high_water = len(pulled)
    time.sleep(0.3)
    assert len(pulled) == high_water  # worker actually stopped
    assert high_water <= 2 + 2 + 2  # consumed + queue depth + in-flight
