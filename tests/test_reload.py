"""Live weight reload (PR 13): engine in-place swap, the scheduler's idle
barrier, prefix-cache invalidation, and the fleet's broadcast —
post-reload greedy tokens pinned BIT-IDENTICAL to a fresh engine built
from the reloaded weights."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.models.pipelined_transformer import (
    init_params,
)
from distributeddeeplearning_tpu.serve import (
    ContinuousBatchingScheduler,
    InferenceEngine,
    PagedInferenceEngine,
    Request,
)
from distributeddeeplearning_tpu.utils import faults as faults_mod

CFG = dict(num_layers=2, d_model=32, num_heads=4, d_ff=64, vocab_size=61,
           max_len=32)
HEADS = CFG["num_heads"]


@pytest.fixture(scope="module")
def params_old():
    return init_params(jax.random.key(1), **CFG)


@pytest.fixture(scope="module")
def params_new():
    return init_params(jax.random.key(2), **CFG)


@pytest.fixture(autouse=True)
def _clean_fault_plan():
    yield
    faults_mod.install_plan("")


def _dense(params, **kw):
    kw.setdefault("num_heads", HEADS)
    kw.setdefault("batch_slots", 2)
    kw.setdefault("max_seq", 24)
    return InferenceEngine(params, **kw)


def _paged(params, **kw):
    kw.setdefault("num_heads", HEADS)
    kw.setdefault("batch_slots", 2)
    kw.setdefault("max_seq", 24)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_chunk", 8)
    return PagedInferenceEngine(params, **kw)


def _run(engine, reqs, **kw):
    res, rep = ContinuousBatchingScheduler(
        engine, max_new_tokens=6, **kw
    ).run([Request(uid=r.uid, prompt=list(r.prompt)) for r in reqs])
    return {r.uid: list(r.tokens) for r in res}, rep


REQS = [
    Request(uid="a", prompt=[5, 9, 2, 17]),
    Request(uid="b", prompt=[3, 3, 8]),
    Request(uid="c", prompt=[11, 4, 4, 4, 7]),
]


# --------------------------------------------------------------------------
# engine-level: in-place swap semantics
# --------------------------------------------------------------------------


@pytest.mark.parametrize("build", [_dense, _paged], ids=["dense", "paged"])
def test_reload_then_serve_matches_fresh_engine(
    build, params_old, params_new
):
    """After reload_params, greedy tokens are bit-identical to a fresh
    engine constructed from the new weights — the reload IS a restart,
    minus the restart."""
    fresh_tokens, _ = _run(build(params_new), REQS)
    engine = build(params_old)
    _run(engine, REQS)  # serve a full batch on the OLD weights first
    engine.reload_params(params_new)
    reloaded_tokens, rep = _run(engine, REQS)
    assert reloaded_tokens == fresh_tokens
    # and the swap really changed the weights (old != new outputs)
    old_tokens, _ = _run(build(params_old), REQS)
    assert reloaded_tokens != old_tokens


@pytest.mark.parametrize("build", [_dense, _paged], ids=["dense", "paged"])
def test_reload_rejects_mismatched_tree(build, params_old):
    engine = build(params_old)
    bad = init_params(jax.random.key(3), **{**CFG, "d_model": 64})
    with pytest.raises(ValueError, match="reload_params"):
        engine.reload_params(bad)
    # dtype change is a mismatch too (compiled programs key on avals)
    cast = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16), params_old
    )
    with pytest.raises(ValueError, match="reload_params"):
        engine.reload_params(cast)


def test_paged_reload_refuses_live_slots(params_old, params_new):
    engine = _paged(params_old)
    engine.prefill_begin(0, [5, 9, 2], 4)
    with pytest.raises(ValueError, match="live slots"):
        engine.reload_params(params_new)


def test_paged_reload_drops_prefix_cache(params_old, params_new):
    """Prefix pages hold K/V computed by the OLD weights; a post-reload
    hit on them would break the fresh-engine pin — the reload must drop
    the table (and the pinned equality below proves no stale page is
    reused)."""
    shared = [7, 7, 7, 7, 1, 2, 3, 4]  # one full page + remainder
    reqs = [
        Request(uid="p1", prompt=shared + [9]),
        Request(uid="p2", prompt=shared + [13]),
    ]
    # batch_slots=1: p2 admits after p1 completes, so p1's published
    # prefix pages are there to hit
    engine = _paged(params_old, batch_slots=1)
    _run(engine, reqs)
    assert engine.prefix_hit_tokens > 0  # the old-weight pages were shared
    engine.reload_params(params_new)
    assert engine.allocator.lookup_prefix(tuple(shared)) is None
    reloaded, _ = _run(engine, reqs)
    fresh, _ = _run(_paged(params_new, batch_slots=1), reqs)
    assert reloaded == fresh


# --------------------------------------------------------------------------
# scheduler-level: the idle barrier
# --------------------------------------------------------------------------


def test_request_reload_is_a_barrier_between_requests(
    params_old, params_new
):
    """Requests in flight at reload time finish on the OLD weights;
    queued requests admitted after the barrier decode on the NEW weights
    — each request sees exactly one weight set, and both halves are
    bit-identical to single-weight-set runs."""
    r1 = Request(uid="inflight", prompt=[5, 9, 2, 17])
    r2 = Request(uid="queued", prompt=[3, 3, 8])
    engine = _paged(params_old, batch_slots=1)  # r2 must queue behind r1
    sched = ContinuousBatchingScheduler(engine, max_new_tokens=6)
    applied = {"at_active": None}

    def apply_reload():
        applied["at_active"] = True
        engine.reload_params(params_new)

    fired = {"done": False}

    def on_step(step):
        if not fired["done"]:
            fired["done"] = True
            sched.request_reload(apply_reload)

    res, _ = sched.run(
        [Request(uid=r.uid, prompt=list(r.prompt)) for r in (r1, r2)],
        on_step=on_step,
    )
    tokens = {r.uid: list(r.tokens) for r in res}
    old_tokens, _ = _run(_paged(params_old, batch_slots=1), [r1])
    new_tokens, _ = _run(_paged(params_new, batch_slots=1), [r2])
    assert tokens["inflight"] == old_tokens["inflight"]
    assert tokens["queued"] == new_tokens["queued"]
    assert applied["at_active"] is True


def test_request_reload_applies_before_first_admission(
    params_old, params_new
):
    """A reload requested before run() applies at the first idle barrier:
    every request decodes on the new weights."""
    engine = _paged(params_old)
    sched = ContinuousBatchingScheduler(engine, max_new_tokens=6)
    sched.request_reload(lambda: engine.reload_params(params_new))
    res, _ = sched.run(
        [Request(uid=r.uid, prompt=list(r.prompt)) for r in REQS]
    )
    tokens = {r.uid: list(r.tokens) for r in res}
    fresh, _ = _run(_paged(params_new), REQS)
    assert tokens == fresh


def test_failed_reload_keeps_serving_old_weights(params_old):
    """apply_fn raising must not kill the loop or poison the weights —
    serving continues on the old set (the fleet worker reports the error
    over the outbox and the replica stays up)."""
    engine = _paged(params_old)
    sched = ContinuousBatchingScheduler(engine, max_new_tokens=6)

    def bad_reload():
        raise IOError("checkpoint store unreachable")

    sched.request_reload(bad_reload)
    res, rep = sched.run(
        [Request(uid=r.uid, prompt=list(r.prompt)) for r in REQS]
    )
    tokens = {r.uid: list(r.tokens) for r in res}
    old_tokens, _ = _run(_paged(params_old), REQS)
    assert tokens == old_tokens
    assert rep.errors == 0


# --------------------------------------------------------------------------
# fleet-level: broadcast + acks + bit-exactness across the boundary
# --------------------------------------------------------------------------

FLEET_MODEL = dict(num_layers=1, d_model=16, num_heads=2, d_ff=32,
                   vocab_size=97, max_len=32)


def _save_params_ckpt(tmp_path, name, seed):
    import dataclasses as dc

    from distributeddeeplearning_tpu.train.checkpoint import Checkpointer

    @dc.dataclass
    class _S:
        step: object
        params: object
        opt_state: object
        batch_stats: object

        def replace(self, **kw):
            return dc.replace(self, **kw)

    params = init_params(jax.random.key(seed), **FLEET_MODEL)
    d = str(tmp_path / name)
    ckpt = Checkpointer(d)
    try:
        ckpt.save(1, _S(step=jnp.int32(1), params=params,
                        opt_state={}, batch_stats={}))
        ckpt.wait()
    finally:
        ckpt.close()
    return d, params


@pytest.mark.timeout(280)
def test_fleet_reload_bit_identical_to_fresh_engine(tmp_path):
    """ISSUE 13 acceptance (test half): serve a batch on checkpoint A,
    FleetRouter.reload(checkpoint B) with every replica acking, serve a
    second batch on the SAME worker processes — whose greedy tokens must
    be bit-identical to a fresh engine built from checkpoint B."""
    from distributeddeeplearning_tpu.serve import ReplicaSpec
    from distributeddeeplearning_tpu.serve.fleet import FleetRouter
    from distributeddeeplearning_tpu.serve.scheduler import (
        synthetic_requests,
    )

    dir_a, _ = _save_params_ckpt(tmp_path, "w-a", seed=1)
    dir_b, params_b = _save_params_ckpt(tmp_path, "w-b", seed=2)
    spec = ReplicaSpec(
        checkpoint_dir=dir_a,
        num_heads=2, batch_slots=2, max_seq=32, kv_layout="paged",
        page_size=8, prefill_chunk=8, max_new_tokens=8,
    )
    batch_a = synthetic_requests(
        4, vocab_size=FLEET_MODEL["vocab_size"], max_prompt=8,
        rng=np.random.default_rng(0),
    )
    batch_b = [
        Request(uid=f"post{i}", prompt=r.prompt)
        for i, r in enumerate(synthetic_requests(
            4, vocab_size=FLEET_MODEL["vocab_size"], max_prompt=8,
            rng=np.random.default_rng(1),
        ))
    ]
    router = FleetRouter(spec, replicas=2, faults="")
    _, rep_a = router.serve(batch_a, shutdown=False)
    assert rep_a.completed_ok == len(batch_a)
    acks = router.reload(dir_b)
    assert sorted(acks) == [0, 1]
    assert all(a["ok"] for a in acks.values()), acks
    assert all(a["step"] == 1 for a in acks.values())
    res_b, rep_b = router.serve(batch_b)
    assert rep_b.completed_ok == len(batch_b)
    assert rep_b.reloads == 1

    ref_engine = PagedInferenceEngine(
        params_b, num_heads=2, batch_slots=2, max_seq=32, page_size=8,
        prefill_chunk=8, rng=jax.random.key(spec.seed),
    )
    ref_res, _ = ContinuousBatchingScheduler(
        ref_engine, max_new_tokens=8,
    ).run([Request(uid=r.uid, prompt=list(r.prompt)) for r in batch_b])
    ref_tokens = {r.uid: list(r.tokens) for r in ref_res}
    for r in res_b:
        assert r.finish_reason in ("eos", "length")
        assert list(r.tokens) == ref_tokens[r.uid], r.uid


@pytest.mark.timeout(280)
def test_fleet_reload_mid_serve_from_another_thread(tmp_path):
    """reload() while a serve is running: the dispatch loop harvests the
    acks (no message stealing) and the run completes with every request
    in a terminal state."""
    from distributeddeeplearning_tpu.serve import ReplicaSpec
    from distributeddeeplearning_tpu.serve.fleet import FleetRouter
    from distributeddeeplearning_tpu.serve.scheduler import (
        synthetic_requests,
    )

    dir_a, _ = _save_params_ckpt(tmp_path, "m-a", seed=1)
    dir_b, _ = _save_params_ckpt(tmp_path, "m-b", seed=2)
    spec = ReplicaSpec(
        checkpoint_dir=dir_a,
        num_heads=2, batch_slots=2, max_seq=32, kv_layout="paged",
        page_size=8, prefill_chunk=8, max_new_tokens=8,
    )
    reqs = synthetic_requests(
        8, vocab_size=FLEET_MODEL["vocab_size"], max_prompt=8,
        rng=np.random.default_rng(3),
    )
    router = FleetRouter(spec, replicas=2, faults="")
    acks_box = {}
    stop = threading.Event()

    def reload_when_live():
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline and not stop.is_set():
            if any(m.ready for m in router._members):
                acks_box.update(router.reload(dir_b, timeout_s=180))
                return
            time.sleep(0.05)

    t = threading.Thread(target=reload_when_live, daemon=True)
    t.start()
    try:
        results, report = router.serve(reqs)
    finally:
        stop.set()
        t.join(timeout=10)
    assert sum(report.finish_reasons.values()) == len(reqs)
    assert report.lost_requests == 0
    # at least one replica was live and acked (a replica may have been
    # mid-spawn when the broadcast targeted the ready set)
    assert acks_box and all(a.get("ok") for a in acks_box.values()), acks_box


@pytest.mark.timeout(280)
def test_serve_after_shutdown_respawns_workers(tmp_path):
    """A serve() after a shutdown serve must RESPAWN (the members are
    terminal), not dispatch onto dead inboxes; and reload() with no live
    replica refuses loudly instead of waiting out its timeout."""
    from distributeddeeplearning_tpu.serve import ReplicaSpec
    from distributeddeeplearning_tpu.serve.fleet import FleetRouter
    from distributeddeeplearning_tpu.serve.scheduler import (
        synthetic_requests,
    )

    dir_a, _ = _save_params_ckpt(tmp_path, "r-a", seed=1)
    spec = ReplicaSpec(
        checkpoint_dir=dir_a,
        num_heads=2, batch_slots=2, max_seq=32, kv_layout="paged",
        page_size=8, prefill_chunk=8, max_new_tokens=6,
    )
    router = FleetRouter(spec, replicas=2, faults="")
    batch1 = synthetic_requests(
        3, vocab_size=FLEET_MODEL["vocab_size"], max_prompt=8,
        rng=np.random.default_rng(5),
    )
    _, rep1 = router.serve(batch1)  # default shutdown=True
    assert rep1.completed_ok == len(batch1)
    assert all(m.dead for m in router._members)
    with pytest.raises(RuntimeError, match="no live ready replica"):
        router.reload(dir_a)
    batch2 = [
        Request(uid=f"second-{i}", prompt=r.prompt)
        for i, r in enumerate(synthetic_requests(
            3, vocab_size=FLEET_MODEL["vocab_size"], max_prompt=8,
            rng=np.random.default_rng(6),
        ))
    ]
    _, rep2 = router.serve(batch2)  # fresh workers, not a hang
    assert rep2.completed_ok == len(batch2)
