"""Host-memory KV page tier (serve/kv_tier.py + allocator tier states).

The load-bearing guarantee: spilling a page to host and restoring it
must be invisible to decode — a greedy stream over a spilled-then-
restored prefix page equals the never-spilled run EXACTLY, on the f32
and int8 page layouts and against the dense-layout oracle.  Around that
sit the lifecycle rules the tier's correctness depends on: a live
(decode-active) page can never spill, a freed page can never stay named
by the prefix table (the seeded-violation test), an in-flight prefetch
pins its host slot and gates admission until it lands, and a preempted
stream's private pages spill instead of vanishing so the resume skips
re-prefill.
"""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.models.pipelined_transformer import (
    forward,
    init_params,
)
from distributeddeeplearning_tpu.obs.ledger import HBMLedger
from distributeddeeplearning_tpu.serve import (
    ContinuousBatchingScheduler,
    HostPageTier,
    InferenceEngine,
    OutOfPages,
    PagedInferenceEngine,
    Request,
    init_paged_cache,
)

CFG = dict(num_layers=3, d_model=32, num_heads=4, d_ff=64, vocab_size=61,
           max_len=64)
HEADS = CFG["num_heads"]


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.key(0), **CFG)


def _naive_greedy(params, prompt, n):
    toks = list(prompt)
    for _ in range(n):
        logits = forward(params, jnp.asarray([toks], jnp.int32),
                         num_heads=HEADS)
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


def _engine(params, *, host_pages=0, cache_dtype=None, num_pages=24,
            batch_slots=2, page_size=4, prefill_chunk=8, max_seq=48,
            **kw):
    return PagedInferenceEngine(
        params, num_heads=HEADS, batch_slots=batch_slots, max_seq=max_seq,
        page_size=page_size, num_pages=num_pages,
        prefill_chunk=prefill_chunk, cache_dtype=cache_dtype,
        host_pages=host_pages, **kw)


def _run(engine, requests, n=6):
    results, report = ContinuousBatchingScheduler(
        engine, max_new_tokens=n).run(requests)
    return {r.uid: list(r.tokens) for r in results}, report


# --------------------------------------------------------------------------
# bit-identical spill/restore round trips
# --------------------------------------------------------------------------

@pytest.mark.parametrize("cache_dtype", [None, jnp.int8],
                         ids=["f32", "int8"])
def test_spill_restore_bit_identical(params, cache_dtype):
    """Greedy decode over spilled-then-restored prefix pages equals the
    never-spilled run, f32 and int8 layouts, with prompt lengths ending
    mid-page AND mid-chunk (page_size 4, prefill_chunk 8: lengths 9, 13
    and 17 exercise every offset class the restore path can meet)."""
    rng = np.random.default_rng(0)
    base = rng.integers(1, CFG["vocab_size"], 8).tolist()
    reqs = [
        Request(uid=f"r{n}",
                prompt=base + rng.integers(1, CFG["vocab_size"],
                                           n - 8).tolist())
        for n in (9, 13, 17)
    ]

    never_eng = _engine(params, cache_dtype=cache_dtype)
    never, _ = _run(never_eng, reqs)

    eng = _engine(params, cache_dtype=cache_dtype, host_pages=16)
    seeded, _ = _run(eng, reqs)
    assert seeded == never
    spilled = eng.spill_cold_pages(10**6)
    assert spilled > 0, "nothing reclaimable spilled — the test is inert"
    assert eng.allocator.host_entries == spilled
    restored_run, rep = _run(eng, reqs)
    assert restored_run == never, (
        "decode over spilled-then-restored pages diverged from the "
        "never-spilled run"
    )
    assert eng.tier.restored_pages > 0
    assert rep.tier_enabled and rep.tier_restored_pages > 0
    assert eng.prefix_hit_tokens_host > 0
    eng.allocator.check()
    eng.tier.check()
    # the f32 run also matches the dense-layout oracle end to end
    if cache_dtype is None:
        for r in reqs:
            assert restored_run[r.uid] == _naive_greedy(
                params, list(r.prompt), 6)


def test_spill_restore_bit_identical_dense_cross_check(params):
    """The dense layout runs the same greedy traffic: the paged engine's
    spilled-then-restored tokens equal the dense engine's (both layouts
    see the identical stream — the tier is invisible across layouts)."""
    rng = np.random.default_rng(3)
    reqs = [
        Request(uid=f"d{i}",
                prompt=rng.integers(1, CFG["vocab_size"], 11).tolist())
        for i in range(3)
    ]
    dense = InferenceEngine(params, num_heads=HEADS, batch_slots=2,
                            max_seq=48, prefill_attention="dense")
    dense_toks, _ = _run(dense, reqs)

    eng = _engine(params, host_pages=16)
    _run(eng, reqs)
    assert eng.spill_cold_pages(10**6) > 0
    restored_run, _ = _run(eng, reqs)
    assert restored_run == dense_toks


# --------------------------------------------------------------------------
# lifecycle rules
# --------------------------------------------------------------------------

def test_never_spill_a_decode_active_page(params):
    """spill_prefix refuses a page a live sequence still references —
    spilling under an active decode would corrupt the stream."""
    eng = _engine(params, host_pages=8)
    task = eng.prefill_begin(0, list(range(1, 10)), 4)
    while not task.done:
        eng.prefill_step(task)
    # the slot holds refs on its prompt pages: every registered prefix
    # key is LIVE, so nothing is cold enough to spill
    assert eng.spill_cold_pages(10**6) == 0
    live_keys = list(eng.allocator._prefix)
    assert live_keys, "prefill registered no prefix pages"
    with pytest.raises(ValueError, match="live"):
        eng.allocator.spill_prefix(live_keys[0])
    eng.release(0)
    # released -> reclaimable -> now spillable
    assert eng.spill_cold_pages(10**6) > 0
    eng.allocator.check()
    eng.tier.check()


def test_out_of_pages_spill_admit_recovery(params):
    """OutOfPages -> spill cold pages -> the same admission succeeds:
    the tier turns page exhaustion into host demotion, not failure."""
    eng = _engine(params, host_pages=16, num_pages=7, batch_slots=2)
    # fill the pool with a completed request's pages (reclaimable prefix
    # entries + free remainder), then occupy the rest
    task = eng.prefill_begin(0, list(range(1, 14)), 4)
    while not task.done:
        eng.prefill_step(task)
    eng.release(0)
    reclaim_before = eng.allocator.reclaimable_pages
    assert reclaim_before > 0
    spilled = eng.spill_cold_pages(10**6)
    assert spilled == reclaim_before
    assert eng.allocator.free_pages >= spilled
    # admission that needs the freed pages now succeeds, and the walk
    # restores the spilled prefix from host instead of re-prefilling
    task = eng.prefill_begin(1, list(range(1, 14)), 4)
    assert eng.prefix_hit_tokens_host > 0
    while not task.done:
        eng.prefill_step(task)
    eng.release(1)
    eng.allocator.check()
    eng.tier.check()


def test_prefetch_inflight_pins_slot_and_drains(params):
    """A dispatched restore holds its host slot in the in-flight ledger
    (the async DMA may still read those bytes); poll/drain retire it.
    The scheduler-facing accessors mirror the same state."""
    eng = _engine(params, host_pages=4)
    task = eng.prefill_begin(0, list(range(1, 10)), 4)
    while not task.done:
        eng.prefill_step(task)
    eng.release(0)
    assert eng.spill_cold_pages(10**6) > 0
    tier = eng.tier
    key = next(iter(eng.allocator._host))
    used_before = tier.used_pages
    dev = tier.dispatch_restore(key)
    assert tier.inflight == 1
    assert tier.used_pages == used_before  # slot still pinned
    tier.check()
    jax.block_until_ready(list(dev.values()))
    assert tier.poll() == 0
    assert tier.inflight == 0
    assert tier.used_pages == used_before - 1
    tier.check()
    # engine accessors: nothing in flight now, drain is a no-op
    assert eng.tier_inflight() == 0
    eng.drain_tier()


def test_host_pool_lru_eviction_and_policy():
    """A full host pool evicts its LRU slot to take a new spill; fifo
    keeps strict spill order (no touch promotion)."""
    cache = init_paged_cache(num_pages=8, num_layers=1, page_size=2,
                             num_heads=1, head_dim=4)
    tier = HostPageTier(cache, 2, policy="lru")
    assert tier.spill_in(cache, "a", 1) == []
    assert tier.spill_in(cache, "b", 2) == []
    tier.touch("a")                       # "a" becomes MRU
    assert tier.spill_in(cache, "c", 3) == ["b"]
    assert tier.has("a") and tier.has("c") and not tier.has("b")
    assert tier.dropped_pages == 1
    tier.check()

    fifo = HostPageTier(cache, 2, policy="fifo")
    fifo.spill_in(cache, "a", 1)
    fifo.spill_in(cache, "b", 2)
    fifo.touch("a")                       # fifo ignores the touch
    assert fifo.spill_in(cache, "c", 3) == ["a"]
    fifo.check()

    with pytest.raises(ValueError, match="policy"):
        HostPageTier(cache, 2, policy="mru")
    with pytest.raises(ValueError, match="host_pages"):
        HostPageTier(cache, 0)


# --------------------------------------------------------------------------
# allocator invariants: the seeded-violation bugfix test
# --------------------------------------------------------------------------

def test_check_catches_prefix_entry_naming_a_freed_page():
    """The PR's bugfix: check() must detect a prefix-table entry whose
    page index sits on the free list (a use-after-free the old
    invariants never looked for) and a key resident in both tiers."""
    from distributeddeeplearning_tpu.serve import PageAllocator

    alloc = PageAllocator(8)
    (page,) = alloc.alloc(1)
    alloc.register_prefix(("k",), page)
    alloc.check()                        # healthy: live + registered
    alloc.decref(page)                   # -> reclaimable (rc 0)
    alloc.check()
    # seed the violation: the page leaks onto the free list while the
    # prefix table still names it — the exact use-after-free shape the
    # old invariants never looked for (the page is NOT live, so the
    # pre-existing "live and free" check stays silent)
    del alloc._reclaim[page]
    alloc._free.append(page)
    with pytest.raises(AssertionError, match="freed page"):
        alloc.check()
    alloc._free.remove(page)
    alloc._reclaim[page] = None
    alloc.check()
    # second seeded violation: one key both resident and host
    alloc._host[("k",)] = None
    with pytest.raises(AssertionError, match="resident and host"):
        alloc.check()


def test_tier_state_transitions_and_strictness():
    from distributeddeeplearning_tpu.serve import PageAllocator

    alloc = PageAllocator(4)
    (page,) = alloc.alloc(1)
    alloc.register_prefix(("p",), page)
    assert alloc.tier_state(("p",)) == "resident"
    with pytest.raises(ValueError):
        alloc.spill_prefix(("p",))       # live page: never spillable
    alloc.decref(page)                   # -> reclaimable
    assert alloc.spill_prefix(("p",)) == page
    assert alloc.tier_state(("p",)) == "host"
    assert alloc.lookup_prefix(("p",)) is None
    alloc.check()
    (fresh,) = alloc.alloc(1)
    alloc.restore_prefix(("p",), fresh)
    assert alloc.tier_state(("p",)) == "resident"
    alloc.check()
    with pytest.raises(KeyError):
        alloc.drop_host(("p",))          # no longer host-resident


# --------------------------------------------------------------------------
# scheduler: preemption spills instead of zeroing, admission drains
# --------------------------------------------------------------------------

def _staged_poll(*stages, idle=400):
    state = {"n": 0}
    by_pass = dict(stages)

    def poll():
        state["n"] += 1
        if state["n"] > idle:
            return None
        return by_pass.get(state["n"], [])

    return poll


def test_preempted_stream_resumes_from_host_tier(params):
    """A preempted best_effort stream's private full pages spill to the
    host tier; the resume's prefix walk restores them (host hits > 0)
    and the final tokens equal the unpressured run — resume WITHOUT
    re-prefilling the whole history."""
    rng = np.random.default_rng(1)
    be = Request(uid="be", prompt=rng.integers(1, CFG["vocab_size"],
                                               8).tolist(),
                 priority="best_effort")
    prem = Request(uid="prem", prompt=rng.integers(1, CFG["vocab_size"],
                                                   5).tolist(),
                   priority="premium")

    clean, _ = ContinuousBatchingScheduler(
        _engine(params, host_pages=16, batch_slots=2),
        max_new_tokens=16).run([be, prem])
    clean_tokens = {r.uid: list(r.tokens) for r in clean}

    eng = _engine(params, host_pages=16, batch_slots=1)
    sched = ContinuousBatchingScheduler(eng, max_new_tokens=16,
                                        preempt_budget=2)
    results, rep = sched.run(
        [], poll=_staged_poll((1, [be]), (14, [prem])))
    by_uid = {r.uid: r for r in results}
    assert by_uid["be"].preemptions >= 1, "the cut never happened"
    assert rep.tier_preempt_spilled_pages >= 1, (
        "preemption zeroed the victim's private pages instead of "
        "spilling them"
    )
    assert eng.prefix_hit_tokens_host > 0, (
        "the resume re-prefilled instead of restoring from host"
    )
    assert list(by_uid["be"].tokens) == clean_tokens["be"]
    assert list(by_uid["prem"].tokens) == clean_tokens["prem"]
    eng.allocator.check()
    eng.tier.check()


def test_admission_drains_inflight_prefetch_before_preempting(params):
    """Prefetch racing admission: with a restore in flight and pages
    tight, the admission ladder fences the prefetch (drain) and
    re-checks instead of cutting a victim against transient accounting."""
    eng = _engine(params, host_pages=8, num_pages=7, batch_slots=1)
    task = eng.prefill_begin(0, list(range(1, 14)), 4)
    while not task.done:
        eng.prefill_step(task)
    eng.release(0)
    assert eng.spill_cold_pages(10**6) > 0
    # dispatch a restore by hand and leave it in flight: admission via
    # the scheduler must drain it and then admit normally
    key = next(iter(eng.allocator._host))
    page = eng._prefetch_page(key)
    assert page is not None
    results, rep = ContinuousBatchingScheduler(eng, max_new_tokens=4).run(
        [Request(uid="x", prompt=list(range(1, 14)))])
    assert results[0].finish_reason == "length"
    assert eng.tier_inflight() == 0
    eng.allocator.check()
    eng.tier.check()


def test_tier_disabled_is_inert(params):
    """host_pages=0: no tier object, no report fields moving — the
    default path is byte-for-byte the pre-tier engine."""
    eng = _engine(params)
    assert eng.tier is None
    toks, rep = _run(eng, [Request(uid="a", prompt=[1, 2, 3, 4, 5])])
    assert not rep.tier_enabled
    assert rep.tier_spilled_pages == 0
    assert rep.tier_preempt_spilled_pages == 0
    assert eng.spill_cold_pages(10) == 0
    assert eng.tier_inflight() == 0
    eng.drain_tier()


# --------------------------------------------------------------------------
# observability: ledger owner, fleet watermarks
# --------------------------------------------------------------------------

def test_ledger_attributes_host_bytes_outside_forecast(params):
    """The kv_host_pages owner attributes host bytes in snapshots and
    gauges but stays OUT of committed/forecast — host RAM is not HBM,
    and counting it would starve admission of the headroom spilling
    just created."""
    ledger = HBMLedger(capacity_bytes=10**9)
    eng = _engine(params, host_pages=8)
    from distributeddeeplearning_tpu.serve.engine import (
        _register_engine_owners,
    )
    _register_engine_owners(eng, ledger=ledger)
    assert "kv_host_pages" in ledger.host_owners()
    committed_before = ledger.committed_bytes()
    _run(eng, [Request(uid="a", prompt=list(range(1, 10)))])
    spilled = eng.spill_cold_pages(10**6)
    assert spilled > 0
    snap = ledger.snapshot()
    host_bytes = snap["host_owners"]["kv_host_pages"]["bytes"]
    assert host_bytes == spilled * eng.tier.page_host_bytes
    assert snap["host_total_bytes"] == host_bytes
    # spilling moved bytes OFF the device: committed may only shrink
    assert ledger.committed_bytes() <= committed_before
    assert ledger.forecast(0)["headroom_bytes"] >= (
        ledger.capacity_bytes - committed_before
    )
    from distributeddeeplearning_tpu.obs.registry import MetricsRegistry
    reg = MetricsRegistry()
    ledger.export_gauges(reg)
    gauges = reg.state()["gauges"]
    assert gauges["hbm.kv_host_pages.bytes"]["value"] == host_bytes
    assert gauges["hbm.host_total_bytes"]["value"] == host_bytes


def test_fleet_tier_watermarks_lift():
    """FleetReport's per-replica tier watermarks lift serve.tier.*
    counters/gauges from shipped registry states, keyed like the HBM
    watermarks; replicas without tier traffic stay absent."""
    from distributeddeeplearning_tpu.serve.fleet import _tier_watermarks

    states = [
        {"replica_id": 0, "pid": 11,
         "counters": {"serve.tier.spilled_pages": 3, "serve.requests": 9},
         "gauges": {"serve.tier.host_pages_peak": {"value": 2.0}}},
        {"replica_id": 1, "pid": 22, "counters": {"serve.requests": 4},
         "gauges": {}},
    ]
    marks = _tier_watermarks(states)
    assert marks == {
        "replica0-11": {"serve.tier.spilled_pages": 3,
                        "serve.tier.host_pages_peak": 2.0},
    }


def test_int8_spill_moves_scale_leaves():
    """The int8 layout's f32 scale leaves ride every spill: a host pool
    built over an int8 cache mirrors k/v AND k_scale/v_scale, and one
    page's host bytes are ~4x smaller than the f32 layout's."""
    kw = dict(num_pages=8, num_layers=1, page_size=4, num_heads=2,
              head_dim=8)
    f32 = init_paged_cache(**kw)
    int8 = init_paged_cache(dtype=jnp.int8, **kw)
    t_f32 = HostPageTier(f32, 2)
    t_int8 = HostPageTier(int8, 2)
    assert set(t_int8._pool) == set(int8.keys())
    assert {"k_scale", "v_scale"} <= set(t_int8._pool)
    # int8 values + f32 scales: ~4x cheaper per page than f32 values
    assert t_int8.page_host_bytes < t_f32.page_host_bytes / 2
    t_int8.spill_in(int8, "k0", 1)
    for name in int8:
        np.testing.assert_array_equal(
            t_int8._pool[name][t_int8._slots["k0"]],
            np.asarray(int8[name][1]),
        )


# --------------------------------------------------------------------------
# CI smoke: the tier bench end-to-end through bench.py on CPU
# --------------------------------------------------------------------------

@pytest.mark.timeout(420)
def test_bench_tier_cpu_smoke(tmp_path):
    """Fast tier-1 smoke: bench.py --tier --small with the smoke cap —
    all four gates must hold on CPU (bit-identity and the hit-rate /
    tokens-per-byte gates are structural; only the timing floor is
    loosened in smoke mode)."""
    report = tmp_path / "TIER_smoke.json"
    proc = subprocess.run(
        [
            sys.executable, "bench.py", "--tier", "--small",
            "--steps-cap", "1", "--report", str(report),
        ],
        capture_output=True, text=True, timeout=400,
        cwd=str(Path(__file__).resolve().parent.parent),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["gates"] == {
        "bit_identical": True, "prefix_hit_rate": True,
        "tokens_per_hbm_byte": True, "decode_tokens_per_sec": True,
    }
    payload = json.loads(report.read_text())
    assert payload["oversubscription"] >= 4
    assert payload["tier_prefix_hit_rate"] > payload[
        "tier_prefix_hit_rate_no_tier"]
    from distributeddeeplearning_tpu.obs.schema import validate_tier_payload
    validate_tier_payload(payload)
