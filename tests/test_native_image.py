"""Native JPEG decoder (data/csrc/ddlt_image.c via data/_native_image.py):
Pillow-parity resampling, colorspace handling, fallback contract."""

import io

import numpy as np
import pytest
from PIL import Image

from distributeddeeplearning_tpu.data._native_image import (
    decode_resize,
    native_available,
)

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no C compiler / libjpeg in this env"
)


def _jpeg(h=371, w=523, quality=95, mode="RGB"):
    yy, xx = np.mgrid[0:h, 0:w]
    img = np.stack(
        [(xx * 255 / w), (yy * 255 / h), ((xx + yy) * 255 / (w + h))], -1
    ).astype(np.uint8)
    if mode == "L":
        pil = Image.fromarray(img[:, :, 0], "L")
    else:
        pil = Image.fromarray(img)
    buf = io.BytesIO()
    pil.save(buf, "JPEG", quality=quality)
    return buf.getvalue()


def _pil_reference(jpeg, size, crop_frac=0.0):
    img = Image.open(io.BytesIO(jpeg)).convert("RGB")
    if crop_frac:
        w, h = img.size
        crop = int(min(h, w) * crop_frac)
        x, y = (w - crop) // 2, (h - crop) // 2
        img = img.crop((x, y, x + crop, y + crop))
    return np.asarray(img.resize((size, size), Image.BILINEAR), np.float32)


@pytest.mark.parametrize("size,crop", [(224, 0.0), (224, 224 / 256), (64, 0.0)])
def test_matches_pillow_bilinear(size, crop):
    jpeg = _jpeg()
    got = decode_resize(jpeg, size, crop)
    assert got is not None and got.shape == (size, size, 3)
    want = _pil_reference(jpeg, size, crop)
    # PIL uses 8-bit fixed-point filter weights; the C path is float —
    # agreement within one count per channel.
    np.testing.assert_allclose(got, want, atol=1.5)


def test_grayscale_jpeg_expands_to_rgb():
    got = decode_resize(_jpeg(mode="L"), 64)
    assert got is not None and got.shape == (64, 64, 3)
    np.testing.assert_allclose(got[..., 0], got[..., 1], atol=1e-3)


def test_corrupt_stream_returns_none_for_fallback():
    assert decode_resize(b"definitely not a jpeg", 64) is None


def test_pipeline_decoders_agree_with_pil_paths():
    """_decode_train/_decode_eval (whichever path they take) stay within
    fixed-point tolerance of the PIL reference implementation."""
    from distributeddeeplearning_tpu.data.native_pipeline import (
        RESIZE_MIN,
        _decode_eval,
        _decode_train,
    )

    jpeg = _jpeg()
    np.testing.assert_allclose(
        _decode_train(jpeg, 128), _pil_reference(jpeg, 128), atol=1.5
    )
    np.testing.assert_allclose(
        _decode_eval(jpeg, 128),
        _pil_reference(jpeg, 128, 128 / RESIZE_MIN),
        atol=1.5,
    )


def test_truncated_stream_returns_none_for_fallback():
    """Premature-EOF JPEGs decode as gray-filled garbage in raw libjpeg;
    the wrapper must report failure so PIL's loud-truncation path decides."""
    jpeg = _jpeg()
    assert decode_resize(jpeg[: len(jpeg) // 2], 64) is None
