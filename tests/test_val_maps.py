"""Devkit-derived validation map (data/val_maps.py).

The reference ships ``imagenet_val_maps.csv`` as a blob; this framework
derives it from the devkit and pins the result by sha256.  Tests run the
full derivation on a synthetic devkit tar (scipy-written meta.mat + ground
truth) and check the CSV round-trips through ``prepare_imagenet``'s loader
in the reference's exact column order.
"""

import hashlib
import io
import os
import tarfile

import numpy as np
import pytest

from distributeddeeplearning_tpu.data.val_maps import (
    DEVKIT_GROUND_TRUTH,
    DEVKIT_META,
    derive_val_maps,
    ensure_val_maps,
    write_val_maps,
)

scipy_io = pytest.importorskip("scipy.io")

N_CLASSES = 5
N_VAL = 50_000  # derive_val_maps pins the official count


def _fake_devkit(path: str, n_val: int = N_VAL):
    """Devkit tar with meta.mat (struct array) + ground-truth ids."""
    wnids = [f"n{90000000 + i:08d}" for i in range(1, N_CLASSES + 1)]
    synsets = np.zeros((len(wnids), 1), dtype=[
        ("ILSVRC2012_ID", object), ("WNID", object), ("words", object),
    ])
    for i, w in enumerate(wnids):
        synsets[i, 0] = (np.array([[i + 1]]), np.array([w]), np.array(["x"]))
    mat_buf = io.BytesIO()
    scipy_io.savemat(mat_buf, {"synsets": synsets})

    ids = [(i % N_CLASSES) + 1 for i in range(n_val)]
    gt = "\n".join(str(i) for i in ids).encode() + b"\n"

    with tarfile.open(path, "w:gz") as tar:
        for name, data in ((DEVKIT_META, mat_buf.getvalue()),
                           (DEVKIT_GROUND_TRUTH, gt)):
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))
    return wnids, ids


@pytest.fixture(scope="module")
def devkit(tmp_path_factory):
    d = tmp_path_factory.mktemp("devkit")
    path = str(d / "ILSVRC2012_devkit_t12.tar.gz")
    wnids, ids = _fake_devkit(path)
    return path, wnids, ids


def test_derivation_maps_ids_to_wnids(devkit):
    path, wnids, ids = devkit
    rows = derive_val_maps(path)
    assert len(rows) == N_VAL
    assert rows[0] == (wnids[ids[0] - 1], "ILSVRC2012_val_00000001.JPEG")
    assert rows[-1] == (
        wnids[ids[-1] - 1], f"ILSVRC2012_val_{N_VAL:08d}.JPEG"
    )


def test_written_csv_matches_reference_format_and_loader(devkit, tmp_path):
    path, _, _ = devkit
    rows = derive_val_maps(path)
    out = str(tmp_path / "imagenet_val_maps.csv")
    digest = write_val_maps(rows, out, verify=False)
    content = open(out).read()
    lines = content.splitlines()
    assert lines[0] == "class,filename"  # reference header order
    assert len(lines) == N_VAL + 1
    assert digest == hashlib.sha256(content.encode()).hexdigest()

    # prepare_imagenet's loader must consume the reference column order...
    from distributeddeeplearning_tpu.data.prepare_imagenet import load_val_map

    mapping = load_val_map(out)
    assert len(mapping) == N_VAL
    assert mapping["ILSVRC2012_val_00000001.JPEG"] == rows[0][0]

    # ...and the transposed order operators may produce.
    flipped = str(tmp_path / "flipped.csv")
    with open(flipped, "w") as f:
        f.write("filename,class\n")
        for wnid, fname in rows[:10]:
            f.write(f"{fname},{wnid}\n")
    assert load_val_map(flipped)["ILSVRC2012_val_00000001.JPEG"] == rows[0][0]


def test_verify_rejects_noncanonical_map(devkit, tmp_path):
    path, _, _ = devkit
    rows = derive_val_maps(path)
    with pytest.raises(ValueError, match="sha256"):
        write_val_maps(rows, str(tmp_path / "x.csv"), verify=True)
    assert not os.path.exists(tmp_path / "x.csv")  # refused before writing


def test_wrong_ground_truth_count_rejected(tmp_path):
    path = str(tmp_path / "short.tar.gz")
    _fake_devkit(path, n_val=10)
    with pytest.raises(ValueError, match="50000"):
        derive_val_maps(path)


def test_ensure_val_maps_turnkey(devkit, tmp_path, monkeypatch):
    path, _, _ = devkit
    # no devkit in dir -> None (caller falls back to operator CSV)
    assert ensure_val_maps(str(tmp_path)) is None
    # devkit present -> derived CSV appears (verification relaxed for the
    # synthetic devkit via monkeypatching the pinned digest)
    import shutil

    import distributeddeeplearning_tpu.data.val_maps as vm

    shutil.copy(path, tmp_path / "ILSVRC2012_devkit_t12.tar.gz")
    rows = derive_val_maps(path)
    real_digest = hashlib.sha256(
        ("class,filename\n" + "".join(f"{w},{f}\n" for w, f in rows)).encode()
    ).hexdigest()
    monkeypatch.setattr(vm, "EXPECTED_SHA256", real_digest)
    out = ensure_val_maps(str(tmp_path))
    assert out is not None and os.path.exists(out)
    # idempotent: second call returns the existing file
    assert ensure_val_maps(str(tmp_path)) == out
