"""Hot-loop host-sync lint — a tier-1 guard on dispatch pipelining.

The trainer's throughput story depends on the step loop never blocking on
device values (the r01 per-step ``float()`` cost ~2x), and the same
contract covers the serve decode loop, the fleet dispatch loop, the spec
draft->verify loop, the jitted step builders and the obs hot API.

Since PR 9 the lint is a real analyzer: the declarative hot-region
registry lives in ``analysis/regions.py`` and the AST checker in
``analysis/host_sync.py`` — import-alias-resolved banned calls (``float(``
/ ``.item()`` / ``np.asarray`` / ``device_get``), strings/comments
structurally invisible, ``# sync-ok`` waivers budgeted exactly and
stale markers flagged.  This file is the thin tier-1 wrapper: every
registered region must be clean against the live source (regions come
from the registry, not indentation scraping), plus the behavioral
pin that enabling the tracer changes no compiled program.
"""

import pytest

from distributeddeeplearning_tpu.analysis import format_findings, host_sync
from distributeddeeplearning_tpu.analysis.regions import (
    ALL_REGIONS,
    JIT_BUILDER_REGIONS,
    OBS_HOT_REGIONS,
    get_region,
)


def _assert_clean(region_name: str) -> None:
    region = get_region(region_name)
    findings = host_sync.check_region(region)
    assert not findings, (
        f"hot region {region_name} has open findings:\n"
        + format_findings(findings)
    )


def test_trainer_step_loop_has_no_unmarked_host_sync():
    """Per-step host syncs in Trainer._fit_inner's step loop serialize
    dispatch; the anomaly detector's documented reads are the only
    waived lines (budget-checked below by the same analyzer)."""
    _assert_clean("trainer-step-loop")


def test_serve_decode_loop_has_no_unmarked_host_sync():
    """The scheduler's ONE designed sync is the token readback inside
    ``engine.decode``; the loop body itself budgets zero."""
    _assert_clean("serve-decode-loop")


def test_fleet_dispatch_loop_has_no_unmarked_host_sync():
    """The router is host bookkeeping by design — any device-value token
    in its dispatch loop means engine state leaked across the process
    boundary."""
    _assert_clean("fleet-dispatch-loop")


def test_spec_draft_verify_loop_has_no_unmarked_host_sync():
    """A host sync between draft dispatches serializes the chain into K
    round trips; the one designed readback (tokens + acceptance +
    finiteness on one sync) is the whole budget."""
    _assert_clean("spec-draft-verify-loop")


@pytest.mark.parametrize(
    "region", JIT_BUILDER_REGIONS, ids=lambda r: r.name
)
def test_step_builders_have_no_host_sync_tokens(region):
    """Inside jit a host coercion is a bug, full stop — markers are not
    honored in the builder regions."""
    _assert_clean(region.name)


@pytest.mark.parametrize(
    "region", OBS_HOT_REGIONS, ids=lambda r: r.name
)
def test_tracer_hot_api_has_no_sync_tokens(region):
    """Everything on the span/event/record hot path is pure host
    bookkeeping; its two documented host-scalar coercions are the only
    budgeted waivers."""
    _assert_clean(region.name)


def test_trainer_step_loop_allowlist_is_alive():
    """The lint must be exercising something: the registry still demands
    the anomaly detector's three designed syncs (the analyzer fails the
    region if the live marker count drifts from this budget in either
    direction)."""
    assert get_region("trainer-step-loop").sync_budget == 3


def test_spec_step_allowlist_is_alive():
    """The spec step's designed readback spans three marked lines — a
    budget of zero would mean the lint stopped guarding the real loop."""
    assert get_region("spec-draft-verify-loop").sync_budget == 3


def test_hot_loops_are_instrumented():
    """The obs spans inside the trainer/serve hot loops are load-bearing
    (the OBS timeline is built from them); the registry pins them as
    landmarks so the analyzer fails if they silently disappear."""
    assert "trace.span(" in get_region("trainer-step-loop").landmarks
    assert "trace.span(" in get_region("serve-decode-loop").landmarks


def test_every_registered_region_is_clean():
    """The whole registry in one sweep — new regions added to
    analysis/regions.py are automatically under tier-1."""
    findings = []
    for region in ALL_REGIONS:
        findings.extend(host_sync.check_region(region))
    assert not findings, format_findings(findings)


def test_disabled_then_enabled_tracer_adds_no_jit_recompiles():
    """Tracing is host-side only: enabling it mid-process must not grow
    any jitted executable cache (a tracer arg leaking into a jit
    signature would recompile every program and stall the hot path)."""
    import jax
    import numpy as np

    from distributeddeeplearning_tpu.models.pipelined_transformer import (
        init_params,
    )
    from distributeddeeplearning_tpu.obs import trace as trace_mod
    from distributeddeeplearning_tpu.serve import (
        ContinuousBatchingScheduler,
        PagedInferenceEngine,
        Request,
    )

    params = init_params(
        jax.random.key(0), num_layers=2, d_model=32, num_heads=2,
        d_ff=64, vocab_size=97, max_len=32,
    )
    engine = PagedInferenceEngine(
        params, num_heads=2, batch_slots=2, max_seq=32, page_size=8,
        prefill_chunk=8, rng=jax.random.key(1),
    )
    rng = np.random.default_rng(0)

    def run():
        reqs = [
            Request(uid=f"r{i}", prompt=rng.integers(1, 97, 6).tolist())
            for i in range(3)
        ]
        ContinuousBatchingScheduler(engine, max_new_tokens=4).run(reqs)

    trace_mod.set_tracer(trace_mod.Tracer(enabled=False))
    try:
        run()  # compiles every shape with tracing OFF
        sizes_off = (
            engine._decode_jit._cache_size(),
            engine._chunk_jit._cache_size(),
            engine.prefill_compiles,
        )
        trace_mod.set_tracer(
            trace_mod.Tracer(enabled=True, annotate=False)
        )
        run()  # identical shapes with tracing ON
        sizes_on = (
            engine._decode_jit._cache_size(),
            engine._chunk_jit._cache_size(),
            engine.prefill_compiles,
        )
    finally:
        trace_mod.set_tracer(trace_mod.Tracer(enabled=False))
    assert sizes_on == sizes_off, (
        f"enabling the tracer changed compiled-program counts: "
        f"{sizes_off} -> {sizes_on}"
    )
