"""Hot-loop host-sync lint — a tier-1 guard on dispatch pipelining.

The trainer's throughput story depends on the step loop never blocking on
device values: metrics accumulate on device and the host syncs only at the
log interval (``train/loop.py``).  That property has been silently lost
before (the r01 per-step ``float()`` cost ~2x) and nothing structural
prevented it from regressing — so this lint greps the actual step-loop
source for per-step host syncs (``float(``, ``.item()``, ``np.asarray``,
``device_get``) and fails on any line not explicitly allow-listed with a
``# sync-ok`` marker (today: the anomaly detector's documented
one-sync-per-step price).  The jitted step builders are held to a stricter
bar: no such token at all (inside jit they would either crash or silently
fall back to host math).

The serve scheduler's decode loop gets the same treatment: its one
designed sync is the sampled-token readback inside ``engine.decode``
(host-side continuous batching needs the ids), so any OTHER per-step sync
token in ``ContinuousBatchingScheduler.run``'s loop body fails the lint
unless allow-listed.
"""

import inspect
import re

# (?<![\w.]) on np.asarray keeps jnp.asarray — a host->device upload,
# dispatch-only — from false-positives; bare np.asarray IS a readback
BANNED = re.compile(
    r"(?<![\w.])float\(|\.item\(\)|(?<![\w.])np\.asarray|device_get"
)
MARKER = "sync-ok"


def _step_loop_body():
    """Source lines of the ``for step_i in range(...)`` hot loop inside
    ``Trainer._fit_inner`` (by indentation, comments included)."""
    from distributeddeeplearning_tpu.train.loop import Trainer

    lines = inspect.getsource(Trainer._fit_inner).splitlines()
    start = next(
        i for i, line in enumerate(lines) if "for step_i in range" in line
    )
    indent = len(lines[start]) - len(lines[start].lstrip())
    body = []
    for line in lines[start + 1:]:
        if line.strip() and (len(line) - len(line.lstrip())) <= indent:
            break
        body.append(line)
    assert body, "could not locate the step loop body"
    return body


def test_trainer_step_loop_has_no_unmarked_host_sync():
    offenders = [
        line.strip()
        for line in _step_loop_body()
        if BANNED.search(line) and MARKER not in line
    ]
    assert not offenders, (
        "per-step host sync in Trainer.fit's hot loop — this serializes "
        "dispatch on every step.  Move it to the log-interval block, or if "
        "it is a deliberate documented price (like the anomaly detector's "
        f"per-step read) tag the line with '# {MARKER}':\n  "
        + "\n  ".join(offenders)
    )


def test_trainer_step_loop_allowlist_is_alive():
    """The lint must be exercising something: the anomaly detector's
    documented sync lines carry the marker (if they move out of the loop,
    update the lint's docstring story too)."""
    body = _step_loop_body()
    marked = [line for line in body if MARKER in line and BANNED.search(line)]
    assert marked, "no allow-listed sync lines found — lint may be scanning the wrong region"


def _serve_loop_body():
    """Source lines of the scheduler's ``while pending or active ...``
    decode loop inside ``ContinuousBatchingScheduler.run`` (by
    indentation, comments included) — the serving hot loop: one decode
    step per iteration, admission between steps."""
    from distributeddeeplearning_tpu.serve.scheduler import (
        ContinuousBatchingScheduler,
    )

    lines = inspect.getsource(ContinuousBatchingScheduler.run).splitlines()
    start = next(
        i for i, line in enumerate(lines)
        if "while pending or active" in line
    )
    indent = len(lines[start]) - len(lines[start].lstrip())
    body = []
    for line in lines[start + 1:]:
        if line.strip() and (len(line) - len(line.lstrip())) <= indent:
            break
        body.append(line)
    assert body, "could not locate the serve decode loop body"
    return body


def test_serve_decode_loop_has_no_unmarked_host_sync():
    """Same lint as the trainer loop, for the serving hot path: the
    scheduler's ONE designed host sync is the token readback inside
    ``engine.decode`` (the host-side scheduler needs the sampled ids to
    admit/release slots) — anything else (``float(``/``.item()``/
    ``np.asarray``/``device_get``) in the loop body is a new per-step
    stall and must carry a ``# sync-ok`` marker with its justification."""
    body = _serve_loop_body()
    # right-region guard: the loop we grep must be the one that decodes
    assert any("engine.decode" in line for line in body), (
        "serve lint is not scanning the decode loop"
    )
    offenders = [
        line.strip()
        for line in body
        if BANNED.search(line) and MARKER not in line
    ]
    assert not offenders, (
        "per-step host sync in the serve scheduler's decode loop — this "
        "serializes dispatch against every decode step.  Move it to the "
        "end-of-run report block, or tag a deliberate documented price "
        f"with '# {MARKER}':\n  " + "\n  ".join(offenders)
    )


def _fleet_dispatch_loop_body():
    """Source lines of the fleet router's dispatch loop inside
    ``FleetRouter.serve`` (by indentation, comments included) — the
    cross-process serving hot loop: queue pumps, health checks and
    least-loaded dispatch between the workers' decode steps."""
    from distributeddeeplearning_tpu.serve.fleet import FleetRouter

    lines = inspect.getsource(FleetRouter.serve).splitlines()
    start = next(
        i for i, line in enumerate(lines)
        if "while len(results) < len(flights)" in line
    )
    indent = len(lines[start]) - len(lines[start].lstrip())
    body = []
    for line in lines[start + 1:]:
        if line.strip() and (len(line) - len(line.lstrip())) <= indent:
            break
        body.append(line)
    assert body, "could not locate the fleet dispatch loop body"
    return body


def test_fleet_dispatch_loop_has_no_unmarked_host_sync():
    """The router is host bookkeeping by design — its ONE blocking call
    is the outbox get with a short timeout (the idle wait on worker
    messages, not a device sync).  Any device-value token (``float(``/
    ``.item()``/``np.asarray``/``device_get``) appearing in the dispatch
    loop means engine state leaked across the process boundary into the
    router's per-iteration path; that must carry a ``# sync-ok`` marker
    with its justification or move into the workers."""
    body = _fleet_dispatch_loop_body()
    # right-region guard: the loop we grep must be the one that pumps the
    # outbox and supervises replica health
    assert any("self._outbox.get" in line for line in body), (
        "fleet lint is not scanning the dispatch loop"
    )
    assert any("handle_death" in line for line in body), (
        "fleet lint is not scanning the supervision path"
    )
    offenders = [
        line.strip()
        for line in body
        if BANNED.search(line) and MARKER not in line
    ]
    assert not offenders, (
        "host-sync token in the fleet router's dispatch loop — the "
        "router must stay pure host bookkeeping (device values never "
        "cross the process boundary).  Move the work into the replica "
        "workers, or tag a deliberate documented price with "
        f"'# {MARKER}':\n  " + "\n  ".join(offenders)
    )


def _spec_step_body():
    """Source lines of ``SpeculativeDecoder.step`` — the draft->verify
    hot loop speculative serving runs once per scheduler iteration: K
    device-chained draft dispatches, one batched verify dispatch, and
    exactly ONE designed readback (the committed tokens + acceptance +
    finiteness riding a single sync)."""
    from distributeddeeplearning_tpu.spec.decode import SpeculativeDecoder

    return inspect.getsource(SpeculativeDecoder.step).splitlines()


def test_spec_draft_verify_loop_has_no_unmarked_host_sync():
    """The spec step's budget is the same as ``engine.decode``'s: one
    readback per step, everything else dispatch-only.  A host sync
    between draft dispatches would serialize the whole chain (K round
    trips instead of one), so any banned token here must carry a
    ``# sync-ok`` marker with its justification."""
    body = _spec_step_body()
    # right-region guards: the source we grep must contain BOTH halves
    # of the loop — the draft dispatch chain and the verify dispatch
    assert any("drafter.propose" in line for line in body), (
        "spec lint is not scanning the draft dispatch chain"
    )
    assert any("self._verify_jit" in line for line in body), (
        "spec lint is not scanning the verify dispatch"
    )
    offenders = [
        line.strip()
        for line in body
        if BANNED.search(line) and MARKER not in line
    ]
    assert not offenders, (
        "host-sync token in the spec draft->verify loop — a sync between "
        "draft dispatches serializes the chain into K round trips.  "
        "Batch it into the verify readback, or tag a deliberate "
        f"documented price with '# {MARKER}':\n  " + "\n  ".join(offenders)
    )


def test_spec_step_allowlist_is_alive():
    """The designed readback (committed tokens/acceptance/finiteness)
    carries the marker — if it moves, the lint must follow it."""
    body = _spec_step_body()
    marked = [
        line for line in body if MARKER in line and BANNED.search(line)
    ]
    assert marked, (
        "no allow-listed sync lines found in SpeculativeDecoder.step — "
        "lint may be scanning the wrong region"
    )


def test_step_builders_have_no_host_sync_tokens():
    from distributeddeeplearning_tpu.train import step as step_mod

    for fn in (step_mod.build_train_step, step_mod._build_comm_overlap_step,
               step_mod.build_eval_step):
        for line in inspect.getsource(fn).splitlines():
            code = line.split("#", 1)[0]
            assert not BANNED.search(code), (
                f"host-sync token inside jitted step builder "
                f"{fn.__name__}: {line.strip()!r}"
            )


# --- obs instrumentation (PR 6) ------------------------------------------
# The tracer lives INSIDE both hot loops now, so it gets the same
# treatment: its hot API must be sync-free, the instrumented regions must
# actually be instrumented (a silent revert would pass the greps above),
# and flipping the tracer on must not change what XLA compiled.


def test_tracer_hot_api_has_no_sync_tokens():
    """Everything on the span/event/record hot path is pure host
    bookkeeping — no device reads, ever (zero-sync by construction)."""
    from distributeddeeplearning_tpu.obs import registry as reg_mod
    from distributeddeeplearning_tpu.obs import trace as trace_mod

    hot = (
        trace_mod.Tracer.span,
        trace_mod.Tracer.event,
        trace_mod._Span.__enter__,
        trace_mod._Span.__exit__,
        trace_mod._NullSpan.__enter__,
        trace_mod._NullSpan.__exit__,
        reg_mod.Histogram.record,
        reg_mod.Counter.inc,
        reg_mod.Gauge.set,
    )
    for fn in hot:
        for line in inspect.getsource(fn).splitlines():
            if MARKER in line:  # documented host-scalar coercions
                continue
            code = line.split("#", 1)[0]
            assert not BANNED.search(code), (
                f"host-sync token in obs hot API {fn.__qualname__}: "
                f"{line.strip()!r}"
            )


def test_hot_loops_are_instrumented():
    """The tracer calls inside the two hot loops are load-bearing (the
    OBS timeline is built from them); the sync-lint above would not
    notice them silently disappearing."""
    assert any(
        "trace.span(" in line for line in _step_loop_body()
    ), "Trainer step loop lost its obs spans"
    assert any(
        "trace.span(" in line for line in _serve_loop_body()
    ), "serve decode loop lost its obs spans"


def test_disabled_then_enabled_tracer_adds_no_jit_recompiles():
    """Tracing is host-side only: enabling it mid-process must not grow
    any jitted executable cache (a tracer arg leaking into a jit
    signature would recompile every program and stall the hot path)."""
    import jax
    import numpy as np

    from distributeddeeplearning_tpu.models.pipelined_transformer import (
        init_params,
    )
    from distributeddeeplearning_tpu.obs import trace as trace_mod
    from distributeddeeplearning_tpu.serve import (
        ContinuousBatchingScheduler,
        PagedInferenceEngine,
        Request,
    )

    params = init_params(
        jax.random.key(0), num_layers=2, d_model=32, num_heads=2,
        d_ff=64, vocab_size=97, max_len=32,
    )
    engine = PagedInferenceEngine(
        params, num_heads=2, batch_slots=2, max_seq=32, page_size=8,
        prefill_chunk=8, rng=jax.random.key(1),
    )
    rng = np.random.default_rng(0)

    def run():
        reqs = [
            Request(uid=f"r{i}", prompt=rng.integers(1, 97, 6).tolist())
            for i in range(3)
        ]
        ContinuousBatchingScheduler(engine, max_new_tokens=4).run(reqs)

    trace_mod.set_tracer(trace_mod.Tracer(enabled=False))
    try:
        run()  # compiles every shape with tracing OFF
        sizes_off = (
            engine._decode_jit._cache_size(),
            engine._chunk_jit._cache_size(),
            engine.prefill_compiles,
        )
        trace_mod.set_tracer(
            trace_mod.Tracer(enabled=True, annotate=False)
        )
        run()  # identical shapes with tracing ON
        sizes_on = (
            engine._decode_jit._cache_size(),
            engine._chunk_jit._cache_size(),
            engine.prefill_compiles,
        )
    finally:
        trace_mod.set_tracer(trace_mod.Tracer(enabled=False))
    assert sizes_on == sizes_off, (
        f"enabling the tracer changed compiled-program counts: "
        f"{sizes_off} -> {sizes_on}"
    )
