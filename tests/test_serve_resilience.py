"""Serving resilience (PR 7): deadlines, cancellation, NaN quarantine,
admission validation, shedding, drain, retry counters, and the supervised
multi-replica fleet's failover story.

The fleet tests spawn real engine worker processes (multiprocessing
spawn, each paying a jax import + engine compile), so they sit at the
slow end of the suite — but they are the only place the WHOLE failover
contract is exercised end to end: deterministic ``DDLT_FAULTS`` chaos
through ``deal_serve_faults``, requeue-with-preserved-tokens, and the
bit-identical-greedy gate against a fault-free fleet.
"""

import threading
import time

import jax
import numpy as np
import pytest

from distributeddeeplearning_tpu.models.pipelined_transformer import (
    init_params,
)
from distributeddeeplearning_tpu.serve import (
    ContinuousBatchingScheduler,
    InferenceEngine,
    PagedInferenceEngine,
    ReplicaSpec,
    Request,
    serve_fleet,
    synthetic_requests,
)
from distributeddeeplearning_tpu.utils import faults as faults_mod

CFG = dict(num_layers=2, d_model=32, num_heads=4, d_ff=64, vocab_size=61,
           max_len=32)
HEADS = CFG["num_heads"]


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.key(0), **CFG)


@pytest.fixture(autouse=True)
def _clean_fault_plan():
    """Tests install explicit plans; none may leak into the next test."""
    yield
    faults_mod.install_plan("")


def _dense(params, **kw):
    kw.setdefault("num_heads", HEADS)
    kw.setdefault("batch_slots", 2)
    kw.setdefault("max_seq", 24)
    return InferenceEngine(params, **kw)


def _paged(params, **kw):
    kw.setdefault("num_heads", HEADS)
    kw.setdefault("batch_slots", 2)
    kw.setdefault("max_seq", 24)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_chunk", 8)
    return PagedInferenceEngine(params, **kw)


# --------------------------------------------------------------------------
# fault grammar: serve-side kinds, dealing, stripping
# --------------------------------------------------------------------------


def test_serve_fault_kinds_parse_and_deal_round_robin():
    text = "replica_death@3,decode_nan@5,io_error@p=0.5,decode_stall@8:secs=0.2"
    dealt = faults_mod.deal_serve_faults(text, 2)
    # serve kinds deal round-robin (one replica each); io_error replicates
    assert "replica_death@3" in dealt[0]
    assert "decode_nan@5" in dealt[1]
    assert "decode_stall@8:secs=0.2" in dealt[0]
    for entry in dealt:
        assert "io_error@p=0.5" in entry
    # an explicit :replica=k option wins over round-robin
    dealt = faults_mod.deal_serve_faults("replica_death@3:replica=1", 2)
    assert "replica_death" not in dealt[0]
    assert "replica_death@3:replica=1" in dealt[1]


def test_strip_kinds_removes_only_the_named_kinds():
    text = "replica_death@3,decode_nan@5,io_error@p=0.5"
    out = faults_mod.strip_kinds(text, ("replica_death",))
    assert "replica_death" not in out
    assert "decode_nan@5" in out and "io_error@p=0.5" in out


def test_replica_death_fires_at_or_after_armed_step_once():
    plan = faults_mod.FaultPlan(faults_mod.parse_spec("replica_death@3"))
    assert not plan.take_replica_death(2)
    # decode steps can jump past the armed step (e.g. no eligible work at
    # exactly step 3): at-or-after still fires, exactly once
    assert plan.take_replica_death(5)
    assert not plan.take_replica_death(6)


def test_reject_admit_fires_at_nth_admission_opportunity():
    plan = faults_mod.FaultPlan(faults_mod.parse_spec("reject_admit@2"))
    assert not plan.maybe_reject_admit()   # opportunity 1
    assert plan.maybe_reject_admit()       # opportunity 2: the Nth
    assert not plan.maybe_reject_admit()   # one-shot


# --------------------------------------------------------------------------
# retry counters (utils/retry -> obs registry)
# --------------------------------------------------------------------------


def test_retry_counters_match_injected_io_error_sequence():
    from distributeddeeplearning_tpu.obs.registry import get_registry
    from distributeddeeplearning_tpu.utils.retry import retry_call

    reg = get_registry()
    plan = faults_mod.install_plan("io_error@2")

    def flaky():
        plan.maybe_io_error("test site")
        return "ok"

    label = "serve resilience test"
    attempts = reg.counter("retry.attempts.serve_resilience_test")
    giveups = reg.counter("retry.giveups.serve_resilience_test")
    a0, g0 = attempts.value, giveups.value
    # opportunity 1 passes; opportunity 2 raises once, the retry (opp 3)
    # succeeds — exactly one attempt counted, no giveup
    assert retry_call(flaky, retries=2, base_delay=0.0,
                      description=label) == "ok"
    assert retry_call(flaky, retries=2, base_delay=0.0,
                      description=label) == "ok"
    assert attempts.value - a0 == 1
    assert giveups.value - g0 == 0

    # an always-failing site: every retry counted, then one giveup
    plan = faults_mod.install_plan("io_error@p=1.0")

    def doomed():
        plan.maybe_io_error("test site")

    with pytest.raises(IOError):
        retry_call(doomed, retries=3, base_delay=0.0, description=label)
    assert attempts.value - a0 == 1 + 3
    assert giveups.value - g0 == 1


# --------------------------------------------------------------------------
# scheduler: deadlines, cancellation, shedding, drain
# --------------------------------------------------------------------------


class _SlowFake:
    """Host-only engine: one token per decode, each decode sleeps."""

    batch_slots = 2
    max_seq = 64

    def __init__(self, step_s=0.02):
        self.step_s = step_s

    def prefill(self, slot, prompt):
        return 1

    def decode(self, tokens, pos):
        time.sleep(self.step_s)
        return np.full(self.batch_slots, 2, np.int32)


def test_deadline_expires_queued_request_without_admission():
    sched = ContinuousBatchingScheduler(_SlowFake(), max_new_tokens=4)
    results, report = sched.run([
        Request("ok", [1, 2]),
        Request("late", [3], deadline_s=1e-9),  # expired before admission
        Request("ok2", [4]),
    ])
    by_uid = {r.uid: r for r in results}
    assert by_uid["late"].finish_reason == "deadline"
    assert by_uid["late"].tokens == []
    assert by_uid["ok"].finish_reason == "length"
    assert by_uid["ok2"].finish_reason == "length"
    assert report.finish_reasons["deadline"] == 1


def test_deadline_cuts_active_request_and_keeps_partial_tokens():
    sched = ContinuousBatchingScheduler(
        _SlowFake(step_s=0.05), max_new_tokens=1000,
    )
    results, _ = sched.run([Request("r", [1, 2], deadline_s=0.2)])
    (res,) = results
    assert res.finish_reason == "deadline"
    assert len(res.tokens) >= 1  # partial output kept
    assert len(res.tokens) < 1000


def test_scheduler_default_deadline_applies_when_request_has_none():
    sched = ContinuousBatchingScheduler(
        _SlowFake(step_s=0.05), max_new_tokens=1000,
        request_deadline_s=0.2,
    )
    results, _ = sched.run([Request("r", [1])])
    assert results[0].finish_reason == "deadline"


def test_request_cancel_finishes_cancelled_with_partial_tokens():
    sched = ContinuousBatchingScheduler(
        _SlowFake(step_s=0.01), max_new_tokens=1000,
    )

    def on_step(step):
        if step == 3:
            sched.request_cancel("r")

    results, _ = sched.run([Request("r", [1])], on_step=on_step)
    (res,) = results
    assert res.finish_reason == "cancelled"
    assert 1 <= len(res.tokens) < 1000


def test_reject_admit_fault_sheds_request(params):
    faults_mod.install_plan("reject_admit@1")
    engine = _dense(params)
    sched = ContinuousBatchingScheduler(engine, max_new_tokens=3)
    results, report = sched.run([Request("a", [1, 2]), Request("b", [3])])
    by_uid = {r.uid: r for r in results}
    shed = [r for r in results if r.finish_reason == "shed"]
    assert len(shed) == 1           # only the Nth admission opportunity
    assert report.finish_reasons["shed"] == 1
    survivors = [r for r in results if r.finish_reason == "length"]
    assert len(survivors) == 1
    assert by_uid[shed[0].uid].tokens == []


def test_should_drain_preempts_queue_and_finishes_active():
    sched = ContinuousBatchingScheduler(
        _SlowFake(step_s=0.01), max_new_tokens=5,
    )
    steps = []

    def should_drain():
        return len(steps) >= 2

    results, report = sched.run(
        [Request("a", [1]), Request("b", [2]), Request("c", [3]),
         Request("d", [4])],
        should_drain=should_drain,
        on_step=steps.append,
    )
    by_uid = {r.uid: r for r in results}
    assert report.drained
    reasons = report.finish_reasons
    # slots = 2: a/b were decoding (finish normally), c/d were queued
    assert reasons.get("length") == 2
    assert reasons.get("preempted") == 2
    for uid in ("c", "d"):
        assert by_uid[uid].tokens == []


def test_duplicate_uid_rejected_without_corrupting_first_copy():
    """A second in-flight copy of a uid finishes "error" at intake; the
    first copy's bookkeeping survives and completes normally (the
    duplicate must not tear down the original's live meta entry)."""
    sched = ContinuousBatchingScheduler(_SlowFake(step_s=0.005),
                                        max_new_tokens=3)
    results, report = sched.run([
        Request("dup", [1, 2]),
        Request("dup", [3]),
        Request("ok", [4]),
    ])
    assert len(results) == 3
    dup_reasons = sorted(
        r.finish_reason for r in results if r.uid == "dup"
    )
    assert dup_reasons == ["error", "length"]
    err = next(
        r for r in results
        if r.uid == "dup" and r.finish_reason == "error"
    )
    assert "duplicate uid" in err.error
    assert report.errors == 1


def test_live_mode_latency_measured_from_arrival_not_run_start():
    """In live mode the loop may be arbitrarily old when a request
    arrives: queue_wait/ttft/total must be measured from the request's
    ARRIVAL, not from run() start."""
    calls = {"n": 0}

    def poll():
        calls["n"] += 1
        if calls["n"] < 200:
            return []          # ~200 idle iterations (>=0.2 s of sleeps)
        if calls["n"] == 200:
            return [Request("late", [1, 2])]
        return None            # source closed

    sched = ContinuousBatchingScheduler(_SlowFake(step_s=0.001),
                                        max_new_tokens=2)
    results, _ = sched.run([], poll=poll)
    (res,) = results
    assert res.finish_reason == "length"
    # run-start-based numbers would all be >= the ~0.2 s idle window
    assert res.queue_wait_s < 0.15
    assert res.ttft_s < 0.15
    assert res.total_s < 0.15


def test_scheduler_watchdog_fires_on_stalled_decode():
    """``watchdog_deadline_s`` arms train/resilience.StepWatchdog over the
    loop: an injected ``decode_stall`` longer than the deadline fires it
    (here the test override records the firing instead of the production
    exit-70 a fleet supervisor would restart)."""
    faults_mod.install_plan("decode_stall@2:secs=1.0")
    fired = threading.Event()
    sched = ContinuousBatchingScheduler(
        _SlowFake(step_s=0.005), max_new_tokens=6,
        watchdog_deadline_s=0.25,
        watchdog_on_timeout=fired.set,
    )
    results, _ = sched.run([Request("r", [1])])
    assert fired.is_set()
    # with the exit overridden the loop recovers once the stall clears
    assert results[0].finish_reason == "length"


def test_scheduler_watchdog_quiet_without_stall():
    fired = threading.Event()
    sched = ContinuousBatchingScheduler(
        _SlowFake(step_s=0.005), max_new_tokens=6,
        watchdog_deadline_s=5.0,
        watchdog_on_timeout=fired.set,
    )
    sched.run([Request("r", [1])])
    assert not fired.is_set()


# --------------------------------------------------------------------------
# admission validation: empty / oversized prompts (both layouts)
# --------------------------------------------------------------------------


@pytest.mark.parametrize("layout", ["dense", "paged"])
def test_admission_rejects_empty_and_oversized_prompts(params, layout):
    engine = _dense(params) if layout == "dense" else _paged(params)
    sched = ContinuousBatchingScheduler(engine, max_new_tokens=2)
    results, report = sched.run([
        Request("empty", []),
        Request("huge", list(range(1, 30))),  # >= max_seq=24: no room
        Request("ok", [1, 2, 3]),
    ])
    by_uid = {r.uid: r for r in results}
    assert by_uid["empty"].finish_reason == "error"
    assert "empty prompt" in by_uid["empty"].error
    assert by_uid["huge"].finish_reason == "error"
    assert "no room" in by_uid["huge"].error
    assert by_uid["ok"].finish_reason == "length"
    assert report.errors == 2


# --------------------------------------------------------------------------
# decode-NaN quarantine: only the poisoned request fails (both layouts)
# --------------------------------------------------------------------------


@pytest.mark.parametrize("layout", ["dense", "paged"])
def test_decode_nan_quarantine_fails_only_poisoned_request(params, layout):
    build = _dense if layout == "dense" else _paged
    prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9]]

    def run(faults):
        faults_mod.install_plan(faults)
        engine = build(params)
        sched = ContinuousBatchingScheduler(engine, max_new_tokens=6)
        results, report = sched.run([
            Request(f"r{i}", p) for i, p in enumerate(prompts)
        ])
        return {r.uid: r for r in results}, report

    clean, _ = run("")
    faulted, report = run("decode_nan@3")
    assert report.quarantined == 1
    poisoned = [u for u, r in faulted.items() if r.finish_reason == "error"]
    assert len(poisoned) == 1
    assert "non-finite" in faulted[poisoned[0]].error
    # the poisoned request kept the tokens generated before the poison,
    # and they match the clean run's prefix (the fault corrupts the
    # CACHE, not the already-emitted stream)
    pt = faulted[poisoned[0]].tokens
    assert pt == clean[poisoned[0]].tokens[: len(pt)]
    # everyone else decodes on, bit-identical
    for uid, res in faulted.items():
        if uid == poisoned[0]:
            continue
        assert res.finish_reason == "length"
        assert res.tokens == clean[uid].tokens, uid


def test_quarantined_slot_is_scrubbed_for_next_occupant(params):
    """After a quarantine the freed slot must serve the next request
    cleanly: no NaN survives in the scrubbed cache region."""
    faults_mod.install_plan("decode_nan@2")
    engine = _paged(params, batch_slots=1)
    sched = ContinuousBatchingScheduler(engine, max_new_tokens=5)
    results, report = sched.run([
        Request("victim", [1, 2, 3]),
        Request("next", [4, 5]),
    ])
    by_uid = {r.uid: r for r in results}
    assert report.quarantined == 1
    assert by_uid["victim"].finish_reason == "error"
    assert by_uid["next"].finish_reason == "length"  # slot reuse is clean
    assert len(by_uid["next"].tokens) == 5


# --------------------------------------------------------------------------
# the fleet: failover, restarts, bounded redelivery, drain (slow)
# --------------------------------------------------------------------------

FLEET_MODEL = dict(num_layers=1, d_model=16, num_heads=2, d_ff=32,
                   vocab_size=97, max_len=32)


def _fleet_spec(**kw):
    kw.setdefault("model", FLEET_MODEL)
    kw.setdefault("seed", 0)
    kw.setdefault("num_heads", 2)
    kw.setdefault("batch_slots", 2)
    kw.setdefault("max_seq", 32)
    kw.setdefault("kv_layout", "paged")
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("max_new_tokens", 8)
    return ReplicaSpec(**kw)


@pytest.mark.timeout(280)
def test_fleet_fault_matrix_failover_is_bit_identical():
    """ISSUE 7 acceptance (test half): a 2-replica fleet driven through
    ``replica_death@3,decode_nan@5,decode_stall@8:secs=0.2`` — the death's
    in-flight requests fail over with preserved tokens (greedy output
    bit-identical to the fault-free fleet), redelivery stays bounded, and
    ``finish_reasons`` accounts for every request exactly once."""
    spec = _fleet_spec()
    reqs = synthetic_requests(
        8, vocab_size=FLEET_MODEL["vocab_size"], max_prompt=10,
        rng=np.random.default_rng(0),
    )
    clean_res, clean_rep = serve_fleet(spec, reqs, replicas=2, faults="")
    assert clean_rep.completed_ok == len(reqs)
    assert clean_rep.lost_requests == 0

    fault_res, fault_rep = serve_fleet(
        spec, reqs, replicas=2, max_restarts=1, max_redeliveries=2,
        faults="replica_death@3,decode_nan@5,decode_stall@8:secs=0.2",
    )
    # every request reached exactly one terminal state
    assert sorted(r.uid for r in fault_res) == sorted(r.uid for r in reqs)
    assert sum(fault_rep.finish_reasons.values()) == len(reqs)
    # the death was detected, survivors absorbed the in-flight work, the
    # replica restarted, and nothing was lost
    assert fault_rep.replica_deaths == 1
    assert fault_rep.restarts == 1
    assert fault_rep.redeliveries >= 1
    assert fault_rep.lost_requests == 0
    # bounded redelivery: at most first delivery + max_redeliveries each
    assert fault_rep.redeliveries <= len(reqs) * 2
    # quarantine precision: exactly the poisoned request failed
    errors = [r for r in fault_res if r.finish_reason == "error"]
    assert len(errors) == 1 and "non-finite" in errors[0].error
    # and the headline: every surviving request's greedy tokens are
    # bit-identical to the fault-free fleet's
    clean_tokens = {r.uid: r.tokens for r in clean_res}
    for r in fault_res:
        if r.finish_reason in ("eos", "length"):
            assert r.tokens == clean_tokens[r.uid], r.uid


@pytest.mark.timeout(280)
def test_fleet_death_without_restart_budget_still_completes_on_survivor():
    spec = _fleet_spec()
    reqs = synthetic_requests(
        6, vocab_size=FLEET_MODEL["vocab_size"], max_prompt=8,
        rng=np.random.default_rng(1),
    )
    results, report = serve_fleet(
        spec, reqs, replicas=2, max_restarts=0, faults="replica_death@2",
    )
    assert report.replica_deaths == 1
    assert report.restarts == 0
    assert report.lost_requests == 0
    assert report.completed_ok == len(reqs)  # survivor served everything


@pytest.mark.timeout(280)
def test_fleet_drain_preempts_unfinished_and_reports_drained():
    spec = _fleet_spec(max_new_tokens=16)
    from distributeddeeplearning_tpu.serve.fleet import FleetRouter

    router = FleetRouter(_fleet_spec(max_new_tokens=16), replicas=2,
                         faults="")
    del spec
    reqs = synthetic_requests(
        12, vocab_size=FLEET_MODEL["vocab_size"], max_prompt=8,
        rng=np.random.default_rng(2),
    )
    # drain once the fleet is actually serving (first replica up)
    stop = threading.Event()

    def drain_when_live():
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and not stop.is_set():
            if any(m.ready for m in router._members):
                router.drain()
                return
            time.sleep(0.05)

    t = threading.Thread(target=drain_when_live, daemon=True)
    t.start()
    try:
        results, report = router.serve(reqs)
    finally:
        stop.set()
        t.join(timeout=5)
    assert report.drained
    # every request reached a terminal state; whatever had not finished
    # came back "preempted" for the control plane to resubmit
    assert sum(report.finish_reasons.values()) == len(reqs)
    assert report.lost_requests == 0
    for r in results:
        assert r.finish_reason in ("eos", "length", "preempted")


# --------------------------------------------------------------------------
# SERVE_RESILIENCE schema: rejection cases
# --------------------------------------------------------------------------


def test_serve_resilience_schema_rejects_drifted_payloads():
    from distributeddeeplearning_tpu.obs.schema import (
        SchemaError,
        validate_serve_resilience_payload,
    )

    def minimal():
        rep = {
            "replicas": 2, "requests": 8, "wall_s": 1.0,
            "goodput_tokens_per_sec": 10.0, "finish_reasons": {"length": 8},
            "ttft_s": {"p50": 0.1, "p99": 0.2}, "tpot_s": {},
            "restarts": 0, "replica_deaths": 0, "redeliveries": 0,
            "lost_requests": 0, "drained": False,
        }
        import copy

        return {
            "metric": "serve_fleet_chaos_recovery_overhead_pct",
            "value": 10.0, "unit": "%", "bench_revision": 12,
            "platform": "cpu", "virtual_pod": True,
            "faults_spec": "replica_death@3", "replicas": 2,
            "recovery_overhead_pct": 10.0, "tokens_bit_identical": True,
            "fleet_events": {"fleet/replica_died": 1},
            "gates": {
                "zero_lost_requests": True, "tokens_bit_identical": True,
                "only_poisoned_failed": True,
                "recovery_overhead_under_limit": True,
            },
            "clean": copy.deepcopy(rep),
            "faulted": {**copy.deepcopy(rep), "replica_deaths": 1,
                        "restarts": 1, "redeliveries": 3},
        }

    validate_serve_resilience_payload(minimal())  # the happy path

    bad = minimal()
    del bad["faulted"]["lost_requests"]
    with pytest.raises(SchemaError, match="lost_requests"):
        validate_serve_resilience_payload(bad)

    bad = minimal()
    bad["gates"]["zero_lost_requests"] = "yes"  # not a bool
    with pytest.raises(SchemaError, match="zero_lost_requests"):
        validate_serve_resilience_payload(bad)

    bad = minimal()
    bad["faulted"]["replica_deaths"] = 1
    bad["fleet_events"] = {}
    with pytest.raises(SchemaError, match="replica_died"):
        validate_serve_resilience_payload(bad)
