"""Control-plane tests: composed gcloud command lines, idempotency, submits.

The reference's control plane shells out to az/azcopy and is untested
(SURVEY.md §4); here every cloud interaction goes through CommandRunner, so
these tests assert the exact composed command lines with a fake runner — no
cloud access, the contract VERDICT.md round 1 asked for.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import pytest

from distributeddeeplearning_tpu.config.settings import load_config
from distributeddeeplearning_tpu.control.command import (
    CommandError,
    CommandResult,
    CommandRunner,
)
from distributeddeeplearning_tpu.control.runs import RunRegistry
from distributeddeeplearning_tpu.control.storage import (
    GcsStorage,
    count_jpegs,
    generate_tfrecords_gated,
)
from distributeddeeplearning_tpu.control.submit import (
    Submitter,
    complete_datastore_paths,
    params_to_flags,
)
from distributeddeeplearning_tpu.control.tpu import TpuPod, topology_from_type


class FakeRunner(CommandRunner):
    """Records argv+env; responds via predicates instead of executing."""

    def __init__(
        self,
        responses: Optional[
            List[Tuple[Callable[[List[str]], bool], CommandResult]]
        ] = None,
    ):
        super().__init__()
        self.responses = responses or []
        self.envs: List[Optional[dict]] = []
        self.streams: List[Optional[str]] = []

    def run(self, argv, *, check=True, capture=True, env=None, timeout=None,
            stream_to=None, retries=0):
        argv = [str(a) for a in argv]
        self.history.append(argv)
        self.envs.append(env)
        self.streams.append(stream_to)
        for predicate, result in self.responses:
            if predicate(argv):
                if check and result.returncode != 0:
                    raise CommandError(argv, result.returncode, "", "")
                return CommandResult(
                    argv=argv,
                    returncode=result.returncode,
                    stdout=result.stdout,
                    stderr=result.stderr,
                )
        return CommandResult(argv=argv, returncode=0)


def _describe_missing(argv):
    return "describe" in argv


def make_pod(runner, **overrides):
    kwargs = dict(
        name="test-pod",
        zone="us-central2-b",
        accelerator_type="v5litepod-32",
        runtime_version="v2-alpha-tpuv5-lite",
        project="proj-1",
    )
    kwargs.update(overrides)
    return TpuPod(runner, **kwargs)


class TestTopology:
    def test_v5e(self):
        assert topology_from_type("v5litepod-32") == {"chips": 32, "hosts": 4}
        assert topology_from_type("v5litepod-8") == {"chips": 8, "hosts": 1}

    def test_core_suffixed_generations(self):
        # v4-32 = 32 cores = 16 chips = 4 hosts
        assert topology_from_type("v4-32") == {"chips": 16, "hosts": 4}
        assert topology_from_type("v3-8") == {"chips": 4, "hosts": 1}

    def test_invalid(self):
        with pytest.raises(ValueError):
            topology_from_type("h100-8")


class TestTpuPod:
    def test_create_composes_gcloud_create_when_missing(self):
        runner = FakeRunner(
            [(_describe_missing, CommandResult([], returncode=1))]
        )
        pod = make_pod(runner)
        assert pod.create() is True
        create = runner.history[-1]
        assert create[:5] == ["gcloud", "compute", "tpus", "tpu-vm", "create"]
        assert "test-pod" in create
        assert ["--zone", "us-central2-b"] == create[create.index("--zone"):][:2]
        assert "--accelerator-type" in create and "v5litepod-32" in create
        assert "--project" in create and "proj-1" in create

    def test_create_is_idempotent_when_pod_exists(self):
        runner = FakeRunner()  # describe returns rc=0 -> exists
        pod = make_pod(runner)
        assert pod.create() is False
        assert all("create" not in argv for argv in runner.history)

    def test_ssh_fans_out_with_env_injection(self):
        runner = FakeRunner()
        pod = make_pod(runner)
        pod.ssh("python3 -m foo", env={"DISTRIBUTED": "True", "A": "1"})
        argv = runner.history[-1]
        assert "ssh" in argv and "--worker" in argv
        assert argv[argv.index("--worker") + 1] == "all"
        command = argv[argv.index("--command") + 1]
        # sorted env exports prefix the command
        assert command.startswith("export A=1 DISTRIBUTED=True && ")
        assert command.endswith("python3 -m foo")

    def test_interactive_composes_plain_ssh(self):
        runner = FakeRunner()
        pod = make_pod(runner)
        pod.interactive(worker="2")
        argv = runner.history[-1]
        assert "ssh" in argv and argv[argv.index("--worker") + 1] == "2"
        assert "--command" not in argv  # interactive shell, not a command

    def test_preemptible_flag(self):
        runner = FakeRunner(
            [(_describe_missing, CommandResult([], returncode=1))]
        )
        pod = make_pod(runner, preemptible=True)
        pod.create()
        assert "--preemptible" in runner.history[-1]


class TestStorage:
    def test_ensure_bucket_creates_and_persists(self, tmp_path):
        env_file = tmp_path / ".env"
        cfg = load_config(env_file)
        runner = FakeRunner(
            [(_describe_missing, CommandResult([], returncode=1))]
        )
        storage = GcsStorage(
            runner, bucket="my-bucket", project="p", location="us-central2"
        )
        assert storage.ensure_bucket(cfg) is True
        create = runner.history[-1]
        assert create[:4] == ["gcloud", "storage", "buckets", "create"]
        assert "gs://my-bucket" in create
        # store_key write-back parity (scripts/storage.py:77-78)
        assert "GCS_BUCKET=my-bucket" in env_file.read_text()

    def test_ensure_bucket_idempotent(self):
        runner = FakeRunner()  # describe ok -> exists
        storage = GcsStorage(runner, bucket="b")
        assert storage.ensure_bucket() is False
        assert all("create" not in argv for argv in runner.history)

    def test_gs_prefix_stripped(self):
        storage = GcsStorage(FakeRunner(), bucket="gs://b2")
        assert storage.url == "gs://b2"

    def test_upload_images_rsyncs_both_splits(self, tmp_path):
        runner = FakeRunner()
        storage = GcsStorage(runner, bucket="b")
        storage.upload_images(str(tmp_path))
        rsyncs = [a for a in runner.history if "rsync" in a]
        assert len(rsyncs) == 2
        assert rsyncs[0][-1] == "gs://b/images/train"
        assert rsyncs[1][-1] == "gs://b/images/validation"

    def test_download_tfrecords_makes_local_dir(self, tmp_path):
        runner = FakeRunner()
        storage = GcsStorage(runner, bucket="b")
        target = tmp_path / "tfr"
        storage.download_tfrecords(str(target))
        assert target.exists()
        assert runner.history[-1][-2] == "gs://b/tfrecords"

    def test_count_jpegs_and_gate(self, tmp_path):
        (tmp_path / "train" / "n01").mkdir(parents=True)
        (tmp_path / "validation" / "n01").mkdir(parents=True)
        (tmp_path / "train" / "n01" / "a.JPEG").write_bytes(b"x")
        (tmp_path / "validation" / "n01" / "b.jpg").write_bytes(b"x")
        assert count_jpegs(tmp_path / "train") == 1
        with pytest.raises(RuntimeError, match="refusing to convert"):
            generate_tfrecords_gated(str(tmp_path), str(tmp_path / "out"))


class TestDatastoreTemplating:
    def test_placeholder_rewritten(self):
        params = {
            "training_data_path": "{datastore}/tfrecords",
            "epochs": 3,
            "note": "plain",
        }
        out = complete_datastore_paths(params, "gs://bucket")
        assert out["training_data_path"] == "gs://bucket/tfrecords"
        assert out["epochs"] == 3 and out["note"] == "plain"

    def test_params_to_flags(self):
        flags = params_to_flags(
            {"epochs": 2, "resume": True, "skip": None, "name": "x"}
        )
        assert flags == ["--epochs", "2", "--resume", "true", "--name", "x"]


@pytest.fixture
def submit_env(tmp_path):
    env_file = tmp_path / ".env"
    env_file.write_text(
        "GCS_BUCKET=bkt\nTPU_NAME=pod-a\nTPU_TYPE=v5litepod-16\n"
        "GCP_ZONE=us-west4-a\nEXPERIMENT_NAME=exp1\n"
        f"PROJECT_DIR={tmp_path}\n"  # preemption retries refuse to ship cwd
    )
    cfg = load_config(env_file)
    runner = FakeRunner([(_describe_missing, CommandResult([], returncode=1))])
    registry = RunRegistry(tmp_path / "runs")
    return cfg, runner, registry


class TestSubmitter:
    def test_remote_composes_per_host_command(self, submit_env):
        cfg, runner, registry = submit_env
        submitter = Submitter(cfg, runner, registry)
        run = submitter.submit_remote(
            "imagenet",
            {
                "data_format": "tfrecords",
                "training_data_path": "{datastore}/tfrecords",
                "epochs": 2,
            },
        )
        # get-or-create happened (describe failed -> create composed)
        assert any("create" in argv for argv in runner.history)
        ssh = runner.history[-1]
        assert "ssh" in ssh and "pod-a" in ssh
        assert ssh[ssh.index("--worker") + 1] == "all"
        command = ssh[ssh.index("--command") + 1]
        assert "DISTRIBUTED=True" in command
        assert "-m distributeddeeplearning_tpu.workloads.imagenet" in command
        assert "--training_data_path gs://bkt/tfrecords" in command
        assert "--save_filepath gs://bkt/runs/exp1/" in command
        assert run.status == "completed" and run.mode == "remote"
        assert registry.runs("exp1")[0].run_id == run.run_id

    def _preemption_runner(self, *, pod_state: str, fail_ssh_times: int):
        """ssh fails ``fail_ssh_times`` times then succeeds; describe
        reports ``pod_state``."""
        counters = {"ssh": 0}

        def ssh_fails(argv):
            # count only workload launches; bootstrap's pip-install ssh and
            # scp must succeed
            if "ssh" not in argv or not any("workloads." in a for a in argv):
                return False
            counters["ssh"] += 1
            return counters["ssh"] <= fail_ssh_times

        def describe_queued(argv):
            # No queued-resource request exists for these on-demand pods —
            # a real gcloud describe of an absent request exits nonzero.
            return "queued-resources" in argv and "describe" in argv

        def describe(argv):
            return "tpu-vm" in argv and "describe" in argv

        return FakeRunner(
            [
                (ssh_fails, CommandResult([], returncode=255)),
                (describe_queued, CommandResult([], returncode=1)),
                (
                    describe,
                    CommandResult(
                        [], returncode=0,
                        stdout='{"state": "%s"}' % pod_state,
                    ),
                ),
            ]
        )

    def test_remote_retries_on_preemption(self, submit_env):
        """Failed launch + non-READY pod → recreate + resubmit, then the
        run completes (the preemption handling the reference lacks)."""
        cfg, _, registry = submit_env
        runner = self._preemption_runner(
            pod_state="PREEMPTED", fail_ssh_times=1
        )
        submitter = Submitter(cfg, runner, registry)
        run = submitter.submit_remote(
            "imagenet", {"data_format": "synthetic"}, max_retries=1
        )
        assert run.status == "completed"
        ssh_calls = [
            a for a in runner.history
            if "ssh" in a and any("workloads." in x for x in a)
        ]
        assert len(ssh_calls) == 2
        assert ssh_calls[0][ssh_calls[0].index("--command") + 1] == (
            ssh_calls[1][ssh_calls[1].index("--command") + 1]
        )  # identical resubmit (resume comes from the checkpoint dir)
        assert any("delete" in a for a in runner.history)  # recreate path
        # fresh VMs get re-bootstrapped (scp + pip install) before resubmit
        assert any("scp" in a for a in runner.history)
        assert any(
            "pip install" in a[a.index("--command") + 1]
            for a in runner.history
            if "ssh" in a and "--command" in a
        )

    def test_remote_retry_refuses_unset_project_dir(self, submit_env):
        """Preempted pod but no recorded PROJECT_DIR → the retry must give
        up rather than scp + pip-install whatever cwd the control process
        happens to run from."""
        cfg, _, registry = submit_env
        cfg.persist("PROJECT_DIR", "")
        runner = self._preemption_runner(
            pod_state="PREEMPTED", fail_ssh_times=1
        )
        submitter = Submitter(cfg, runner, registry)
        run = submitter.submit_remote(
            "imagenet", {"data_format": "synthetic"}, max_retries=1
        )
        assert run.status == "failed"
        assert not any("scp" in a for a in runner.history)

    def test_remote_no_retry_when_pod_ready(self, submit_env):
        """A workload failure on a healthy pod must NOT trigger recreate —
        the same code would fail the same way."""
        cfg, _, registry = submit_env
        runner = self._preemption_runner(pod_state="READY", fail_ssh_times=9)
        submitter = Submitter(cfg, runner, registry)
        run = submitter.submit_remote(
            "imagenet", {"data_format": "synthetic"}, max_retries=3
        )
        assert run.status == "failed"
        assert len([
            a for a in runner.history
            if "ssh" in a and any("workloads." in x for x in a)
        ]) == 1
        assert not any("delete" in a for a in runner.history)

    def test_recreate_failure_records_failed_not_running(self, submit_env):
        """Capacity stockout during recreate must not strand the run in
        'running' — it records 'failed' and stops."""
        cfg, _, registry = submit_env
        seen = {"deleted": False}

        def workload_ssh(argv):
            return "ssh" in argv and any("workloads." in a for a in argv)

        def delete_marks(argv):
            if "delete" in argv:
                seen["deleted"] = True
            return False  # observe only; default rc=0 applies

        def describe(argv):
            return "describe" in argv

        def create_after_delete(argv):
            # the recreate attempt hits a capacity stockout
            return "create" in argv and seen["deleted"]

        runner = FakeRunner(
            [
                (delete_marks, CommandResult([], returncode=0)),
                (workload_ssh, CommandResult([], returncode=255)),
                (create_after_delete, CommandResult([], returncode=1)),
                (
                    describe,
                    # exists (PREEMPTED) until deleted, then missing
                    CommandResult([], returncode=0,
                                  stdout='{"state": "PREEMPTED"}'),
                ),
            ]
        )

        # swap the describe response to missing once the pod was deleted
        orig_run = runner.run

        def run_with_state(argv, **kw):
            argv_s = [str(a) for a in argv]
            if "describe" in argv_s and seen["deleted"]:
                runner.history.append(argv_s)
                runner.envs.append(kw.get("env"))
                return CommandResult(argv=argv_s, returncode=1)
            return orig_run(argv, **kw)

        runner.run = run_with_state
        submitter = Submitter(cfg, runner, registry)
        run = submitter.submit_remote(
            "imagenet", {"data_format": "synthetic"}, max_retries=2
        )
        assert run.status == "failed"
        # the stockout aborted the retry: only one workload launch happened
        assert (
            len([a for a in runner.history if "ssh" in a
                 and any("workloads." in x for x in a)]) == 1
        )

    def test_remote_retry_default_from_settings(self, submit_env):
        cfg, _, registry = submit_env
        cfg.values["MAX_RETRIES"] = "2"
        runner = self._preemption_runner(
            pod_state="PREEMPTED", fail_ssh_times=2
        )
        submitter = Submitter(cfg, runner, registry)
        run = submitter.submit_remote("imagenet", {"data_format": "synthetic"})
        assert run.status == "completed"
        assert len([
            a for a in runner.history
            if "ssh" in a and any("workloads." in x for x in a)
        ]) == 3

    def test_remote_requires_bucket_for_datastore_paths(self, tmp_path):
        env_file = tmp_path / ".env"
        env_file.write_text("TPU_NAME=p\n")
        cfg = load_config(env_file)
        submitter = Submitter(cfg, FakeRunner(), RunRegistry(tmp_path / "r"))
        with pytest.raises(ValueError, match="GCS_BUCKET"):
            submitter.submit_remote(
                "imagenet", {"training_data_path": "{datastore}/x"}
            )

    def test_local_runs_entry_module_with_distributed_false(self, submit_env):
        cfg, runner, registry = submit_env
        submitter = Submitter(cfg, runner, registry)
        run = submitter.submit_local(
            "imagenet", {"data_format": "synthetic", "epochs": 1}
        )
        argv = runner.history[-1]
        assert argv[1:3] == ["-m", "distributeddeeplearning_tpu.workloads.imagenet"]
        assert "--data_format" in argv and "synthetic" in argv
        # local {datastore} resolution + DISTRIBUTED=False env switch
        assert runner.envs[-1]["DISTRIBUTED"] == "False"
        assert run.status == "completed" and run.mode == "local"

    def test_local_resolves_datastore_to_data_dir(self, submit_env):
        cfg, runner, registry = submit_env
        cfg.values["DATA_DIR"] = "/data"
        submitter = Submitter(cfg, runner, registry)
        submitter.submit_local(
            "imagenet", {"training_data_path": "{datastore}/images/train"}
        )
        argv = runner.history[-1]
        assert argv[argv.index("--training_data_path") + 1] == "/data/images/train"

    def test_failed_local_run_recorded(self, submit_env):
        cfg, runner, registry = submit_env
        runner.responses.append(
            (lambda argv: "-m" in argv, CommandResult([], returncode=3))
        )
        submitter = Submitter(cfg, runner, registry)
        run = submitter.submit_local("imagenet", {"data_format": "synthetic"})
        assert run.status == "failed" and run.returncode == 3

    def test_unknown_workload_rejected(self, submit_env):
        cfg, runner, registry = submit_env
        with pytest.raises(ValueError, match="unknown workload"):
            Submitter(cfg, runner, registry).submit_local("nope", {})

    def test_experiment_prefers_local_scaffold_copy(
        self, submit_env, tmp_path, monkeypatch
    ):
        cfg, runner, registry = submit_env
        monkeypatch.chdir(tmp_path)
        (tmp_path / "experiment.py").write_text("# user scaffold\n")
        Submitter(cfg, runner, registry).submit_local("experiment", {})
        argv = runner.history[-1]
        assert "experiment.py" in argv  # the user's file, not the module

    def test_remote_command_is_shell_quoted(self, submit_env):
        cfg, runner, registry = submit_env
        submitter = Submitter(cfg, runner, registry)
        submitter.submit_remote(
            "imagenet", {"data_format": "synthetic", "note": "two words"}
        )
        ssh = runner.history[-1]
        command = ssh[ssh.index("--command") + 1]
        assert "'two words'" in command

    def test_bootstrap_pod_scp_and_install(self, submit_env):
        cfg, runner, registry = submit_env
        Submitter(cfg, runner, registry).bootstrap_pod("/src/proj")
        scp = [a for a in runner.history if "scp" in a]
        assert scp and "/src/proj" in scp[0]
        install = runner.history[-1]
        command = install[install.index("--command") + 1]
        assert "pip install" in command


class TestRunRegistry:
    def test_lifecycle_and_listing(self, tmp_path):
        registry = RunRegistry(tmp_path / "runs")
        run = registry.new_run("expA", "imagenet", "local", ["python"])
        assert run.status == "queued"
        registry.update(run, status="running")
        registry.update(run, status="completed", returncode=0)
        runs = registry.runs("expA")
        assert len(runs) == 1
        assert runs[0].status == "completed"
        assert runs[0].finished_at
        assert registry.experiments() == ["expA"]
        table = registry.format_runs("expA")
        assert "imagenet" in table and "completed" in table

    def test_unique_ids_same_second(self, tmp_path):
        registry = RunRegistry(tmp_path / "runs")
        a = registry.new_run("e", "w", "local", [])
        b = registry.new_run("e", "w", "local", [])
        assert a.run_id != b.run_id

    def test_empty_listing(self, tmp_path):
        registry = RunRegistry(tmp_path / "none")
        assert registry.runs("x") == []
        assert registry.experiments() == []
        assert "no runs" in registry.format_runs("x")


class TestStreamingAndPoll:
    """Live remote-run output + registry status polling (VERDICT r02 item 3:
    aml_compute.py:391-392 wait_for_completion(show_output=True) parity)."""

    def test_remote_submit_streams_to_run_log(self, submit_env):
        cfg, runner, registry = submit_env
        submitter = Submitter(cfg, runner, registry)
        run = submitter.submit_remote("imagenet", {"data_format": "synthetic"})
        # the workload fan-out ssh must carry stream_to=<run_dir>/log.txt
        launch_streams = [
            s for a, s in zip(runner.history, runner.streams)
            if "ssh" in a and any("workloads." in x for x in a)
        ]
        assert launch_streams, "no workload ssh recorded"
        expected = str(registry.run_dir(run) / "log.txt")
        assert launch_streams[0] == expected
        assert run.extra["log_path"] == expected

    def test_command_runner_tees_live_output(self, tmp_path, capsys):
        log = tmp_path / "log.txt"
        runner = CommandRunner()
        result = runner.run(
            ["sh", "-c", "echo line-out; echo line-err >&2; exit 3"],
            check=False,
            stream_to=str(log),
        )
        assert result.returncode == 3
        text = log.read_text()
        assert "line-out" in text and "line-err" in text  # merged streams
        assert "line-out" in result.stdout  # tail kept for failure reports
        captured = capsys.readouterr()
        assert "line-out" in captured.out  # live console echo

    def test_streamed_retries_append_to_same_log(self, tmp_path):
        log = tmp_path / "log.txt"
        runner = CommandRunner()
        runner.run(["sh", "-c", "echo first"], stream_to=str(log))
        runner.run(["sh", "-c", "echo second"], stream_to=str(log))
        assert log.read_text() == "first\nsecond\n"

    def _poll_runner(self, pod_state="READY", probe="DEAD\nDEAD"):
        # submit_env's TPU_TYPE=v5litepod-16 is a 2-host pod; the probe
        # fans out --worker=all, so a full answer is one line per host.
        def describe(argv):
            return "describe" in argv

        def pgrep(argv):
            return any("pgrep" in str(x) for x in argv)

        return FakeRunner(
            [
                (pgrep, CommandResult([], returncode=0, stdout=probe + "\n")),
                (
                    describe,
                    CommandResult(
                        [], returncode=0,
                        stdout='{"state": "%s"}' % pod_state,
                    ),
                ),
            ]
        )

    def _stranded_run(self, cfg, registry):
        run = registry.new_run("exp1", "imagenet", "remote", ["python3"])
        registry.update(run, status="running")
        return run

    def test_poll_flips_stranded_run_to_failed(self, submit_env):
        cfg, _, registry = submit_env
        runner = self._poll_runner(probe="DEAD\nDEAD")
        run = self._stranded_run(cfg, registry)
        polled = Submitter(cfg, runner, registry).poll_run("exp1", run.run_id)
        assert polled.status == "failed"
        assert "no launcher process on 2/2 workers" in polled.extra["poll"]
        assert registry.find("exp1", run.run_id).status == "failed"
        # The probe must fan out to every worker, not just worker 0.
        probe_argv = next(
            a for a in runner.history
            if "--command" in a and "pgrep" in a[a.index("--command") + 1]
        )
        assert probe_argv[probe_argv.index("--worker") + 1] == "all"

    def test_poll_keeps_live_run_running(self, submit_env):
        cfg, _, registry = submit_env
        runner = self._poll_runner(probe="ALIVE\nALIVE")
        run = self._stranded_run(cfg, registry)
        polled = Submitter(cfg, runner, registry).poll_run("exp1", run.run_id)
        assert polled.status == "running"

    def test_poll_any_live_worker_outvotes_dead_ones(self, submit_env):
        """A dead worker-0 launcher with a live peer must NOT fail the run —
        the pre-quorum poll decided from worker 0 alone (VERDICT r03 #7)."""
        cfg, _, registry = submit_env
        runner = self._poll_runner(probe="DEAD\nALIVE")
        run = self._stranded_run(cfg, registry)
        polled = Submitter(cfg, runner, registry).poll_run("exp1", run.run_id)
        assert polled.status == "running"
        assert polled.extra["poll_workers"] == {
            "alive": 1, "dead": 1, "expected": 2,
        }

    def test_poll_dead_minority_is_inconclusive(self, submit_env):
        """One DEAD answer from a 2-host pod (other worker unreachable) is
        not a quorum — a half-blind probe must not condemn the run."""
        cfg, _, registry = submit_env
        runner = self._poll_runner(probe="DEAD")
        run = self._stranded_run(cfg, registry)
        polled = Submitter(cfg, runner, registry).poll_run("exp1", run.run_id)
        assert polled.status == "running"
        assert polled.extra["poll_workers"]["dead"] == 1

    def test_poll_fails_run_when_pod_gone(self, submit_env):
        cfg, _, registry = submit_env
        runner = self._poll_runner(pod_state="PREEMPTED")
        run = self._stranded_run(cfg, registry)
        polled = Submitter(cfg, runner, registry).poll_run("exp1", run.run_id)
        assert polled.status == "failed"
        assert "PREEMPTED" in polled.extra["poll"]

    def test_poll_leaves_finished_runs_untouched(self, submit_env):
        cfg, _, registry = submit_env
        run = registry.new_run("exp1", "imagenet", "remote", [])
        registry.update(run, status="completed", returncode=0)
        runner = self._poll_runner()
        polled = Submitter(cfg, runner, registry).poll_run("exp1", run.run_id)
        assert polled.status == "completed"
        assert not runner.history  # no cloud calls for a finished run

    def test_poll_probe_brackets_pattern_against_self_match(self, submit_env):
        """pgrep -f must not match the probe's own wrapping shell: the
        pattern's first char is bracketed."""
        cfg, _, registry = submit_env
        runner = self._poll_runner(probe="DEAD")
        run = self._stranded_run(cfg, registry)
        Submitter(cfg, runner, registry).poll_run("exp1", run.run_id)
        probe_cmds = [
            a[a.index("--command") + 1]
            for a in runner.history
            if "--command" in a and "pgrep" in a[a.index("--command") + 1]
        ]
        assert probe_cmds
        assert "[d]istributeddeeplearning_tpu" in probe_cmds[0]

    def test_poll_inconclusive_probe_leaves_status(self, submit_env):
        """A failed ssh probe says nothing about the workload — the run must
        stay 'running', not be condemned by a network blip."""
        cfg, _, registry = submit_env

        def pgrep(argv):
            return any("pgrep" in str(x) for x in argv)

        def describe(argv):
            return "describe" in argv

        runner = FakeRunner(
            [
                (pgrep, CommandResult([], returncode=255)),
                (
                    describe,
                    CommandResult([], returncode=0, stdout='{"state": "READY"}'),
                ),
            ]
        )
        run = self._stranded_run(cfg, registry)
        polled = Submitter(cfg, runner, registry).poll_run("exp1", run.run_id)
        assert polled.status == "running"


class TestQueuedResources:
    """Queued-resource provisioning — how v5e+ capacity is obtained when
    on-demand create stockouts (the AML autoscale-quota role)."""

    def test_request_composes_gcloud_queued_create(self):
        runner = FakeRunner()
        pod = make_pod(runner)
        rid = pod.request_queued(spot=True, valid_until_duration="6h")
        assert rid == "test-pod-req"
        argv = runner.history[-1]
        assert argv[:5] == [
            "gcloud", "compute", "tpus", "queued-resources", "create"
        ]
        assert "test-pod-req" in argv
        assert argv[argv.index("--node-id") + 1] == "test-pod"
        assert argv[argv.index("--accelerator-type") + 1] == "v5litepod-32"
        assert "--spot" in argv
        assert argv[argv.index("--valid-until-duration") + 1] == "6h"

    def test_queued_state_parses_nested_state(self):
        def describe(argv):
            return "queued-resources" in argv and "describe" in argv

        runner = FakeRunner(
            [
                (
                    describe,
                    CommandResult(
                        [], returncode=0,
                        stdout='{"state": {"state": "WAITING_FOR_RESOURCES"}}',
                    ),
                )
            ]
        )
        pod = make_pod(runner)
        assert pod.queued_state() == "WAITING_FOR_RESOURCES"

    def test_queued_state_absent(self):
        def describe(argv):
            return "queued-resources" in argv and "describe" in argv

        runner = FakeRunner([(describe, CommandResult([], returncode=1))])
        assert make_pod(runner).queued_state() is None

    def test_delete_queued_forces(self):
        runner = FakeRunner()
        make_pod(runner).delete_queued("custom-req")
        argv = runner.history[-1]
        assert "delete" in argv and "custom-req" in argv and "--force" in argv

    def test_cli_queue_verbs(self, tmp_path, monkeypatch, capsys):
        monkeypatch.chdir(tmp_path)
        (tmp_path / ".env").write_text(
            "TPU_NAME=pod-q\nTPU_TYPE=v5litepod-16\nGCP_ZONE=us-west4-a\n"
        )
        from distributeddeeplearning_tpu.cli.main import main

        rc = main(["--dry-run", "tpu", "queue", "--spot"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "queued-resources create pod-q-req" in out
        assert "--node-id pod-q" in out

    def test_delete_queued_refuses_active_without_force(self):
        def describe(argv):
            return "queued-resources" in argv and "describe" in argv

        runner = FakeRunner(
            [
                (
                    describe,
                    CommandResult(
                        [], returncode=0,
                        stdout='{"state": {"state": "ACTIVE"}}',
                    ),
                )
            ]
        )
        pod = make_pod(runner)
        assert pod.delete_queued() is False
        assert not any("delete" in a for a in runner.history)
        assert pod.delete_queued(force=True) is True
        assert any("delete" in a for a in runner.history)

    def test_preemptible_pod_requests_spot_capacity(self):
        runner = FakeRunner()
        pod = make_pod(runner, preemptible=True)
        pod.request_queued()
        assert "--spot" in runner.history[-1]

    def test_recreate_requeues_queued_managed_pod(self):
        """Preemption recovery for a queued-provisioned pod must go through
        the queued-resources surface (tpu-vm delete cannot remove it)."""
        def describe_q(argv):
            return "queued-resources" in argv and "describe" in argv

        runner = FakeRunner(
            [
                (
                    describe_q,
                    CommandResult(
                        [], returncode=0,
                        stdout='{"state": {"state": "SUSPENDED"}}',
                    ),
                )
            ]
        )
        pod = make_pod(runner)
        pod.recreate()
        surfaces = [
            (a[3], a[4]) for a in runner.history if len(a) > 4 and a[0] == "gcloud"
        ]
        assert ("queued-resources", "delete") in surfaces
        assert ("queued-resources", "create") in surfaces
        # and no tpu-vm create/delete happened
        assert ("tpu-vm", "create") not in surfaces
        assert ("tpu-vm", "delete") not in surfaces
