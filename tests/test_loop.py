"""Trainer epoch loop: metrics, checkpointing, resume, TB files, summary."""

import itertools

import jax
import jax.flatten_util
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearning_tpu.data.synthetic import SyntheticDataset
from distributeddeeplearning_tpu.models import get_model
from distributeddeeplearning_tpu.parallel import MeshSpec, create_mesh
from distributeddeeplearning_tpu.train.loop import Trainer, TrainerConfig
from distributeddeeplearning_tpu.train.state import create_train_state, sgd_momentum
from distributeddeeplearning_tpu.train.step import build_eval_step, build_train_step

IMG = (24, 24, 3)
NCLS = 5
GLOBAL_BATCH = 16


@pytest.fixture(scope="module")
def parts():
    mesh = create_mesh(MeshSpec())
    model = get_model("resnet18", num_classes=NCLS, dtype=jnp.float32)
    tx = sgd_momentum(optax.constant_schedule(0.05))

    def mk_state():
        return create_train_state(jax.random.key(0), model, (8, *IMG), tx)

    train_step = build_train_step(mesh, mk_state(), compute_dtype=jnp.float32)
    eval_step = build_eval_step(mesh, mk_state(), compute_dtype=jnp.float32)
    return mesh, mk_state, train_step, eval_step


def _train_stream():
    ds = SyntheticDataset(length=10_000, image_shape=IMG, num_classes=NCLS)
    return itertools.cycle(ds.batches(GLOBAL_BATCH))


def _eval_stream():
    ds = SyntheticDataset(length=2 * GLOBAL_BATCH, image_shape=IMG, num_classes=NCLS, seed=9)
    return iter(list(ds.batches(GLOBAL_BATCH)))


def test_fit_runs_epochs_and_reports(parts, tmp_path):
    mesh, mk_state, train_step, eval_step = parts
    cfg = TrainerConfig(
        epochs=2,
        steps_per_epoch=3,
        global_batch_size=GLOBAL_BATCH,
        log_every=2,
        checkpoint_dir=str(tmp_path / "ckpt"),
        tensorboard_dir=str(tmp_path / "tb"),
    )
    trainer = Trainer(mesh, train_step, eval_step=eval_step, config=cfg)
    state, result = trainer.fit(mk_state(), _train_stream(), _eval_stream)

    assert result.epochs_run == 2
    assert int(state.step) == 6
    assert result.total_images == 2 * 3 * GLOBAL_BATCH
    assert result.images_per_second > 0
    assert "loss" in result.final_train_metrics
    assert "top1" in result.final_eval_metrics
    # checkpoint written at each epoch boundary
    assert trainer.checkpointer.latest_step() == 6
    # TB event files exist
    assert any((tmp_path / "tb").iterdir())


def test_fit_resumes_from_checkpoint(parts, tmp_path):
    mesh, mk_state, train_step, eval_step = parts
    ckpt_dir = str(tmp_path / "resume_ckpt")
    cfg1 = TrainerConfig(
        epochs=1, steps_per_epoch=2, global_batch_size=GLOBAL_BATCH,
        checkpoint_dir=ckpt_dir,
    )
    Trainer(mesh, train_step, config=cfg1).fit(mk_state(), _train_stream())

    cfg2 = TrainerConfig(
        epochs=3, steps_per_epoch=2, global_batch_size=GLOBAL_BATCH,
        checkpoint_dir=ckpt_dir,
    )
    state, result = Trainer(mesh, train_step, config=cfg2).fit(
        mk_state(), _train_stream()
    )
    # resumed at epoch 1, ran epochs 2..3
    assert result.epochs_run == 2
    assert int(state.step) == 6


def test_metrics_jsonl_rows(parts, tmp_path):
    """run.log_row parity: one JSON row per epoch with train/val metrics
    and the epoch's train-phase throughput."""
    import json

    mesh, mk_state, train_step, eval_step = parts
    path = tmp_path / "m" / "metrics.jsonl"
    cfg = TrainerConfig(
        epochs=2, steps_per_epoch=2, global_batch_size=GLOBAL_BATCH,
        metrics_path=str(path),
    )
    Trainer(mesh, train_step, eval_step=eval_step, config=cfg).fit(
        mk_state(), _train_stream(), _eval_stream
    )
    rows = [json.loads(line) for line in path.read_text().splitlines()]
    assert [r["epoch"] for r in rows] == [1, 2]
    for row in rows:
        assert "train_loss" in row and "val_top1" in row
        assert row["images_per_second"] > 0


def test_fit_requires_steps_per_epoch(parts):
    mesh, _, train_step, _ = parts
    with pytest.raises(ValueError, match="steps_per_epoch"):
        Trainer(mesh, train_step, config=TrainerConfig(epochs=1))


def test_steps_per_epoch_world_scaling():
    """steps = total_batches // world size — resnet_main.py:246-247."""
    total_images = 1281167
    batch_per_chip = 64
    world = 32
    steps = total_images // (batch_per_chip * world)
    assert steps == total_images // batch_per_chip // world


def test_drain_bounded_guards_eval_buffer():
    """The multi-host eval drain must refuse to buffer past the cap (an
    oversized eval split fails loudly instead of swapping the host), honor
    the eval_steps limit, and pass small drains through untouched."""
    from distributeddeeplearning_tpu.train.loop import _drain_bounded

    assert _drain_bounded(iter(range(5)), None, 10) == [0, 1, 2, 3, 4]
    assert _drain_bounded(iter(range(5)), 3, 10) == [0, 1, 2]
    # limit wins over cap when it stops the drain first
    assert _drain_bounded(iter(range(100)), 4, 4) == [0, 1, 2, 3]
    with pytest.raises(RuntimeError, match="eval_buffer_batches"):
        _drain_bounded(iter(range(100)), None, 8)
    with pytest.raises(RuntimeError, match="eval_buffer_batches"):
        _drain_bounded(iter(range(100)), 50, 8)


def _step_indexed_factory(start_step: int):
    """Deterministic step-indexed batch stream: batch for true step i is a
    pure function of i — the replay-free resume contract."""

    def batches():
        i = start_step
        while True:
            rng = np.random.default_rng(1000 + i)
            yield {
                "image": rng.standard_normal((GLOBAL_BATCH, *IMG)).astype(
                    np.float32
                ),
                "label": rng.integers(0, NCLS, (GLOBAL_BATCH,)).astype(
                    np.int32
                ),
            }
            i += 1

    return batches()


def test_midepoch_resume_bit_identical(parts, tmp_path):
    """Kill at step k, resume, finish — the final state must equal the
    uninterrupted run's bit for bit (VERDICT r03 #5).  checkpoint_every_steps
    saves inside the epoch; resume lands on the exact step and the
    step-indexed factory hands back the stream from there, so no batch
    repeats and none is skipped."""
    mesh, mk_state, train_step, eval_step = parts

    # Uninterrupted reference: 2 epochs x 5 steps.
    cfg_ref = TrainerConfig(
        epochs=2, steps_per_epoch=5, global_batch_size=GLOBAL_BATCH,
        prefetch=0,
    )
    ref_state, _ = Trainer(mesh, train_step, config=cfg_ref).fit(
        mk_state(), _step_indexed_factory
    )

    # Interrupted run: same config + step-interval checkpoints; the data
    # stream dies after 7 batches (mid-epoch-2 "preemption").
    ckpt = str(tmp_path / "mid_ckpt")
    cfg = TrainerConfig(
        epochs=2, steps_per_epoch=5, global_batch_size=GLOBAL_BATCH,
        checkpoint_dir=ckpt, checkpoint_every_steps=3, prefetch=0,
    )

    def dying_factory(start_step: int):
        return itertools.islice(_step_indexed_factory(start_step), 7)

    with pytest.raises(StopIteration):
        Trainer(mesh, train_step, config=cfg).fit(mk_state(), dying_factory)
    # steps 3 and 6 were checkpointed before the crash at step 8
    assert Trainer(
        mesh, train_step, config=cfg
    ).checkpointer.latest_step() == 6

    # Resume: restores step 6, asks the factory for the stream from step 6,
    # runs steps 7..10.
    resumed_state, result = Trainer(mesh, train_step, config=cfg).fit(
        mk_state(), _step_indexed_factory
    )
    assert int(resumed_state.step) == 10
    ref_flat, _ = jax.flatten_util.ravel_pytree(
        {"p": ref_state.params, "o": ref_state.opt_state,
         "b": ref_state.batch_stats}
    )
    res_flat, _ = jax.flatten_util.ravel_pytree(
        {"p": resumed_state.params, "o": resumed_state.opt_state,
         "b": resumed_state.batch_stats}
    )
    np.testing.assert_array_equal(np.asarray(ref_flat), np.asarray(res_flat))
    # the resumed run executed only the 4 remaining steps (7..10)
    assert result.total_images == 4 * GLOBAL_BATCH


def test_step_checkpoint_cadence(parts, tmp_path):
    """checkpoint_every_steps saves on true-step boundaries across epochs."""
    mesh, mk_state, train_step, _ = parts
    ckpt = str(tmp_path / "cadence")
    cfg = TrainerConfig(
        epochs=2, steps_per_epoch=3, global_batch_size=GLOBAL_BATCH,
        checkpoint_dir=ckpt, checkpoint_every_steps=2, prefetch=0,
    )
    trainer = Trainer(mesh, train_step, config=cfg)
    trainer.fit(mk_state(), _step_indexed_factory)
    trainer.checkpointer.wait()
    steps = set(trainer.checkpointer._mgr.all_steps())
    # every-2 saves at 2,4,6 plus epoch-end saves at 3,6
    assert {2, 3, 4, 6}.issubset(steps)
