"""Mixture-of-Experts layer + expert parallelism (models/moe.py, RULES_EP).

The reference has no MoE (SURVEY.md §2 "Expert parallelism: Absent"); this
is beyond-reference parallelism surface.  Tests pin: routing/combine math
(single-expert degenerate case equals a dense FFN), capacity dropping,
load-balance aux loss wiring through the train step, and expert-axis
parameter sharding on the CPU mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearning_tpu.models.moe import MOE_LOSS_COLLECTION, MoeMlp


def _apply(module, x, train=True):
    variables = module.init(jax.random.key(0), x, train=False)
    if train:
        y, aux = module.apply(
            x=x, train=True, variables=variables, mutable=[MOE_LOSS_COLLECTION]
        )
        return y, variables, aux
    return module.apply(variables, x, train=False), variables, {}


def test_single_expert_equals_dense_ffn():
    """E=1 with ample capacity routes every token with gate 1.0 — the MoE
    must reproduce the plain FFN computed from the same weights."""
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((2, 8, 16)), jnp.float32
    )
    moe = MoeMlp(
        num_experts=1, intermediate_size=32, capacity_factor=2.0,
        dtype=jnp.float32,
    )
    y, variables, _ = _apply(moe, x)
    from flax.core import meta

    p = meta.unbox(variables)["params"]
    h = jnp.einsum("bsh,hm->bsm", x, p["w_in"][0]) + p["b_in"][0]
    h = jax.nn.gelu(h, approximate=False)
    want = jnp.einsum("bsm,mh->bsh", h, p["w_out"][0]) + p["b_out"][0]
    np.testing.assert_allclose(np.asarray(y), np.asarray(want), atol=1e-5)


def test_output_shape_and_aux_loss():
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal((2, 16, 24)), jnp.float32
    )
    moe = MoeMlp(num_experts=4, intermediate_size=48, dtype=jnp.float32)
    y, _, aux = _apply(moe, x)
    assert y.shape == x.shape and np.isfinite(np.asarray(y)).all()
    (loss,) = jax.tree_util.tree_leaves(aux[MOE_LOSS_COLLECTION])
    # Switch load-balance loss: >= 1, == 1 only at a perfectly uniform router
    assert float(loss) >= 1.0 - 1e-5


def test_eval_mode_sows_nothing():
    x = jnp.zeros((1, 4, 8))
    moe = MoeMlp(num_experts=2, intermediate_size=16, dtype=jnp.float32)
    variables = moe.init(jax.random.key(0), x, train=False)
    y, aux = moe.apply(
        variables, x, train=False, mutable=[MOE_LOSS_COLLECTION]
    )
    assert not jax.tree_util.tree_leaves(aux)


def test_capacity_drops_overflow_tokens():
    """With capacity ~0, every expert queue overflows: dropped tokens emit
    zeros (the residual connection outside the layer carries them)."""
    x = jnp.asarray(
        np.random.default_rng(2).standard_normal((1, 32, 8)), jnp.float32
    )
    moe = MoeMlp(
        num_experts=2, intermediate_size=16, capacity_factor=0.01,
        router_top_k=1, dtype=jnp.float32,
    )
    y, _, _ = _apply(moe, x)
    # capacity = max(ceil(32/2*0.01), 1) = 1 per expert: <= 2 tokens survive
    nonzero_tokens = int((np.abs(np.asarray(y)[0]).sum(-1) > 1e-9).sum())
    assert nonzero_tokens <= 2


def test_expert_axis_param_sharding():
    from distributeddeeplearning_tpu.parallel import MeshSpec, create_mesh
    from distributeddeeplearning_tpu.parallel.sharding import (
        RULES_EP,
        model_logical_axes,
        param_shardings,
    )

    mesh = create_mesh(MeshSpec(expert=2))
    x = jnp.zeros((1, 4, 8))
    moe = MoeMlp(num_experts=4, intermediate_size=16, dtype=jnp.float32)
    axes = model_logical_axes(moe, jax.random.key(0), x, train=False)
    shardings = param_shardings(mesh, moe.init(jax.random.key(0), x,
                                               train=False)["params"],
                                RULES_EP, axes)
    assert shardings["w_in"].spec[0] == "expert"
    assert shardings["w_out"].spec[0] == "expert"
    # router kernel [H, E]: its expert output dim shards too (tiny; XLA
    # all-gathers the routing logits where needed)
    assert shardings["router"]["kernel"].spec == (None, "expert")


@pytest.mark.slow
def test_bert_moe_trains_with_expert_parallelism(tmp_path):
    """Full driver: MoE BERT on dp×expert mesh, aux loss in the total."""
    from distributeddeeplearning_tpu.workloads import bert

    cfg = dict(
        epochs=1,
        steps_per_epoch=2,
        batch_size=2,
        seq_len=16,
        num_classes=3,
        vocab_size=101,
        train_examples=32,
        num_layers=2,
        hidden_size=32,
        num_heads=4,
        intermediate_size=64,
        max_position_embeddings=16,
        compute_dtype="float32",
        dropout_rate=0.0,
    )
    state, result = bert.main(**cfg, num_experts=4, expert=2)
    assert np.isfinite(result.final_train_metrics["loss"])
    # layer1 (2nd layer) carries the MoE block; layer0 stays dense
    assert "moe_mlp" in state.params["layer1"]
    assert "mlp_in" in state.params["layer0"]


def test_expert_axis_requires_experts():
    from distributeddeeplearning_tpu.workloads import bert

    with pytest.raises(ValueError, match="num_experts"):
        bert.main(epochs=1, steps_per_epoch=1, batch_size=1, expert=2)
