"""Pipeline parallelism (ops/pipeline.py) on the virtual CPU mesh.

Correctness is defined against plain sequential stage application: the
GPipe schedule with ppermute rotation must produce bit-comparable outputs
and gradients for any (stages, microbatches) geometry.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.ops.pipeline import pipeline_apply
from distributeddeeplearning_tpu.parallel import MeshSpec, create_mesh

HID = 16


def _stage_fn(params, x):
    # one residual dense block per stage
    return x + jnp.tanh(x @ params["w"] + params["b"])


def _stacked_params(n_stages, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(
            rng.standard_normal((n_stages, HID, HID)) * 0.3, jnp.float32
        ),
        "b": jnp.asarray(rng.standard_normal((n_stages, HID)) * 0.1, jnp.float32),
    }


def _sequential(params, x, n_stages):
    for s in range(n_stages):
        x = _stage_fn(jax.tree.map(lambda p: p[s], params), x)
    return x


@pytest.mark.parametrize("n_stages,n_micro", [(2, 4), (4, 2), (4, 8)])
def test_matches_sequential(n_stages, n_micro):
    mesh = create_mesh(MeshSpec(pipe=n_stages))
    params = _stacked_params(n_stages)
    batch = 8 * n_micro  # divisible by microbatches and the data axes
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal((batch, HID)), jnp.float32
    )
    got = pipeline_apply(
        _stage_fn, params, x, mesh=mesh, num_microbatches=n_micro
    )
    want = _sequential(params, x, n_stages)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_gradients_match_sequential():
    n_stages, n_micro = 4, 4
    mesh = create_mesh(MeshSpec(pipe=n_stages))
    params = _stacked_params(n_stages, seed=2)
    x = jnp.asarray(
        np.random.default_rng(3).standard_normal((8, HID)), jnp.float32
    )
    target = jnp.ones((8, HID))

    def loss_pipe(p):
        y = pipeline_apply(_stage_fn, p, x, mesh=mesh, num_microbatches=n_micro)
        return ((y - target) ** 2).mean()

    def loss_seq(p):
        return ((_sequential(p, x, n_stages) - target) ** 2).mean()

    g_pipe = jax.grad(loss_pipe)(params)
    g_seq = jax.grad(loss_seq)(params)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(g_pipe[k]), np.asarray(g_seq[k]), atol=1e-5, rtol=1e-4
        )


def test_composes_with_data_axis():
    """pipe×data mesh: batch sharded over data, stages over pipe."""
    mesh = create_mesh(MeshSpec(pipe=2, data=4))
    params = _stacked_params(2)
    x = jnp.asarray(
        np.random.default_rng(4).standard_normal((16, HID)), jnp.float32
    )
    got = pipeline_apply(_stage_fn, params, x, mesh=mesh, num_microbatches=2)
    want = _sequential(params, x, 2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_geometry_validation():
    mesh = create_mesh(MeshSpec(pipe=2))
    params = _stacked_params(4)  # wrong stage count
    x = jnp.zeros((8, HID))
    with pytest.raises(ValueError, match="leading dim"):
        pipeline_apply(_stage_fn, params, x, mesh=mesh, num_microbatches=2)
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_apply(
            _stage_fn, _stacked_params(2), x, mesh=mesh, num_microbatches=3
        )


def test_pipeline_remat_matches_plain_gradients():
    """remat=True recomputes stage forwards in the backward; gradients must
    be identical to the stored-activation path."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributeddeeplearning_tpu.ops.pipeline import pipeline_apply
    from distributeddeeplearning_tpu.parallel import MeshSpec, create_mesh

    mesh = create_mesh(MeshSpec(pipe=2))
    rng = np.random.default_rng(0)
    params = {
        "w": jnp.asarray(rng.standard_normal((2, 8, 8)) * 0.3, jnp.float32),
        "b": jnp.asarray(rng.standard_normal((2, 8)) * 0.1, jnp.float32),
    }
    x = jnp.asarray(rng.standard_normal((8, 8)), jnp.float32)

    def stage(p, mb):
        return mb + jnp.tanh(mb @ p["w"] + p["b"])

    def loss(params, remat):
        y = pipeline_apply(
            stage, params, x, mesh=mesh, num_microbatches=2, remat=remat
        )
        return (y ** 2).sum()

    g_plain = jax.grad(lambda p: loss(p, False))(params)
    g_remat = jax.grad(lambda p: loss(p, True))(params)
    # identical math, different op ordering in the recomputed backward —
    # tolerance covers fp reassociation only
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        g_plain,
        g_remat,
    )


FF = 32


def _mlp_stacked_params(n_stages, seed=7):
    rng = np.random.default_rng(seed)
    return {
        "w_in": jnp.asarray(
            rng.standard_normal((n_stages, HID, FF)) * 0.3, jnp.float32
        ),
        "w_out": jnp.asarray(
            rng.standard_normal((n_stages, FF, HID)) * 0.3, jnp.float32
        ),
    }


def _mlp_sequential(params, x, n_stages):
    for s in range(n_stages):
        p = jax.tree.map(lambda a, s=s: a[s], params)
        x = x + jnp.tanh(x @ p["w_in"]) @ p["w_out"]
    return x


def test_param_partition_tensor_parallel():
    """pipe×tensor×data: stage MLP width Megatron-sharded over ``tensor``
    inside the pipeline (param_partition), partial sums psum'd — forward and
    grads must match the sequential full-width model."""
    mesh = create_mesh(MeshSpec(pipe=2, tensor=2))  # data absorbs the rest
    params = _mlp_stacked_params(2)
    x = jnp.asarray(
        np.random.default_rng(8).standard_normal((8, HID)), jnp.float32
    )

    def stage_tp(p, mb):
        # p["w_in"]: [HID, FF/tp] local columns; p["w_out"]: [FF/tp, HID]
        h = jnp.tanh(mb @ p["w_in"])
        return mb + jax.lax.psum(h @ p["w_out"], "tensor")

    part = {"w_in": (None, "tensor"), "w_out": ("tensor", None)}

    def run(p):
        return pipeline_apply(
            stage_tp, p, x, mesh=mesh, num_microbatches=2,
            param_partition=part,
        )

    got = run(params)
    want = _mlp_sequential(params, x, 2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)

    g_pipe = jax.grad(lambda p: (run(p) ** 2).mean())(params)
    g_seq = jax.grad(lambda p: (_mlp_sequential(p, x, 2) ** 2).mean())(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        g_pipe,
        g_seq,
    )


def test_param_partition_fsdp():
    """pipe×fsdp×data: stage weights ZeRO-3-sharded over ``fsdp`` inside the
    pipeline, all-gathered per tick (grad transposes to reduce-scatter);
    batch additionally sharded over (data, fsdp)."""
    mesh = create_mesh(MeshSpec(pipe=2, fsdp=2))  # data absorbs the rest
    params = _mlp_stacked_params(2, seed=9)
    x = jnp.asarray(
        np.random.default_rng(10).standard_normal((8, HID)), jnp.float32
    )

    def stage_fsdp(p, mb):
        w_in = jax.lax.all_gather(p["w_in"], "fsdp", axis=1, tiled=True)
        w_out = jax.lax.all_gather(p["w_out"], "fsdp", axis=0, tiled=True)
        return mb + jnp.tanh(mb @ w_in) @ w_out

    part = {"w_in": (None, "fsdp"), "w_out": ("fsdp", None)}

    def run(p):
        return pipeline_apply(
            stage_fsdp, p, x, mesh=mesh, num_microbatches=2,
            param_partition=part,
        )

    got = run(params)
    want = _mlp_sequential(params, x, 2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)

    g_pipe = jax.grad(lambda p: (run(p) ** 2).mean())(params)
    g_seq = jax.grad(lambda p: (_mlp_sequential(p, x, 2) ** 2).mean())(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        g_pipe,
        g_seq,
    )


def test_param_partition_validation():
    mesh = create_mesh(MeshSpec(pipe=2))
    params = _mlp_stacked_params(2)
    x = jnp.zeros((8, HID))
    with pytest.raises(ValueError, match="more dims"):
        pipeline_apply(
            lambda p, mb: mb, params, x, mesh=mesh, num_microbatches=2,
            param_partition={
                "w_in": (None, None, "tensor"), "w_out": None,
            },
        )
