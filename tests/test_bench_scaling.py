"""The scaling sweep (bench.py --devices) — BASELINE.json's second
north-star metric, reported as compiled-HLO collective signatures per mesh
size (the platform-independent content of a scaling claim) with wall clock
demoted to an explicitly-labeled debug column (VERDICT r4 item 7)."""

import io
import json
import sys
import types
from contextlib import redirect_stdout
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import bench  # noqa: E402


def _sweep(devices):
    args = types.SimpleNamespace(
        batch_size=8, image_size=32, seq_len=32, model="resnet18",
        num_iters=1, num_batches_per_iter=2, num_warmup=1,
        small=False, fp32=True, fit=False, devices=devices,
        trace_dir=None, attention="default", remat="none",
    )
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bench._run_scaling(args)
    assert rc == 0
    return json.loads(buf.getvalue().strip().splitlines()[-1])


def test_scaling_sweep_emits_collective_signatures():
    line = _sweep("1,2,4")
    assert line["metric"] == "resnet18_collective_bytes_per_step_4chip"
    assert line["unit"] == "bytes"
    coll = line["collectives_per_step"]
    assert set(coll) == {"1", "2", "4"}  # complete: every requested size
    # 1 chip: nothing to communicate
    assert coll["1"] == {}
    # >1 chip: DP must emit grad all-reduce traffic, and the headline value
    # is the n_max byte total
    for n in ("2", "4"):
        assert "all-reduce" in coll[n], coll[n]
        assert coll[n]["all-reduce"]["count"] >= 1
        assert coll[n]["all-reduce"]["bytes"] > 0
    assert line["value"] == sum(s["bytes"] for s in coll["4"].values())
    # wall clock survives only as labeled debug data
    dbg = line["debug_wall_clock"]
    assert dbg["platform"] == "cpu"
    assert "not an ICI measurement" in dbg["caveat"]
    assert set(dbg["img_sec_total"]) == {"1", "2", "4"}
    assert dbg["ratio_vs_linear"]["1"] == 1.0


def test_scaling_sweep_inserts_missing_one_chip_baseline():
    line = _sweep("2")
    assert set(line["collectives_per_step"]) == {"1", "2"}
    assert set(line["debug_wall_clock"]["img_sec_total"]) == {"1", "2"}


def test_collective_stats_parses_hlo():
    text = """
  %ar-start = (f32[128]{0}, f32[128]{0}) all-reduce-start(%p0), replica_groups={}
  %ar-done = f32[128]{0} all-reduce-done(%ar-start)
  %ag = bf16[2,64]{1,0} all-gather(%p1), dimensions={0}
  %cp = f32[8,8]{1,0} collective-permute(%p2), source_target_pairs={{0,1}}
  %x = f32[4]{0} add(%a, %b)
"""
    stats = bench._collective_stats(text)
    # async start tuple (operand, result) counts the moved tensor once
    assert stats["all-reduce"] == {"count": 1, "bytes": 128 * 4}
    assert stats["all-gather"] == {"count": 1, "bytes": 2 * 64 * 2}
    assert stats["collective-permute"] == {"count": 1, "bytes": 8 * 8 * 4}
    assert "all-to-all" not in stats


def test_collective_stats_async_start_result_half():
    """Async -start tuples count the RESULT half only: an all-gather-start
    whose operand and result differ by the axis-size factor reports the
    gathered (output-shape) bytes, not 75% of them; equal-size tuples
    (all-reduce) are unchanged, and odd tuples fall back to halving."""
    text = """
  %ag-start = (f32[64]{0}, f32[128]{0}) all-gather-start(%p0), dimensions={0}
  %ag-done = f32[128]{0} all-gather-done(%ag-start)
  %rs-start = (bf16[4,64]{1,0}, bf16[2,64]{1,0}) reduce-scatter-start(%p1)
  %ar-start = (f32[32]{0}, f32[32]{0}, u32[], u32[]) all-reduce-start(%p2)
"""
    stats = bench._collective_stats(text)
    assert stats["all-gather"] == {"count": 1, "bytes": 128 * 4}
    assert stats["reduce-scatter"] == {"count": 1, "bytes": 2 * 64 * 2}
    # u32[] context scalars are bookkeeping, not traffic: the (operand,
    # result, u32[], u32[]) tuple still reports the moved tensor once
    assert stats["all-reduce"] == {"count": 1, "bytes": 32 * 4}
