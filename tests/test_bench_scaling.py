"""The scaling-efficiency sweep (bench.py --devices) — BASELINE.json's
second north-star metric must emit a monotone-complete table."""

import io
import json
import sys
import types
from contextlib import redirect_stdout
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import bench  # noqa: E402


def test_scaling_sweep_emits_complete_efficiency_table():
    args = types.SimpleNamespace(
        batch_size=8, image_size=32, seq_len=32, model="resnet18",
        num_iters=1, num_batches_per_iter=2, num_warmup=1,
        small=False, fp32=True, fit=False, devices="1,2,4",
        trace_dir=None, attention="default", remat="none",
    )
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bench._run_scaling(args)
    assert rc == 0
    line = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert line["metric"] == "resnet18_scaling_efficiency_4chip"
    assert line["platform"] == "cpu"  # shape check, not an ICI measurement
    eff = line["efficiency"]
    assert set(eff) == {"1", "2", "4"}  # complete: every requested size
    assert eff["1"] == 1.0  # efficiency is defined against the 1-chip point
    for v in eff.values():
        assert 0.0 < v  # monotone-complete: all points present and positive
    assert set(line["img_sec_total"]) == {"1", "2", "4"}


def test_scaling_sweep_inserts_missing_one_chip_baseline():
    args = types.SimpleNamespace(
        batch_size=8, image_size=32, seq_len=32, model="resnet18",
        num_iters=1, num_batches_per_iter=2, num_warmup=1,
        small=False, fp32=True, fit=False, devices="2",
        trace_dir=None, attention="default", remat="none",
    )
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert bench._run_scaling(args) == 0
    line = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert set(line["efficiency"]) == {"1", "2"}
