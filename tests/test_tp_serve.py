"""Tensor-parallel serving: TP=2 vs TP=1 on the suite's virtual pod.

The load-bearing guarantee mirrors the dense-vs-paged suite's: sharding
the serve-path weights over the ``tensor`` axis is a LAYOUT change, never
a math change.  Greedy decode through ``tensor_parallel_engine`` must
produce the SAME tokens as the single-device engine on both KV layouts
and both cache dtypes (the margin-profiled tied-embedding params make the
argmax invariant to the all-reduce's f32 reassociation), chunked-prefill
prefix reuse must survive the sharded page pool, the ServeReport must
carry the TP degree + rule-table provenance into every artifact, and the
TP decode program's per-block all-reduces must classify under
``tp-all-reduce`` — visible to the bench gate, invisible to the gradient
all-reduce count the comm-path lint audits.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributeddeeplearning_tpu.models.pipelined_transformer import (
    forward,
    init_params,
)
from distributeddeeplearning_tpu.parallel import MeshSpec, comms, create_mesh
from distributeddeeplearning_tpu.parallel.compat import shard_map
from distributeddeeplearning_tpu.parallel.sharding import (
    layout_rules_provenance,
)
from distributeddeeplearning_tpu.serve import (
    ContinuousBatchingScheduler,
    Request,
)
from distributeddeeplearning_tpu.serve.engine import tensor_parallel_engine

# TP-divisible tiny geometry: heads, d_model, d_ff and vocab all split
# over tensor=2 (an odd vocab would divisibility-drop the head rule and
# the test would silently measure less sharding than it claims)
CFG = dict(num_layers=2, d_model=32, num_heads=4, d_ff=64, vocab_size=64,
           max_len=48)
HEADS = CFG["num_heads"]
MAX_SEQ = 32


@pytest.fixture(scope="module")
def params():
    p = init_params(jax.random.key(0), **CFG)
    # trained-model margin profile (the bench --tp recipe): tied 4x-gain
    # embedding head so top-2 logit gaps dwarf all-reduce reassociation
    # noise and token equality measures the layout, not tie-breaking
    p["embed"] = p["embed"] * 4.0
    p["head"] = p["embed"].T
    return p


def _build(params, tp, kv_layout, cache_dtype):
    kw = dict(
        tp=tp, num_heads=HEADS, batch_slots=2, max_seq=MAX_SEQ,
        temperature=0.0,
    )
    if cache_dtype is not None:
        kw["cache_dtype"] = cache_dtype
    if kv_layout == "paged":
        kw.update(kv_layout="paged", page_size=4, prefill_chunk=8)
    engine, mesh = tensor_parallel_engine(params, **kw)
    return engine, mesh


def _requests():
    rng = np.random.default_rng(7)
    return [
        Request(
            uid=f"r{i}",
            prompt=rng.integers(
                1, CFG["vocab_size"], 4 + 2 * (i % 3)
            ).tolist(),
        )
        for i in range(4)
    ]


def _naive_greedy(params, prompt, n):
    toks = list(prompt)
    for _ in range(n):
        logits = forward(params, jnp.asarray([toks], jnp.int32),
                         num_heads=HEADS)
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


@pytest.mark.parametrize(
    "kv_layout,cache_dtype",
    [
        ("dense", None),
        ("dense", jnp.int8),
        ("paged", None),
        ("paged", jnp.int8),
    ],
    ids=["dense_f32", "dense_int8", "paged_f32", "paged_int8"],
)
def test_tp2_greedy_bit_identical(params, kv_layout, cache_dtype):
    """TP=2 greedy streams equal TP=1 token-for-token on every layout x
    cache dtype — and the f32 configs also match the full-forward oracle
    (int8 quantizes the cache, so its anchor is the TP=1 run alone)."""
    maps = {}
    for tp in (1, 2):
        engine, mesh = _build(params, tp, kv_layout, cache_dtype)
        assert (mesh is None) == (tp == 1)
        res, rep = ContinuousBatchingScheduler(
            engine, max_new_tokens=4
        ).run(_requests())
        maps[tp] = {r.uid: r.tokens for r in res}
        assert rep.tp == tp
    assert maps[1] == maps[2], f"TP=2 diverged on {kv_layout}/{cache_dtype}"
    if cache_dtype is None:
        # one-request oracle anchor: TP=1 == oracle is already pinned
        # exhaustively by the dense/paged suites, so this only guards
        # against BOTH engines sharing a wrong compiled program here
        req = _requests()[0]
        assert maps[2][req.uid] == _naive_greedy(params, req.prompt, 4)


def test_tp2_chunked_prefill_prefix_hits_preserved(params):
    """Shared system-prompt traffic through the TP=2 paged engine: later
    requests still map the shared full pages (nonzero hit rate over the
    SHARDED page pool) and the streams stay equal to TP=1."""
    rng = np.random.default_rng(3)
    prefix = rng.integers(1, CFG["vocab_size"], 12).tolist()
    prompts = {
        f"s{i}": prefix + rng.integers(1, CFG["vocab_size"], 4).tolist()
        for i in range(4)
    }

    def reqs():
        return [Request(uid=u, prompt=p) for u, p in prompts.items()]

    maps, hits = {}, {}
    for tp in (1, 2):
        engine, _ = _build(params, tp, "paged", None)
        res, rep = ContinuousBatchingScheduler(
            engine, max_new_tokens=3
        ).run(reqs())
        maps[tp] = {r.uid: r.tokens for r in res}
        hits[tp] = rep.prefix_hit_rate
        engine.allocator.check()
    assert maps[1] == maps[2]
    assert hits[2] > 0, "prefix reuse vanished under TP"
    assert hits[2] == hits[1], "TP changed WHAT is shareable"


def test_serve_report_carries_tp_and_layout_provenance(params):
    """The satellite provenance contract: every ServeReport (hence every
    SERVE_*/QUANT_*/TP_* artifact line) names its TP degree and the rule
    table that resolved the layout."""
    for tp in (1, 2):
        engine, _ = _build(params, tp, "dense", None)
        _, rep = ContinuousBatchingScheduler(
            engine, max_new_tokens=2
        ).run(_requests()[:2])
        assert rep.tp == tp
        assert rep.layout_rules == layout_rules_provenance()
        line = rep.to_dict()
        assert line["tp"] == tp and line["layout_rules"]


def test_tp2_decode_program_all_reduces_classify_as_tp(params):
    """The compiled TP=2 decode program carries >= 1 per-block all-reduce
    and ``collective_stats(mesh=...)`` files ALL of them under
    ``tp-all-reduce`` — a plain all-reduce residue here would leak into
    the gradient-sync count the comm-path lint audits."""
    engine, mesh = _build(params, 2, "dense", None)
    ContinuousBatchingScheduler(engine, max_new_tokens=2).run(
        _requests()[:2]
    )
    prog = engine._decode_jit
    sig_args, sig_kwargs = list(prog._sigs.values())[-1]
    hlo = prog._fn.lower(*sig_args, **sig_kwargs).compile().as_text()
    stats = comms.collective_stats(hlo, mesh=mesh)
    assert stats.get(comms.TP_ALL_REDUCE, {}).get("count", 0) >= 1, stats
    assert stats.get("all-reduce", {}).get("count", 0) == 0, stats
    # meshless parse: the same traffic reads as plain all-reduce (the
    # classification is the mesh's replica-group knowledge, not a rename)
    flat = comms.collective_stats(hlo)
    assert flat.get("all-reduce", {}).get("count", 0) >= 1, flat


def test_collective_stats_splits_tp_from_data_all_reduce():
    """Unit pin for the classifier: on a data=2 x tensor=2 mesh, a psum
    over ``tensor`` classifies as tp-all-reduce while a psum over the
    data axes stays a plain all-reduce."""
    mesh = create_mesh(
        MeshSpec(data=2, tensor=2), devices=jax.devices()[:4]
    )

    def f(x):
        # two DISTINCT live outputs — a nested psum would let XLA fuse
        # both reductions into one whole-mesh collective
        return jax.lax.psum(x, "tensor"), jax.lax.psum(x, "data")

    fn = shard_map(
        f, mesh=mesh, in_specs=P(("data", "tensor")),
        out_specs=(P("data"), P("tensor")),
    )
    hlo = jax.jit(fn).lower(jnp.ones(8, jnp.float32)).compile().as_text()
    stats = comms.collective_stats(hlo, mesh=mesh)
    assert stats.get(comms.TP_ALL_REDUCE, {}).get("count", 0) >= 1, stats
    assert stats.get("all-reduce", {}).get("count", 0) >= 1, stats
