"""Seeded host-sync violations for the analyzer's detection pins.

This module is NEVER imported by production code — the test points the
AST checker at this file and asserts it catches exactly the planted
syncs (and none of the regex era's false positives).
"""

import numpy as renamed_np  # alias rename: the regex grep missed this
from numpy import asarray as local_asarray
from jax import device_get as renamed_get  # noqa: F401  (fixture import)
import jax.numpy as jnp  # noqa: F401


def hot_loop(xs, engine):
    """A decode-shaped hot loop with one sync per banned class."""
    total = 0.0
    note = "a float( inside a string must never be flagged"
    for x in xs:  # the hot loop the fixture region locates
        # a commented float( must never be flagged either
        out = engine.decode(x)  # landmark
        total += float(out)  # PLANTED: host coercion
        arr = renamed_np.asarray(out)  # PLANTED: aliased np.asarray
        arr2 = local_asarray(out)  # PLANTED: from-import alias
        host = renamed_get(out)  # PLANTED: renamed jax.device_get
        scalar = out.item()  # PLANTED: .item() readback
        mapped = list(map(renamed_np.asarray, x))  # PLANTED: reference
        keyed = sorted(x, key=renamed_get)  # PLANTED: ref via keyword
        dev = jnp.asarray(x)  # clean: host->device upload, dispatch-only
        del arr, arr2, host, scalar, mapped, keyed, dev, note
    return total
