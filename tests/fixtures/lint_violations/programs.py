"""Seeded program-level violations: one bad jitted program per checker.

Each builder returns a :class:`~distributeddeeplearning_tpu.analysis.
program_audit.ProgramRecord` (or the raw pieces a checker consumes) whose
planted bug exactly one program audit must catch.  Built lazily so
importing the fixture module costs nothing until a test asks.
"""

import jax
import jax.numpy as jnp

from distributeddeeplearning_tpu.analysis.program_audit import (
    ProgramRecord,
    _sds,
)

_CACHE = {
    "k": jax.ShapeDtypeStruct((2, 2, 64, 2, 8), jnp.int8),
    "v": jax.ShapeDtypeStruct((2, 2, 64, 2, 8), jnp.int8),
    "k_scale": jax.ShapeDtypeStruct((2, 2, 64, 2), jnp.float32),
    "v_scale": jax.ShapeDtypeStruct((2, 2, 64, 2), jnp.float32),
}


def lost_donation() -> ProgramRecord:
    """A decode-shaped step that FORGOT donate_argnums on its cache."""

    def step(cache, tok):
        return {"k": cache["k"].at[0, 0].set(tok)}, tok + 1

    jitted = jax.jit(step)  # planted: no donate_argnums=(0,)
    return ProgramRecord(
        "fixture.lost_donation", jitted,
        ({"k": _sds((2, 4), jnp.int8)}, _sds((), jnp.int8)),
        donate_min=1,
    )


def callback_in_jit() -> ProgramRecord:
    """A hot program with a debug print (host round-trip) inside."""

    def step(x):
        jax.debug.print("x = {x}", x=x)  # planted: callback in jit
        return x * 2.0

    return ProgramRecord(
        "fixture.callback_in_jit", jax.jit(step),
        (_sds((4,), jnp.float32),),
    )


def hoisted_collective():
    """A comm-overlap-shaped step whose gradient sync was hoisted OUT of
    the accumulation scan into a post-scan all-reduce (the exact schedule
    regression the in-scan reduce-scatter contract exists to catch).

    Returns ``(jaxpr, n_buckets)`` for ``check_collective_contract``.
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P
    import numpy as np

    devs = np.array(jax.devices())
    mesh = Mesh(devs.reshape(len(devs)), ("data",))

    def inner(micro):
        def body(acc, xs):
            grads = xs * 2.0  # stand-in backward
            return acc + grads, ()  # planted: accumulates FULL grads

        acc, _ = jax.lax.scan(body, jnp.zeros(micro.shape[-1]), micro)
        # planted: ONE hoisted all-reduce after the scan instead of a
        # per-microbatch in-scan reduce-scatter
        g = jax.lax.psum(acc, "data")
        metrics = jax.lax.pmean(acc.sum(), "data")
        return g, metrics

    sm = shard_map(
        inner, mesh=mesh, in_specs=(P(None, "data"),),
        out_specs=(P("data"), P()), check_rep=False,
    )
    traced = jax.jit(sm).trace(_sds((2, 8 * len(devs)), jnp.float32))
    return traced.jaxpr.jaxpr


def f32_history_returned() -> ProgramRecord:
    """An int8-cache decode that dequantizes the WHOLE history and
    returns it f32 — the QUANT_r10 materialization regression."""

    def step(cache, tok):
        hist = cache["k"].astype(jnp.float32) * cache["k_scale"][..., None]
        out = dict(cache)
        out["k"] = cache["k"].at[0, 0, 0, 0, 0].set(tok)
        return out, hist  # planted: history-shaped f32 output

    return ProgramRecord(
        "fixture.f32_history_returned", jax.jit(step, donate_argnums=(0,)),
        (_CACHE, _sds((), jnp.int8)),
        donate_min=2, int8_history_len=64,
    )


def bf16_history_returned() -> ProgramRecord:
    """Half-width evasion attempt: dequantize the history to bf16 and
    return it — same materialization regression at half the bytes, and
    the audit must not be fooled by the narrower float."""

    def step(cache, tok):
        hist = (
            cache["k"].astype(jnp.bfloat16)
            * cache["k_scale"][..., None].astype(jnp.bfloat16)
        )
        out = dict(cache)
        out["k"] = cache["k"].at[0, 0, 0, 0, 0].set(tok)
        return out, hist  # planted: history-shaped bf16 output

    return ProgramRecord(
        "fixture.bf16_history_returned", jax.jit(step, donate_argnums=(0,)),
        (_CACHE, _sds((), jnp.int8)),
        donate_min=2, int8_history_len=64,
    )


def f32_history_written() -> ProgramRecord:
    """An int8-cache decode that writes dequantized f32 history back
    into a persistent f32 buffer (storing what should stay fused)."""

    def step(cache, f32_shadow, tok):
        hist = cache["k"].astype(jnp.float32) * cache["k_scale"][..., None]
        # planted: full-history f32 update stored via dynamic_update_slice
        shadow = jax.lax.dynamic_update_slice(
            f32_shadow, hist, (0, 0, 0, 0, 0)
        )
        out = dict(cache)
        out["k"] = cache["k"].at[0, 0, 0, 0, 0].set(tok)
        return out, shadow

    return ProgramRecord(
        "fixture.f32_history_written", jax.jit(step, donate_argnums=(0,)),
        (_CACHE, _sds((2, 2, 64, 2, 8), jnp.float32), _sds((), jnp.int8)),
        donate_min=2, int8_history_len=64,
    )


def f32_history_intermediate() -> ProgramRecord:
    """An int8-cache decode that dequantizes the whole history at
    history granularity but keeps the f32 tensor INTERNAL (reduced away
    before the outputs) — invisible to the output/write checks, caught
    only by the strict intermediate audit the flash-decode records arm
    (``int8_head_dim``).  This is the exact shape of the QUANT_r10
    regression: the materialization was a fusable *intermediate*, and it
    still cost +82 ms/step."""

    def step(cache, tok):
        # planted: scale multiply at [.., hist, heads, head_dim] — the
        # history-granular dequant the fused kernel exists to delete
        hist = cache["k"].astype(jnp.float32) * cache["k_scale"][..., None]
        out = dict(cache)
        out["k"] = cache["k"].at[0, 0, 0, 0, 0].set(tok)
        return out, hist.sum()  # reduced: no history-shaped OUTPUT

    return ProgramRecord(
        "fixture.f32_history_intermediate",
        jax.jit(step, donate_argnums=(0,)),
        (_CACHE, _sds((), jnp.int8)),
        donate_min=2, int8_history_len=64, int8_head_dim=8,
    )


def unsharded_leaf():
    """A cache tree that grew a leaf the sharding resolver doesn't know
    — returns ``(tree_abs, shardings)`` for ``check_tree_coverage``."""
    from distributeddeeplearning_tpu.parallel import MeshSpec, create_mesh
    from distributeddeeplearning_tpu.serve.kv_cache import (
        cache_sharding,
        init_cache,
    )

    mesh = create_mesh(MeshSpec())
    cache_abs = jax.eval_shape(
        lambda: init_cache(
            batch_slots=2, num_layers=2, max_seq=16, num_heads=2,
            head_dim=8, dtype=jnp.int8,
        )
    )
    # planted: a new leaf (asymmetric-quantization zero points) the
    # resolver was never taught about
    cache_abs = dict(cache_abs)
    cache_abs["k_zero_point"] = jax.ShapeDtypeStruct(
        (2, 2, 16, 2), jnp.float32
    )
    return cache_abs, cache_sharding(mesh, quantized=True)


def rule_fallthrough_tree():
    """A serve param tree that grew a leaf name NO partition rule
    matches — the planted input for ``check_rule_fallthrough``, the
    layout-table sibling of ``unsharded_leaf``: the leaf would silently
    replicate on every chip instead of failing loudly."""
    return {
        "blocks": {
            "0": {
                # matched sibling (the column-parallel qkv rule) — must
                # NOT fire, pinning that the checker flags only the
                # fallthrough leaf
                "qkv": jax.ShapeDtypeStruct((16, 3, 32), jnp.float32),
                # planted: a LoRA adapter grafted onto the attention
                # block — no qkv/proj rule matches, no terminal rule
                # catches it
                "wq_lora_adapter": jax.ShapeDtypeStruct(
                    (16, 4), jnp.float32
                ),
            }
        }
    }
