"""Fixture faults module: two declared kinds, one injection hook each."""

KINDS = ("covered_kind", "orphan_kind")


class FaultPlan:
    def fire_covered(self):
        return True

    def fire_orphan(self):
        return True
