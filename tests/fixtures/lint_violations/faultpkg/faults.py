"""Fixture faults module: declared kinds with one injection hook each.

``covered_kind`` is wired in ``consumer.py``; ``orphan_kind`` and the
checkpoint-durability kind ``ckpt_corrupt`` are declared (hooks exist on
``FaultPlan``) but never CALLED anywhere — the coverage pass must report
both as uncovered.
"""

KINDS = ("covered_kind", "orphan_kind", "ckpt_corrupt")


class FaultPlan:
    def fire_covered(self):
        return True

    def fire_orphan(self):
        return True

    def take_ckpt_corrupt(self):
        return {"mode": "flip"}
