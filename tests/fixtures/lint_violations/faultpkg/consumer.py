"""Fixture injection site: only ``covered_kind``'s hook is ever called.

``fire_orphan`` appears below in a comment and a string — neither is a
call, so the AST pass must still report ``orphan_kind`` as uncovered.
"""

# plan.fire_orphan() — a comment is not an injection site
DOC = "plan.fire_orphan() in a string is not an injection site either"


def run(plan):
    if plan:
        plan.fire_covered()
