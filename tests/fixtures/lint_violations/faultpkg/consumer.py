"""Fixture injection site: only ``covered_kind``'s hook is ever called.

``fire_orphan`` and ``take_ckpt_corrupt`` appear below in comments and
strings — neither is a call, so the AST pass must still report
``orphan_kind`` AND ``ckpt_corrupt`` as uncovered.
"""

# plan.fire_orphan() — a comment is not an injection site
# plan.take_ckpt_corrupt() — neither is this one
DOC = "plan.fire_orphan() in a string is not an injection site either"
CKPT_DOC = "plan.take_ckpt_corrupt() in a string does not count"


def run(plan):
    if plan:
        plan.fire_covered()
