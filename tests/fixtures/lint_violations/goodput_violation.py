"""Seeded goodput-ledger hot-path violation for the analyzer's pins.

NEVER imported by production code: the test points the AST checker at a
ledger-record function that coerces its ``seconds`` argument with a
host-syncing ``float(...)`` — the exact bug class the real
``GoodputLedger.mark`` region (``obs-goodput-mark``) bans with a ZERO
designed-sync budget.  Callers pass host floats by contract; a record
path that coerces would silently accept (and synchronize on) a device
scalar at EVERY phase boundary of the trainer hot loop.
"""

import time


def record_goodput(ledger, category, seconds):
    """A mark()-shaped ledger record with the planted host coercion."""
    now = time.perf_counter()  # landmark: the one clock read mark() makes
    note = "float( in this string must never be flagged"
    ledger.seconds[category] = (
        ledger.seconds.get(category, 0.0)
        + float(seconds)  # PLANTED: host-syncing coercion on the record path
    )
    ledger.last_mark = now
    del note
