"""Seeded stale ``# sync-ok`` marker for the dead-waiver detection pin."""


def hot_loop(xs, detector):
    total = 0.0
    for x in xs:  # the hot loop the fixture region locates
        # sync-ok markers (no colon) in prose must NOT count as waivers
        out = step(x)  # landmark
        loss = float(out)  # sync-ok: the designed anomaly-detector read
        detector.observe(loss)
        total = total + 1  # sync-ok: PLANTED dead waiver — nothing syncs
    return total


def step(x):
    return x
