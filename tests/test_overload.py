"""Overload survival (PR 17): tenant SLO classes, lossless priority
preemption, admission-time load shedding, and the OVERLOAD chaos bench.

The load-bearing guarantees:

- the queue dequeues strictly by priority class (premium before standard
  before best_effort), whatever order requests arrived in;
- a blocked higher-class head preempts the lowest-class active decode
  LOSSLESSLY: the resumed stream's tokens are bit-identical to a run
  that was never preempted, on BOTH KV layouts;
- the per-request preemption budget bounds starvation: past it the
  victim finishes terminal ``"preempted"`` — never a livelock;
- admission-time shedding fires ONLY for the lowest class, ONLY under
  memory/forecast pressure, never against a resumed preempted stream,
  and every shed carries a ``retry_after_s`` backoff hint;
- shed and preempted finish paths free their pages through the normal
  release path (``PageAllocator.check`` stays green, nothing leaks);
- per-tenant SLOs evaluate per class over bucket-merged latency;
- synthetic traffic schedules are deterministic in (tenants, seed) and
  consume ``burst`` chaos from the process fault plan.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from distributeddeeplearning_tpu.models.pipelined_transformer import (
    init_params,
)
from distributeddeeplearning_tpu.obs.fleet import (
    evaluate_class_slos,
    parse_class_slos,
)
from distributeddeeplearning_tpu.serve import (
    ContinuousBatchingScheduler,
    InferenceEngine,
    PagedInferenceEngine,
    Request,
)
from distributeddeeplearning_tpu.serve.traffic import (
    TenantSpec,
    TrafficGenerator,
    poll_source,
)
from distributeddeeplearning_tpu.utils import faults as faults_mod

CFG = dict(num_layers=2, d_model=32, num_heads=4, d_ff=64, vocab_size=61,
           max_len=64)
HEADS = CFG["num_heads"]


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.key(0), **CFG)


@pytest.fixture(autouse=True)
def _no_ambient_faults():
    faults_mod.reset()
    yield
    faults_mod.reset()


def _prompt(rng, n=6):
    return rng.integers(1, CFG["vocab_size"], n).tolist()


def _staged_poll(*stages, idle=400):
    """poll() releasing each stage's requests at its scheduled loop pass:
    ``stages`` are (pass_number, [requests]); returns None (source
    closed) after ``idle`` passes."""
    state = {"n": 0}
    by_pass = dict(stages)

    def poll():
        state["n"] += 1
        if state["n"] > idle:
            return None
        return by_pass.get(state["n"], [])

    return poll


# --------------------------------------------------------------------------
# priority queue + dequeue order
# --------------------------------------------------------------------------

def test_priority_dequeue_order(params):
    """One slot, all classes submitted upfront in REVERSE priority
    order: completions come out premium, then standard, then
    best_effort — arrival order never outranks class."""
    rng = np.random.default_rng(0)
    reqs = [
        Request(uid="be-0", prompt=_prompt(rng), priority="best_effort"),
        Request(uid="be-1", prompt=_prompt(rng), priority="best_effort"),
        Request(uid="std-0", prompt=_prompt(rng), priority="standard"),
        Request(uid="prem-0", prompt=_prompt(rng), priority="premium"),
        Request(uid="prem-1", prompt=_prompt(rng), priority="premium"),
    ]
    engine = InferenceEngine(params, num_heads=HEADS, batch_slots=1,
                             max_seq=24, prefill_attention="dense")
    results, rep = ContinuousBatchingScheduler(
        engine, max_new_tokens=3).run(reqs)
    order = [r.uid for r in results]
    assert order == ["prem-0", "prem-1", "std-0", "be-0", "be-1"]
    assert rep.per_class["premium"]["requests"] == 2
    assert rep.per_class["best_effort"]["requests"] == 2
    # unlabeled aggregate stays authoritative alongside the class split
    assert rep.requests == 5
    assert rep.ttft_s["p99"] >= rep.ttft_s["p50"]


def test_unknown_priority_rejected(params):
    """An unknown class is rejected per-request ("error"), never raised:
    in live mode a raise out of run() would kill the worker over one
    malformed client request."""
    engine = InferenceEngine(params, num_heads=HEADS, batch_slots=1,
                             max_seq=24, prefill_attention="dense")
    sched = ContinuousBatchingScheduler(engine, max_new_tokens=2)
    results, _ = sched.run(
        [Request(uid="x", prompt=[1, 2], priority="platinum")])
    assert results[0].finish_reason == "error"
    assert "unknown priority class" in results[0].error


# --------------------------------------------------------------------------
# lossless preemption: bit-identical resume on both layouts
# --------------------------------------------------------------------------

@pytest.mark.parametrize("layout", ["dense", "paged"])
def test_preempted_resume_bit_identical(params, layout):
    """A best_effort decode is cut mid-stream by an arriving premium
    request (one slot — slot pressure), requeued, and resumed; its final
    tokens are EXACTLY the tokens of an unpressured run."""
    rng = np.random.default_rng(1)
    be = Request(uid="be", prompt=_prompt(rng, 8), priority="best_effort")
    prem = Request(uid="prem", prompt=_prompt(rng, 5), priority="premium")

    def make_engine(slots):
        if layout == "paged":
            return PagedInferenceEngine(
                params, num_heads=HEADS, batch_slots=slots, max_seq=32,
                page_size=4, prefill_chunk=8)
        return InferenceEngine(params, num_heads=HEADS, batch_slots=slots,
                               max_seq=32, prefill_attention="dense")

    clean, _ = ContinuousBatchingScheduler(
        make_engine(2), max_new_tokens=12).run([be, prem])
    clean_tokens = {r.uid: list(r.tokens) for r in clean}

    sched = ContinuousBatchingScheduler(
        make_engine(1), max_new_tokens=12, preempt_budget=2)
    results, rep = sched.run(
        [], poll=_staged_poll((1, [be]), (5, [prem])))
    by_uid = {r.uid: r for r in results}
    assert by_uid["prem"].finish_reason == "length"
    assert by_uid["be"].finish_reason == "length"
    assert by_uid["be"].preemptions >= 1, "the cut never happened"
    assert rep.preemptions >= 1
    assert rep.per_class["best_effort"]["preemptions"] >= 1
    # THE gate: lossless preemption is not allowed to change output
    assert list(by_uid["be"].tokens) == clean_tokens["be"]
    assert list(by_uid["prem"].tokens) == clean_tokens["prem"]
    # premium never waited behind the full best_effort stream
    order = [r.uid for r in results]
    assert order.index("prem") < order.index("be")


def test_preempt_budget_exhaustion_terminal_never_livelocks(params):
    """preempt_budget=0: the first cut retires the victim terminal
    "preempted" (no tokens — the resubmit replays the whole stream), the
    premium head proceeds, and the run terminates."""
    rng = np.random.default_rng(2)
    be = Request(uid="be", prompt=_prompt(rng, 8), priority="best_effort")
    prem = Request(uid="prem", prompt=_prompt(rng, 5), priority="premium")
    engine = InferenceEngine(params, num_heads=HEADS, batch_slots=1,
                             max_seq=32, prefill_attention="dense")
    sched = ContinuousBatchingScheduler(
        engine, max_new_tokens=12, preempt_budget=0)
    results, rep = sched.run(
        [], poll=_staged_poll((1, [be]), (5, [prem])))
    by_uid = {r.uid: r for r in results}
    assert by_uid["be"].finish_reason == "preempted"
    assert by_uid["be"].tokens == []
    assert by_uid["prem"].finish_reason == "length"
    assert rep.per_class["best_effort"]["preempted"] == 1


def test_pages_released_after_preempt_and_shed(params):
    """Shed and preempted finishes free their bookkeeping through the
    normal release path: after a run with both, the allocator audit is
    green and no page is still in use (prefix pages may sit reclaimable
    — that is the cache, not a leak)."""
    rng = np.random.default_rng(3)
    be = [Request(uid=f"be-{i}", prompt=_prompt(rng, 12),
                  priority="best_effort") for i in range(6)]
    prem = [Request(uid=f"prem-{i}", prompt=_prompt(rng, 12),
                    priority="premium") for i in range(2)]
    engine = PagedInferenceEngine(
        params, num_heads=HEADS, batch_slots=3, max_seq=32,
        page_size=8, num_pages=11, prefill_chunk=8)
    sched = ContinuousBatchingScheduler(
        engine, max_new_tokens=16, shed_policy="shed", preempt_budget=2,
        shed_patience=0)
    results, rep = sched.run(
        [], poll=_staged_poll((1, be), (6, prem)))
    assert len(results) == 8
    assert rep.per_class["best_effort"]["shed"] > 0 or rep.preemptions > 0
    engine.allocator.check()
    assert engine.allocator.pages_in_use == 0


# --------------------------------------------------------------------------
# admission-time shedding
# --------------------------------------------------------------------------

class _OneAdmitLedger:
    """Fake HBM forecast: admits exactly one request, rejects the rest —
    deterministic forecast pressure without building a real ledger."""

    capacity_bytes = 1  # non-None: the committed walk engages

    def __init__(self):
        self.admitted = 0

    def committed_bytes(self):
        return 0

    def admit_ok(self, extra, committed=None):
        if self.admitted == 0:
            self.admitted += 1
            return True
        return False


def test_forecast_pressure_sheds_best_effort_not_premium(params):
    """Injected forecast pressure (ledger admits one): the premium head
    is admitted and completes; every best_effort head is shed with a
    retry_after_s hint; nothing is lost."""
    rng = np.random.default_rng(4)
    reqs = [
        Request(uid="be-0", prompt=_prompt(rng), priority="best_effort"),
        Request(uid="be-1", prompt=_prompt(rng), priority="best_effort"),
        Request(uid="prem", prompt=_prompt(rng), priority="premium"),
    ]
    engine = PagedInferenceEngine(
        params, num_heads=HEADS, batch_slots=2, max_seq=32,
        page_size=8, prefill_chunk=8)
    sched = ContinuousBatchingScheduler(
        engine, max_new_tokens=4, shed_policy="shed", shed_patience=0,
        hbm_ledger=_OneAdmitLedger())
    results, rep = sched.run(reqs)
    by_uid = {r.uid: r for r in results}
    assert by_uid["prem"].finish_reason == "length"
    for uid in ("be-0", "be-1"):
        assert by_uid[uid].finish_reason == "shed"
        assert by_uid[uid].tokens == []
        assert by_uid[uid].retry_after_s is not None
        assert by_uid[uid].retry_after_s > 0
    assert rep.per_class["best_effort"]["shed"] == 2
    assert rep.per_class["premium"]["shed"] == 0
    assert rep.finish_reasons == {"length": 1, "shed": 2}


def test_shed_policy_block_never_sheds(params):
    """Default policy: the same pressure only queues — page pressure
    with work in flight waits for completions, nothing sheds."""
    rng = np.random.default_rng(5)
    reqs = [Request(uid=f"be-{i}", prompt=_prompt(rng, 12),
                    priority="best_effort") for i in range(5)]
    engine = PagedInferenceEngine(
        params, num_heads=HEADS, batch_slots=3, max_seq=32,
        page_size=8, num_pages=11, prefill_chunk=8)
    results, rep = ContinuousBatchingScheduler(
        engine, max_new_tokens=8).run(reqs)
    assert rep.finish_reasons == {"length": 5}
    assert rep.per_class["best_effort"]["shed"] == 0


def test_shed_patience_rides_out_transient_pressure(params):
    """With enough patience, pressure that in-flight completions relieve
    within a few decode steps sheds NOTHING — the valve only opens when
    the head stays blocked past the patience window."""
    rng = np.random.default_rng(6)
    reqs = [Request(uid=f"be-{i}", prompt=_prompt(rng, 12),
                    priority="best_effort") for i in range(4)]
    engine = PagedInferenceEngine(
        params, num_heads=HEADS, batch_slots=3, max_seq=32,
        page_size=8, num_pages=11, prefill_chunk=8)
    results, rep = ContinuousBatchingScheduler(
        engine, max_new_tokens=4, shed_policy="shed",
        shed_patience=1_000_000).run(reqs)
    assert rep.finish_reasons == {"length": 4}


def test_preempted_stream_never_shed(params):
    """Lossless means lossless: once a stream has been preempted it is
    exempt from the shed valve — it resumes or retires terminal
    "preempted", it never comes back "shed" with its tokens thrown
    away."""
    rng = np.random.default_rng(7)
    be = [Request(uid=f"be-{i}", prompt=_prompt(rng, 12),
                  priority="best_effort") for i in range(6)]
    prem = [Request(uid=f"prem-{i}", prompt=_prompt(rng, 12),
                    priority="premium") for i in range(3)]
    engine = PagedInferenceEngine(
        params, num_heads=HEADS, batch_slots=3, max_seq=32,
        page_size=8, num_pages=11, prefill_chunk=8)
    sched = ContinuousBatchingScheduler(
        engine, max_new_tokens=16, shed_policy="shed", preempt_budget=2,
        shed_patience=0)
    results, _ = sched.run([], poll=_staged_poll((1, be), (6, prem)))
    for r in results:
        if r.preemptions > 0:
            assert r.finish_reason != "shed", r.uid


def test_scheduler_knob_validation(params):
    engine = InferenceEngine(params, num_heads=HEADS, batch_slots=1,
                             max_seq=16, prefill_attention="dense")
    with pytest.raises(ValueError, match="shed_policy"):
        ContinuousBatchingScheduler(engine, shed_policy="drop")
    with pytest.raises(ValueError, match="preempt_budget"):
        ContinuousBatchingScheduler(engine, preempt_budget=-1)
    with pytest.raises(ValueError, match="shed_patience"):
        ContinuousBatchingScheduler(engine, shed_patience=-1)
    with pytest.raises(ValueError, match="priority_classes"):
        ContinuousBatchingScheduler(engine, priority_classes=())
    with pytest.raises(ValueError, match="duplicate"):
        ContinuousBatchingScheduler(engine, priority_classes=("a", "a"))


# --------------------------------------------------------------------------
# per-tenant SLOs
# --------------------------------------------------------------------------

def _latency(p99_ttft, p99_tpot, samples=5):
    return {
        "ttft_s": {"p99": p99_ttft}, "ttft_samples": samples,
        "tpot_s": {"p99": p99_tpot}, "tpot_samples": samples,
    }


def test_parse_class_slos():
    slos = parse_class_slos([
        "premium:ttft_p99_s=0.5,tpot_p99_s=0.1",
        "best_effort:max_error_rate=0.5",
    ])
    assert set(slos) == {"premium", "best_effort"}
    assert slos["premium"].ttft_p99_s == 0.5
    assert slos["best_effort"].ttft_p99_s is None
    with pytest.raises(ValueError, match="not <class>"):
        parse_class_slos(["ttft_p99_s=0.5"])
    with pytest.raises(ValueError, match="duplicate"):
        parse_class_slos(["premium:ttft_p99_s=1", "premium:tpot_p99_s=1"])


def test_evaluate_class_slos_pass_and_violation():
    slos = parse_class_slos(["premium:ttft_p99_s=0.5"])
    report = {
        "per_class": {"premium": {"requests": 5, "errors": 0}},
        "lost_requests": 0,
    }
    ok = evaluate_class_slos(
        slos, fleet_report=report,
        per_class_latency={"premium": _latency(0.2, 0.01)})
    assert ok["pass"] is True
    assert ok["per_class"]["premium"]["criteria"]["ttft_p99_s"]["ok"]

    bad = evaluate_class_slos(
        slos, fleet_report=report,
        per_class_latency={"premium": _latency(0.9, 0.01)})
    assert bad["pass"] is False

    # zero-sample class FAILS its latency criteria: an SLO that cannot
    # be demonstrated is not met
    empty = evaluate_class_slos(
        slos, fleet_report={"per_class": {}, "lost_requests": 0},
        per_class_latency={})
    assert empty["pass"] is False

    # lost requests are fleet-global: they violate every evaluated class
    lost = evaluate_class_slos(
        slos, fleet_report=dict(report, lost_requests=1),
        per_class_latency={"premium": _latency(0.2, 0.01)})
    assert lost["pass"] is False


# --------------------------------------------------------------------------
# synthetic traffic
# --------------------------------------------------------------------------

_TENANTS = (
    TenantSpec(name="prem", priority="premium", rate_rps=3.0),
    TenantSpec(name="be", priority="best_effort", rate_rps=5.0,
               arrival="bursty", burst_secs=1.0, burst_period_s=2.0),
)


def test_traffic_schedule_deterministic():
    a = TrafficGenerator(_TENANTS, vocab_size=61, seed=7).schedule(4.0)
    b = TrafficGenerator(_TENANTS, vocab_size=61, seed=7).schedule(4.0)
    assert [(t.at_s, t.request.uid, t.request.prompt) for t in a] == \
           [(t.at_s, t.request.uid, t.request.prompt) for t in b]
    c = TrafficGenerator(_TENANTS, vocab_size=61, seed=8).schedule(4.0)
    assert [(t.at_s, t.request.uid) for t in a] != \
           [(t.at_s, t.request.uid) for t in c]
    # adding a tenant never perturbs an existing tenant's schedule
    widened = TrafficGenerator(
        _TENANTS + (TenantSpec(name="std", rate_rps=2.0),),
        vocab_size=61, seed=7).schedule(4.0)
    assert [(t.at_s, t.request.uid) for t in widened
            if t.request.tenant == "prem"] == \
           [(t.at_s, t.request.uid) for t in a if t.request.tenant == "prem"]
    for tr in a:
        assert tr.request.priority in ("premium", "best_effort")
        assert tr.request.tenant in ("prem", "be")
        assert all(0 < tok < 61 for tok in tr.request.prompt)


def test_traffic_burst_fault_consumed():
    """A DDLT_FAULTS burst spec splices extra arrivals into the named
    tenant exactly once — the plan entry is consumed by the build."""
    base = TrafficGenerator(_TENANTS, vocab_size=61, seed=7).schedule(4.0)
    faults_mod.install_plan("burst@1:tenant=be:rps=30:secs=2:at=0.5")
    try:
        gen = TrafficGenerator(_TENANTS, vocab_size=61, seed=7)
        burst = gen.schedule(4.0)
        n_be = sum(1 for t in burst if t.request.tenant == "be")
        n_be_base = sum(1 for t in base if t.request.tenant == "be")
        assert n_be > n_be_base + 10, "burst never spliced in"
        # premium arrivals untouched by the best_effort burst
        assert [(t.at_s, t.request.uid) for t in burst
                if t.request.tenant == "prem"] == \
               [(t.at_s, t.request.uid) for t in base
                if t.request.tenant == "prem"]
        # consumed: a second build sees no burst entry
        again = TrafficGenerator(_TENANTS, vocab_size=61, seed=7).schedule(4.0)
        assert len(again) == len(base)
    finally:
        faults_mod.reset()


def test_traffic_slow_tenant_fault_scales_prompts():
    faults_mod.install_plan("slow_tenant@1:tenant=be:factor=3")
    try:
        slow = TrafficGenerator(_TENANTS, vocab_size=61, seed=7).schedule(4.0)
    finally:
        faults_mod.reset()
    base = TrafficGenerator(_TENANTS, vocab_size=61, seed=7).schedule(4.0)
    slow_max = max(len(t.request.prompt) for t in slow
                   if t.request.tenant == "be")
    base_max = max(len(t.request.prompt) for t in base
                   if t.request.tenant == "be")
    assert slow_max > base_max


def test_poll_source_replays_in_order():
    sched = TrafficGenerator(_TENANTS, vocab_size=61, seed=7).schedule(2.0)
    clock = {"t": 0.0}
    poll = poll_source(sched, speedup=1.0, clock=lambda: clock["t"])
    got = []
    batch = poll()  # clock starts here, releases at_s == 0 arrivals
    got.extend(batch)
    for _ in range(400):
        clock["t"] += 0.05
        batch = poll()
        if batch is None:
            break
        got.extend(batch)
    assert batch is None, "source never closed"
    assert [r.uid for r in got] == [t.request.uid for t in sched]


def test_traffic_validation():
    with pytest.raises(ValueError, match="arrival"):
        TenantSpec(name="x", arrival="lumpy")
    with pytest.raises(ValueError, match="rate_rps"):
        TenantSpec(name="x", rate_rps=0)
    with pytest.raises(ValueError, match="burst_secs"):
        TenantSpec(name="x", arrival="bursty", burst_secs=5.0,
                   burst_period_s=2.0)
    with pytest.raises(ValueError, match="duplicate"):
        TrafficGenerator(
            (TenantSpec(name="x"), TenantSpec(name="x")), vocab_size=61)
    with pytest.raises(ValueError, match="speedup"):
        poll_source([], speedup=0)


# --------------------------------------------------------------------------
# the OVERLOAD bench end to end (CPU smoke)
# --------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.timeout(280)
def test_bench_overload_smoke(tmp_path):
    """``bench.py --overload --small --steps-cap 1``: schema-valid
    OVERLOAD artifact with every gate green."""
    import os
    import subprocess
    import sys as _sys

    from distributeddeeplearning_tpu.obs.schema import (
        validate_artifact,
        validate_overload_payload,
    )

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    report = tmp_path / "OVERLOAD_r99.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DDLT_FAULTS", None)
    proc = subprocess.run(
        [
            _sys.executable, os.path.join(repo, "bench.py"),
            "--overload", "--small", "--steps-cap", "1",
            "--serve-replicas", "2",
            "--report", str(report),
        ],
        cwd=repo, env=env, capture_output=True, text=True, timeout=260,
    )
    # rc 1 = a throughput-dependent gate (shed/preempt counts) missed on
    # this host — tolerated in smoke; anything else is a crash
    assert proc.returncode in (0, 1), proc.stderr[-3000:]
    assert report.exists(), proc.stderr[-3000:]
    line = validate_artifact(str(report))
    import json as _json
    validate_overload_payload(_json.loads(report.read_text()))
    assert line["bench_revision"] >= 19
    # the CORRECTNESS invariants hold unconditionally, whatever the
    # timing did: nothing lost, no resumed stream diverged, no shed
    # outside the best_effort class
    assert line["fleet_report"]["lost_requests"] == 0
    assert line["mismatched_uids"] == []
    assert all(
        n == 0 for cls, n in line["shed_by_class"].items()
        if cls != "best_effort"
    )
    if proc.returncode == 0:
        assert all(line["gates"].values()), line["gates"]
        assert line["shed_count"] > 0
        assert line["preemptions"] > 0
