"""CLI verb-tree tests: the reference's `inv` surface, verb for verb.

Reference CLI listing: ``README.md:271-311``; namespace assembly
``tasks.py:180-225``.  Cloud-touching verbs run under ``--dry-run`` and are
asserted on the printed command lines.
"""

from __future__ import annotations

import pytest

from distributeddeeplearning_tpu.cli.main import build_parser, main
from distributeddeeplearning_tpu.version import __version__
from distributeddeeplearning_tpu.workloads._runner import (
    _coerce,
    parse_flags,
    run_from_argv,
)


@pytest.fixture
def project(tmp_path, monkeypatch):
    """Run the CLI from a throwaway project dir with a populated .env."""
    monkeypatch.chdir(tmp_path)
    (tmp_path / ".env").write_text(
        "GCS_BUCKET=bkt\nTPU_NAME=pod-x\nGCP_ZONE=us-west4-a\n"
        "EXPERIMENT_NAME=e2e\n"
    )
    return tmp_path


def test_help_lists_full_verb_tree():
    tree = build_parser().format_help()
    for verb in (
        "setup", "login", "select-project", "delete", "tpu", "storage",
        "imagenet", "bert", "benchmark", "experiment", "tensorboard",
        "runs", "experiments", "new", "config", "version",
    ):
        assert verb in tree


def test_version(capsys):
    assert main(["version"]) == 0
    assert capsys.readouterr().out.strip() == __version__


def test_config_set_show_roundtrip(project, capsys):
    assert main(["config", "set", "tpu_type", "v5litepod-64"]) == 0
    assert "TPU_TYPE=v5litepod-64" in (project / ".env").read_text()
    main(["config", "show"])
    assert "TPU_TYPE=v5litepod-64" in capsys.readouterr().out


def test_dry_run_remote_submit_prints_fanout(project, capsys):
    rc = main(
        ["--dry-run", "imagenet", "submit", "remote", "tfrecords", "--epochs", "2"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "gcloud compute tpus tpu-vm ssh pod-x" in out
    assert "--worker all" in out
    assert "DISTRIBUTED=True" in out
    assert "workloads.imagenet" in out
    assert "gs://bkt/tfrecords" in out


def test_dry_run_local_submit_resolves_data_dir(project, capsys):
    main(["config", "set", "DATA_DIR", str(project / "data")])
    capsys.readouterr()
    rc = main(["--dry-run", "imagenet", "submit", "local", "images"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "workloads.imagenet" in out
    assert f"{project}/data/images/train" in out
    assert "gcloud" not in out  # local path touches no cloud


def test_dry_run_benchmark_and_bert_trees(project, capsys):
    assert main(["--dry-run", "benchmark", "submit", "local", "synthetic"]) == 0
    assert "workloads.benchmark" in capsys.readouterr().out
    assert main(["--dry-run", "bert", "submit", "remote", "synthetic"]) == 0
    assert "workloads.bert" in capsys.readouterr().out
    # bert has no raw-image path: rejected at parse time, not at runtime
    with pytest.raises(SystemExit):
        main(["--dry-run", "bert", "submit", "remote", "images"])


def test_dry_run_setup_skips_data_plane(project, capsys):
    rc = main(["--dry-run", "setup", "--train-tar", "t.tar", "--val-tar",
               "v.tar", "--val-map", "m.csv"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "[dry-run] prepare_imagenet" in out
    assert "[dry-run] generate_tfrecords" in out
    assert "setup complete (dry run)" in out


def test_tensorboard_resolves_remote_run_gs_dir(project, capsys):
    """A remote run's recorded gs:// TB dir wins over the local registry
    path — streaming a running pod job (aml_compute.py:567-635 role)."""
    from distributeddeeplearning_tpu.control.runs import RunRegistry

    registry = RunRegistry("runs")
    run = registry.new_run("e2e", "imagenet", "remote", [])
    run.extra["tensorboard_dir"] = f"gs://bkt/runs/e2e/{run.run_id}/tb"
    registry.update(run, status="running")

    rc = main(["--dry-run", "tensorboard", "--run", run.run_id])
    out = capsys.readouterr().out
    assert rc == 0
    assert f"gs://bkt/runs/e2e/{run.run_id}/tb" in out

    # a run without a recorded dir falls back to the local registry tree
    run2 = registry.new_run("e2e", "imagenet", "local", [])
    rc = main(["--dry-run", "tensorboard", "--run", run2.run_id])
    out = capsys.readouterr().out
    assert rc == 0 and f"runs/e2e/{run2.run_id}/tb" in out


def test_runs_show_metrics_rows(project, capsys):
    """`ddlt runs --run ID` prints the per-epoch JSONL rows the Trainer
    appended (the reference's run.log_row channel)."""
    import json

    from distributeddeeplearning_tpu.control.runs import RunRegistry

    registry = RunRegistry("runs")
    run = registry.new_run("e2e", "imagenet", "local", [])
    metrics = registry.run_dir(run) / "metrics.jsonl"
    metrics.write_text(
        json.dumps({"epoch": 1, "train_loss": 2.5}) + "\n"
        + json.dumps({"epoch": 2, "train_loss": 1.9}) + "\n"
    )
    rc = main(["runs", "--run", run.run_id])
    out = capsys.readouterr().out
    assert rc == 0 and '"epoch": 2' in out and '"train_loss": 1.9' in out

    rc = main(["runs", "--run", "nope"])
    assert rc == 1
    assert "unknown run" in capsys.readouterr().out


def test_dry_run_storage_and_tpu_verbs(project, capsys):
    assert main(["--dry-run", "storage", "create-bucket"]) == 0
    assert "gcloud storage buckets create gs://bkt" in capsys.readouterr().out
    assert main(["--dry-run", "tpu", "create"]) == 0
    assert "tpu-vm create pod-x" in capsys.readouterr().out
    assert main(["--dry-run", "tpu", "ssh", "hostname"]) == 0
    assert "--command hostname" in capsys.readouterr().out
    assert main(["--dry-run", "delete", "--storage"]) == 0
    out = capsys.readouterr().out
    assert "tpu-vm delete pod-x" in out and "storage rm -r gs://bkt" in out


def test_global_flags_accepted_after_verb(project, capsys):
    env = project / "alt.env"
    env.write_text("GCS_BUCKET=other\n")
    assert main(["storage", "create-bucket", "--env-file", str(env), "--dry-run"]) == 0
    assert "gs://other" in capsys.readouterr().out


def test_runs_and_experiments_listing(project, capsys):
    from distributeddeeplearning_tpu.control.runs import RunRegistry

    registry = RunRegistry(project / "runs")
    run = registry.new_run("e2e", "imagenet", "local", [])
    registry.update(run, status="completed", returncode=0)
    assert main(["runs"]) == 0
    assert "imagenet" in capsys.readouterr().out
    assert main(["experiments"]) == 0
    assert "e2e" in capsys.readouterr().out


def test_new_generates_project(project, capsys):
    rc = main(
        ["new", "myproj", "--gcp-project", "gp", "--gcs-bucket", "gb",
         "--tpu-type", "v5litepod-8"]
    )
    assert rc == 0
    env_text = (project / "myproj" / ".env").read_text()
    assert "PROJECT_NAME=myproj" in env_text
    assert "GCP_PROJECT=gp" in env_text
    assert "GCS_BUCKET=gb" in env_text
    assert "TPU_TYPE=v5litepod-8" in env_text
    assert (project / "myproj" / "Makefile").exists()
    assert (project / "myproj" / "experiment.py").exists()
    assert "ddlt" in (project / "myproj" / "README.md").read_text()
    # refuses to overwrite
    with pytest.raises(FileExistsError):
        main(["new", "myproj"])


def test_unknown_flag_rejected_for_non_submit_verbs(project, capsys):
    with pytest.raises(SystemExit):
        main(["runs", "--bogus", "1"])


# --- the fire-equivalent flag runner ---------------------------------------


def test_parse_flags_forms():
    assert parse_flags(["--a", "1", "--b=x", "--kebab-case", "v"]) == {
        "a": "1", "b": "x", "kebab_case": "v",
    }
    with pytest.raises(SystemExit):
        parse_flags(["positional"])
    with pytest.raises(SystemExit):
        parse_flags(["--dangling"])


def test_coerce_by_default_type():
    assert _coerce("3", 1) == 3
    assert _coerce("0.5", 1.0) == 0.5
    assert _coerce("true", False) is True
    assert _coerce("no", True) is False
    assert _coerce("plain", "s") == "plain"
    assert _coerce("7", None) == 7  # literal fallback
    assert _coerce("gs://x", None) == "gs://x"


def test_run_from_argv_signature_checking():
    def target(*, epochs: int = 1, name: str = "a"):
        return epochs, name

    assert run_from_argv(target, ["--epochs", "4", "--name", "z"]) == (4, "z")
    with pytest.raises(SystemExit, match="unknown flag"):
        run_from_argv(target, ["--nope", "1"])


def test_select_project_interactive_chooser(project, capsys, monkeypatch):
    """inv select-subscription parity (tasks.py:56-71): tabulate the
    account's projects, prompt by number, persist the pick to .env."""
    import json

    from distributeddeeplearning_tpu.control.command import (
        CommandResult,
        CommandRunner,
    )

    calls = []

    def fake_run(self, argv, **kwargs):
        argv = [str(a) for a in argv]
        calls.append(argv)
        if "projects" in argv and "list" in argv:
            listing = [
                {"projectId": "proj-alpha", "name": "Alpha"},
                {"projectId": "proj-beta", "name": "Beta"},
            ]
            return CommandResult(argv=argv, returncode=0, stdout=json.dumps(listing))
        return CommandResult(argv=argv, returncode=0)

    monkeypatch.setattr(CommandRunner, "run", fake_run)
    monkeypatch.setattr("sys.stdin.isatty", lambda: True)
    monkeypatch.setattr("builtins.input", lambda prompt="": "1")
    assert main(["select-project"]) == 0
    out = capsys.readouterr().out
    assert "proj-alpha" in out and "proj-beta" in out  # tabulated listing
    assert "GCP_PROJECT=proj-beta" in (project / ".env").read_text()
    assert any("set" in a and "proj-beta" in a for a in calls)


def test_select_project_invalid_choice_errors(project, monkeypatch, capsys):
    import json

    from distributeddeeplearning_tpu.control.command import (
        CommandResult,
        CommandRunner,
    )

    def fake_run(self, argv, **kwargs):
        argv = [str(a) for a in argv]
        if "projects" in argv:
            return CommandResult(
                argv=argv, returncode=0,
                stdout=json.dumps([{"projectId": "p1", "name": "P1"}]),
            )
        return CommandResult(argv=argv, returncode=0)

    monkeypatch.setattr(CommandRunner, "run", fake_run)
    monkeypatch.setattr("sys.stdin.isatty", lambda: True)
    monkeypatch.setattr("builtins.input", lambda prompt="": "9")
    assert main(["select-project"]) == 1


def test_runs_status_filter(project, capsys):
    from distributeddeeplearning_tpu.control.runs import RunRegistry

    registry = RunRegistry(project / "runs")
    r1 = registry.new_run("e2e", "imagenet", "remote", [])
    registry.update(r1, status="running")
    r2 = registry.new_run("e2e", "bert", "local", [])
    registry.update(r2, status="completed", returncode=0)

    assert main(["runs", "--status", "running"]) == 0
    out = capsys.readouterr().out
    assert r1.run_id in out and r2.run_id not in out

    assert main(["runs", "--status", "failed"]) == 0
    assert "no failed runs" in capsys.readouterr().out


class TestCompletionAndRepl:
    def test_completion_bash_covers_verb_tree(self, capsys):
        assert main(["completion", "bash"]) == 0
        script = capsys.readouterr().out
        # every top-level verb present, generated from the live parser
        for verb in ("setup", "tpu", "storage", "runs", "imagenet",
                     "interactive", "completion", "tensorboard"):
            assert verb in script
        # nested verbs and flags are baked in
        assert "prepare-imagenet" in script
        assert "val-maps" in script
        assert "--dry-run" in script
        assert "complete -F _ddlt_complete ddlt" in script

    def test_completion_bash_is_valid_shell(self, capsys, tmp_path):
        import subprocess

        main(["completion", "bash"])
        script = tmp_path / "c.sh"
        script.write_text(capsys.readouterr().out)
        assert subprocess.run(["bash", "-n", str(script)]).returncode == 0

    def test_completion_zsh_wraps_bashcompinit(self, capsys):
        assert main(["completion", "zsh"]) == 0
        out = capsys.readouterr().out
        assert "bashcompinit" in out

    def test_interactive_repl_preloads_sdk(self, tmp_path, monkeypatch):
        """--repl hands cfg/pod/submitter/registry to the REPL namespace
        (tasks.py:84-87 parity) instead of SSHing to a worker."""
        env = tmp_path / ".env"
        env.write_text(
            "GCS_BUCKET=b\nTPU_NAME=pod-x\nTPU_TYPE=v5litepod-16\n"
            "GCP_ZONE=us-west4-a\n"
        )
        captured = {}

        def fake_ipython(argv, user_ns, config=None):
            captured.update(user_ns)
            # banner text must travel via the traitlets config (the real
            # start_ipython rejects a string display_banner)
            assert "ddlt interactive REPL" in (
                config.TerminalInteractiveShell.banner1
            )

        import distributeddeeplearning_tpu.cli.main as cli_main

        monkeypatch.setitem(
            __import__("sys").modules, "IPython",
            type("M", (), {"start_ipython": staticmethod(fake_ipython)}),
        )
        assert main(["--env-file", str(env), "interactive", "--repl"]) == 0
        assert {"cfg", "runner", "registry", "pod", "submitter"} <= set(captured)
        assert captured["pod"].name == "pod-x"


def test_storage_build_cache_verb(tmp_path):
    """ddlt storage build-cache decodes a shard set into the raw cache."""
    from distributeddeeplearning_tpu.data.bench_data import (
        generate_bench_shards,
    )
    from distributeddeeplearning_tpu.data.raw_cache import open_raw_cache

    d = str(tmp_path / "shards")
    generate_bench_shards(d, num_images=6, num_shards=1, seed=3)
    cache = str(tmp_path / "cache")
    assert main([
        "storage", "build-cache", "--data-dir", d, "--split", "train",
        "--image-size", "32", "--cache-dir", cache,
    ]) == 0
    manifest, images, labels = open_raw_cache(cache)
    assert manifest["count"] == 6
    assert images.shape == (6, 32, 32, 3)

    # dry-run does not build
    assert main([
        "--dry-run", "storage", "build-cache", "--data-dir", d,
        "--cache-dir", str(tmp_path / "nope"),
    ]) == 0
    import os

    assert not os.path.exists(tmp_path / "nope")


def test_storage_build_cache_shard_flags(tmp_path):
    """--shard-count/--shard-index pre-build the per-host '-shardIofN'
    cache dirs multi-host runs actually read (unsuffixed caches were
    silently ignored by sharded jobs)."""
    from distributeddeeplearning_tpu.data.bench_data import (
        generate_bench_shards,
    )
    from distributeddeeplearning_tpu.data.raw_cache import (
        cache_path_for,
        open_raw_cache,
    )

    d = str(tmp_path / "shards")
    generate_bench_shards(d, num_images=8, num_shards=2, seed=4)
    assert main([
        "storage", "build-cache", "--data-dir", d, "--split", "train",
        "--image-size", "32", "--shard-count", "2", "--shard-index", "1",
    ]) == 0
    expected = cache_path_for(d, True, 32, shard_count=2, shard_index=1)
    assert expected.endswith("-shard1of2")
    manifest, images, labels = open_raw_cache(expected)
    assert manifest["count"] > 0
    assert images.shape[1:] == (32, 32, 3)

    # out-of-range index is rejected loudly
    assert main([
        "storage", "build-cache", "--data-dir", d,
        "--shard-count", "2", "--shard-index", "2",
    ]) == 1
