"""Paged flash-decode kernel (``ops.flash_decode``) — PR 12 pins.

The load-bearing guarantees:

- **f32 bit-exactness**: ``kernel="flash"`` decode/chunk/verify logits
  are BITWISE identical to the gather-dense reference at every position
  on both layouts (off-TPU the flash twin is op-for-op the gather
  program — the decode==full-forward pin extends through it for free),
  pinned over a teacher-forced multi-position walk;
- **Pallas kernel math**: the actual kernel (interpret mode on CPU,
  ``kernel="pallas"``) matches the gather reference to f32 tolerance
  with identical argmaxes, on both layouts, f32 AND int8 — including the
  in-tile dequant and the exact-own-token overlay;
- **int8 scale-exactness**: the flash int8 path reads the SAME int8
  codes + scales the gather path reads (cache writes are kernel-
  independent, pinned bitwise) and its folded dequant tracks the
  history-granular reference to float tolerance with identical greedy
  choices; the flash int8 engine is run-to-run deterministic;
- **prefix-cache interplay**: an int8 flash engine decodes bit-
  identically on a prefix-cache hit whose shared length is NOT a chunk
  multiple (chunk-alignment invariance survives the kernel);
- **spec interplay**: rollback-then-redecode over the flash kernel —
  a forced-rejection speculative step followed by rollback leaves the
  cache decoding exactly as a never-drafted run (both layouts ride the
  same kernel through ``forward_verify*``);
- ``bench.py --quant`` (which now gates the kv_int8 both-axes win and
  the f32 flash==gather token identity) smokes end-to-end on CPU.
"""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.models.pipelined_transformer import (
    forward_decode,
    forward_decode_paged,
    forward_prefill_chunk,
    init_params,
)
from distributeddeeplearning_tpu.ops import flash_decode as fd
from distributeddeeplearning_tpu.serve import (
    ContinuousBatchingScheduler,
    PagedInferenceEngine,
    init_cache,
    init_paged_cache,
    synthetic_requests,
)

CFG = dict(num_layers=2, d_model=32, num_heads=2, d_ff=48, vocab_size=53,
           max_len=64)
HEADS = CFG["num_heads"]
HD = CFG["d_model"] // HEADS
L = CFG["num_layers"]
S = 64
PS = 8  # page size >= fd.PALLAS_BLOCK_FLOOR so "pallas" runs the kernel
B = 2


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.key(0), **CFG)


def _paged_setup(dtype=None):
    nb = S // PS
    cache = init_paged_cache(
        num_pages=B * nb + 2, num_layers=L, page_size=PS, num_heads=HEADS,
        head_dim=HD, dtype=dtype or jnp.float32,
    )
    # slot i owns pages [1 + i*nb, 1 + (i+1)*nb) — fixed disjoint tables
    tables = jnp.asarray(
        1 + np.arange(B)[:, None] * nb + np.arange(nb)[None], jnp.int32
    )
    return cache, tables


_WALKS: dict = {}


def _decode_walk(params, kernel, *, layout, dtype=None, steps=16):
    """Teacher-forced decode walk from an empty cache: fixed token
    stream, per-step logits collected — positions 0..steps-1 so every
    comparison covers a different history depth.  Memoized per
    (kernel, layout, dtype): several tests compare against the same
    gather reference, and the walk is the expensive part."""
    key = (kernel, layout, str(dtype), steps)
    if key in _WALKS:
        return _WALKS[key]
    rng = np.random.default_rng(5)
    toks = rng.integers(0, CFG["vocab_size"], size=(steps, B)).astype(
        np.int32
    )
    if layout == "paged":
        cache, tables = _paged_setup(dtype)
    else:
        cache = init_cache(
            batch_slots=B, num_layers=L, max_seq=S, num_heads=HEADS,
            head_dim=HD, dtype=dtype or jnp.float32,
        )
    out = []
    for i in range(steps):
        pos = jnp.full((B,), i, jnp.int32)
        if layout == "paged":
            logits, cache = forward_decode_paged(
                params, jnp.asarray(toks[i]), cache, pos, tables,
                num_heads=HEADS, page_size=PS, kernel=kernel,
            )
        else:
            logits, cache = forward_decode(
                params, jnp.asarray(toks[i]), cache, pos,
                num_heads=HEADS, kernel=kernel,
            )
        out.append(np.asarray(logits))
    _WALKS[key] = (np.stack(out), cache)
    return _WALKS[key]


@pytest.mark.parametrize("layout", ["dense", "paged"])
def test_flash_f32_bit_exact_vs_gather_every_position(params, layout):
    """THE f32 pin: flash logits == gather logits BITWISE at every
    position of a 20-step walk, and the caches land bit-identical."""
    ref, c_ref = _decode_walk(params, "gather", layout=layout)
    got, c_got = _decode_walk(params, "flash", layout=layout)
    np.testing.assert_array_equal(ref, got)
    for key in c_ref:
        np.testing.assert_array_equal(
            np.asarray(c_ref[key]), np.asarray(c_got[key])
        )


@pytest.mark.parametrize("layout", ["dense", "paged"])
@pytest.mark.parametrize("dtype", [None, jnp.int8])
def test_pallas_kernel_matches_gather_reference(params, layout, dtype):
    """The actual Pallas kernel (interpret mode on CPU): online-softmax
    split-K over pages — f32-tolerance match against the gather-dense
    reference with identical argmaxes at every walk position, f32 and
    int8 (in-tile dequant + exact-own-token overlay) on both layouts."""
    ref, _ = _decode_walk(params, "gather", layout=layout, dtype=dtype)
    got, _ = _decode_walk(params, "pallas", layout=layout, dtype=dtype)
    np.testing.assert_allclose(got, ref, atol=5e-5, rtol=1e-5)
    np.testing.assert_array_equal(
        ref.argmax(axis=-1), got.argmax(axis=-1)
    )


@pytest.mark.parametrize("layout", ["dense", "paged"])
def test_int8_flash_scale_exact_vs_gather(params, layout):
    """Int8 scale-exactness: fed the SAME quantized cache state, the
    flash read (scales folded into the score/probability vectors)
    matches the history-granular gather dequant to fold-reassociation
    tolerance with identical greedy argmaxes — pinned at the ops level
    (one attention call, no cross-layer feedback) AND over a full walk
    (where attention deltas feed the residual stream, so tolerance is
    the honest contract — int8 fidelity itself is the 99% gate in
    bench --quant).  Run-to-run determinism is pinned exactly."""
    # ops level: identical cache leaves in, fold order the ONLY delta
    rng = np.random.default_rng(9)
    nb = S // PS
    P = B * nb + 2
    pool = lambda *sh: jnp.asarray(  # noqa: E731
        rng.integers(-127, 128, size=sh, dtype=np.int8)
    )
    scales = lambda *sh: jnp.asarray(  # noqa: E731
        rng.uniform(0.01, 0.1, size=sh).astype(np.float32)
    )
    f32 = lambda *sh: jnp.asarray(  # noqa: E731
        rng.normal(size=sh).astype(np.float32)
    )
    q3, k_t, v_t = f32(B, HEADS, HD), f32(B, HEADS, HD), f32(B, HEADS, HD)
    pos = jnp.asarray([S - 2, S // 2], jnp.int32)
    if layout == "paged":
        _, tables = _paged_setup()
        args = (
            q3, pool(P, PS, HEADS, HD), pool(P, PS, HEADS, HD),
            scales(P, PS, HEADS), scales(P, PS, HEADS), k_t, v_t, pos,
            tables,
        )
        ref1 = fd.decode_attention_paged(*args, page_size=PS,
                                         kernel="gather")
        got1 = fd.decode_attention_paged(*args, page_size=PS,
                                         kernel="flash")
    else:
        args = (
            q3, pool(B, S, HEADS, HD), pool(B, S, HEADS, HD),
            scales(B, S, HEADS), scales(B, S, HEADS), k_t, v_t, pos,
        )
        ref1 = fd.decode_attention_dense(*args, kernel="gather")
        got1 = fd.decode_attention_dense(*args, kernel="flash")
    np.testing.assert_allclose(
        np.asarray(got1), np.asarray(ref1), atol=2e-6, rtol=1e-5
    )

    # walk level: greedy choices identical, logits within tolerance
    ref, _ = _decode_walk(params, "gather", layout=layout, dtype=jnp.int8)
    got, _ = _decode_walk(params, "flash", layout=layout, dtype=jnp.int8)
    np.testing.assert_allclose(got, ref, atol=5e-5, rtol=1e-5)
    np.testing.assert_array_equal(ref.argmax(axis=-1), got.argmax(axis=-1))
    # determinism: a fresh (shorter, so the memo can't answer) walk
    # reproduces the same prefix bit-for-bit
    again, _ = _decode_walk(
        params, "flash", layout=layout, dtype=jnp.int8, steps=12
    )
    np.testing.assert_array_equal(got[:12], again)


def test_chunk_attention_flash_bit_exact_f32(params):
    """Chunked prefill through the kernel dispatch: f32 flash == gather
    bitwise, chunk by chunk, including the non-chunk-aligned offsets a
    prefix hit produces."""
    prompt = np.arange(1, 25, dtype=np.int32)  # 24 tokens, 3 pages
    for offset in (0, 12):  # 12 = mid-chunk, the prefix-hit shape
        caches = {}
        for kernel in ("gather", "flash"):
            cache, tables = _paged_setup()
            lg, cache = forward_prefill_chunk(
                params, jnp.asarray(prompt[offset:][None]), cache,
                tables[0], jnp.int32(offset), num_heads=HEADS,
                page_size=PS, kernel=kernel,
            )
            caches[kernel] = (np.asarray(lg), cache)
        np.testing.assert_array_equal(
            caches["gather"][0], caches["flash"][0]
        )
        for key in caches["gather"][1]:
            np.testing.assert_array_equal(
                np.asarray(caches["gather"][1][key]),
                np.asarray(caches["flash"][1][key]),
            )


def test_int8_flash_prefix_hit_non_chunk_multiple(params):
    """Engine-level int8 + flash kernel: a prefix-cache hit whose shared
    length (12) is NOT a multiple of prefill_chunk (16) decodes bit-
    identically to a cold run — quantized prefill stays chunk-alignment-
    invariant through the kernel."""
    reqs = synthetic_requests(
        6, vocab_size=CFG["vocab_size"], max_prompt=12, min_prompt=4,
        shared_prefix_len=12, rng=np.random.default_rng(3),
    )
    kw = dict(num_heads=HEADS, batch_slots=2, max_seq=48, page_size=4,
              prefill_chunk=16, rng=jax.random.key(1),
              cache_dtype=jnp.int8, decode_kernel="flash")
    hit = PagedInferenceEngine(params, **kw)
    res_h, rep_h = ContinuousBatchingScheduler(
        hit, max_new_tokens=6
    ).run(list(reqs))
    miss = PagedInferenceEngine(params, prefix_cache=False, **kw)
    res_m, rep_m = ContinuousBatchingScheduler(
        miss, max_new_tokens=6
    ).run(list(reqs))
    assert rep_h.prefix_hit_rate > 0.0 and rep_m.prefix_hit_rate == 0.0
    assert rep_h.decode_kernel == "flash"
    assert {r.uid: r.tokens for r in res_h} == {
        r.uid: r.tokens for r in res_m
    }
    hit.allocator.check()


@pytest.mark.parametrize("layout", ["dense", "paged"])
def test_rollback_then_redecode_matches_never_drafted(params, layout):
    """Spec interplay on the flash kernel: draft K tokens through a
    garbage drafter (guaranteed total rejection), verify, roll the
    rejected tail back, then KEEP DECODING — the continued stream must
    be bit-identical to a run that never drafted (rollback restored the
    cache exactly, through the same kernel decode reads)."""
    from distributeddeeplearning_tpu.spec import SpeculativeDecoder
    from distributeddeeplearning_tpu.spec.drafter import Drafter

    class GarbageDrafter(Drafter):
        name = "garbage"

        def bind(self, engine):
            self._vocab = engine.vocab_size

        def propose(self, cache, tokens, pos):
            # propose an impossible constant stream; leaves the cache
            # untouched (the verify writes are what rollback must undo)
            return jnp.full_like(tokens, self._vocab - 1), cache

    def build():
        kw = dict(num_heads=HEADS, batch_slots=B, max_seq=S,
                  rng=jax.random.key(1), decode_kernel="flash")
        if layout == "paged":
            return PagedInferenceEngine(params, page_size=PS, **kw)
        from distributeddeeplearning_tpu.serve import InferenceEngine

        return InferenceEngine(
            params, prefill_attention="dense", **kw
        )

    prompt = [3, 1, 4, 1, 5, 9, 2, 6]

    # reference: plain decode walk, never drafted
    eng_ref = build()
    if layout == "paged":
        first_ref = eng_ref.prefill(0, prompt, max_new_tokens=10)
    else:
        first_ref = eng_ref.prefill(0, prompt)
    toks = np.zeros(B, np.int32)
    pos = np.zeros(B, np.int32)
    stream_ref = [first_ref]
    cur = first_ref
    for i in range(6):
        toks[0] = cur
        pos[0] = len(prompt) + i
        cur = int(eng_ref.decode(toks, pos)[0])
        stream_ref.append(cur)

    # candidate: one forced-rejection spec step + rollback, then decode
    eng = build()
    spec = SpeculativeDecoder(eng, drafter=GarbageDrafter(),
                              draft_tokens=3)
    if layout == "paged":
        first = eng.prefill(0, prompt, max_new_tokens=10)
    else:
        first = eng.prefill(0, prompt)
    assert first == first_ref
    toks = np.zeros(B, np.int32)
    toks[0] = first
    pos = np.zeros(B, np.int32)
    pos[0] = len(prompt)
    dlen = np.zeros(B, np.int32)
    dlen[0] = 3
    res = spec.step(toks, pos, dlen)
    assert int(res.accepted[0]) == 0  # garbage drafts: total rejection
    # commit only the bonus token, roll the rejected tail back
    spec.rollback(pos, np.ones(B, np.int32))
    committed = int(res.tokens[0, 0])
    assert committed == stream_ref[1]
    # redecode the rest plainly — bit-identical to never-drafted
    cur = committed
    stream = [first, committed]
    for i in range(1, 6):
        toks[0] = cur
        pos[0] = len(prompt) + i
        cur = int(eng.decode(toks, pos)[0])
        stream.append(cur)
    assert stream == stream_ref[:7]


def test_resolve_kernel_contract():
    assert fd.resolve_kernel("auto") == "flash"
    assert fd.resolve_kernel("flash") == "flash"
    assert fd.resolve_kernel("gather") == "gather"
    with pytest.raises(ValueError, match="unknown decode kernel"):
        fd.resolve_kernel("fused")
    # engines resolve at construction and report provenance
    eng = PagedInferenceEngine(
        init_params(jax.random.key(0), **CFG), num_heads=HEADS,
        batch_slots=1, max_seq=16, page_size=8,
    )
    assert eng.decode_kernel == "flash"


@pytest.mark.timeout(280)
def test_bench_quant_smoke_flash_kernel(tmp_path):
    """CPU smoke of the PR-12 bench: 5 configs (flash + gather exhibits),
    the f32 flash==gather token identity asserted in-run, artifact
    carries kernel provenance.  --steps-cap keeps it in the fast tier;
    the full-geometry run (which also gates the kv_int8 speed win) is
    the committed-artifact path."""
    import json

    report = tmp_path / "quant_smoke.json"
    out = subprocess.run(
        [
            sys.executable, "bench.py", "--quant", "--small",
            "--serve-requests", "4", "--batch-slots", "2",
            "--max-new-tokens", "6", "--steps-cap", "40",
            "--report", str(report),
        ],
        capture_output=True, text=True, timeout=260,
        cwd=str(Path(__file__).resolve().parents[1]),
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stdout + out.stderr
    line = json.loads(report.read_text())
    assert line["flash_f32_bit_identical_to_gather"] is True
    assert line["decode_kernel"]["kv_int8"] == "flash"
    assert line["decode_kernel"]["kv_int8_gather"] == "gather"
    assert set(line["decode_tokens_per_sec"]) == {
        "f32", "kv_int8", "kv_w_int8", "f32_gather", "kv_int8_gather"
    }
