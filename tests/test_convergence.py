"""Deterministic small-scale train-to-accuracy (VERDICT r02 item 9).

The reference's contract is "identical top-1" under the Goyal recipe
(BASELINE.md); a full ImageNet run can't gate CI, so this is the cheap
sentinel: a fixed-seed 3-class solid-color image tree through the REAL
raw-image pipeline (``augment="reference"``, the resize-only train path) and
the REAL imagenet workload (ResNet-18, SGD momentum 0.9 / wd 5e-5, warmup +
step-decay schedule).  A recipe regression — preprocessing change, label
offset slip, LR schedule break — shows up as this trivially-separable
problem failing to clear the accuracy band.
"""

from __future__ import annotations

import numpy as np
import pytest

WNIDS = ("n01440764", "n01443537", "n01484850")
COLORS = ((220, 30, 30), (30, 220, 30), (30, 30, 220))
N_TRAIN_PER_CLASS = 8
N_VAL_PER_CLASS = 8


@pytest.fixture(scope="module")
def image_tree(tmp_path_factory):
    from PIL import Image

    root = tmp_path_factory.mktemp("imgs")
    rng = np.random.default_rng(0)
    for split, n in (("train", N_TRAIN_PER_CLASS), ("validation", N_VAL_PER_CLASS)):
        for wnid, color in zip(WNIDS, COLORS):
            d = root / split / wnid
            d.mkdir(parents=True)
            for i in range(n):
                base = np.tile(
                    np.asarray(color, np.uint8), (96, 96, 1)
                ).astype(np.int16)
                noise = rng.integers(-20, 20, base.shape, np.int16)
                arr = np.clip(base + noise, 0, 255).astype(np.uint8)
                Image.fromarray(arr).save(d / f"img_{i}.JPEG", quality=95)
    return root


def test_three_class_reference_recipe_converges(image_tree):
    from distributeddeeplearning_tpu.workloads.imagenet import main

    state, fit = main(
        model="resnet18",
        data_format="images",
        training_data_path=str(image_tree / "train"),
        validation_data_path=str(image_tree / "validation"),
        epochs=6,
        batch_size=2,   # x8 virtual chips = global 16 (fits the 24-image val split)
        base_lr=0.004,  # scaled recipe: 0.0125-class schedule, small batch
        warmup_epochs=1,
        image_size=64,
        num_classes=4,  # 3 wnids + background class 0 (1-based labels)
        steps_per_epoch=3,
        train_images=24,
        seed=7,
        compute_dtype="float32",
        augment="reference",
        resume=False,
        distributed=False,
    )
    assert fit.final_eval_metrics  # val must actually yield batches
    top1 = fit.final_eval_metrics["top1"]
    # Solid colors are linearly separable; the reference recipe must nail
    # them. The band (not exact pin) absorbs BN/jpeg/platform jitter while
    # still catching label-offset or preprocessing regressions, which land
    # at ~1/3 or worse.
    assert top1 >= 0.9, fit.final_eval_metrics
    assert np.isfinite(fit.final_train_metrics["loss"])
