"""Class-index data contracts (SURVEY.md §2 #19).

The reference ships ``imagenet_nounid_to_class.json`` (consumed by
``data/images.py:12-24``) and the canonical ``scripts/imagenet_class_index.json``.
Here the first is derived from the data tree and the second is verified
against it; these tests pin both formats, the framework's 1-based training
labels (background=0), and the off-by-one detection.
"""

from __future__ import annotations

import json

import pytest

from distributeddeeplearning_tpu.data.class_index import (
    build_nounid_to_class,
    class_names,
    list_wnids,
    load_class_index,
    load_nounid_to_class,
    verify_class_index,
    write_nounid_to_class,
)

WNIDS = ["n01440764", "n01443537", "n01484850"]
CANONICAL = {
    "0": ["n01440764", "tench"],
    "1": ["n01443537", "goldfish"],
    "2": ["n01484850", "great_white_shark"],
}


@pytest.fixture
def image_dir(tmp_path):
    for wnid in WNIDS:
        (tmp_path / "train" / wnid).mkdir(parents=True)
    # non-directory clutter must be ignored
    (tmp_path / "train" / "LICENSE.txt").write_text("x")
    return tmp_path / "train"


def _canonical(tmp_path, entries):
    path = tmp_path / "imagenet_class_index.json"
    path.write_text(json.dumps(entries))
    return path


def test_derive_matches_training_labels(image_dir):
    assert list_wnids(image_dir) == WNIDS
    # Default: the 1-based labels the loaders train with (background=0,
    # data/images.py {w: i+1}; data/tfrecords.py "1-based, background=0").
    assert build_nounid_to_class(image_dir) == {
        "n01440764": 1,
        "n01443537": 2,
        "n01484850": 3,
    }
    # Reference file-format parity: 0-based.
    assert build_nounid_to_class(image_dir, label_offset=0) == {
        "n01440764": 0,
        "n01443537": 1,
        "n01484850": 2,
    }


def test_write_and_load_roundtrip_reference_format(image_dir, tmp_path):
    mapping = build_nounid_to_class(image_dir, label_offset=0)
    out = tmp_path / "imagenet_nounid_to_class.json"
    write_nounid_to_class(mapping, out)
    # Reference format: ONE json object mapping wnid -> int class, 0-based.
    raw = json.loads(out.read_text())
    assert raw == {"n01440764": 0, "n01443537": 1, "n01484850": 2}
    assert load_nounid_to_class(out) == mapping


def test_verify_agreement_and_names(image_dir, tmp_path):
    index = load_class_index(_canonical(tmp_path, CANONICAL))
    # Default offsets line up: canonical 0-based + 1 == training labels.
    assert verify_class_index(index, build_nounid_to_class(image_dir)) == []
    # And the 0-based pair agrees at offset 0.
    assert verify_class_index(
        index, build_nounid_to_class(image_dir, label_offset=0), label_offset=0
    ) == []
    assert class_names(index, 3) == ["tench", "goldfish", "great_white_shark"]


def test_verify_detects_background_offset_mismatch(image_dir, tmp_path):
    """A 0-based mapping checked against the training convention (offset 1)
    must fail — this is exactly the off-by-one the tool exists to catch."""
    index = load_class_index(_canonical(tmp_path, CANONICAL))
    zero_based = build_nounid_to_class(image_dir, label_offset=0)
    problems = verify_class_index(index, zero_based)  # default offset 1
    assert len(problems) == 3 and "offset 1" in problems[0]


def test_verify_detects_missing_and_misordered_wnids(image_dir, tmp_path):
    canonical = _canonical(
        tmp_path,
        {
            "0": ["n01443537", "goldfish"],  # swapped order
            "1": ["n01440764", "tench"],
            "2": ["n99999999", "ghost"],  # not in the tree
        },
    )
    problems = verify_class_index(
        load_class_index(canonical), build_nounid_to_class(image_dir)
    )
    assert any("missing from data tree" in p for p in problems)
    assert any("n01443537" in p for p in problems)


def test_malformed_class_index_rejected(tmp_path):
    bad = _canonical(tmp_path, {"0": ["only-one-field"]})
    with pytest.raises(ValueError, match="not \\[wnid, text\\]"):
        load_class_index(bad)


def test_cli_class_index_verb(image_dir, tmp_path, monkeypatch, capsys):
    from distributeddeeplearning_tpu.cli.main import main

    monkeypatch.chdir(tmp_path)
    canonical = _canonical(tmp_path, CANONICAL)
    rc = main(
        [
            "storage", "class-index",
            "--image-dir", str(image_dir),
            "--output", str(tmp_path / "mapping.json"),
            "--verify", str(canonical),
        ]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "3-class mapping" in out and "OK" in out
    # Default CLI output: the 1-based training labels.
    assert json.loads((tmp_path / "mapping.json").read_text()) == {
        "n01440764": 1, "n01443537": 2, "n01484850": 3,
    }
    # --label-offset 0 writes the reference's 0-based format and verifies.
    rc = main(
        [
            "storage", "class-index",
            "--image-dir", str(image_dir),
            "--output", str(tmp_path / "mapping0.json"),
            "--verify", str(canonical),
            "--label-offset", "0",
        ]
    )
    assert rc == 0
    assert json.loads((tmp_path / "mapping0.json").read_text()) == {
        "n01440764": 0, "n01443537": 1, "n01484850": 2,
    }


class TestShippedFiles:
    """The in-repo canonical contract files (VERDICT r02 item 7): --verify
    must work out of the box, matching the reference's shipped
    scripts/imagenet_class_index.json + imagenet_nounid_to_class.json."""

    def test_shipped_class_index_is_canonical(self):
        from distributeddeeplearning_tpu.data.class_index import (
            load_class_index,
            shipped_class_index_path,
        )

        idx = load_class_index(shipped_class_index_path())
        assert len(idx) == 1000
        assert idx[0] == ("n01440764", "tench")
        assert idx[999][0] == "n15075141"
        wnids = [idx[i][0] for i in range(1000)]
        assert wnids == sorted(wnids)  # canonical sorted-wnid order

    def test_shipped_nounid_map_matches_index(self):
        from distributeddeeplearning_tpu.data.class_index import (
            load_class_index,
            load_nounid_to_class,
            shipped_class_index_path,
            shipped_nounid_to_class_path,
            verify_class_index,
        )

        idx = load_class_index(shipped_class_index_path())
        mapping = load_nounid_to_class(shipped_nounid_to_class_path())
        # the shipped map is the reference's 0-based format
        assert verify_class_index(idx, mapping, label_offset=0) == []

    def test_cli_verify_uses_shipped_default(self, tmp_path, capsys):
        # fake 3-class tree keyed to the first three canonical wnids
        for w in ("n01440764", "n01443537", "n01484850"):
            (tmp_path / w).mkdir()
        from distributeddeeplearning_tpu.cli.main import main

        rc = main([
            "storage", "class-index",
            "--image-dir", str(tmp_path),
            "--output", str(tmp_path / "out.json"),
            "--label-offset", "0",
            "--verify",
        ])
        captured = capsys.readouterr()
        # 3-class tree vs 1000-class canon -> the shipped file must have been
        # resolved (no path given) and the size mismatch reported: that IS
        # the out-of-the-box --verify behavior working.
        assert rc == 1
        assert "size mismatch" in captured.err
        assert (tmp_path / "out.json").exists()
