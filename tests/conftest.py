"""Test harness: fake an 8-device TPU pod on CPU.

SURVEY.md §4: the reference de-risks multi-node behavior through a local
single-GPU path with the DISTRIBUTED switch off.  The JAX-native analogue is a
virtual multi-device CPU platform, which lets every data-parallel semantic
(mesh construction, psum gradient sync, sharded batches, LR scaling, resume)
run in CI with no TPU attached.

The interactive environment registers a real-TPU PJRT plugin at interpreter
startup and pins JAX_PLATFORMS, so env vars alone are not enough: we must
flip the platform via jax.config before the backend is first queried.
"""

import os

# Must precede backend initialization (first jax.devices()/jit call).
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def tmp_env(tmp_path):
    """A throwaway .env path."""
    return tmp_path / ".env"
