"""Test harness: fake an 8-device TPU pod on CPU.

SURVEY.md §4: the reference de-risks multi-node behavior through a local
single-GPU path with the DISTRIBUTED switch off.  The JAX-native analogue is a
virtual multi-device CPU platform, which lets every data-parallel semantic
(mesh construction, psum gradient sync, sharded batches, LR scaling, resume)
run in CI with no TPU attached.

The interactive environment registers a real-TPU PJRT plugin at interpreter
startup and pins JAX_PLATFORMS, so env vars alone are not enough: we must
flip the platform via jax.config before the backend is first queried.
"""

import os

# Must precede backend initialization (first jax.devices()/jit call).
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_addoption(parser):
    # pyproject.toml sets `timeout` / `timeout_method` for pytest-timeout
    # (per-test deadlines so a hang in watchdog/prefetch/scheduler threading
    # fails loudly).  When the plugin is not installed, declare the same ini
    # keys as inert placeholders so the options don't raise unknown-key
    # warnings — the suite then simply runs without per-test deadlines.
    try:
        import pytest_timeout  # noqa: F401
    except ImportError:
        parser.addini("timeout", "per-test deadline (pytest-timeout absent: inert)")
        parser.addini("timeout_method", "pytest-timeout method (inert)")

# --- two-tier suite -------------------------------------------------------
# tests/slow_tests.txt lists test IDs (relative to tests/, parametrized IDs
# cover every param) measured over ~5 s on a single core; conftest marks
# them ``slow`` at collection so ``make test-fast`` (-m "not slow") stays
# under its CI budget.  Regenerate after perf-relevant changes with:
#   python -m pytest tests/ -q --durations=80   (then paste calls >5 s)
_SLOW_MANIFEST = os.path.join(os.path.dirname(__file__), "slow_tests.txt")


def _slow_ids():
    try:
        with open(_SLOW_MANIFEST) as f:
            return {ln.strip() for ln in f if ln.strip() and not ln.startswith("#")}
    except OSError:
        return None


def pytest_collection_modifyitems(config, items):
    slow = _slow_ids()
    if slow is None:
        # Without the manifest the "fast" tier silently becomes the full
        # ~45-minute suite; make the degradation loud.
        import warnings

        warnings.warn(
            f"slow-test manifest {_SLOW_MANIFEST} missing — no slow marks "
            "applied, -m 'not slow' will run (almost) everything",
            stacklevel=1,
        )
        return
    if not slow:
        return
    for item in items:
        # item.nodeid is "tests/test_x.py::test_y[param]"; the manifest
        # stores it without the tests/ prefix and without param brackets so
        # one line covers every parametrization.
        nodeid = item.nodeid
        if nodeid.startswith("tests/"):
            nodeid = nodeid[len("tests/"):]
        base = nodeid.split("[", 1)[0]
        if nodeid in slow or base in slow:
            item.add_marker(pytest.mark.slow)


@pytest.fixture
def tmp_env(tmp_path):
    """A throwaway .env path."""
    return tmp_path / ".env"
