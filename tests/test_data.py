"""Data plane: converter ↔ reader roundtrip, raw-image pipeline, preparation.

Mirrors the reference's guardrail strategy (SURVEY.md §4.4) with real
automated tests over tiny synthetic JPEG trees.
"""

import os
import tarfile

import numpy as np
import pytest

from distributeddeeplearning_tpu.data import convert_tfrecords, images, tfrecords
from distributeddeeplearning_tpu.data.preprocessing import (
    CHANNEL_MEANS,
    central_crop_np,
    normalize_np,
)

WNIDS = ["n01440764", "n01443537", "n02102040"]


def _make_image_tree(root, per_class=4, size=(48, 56)):
    from PIL import Image

    rng = np.random.default_rng(0)
    for wnid in WNIDS:
        d = root / wnid
        d.mkdir(parents=True)
        for i in range(per_class):
            arr = rng.integers(0, 255, (*size, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"{wnid}_{i}.JPEG", quality=95)


@pytest.fixture(scope="module")
def image_tree(tmp_path_factory):
    root = tmp_path_factory.mktemp("imagenet") / "train"
    _make_image_tree(root)
    return root


@pytest.fixture(scope="module")
def tfrecord_dir(tmp_path_factory, image_tree):
    out = tmp_path_factory.mktemp("tfrecords")
    n = convert_tfrecords.convert_dataset(str(image_tree), str(out), "train", 4)
    assert n == 12
    n = convert_tfrecords.convert_dataset(str(image_tree), str(out), "validation", 4)
    assert n == 12
    return out


def test_find_image_files_labels_and_shuffle(image_tree):
    files, labels, synsets, wnid_map = convert_tfrecords.find_image_files(
        str(image_tree)
    )
    assert len(files) == 12
    # 1-based labels by sorted wnid (background=0 convention)
    assert wnid_map == {w: i + 1 for i, w in enumerate(sorted(WNIDS))}
    assert set(labels) == {1, 2, 3}
    # deterministic seed-42 shuffle
    files2, *_ = convert_tfrecords.find_image_files(str(image_tree))
    assert files == files2
    assert files != sorted(files)


def test_clean_image_bytes_png_and_cmyk(tmp_path):
    from PIL import Image

    rng = np.random.default_rng(1)
    arr = rng.integers(0, 255, (20, 20, 3), dtype=np.uint8)
    png = tmp_path / "x.png"
    Image.fromarray(arr).save(png)
    jpeg_bytes, h, w = convert_tfrecords.clean_image_bytes(png.read_bytes())
    assert (h, w) == (20, 20)
    img = Image.open(__import__("io").BytesIO(jpeg_bytes))
    assert img.format == "JPEG" and img.mode == "RGB"

    cmyk = tmp_path / "y.jpg"
    Image.fromarray(arr).convert("CMYK").save(cmyk, format="JPEG")
    jpeg_bytes, _, _ = convert_tfrecords.clean_image_bytes(cmyk.read_bytes())
    img = Image.open(__import__("io").BytesIO(jpeg_bytes))
    assert img.mode == "RGB"


def test_shard_files_exist_and_missing_raises(tfrecord_dir, tmp_path):
    names = tfrecords.shard_filenames(str(tfrecord_dir), True, num_shards=4)
    assert len(names) == 4
    with pytest.raises(FileNotFoundError, match="expected TFRecord shards"):
        tfrecords.shard_filenames(str(tmp_path), True, num_shards=4)


def test_tfrecord_roundtrip_training(tfrecord_dir):
    it = tfrecords.input_fn(
        str(tfrecord_dir), True, batch_size=4, num_shards=4,
        image_size=32, shuffle_buffer=16, seed=0,
    )
    batch = next(it)
    assert batch["image"].shape == (4, 32, 32, 3)
    assert batch["image"].dtype == np.float32
    assert batch["label"].dtype == np.int32
    assert set(batch["label"]) <= {1, 2, 3}
    # mean subtraction applied: values centred, not 0..255
    assert batch["image"].min() < -20


def test_tfrecord_eval_deterministic(tfrecord_dir):
    def grab():
        it = tfrecords.input_fn(
            str(tfrecord_dir), False, batch_size=4, num_shards=4,
            image_size=32, repeat=False,
        )
        return np.concatenate([b["label"] for b in it])

    a, b = grab(), grab()
    np.testing.assert_array_equal(a, b)
    assert len(a) == 12


def test_host_sharding_partitions_files(tfrecord_dir):
    labels = []
    for rank in range(2):
        it = tfrecords.input_fn(
            str(tfrecord_dir), False, batch_size=2, num_shards=4,
            image_size=32, repeat=False, shard_count=2, shard_index=rank,
        )
        labels.append(np.concatenate([b["label"] for b in it]))
    # disjoint halves covering everything
    assert len(labels[0]) + len(labels[1]) == 12
    combined = sorted(np.concatenate(labels).tolist())
    assert combined == sorted([1, 1, 1, 1, 2, 2, 2, 2, 3, 3, 3, 3])


def test_raw_images_pipeline(image_tree):
    it = images.input_fn(
        str(image_tree), True, batch_size=4, image_size=32, seed=0,
    )
    batch = next(it)
    assert batch["image"].shape == (4, 32, 32, 3)
    assert set(batch["label"]) <= {1, 2, 3}


def test_raw_images_eval_path_works(image_tree):
    """The reference's eval path is broken (images.py:178-197 mis-indent);
    ours must not be."""
    it = images.input_fn(
        str(image_tree), False, batch_size=3, image_size=32, repeat=False,
    )
    batches = list(it)
    assert len(batches) == 4


def test_labels_agree_between_raw_and_tfrecords(image_tree, tfrecord_dir):
    _, _, wnid_map = images.list_images(str(image_tree))
    _, _, _, conv_map = convert_tfrecords.find_image_files(str(image_tree))
    assert wnid_map == conv_map


def test_normalize_np():
    img = np.full((4, 4, 3), 128, np.uint8)
    out = normalize_np(img)
    np.testing.assert_allclose(
        out[0, 0], 128 - np.asarray(CHANNEL_MEANS), rtol=1e-5
    )


def test_central_crop_np_shape():
    img = np.zeros((300, 400, 3), np.uint8)
    out = central_crop_np(img, 224)
    assert out.shape == (224, 224, 3)


class TestPrepareImagenet:
    def _make_tars(self, tmp_path):
        from PIL import Image

        rng = np.random.default_rng(2)
        src = tmp_path / "src"
        inner_tars = []
        for wnid in WNIDS[:2]:
            cdir = src / wnid
            cdir.mkdir(parents=True)
            for i in range(2):
                arr = rng.integers(0, 255, (16, 16, 3), dtype=np.uint8)
                Image.fromarray(arr).save(cdir / f"{wnid}_{i}.JPEG")
            t = tmp_path / f"{wnid}.tar"
            with tarfile.open(t, "w") as tar:
                for f in sorted(cdir.iterdir()):
                    tar.add(f, arcname=f.name)
            inner_tars.append(t)
        train_tar = tmp_path / "train.tar"
        with tarfile.open(train_tar, "w") as tar:
            for t in inner_tars:
                tar.add(t, arcname=t.name)

        val_imgs = []
        vdir = tmp_path / "val_flat"
        vdir.mkdir()
        for i in range(4):
            arr = rng.integers(0, 255, (16, 16, 3), dtype=np.uint8)
            name = f"ILSVRC2012_val_{i:08d}.JPEG"
            Image.fromarray(arr).save(vdir / name)
            val_imgs.append(name)
        val_tar = tmp_path / "val.tar"
        with tarfile.open(val_tar, "w") as tar:
            for name in val_imgs:
                tar.add(vdir / name, arcname=name)
        val_map = tmp_path / "val_map.csv"
        rows = [f"{name},{WNIDS[i % 2]}" for i, name in enumerate(val_imgs)]
        val_map.write_text("filename,wnid\n" + "\n".join(rows) + "\n")
        return train_tar, val_tar, val_map

    def test_full_preparation(self, tmp_path):
        from distributeddeeplearning_tpu.data import prepare_imagenet as prep

        train_tar, val_tar, val_map = self._make_tars(tmp_path)
        target = tmp_path / "out"
        prep.prepare_imagenet(
            str(train_tar), str(val_tar), str(target), str(val_map),
            check_sha1=False,
        )
        assert sorted(p.name for p in (target / "train").iterdir()) == WNIDS[:2]
        assert len(list((target / "train" / WNIDS[0]).glob("*.JPEG"))) == 2
        val_classes = sorted(p.name for p in (target / "validation").iterdir())
        assert val_classes == WNIDS[:2]
        total_val = sum(
            1 for d in (target / "validation").iterdir() for _ in d.iterdir()
        )
        assert total_val == 4

    def test_checksum_mismatch_raises(self, tmp_path):
        from distributeddeeplearning_tpu.data import prepare_imagenet as prep

        f = tmp_path / "bogus.tar"
        f.write_bytes(b"not a tar")
        with pytest.raises(ValueError, match="checksum mismatch"):
            prep.verify_checksum(str(f), "0" * 40)

    def test_val_map_parsing(self, tmp_path):
        from distributeddeeplearning_tpu.data import prepare_imagenet as prep

        m = tmp_path / "map.csv"
        m.write_text("filename,wnid\na.JPEG,n01440764\nb.JPEG,n01443537\n")
        assert prep.load_val_map(str(m)) == {
            "a.JPEG": "n01440764",
            "b.JPEG": "n01443537",
        }
        empty = tmp_path / "empty.csv"
        empty.write_text("filename,wnid\n")
        with pytest.raises(ValueError):
            prep.load_val_map(str(empty))
