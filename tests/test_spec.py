"""Speculative decoding: bit-exact verification, rollback, drafters.

The load-bearing guarantee extends the repo's oldest serving pin
(decode == full forward, dense == paged): a speculative greedy run must
produce tokens BIT-IDENTICAL to the non-speculative f32 run, whatever
the drafter proposes — every emitted token is the verifier's own f32
argmax over the committed history, so the drafter can only change HOW
FAST tokens appear, never WHICH tokens.  On top of that:

- ``forward_verify`` / ``forward_verify_paged`` logits are pinned
  bitwise against a sequential ``forward_decode`` walk, position for
  position, including the cache writes;
- rejected draft tails roll back to EXACTLY the never-drafted cache
  state (a forced-total-rejection run's cache equals a non-speculative
  run's, both layouts) — the batched rollback is pinned equivalent to
  the host ``scrub_slot(slot, from_pos)`` path;
- ``scrub_slot(from_pos > 0)`` partial rollback is pinned directly on
  both layouts: positions below ``from_pos`` preserved bit-exact,
  positions at/above zeroed, prefix-SHARED pages never written;
- the greedy-only / f32-cache-only guards, the spec ServeReport fields,
  the SPEC artifact schema, and the ``bench.py --spec`` CPU smoke.
"""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.models.pipelined_transformer import (
    forward_decode,
    forward_decode_paged,
    forward_prefill,
    forward_verify,
    forward_verify_paged,
    init_params,
)
from distributeddeeplearning_tpu.serve import (
    ContinuousBatchingScheduler,
    InferenceEngine,
    PagedInferenceEngine,
    Request,
    synthetic_requests,
)
from distributeddeeplearning_tpu.spec import (
    Drafter,
    SpeculativeDecoder,
    build_drafter,
)
from distributeddeeplearning_tpu.utils import faults as faults_mod

CFG = dict(num_layers=4, d_model=32, num_heads=4, d_ff=64, vocab_size=61,
           max_len=64)
HEADS = CFG["num_heads"]
MAX_SEQ = CFG["max_len"]


@pytest.fixture(autouse=True)
def _no_inherited_faults():
    faults_mod.install_plan("")
    yield
    faults_mod.install_plan("")


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.key(0), **CFG)


def _dense(params, slots=3, **kw):
    kw.setdefault("rng", jax.random.key(1))
    return InferenceEngine(
        params, num_heads=HEADS, batch_slots=slots, max_seq=MAX_SEQ, **kw
    )


def _paged(params, slots=3, **kw):
    kw.setdefault("rng", jax.random.key(1))
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_chunk", 8)
    return PagedInferenceEngine(
        params, num_heads=HEADS, batch_slots=slots, max_seq=MAX_SEQ, **kw
    )


def _requests(n=7, vocab=CFG["vocab_size"], max_prompt=12, seed=0):
    return [
        Request(uid=r.uid, prompt=list(r.prompt))
        for r in synthetic_requests(
            n, vocab_size=vocab, max_prompt=max_prompt, min_prompt=3,
            rng=np.random.default_rng(seed),
        )
    ]


def _run(engine, spec_decoder=None, max_new_tokens=9, eos_id=None,
         reqs=None):
    results, report = ContinuousBatchingScheduler(
        engine, max_new_tokens=max_new_tokens, eos_id=eos_id,
        spec_decoder=spec_decoder,
    ).run(reqs if reqs is not None else _requests())
    return {r.uid: r.tokens for r in results}, report


# --------------------------------------------------------------------------
# model level: the batched verify IS a sequential decode walk, bitwise
# --------------------------------------------------------------------------

def _seed_dense_slot(params, engine, slot, prompt):
    logits, k, v = forward_prefill(
        params, jnp.asarray([prompt], jnp.int32), num_heads=HEADS
    )
    from distributeddeeplearning_tpu.serve import insert_sequence

    engine._cache = insert_sequence(engine._cache, k, v, slot)


@pytest.mark.parametrize("layout", ["dense", "paged"])
def test_verify_matches_sequential_decode_bitwise(params, layout):
    """Per-position logits of ONE batched verify == K1 sequential decode
    steps, bitwise, and the cache writes match too — the foundation the
    whole acceptance rule stands on."""
    B, K1, plen = 3, 4, 6
    rng = np.random.default_rng(3)
    prompts = rng.integers(1, CFG["vocab_size"], (B, plen)).tolist()
    pend = np.asarray(rng.integers(1, CFG["vocab_size"], B), np.int32)

    def build():
        eng = (_dense if layout == "dense" else _paged)(params, slots=B)
        for i, p in enumerate(prompts):
            if layout == "dense":
                _seed_dense_slot(params, eng, i, p)
            else:
                eng.prefill(i, p, max_new_tokens=K1 + 2)
        return eng

    # sequential greedy walk
    eng_a = build()
    toks, pos = pend.copy(), np.full(B, plen, np.int32)
    seq_logits = []
    for _ in range(K1):
        if layout == "dense":
            lg, eng_a._cache = forward_decode(
                params, jnp.asarray(toks), eng_a._cache,
                jnp.asarray(pos), num_heads=HEADS,
            )
        else:
            lg, eng_a._cache = forward_decode_paged(
                params, jnp.asarray(toks), eng_a._cache,
                jnp.asarray(pos), jnp.asarray(eng_a.block_tables),
                num_heads=HEADS, page_size=eng_a.page_size,
            )
        seq_logits.append(np.asarray(lg))
        toks = np.asarray(jnp.argmax(lg, -1)).astype(np.int32)
        pos += 1
    seq_logits = np.stack(seq_logits, axis=1)  # [B, K1, V]

    # one batched verify fed the same greedy chain as drafts
    eng_b = build()
    mat = np.zeros((B, K1), np.int32)
    mat[:, 0] = pend
    for j in range(1, K1):
        mat[:, j] = np.argmax(seq_logits[:, j - 1], -1)
    dlen = np.full(B, K1 - 1, np.int32)
    if layout == "dense":
        vlog, vcache = forward_verify(
            params, jnp.asarray(mat), eng_b._cache,
            jnp.asarray(np.full(B, plen, np.int32)), jnp.asarray(dlen),
            num_heads=HEADS,
        )
    else:
        vlog, vcache = forward_verify_paged(
            params, jnp.asarray(mat), eng_b._cache,
            jnp.asarray(np.full(B, plen, np.int32)), jnp.asarray(dlen),
            jnp.asarray(eng_b.block_tables),
            num_heads=HEADS, page_size=eng_b.page_size,
        )
    np.testing.assert_array_equal(np.asarray(vlog), seq_logits)
    # cache parity: verify wrote exactly what the sequential walk wrote
    for key in ("k", "v"):
        np.testing.assert_array_equal(
            np.asarray(vcache[key]), np.asarray(eng_a._cache[key])
        )


def test_verify_rejects_int8_cache(params):
    cache = {"k": jnp.zeros((1, 1, 4, 2, 2), jnp.int8),
             "v": jnp.zeros((1, 1, 4, 2, 2), jnp.int8),
             "k_scale": jnp.zeros((1, 1, 4, 2)),
             "v_scale": jnp.zeros((1, 1, 4, 2))}
    with pytest.raises(ValueError, match="f32 cache"):
        forward_verify(
            params, jnp.zeros((1, 2), jnp.int32), cache,
            jnp.zeros(1, jnp.int32), jnp.zeros(1, jnp.int32),
            num_heads=HEADS,
        )


# --------------------------------------------------------------------------
# scheduler level: spec greedy == non-spec greedy, whatever the drafter
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def paged_baseline(params):
    """Default-workload non-speculative paged run, shared by every test
    that compares a spec run against it (each baseline is a full engine
    build + compile — recomputing it per param combo is pure wall)."""
    return _run(_paged(params))


@pytest.mark.parametrize("drafter,kw", [
    ("truncated", dict(draft_layers=1)),   # shallow: real rejections
    ("truncated", dict(draft_layers=4)),   # full depth: acceptance 1.0
    ("int8", dict()),
])
def test_spec_greedy_bit_identical_paged(params, paged_baseline, drafter,
                                         kw):
    base_tokens, base_rep = paged_baseline
    eng = _paged(params)
    sd = SpeculativeDecoder(eng, drafter=drafter, draft_tokens=3, **kw)
    spec_tokens, rep = _run(eng, spec_decoder=sd)
    assert spec_tokens == base_tokens
    assert rep.speculative and rep.drafter == drafter
    assert rep.draft_tokens == 3
    assert 0.0 <= rep.acceptance_rate <= 1.0
    assert rep.tokens_per_verify >= 1.0
    assert rep.decode_steps <= base_rep.decode_steps
    if kw.get("draft_layers") == CFG["num_layers"]:
        # drafter == verifier: every draft is the verifier's own argmax,
        # and the step count collapses by ~(K+1)x — the amortization the
        # subsystem exists for
        assert rep.acceptance_rate == 1.0
        assert rep.decode_steps <= base_rep.decode_steps / 2


def test_spec_greedy_bit_identical_dense(params):
    """One dense scheduler-level pin (the shallow drafter: real
    rejections every step); the dense verify math itself is already
    pinned bitwise at the model level above."""
    base_tokens, _ = _run(_dense(params))
    eng = _dense(params)
    sd = SpeculativeDecoder(eng, drafter="truncated", draft_tokens=3,
                            draft_layers=1)
    spec_tokens, rep = _run(eng, spec_decoder=sd)
    assert spec_tokens == base_tokens
    assert 0.0 <= rep.acceptance_rate <= 1.0


class _CacheScribblingGarbageDrafter(Drafter):
    """Adversarial drafter: proposes an (almost certainly) wrong token
    every time AND scribbles real drafter K/V at the draft positions
    (like a production drafter would) — forcing acceptance 0 so every
    step exercises the bonus-token path, with rollback required to
    erase every trace of the writes."""

    name = "garbage-scribble"

    def __init__(self, token: int, layers: int):
        self.token = token
        self.layers = layers
        self._jit = None

    def bind(self, engine):
        from distributeddeeplearning_tpu.spec.drafter import (
            TruncatedDrafter,
        )

        self._inner = TruncatedDrafter(self.layers)
        self._inner.bind(engine)

    def propose(self, cache, tokens, pos):
        _, cache = self._inner.propose(cache, tokens, pos)
        return jnp.full_like(tokens, self.token), cache


@pytest.mark.parametrize("layout", ["dense", "paged"])
def test_forced_rejection_rolls_back_to_never_drafted_state(
    params, layout
):
    """Total rejection is the rollback worst case: every step drafts K
    tokens, all rejected, one bonus token emitted.  Output must STILL be
    bit-identical (the bonus IS the greedy token) and the final cache
    must equal the never-drafted run's cache bit-for-bit — including
    the zeros where rejected drafts briefly lived.  The scribbling
    drafter is the stronger adversary (it supersets the write-nothing
    one): garbage proposals AND garbage K/V written at every draft
    position, all of which rollback must erase."""
    build = _dense if layout == "dense" else _paged
    reqs = _requests(n=2)

    base_eng = build(params, slots=2)
    base_tokens, _ = _run(base_eng, reqs=[
        Request(uid=r.uid, prompt=list(r.prompt)) for r in reqs
    ])

    eng = build(params, slots=2)
    drafter = _CacheScribblingGarbageDrafter(0, 2)
    sd = SpeculativeDecoder(eng, drafter=drafter, draft_tokens=3)
    spec_tokens, rep = _run(eng, spec_decoder=sd, reqs=[
        Request(uid=r.uid, prompt=list(r.prompt)) for r in reqs
    ])
    assert spec_tokens == base_tokens
    assert rep.acceptance_rate == 0.0
    assert rep.tokens_per_verify == 1.0  # bonus-only progress
    # the cache after rollback equals a never-drafted run's, bitwise —
    # every real page/slot, including pages already released back to the
    # pool.  The paged scratch page (id 0) is excluded: it is the
    # designed dustbin for inactive-lane writes and legitimately
    # accumulates different garbage under different step programs.
    lo = 1 if layout == "paged" else 0
    for key in base_eng._cache:
        np.testing.assert_array_equal(
            np.asarray(eng._cache[key])[lo:],
            np.asarray(base_eng._cache[key])[lo:],
            err_msg=f"{layout}/{key}: rollback left rejected-draft residue",
        )


def test_rollback_equals_scrub_slot(params):
    """The batched rollback is the jitted form of the host
    ``scrub_slot(slot, from_pos)`` path — pin the equivalence on a live
    cache so the two can never drift.  Prompts are bucket-aligned (8 =
    page_size = prefill_chunk) so no prefill-padding garbage sits beyond
    the rollback window: rollback zeroes exactly the spec write horizon
    ``[from_pos, pos+K]`` while scrub_slot zeroes to the end of the
    slot's pages — equivalent wherever nothing else was ever written,
    which is the invariant spec rollback runs under."""
    prompts = {0: list(range(1, 9)), 1: list(range(11, 19))}
    eng_a = _paged(params, slots=2)
    eng_b = _paged(params, slots=2)
    for eng in (eng_a, eng_b):
        eng.prefill(0, prompts[0], max_new_tokens=8)
        eng.prefill(1, prompts[1], max_new_tokens=8)
        # a few decode steps so there is decode-written state to cut
        toks = np.asarray([1, 2], np.int32)
        pos = np.asarray([8, 8], np.int32)
        for _ in range(4):
            toks = eng.decode(toks, pos)
            pos = pos + 1
    sd = SpeculativeDecoder(eng_a, drafter="truncated", draft_layers=1,
                            draft_tokens=3)
    # cut slot 0 back to position 10, slot 1 to position 9: rollback
    # form is keep[i] = from_pos[i] - pos[i]
    sd.rollback(np.asarray([8, 8], np.int32), np.asarray([2, 1], np.int32))
    eng_b.scrub_slot(0, 10)
    eng_b.scrub_slot(1, 9)
    for key in eng_a._cache:
        np.testing.assert_array_equal(
            # scratch page excluded: rollback parks its no-op lanes
            # there, scrub_slot gathers-and-rewrites it unchanged
            np.asarray(eng_a._cache[key])[1:],
            np.asarray(eng_b._cache[key])[1:],
        )


# --------------------------------------------------------------------------
# scrub_slot(from_pos > 0): partial rollback, both layouts (satellite)
# --------------------------------------------------------------------------

@pytest.mark.parametrize("layout", ["dense", "paged"])
def test_scrub_slot_partial_preserves_prefix_bitwise(params, layout):
    build = _dense if layout == "dense" else _paged
    eng = build(params, slots=2)
    prompt = [7, 11, 13, 17, 19, 23]
    if layout == "dense":
        _seed_dense_slot(params, eng, 0, prompt)
    else:
        eng.prefill(0, prompt, max_new_tokens=12)
    toks = np.asarray([3, 0], np.int32)
    pos = np.asarray([len(prompt), 0], np.int32)
    for _ in range(5):
        toks = eng.decode(toks, pos)
        pos = pos + 1
    before = {k: np.asarray(v).copy() for k, v in eng._cache.items()}
    from_pos = len(prompt) + 3  # = 9: NOT page-aligned (page_size=8) —
    # the boundary page holds both preserved and scrubbed positions
    eng.scrub_slot(0, from_pos)
    after = {k: np.asarray(v) for k, v in eng._cache.items()}

    def slot_view(tree, key):
        if layout == "dense":
            return tree[key][0]  # [L, S, ...]
        pages = eng._slot_pages[0]
        return np.concatenate(
            [tree[key][p] for p in pages], axis=1
        )  # [L, n*ps, ...]

    for key in before:
        b, a = slot_view(before, key), slot_view(after, key)
        np.testing.assert_array_equal(
            a[:, :from_pos], b[:, :from_pos],
            err_msg=f"{key}: positions below from_pos were not preserved",
        )
        assert not np.any(a[:, from_pos:]), (
            f"{key}: positions at/above from_pos were not scrubbed"
        )
    # other slots untouched
    if layout == "dense":
        for key in before:
            np.testing.assert_array_equal(
                after[key][1], before[key][1]
            )


def test_scrub_slot_never_writes_prefix_shared_pages(params):
    """Two slots share prefix pages; scrubbing one slot's decode region
    must leave the shared pages bit-identical, and a scrub that WOULD
    reach into the shared region must refuse loudly."""
    eng = _paged(params, slots=2)
    shared_prompt = list(range(1, 17))  # two full pages at page_size=8
    eng.prefill(0, shared_prompt + [21, 22], max_new_tokens=8)
    eng.prefill(1, shared_prompt + [31, 32], max_new_tokens=8)
    shared_pages = eng._slot_pages[0][:2]
    assert shared_pages == eng._slot_pages[1][:2], "prefix hit expected"
    assert all(eng.allocator.is_shared(p) for p in shared_pages)
    toks = np.asarray([1, 2], np.int32)
    pos = np.asarray([18, 18], np.int32)
    for _ in range(3):
        toks = eng.decode(toks, pos)
        pos = pos + 1
    before = {
        key: np.asarray(leaf)[shared_pages].copy()
        for key, leaf in eng._cache.items()
    }
    eng.scrub_slot(0, 18)  # the delivery's prompt length
    for key, leaf in eng._cache.items():
        np.testing.assert_array_equal(
            np.asarray(leaf)[shared_pages], before[key],
            err_msg=f"{key}: scrub touched a prefix-shared page",
        )
    with pytest.raises(ValueError, match="prefix-shared"):
        eng.scrub_slot(0, 3)  # inside the shared prefix: must refuse


# --------------------------------------------------------------------------
# guards, edge cases, report fields
# --------------------------------------------------------------------------

def test_spec_requires_greedy(params):
    eng = _paged(params, temperature=0.7)
    with pytest.raises(ValueError, match="greedy-only"):
        SpeculativeDecoder(eng, drafter="truncated", draft_layers=1)


def test_spec_requires_f32_cache(params):
    eng = _paged(params, cache_dtype=jnp.int8)
    with pytest.raises(ValueError, match="f32 KV cache"):
        SpeculativeDecoder(eng, drafter="truncated", draft_layers=1)


def test_spec_rejects_foreign_engine(params):
    eng_a = _paged(params)
    eng_b = _paged(params)
    sd = SpeculativeDecoder(eng_a, drafter="truncated", draft_layers=1)
    with pytest.raises(ValueError, match="different engine"):
        ContinuousBatchingScheduler(eng_b, spec_decoder=sd)


def test_build_drafter_validation():
    with pytest.raises(ValueError, match="draft_layers"):
        build_drafter("truncated")
    with pytest.raises(ValueError, match="unknown drafter"):
        build_drafter("telepathy")
    with pytest.raises(ValueError, match=">= 1"):
        build_drafter("truncated", draft_layers=0)


def test_spec_eos_cut_matches_baseline(params):
    """An EOS landing mid-draft must cut the committed stream exactly
    where the non-speculative run stops."""
    eos = 7
    reqs = _requests(n=6, seed=4)
    base_tokens, _ = _run(
        _paged(params), eos_id=eos, max_new_tokens=12,
        reqs=[Request(uid=r.uid, prompt=list(r.prompt)) for r in reqs],
    )
    eng = _paged(params)
    sd = SpeculativeDecoder(eng, drafter="truncated", draft_layers=4,
                            draft_tokens=4)
    spec_tokens, _ = _run(
        eng, spec_decoder=sd, eos_id=eos, max_new_tokens=12,
        reqs=[Request(uid=r.uid, prompt=list(r.prompt)) for r in reqs],
    )
    assert spec_tokens == base_tokens


def test_spec_budget_one_degenerates_to_plain_decode(params):
    """budget 1 => draft_len 0 every step: the verify program IS the
    decode step (bonus token only), still bit-identical."""
    base_tokens, _ = _run(_paged(params), max_new_tokens=1)
    eng = _paged(params)
    sd = SpeculativeDecoder(eng, drafter="truncated", draft_layers=1,
                            draft_tokens=3)
    spec_tokens, rep = _run(eng, spec_decoder=sd, max_new_tokens=1)
    assert spec_tokens == base_tokens
    assert rep.acceptance_rate is None  # zero drafts proposed


def test_spec_quarantine_fails_poisoned_slot_alone(params):
    faults_mod.install_plan("decode_nan@2")
    eng = _paged(params, slots=2)
    sd = SpeculativeDecoder(eng, drafter="truncated", draft_layers=1,
                            draft_tokens=2)
    reqs = _requests(n=2, seed=6)
    tokens, rep = _run(eng, spec_decoder=sd, max_new_tokens=8, reqs=reqs)
    assert rep.quarantined == 1
    assert rep.errors == 1
    # the survivor matches the clean baseline
    clean_tokens, _ = _run(
        _paged(params, slots=2), max_new_tokens=8,
        reqs=_requests(n=2, seed=6),
    )
    survivors = [uid for uid in tokens if len(tokens[uid]) == 8]
    assert survivors
    for uid in survivors:
        assert tokens[uid] == clean_tokens[uid]


def test_decode_tokens_per_sec_reported(params):
    """Satellite: decode-phase-only throughput lives next to the
    whole-wall tokens_per_sec on EVERY run, spec or not."""
    _, rep = _run(_paged(params))
    assert rep.decode_tokens_per_sec > 0
    d = rep.to_dict()
    assert "decode_tokens_per_sec" in d
    assert d["speculative"] is False and d["drafter"] is None

    eng = _paged(params)
    sd = SpeculativeDecoder(eng, drafter="truncated", draft_layers=2,
                            draft_tokens=3)
    _, srep = _run(eng, spec_decoder=sd)
    assert srep.decode_tokens_per_sec > 0
    assert srep.draft_step_s["p50"] >= 0
    assert srep.verify_step_s["p99"] >= srep.verify_step_s["p50"]
    assert srep.verify_step_s["p50"] > 0


def test_spec_registry_gauges(params):
    from distributeddeeplearning_tpu.obs.registry import get_registry

    eng = _paged(params)
    sd = SpeculativeDecoder(eng, drafter="truncated", draft_layers=4,
                            draft_tokens=3)
    _, rep = _run(eng, spec_decoder=sd)
    reg = get_registry()
    assert reg.gauge("serve.acceptance_rate").value == rep.acceptance_rate
    assert reg.gauge("serve.decode_tokens_per_sec").value is not None
    assert reg.histogram("serve.draft_step_s").count >= rep.decode_steps
    assert reg.histogram("serve.verify_step_s").count >= rep.decode_steps


def test_spec_phase_breakdown(params):
    """obs.profile.decode_phase_breakdown learns the draft/verify phases
    and attribute_regression can name an acceptance collapse."""
    from distributeddeeplearning_tpu.obs.profile import (
        attribute_regression,
        decode_phase_breakdown,
    )

    eng = _paged(params)
    eng.prefill(0, [1, 2, 3], max_new_tokens=4)
    sd = SpeculativeDecoder(eng, drafter="truncated", draft_layers=4,
                            draft_tokens=2)
    healthy = decode_phase_breakdown(
        eng, iters=2, warmup=1, spec_decoder=sd
    )
    for key in ("draft", "verify"):
        assert key in healthy["phases_ms"]
    assert healthy["tokens_per_verify"] >= 1.0
    assert healthy["ms_per_committed_token"] > 0

    # simulate an acceptance collapse: same costs, tokens_per_verify ~1
    collapsed = dict(healthy)
    collapsed["tokens_per_verify"] = 1.0
    collapsed["ms_per_committed_token"] = healthy["spec_step_ms"]
    attrib = attribute_regression(healthy, collapsed)
    assert attrib["hottest_phase"] in collapsed["phases_ms"]


# --------------------------------------------------------------------------
# schema + CLI guards + bench smoke
# --------------------------------------------------------------------------

def _spec_payload(**over):
    base = {
        "metric": "lm_serve_spec_decode_speedup", "value": 1.3,
        "unit": "x", "bench_revision": 13, "platform": "cpu",
        "virtual_pod": False, "draft_tokens": 4,
        "baseline": {"decode_tokens_per_sec": 100.0},
        "drafters": {
            "spec_truncated": {
                "acceptance_rate": 0.9, "tokens_per_verify": 4.2,
                "decode_tokens_per_sec": 130.0, "bit_identical": True,
            },
        },
        "gates": {"bit_identical": True, "spec_decode_speedup": True},
    }
    base.update(over)
    return base


def test_spec_schema_accepts_good_payload():
    from distributeddeeplearning_tpu.obs.schema import validate_spec_payload

    validate_spec_payload(_spec_payload())


@pytest.mark.parametrize("mutation,match", [
    (dict(drafters={"d": {"acceptance_rate": 1.7,
                          "tokens_per_verify": 4.0,
                          "decode_tokens_per_sec": 1.0,
                          "bit_identical": True}}), "acceptance_rate"),
    (dict(drafters={"d": {"acceptance_rate": 0.5,
                          "tokens_per_verify": 0.4,
                          "decode_tokens_per_sec": 1.0,
                          "bit_identical": True}}), "tokens_per_verify"),
    (dict(gates={"bit_identical": True}), "spec_decode_speedup"),
    (dict(baseline="nope"), "baseline"),
])
def test_spec_schema_rejects_bad_payloads(mutation, match):
    from distributeddeeplearning_tpu.obs.schema import (
        SchemaError,
        validate_spec_payload,
    )

    with pytest.raises(SchemaError, match=match):
        validate_spec_payload(_spec_payload(**mutation))


def test_spec_artifact_file_validated(tmp_path):
    from distributeddeeplearning_tpu.obs.schema import (
        SchemaError,
        validate_artifact,
    )

    good = tmp_path / "SPEC_r99.json"
    good.write_text(json.dumps(_spec_payload()))
    validate_artifact(str(good))
    bad = tmp_path / "SPEC_r98.json"
    bad.write_text(json.dumps(_spec_payload(gates={})))
    with pytest.raises(SchemaError):
        validate_artifact(str(bad))


REPO_ROOT = Path(__file__).resolve().parent.parent


def test_cli_speculative_flag_guards(capsys):
    """Satellite: --speculative + temperature > 0 errors at CLI-parse
    time (before any engine build), as do --quantize-kv / --replicas /
    bad draft knobs.  --dry-run proves no engine was ever constructed."""
    from distributeddeeplearning_tpu.cli.main import main

    for extra, needle in [
        (["--temperature", "0.5"], "greedy-only"),
        (["--quantize-kv", "int8"], "f32 KV cache"),
        (["--replicas", "2"], "single-replica"),
        (["--draft-tokens", "0"], "--draft-tokens"),
        (["--draft-layers", "0"], "--draft-layers"),
    ]:
        rc = main(
            ["serve", "--synthetic", "--speculative", "--dry-run"] + extra
        )
        err = capsys.readouterr().err
        assert rc == 1, extra
        assert needle in err, (extra, err)
    # the clean combination dry-runs fine
    assert main(["serve", "--synthetic", "--speculative", "--dry-run"]) == 0


@pytest.mark.timeout(240)
def test_bench_spec_cpu_smoke(tmp_path):
    """Fast tier-1 smoke: bench.py --spec end-to-end with a hard
    --steps-cap so the three-engine comparison can never hang CI."""
    report = tmp_path / "spec.json"
    proc = subprocess.run(
        [
            sys.executable, "bench.py", "--spec", "--small",
            "--seq-len", "12", "--serve-requests", "5",
            "--batch-slots", "2", "--max-new-tokens", "6",
            "--page-size", "4", "--prefill-chunk", "8",
            "--draft-tokens", "2", "--draft-layers", "1",
            "--steps-cap", "60", "--report", str(report),
        ],
        capture_output=True, text=True, timeout=220,
        cwd=str(REPO_ROOT),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert set(line["drafters"]) == {"spec_truncated", "spec_int8"}
    for d in line["drafters"].values():
        assert 0.0 <= d["acceptance_rate"] <= 1.0
        assert d["tokens_per_verify"] >= 1.0
        assert d["bit_identical"] is True
    assert line["configs"]["spec_truncated"]["speculative"] is True
    assert report.exists()
