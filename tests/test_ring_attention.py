"""Ring attention: exactness vs dense attention, gradients, masking.

The op has no reference counterpart (the reference has no attention model);
the correctness oracle is the dense fused attention it must match bit-close.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.models.bert import dot_product_attention
from distributeddeeplearning_tpu.ops import make_ring_attention, ring_attention
from distributeddeeplearning_tpu.parallel import MeshSpec, create_mesh

B, S, H, D = 4, 16, 2, 8


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(7)
    make = lambda: jnp.asarray(
        rng.standard_normal((B, S, H, D)), jnp.float32
    )
    return make(), make(), make()


@pytest.fixture(scope="module")
def padding_mask():
    rng = np.random.default_rng(8)
    lengths = rng.integers(1, S + 1, size=(B,))
    mask = np.arange(S)[None, :] < lengths[:, None]
    return jnp.asarray(mask[:, None, None, :])


@pytest.mark.parametrize("ring_size", [2, 4, 8])
def test_matches_dense_attention(qkv, padding_mask, ring_size):
    q, k, v = qkv
    mesh = create_mesh(MeshSpec(seq=ring_size))
    dense = dot_product_attention(q, k, v, padding_mask, dtype=jnp.float32)
    ring = ring_attention(q, k, v, padding_mask, mesh=mesh, dtype=jnp.float32)
    np.testing.assert_allclose(ring, dense, atol=1e-5)


def test_no_mask_matches_dense(qkv):
    q, k, v = qkv
    mesh = create_mesh(MeshSpec(seq=4))
    dense = dot_product_attention(q, k, v, None, dtype=jnp.float32)
    ring = ring_attention(q, k, v, None, mesh=mesh, dtype=jnp.float32)
    np.testing.assert_allclose(ring, dense, atol=1e-5)


def test_seq_axis_one_falls_back_to_dense(qkv, padding_mask):
    q, k, v = qkv
    mesh = create_mesh(MeshSpec())  # seq=1
    dense = dot_product_attention(q, k, v, padding_mask, dtype=jnp.float32)
    ring = ring_attention(q, k, v, padding_mask, mesh=mesh, dtype=jnp.float32)
    np.testing.assert_allclose(ring, dense, atol=1e-6)


def test_gradients_match_dense(qkv, padding_mask):
    q, k, v = qkv
    mesh = create_mesh(MeshSpec(seq=4))

    def dense_loss(q):
        return (dot_product_attention(q, k, v, padding_mask, dtype=jnp.float32) ** 2).sum()

    def ring_loss(q):
        return (
            ring_attention(q, k, v, padding_mask, mesh=mesh, dtype=jnp.float32) ** 2
        ).sum()

    g_dense = jax.grad(dense_loss)(q)
    g_ring = jax.grad(ring_loss)(q)
    np.testing.assert_allclose(g_ring, g_dense, atol=1e-4)


def test_fully_masked_rows_stay_finite(qkv):
    q, k, v = qkv
    mesh = create_mesh(MeshSpec(seq=4))
    mask = jnp.zeros((B, 1, 1, S), bool).at[1:].set(True)  # row 0 all-padding
    out = ring_attention(q, k, v, mask, mesh=mesh, dtype=jnp.float32)
    assert bool(jnp.isfinite(out).all())


def test_make_ring_attention_inside_jit(qkv, padding_mask):
    q, k, v = qkv
    mesh = create_mesh(MeshSpec(seq=2))
    attention_fn = make_ring_attention(mesh)

    @jax.jit
    def fn(q, k, v, mask):
        return attention_fn(q, k, v, mask, dtype=jnp.float32)

    dense = dot_product_attention(q, k, v, padding_mask, dtype=jnp.float32)
    np.testing.assert_allclose(fn(q, k, v, padding_mask), dense, atol=1e-5)


def test_bf16_output_dtype(qkv, padding_mask):
    q, k, v = qkv
    mesh = create_mesh(MeshSpec(seq=2))
    out = ring_attention(
        q.astype(jnp.bfloat16),
        k.astype(jnp.bfloat16),
        v.astype(jnp.bfloat16),
        padding_mask,
        mesh=mesh,
        dtype=jnp.bfloat16,
    )
    assert out.dtype == jnp.bfloat16


def test_ring_program_size_constant_in_ring(monkeypatch):
    """The scan-ified ring (VERDICT r02 item 8): the traced program must
    contain ONE ppermute-carrying loop body regardless of ring size — a
    Python-unrolled ring would grow ppermute count (and compile time)
    linearly with the seq axis."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributeddeeplearning_tpu.ops.ring_attention import ring_attention
    from distributeddeeplearning_tpu.parallel import MeshSpec, create_mesh

    def count_ppermutes(ring):
        mesh = create_mesh(MeshSpec(seq=ring))
        b, s, h, d = 8, 8 * ring, 2, 4
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)

        def f(q):
            return ring_attention(q, q, q, None, mesh=mesh, dtype=jnp.float32)

        return str(jax.make_jaxpr(f)(q)).count("ppermute")

    n2, n8 = count_ppermutes(2), count_ppermutes(8)
    assert n2 == n8, (n2, n8)
    assert n8 <= 2  # k and v inside one scan body, nothing else


# ---------------------------------------------------------------------------
# Ring x flash composition (VERDICT r03 #8): blocked inner loop bounds the
# per-tick score tile at O(Sq*block_k); must stay exact for every block size
# in forward and gradients, at sequence lengths where the unblocked tick
# would materialize the full S/n x S/n tile.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("block_k", [1, 2, 4])
def test_blocked_tick_matches_dense(qkv, padding_mask, block_k):
    q, k, v = qkv
    mesh = create_mesh(MeshSpec(seq=4))
    dense = dot_product_attention(q, k, v, padding_mask, dtype=jnp.float32)
    ring = ring_attention(
        q, k, v, padding_mask, mesh=mesh, dtype=jnp.float32, block_k=block_k
    )
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(dense), atol=2e-5, rtol=2e-5
    )


def test_blocked_long_sequence_matches_unblocked():
    """Longer sequence (S=256 over ring 8 -> Skv=32/tick, blocked at 8):
    the regime where blocking matters; exactness against both the unblocked
    ring and dense."""
    rng = np.random.default_rng(11)
    b, s, h, d = 2, 256, 2, 8
    q, k, v = (
        jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
        for _ in range(3)
    )
    lengths = rng.integers(s // 2, s + 1, b)
    mask = jnp.asarray(
        (np.arange(s)[None, :] < lengths[:, None])[:, None, None, :]
    )
    mesh = create_mesh(MeshSpec(seq=8))
    dense = dot_product_attention(q, k, v, mask, dtype=jnp.float32)
    blocked = ring_attention(
        q, k, v, mask, mesh=mesh, dtype=jnp.float32, block_k=8
    )
    unblocked = ring_attention(q, k, v, mask, mesh=mesh, dtype=jnp.float32)
    np.testing.assert_allclose(
        np.asarray(blocked), np.asarray(dense), atol=3e-5, rtol=3e-5
    )
    np.testing.assert_allclose(
        np.asarray(blocked), np.asarray(unblocked), atol=2e-5, rtol=2e-5
    )


def test_blocked_gradients_match_dense(qkv, padding_mask):
    q, k, v = qkv
    mesh = create_mesh(MeshSpec(seq=4))

    def dense_loss(q):
        return (
            dot_product_attention(q, k, v, padding_mask, dtype=jnp.float32)
            ** 2
        ).sum()

    def blocked_loss(q):
        return (
            ring_attention(
                q, k, v, padding_mask, mesh=mesh, dtype=jnp.float32,
                block_k=2,
            )
            ** 2
        ).sum()

    np.testing.assert_allclose(
        np.asarray(jax.grad(blocked_loss)(q)),
        np.asarray(jax.grad(dense_loss)(q)),
        atol=5e-4, rtol=5e-4,
    )


def test_invalid_block_rejected(qkv, padding_mask):
    q, k, v = qkv
    mesh = create_mesh(MeshSpec(seq=4))
    with pytest.raises(ValueError, match="block_k"):
        ring_attention(
            q, k, v, padding_mask, mesh=mesh, dtype=jnp.float32, block_k=3
        )


# ---------------------------------------------------------------------------
# Causal ring (round 4): the autoregressive triangle applied in GLOBAL
# positions — each tick's mask is full/triangular/empty depending on where
# the rotating kv block sits relative to this shard's queries.  Oracle:
# dense attention over the combined padding & tril mask.
# ---------------------------------------------------------------------------


def _dense_causal(q, k, v, mask):
    s = q.shape[1]
    tril = jnp.tril(jnp.ones((s, s), bool))[None, None]
    full = tril if mask is None else jnp.logical_and(mask, tril)
    return dot_product_attention(q, k, v, full, dtype=jnp.float32)


@pytest.mark.parametrize("ring_size", [2, 4, 8])
def test_causal_matches_dense(qkv, padding_mask, ring_size):
    q, k, v = qkv
    mesh = create_mesh(MeshSpec(seq=ring_size))
    dense = _dense_causal(q, k, v, padding_mask)
    ring = ring_attention(
        q, k, v, padding_mask, mesh=mesh, dtype=jnp.float32, causal=True
    )
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(dense), atol=2e-5, rtol=2e-5
    )


def test_causal_no_mask_matches_dense(qkv):
    q, k, v = qkv
    mesh = create_mesh(MeshSpec(seq=4))
    dense = _dense_causal(q, k, v, None)
    ring = ring_attention(
        q, k, v, None, mesh=mesh, dtype=jnp.float32, causal=True
    )
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(dense), atol=2e-5, rtol=2e-5
    )


@pytest.mark.parametrize("block_k", [1, 2, 4])
def test_causal_blocked_matches_dense(qkv, padding_mask, block_k):
    q, k, v = qkv
    mesh = create_mesh(MeshSpec(seq=4))
    dense = _dense_causal(q, k, v, padding_mask)
    ring = ring_attention(
        q, k, v, padding_mask, mesh=mesh, dtype=jnp.float32, causal=True,
        block_k=block_k,
    )
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(dense), atol=2e-5, rtol=2e-5
    )


def test_causal_gradients_match_dense(qkv, padding_mask):
    q, k, v = qkv
    mesh = create_mesh(MeshSpec(seq=4))

    def dense_loss(q):
        return (_dense_causal(q, k, v, padding_mask) ** 2).sum()

    def ring_loss(q):
        return (
            ring_attention(
                q, k, v, padding_mask, mesh=mesh, dtype=jnp.float32,
                causal=True, block_k=2,
            )
            ** 2
        ).sum()

    np.testing.assert_allclose(
        np.asarray(jax.grad(ring_loss)(q)),
        np.asarray(jax.grad(dense_loss)(q)),
        atol=5e-4, rtol=5e-4,
    )


def test_causal_seq_axis_one_falls_back_to_dense(qkv, padding_mask):
    q, k, v = qkv
    mesh = create_mesh(MeshSpec())  # seq=1
    dense = _dense_causal(q, k, v, padding_mask)
    ring = ring_attention(
        q, k, v, padding_mask, mesh=mesh, dtype=jnp.float32, causal=True
    )
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(dense), atol=1e-6
    )


def test_backward_rerotates_instead_of_saving_ticks():
    """Training-memory contract: the custom backward re-rotates k/v (4
    ppermutes per bwd tick: k, v, dk, dv) instead of letting scan AD stack
    per-tick k/v residuals.  The grad jaxpr must contain exactly the fwd
    scan's 2 ppermute sites plus the bwd scan's 4 — constant in ring size —
    and no [ring, ...]-stacked k/v residual output from the forward scan."""
    mesh = create_mesh(MeshSpec(seq=8))
    b, s, h, d = 2, 64, 2, 8
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)

    def loss(q):
        return (
            ring_attention(
                q, q, q, None, mesh=mesh, dtype=jnp.float32, causal=True
            )
            ** 2
        ).sum()

    jaxpr = str(jax.make_jaxpr(jax.grad(loss))(q))
    assert jaxpr.count("ppermute") == 6, jaxpr.count("ppermute")
    # scan-AD residual stacking would show as a fwd-scan output of shape
    # [ring=8, b, skv=s/8, h, d] = f32[8,2,8,2,8]
    assert "f32[8,2,8,2,8]" not in jaxpr


def test_bf16_gradients_finite_and_close():
    """The custom backward must hand back bf16 cotangents matching the
    primal dtypes (custom_vjp aval contract) and stay close to the f32
    dense oracle at bf16 tolerance."""
    rng = np.random.default_rng(13)
    q, k, v = (
        jnp.asarray(rng.standard_normal((2, 16, 2, 8)), jnp.bfloat16)
        for _ in range(3)
    )
    mesh = create_mesh(MeshSpec(seq=4))

    def ring_loss(q, k, v):
        return (
            ring_attention(
                q, k, v, None, mesh=mesh, dtype=jnp.bfloat16, causal=True
            ).astype(jnp.float32)
            ** 2
        ).sum()

    def dense_loss(q, k, v):
        return (
            _dense_causal(
                q.astype(jnp.float32), k.astype(jnp.float32),
                v.astype(jnp.float32), None,
            )
            ** 2
        ).sum()

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        assert gr.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(gr, np.float32), np.asarray(gd), atol=0.15, rtol=0.1
        )
