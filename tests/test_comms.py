"""Explicit gradient comms (parallel/comms.py + comm_overlap train step).

The load-bearing property: the explicit schedule — bucketed reduce-scatter
inside the accumulation scan, ZeRO weight-update sharding, bf16 compressed
wire with error feedback — must reproduce the implicit-GSPMD step's
numerics.  With a single microbatch the two programs perform the same
reductions in the same order modulo exact power-of-two rescales, so the
matrix pins params AND metrics **bit-exact** across bucket sizes (including
a bucket smaller than the largest param and one larger than the whole
model) and weight-update sharding on/off.  With accum_steps > 1 GSPMD
defers its allreduce out of the scan (a different — coarser — summation
grouping), so that case pins ulp-level agreement instead.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearning_tpu.models import get_model
from distributeddeeplearning_tpu.parallel import MeshSpec, comms, create_mesh, shard_batch
from distributeddeeplearning_tpu.train.state import create_train_state, sgd_momentum
from distributeddeeplearning_tpu.train.step import build_train_step

REPO = Path(__file__).resolve().parents[1]


_FIXTURE_CACHE = {}


def _bert_state(seed=0, lr=0.05):
    # one model/tx PAIR per lr: states fed to a compiled step must share
    # the state_example's static pytree fields (apply_fn, tx), and the
    # checkpoint restore template likewise
    if lr not in _FIXTURE_CACHE:
        model = get_model(
            "bert-base", num_layers=1, hidden_size=32, num_heads=2,
            intermediate_size=64, vocab_size=50, num_classes=3,
            max_position_embeddings=16, dropout_rate=0.0, dtype=jnp.float32,
        )
        _FIXTURE_CACHE[lr] = (model, sgd_momentum(optax.constant_schedule(lr)))
    model, tx = _FIXTURE_CACHE[lr]
    return create_train_state(
        jax.random.key(seed), model, (2, 8), tx, input_dtype=jnp.int32
    )


def _token_batch(mesh, n=32, seed=7):
    rng = np.random.default_rng(seed)
    return shard_batch(mesh, {
        "input": rng.integers(0, 50, (n, 8)).astype(np.int32),
        "label": rng.integers(0, 3, (n,)).astype(np.int32),
    })


@pytest.fixture(scope="module")
def mesh8():
    return create_mesh(MeshSpec())


@pytest.fixture(scope="module")
def baseline_accum1(mesh8):
    """(params_leaves, metrics) after 2 implicit-GSPMD steps, accum=1."""
    state = _bert_state()
    step = build_train_step(mesh8, state, compute_dtype=jnp.float32)
    batch = _token_batch(mesh8)
    for _ in range(2):
        state, metrics = step(state, batch)
    return (
        [np.asarray(x) for x in jax.tree_util.tree_leaves(state.params)],
        {k: float(v) for k, v in metrics.items()},
    )


# ---------------------------------------------------------------------------
# BucketLayout
# ---------------------------------------------------------------------------


def test_bucket_layout_roundtrip_and_padding():
    tree = {
        "w": jnp.arange(1000, dtype=jnp.float32).reshape(50, 20),
        "b": jnp.ones((7,), jnp.bfloat16),
        "s": jnp.asarray(3.0),
    }
    layout = comms.BucketLayout.for_tree(tree, bucket_bytes=600, shards=8)
    # 600 bytes -> 150 elems -> rounded up to 152 (multiple of 8)
    assert all(n % 8 == 0 for n in layout.bucket_sizes)
    assert layout.total == 1008
    assert layout.padded_total >= layout.total
    assert layout.num_buckets > 1  # bucket smaller than the largest param
    out = layout.from_buckets(layout.to_buckets(tree))
    assert out["b"].dtype == jnp.bfloat16
    for k in tree:
        np.testing.assert_array_equal(np.asarray(out[k]), np.asarray(tree[k]))


def test_bucket_layout_single_bucket_covers_model():
    tree = {"w": jnp.ones((13,), jnp.float32)}
    layout = comms.BucketLayout.for_tree(tree, bucket_bytes=1 << 30, shards=8)
    assert layout.num_buckets == 1
    assert layout.padded_total == 16  # 13 padded to the next multiple of 8
    out = layout.from_buckets(layout.to_buckets(tree))
    np.testing.assert_array_equal(np.asarray(out["w"]), np.ones(13))


def test_ring_wire_bytes_compression_halves():
    tree = {"w": jnp.ones((4096,), jnp.float32)}
    layout = comms.BucketLayout.for_tree(tree, bucket_bytes=4096, shards=8)
    f32 = comms.ring_wire_bytes(layout, comm_dtype=None, accum_steps=2)
    bf16 = comms.ring_wire_bytes(
        layout, comm_dtype=jnp.bfloat16, accum_steps=2
    )
    assert bf16["reduce_scatter_bytes"] * 2 == f32["reduce_scatter_bytes"]
    wus = comms.ring_wire_bytes(
        layout, comm_dtype=None, weight_update_sharding=True
    )
    assert wus["all_gather_bytes"] > 0
    assert f32["all_gather_bytes"] == 0


# ---------------------------------------------------------------------------
# Numeric equivalence vs the implicit GSPMD step
# ---------------------------------------------------------------------------

# bucket_mb=0.004 -> ~1048-elem buckets, smaller than the 50x32 embedding
# table; 64 MB -> one bucket larger than the whole model.
@pytest.mark.parametrize("wus", [False, True])
@pytest.mark.parametrize("bucket_mb", [0.004, 64.0])
def test_comm_overlap_bitexact_vs_implicit(mesh8, baseline_accum1, wus, bucket_mb):
    base_params, base_metrics = baseline_accum1
    state = _bert_state()
    step = build_train_step(
        mesh8, state, compute_dtype=jnp.float32,
        comm_overlap=True, bucket_mb=bucket_mb, weight_update_sharding=wus,
    )
    state = step.prepare_state(state)
    batch = _token_batch(mesh8)
    for _ in range(2):
        state, metrics = step(state, batch)
    for a, b in zip(base_params, jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_array_equal(a, np.asarray(b))
    assert set(metrics) == set(base_metrics)
    for k, v in base_metrics.items():
        assert float(metrics[k]) == v, f"metric {k} not bit-exact"


def test_comm_overlap_accum_matches_baseline_to_ulps(mesh8):
    """accum>1: GSPMD hoists its allreduce out of the scan (coarser
    summation grouping), so agreement is ulp-level, not bitwise."""
    batch = None
    results = []
    for kwargs in (
        {},
        dict(comm_overlap=True, bucket_mb=0.004, weight_update_sharding=True),
    ):
        state = _bert_state()
        step = build_train_step(
            mesh8, state, compute_dtype=jnp.float32, accum_steps=4, **kwargs
        )
        if kwargs:
            state = step.prepare_state(state)
        batch = _token_batch(mesh8)
        for _ in range(3):
            state, metrics = step(state, batch)
        results.append((state.params, metrics))
    (p_a, m_a), (p_b, m_b) = results
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        ),
        p_a, p_b,
    )
    np.testing.assert_allclose(
        float(m_a["loss"]), float(m_b["loss"]), rtol=1e-5
    )


def test_wus_shards_optimizer_hbm(mesh8):
    """The ZeRO claim: params-shaped optimizer buffers live as flat bucket
    shards over the data axes — each chip addresses 1/N of the elements."""
    state = _bert_state()
    n_params = sum(
        int(np.prod(p.shape))
        for p in jax.tree_util.tree_leaves(state.params)
    )
    step = build_train_step(
        mesh8, state, compute_dtype=jnp.float32,
        comm_overlap=True, bucket_mb=0.004, weight_update_sharding=True,
    )
    state = step.prepare_state(state)
    buckets = [
        leaf for leaf in jax.tree_util.tree_leaves(state.opt_state["base"])
        if leaf.ndim == 1 and leaf.size >= 8
    ]
    assert buckets, "no flat-sharded optimizer buckets found"
    momentum_elems = sum(b.size for b in buckets)
    assert momentum_elems == step.layout.padded_total  # one momentum tree
    for b in buckets:
        # physically sharded: each device holds size/8 elements
        assert len(b.sharding.device_set) == 8
        shard_size = {s.data.size for s in b.addressable_shards}
        assert shard_size == {b.size // 8}
    assert momentum_elems < 2 * n_params  # padding stayed bounded


def test_bf16_error_feedback_converges_and_roundtrips_checkpoint(mesh8, tmp_path):
    from distributeddeeplearning_tpu.train.checkpoint import Checkpointer

    state = _bert_state()
    step = build_train_step(
        mesh8, state, compute_dtype=jnp.float32, accum_steps=2,
        comm_overlap=True, bucket_mb=0.004, comm_dtype="bf16",
        weight_update_sharding=True,
    )
    state = step.prepare_state(state)
    batch = _token_batch(mesh8)
    first = None
    for _ in range(6):
        state, metrics = step(state, batch)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first  # tiny-fixture convergence
    residual_l1 = sum(
        float(jnp.sum(jnp.abs(r))) for r in state.opt_state["residual"]
    )
    assert residual_l1 > 0  # compression error is being carried

    ckpt = Checkpointer(str(tmp_path / "ckpt"), async_save=False)
    try:
        ckpt.save(int(state.step), state)
        ckpt.wait()
        template = step.prepare_state(_bert_state())
        restored, at = ckpt.restore(template)
    finally:
        ckpt.close()
    assert at == int(state.step)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        {"r": state.opt_state["residual"], "o": state.opt_state["base"],
         "p": state.params},
        {"r": restored.opt_state["residual"], "o": restored.opt_state["base"],
         "p": restored.params},
    )
    # the restored state must keep training through the same compiled step
    restored, m2 = step(restored, batch)
    assert np.isfinite(float(m2["loss"]))


def test_comm_overlap_skip_nonfinite_discards_update(mesh8):
    from distributeddeeplearning_tpu.train.step import cross_entropy_loss

    def poisoned_loss(logits, labels, *, label_smoothing=0.0):
        return cross_entropy_loss(logits, labels) * jnp.nan

    state = _bert_state()
    step = build_train_step(
        mesh8, state, compute_dtype=jnp.float32,
        comm_overlap=True, bucket_mb=0.004, weight_update_sharding=True,
        skip_nonfinite=True, loss_fn=poisoned_loss,
    )
    state = step.prepare_state(state)
    before = [np.asarray(x) for x in jax.tree_util.tree_leaves(state.params)]
    state, metrics = step(state, _token_batch(mesh8))
    assert float(metrics["anomalous"]) == 1.0
    assert int(state.step) == 1  # step advances, update discarded
    for a, b in zip(before, jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_array_equal(a, np.asarray(b))


# ---------------------------------------------------------------------------
# Program-shape pins and gates
# ---------------------------------------------------------------------------


def test_accum1_compiles_without_scan(mesh8):
    """accum_steps == 1 must lower the minimal program: no scan wrapper
    (stablehlo while) and no zero grad-accumulator, in BOTH paths."""
    batch = _token_batch(mesh8)
    for kwargs in ({}, dict(comm_overlap=True, bucket_mb=64.0)):
        state = _bert_state()
        step = build_train_step(
            mesh8, state, compute_dtype=jnp.float32, accum_steps=1, **kwargs
        )
        if kwargs:
            state = step.prepare_state(state)
        text = step.lower(state, batch).as_text()
        assert "while" not in text, f"accum=1 program has a loop ({kwargs})"
    state = _bert_state()
    step4 = build_train_step(
        mesh8, state, compute_dtype=jnp.float32, accum_steps=4
    )
    assert "while" in step4.lower(state, batch).as_text()


def test_comm_overlap_rejects_sharded_params(mesh8):
    from distributeddeeplearning_tpu.parallel.sharding import RULES_FSDP

    state = _bert_state()
    with pytest.raises(ValueError, match="replicated-params"):
        build_train_step(
            mesh8, state, comm_overlap=True, rules=RULES_FSDP,
            logical_axes={"dummy": None},
        )
    with pytest.raises(ValueError, match="require comm_overlap"):
        build_train_step(mesh8, state, weight_update_sharding=True)
    with pytest.raises(ValueError, match="comm_dtype"):
        build_train_step(mesh8, state, comm_overlap=True, comm_dtype="fp8")


def test_transformer_workload_comm_overlap_end_to_end(tmp_path):
    """The full wiring: workload main -> comm step -> prepare_state ->
    Trainer.fit -> checkpoint -> RESUME through the prepared template
    (residual and flat-sharded optimizer buckets included)."""
    from distributeddeeplearning_tpu.workloads.transformer import main

    kwargs = dict(
        batch_size=2, seq_len=8, vocab_size=37, num_layers=1, d_model=16,
        num_heads=2, d_ff=32, steps_per_epoch=2, train_examples=64,
        compute_dtype="float32", comm_overlap=True, bucket_mb=0.002,
        comm_dtype="bf16", weight_update_sharding=True, grad_clip_norm=0.0,
        save_filepath=str(tmp_path / "ckpt"), seed=0,
    )
    state, fit = main(epochs=1, **kwargs)
    assert int(state.step) == 2
    assert np.isfinite(fit.final_train_metrics["loss"])
    assert "residual" in state.opt_state
    # resume: epochs=2 restores step 2 from the comm-layout checkpoint and
    # trains 2 more steps
    state2, _ = main(epochs=2, **kwargs)
    assert int(state2.step) == 4


def test_transformer_workload_rejects_wus_with_global_norm_clip():
    from distributeddeeplearning_tpu.workloads.transformer import main

    with pytest.raises(ValueError, match="SHARD norm"):
        main(
            epochs=1, batch_size=2, seq_len=8, vocab_size=37, num_layers=1,
            d_model=16, num_heads=2, d_ff=32, steps_per_epoch=1,
            comm_overlap=True, weight_update_sharding=True,
        )


def test_cli_train_forwards_comm_flags(capsys):
    from distributeddeeplearning_tpu.cli.main import main as cli_main

    rc = cli_main([
        "train", "imagenet", "--dry-run", "--comm-overlap",
        "--bucket-mb", "2", "--comm-dtype", "bf16",
        "--weight-update-sharding",
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "--comm_overlap True" in out
    assert "--bucket_mb 2.0" in out
    assert "--comm_dtype bf16" in out
    assert "--weight_update_sharding True" in out


@pytest.mark.timeout(280)
def test_bench_comms_smoke(tmp_path):
    """CPU `bench.py --comms --steps-cap` end to end: both modes run on the
    virtual pod and the artifact carries the documented fields."""
    report = tmp_path / "COMMS_smoke.json"
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "bench.py"), "--comms",
            "--model", "resnet18", "--batch-size", "4", "--image-size", "32",
            "--bucket-mb", "1.0", "--steps-cap", "2",
            "--comms-modes", "implicit,overlap",
            "--report", str(report),
        ],
        # inherited env: conftest's XLA_FLAGS already fakes the 8-device
        # pod, so the child skips its own virtual-pod re-exec
        cwd=str(REPO), env=dict(os.environ),
        capture_output=True, text=True, timeout=260,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = json.loads(report.read_text())
    assert set(line["modes"]) == {"implicit", "overlap"}
    overlap = line["modes"]["overlap"]
    assert overlap["step_time_s"] > 0
    assert 0 < overlap["overlap_efficiency"] <= 1.0
    assert "reduce-scatter" in overlap["collectives_per_step"]
    wire = overlap["ring_wire_bytes_per_step_per_device"]
    assert wire["total_bytes"] > 0
    # compressed mode's wire is half of f32 (analytic ring model)
    assert line["compressed_vs_f32_wire_ratio"] == 0.5
    assert line["modes"]["implicit"]["collectives_per_step"]["all-reduce"]["bytes"] > 0
