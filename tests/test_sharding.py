"""Sharding rules and batch placement — both rule systems: the
logical-axis training rules and the partition-rule layout table."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributeddeeplearning_tpu.data.synthetic import synthetic_batch
from distributeddeeplearning_tpu.parallel import (
    MeshSpec,
    batch_sharding,
    create_mesh,
    param_shardings,
    replicated,
    shard_batch,
)
from distributeddeeplearning_tpu.parallel.mesh import DATA_AXES
from distributeddeeplearning_tpu.parallel.sharding import (
    LAYOUT_RULES,
    RULES_FSDP,
    RULES_TP,
    layout_rules_provenance,
    logical_to_spec,
    match_partition_rules,
    spec_for,
    unmatched_leaves,
)


def test_batch_sharded_over_data_axes():
    mesh = create_mesh(MeshSpec())
    batch = shard_batch(mesh, synthetic_batch(16, (8, 8, 3), 5))
    img = batch["image"]
    # 8-way split on the leading dim: each device holds 2 rows
    assert img.sharding.is_equivalent_to(batch_sharding(mesh), img.ndim)
    shard_shapes = {s.data.shape for s in img.addressable_shards}
    assert shard_shapes == {(2, 8, 8, 3)}


def test_batch_content_roundtrip():
    mesh = create_mesh(MeshSpec())
    src = synthetic_batch(8, (4, 4, 3), 5, seed=7)
    batch = shard_batch(mesh, src)
    np.testing.assert_array_equal(np.asarray(batch["label"]), src["label"])
    np.testing.assert_allclose(np.asarray(batch["image"]), src["image"], rtol=1e-6)


def test_param_shardings_default_replicated():
    mesh = create_mesh(MeshSpec())
    params = {"a": np.zeros((4, 4)), "b": {"c": np.zeros((3,))}}
    sh = param_shardings(mesh, params)
    for leaf in jax.tree_util.tree_leaves(sh):
        assert leaf.is_equivalent_to(replicated(mesh), 2)


def test_logical_to_spec_fsdp():
    spec = logical_to_spec(("embed", "mlp"), RULES_FSDP)
    assert spec == P("fsdp", None)  # fsdp used once, second match skipped


def test_logical_to_spec_tp():
    spec = logical_to_spec(("embed", "heads", "kv"), RULES_TP)
    assert spec == P("fsdp", "tensor", None)


def test_logical_to_spec_unmatched_replicates():
    spec = logical_to_spec((None, "nonexistent"), RULES_TP)
    assert spec == P(None, None)


# ---------------------------------------------------------------------------
# the partition-rule layout table (match_partition_rules and friends)
# ---------------------------------------------------------------------------


def _tp_mesh():
    """data=1 × tensor=2 over the first two virtual-pod devices."""
    return create_mesh(
        MeshSpec(data=1, tensor=2), devices=jax.devices()[:2]
    )


def test_rule_table_first_match_wins():
    # the io/ namespace rule sits ABOVE the terminal (^|/)pos$ replicate
    # rule, so io/pos binds to the data axes while a param pos replicates
    assert spec_for("io/pos", shape=(4,)) == P(DATA_AXES)
    assert spec_for("params/pos", shape=(64, 16)) == P()
    # synthetic table: the broad pattern shadows the specific one below it
    rules = ((r"w", ("tensor",)), (r"^w$", (None, "tensor")))
    assert spec_for("w", shape=(8, 8), rules=rules) == P("tensor")


def test_rule_table_axis_used_once():
    # XLA forbids one mesh axis on two dims of one leaf: the second use
    # drops (first wins), trailing replicated dims trim off the spec
    rules = ((r"^dup$", ("tensor", "tensor")),)
    assert spec_for("dup", shape=(4, 4), rules=rules) == P("tensor")


def test_rule_table_qtensor_scale_leaves():
    """QTensor scale leaves (axis=-2 keepdims quantization): column-
    parallel scales shard with their values' output dim; row-parallel
    scales' contracted dim collapses to size 1, which the divisibility
    drop de-shards — scales replicate exactly when they must."""
    mesh = _tp_mesh()
    # column-parallel w_in: values [L, d, d_ff], scales [L, 1, d_ff]
    assert spec_for(
        "params/blocks/w_in/values", shape=(2, 16, 24), mesh=mesh
    ) == P(None, None, "tensor")
    assert spec_for(
        "params/blocks/w_in/scales", shape=(2, 1, 24), mesh=mesh
    ) == P(None, None, "tensor")
    # row-parallel w_out: values [L, d_ff, d] contract over tensor;
    # scales [L, 1, d] lose the mapping to the divisibility drop
    assert spec_for(
        "params/blocks/w_out/values", shape=(2, 24, 16), mesh=mesh
    ) == P(None, "tensor")
    assert spec_for(
        "params/blocks/w_out/scales", shape=(2, 1, 16), mesh=mesh
    ) == P()


def test_rule_table_divisibility_drop():
    mesh = _tp_mesh()  # tensor=2
    # vocab-parallel head [d, V]: an odd vocab cannot split over 2 chips
    assert spec_for("params/head", shape=(16, 33), mesh=mesh) == P()
    assert spec_for("params/head", shape=(16, 32), mesh=mesh) == P(
        None, "tensor"
    )


def test_match_partition_rules_strict_raises_on_fallthrough():
    with pytest.raises(ValueError, match="wq_lora"):
        match_partition_rules(
            {"wq_lora": jax.ShapeDtypeStruct((4, 4), jnp.float32)},
            prefix="params",
        )


def test_match_partition_rules_none_placeholders_resolve_by_name():
    # name-only trees ({"bucket": None}) resolve by path alone — JAX
    # would otherwise flatten None into empty structure and skip the rule
    specs = match_partition_rules({"bucket": None}, prefix="comm")
    assert specs["bucket"] == P(DATA_AXES)


def test_unmatched_leaves_scalars_exempt():
    tree = {
        "mystery": jax.ShapeDtypeStruct((4,), jnp.float32),
        "scalar": jax.ShapeDtypeStruct((), jnp.float32),
    }
    assert unmatched_leaves(tree, prefix="params") == ["params/mystery"]


def test_layout_rules_provenance_tracks_table_content():
    tag = layout_rules_provenance()
    assert tag.startswith(f"LAYOUT_RULES#{len(LAYOUT_RULES)}@")
    # a silent table edit must change the stamp
    assert layout_rules_provenance(LAYOUT_RULES[:-1]) != tag
