"""Sharding rules and batch placement."""

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from distributeddeeplearning_tpu.data.synthetic import synthetic_batch
from distributeddeeplearning_tpu.parallel import (
    MeshSpec,
    batch_sharding,
    create_mesh,
    param_shardings,
    replicated,
    shard_batch,
)
from distributeddeeplearning_tpu.parallel.sharding import (
    RULES_FSDP,
    RULES_TP,
    logical_to_spec,
)


def test_batch_sharded_over_data_axes():
    mesh = create_mesh(MeshSpec())
    batch = shard_batch(mesh, synthetic_batch(16, (8, 8, 3), 5))
    img = batch["image"]
    # 8-way split on the leading dim: each device holds 2 rows
    assert img.sharding.is_equivalent_to(batch_sharding(mesh), img.ndim)
    shard_shapes = {s.data.shape for s in img.addressable_shards}
    assert shard_shapes == {(2, 8, 8, 3)}


def test_batch_content_roundtrip():
    mesh = create_mesh(MeshSpec())
    src = synthetic_batch(8, (4, 4, 3), 5, seed=7)
    batch = shard_batch(mesh, src)
    np.testing.assert_array_equal(np.asarray(batch["label"]), src["label"])
    np.testing.assert_allclose(np.asarray(batch["image"]), src["image"], rtol=1e-6)


def test_param_shardings_default_replicated():
    mesh = create_mesh(MeshSpec())
    params = {"a": np.zeros((4, 4)), "b": {"c": np.zeros((3,))}}
    sh = param_shardings(mesh, params)
    for leaf in jax.tree_util.tree_leaves(sh):
        assert leaf.is_equivalent_to(replicated(mesh), 2)


def test_logical_to_spec_fsdp():
    spec = logical_to_spec(("embed", "mlp"), RULES_FSDP)
    assert spec == P("fsdp", None)  # fsdp used once, second match skipped


def test_logical_to_spec_tp():
    spec = logical_to_spec(("embed", "heads", "kv"), RULES_TP)
    assert spec == P("fsdp", "tensor", None)


def test_logical_to_spec_unmatched_replicates():
    spec = logical_to_spec((None, "nonexistent"), RULES_TP)
    assert spec == P(None, None)
