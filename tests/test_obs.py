"""Observability layer: tracer, registry, profiling merge, artifacts.

Covers the OBS_r11 contract:

- span nesting + Chrome-trace export schema round-trip (and the merge
  onto a device trace's clock);
- streaming-histogram percentile accuracy against numpy quantiles;
- registry snapshots surviving injected storage faults AND a restart
  (append-only JSONL through the retry layer);
- the scheduler routing its percentile/TPOT blocks through obs, with
  request-lifecycle events on the timeline;
- ``bench.py --obs --steps-cap`` CPU smoke under pytest-timeout;
- schema validation of EVERY committed ``*_r*.json`` artifact, so
  artifact drift fails tier-1 instead of rotting silently.
"""

import glob
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from distributeddeeplearning_tpu.obs.registry import (
    Histogram,
    MetricsRegistry,
    summarize,
)
from distributeddeeplearning_tpu.obs.trace import Tracer
from distributeddeeplearning_tpu.utils import faults

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- tracer ---------------------------------------------------------------

def test_span_nesting_and_depth():
    t = Tracer(enabled=True, annotate=False)
    with t.span("outer", step=1):
        with t.span("inner"):
            pass
        with t.span("inner2"):
            pass
    spans = {e["name"]: e for e in t.events}
    assert spans["outer"]["args"]["depth"] == 0
    assert spans["inner"]["args"]["depth"] == 1
    assert spans["inner2"]["args"]["depth"] == 1
    assert spans["outer"]["args"]["step"] == 1
    # time containment: children start after and end before the parent
    for child in ("inner", "inner2"):
        assert spans[child]["ts"] >= spans["outer"]["ts"]
        assert (
            spans[child]["ts"] + spans[child]["dur"]
            <= spans["outer"]["ts"] + spans["outer"]["dur"] + 1.0
        )


def test_chrome_export_roundtrip(tmp_path):
    t = Tracer(enabled=True, annotate=False)
    with t.span("phase", kind="test"):
        pass
    t.event("mark", step=7)
    path = t.export(str(tmp_path / "trace.json"))
    with open(path) as f:
        loaded = json.load(f)
    events = loaded["traceEvents"]
    # process metadata names the host lane
    meta = [e for e in events if e.get("ph") == "M"]
    assert any(
        e["name"] == "process_name"
        and e["args"]["name"] == "ddlt-host"
        for e in meta
    )
    xs = [e for e in events if e.get("ph") == "X"]
    instants = [e for e in events if e.get("ph") == "i"]
    assert xs[0]["name"] == "phase" and xs[0]["args"]["kind"] == "test"
    assert instants[0]["name"] == "mark" and instants[0]["args"]["step"] == 7
    for e in xs:
        assert e["dur"] >= 0 and e["ts"] >= 0


def test_disabled_tracer_records_nothing_and_reuses_null_span():
    t = Tracer(enabled=False)
    s1 = t.span("a", big_arg=list(range(10)))
    s2 = t.span("b")
    with s1:
        pass
    t.event("never")
    assert s1 is s2  # the shared no-op: no per-call allocation
    assert t.events == []


def test_merge_host_device_aligns_clocks(tmp_path):
    from distributeddeeplearning_tpu.obs.profile import merge_host_device

    t = Tracer(enabled=True, annotate=False)
    with t.span("shared_phase"):
        pass
    host_ts = t.events[0]["ts"]
    # synthetic xprof trace: the same span name at a different clock
    # origin, plus a device op — the merge must shift both by the offset
    trace_dir = tmp_path / "plugins" / "profile" / "run1"
    trace_dir.mkdir(parents=True)
    device = {
        "traceEvents": [
            {"ph": "M", "name": "process_name", "pid": 7,
             "args": {"name": "/device:TPU:0"}},
            {"ph": "X", "pid": 7, "tid": 1, "ts": 5000.0, "dur": 10.0,
             "name": "shared_phase"},
            {"ph": "X", "pid": 7, "tid": 1, "ts": 5002.0, "dur": 3.0,
             "name": "fusion.1"},
        ]
    }
    import gzip

    with gzip.open(trace_dir / "host.trace.json.gz", "wt") as f:
        json.dump(device, f)
    merged = merge_host_device(t, str(tmp_path))
    assert merged["metadata"]["device_trace"] == "merged"
    offset = merged["metadata"]["clock_offset_us"]
    assert offset == pytest.approx(host_ts - 5000.0)
    fusion = next(
        e for e in merged["traceEvents"] if e.get("name") == "fusion.1"
    )
    assert fusion["ts"] == pytest.approx(5002.0 + offset)
    # host spans untouched, on the tracer's own (derived) pid
    host = next(
        e for e in merged["traceEvents"]
        if e.get("name") == "shared_phase" and e.get("pid") == t.pid
    )
    assert host["ts"] == pytest.approx(host_ts)


def test_merge_without_device_trace_reports_absent(tmp_path):
    from distributeddeeplearning_tpu.obs.profile import merge_host_device

    t = Tracer(enabled=True, annotate=False)
    with t.span("solo"):
        pass
    merged = merge_host_device(t, str(tmp_path))
    assert merged["metadata"]["device_trace"] == "absent"
    assert any(e.get("name") == "solo" for e in merged["traceEvents"])


# --- histogram / summarize ------------------------------------------------

@pytest.mark.parametrize(
    "samples",
    [
        np.random.default_rng(0).lognormal(0.0, 1.0, 4000),
        np.random.default_rng(1).uniform(0.001, 10.0, 4000),
        np.full(100, 3.25),
    ],
    ids=["lognormal", "uniform", "constant"],
)
def test_histogram_percentiles_match_numpy(samples):
    h = Histogram(max_rel_err=0.01)
    h.record_many(samples)
    for q in (50, 90, 99):
        got = h.percentile(q)
        want = float(np.percentile(samples, q))
        # 1% sketch error + the interpolation-convention gap on finite n
        assert got == pytest.approx(want, rel=0.03), (q, got, want)
    assert h.max == pytest.approx(float(samples.max()))
    assert h.mean == pytest.approx(float(samples.mean()), rel=1e-9)


def test_histogram_percentiles_are_monotone_and_clamped():
    h = Histogram()
    h.record_many([0.0, 0.0, 1e-9, 5.0, 5.0, 5.0])
    p50, p90, p99 = h.percentile(50), h.percentile(90), h.percentile(99)
    assert p50 <= p90 <= p99 <= h.max
    assert h.percentile(0) >= h.min


def test_summarize_keys_and_empty():
    s = summarize([1.0, 2.0, 3.0])
    assert {"p50", "p90", "p99", "mean", "max"} <= set(s)
    assert s["max"] == 3.0
    empty = summarize([])
    assert empty["p50"] == 0.0 and empty["max"] == 0.0


def test_histogram_merge():
    a, b = Histogram(), Histogram()
    a.record_many([1.0, 2.0])
    b.record_many([3.0, 4.0])
    a.merge(b)
    assert a.count == 4 and a.max == 4.0 and a.min == 1.0


# --- registry + snapshots -------------------------------------------------

def test_registry_counters_gauges_idempotent_names():
    reg = MetricsRegistry()
    reg.counter("x").inc()
    reg.counter("x").inc(2)
    reg.gauge("g").set(1.5)
    reg.histogram("h").record(0.25)
    snap = reg.snapshot(extra_field="yes")
    assert snap["counters"]["x"] == 3
    assert snap["gauges"]["g"] == 1.5
    assert snap["histograms"]["h"]["count"] == 1
    assert snap["extra_field"] == "yes"


def test_snapshot_survives_injected_io_error_and_restart(
    tmp_path, monkeypatch
):
    """The satellite contract: snapshot writes retry through injected
    storage faults, and rows written before a 'restart' (a fresh registry
    — process state lost) are still in the file after it."""
    path = str(tmp_path / "obs.jsonl")
    monkeypatch.setenv(faults.ENV_VAR, "io_error@1")
    faults.reset()  # arm: the FIRST storage opportunity raises
    try:
        reg = MetricsRegistry()
        reg.counter("runs").inc()
        assert reg.write_snapshot(path, phase="before")  # retry absorbs it
        assert reg.snapshots_written == 1
        # restart: new registry (in-memory state gone), same file
        reg2 = MetricsRegistry()
        reg2.counter("runs").inc()
        assert reg2.write_snapshot(path, phase="after")
    finally:
        monkeypatch.delenv(faults.ENV_VAR)
        faults.reset()
    rows = [json.loads(ln) for ln in open(path)]
    assert [r["phase"] for r in rows] == ["before", "after"]
    assert all(r["counters"]["runs"] == 1 for r in rows)


def test_snapshot_exhausted_retries_drop_row_not_process(
    tmp_path, monkeypatch
):
    path = str(tmp_path / "obs.jsonl")
    monkeypatch.setenv(faults.ENV_VAR, "io_error@p=1.0")  # every attempt
    faults.reset()
    try:
        reg = MetricsRegistry()
        assert reg.write_snapshot(path) is False  # dropped, no raise
        assert reg.snapshots_dropped == 1
    finally:
        monkeypatch.delenv(faults.ENV_VAR)
        faults.reset()
    assert not os.path.exists(path)


# --- scheduler integration ------------------------------------------------

class _FakeEngine:
    """Duck-typed engine: instant prefill/decode, fixed token stream."""

    batch_slots = 2
    max_seq = 64
    chunked_prefill = False
    prefill_compiles = 0

    def prefill(self, slot, prompt):
        return 1

    def decode(self, tokens, pos):
        return np.full(self.batch_slots, 2, np.int32)


def _run_fake_scheduler():
    from distributeddeeplearning_tpu.serve.scheduler import (
        ContinuousBatchingScheduler,
        Request,
    )

    reqs = [Request(uid=f"r{i}", prompt=[1, 2, 3]) for i in range(4)]
    return ContinuousBatchingScheduler(
        _FakeEngine(), max_new_tokens=4
    ).run(reqs)


def test_scheduler_report_routes_through_obs_and_adds_tpot():
    results, report = _run_fake_scheduler()
    for block in (report.ttft_s, report.decode_step_s,
                  report.queue_wait_s, report.tpot_s):
        assert {"p50", "p90", "p99", "mean", "max"} <= set(block)
    # every request generated 4 tokens: TPOT is measurable and finite
    assert report.tpot_s["max"] >= 0
    d = report.to_dict()
    assert "tpot_s" in d


def test_scheduler_emits_lifecycle_trace_events():
    from distributeddeeplearning_tpu.obs import trace as trace_mod

    tracer = trace_mod.set_tracer(Tracer(enabled=True, annotate=False))
    try:
        _run_fake_scheduler()
        names = [e["name"] for e in tracer.events]
    finally:
        trace_mod.set_tracer(Tracer(enabled=False))
    assert "serve/prefill" in names
    assert "serve/decode_step" in names
    assert names.count("serve/request_complete") == 4


def test_scheduler_disabled_tracer_emits_nothing():
    from distributeddeeplearning_tpu.obs import trace as trace_mod

    tracer = trace_mod.set_tracer(Tracer(enabled=False))
    try:
        _run_fake_scheduler()
        assert tracer.events == []
    finally:
        trace_mod.set_tracer(Tracer(enabled=False))


# --- artifact schema ------------------------------------------------------

def test_every_committed_revision_artifact_validates():
    """Artifact drift (a dropped key, a malformed percentile block,
    invalid JSON) fails tier-1 here — every committed ``*_r*.json``."""
    from distributeddeeplearning_tpu.obs.schema import validate_artifact

    paths = sorted(glob.glob(os.path.join(REPO_ROOT, "*_r*.json")))
    assert paths, "no committed revision artifacts found"
    for path in paths:
        validate_artifact(path)


def test_obs_schema_rejects_drift(tmp_path):
    from distributeddeeplearning_tpu.obs.schema import (
        SchemaError,
        validate_artifact,
    )

    bad = tmp_path / "OBS_r99.json"
    bad.write_text(json.dumps({"metric": "m", "value": 1, "unit": "x"}))
    with pytest.raises(SchemaError, match="decode_breakdown"):
        validate_artifact(str(bad))
    notjson = tmp_path / "X_r99.json"
    notjson.write_text("{nope")
    with pytest.raises(SchemaError, match="not valid JSON"):
        validate_artifact(str(notjson))
    badp99 = tmp_path / "S_r99.json"
    badp99.write_text(json.dumps(
        {"ttft_s": {"p50": 2.0, "p99": 1.0}}
    ))
    with pytest.raises(SchemaError, match="p99 < p50"):
        validate_artifact(str(badp99))


def test_attribute_regression_across_phase_schemas():
    """Cross-revision attribution: comparing an old-schema breakdown
    (``attention_mlp_other``) against the PR-12 split must NOT report a
    candidate-only phase's whole time as 'growth' — one-sided phases
    land in ``unmatched_phases`` and deltas cover only shared phases."""
    from distributeddeeplearning_tpu.obs.profile import attribute_regression

    old = {
        "decode_step_ms": 200.0,
        "phases_ms": {"page_gather": 5.0, "scale_dequant": 0.0,
                      "attention_mlp_other": 150.0},
    }
    new = {
        "decode_step_ms": 210.0,
        "phases_ms": {"page_gather": 6.0, "scale_dequant": 0.0,
                      "attention_kernel": 90.0, "mlp_other": 80.0},
    }
    out = attribute_regression(old, new)
    assert set(out["phase_delta_ms"]) == {"page_gather", "scale_dequant"}
    assert out["unmatched_phases"] == [
        "attention_kernel", "attention_mlp_other", "mlp_other"
    ]
    # a same-schema comparison still names the grown phase
    new2 = dict(new, phases_ms=dict(new["phases_ms"], attention_kernel=120.0))
    out2 = attribute_regression(new, new2)
    assert out2["hottest_phase"] == "attention_kernel"
    assert out2["hottest_phase_delta_ms"] == 30.0
    assert "unmatched_phases" not in out2


# --- bench --obs CPU smoke ------------------------------------------------

@pytest.mark.timeout(280)
def test_bench_obs_steps_cap_smoke(tmp_path):
    """End-to-end: ``bench.py --obs --small --steps-cap`` must emit a
    schema-valid OBS artifact with a merged timeline and a per-engine
    decode breakdown, on CPU, inside the fast tier's deadline."""
    from distributeddeeplearning_tpu.obs.schema import validate_artifact

    report = tmp_path / "OBS_smoke.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DDLT_FAULTS", None)
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO_ROOT, "bench.py"),
            "--obs", "--small", "--steps-cap", "2",
            "--serve-requests", "3", "--max-new-tokens", "3",
            "--report", str(report),
            "--trace-dir", str(tmp_path / "trace"),
        ],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True,
        timeout=260,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    line = validate_artifact(str(report))
    assert line["bench_revision"] >= 11
    assert set(line["decode_breakdown"]) == {"f32", "kv_int8"}
    assert line["decode_breakdown"]["kv_int8"]["kv_dtype"] == "int8"
    # the attribution names a real phase of the int8 engine
    hottest = line["regression_attribution"]["hottest_phase"]
    assert hottest in line["decode_breakdown"]["kv_int8"]["phases_ms"]
    # merged timeline digest carries both halves
    counts = line["timeline"]["event_counts"]
    assert counts["host_spans"] > 0
    # full merged chrome trace landed next to the device trace
    assert os.path.exists(tmp_path / "trace" / "merged.trace.json")
