"""The fault matrix: resilience layer + fault injection + retrying I/O.

Covers (ISSUE 2 acceptance): nan-skip / abort-rollback, preempt →
emergency-checkpoint → resume on the exact step, watchdog deadline +
stack dump, retry backoff inside the jitter bounds, per-request serve
fault isolation, and ``ddlt train --max-restarts`` surviving an injected
preemption and a mid-epoch data-stream death.
"""

import itertools
import logging
import os
import random
import signal

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax import linen as nn

from distributeddeeplearning_tpu.parallel import MeshSpec, create_mesh
from distributeddeeplearning_tpu.train import resilience
from distributeddeeplearning_tpu.train.loop import Trainer, TrainerConfig
from distributeddeeplearning_tpu.train.resilience import (
    AnomalyDetector,
    AnomalyError,
    PreemptionError,
    PreemptionGuard,
    StepWatchdog,
)
from distributeddeeplearning_tpu.train.state import (
    create_train_state,
    sgd_momentum,
)
from distributeddeeplearning_tpu.train.step import build_train_step
from distributeddeeplearning_tpu.utils import faults
from distributeddeeplearning_tpu.utils.faults import (
    DataStreamDeath,
    FaultPlan,
    InjectedIOError,
    parse_spec,
)
from distributeddeeplearning_tpu.utils.retry import (
    RateLimitedLogger,
    backoff_delays,
    retry_call,
)

GLOBAL_BATCH = 16
IMG = (4, 4, 3)
NCLS = 5


@pytest.fixture(autouse=True)
def _clean_fault_plan(monkeypatch):
    """Every test starts and ends with an empty process fault plan."""
    monkeypatch.delenv(faults.ENV_VAR, raising=False)
    faults.reset()
    yield
    faults.reset()


def _arm(monkeypatch, spec: str) -> FaultPlan:
    monkeypatch.setenv(faults.ENV_VAR, spec)
    return faults.reset()


# --------------------------------------------------------------------------
# fault spec grammar
# --------------------------------------------------------------------------


def test_fault_spec_grammar_roundtrip():
    specs = parse_spec(
        "nan_loss@12,data_stall@30:secs=2,preempt@50,io_error@p=0.05:seed=7"
    )
    assert [s.kind for s in specs] == [
        "nan_loss", "data_stall", "preempt", "io_error"
    ]
    assert specs[0].step == 12 and specs[0].prob is None
    assert specs[1].options == {"secs": 2}
    assert specs[3].prob == 0.05 and specs[3].options["seed"] == 7
    assert specs[1].describe() == "data_stall@30:secs=2"


@pytest.mark.parametrize(
    "bad",
    [
        "explode@3",            # unknown kind
        "nan_loss",             # missing trigger
        "nan_loss@0",           # steps are 1-based
        "io_error@p=1.5",       # probability outside [0, 1]
        "data_stall@5:secs",    # option without value
    ],
)
def test_fault_spec_rejects_malformed(bad):
    with pytest.raises(ValueError):
        parse_spec(bad)


def test_step_keyed_faults_fire_once(monkeypatch):
    plan = _arm(monkeypatch, "nan_loss@2")
    batch = {"image": np.ones((4, 2), np.float32), "label": np.zeros(4, np.int32)}
    assert not np.isnan(plan.poison_batch(1, batch)["image"]).any()
    poisoned = plan.poison_batch(2, batch)
    assert np.isnan(poisoned["image"]).all()
    assert not np.isnan(poisoned["label"].astype(np.float64)).any()
    # one-shot: step 2 again (after an in-process restart) does NOT re-fire
    assert not np.isnan(plan.poison_batch(2, batch)["image"]).any()
    assert [e.kind for e in plan.events] == ["nan_loss"]


def test_nan_loss_on_float_free_batch_is_loud(monkeypatch):
    plan = _arm(monkeypatch, "nan_loss@1")
    with pytest.raises(ValueError, match="no float array"):
        plan.poison_batch(1, {"input": np.zeros((2, 3), np.int32)})


def test_io_error_fault_deterministic_by_seed(monkeypatch):
    def firing_sequence():
        plan = _arm(monkeypatch, "io_error@p=0.5:seed=7")
        fired = []
        for _ in range(20):
            try:
                plan.maybe_io_error("site")
                fired.append(False)
            except InjectedIOError:
                fired.append(True)
        return fired

    first, second = firing_sequence(), firing_sequence()
    assert first == second
    assert any(first) and not all(first)


def test_data_faults_wrap_iterator(monkeypatch):
    plan = _arm(monkeypatch, "data_death@3")
    stream = plan.wrap_data(iter([{"x": i} for i in range(5)]), start_step=0)
    assert next(stream) == {"x": 0}
    assert next(stream) == {"x": 1}
    with pytest.raises(DataStreamDeath) as exc:
        next(stream)
    assert exc.value.step == 3


# --------------------------------------------------------------------------
# retry backoff
# --------------------------------------------------------------------------


def test_backoff_delays_stay_within_jitter_bounds():
    base, cap = 0.1, 5.0
    delays = list(
        backoff_delays(12, base_delay=base, max_delay=cap, rng=random.Random(3))
    )
    assert len(delays) == 12
    for i, d in enumerate(delays):
        assert 0.0 <= d <= min(cap, base * 2**i)
    # the later draws must actually use the grown window, not the first cap
    assert max(delays) > base


def test_retry_call_retries_then_succeeds():
    calls, slept = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise IOError("transient")
        return "ok"

    assert retry_call(
        flaky, retries=4, sleep=slept.append, rng=random.Random(0)
    ) == "ok"
    assert len(calls) == 3 and len(slept) == 2


def test_retry_call_bounded_and_raises_last_error():
    calls, slept = [], []

    def always_fails():
        calls.append(1)
        raise IOError("still down")

    with pytest.raises(IOError, match="still down"):
        retry_call(
            always_fails, retries=3, sleep=slept.append, rng=random.Random(0)
        )
    assert len(calls) == 4 and len(slept) == 3  # bounded: no infinite loop


def test_retry_call_deadline_stops_retrying_when_budget_spent():
    """deadline_s bounds the WHOLE retry sequence: once the (injected)
    clock passes the budget, the current failure re-raises immediately —
    no further sleeps, no further attempts (the emergency-checkpoint
    path's grace-window contract)."""
    clock = {"t": 0.0}
    calls, slept = [], []

    def tick_sleep(d):
        slept.append(d)
        clock["t"] += d

    def always_fails():
        calls.append(1)
        clock["t"] += 2.0  # each attempt itself burns wall clock
        raise IOError("still down")

    with pytest.raises(IOError, match="still down"):
        retry_call(
            always_fails, retries=10, base_delay=1.0, max_delay=1.0,
            sleep=tick_sleep, rng=random.Random(0),
            clock=lambda: clock["t"], deadline_s=5.0,
        )
    # attempt 1 (t=2), sleep, attempt 2 (t>=4), sleep clamped, attempt 3
    # (t>=6 > 5) -> raise without sleeping.  Far fewer than retries=10.
    assert len(calls) <= 3
    assert clock["t"] <= 5.0 + 2.0 + 1.0  # never slept past the window


def test_retry_call_deadline_clamps_sleep_to_remaining_window():
    clock = {"t": 0.0}
    slept = []

    def tick_sleep(d):
        slept.append(d)
        clock["t"] += d

    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 2:
            raise IOError("transient")
        return "ok"

    # base_delay huge: without the deadline the first sleep would be up
    # to 100s; the 3s budget must clamp it
    assert retry_call(
        flaky, retries=3, base_delay=100.0, max_delay=100.0,
        sleep=tick_sleep, rng=random.Random(1),
        clock=lambda: clock["t"], deadline_s=3.0,
    ) == "ok"
    assert len(slept) == 1 and slept[0] <= 3.0


def test_retry_call_deadline_none_keeps_unbounded_behavior():
    calls, slept = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 4:
            raise IOError("transient")
        return "ok"

    assert retry_call(
        flaky, retries=4, sleep=slept.append, rng=random.Random(0),
    ) == "ok"
    assert len(calls) == 4 and len(slept) == 3


def test_retry_call_rejects_negative_deadline():
    with pytest.raises(ValueError, match="deadline_s"):
        retry_call(lambda: None, deadline_s=-1.0)


def test_preemption_guard_remaining_grace(monkeypatch):
    from distributeddeeplearning_tpu.train import resilience as res

    clock = {"t": 100.0}
    monkeypatch.setattr(res.time, "monotonic", lambda: clock["t"])
    guard = res.PreemptionGuard(grace_s=30.0)
    assert guard.remaining_grace() is None  # no signal yet
    guard.trigger("injected")
    clock["t"] += 12.0
    assert guard.remaining_grace() == pytest.approx(18.0)
    clock["t"] += 100.0
    assert guard.remaining_grace() == 0.0  # floored, never negative
    # without a configured window the guard reports None (no deadline)
    g2 = res.PreemptionGuard()
    g2.trigger("injected")
    assert g2.remaining_grace() is None


def test_emergency_stop_plumbs_grace_deadline_into_checkpointer():
    """Trainer._emergency_stop must pass the REMAINING grace window into
    both save() and wait() as their retry deadline — re-read before each
    phase (save may consume most of the budget)."""
    from distributeddeeplearning_tpu.train.loop import Trainer, TrainerConfig
    from distributeddeeplearning_tpu.train.resilience import (
        PreemptionError as PE,
        PreemptionGuard,
    )

    class FakeCkpt:
        def __init__(self):
            self.deadlines = []

        def save(self, step, state, *, deadline_s=None):
            self.deadlines.append(("save", deadline_s))

        def wait(self, *, deadline_s=None):
            self.deadlines.append(("wait", deadline_s))

    trainer = Trainer.__new__(Trainer)  # no mesh/step needed for this path
    trainer.checkpointer = FakeCkpt()
    trainer.config = TrainerConfig(steps_per_epoch=1)
    guard = PreemptionGuard(grace_s=60.0)
    guard.trigger("injected preempt")
    with pytest.raises(PE):
        trainer._emergency_stop(5, None, None, guard=guard)
    kinds = [k for k, _ in trainer.checkpointer.deadlines]
    assert kinds == ["save", "wait"]
    for _, deadline in trainer.checkpointer.deadlines:
        assert deadline is not None and 0.0 <= deadline <= 60.0
    # no guard: deadlines stay None (unknown window)
    trainer.checkpointer = FakeCkpt()
    with pytest.raises(PE):
        trainer._emergency_stop(6, None, None, guard=None)
    assert trainer.checkpointer.deadlines == [
        ("save", None), ("wait", None)
    ]


def test_rate_limited_logger_suppresses_within_interval():
    clock = {"t": 0.0}
    lines = []
    rl = RateLimitedLogger(
        lambda msg, *a: lines.append(msg % a if a else msg),
        min_interval_s=60.0, clock=lambda: clock["t"],
    )
    assert rl("drop %d", 1)
    for i in range(5):
        clock["t"] += 1.0
        assert not rl("drop %d", i)
    clock["t"] += 60.0
    assert rl("drop %d", 9)
    assert len(lines) == 2 and "5 similar suppressed" in lines[1]


def test_command_runner_retries_failing_command():
    from distributeddeeplearning_tpu.control.command import CommandRunner

    runner = CommandRunner()
    slept = []
    runner._sleep = slept.append
    result = runner.run(
        ["python", "-c", "import sys; sys.exit(3)"],
        check=False, retries=2,
    )
    assert result.returncode == 3
    assert len(runner.history) == 3 and len(slept) == 2
    # success consumes no retries
    runner2 = CommandRunner()
    runner2._sleep = slept.append
    assert runner2.run(["python", "-c", "pass"], retries=2).ok
    assert len(runner2.history) == 1


# --------------------------------------------------------------------------
# MetricsLog drop path
# --------------------------------------------------------------------------


def test_metrics_log_drops_row_with_rate_limited_warning(
    monkeypatch, tmp_path, caplog
):
    from distributeddeeplearning_tpu.train.loop import MetricsLog

    _arm(monkeypatch, "io_error@p=1:seed=0")  # every write fails
    log = MetricsLog(str(tmp_path / "metrics.jsonl"))
    with caplog.at_level(logging.WARNING, logger="ddlt.train"):
        log.append({"epoch": 1})
        log.append({"epoch": 2})
    assert log.dropped_rows == 2
    assert not (tmp_path / "metrics.jsonl").exists()
    drops = [r for r in caplog.records
             if r.name == "ddlt.train" and "dropped" in r.getMessage()]
    assert len(drops) == 1  # rate-limited: one line, not one per row


def test_metrics_log_survives_transient_io_error(monkeypatch, tmp_path):
    from distributeddeeplearning_tpu.train.loop import MetricsLog

    # fail exactly the first write opportunity; the retry lands the row
    _arm(monkeypatch, "io_error@1")
    path = tmp_path / "metrics.jsonl"
    log = MetricsLog(str(path))
    log.append({"epoch": 1})
    assert log.dropped_rows == 0
    assert '"epoch": 1' in path.read_text()


def test_checkpoint_save_retries_through_injected_io_error(
    monkeypatch, tmp_path, tiny_parts
):
    from distributeddeeplearning_tpu.train.checkpoint import Checkpointer

    _, mk_state, _ = tiny_parts
    plan = _arm(monkeypatch, "io_error@1")
    ckpt = Checkpointer(str(tmp_path / "ck"))
    assert ckpt.save(1, mk_state()) is True  # retried past the injection
    ckpt.wait()
    assert ckpt.latest_step() == 1
    assert [e.kind for e in plan.events] == ["io_error"]


# --------------------------------------------------------------------------
# trainer-level fault matrix (tiny dense model: compile stays cheap)
# --------------------------------------------------------------------------


class _Tiny(nn.Module):
    @nn.compact
    def __call__(self, x, train=False):
        return nn.Dense(NCLS)(x.reshape((x.shape[0], -1)))


@pytest.fixture(scope="module")
def tiny_parts():
    mesh = create_mesh(MeshSpec())
    model = _Tiny()
    tx = sgd_momentum(optax.constant_schedule(0.05))

    def mk_state():
        return create_train_state(jax.random.key(0), model, (8, *IMG), tx)

    guarded_step = build_train_step(
        mesh, mk_state(), compute_dtype=jnp.float32, skip_nonfinite=True
    )
    return mesh, mk_state, guarded_step


def _factory(start_step: int):
    """Step-indexed deterministic stream (exact-resume contract)."""

    def gen():
        i = start_step
        while True:
            rng = np.random.default_rng(1000 + i)
            yield {
                "image": rng.standard_normal(
                    (GLOBAL_BATCH, *IMG)
                ).astype(np.float32),
                "label": rng.integers(0, NCLS, (GLOBAL_BATCH,)).astype(
                    np.int32
                ),
            }
            i += 1

    return gen()


def _flat(state):
    import jax.flatten_util

    leaves, _ = jax.flatten_util.ravel_pytree(
        {"p": state.params, "o": state.opt_state}
    )
    return np.asarray(leaves)


def test_nan_loss_step_is_skipped_not_applied(monkeypatch, tiny_parts):
    """The poisoned step's update must be discarded on device (the
    skip_nonfinite guard), counted by the detector, excluded from the epoch
    metrics — and every parameter must stay finite."""
    mesh, mk_state, step = tiny_parts
    cfg = TrainerConfig(
        epochs=2, steps_per_epoch=3, global_batch_size=GLOBAL_BATCH,
        prefetch=0, anomaly_max_consecutive=3,
    )
    _arm(monkeypatch, "nan_loss@4")
    state, fit = Trainer(mesh, step, config=cfg).fit(mk_state(), _factory)
    assert fit.anomalous_steps == 1
    assert int(state.step) == 6  # step advances even when skipped
    assert np.isfinite(_flat(state)).all()
    # epoch 2 contains the anomalous step 4: its loss mean excludes the NaN
    # and the row carries the anomaly count
    assert np.isfinite(fit.final_train_metrics["loss"])
    assert fit.final_train_metrics["anomalous_steps"] == 1.0


def test_anomaly_abort_after_consecutive(monkeypatch, tiny_parts):
    mesh, mk_state, step = tiny_parts
    cfg = TrainerConfig(
        epochs=2, steps_per_epoch=3, global_batch_size=GLOBAL_BATCH,
        prefetch=0, anomaly_max_consecutive=2,
    )
    _arm(monkeypatch, "nan_loss@2,nan_loss@3")
    with pytest.raises(AnomalyError) as exc:
        Trainer(mesh, step, config=cfg).fit(mk_state(), _factory)
    assert exc.value.consecutive == 2 and exc.value.step == 3


def test_anomaly_abort_rolls_back_to_checkpoint(
    monkeypatch, tiny_parts, tmp_path
):
    """abort-rollback: after N consecutive anomalies the Trainer restores
    the last checkpoint and finishes (the injected faults are one-shot)."""
    mesh, mk_state, step = tiny_parts
    cfg = TrainerConfig(
        epochs=2, steps_per_epoch=3, global_batch_size=GLOBAL_BATCH,
        prefetch=0, anomaly_max_consecutive=2, anomaly_rollback=True,
        checkpoint_dir=str(tmp_path / "rb"), checkpoint_every_steps=2,
    )
    _arm(monkeypatch, "nan_loss@3,nan_loss@4")
    state, fit = Trainer(mesh, step, config=cfg).fit(mk_state(), _factory)
    assert fit.rollbacks == 1
    assert int(state.step) == 6
    assert np.isfinite(_flat(state)).all()


def test_preempt_emergency_checkpoint_then_exact_resume(
    monkeypatch, tiny_parts, tmp_path
):
    """preempt → synchronous emergency checkpoint at the preempted step →
    resume lands on that exact step → final state bit-identical to an
    uninterrupted run."""
    mesh, mk_state, step = tiny_parts
    base = dict(
        epochs=2, steps_per_epoch=4, global_batch_size=GLOBAL_BATCH,
        prefetch=0,
    )
    ref_state, _ = Trainer(
        mesh, step, config=TrainerConfig(**base)
    ).fit(mk_state(), _factory)

    ckpt = str(tmp_path / "pe")
    cfg = TrainerConfig(checkpoint_dir=ckpt, **base)
    _arm(monkeypatch, "preempt@5")
    trainer = Trainer(mesh, step, config=cfg)
    with pytest.raises(PreemptionError) as exc:
        trainer.fit(mk_state(), _factory)
    assert exc.value.step == 5
    # the emergency checkpoint landed SYNCHRONOUSLY at the preempted step
    assert trainer.checkpointer.latest_step() == 5

    resumed, fit = Trainer(mesh, step, config=cfg).fit(mk_state(), _factory)
    assert int(resumed.step) == 8
    assert fit.total_images == 3 * GLOBAL_BATCH  # only steps 6..8 re-ran
    np.testing.assert_array_equal(_flat(resumed), _flat(ref_state))


def test_sigterm_triggers_guard_and_restores_handler():
    guard = PreemptionGuard(signals=(signal.SIGTERM,))
    prev = signal.getsignal(signal.SIGTERM)
    with guard:
        assert not guard.preempted()
        os.kill(os.getpid(), signal.SIGTERM)
        assert guard.preempted()
        assert "SIGTERM" in guard.reason
    assert signal.getsignal(signal.SIGTERM) is prev


def test_anomaly_detector_tolerates_isolated_blips():
    det = AnomalyDetector(max_consecutive=2)
    assert det.observe(1, float("nan"))
    assert not det.observe(2, 0.5)          # resets the consecutive count
    assert det.observe(3, 1.0, float("inf"))  # grad-norm anomaly counts too
    assert not det.observe(4, 0.5)
    assert det.total == 2
    det.observe(5, float("nan"))
    with pytest.raises(AnomalyError):
        det.observe(6, float("nan"))


# --------------------------------------------------------------------------
# watchdog
# --------------------------------------------------------------------------


def test_watchdog_fires_and_dumps_stacks():
    import io
    import time

    buf = io.StringIO()
    fired = []
    wd = StepWatchdog(
        0.2, on_timeout=lambda: fired.append(1), poll_s=0.02, stream=buf
    )
    with wd:
        wd.tick()
        deadline = time.monotonic() + 5.0
        while not fired and time.monotonic() < deadline:
            time.sleep(0.02)
    assert fired and wd.fired
    out = buf.getvalue()
    assert "watchdog" in out
    # the all-thread stack dump names at least this (the main) thread
    assert "Thread" in out or "thread" in out


def test_watchdog_quiet_while_ticking():
    import time

    fired = []
    wd = StepWatchdog(0.3, on_timeout=lambda: fired.append(1), poll_s=0.02)
    with wd:
        for _ in range(10):
            wd.tick()
            time.sleep(0.05)
        wd.pause()
        time.sleep(0.5)  # paused: an idle gap must not fire
    assert not fired


def test_watchdog_unarmed_until_first_tick():
    import time

    fired = []
    wd = StepWatchdog(0.1, on_timeout=lambda: fired.append(1), poll_s=0.02)
    with wd:
        time.sleep(0.4)  # compile-phase analogue: no tick yet
    assert not fired


# --------------------------------------------------------------------------
# supervisor
# --------------------------------------------------------------------------


def test_supervise_restart_budget():
    calls = []

    def fn(attempt):
        calls.append(attempt)
        if len(calls) < 3:
            raise resilience.RestartableError("again", step=len(calls))
        return "done"

    result, restarts = resilience.supervise(fn, max_restarts=2)
    assert result == "done" and restarts == 2 and calls == [0, 1, 2]

    calls.clear()
    with pytest.raises(resilience.RestartableError):
        resilience.supervise(fn, max_restarts=1)


def test_control_plane_exit_code_matches_resilience_contract():
    """control/submit.py declares the resumable exit code as a literal (to
    keep the control plane jax-free); it must stay equal to the runner's."""
    from distributeddeeplearning_tpu.control import submit

    assert submit.RESUMABLE_EXIT_CODE == resilience.RESUMABLE_EXIT_CODE


def test_runner_exits_resumable_code_on_preemption():
    from distributeddeeplearning_tpu.workloads._runner import run_from_argv

    def main(*, epochs: int = 1):
        raise PreemptionError("preempted at step 3", step=3)

    with pytest.raises(SystemExit) as exc:
        run_from_argv(main, ["--epochs", "2"])
    assert exc.value.code == resilience.RESUMABLE_EXIT_CODE


# --------------------------------------------------------------------------
# serve scheduler fault isolation
# --------------------------------------------------------------------------


class _FakeEngine:
    batch_slots = 2
    max_seq = 32

    def prefill(self, slot, prompt):
        if len(prompt) == 13:
            raise RuntimeError("bad prompt blew up the kernel")
        return 1

    def decode(self, tokens, pos):
        return np.full(self.batch_slots, 2, np.int32)


def test_scheduler_isolates_per_request_prefill_failure():
    from distributeddeeplearning_tpu.serve.scheduler import (
        ContinuousBatchingScheduler,
        Request,
    )

    sched = ContinuousBatchingScheduler(_FakeEngine(), max_new_tokens=3)
    results, report = sched.run([
        Request("ok1", [1, 2, 3]),
        Request("bad", list(range(13))),
        Request("ok2", [4, 5]),
    ])
    by_uid = {r.uid: r for r in results}
    assert by_uid["bad"].finish_reason == "error"
    assert "blew up" in by_uid["bad"].error
    assert by_uid["ok1"].finish_reason == "length"
    assert by_uid["ok2"].finish_reason == "length"
    assert len(by_uid["ok1"].tokens) == 3  # unaffected by the bad request
    assert report.errors == 1
    assert report.finish_reasons == {"error": 1, "length": 2}
    assert report.to_dict()["errors"] == 1  # surfaced in the artifact schema


def test_scheduler_requeues_surviving_slots_on_decode_failure():
    """PR 7 semantics: an exception out of ``engine.decode`` itself is not
    any request's fault — the active slots are requeued ONCE (tokens
    already generated preserved, budget reduced) instead of all finishing
    "error", and the queue keeps draining."""

    class _FlakyDecode(_FakeEngine):
        def __init__(self):
            self.calls = 0

        def prefill(self, slot, prompt):
            return 1

        def decode(self, tokens, pos):
            self.calls += 1
            if self.calls == 1:
                raise RuntimeError("collective died")
            return np.full(self.batch_slots, 2, np.int32)

    from distributeddeeplearning_tpu.serve.scheduler import (
        ContinuousBatchingScheduler,
        Request,
    )

    sched = ContinuousBatchingScheduler(_FlakyDecode(), max_new_tokens=2)
    results, report = sched.run(
        [Request("x", [1]), Request("y", [2]), Request("z", [3])]
    )
    by_uid = {r.uid: r for r in results}
    # the two slots active at the failure survived via requeue
    assert report.errors == 0
    assert report.decode_retries == 2
    assert {r.finish_reason for r in results} == {"length"}
    for uid in ("x", "y"):
        # prefill's token was preserved across the requeue and the final
        # result restores the original prompt/output split
        assert by_uid[uid].tokens[0] == 1
        assert len(by_uid[uid].tokens) == 2
        assert by_uid[uid].prompt_len == 1
    assert len(by_uid["z"].tokens) == 2  # queued request still served


def test_scheduler_decode_failure_retry_budget_is_bounded():
    """A decode that fails every time must not requeue forever: the
    second failure under the same request completes it "error"."""

    class _DeadDecode(_FakeEngine):
        def prefill(self, slot, prompt):
            return 1

        def decode(self, tokens, pos):
            raise RuntimeError("collective very dead")

    from distributeddeeplearning_tpu.serve.scheduler import (
        ContinuousBatchingScheduler,
        Request,
    )

    sched = ContinuousBatchingScheduler(_DeadDecode(), max_new_tokens=3)
    results, report = sched.run([Request("x", [1, 2])])
    (res,) = results
    assert res.finish_reason == "error"
    assert "retry budget spent" in res.error
    assert report.errors == 1
    assert report.decode_retries == 1  # exactly one retry was granted


# --------------------------------------------------------------------------
# ddlt train --max-restarts (the CLI supervisor, end to end on CPU)
# --------------------------------------------------------------------------


def test_cli_train_survives_nan_and_preemption_exactly(
    monkeypatch, tmp_path, capsys
):
    """ISSUE 2 acceptance: DDLT_FAULTS="nan_loss@12,preempt@50" — the run
    skips the anomalous step, emergency-checkpoints at the simulated
    preemption, and ``ddlt train --max-restarts 1`` resumes to finish with
    the exact configured step count (3 epochs x 20 = 60)."""
    from distributeddeeplearning_tpu.cli.main import main as cli_main
    from distributeddeeplearning_tpu.train.checkpoint import Checkpointer

    ckpt = str(tmp_path / "ck")
    monkeypatch.setenv(faults.ENV_VAR, "nan_loss@12,preempt@50")
    rc = cli_main([
        "train", "imagenet", "--max-restarts", "1",
        "--model", "resnet18", "--image_size", "16", "--batch_size", "1",
        "--num_classes", "3", "--epochs", "3", "--steps_per_epoch", "20",
        "--train_images", "480", "--compute_dtype", "float32",
        "--skip_nonfinite", "true", "--anomaly_max_consecutive", "5",
        "--save_filepath", ckpt,
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "restarts=1" in out and "completed at step 60" in out
    ck = Checkpointer(ckpt)
    try:
        steps = set(ck._mgr.all_steps())
    finally:
        ck.close()
    assert 50 in steps   # the emergency checkpoint at the preempted step
    assert 60 in steps   # ...and the resumed run finished exactly
    plan = faults.get_plan()
    assert {e.kind for e in plan.events} == {"nan_loss", "preempt"}


def test_cli_train_survives_mid_epoch_data_stream_death(
    monkeypatch, tmp_path, capsys
):
    """A data stream that dies mid-epoch is restartable: the supervisor
    re-enters the workload, which resumes from the last periodic
    checkpoint and completes the configured step count."""
    from distributeddeeplearning_tpu.cli.main import main as cli_main
    from distributeddeeplearning_tpu.train.checkpoint import Checkpointer

    ckpt = str(tmp_path / "ck")
    monkeypatch.setenv(faults.ENV_VAR, "data_death@6")
    rc = cli_main([
        "train", "transformer", "--max-restarts", "1",
        "--num_layers", "2", "--d_model", "32", "--num_heads", "2",
        "--d_ff", "64", "--vocab_size", "64", "--seq_len", "16",
        "--batch_size", "1", "--epochs", "2", "--steps_per_epoch", "4",
        "--compute_dtype", "float32", "--checkpoint_every_steps", "2",
        "--save_filepath", ckpt,
    ])
    assert rc == 0
    assert "restarts=1" in capsys.readouterr().out
    ck = Checkpointer(ckpt)
    try:
        assert ck.latest_step() == 8
    finally:
        ck.close()


def test_cli_train_exhausted_preemption_budget_exits_resumable(
    monkeypatch, tmp_path
):
    """With no restart budget a preemption exits RESUMABLE_EXIT_CODE (75):
    the handoff contract to an OUTER supervisor."""
    from distributeddeeplearning_tpu.cli.main import main as cli_main

    monkeypatch.setenv(faults.ENV_VAR, "preempt@2")
    rc = cli_main([
        "train", "transformer", "--max-restarts", "0",
        "--num_layers", "2", "--d_model", "32", "--num_heads", "2",
        "--d_ff", "64", "--vocab_size", "64", "--seq_len", "16",
        "--batch_size", "1", "--epochs", "1", "--steps_per_epoch", "3",
        "--compute_dtype", "float32",
        "--save_filepath", str(tmp_path / "ck"),
    ])
    assert rc == resilience.RESUMABLE_EXIT_CODE


def test_cli_train_dry_run_and_flag_passthrough(capsys):
    from distributeddeeplearning_tpu.cli.main import main as cli_main

    rc = cli_main([
        "train", "imagenet", "--max-restarts", "2", "--dry-run",
        "--epochs", "1", "--model", "resnet18",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "supervise imagenet" in out and "max_restarts=2" in out
