"""BERT fine-tune workload + tokenized-text data pipeline tests.

BASELINE.md tracked config: "BERT-base fine-tune pod-scale DP".  The CPU
mesh runs a tiny config through the FULL driver — mesh, AdamW, warmup/decay
schedule, ring attention when seq>1 — and the text TFRecord pipeline round-
trips the Example schema.
"""

from __future__ import annotations

import numpy as np
import pytest

from distributeddeeplearning_tpu.data.synthetic import SyntheticTextDataset
from distributeddeeplearning_tpu.workloads import bert

TINY = dict(
    epochs=1,
    batch_size=2,
    seq_len=16,
    num_classes=3,
    vocab_size=101,
    train_examples=32,
    num_layers=2,
    hidden_size=32,
    num_heads=4,
    intermediate_size=64,
    max_position_embeddings=16,
    compute_dtype="float32",
    dropout_rate=0.0,
)


class TestSyntheticText:
    def test_shapes_and_determinism(self):
        ds = SyntheticTextDataset(length=16, seq_len=8, vocab_size=50, seed=3)
        batches = list(ds.batches(4))
        assert len(batches) == 4
        b = batches[0]
        assert b["input"].shape == (4, 8) and b["input"].dtype == np.int32
        assert b["attention_mask"].shape == (4, 8)
        assert b["label"].shape == (4,)
        # padding positions hold pad_id
        assert (b["input"][b["attention_mask"] == 0] == 0).all()
        again = next(iter(SyntheticTextDataset(16, 8, 50, seed=3).batches(4)))
        np.testing.assert_array_equal(b["input"], again["input"])


class TestTextTfrecords:
    def test_write_read_roundtrip(self, tmp_path):
        pytest.importorskip("tensorflow")
        from distributeddeeplearning_tpu.data import text

        ds = SyntheticTextDataset(length=12, seq_len=8, vocab_size=50, seed=1)
        examples = [
            {"input": ids, "attention_mask": m, "label": lab}
            for batch in ds.batches(1)
            for ids, m, lab in zip(
                batch["input"], batch["attention_mask"], batch["label"]
            )
        ]
        n = text.write_tfrecords(
            examples, str(tmp_path), prefix="train", num_shards=3
        )
        assert n == 12
        batches = list(
            text.input_fn(
                str(tmp_path), False, 4, seq_len=8, repeat=False,
                shard_count=1, shard_index=0, prefix="train",
            )
        )
        assert sum(b["input"].shape[0] for b in batches) == 12
        got = np.sort(np.concatenate([b["label"] for b in batches]))
        want = np.sort(np.array([e["label"] for e in examples]))
        np.testing.assert_array_equal(got, want)

    def test_missing_shards_raise(self, tmp_path):
        pytest.importorskip("tensorflow")
        from distributeddeeplearning_tpu.data import text

        with pytest.raises(FileNotFoundError):
            list(text.input_fn(str(tmp_path), True, 2))


class TestBertFineTune:
    def test_dp_fine_tune_end_to_end(self, tmp_path):
        state, result = bert.main(
            **TINY, save_filepath=str(tmp_path / "ckpt")
        )
        assert result.epochs_run == 1
        assert np.isfinite(result.final_train_metrics["loss"])
        assert result.final_eval_metrics is not None
        assert int(state.step) == result.total_images // (2 * 8)

    def test_sharded_fine_tune_with_ring_attention(self):
        # dp×fsdp×tp×sp on the 8-device CPU mesh: 1×2×2×2
        state, result = bert.main(**TINY, fsdp=2, tensor=2, seq=2)
        assert np.isfinite(result.final_train_metrics["loss"])

    def test_fine_tune_with_flash_attention(self):
        # The Pallas kernel (interpret mode on CPU) through the full driver.
        state, result = bert.main(**TINY, attention="flash")
        assert np.isfinite(result.final_train_metrics["loss"])

    def test_seq_axis_rejects_non_ring_attention(self):
        with pytest.raises(ValueError, match="requires attention='ring'"):
            bert.main(**TINY, seq=2, attention="flash")

    def test_seq_len_divisibility_enforced(self):
        cfg = dict(TINY)
        cfg["seq_len"] = 10
        with pytest.raises(ValueError, match="not divisible"):
            bert.main(**cfg, seq=4)

    def test_tfrecord_input_path(self, tmp_path):
        pytest.importorskip("tensorflow")
        from distributeddeeplearning_tpu.data import text

        ds = SyntheticTextDataset(length=64, seq_len=16, vocab_size=101,
                                  num_classes=3, seed=5)
        for prefix, count in (("train", 48), ("validation", 16)):
            examples = []
            for batch in ds.batches(1):
                for ids, m, lab in zip(
                    batch["input"], batch["attention_mask"], batch["label"]
                ):
                    examples.append(
                        {"input": ids, "attention_mask": m, "label": lab}
                    )
                if len(examples) >= count:
                    break
            text.write_tfrecords(
                examples[:count], str(tmp_path), prefix=prefix, num_shards=2
            )
        cfg = dict(TINY)
        cfg.update(
            data_format="tfrecords",
            training_data_path=str(tmp_path),
            validation_data_path=str(tmp_path),
            steps_per_epoch=2,
        )
        state, result = bert.main(**cfg)
        assert np.isfinite(result.final_train_metrics["loss"])
        assert result.final_eval_metrics is not None
