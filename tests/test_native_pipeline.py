"""TF-free native input pipeline (data/native_pipeline.py).

Cross-checks the native reader + PIL + numpy path against the tf.data
pipeline on the same shards: labels must agree exactly, images must agree
closely (both implement the reference recipe; PIL and TF bilinear kernels
differ at the pixel level, so the check is distributional, not bitwise).
"""

from __future__ import annotations

import numpy as np
import pytest

from distributeddeeplearning_tpu.data import convert_tfrecords, tfrecords
from distributeddeeplearning_tpu.data.native_pipeline import native_input_fn

WNIDS = ["n01440764", "n01443537", "n02102040"]


@pytest.fixture(scope="module")
def tfrecord_dir(tmp_path_factory):
    from PIL import Image

    rng = np.random.default_rng(0)
    root = tmp_path_factory.mktemp("np-imagenet") / "train"
    for wnid in WNIDS:
        d = root / wnid
        d.mkdir(parents=True)
        for i in range(4):
            arr = rng.integers(0, 255, (48, 56, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"{wnid}_{i}.JPEG", quality=95)
    out = tmp_path_factory.mktemp("np-tfrecords")
    assert convert_tfrecords.convert_dataset(str(root), str(out), "train", 4) == 12
    assert (
        convert_tfrecords.convert_dataset(str(root), str(out), "validation", 4)
        == 12
    )
    return out


def test_eval_labels_match_tf_pipeline(tfrecord_dir):
    kwargs = dict(
        batch_size=3, num_shards=4, image_size=32,
        repeat=False, shard_count=1, shard_index=0,
    )
    native = list(
        native_input_fn(str(tfrecord_dir), False, **kwargs)
    )
    tf_batches = list(tfrecords.input_fn(str(tfrecord_dir), False, **kwargs))
    assert len(native) == len(tf_batches) == 4
    nat_labels = np.concatenate([b["label"] for b in native])
    tf_labels = np.concatenate([b["label"] for b in tf_batches])
    # eval order is deterministic in both pipelines
    assert nat_labels.tolist() == tf_labels.tolist()
    assert native[0]["image"].shape == (3, 32, 32, 3)
    assert native[0]["image"].dtype == np.float32


def test_eval_images_close_to_tf_pipeline(tfrecord_dir):
    kwargs = dict(
        batch_size=12, num_shards=4, image_size=32,
        repeat=False, shard_count=1, shard_index=0,
    )
    nat = next(native_input_fn(str(tfrecord_dir), False, **kwargs))["image"]
    tfb = next(tfrecords.input_fn(str(tfrecord_dir), False, **kwargs))["image"]
    # Same recipe, different bilinear kernels: mean abs diff stays small
    # relative to the ~[-124, 131] mean-subtracted range.
    assert np.mean(np.abs(nat - tfb)) < 10.0


def test_train_path_shuffles_and_repeats(tfrecord_dir):
    it = native_input_fn(
        str(tfrecord_dir), True, batch_size=4, num_shards=4, image_size=32,
        shard_count=1, shard_index=0, seed=7,
    )
    batches = [next(it) for _ in range(7)]  # > one epoch (12 records)
    assert all(b["image"].shape == (4, 32, 32, 3) for b in batches)
    labels = np.concatenate([b["label"] for b in batches[:3]])
    assert sorted(labels.tolist()) == sorted([1, 1, 1, 1, 2, 2, 2, 2, 3, 3, 3, 3])


def test_train_path_record_level_shuffle(tfrecord_dir):
    """Record shuffle (the tf pipeline's 10k buffer role) must reorder
    records WITHIN an epoch, not just permute files: with a buffer covering
    the epoch, the label sequence is not a concatenation of per-file runs."""
    def epoch_labels(seed):
        it = native_input_fn(
            str(tfrecord_dir), True, batch_size=4, num_shards=4,
            image_size=32, shard_count=1, shard_index=0, seed=seed,
        )
        return np.concatenate([next(it)["label"] for _ in range(3)]).tolist()

    seqs = {tuple(epoch_labels(seed)) for seed in range(4)}
    assert len(seqs) > 1  # different seeds → different orders
    # a pure file-order shuffle yields runs of 3 equal labels (3 per file);
    # record-level shuffling must break at least one such run for some seed
    def is_file_order(seq):
        return all(len(set(seq[i : i + 3])) == 1 for i in range(0, 12, 3))

    assert not all(is_file_order(list(s)) for s in seqs)


def test_gs_paths_rejected(tfrecord_dir):
    with pytest.raises(ValueError, match="local files only"):
        next(native_input_fn("gs://bucket/tfrecords", False, batch_size=2,
                             shard_count=1, shard_index=0))


def test_mixed_shard_layouts_detect_largest(tfrecord_dir, tmp_path):
    """Auto-detection with mixed -of-N layouts picks the largest count
    deterministically (a subsample left in the directory must not win)."""
    import shutil

    from distributeddeeplearning_tpu.data.tfrecords import shard_filenames

    d = tmp_path / "mixed"
    d.mkdir()
    for f in tfrecord_dir.iterdir():
        shutil.copy(f, d / f.name)
    # leave a stale 2-shard subsample beside the real 4-shard validation set
    shutil.copy(
        d / "validation-00000-of-00004", d / "validation-00000-of-00002"
    )
    shutil.copy(
        d / "validation-00001-of-00004", d / "validation-00001-of-00002"
    )
    names = shard_filenames(str(d), is_training=False, num_shards=None)
    assert len(names) == 4 and names[0].endswith("validation-00000-of-00004")


@pytest.mark.slow
def test_imagenet_workload_trains_on_native_pipeline(tfrecord_dir, tmp_path):
    """Full imagenet driver over the TF-free pipeline on the CPU mesh."""
    from distributeddeeplearning_tpu.workloads import imagenet

    state, result = imagenet.main(
        model="resnet18",
        data_format="tfrecords",
        input_pipeline="native",
        training_data_path=str(tfrecord_dir),
        validation_data_path=str(tfrecord_dir),
        epochs=1,
        steps_per_epoch=2,
        batch_size=1,
        image_size=32,
        num_classes=11,
        train_images=12,
        compute_dtype="float32",
        tensorboard_dir=str(tmp_path / "tb"),
    )
    assert result.epochs_run == 1
    assert np.isfinite(result.final_train_metrics["loss"])
    assert result.final_eval_metrics is not None


def test_host_sharding_partitions_files(tfrecord_dir):
    halves = []
    for rank in range(2):
        it = native_input_fn(
            str(tfrecord_dir), False, batch_size=2, num_shards=4,
            image_size=32, repeat=False, shard_count=2, shard_index=rank,
        )
        halves.append(np.concatenate([b["label"] for b in it]))
    assert len(halves[0]) + len(halves[1]) == 12
    combined = sorted(np.concatenate(halves).tolist())
    assert combined == sorted([1, 1, 1, 1, 2, 2, 2, 2, 3, 3, 3, 3])
