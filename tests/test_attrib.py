"""Attribution layer (ISSUE 15): program cost registry, HBM ledger,
forecast-gated admission, straggler timing, recorder dump context, and
the hardened perf-history reader.

The owner-totals-vs-live-bytes reconciliation gates run in a SUBPROCESS
(``ddlt obs attrib --check``): ``jax.live_arrays()`` in the shared
pytest process carries every other test's leftovers, so the residual is
only meaningful in a process the check owns end to end.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.obs import attrib as attrib_mod
from distributeddeeplearning_tpu.obs import ledger as ledger_mod
from distributeddeeplearning_tpu.obs.attrib import (
    ProgramCostRegistry,
    TrackedProgram,
    compute_collective_split,
    step_phase_stats,
    straggler_report,
)
from distributeddeeplearning_tpu.obs.ledger import HBMLedger
from distributeddeeplearning_tpu.obs.recorder import (
    FlightRecorder,
    register_dump_context,
)
from distributeddeeplearning_tpu.utils.roofline import program_roofline


# --- program cost registry -------------------------------------------------


class TestTrackedProgram:
    def test_records_signature_per_compile_and_resolves_cost(self):
        reg = ProgramCostRegistry()
        fn = reg.track("t.matmul", jax.jit(lambda a, b: a @ b))
        x = jnp.ones((16, 16))
        fn(x, x)
        fn(x, x)  # same shape: no new compile, no new signature
        assert len(fn.signatures) == 1
        y = jnp.ones((32, 32))
        fn(y, y)  # new shape -> new compile -> second signature
        assert len(fn.signatures) == 2
        costs = fn.collect()
        assert len(costs) == 2
        assert all(c.available for c in costs)
        # 2*n^3 model flops per matmul: the two signatures differ 8x
        flops = sorted(c.flops for c in costs)
        assert flops[0] > 0 and flops[1] == pytest.approx(
            flops[0] * 8, rel=0.01
        )

    def test_memory_analysis_on_demand(self):
        reg = ProgramCostRegistry()
        fn = reg.track("t.add", jax.jit(lambda a: a + 1.0))
        fn(jnp.ones((64,)))
        (cost,) = fn.collect(memory=True)
        assert cost.argument_bytes == 64 * 4
        assert cost.output_bytes == 64 * 4
        assert cost.temp_bytes is not None

    def test_donated_args_record_fine(self):
        # signatures abstract AFTER the call — donated (deleted) buffers
        # must still yield their aval metadata
        reg = ProgramCostRegistry()
        fn = reg.track(
            "t.donate",
            jax.jit(lambda c: {"k": c["k"] * 2}, donate_argnums=(0,)),
        )
        fn({"k": jnp.ones((8, 8))})
        assert len(fn.signatures) == 1
        (cost,) = fn.collect()
        assert cost.available

    def test_static_args_survive_relowering(self):
        reg = ProgramCostRegistry()
        fn = reg.track("t.static", jax.jit(
            lambda a, flag: a * 2 if flag else a, static_argnums=(1,)
        ))
        fn(jnp.ones((8,)), True)
        (cost,) = fn.collect()
        assert cost.available and cost.error is None

    def test_attribute_forwarding(self):
        # the program audit calls .trace/.lower and the lint pins
        # _cache_size on the wrapped jit — the wrapper must be
        # transparent to all of them
        reg = ProgramCostRegistry()
        inner = jax.jit(lambda a: a + 1)
        fn = reg.track("t.fwd", inner)
        assert fn._cache_size() == 0
        lowered = fn.lower(jax.ShapeDtypeStruct((4,), jnp.float32))
        assert "stablehlo" in lowered.as_text() or lowered is not None
        fn(jnp.ones((4,)))
        assert fn._cache_size() == 1

    def test_registry_holds_programs_weakly(self):
        import gc

        reg = ProgramCostRegistry()
        fn = reg.track("t.weak", jax.jit(lambda a: a))
        assert reg.names() == ["t.weak"]
        del fn
        gc.collect()
        assert reg.names() == []

    def test_collect_skips_never_compiled_programs(self):
        reg = ProgramCostRegistry()
        reg.track("t.nevercalled", jax.jit(lambda a: a))
        assert reg.collect() == {}

    def test_dump_table_never_lowers(self):
        # before any collect, the crash-dump attachment is the bare
        # signature inventory (mid-failure it must not trace anything)
        reg = ProgramCostRegistry()
        fn = reg.track("t.dump", jax.jit(lambda a: a * 3))
        fn(jnp.ones((4,)))
        table = reg.dump_table()
        assert table and table[0]["name"] == "t.dump"
        assert table[0]["available"] is False
        reg.collect()
        assert reg.dump_table()[0]["available"] is True


# --- HBM ledger ------------------------------------------------------------


class TestHBMLedger:
    def test_owner_totals_and_dedup(self):
        led = HBMLedger()
        a = jnp.ones((128,))  # 512 B
        b = jnp.ones((64,))   # 256 B
        holder = {"a": a, "b": b}
        led.register("one", holder, lambda h: {"a": h["a"]})
        led.register("two", holder, lambda h: {"a": h["a"], "b": h["b"]})
        snap = led.snapshot(reconcile=False)
        # leaf `a` is claimed by owner "one" first; owner "two" gets
        # only the unclaimed `b` — no byte counts twice
        assert snap["owners"]["one"]["bytes"] == 512
        assert snap["owners"]["two"]["bytes"] == 256
        assert snap["total_bytes"] == 768
        assert snap["per_device_bytes"]
        assert sum(snap["per_device_bytes"].values()) == 768

    def test_committed_overrides_and_forecast(self):
        led = HBMLedger()
        pool = {"k": jnp.ones((256,))}  # 1024 B reserved
        state = {"committed": 128}
        led.register(
            "pool", state, lambda s: pool,
            committed=lambda s: s["committed"],
        )
        snap = led.snapshot(reconcile=False)
        assert snap["owners"]["pool"]["bytes"] == 1024
        assert snap["owners"]["pool"]["committed_bytes"] == 128
        # no capacity: always admit, cheap path
        assert led.admit_ok(10**12)
        f = led.forecast(100)
        assert f["admit"] and f["capacity_bytes"] is None
        led.set_capacity(300)
        assert led.forecast(100)["admit"] is True   # 128+100 <= 300
        assert led.forecast(200)["admit"] is False  # 128+200 > 300
        state["committed"] = 300
        assert led.admit_ok(1) is False  # live committed read each time

    def test_weakref_target_drop(self):
        import gc

        led = HBMLedger()

        class Holder:
            pass

        h = Holder()
        h.tree = {"x": jnp.ones((32,))}
        led.register("gone", h, lambda o: o.tree)
        assert led.snapshot(reconcile=False)["owners"]["gone"]["bytes"] > 0
        del h
        gc.collect()
        assert "gone" not in led.snapshot(reconcile=False)["owners"]

    def test_watermarks_are_monotone(self):
        led = HBMLedger()
        holder = {"t": jnp.ones((256,))}
        led.register("w", holder, lambda h: dict(h))
        led.snapshot(reconcile=False)
        assert led.watermarks["w"] == 1024
        holder.clear()
        snap = led.snapshot(reconcile=False)
        assert snap["owners"]["w"]["bytes"] == 0
        assert snap["owners"]["w"]["peak_bytes"] == 1024  # held

    def test_export_gauges(self):
        from distributeddeeplearning_tpu.obs.registry import MetricsRegistry

        led = HBMLedger()
        led.register("g", {"t": jnp.ones((64,))}, lambda h: dict(h))
        reg = MetricsRegistry()
        led.export_gauges(reg)
        snap = reg.snapshot()
        assert snap["gauges"]["hbm.g.bytes"] == 256.0
        assert snap["gauges"]["hbm.total_bytes"] == 256.0
        assert snap["gauges"]["hbm.g.peak_bytes"] == 256.0

    def test_accounting_never_inflates_live_arrays(self):
        # the 50%-residual bug class: walking shards (or even
        # hasattr(addressable_shards)) registers tracked per-shard
        # views, inflating the live_arrays() total the ledger
        # reconciles against.  The walk must be metadata-only.
        led = HBMLedger()
        holder = {"x": jnp.ones((128, 128))}
        led.register("inflate", holder, lambda h: dict(h))
        import gc

        gc.collect()
        before = len(jax.live_arrays())
        for _ in range(3):
            led.snapshot(reconcile=True)
        gc.collect()
        assert len(jax.live_arrays()) == before


# --- forecast-gated admission (the acceptance-criterion test) --------------


@pytest.mark.timeout(240)
class TestForecastAdmission:
    def test_headroom_zero_backpressures_never_ooms(self):
        """Drive predicted headroom to ~one request: every request still
        completes (backpressure queues, never a mid-decode OOM path),
        and committed bytes never exceed the configured capacity."""
        from distributeddeeplearning_tpu.models.pipelined_transformer import (
            init_params,
        )
        from distributeddeeplearning_tpu.serve.engine import (
            PagedInferenceEngine,
            _register_engine_owners,
        )
        from distributeddeeplearning_tpu.serve.scheduler import (
            ContinuousBatchingScheduler,
            synthetic_requests,
        )

        params = init_params(
            jax.random.key(0), max_len=48, num_layers=2, d_model=32,
            num_heads=4, d_ff=64, vocab_size=211,
        )
        engine = PagedInferenceEngine(
            params, num_heads=4, batch_slots=4, max_seq=48,
            page_size=8, prefill_chunk=8,
        )
        led = HBMLedger()
        _register_engine_owners(engine, led)
        reqs = synthetic_requests(
            5, vocab_size=211, max_prompt=16,
            rng=np.random.default_rng(0),
        )
        new_tokens = 4
        worst = max(
            engine.admit_bytes(len(r.prompt), new_tokens) for r in reqs
        )
        capacity = led.committed_bytes() + worst + engine._page_bytes
        led.set_capacity(capacity)
        max_in_use = 0

        def on_step(_step):
            nonlocal max_in_use
            max_in_use = max(max_in_use, engine.allocator.pages_in_use)

        results, report = ContinuousBatchingScheduler(
            engine, max_new_tokens=new_tokens, hbm_ledger=led,
        ).run(list(reqs), on_step=on_step)
        assert report.errors == 0
        assert len(results) == len(reqs)
        assert all(r.finish_reason in ("eos", "length") for r in results)
        # the forecast held: committed demand never exceeded capacity
        assert 0 < led.peak_committed_bytes <= capacity
        # and the pool genuinely serialized: free slots/pages existed
        # for more concurrency than the ledger allowed
        assert max_in_use * engine._page_bytes <= worst + engine._page_bytes

    def test_no_capacity_is_a_noop(self):
        led = HBMLedger()
        assert led.admit_ok(10**15)


# --- recorder dump context -------------------------------------------------


class TestDumpContext:
    def test_dump_carries_ledger_and_program_costs(self):
        rec = FlightRecorder(capacity=16)
        rec.record_event("warmup")
        payload = rec.dump("unit_test")
        # obs.ledger / obs.attrib registered their providers at import
        assert "hbm_ledger" in payload
        assert isinstance(payload["hbm_ledger"], dict)
        assert "owners" in payload["hbm_ledger"]
        assert "program_costs" in payload
        assert isinstance(payload["program_costs"], list)

    def test_broken_provider_never_breaks_dump(self):
        def boom():
            raise RuntimeError("mid-crash provider")

        register_dump_context("broken_ctx", boom)
        try:
            payload = FlightRecorder(capacity=4).dump("unit_test")
            assert payload["broken_ctx"] is None
        finally:
            register_dump_context("broken_ctx", None)

    def test_explicit_context_wins_over_provider(self):
        register_dump_context("clash", lambda: "from-provider")
        try:
            payload = FlightRecorder(capacity=4).dump(
                "unit_test", clash="explicit"
            )
            assert payload["clash"] == "explicit"
        finally:
            register_dump_context("clash", None)


# --- straggler / clock-skew ------------------------------------------------


def _make_shard(process_name, pid, spans, epoch_shift_s=0.0):
    """A synthetic Chrome-trace shard: ``spans`` = [(name, ts_us,
    dur_us)], with the wall epoch optionally skewed."""
    import time

    return {
        "traceEvents": [
            {
                "ph": "M", "name": "process_name", "pid": pid,
                "args": {"name": process_name},
            },
        ] + [
            {
                "ph": "X", "name": name, "cat": "host", "pid": pid,
                "tid": 1, "ts": ts, "dur": dur, "args": {},
            }
            for name, ts, dur in spans
        ],
        "metadata": {
            "tracer_epoch_unix_s": time.time() + epoch_shift_s,
            "host_pids": [pid],
            "process_name": process_name,
        },
    }


class TestStragglerTiming:
    def test_slowest_host_attribution(self):
        fast = _make_shard("host-a", 11, [
            ("train/step", 0.0, 1000.0),
            ("train/step", 2000.0, 1200.0),
        ])
        slow = _make_shard("host-b", 22, [
            ("train/step", 0.0, 3000.0),
            ("train/step", 4000.0, 3400.0),
        ])
        rep = straggler_report([fast, slow], phases=("train/step",))
        phase = rep["phases"]["train/step"]
        assert phase["slowest_host"] == "host-b"
        assert phase["fastest_host"] == "host-a"
        assert phase["skew_pct"] == pytest.approx(
            (3200.0 - 1100.0) / 1100.0 * 100.0, abs=0.01
        )
        assert rep["negative_spans"] == 0

    def test_wall_clock_skew_cannot_corrupt_durations_or_stats(self):
        # the satellite pin: durations are single-clock measurements, so
        # an arbitrary wall-clock offset between hosts changes NOTHING
        # in the per-host table and can never make a duration negative
        spans_a = [("serve/decode_step", 100.0, 500.0)]
        spans_b = [("serve/decode_step", 100.0, 900.0)]
        plain = straggler_report(
            [_make_shard("a", 1, spans_a), _make_shard("b", 2, spans_b)],
            phases=("serve/decode_step",),
        )
        skewed = straggler_report(
            [
                _make_shard("a", 1, spans_a, epoch_shift_s=-3600.0),
                _make_shard("b", 2, spans_b, epoch_shift_s=+7200.0),
            ],
            phases=("serve/decode_step",),
        )
        assert plain["phases"] == skewed["phases"]
        assert skewed["negative_spans"] == 0

    def test_phase_filter(self):
        shard = _make_shard("a", 1, [
            ("train/step", 0.0, 10.0),
            ("some/other_span", 0.0, 10.0),
        ])
        stats = step_phase_stats(
            shard["traceEvents"], phases=("train/step",)
        )
        assert set(stats) == {"train/step"}

    def test_colliding_pids_stay_separate_hosts(self):
        # two containerized workers on different machines can BOTH be
        # pid 1 — the exact collision merge_fleet_trace remaps; the
        # straggler table must keep them separate hosts, not average
        # them into one fictional row that hides the real straggler
        fast = _make_shard("host-a", 1, [("train/step", 0.0, 1000.0)])
        slow = _make_shard("host-b", 1, [("train/step", 0.0, 3000.0)])
        report = straggler_report([fast, slow])
        assert report["hosts"] == ["host-a", "host-b"]
        phase = report["phases"]["train/step"]
        assert phase["slowest_host"] == "host-b"
        assert phase["fastest_host"] == "host-a"
        assert phase["skew_pct"] == 200.0

    def test_raw_event_list_and_bare_dict_shards(self):
        # a shard may be a raw Chrome-trace event LIST (the JSON-array
        # flavor of the format) or a dict without traceEvents — neither
        # may crash the report
        raw = _make_shard("host-c", 7, [("train/step", 0.0, 500.0)])
        report = straggler_report([raw["traceEvents"]])
        assert report["hosts"] == ["host-c"]
        assert "train/step" in report["phases"]
        assert straggler_report([{"displayTimeUnit": "ms"}])["hosts"] == []


class TestMergeUnderSkew:
    """Cross-process trace-span merging under clock skew (obs/fleet.py
    + obs/trace.py): offsets shift timestamps only — one host's span
    ORDER survives, durations stay non-negative, and a handshake offset
    restores cross-host order that raw skewed walls would scramble."""

    def _merge(self, router, shards, **kw):
        from distributeddeeplearning_tpu.obs.fleet import merge_fleet_trace

        return merge_fleet_trace(router, shards, **kw)

    def test_skew_preserves_per_host_order_and_durations(self):
        import time

        router = {
            "traceEvents": [],
            "metadata": {
                "tracer_epoch_unix_s": time.time(), "host_pids": [1],
            },
        }
        # worker wall clock 90 s ahead; its own spans are strictly
        # ordered A -> B on its clock
        shard = _make_shard("worker", 33, [
            ("serve/decode_step", 1000.0, 400.0),
            ("serve/decode_step", 2000.0, 450.0),
        ], epoch_shift_s=90.0)
        merged = self._merge(router, [shard])
        spans = [
            e for e in merged["traceEvents"]
            if e.get("ph") == "X" and e.get("pid") == 33
        ]
        assert len(spans) == 2
        assert spans[0]["ts"] < spans[1]["ts"]  # order survives
        assert spans[0]["ts"] + spans[0]["dur"] <= spans[1]["ts"]
        assert all(e["dur"] >= 0 for e in spans)
        # the epoch offset landed them ~90 s later on the router clock
        assert spans[0]["ts"] == pytest.approx(90e6 + 1000.0, abs=5e5)

    def test_handshake_offset_restores_cross_host_order(self):
        import time

        epoch = time.time()
        router = {
            "traceEvents": [
                {"ph": "X", "name": "router/admit", "pid": 1, "tid": 1,
                 "ts": 0.0, "dur": 100.0, "args": {}},
            ],
            "metadata": {"tracer_epoch_unix_s": epoch, "host_pids": [1]},
        }
        # worker span REALLY happened 5 ms after the router admit, but
        # its wall epoch claims an hour earlier — epoch alignment alone
        # would sort it before the admit; the measured handshake offset
        # (+5000 us onto the router clock) must win
        shard = _make_shard("worker", 44, [
            ("serve/prefill_chunk", 0.0, 2000.0),
        ], epoch_shift_s=-3600.0)
        merged = self._merge(router, [shard], offsets_us={44: 5000.0})
        span = next(
            e for e in merged["traceEvents"]
            if e.get("ph") == "X" and e.get("name") == "serve/prefill_chunk"
        )
        assert span["ts"] == pytest.approx(5000.0)
        assert span["ts"] > 0.0  # lands after the admit span's start
        assert merged["metadata"]["shards"][0]["offset_source"] == (
            "handshake"
        )
        assert span["dur"] == 2000.0  # never rescaled by alignment


# --- roofline / split math -------------------------------------------------


class TestRooflineMath:
    def test_program_roofline_with_peaks(self):
        out = program_roofline(
            1e12, 1e9, 0.01, peak_tflops=100.0, peak_hbm_gbps=1000.0,
        )
        assert out["roofline_available"]
        assert out["achieved_tflops"] == pytest.approx(100.0)
        assert out["pct_of_compute_roofline"] == pytest.approx(1.0)
        # compute time 0.01 s vs bandwidth time 0.000001 s
        assert out["bound"] == "compute"
        assert out["roofline_s"] == pytest.approx(0.01)
        assert out["efficiency"] == pytest.approx(1.0)

    def test_program_roofline_without_peaks(self):
        out = program_roofline(1e9, 1e9, 0.5)
        assert out["roofline_available"] is False
        assert "pct_of_compute_roofline" not in out
        assert out["achieved_gbps"] == pytest.approx(2.0)

    def test_compute_collective_split(self):
        out = compute_collective_split(
            1e12, 1e9, peak_flops=1e12, interconnect_gbps=1.0,
            measured_step_s=4.0,
        )
        assert out["estimated"] is True
        assert out["compute_s"] == pytest.approx(1.0)
        assert out["collective_s"] == pytest.approx(1.0)
        assert out["compute_fraction"] == pytest.approx(0.5)
        assert out["unexplained_s"] == pytest.approx(3.0)

    def test_reference_peaks_never_mix_sources(self):
        # the "device" label requires BOTH ceilings from the real chip's
        # datasheet tables — a chip with a known compute peak must not be
        # paired with another chip's memory bandwidth (a v5p roofline
        # built on v5e's 819 GB/s would flip compute-bound programs to
        # "hbm-bandwidth"); on CPU both lookups miss and the v5e
        # nominals are returned explicitly labeled as reference numbers
        from types import SimpleNamespace

        from distributeddeeplearning_tpu.obs.attrib import reference_peaks
        from distributeddeeplearning_tpu.utils.hardware import (
            peak_bf16_flops,
            peak_hbm_gbps,
        )

        tflops, gbps, source = reference_peaks()
        assert source == "v5e-nominal-reference"  # CPU backend
        assert (tflops, gbps) == (197.0, 819.0)
        v5p = SimpleNamespace(device_kind="TPU v5p")
        assert peak_hbm_gbps(v5p) == 2765.0
        assert peak_bf16_flops(v5p) == 459e12
        assert peak_hbm_gbps(SimpleNamespace(device_kind="cpu")) is None


# --- hardened history reader ------------------------------------------------


class TestHistoryHardening:
    def _write(self, path, payload):
        with open(path, "w") as f:
            if isinstance(payload, str):
                f.write(payload)
            else:
                json.dump(payload, f)

    def _mk(self, tmp, r02_value=100.0):
        self._write(tmp / "PERF_r01.json", {
            "metric": "tok", "value": 100.0, "unit": "tok/s",
            "decode_tokens_per_sec": 100.0,
        })
        self._write(tmp / "PERF_r02.json", {
            "metric": "tok", "value": r02_value, "unit": "tok/s",
            "decode_tokens_per_sec": r02_value,
        })
        # a partially-written artifact (writer died mid-dump)
        self._write(tmp / "PERF_r03.json", '{"metric": "tok", "val')

    def test_truncated_artifact_skipped_with_warning_gate_green(self, tmp_path):
        from distributeddeeplearning_tpu.obs.history import run_history

        self._mk(tmp_path)
        rc, out = run_history(str(tmp_path), gate=True)
        assert rc == 0, out
        assert "skipped malformed artifact" in out
        assert "PERF_r03.json" in out

    def test_gate_still_red_on_genuine_regression(self, tmp_path):
        from distributeddeeplearning_tpu.obs.history import run_history

        self._mk(tmp_path, r02_value=50.0)  # -50% decode throughput
        rc, out = run_history(str(tmp_path), gate=True)
        assert rc == 1
        assert "skipped malformed artifact" in out
        assert "REGRESSION" in out

    def test_empty_container_treated_as_malformed(self, tmp_path):
        from distributeddeeplearning_tpu.obs.history import run_history

        self._mk(tmp_path)
        self._write(tmp_path / "PERF_r04.json", "{}")
        rc, out = run_history(str(tmp_path), gate=True)
        assert rc == 0, out
        assert "PERF_r04.json" in out

    def test_new_tolerances_registered(self):
        from distributeddeeplearning_tpu.obs.history import TOLERANCES

        assert "unaccounted_hbm_pct" in TOLERANCES
        assert TOLERANCES["unaccounted_hbm_pct"].higher_is_better is False
        assert "programs_covered" in TOLERANCES
        assert TOLERANCES["programs_covered"].higher_is_better is True

    def test_programs_covered_shrink_gates_red(self, tmp_path):
        from distributeddeeplearning_tpu.obs.history import run_history

        self._write(tmp_path / "A_r01.json", {
            "metric": "m", "value": 1.0, "unit": "u",
            "programs_covered": 10,
        })
        self._write(tmp_path / "A_r02.json", {
            "metric": "m", "value": 1.0, "unit": "u",
            "programs_covered": 9,
        })
        rc, out = run_history(str(tmp_path), gate=True)
        assert rc == 1
        assert "programs_covered" in out


# --- artifact schema -------------------------------------------------------


class TestAttribSchema:
    def _load_committed(self):
        path = os.path.join(os.path.dirname(__file__), "..",
                            "ATTRIB_r18.json")
        with open(path) as f:
            return json.load(f)

    def test_committed_artifact_validates(self):
        from distributeddeeplearning_tpu.obs.schema import (
            validate_attrib_payload,
        )

        validate_attrib_payload(self._load_committed())

    def test_residual_over_limit_rejected(self):
        from distributeddeeplearning_tpu.obs.schema import (
            SchemaError,
            validate_attrib_payload,
        )

        bad = self._load_committed()
        bad["unaccounted_hbm_pct"] = 40.0
        with pytest.raises(SchemaError, match="residual gate"):
            validate_attrib_payload(bad)

    def test_negative_spans_rejected(self):
        from distributeddeeplearning_tpu.obs.schema import (
            SchemaError,
            validate_attrib_payload,
        )

        bad = self._load_committed()
        bad["straggler"]["negative_spans"] = 2
        with pytest.raises(SchemaError, match="negative"):
            validate_attrib_payload(bad)

    def test_missing_gate_rejected(self):
        from distributeddeeplearning_tpu.obs.schema import (
            SchemaError,
            validate_attrib_payload,
        )

        bad = self._load_committed()
        del bad["gates"]["forecast_backpressure"]
        with pytest.raises(SchemaError, match="forecast_backpressure"):
            validate_attrib_payload(bad)


# --- fleet watermark lift --------------------------------------------------


class TestFleetWatermarks:
    def test_hbm_gauges_lifted_per_replica(self):
        from distributeddeeplearning_tpu.serve.fleet import _hbm_watermarks

        states = [
            {
                "replica_id": 0, "pid": 100,
                "gauges": {
                    "hbm.kv_pages.bytes": {"value": 4096.0},
                    "hbm.kv_pages.peak_bytes": {"value": 8192.0},
                    "serve.tokens_per_sec": {"value": 12.0},
                },
            },
            {"replica_id": 1, "pid": 101, "gauges": {}},
        ]
        wm = _hbm_watermarks(states)
        assert wm == {
            "replica0-100": {
                "hbm.kv_pages.bytes": 4096.0,
                "hbm.kv_pages.peak_bytes": 8192.0,
            },
        }


# --- trainer registration --------------------------------------------------


class TestTrainerLedgerOwners:
    def test_register_hbm_owners_reads_live_state(self):
        from distributeddeeplearning_tpu.train.loop import Trainer

        led = ledger_mod.set_ledger(HBMLedger())
        try:
            t = Trainer.__new__(Trainer)

            class FakeState:
                params = {"w": jnp.ones((64,))}
                opt_state = {"m": jnp.ones((64,))}
                batch_stats = {}

            t._obs_state = FakeState()
            t._register_hbm_owners()
            t._register_hbm_owners()  # idempotent
            snap = led.snapshot(reconcile=False)
            assert snap["owners"]["params"]["bytes"] == 256
            assert snap["owners"]["opt_state"]["bytes"] == 256
            # keep `t` alive through the snapshot (weakref provider)
            assert t._hbm_registered
        finally:
            ledger_mod.set_ledger(HBMLedger())


# --- the hermetic gate (subprocess: owns its own live_arrays) ---------------


@pytest.mark.timeout(280)
def test_obs_attrib_check_green_in_subprocess():
    """``ddlt obs attrib --check`` — the make obs-gate half: every
    tracked program resolves a cost row on the CPU backend, ledger
    owner totals reconcile against the process's live device bytes
    within 1%, and the unaccounted-HBM residual stays under 5%."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("DDLT_FAULTS", None)
    proc = subprocess.run(
        [sys.executable, "-m", "distributeddeeplearning_tpu.cli.main",
         "obs", "attrib", "--check"],
        env=env, text=True, capture_output=True, timeout=260,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict["gates"]["programs_covered"] is True
    assert verdict["gates"]["owner_totals_match_live"] is True
    assert verdict["gates"]["residual_under_limit"] is True
    assert verdict["unaccounted_hbm_pct"] <= 5.0
