"""Pipeline-parallel transformer (models/pipelined_transformer.py).

The model-level consumer of the pipe axis: forward and gradients through
``forward_pipelined`` must match the sequential scan-over-layers path, and
a few SGD steps must actually reduce the causal-LM loss.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.models.pipelined_transformer import (
    forward,
    forward_pipelined,
    init_params,
    next_token_loss,
)
from distributeddeeplearning_tpu.parallel import MeshSpec, create_mesh

CFG = dict(num_layers=4, d_model=32, num_heads=4, d_ff=64, vocab_size=97,
           max_len=16)
HEADS = CFG["num_heads"]


@pytest.fixture(scope="module")
def setup():
    params = init_params(jax.random.key(0), **CFG)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, CFG["vocab_size"], (8, 16)),
        jnp.int32,
    )
    return params, tokens


def test_pipelined_forward_matches_sequential(setup):
    params, tokens = setup
    mesh = create_mesh(MeshSpec(pipe=2))
    want = forward(params, tokens, num_heads=HEADS)
    got = forward_pipelined(
        params, tokens, num_heads=HEADS, mesh=mesh, num_microbatches=2
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_pipelined_gradients_match_sequential(setup):
    params, tokens = setup
    mesh = create_mesh(MeshSpec(pipe=4))

    def loss_seq(p):
        return next_token_loss(forward(p, tokens, num_heads=HEADS), tokens)

    def loss_pipe(p):
        return next_token_loss(
            forward_pipelined(
                p, tokens, num_heads=HEADS, mesh=mesh, num_microbatches=2
            ),
            tokens,
        )

    g_seq = jax.grad(loss_seq)(params)
    g_pipe = jax.grad(loss_pipe)(params)
    flat_seq = jax.tree_util.tree_leaves(g_seq)
    flat_pipe = jax.tree_util.tree_leaves(g_pipe)
    for a, b in zip(flat_pipe, flat_seq):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=1e-3
        )


def test_pipelined_training_reduces_loss(setup):
    params, tokens = setup
    mesh = create_mesh(MeshSpec(pipe=2, data=4))

    @jax.jit
    def step(p):
        def loss(p):
            return next_token_loss(
                forward_pipelined(
                    p, tokens, num_heads=HEADS, mesh=mesh, num_microbatches=2
                ),
                tokens,
            )

        l, g = jax.value_and_grad(loss)(p)
        return jax.tree.map(lambda w, gw: w - 0.5 * gw, p, g), l

    losses = []
    p = params
    for _ in range(5):
        p, l = step(p)
        losses.append(float(l))
    assert losses[-1] < losses[0], losses
    assert all(np.isfinite(l) for l in losses)


def test_layer_count_must_divide_stages(setup):
    params, tokens = setup
    mesh = create_mesh(MeshSpec(pipe=8))  # 4 layers / 8 stages
    with pytest.raises(ValueError, match="not divisible"):
        forward_pipelined(
            params, tokens, num_heads=HEADS, mesh=mesh, num_microbatches=1
        )


def test_bf16_params_keep_scan_carry_dtype():
    """Regression: the dense attention path promoted a bf16 residual stream
    to f32 (f32 softmax output flowed into the stream), breaking the
    scan-over-layers carry dtype contract — caught by the round-4 LM bench.
    Both attention paths must run a full forward+grad in bf16."""
    import jax
    import jax.flatten_util
    import jax.numpy as jnp
    import numpy as np

    from distributeddeeplearning_tpu.models.pipelined_transformer import (
        forward,
        init_params,
        next_token_loss,
    )

    params = init_params(
        jax.random.key(0), num_layers=2, d_model=64, num_heads=4, d_ff=128,
        vocab_size=97, max_len=32,
    )
    bf16_params = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16), params
    )
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, 97, (2, 32)), jnp.int32
    )
    for attention in ("dense", "flash"):
        logits = forward(bf16_params, toks, num_heads=4, attention=attention)
        assert np.isfinite(np.asarray(logits, np.float32)).all()
        grads = jax.grad(
            lambda p, a=attention: next_token_loss(
                forward(p, toks, num_heads=4, attention=a).astype(
                    jnp.float32
                ),
                toks,
            )
        )(bf16_params)
        flat, _ = jax.flatten_util.ravel_pytree(
            jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads)
        )
        assert np.isfinite(np.asarray(flat)).all()


def test_remat_matches_no_remat():
    """remat=True must be a pure memory/time trade: identical logits and
    gradients to the plain scan (jax.checkpoint changes scheduling, not
    math)."""
    import jax
    import jax.flatten_util
    import jax.numpy as jnp
    import numpy as np

    from distributeddeeplearning_tpu.models.pipelined_transformer import (
        forward,
        init_params,
        next_token_loss,
    )

    params = init_params(
        jax.random.key(2), num_layers=3, d_model=48, num_heads=2, d_ff=96,
        vocab_size=89, max_len=24,
    )
    toks = jnp.asarray(
        np.random.default_rng(5).integers(0, 89, (2, 24)), jnp.int32
    )

    def loss(p, remat):
        return next_token_loss(
            forward(p, toks, num_heads=2, remat=remat), toks
        )

    np.testing.assert_allclose(
        float(loss(params, False)), float(loss(params, True)), rtol=1e-6
    )
    g0, _ = jax.flatten_util.ravel_pytree(
        jax.grad(lambda p: loss(p, False))(params)
    )
    g1, _ = jax.flatten_util.ravel_pytree(
        jax.grad(lambda p: loss(p, True))(params)
    )
    np.testing.assert_allclose(
        np.asarray(g0), np.asarray(g1), atol=1e-6, rtol=1e-5
    )


@pytest.mark.parametrize("loss_chunk", [None, 5, 23])
def test_per_token_loss_matches_full_logits(loss_chunk):
    """The chunked head-matmul+CE (per_token_loss) must equal the one-shot
    next_token_loss(forward(...)) in value and gradients — the fusion is a
    memory transform, not a different loss."""
    import jax.flatten_util

    from distributeddeeplearning_tpu.models.pipelined_transformer import (
        per_token_loss,
    )

    params = init_params(
        jax.random.key(4), num_layers=2, d_model=32, num_heads=2, d_ff=64,
        vocab_size=131, max_len=24,
    )
    toks = jnp.asarray(
        np.random.default_rng(9).integers(0, 131, (2, 24)), jnp.int32
    )  # s-1 = 23: chunk 23 = single chunk, chunk 5 would not divide -> use 23
    if loss_chunk == 5:
        toks = toks[:, :21]  # s-1 = 20, divisible by 5

    def full(p):
        return next_token_loss(forward(p, toks, num_heads=2), toks)

    def chunked(p):
        return per_token_loss(
            p, toks, num_heads=2, loss_chunk=loss_chunk
        ).mean()

    np.testing.assert_allclose(
        float(full(params)), float(chunked(params)), rtol=1e-6
    )
    g0, _ = jax.flatten_util.ravel_pytree(jax.grad(full)(params))
    g1, _ = jax.flatten_util.ravel_pytree(jax.grad(chunked)(params))
    np.testing.assert_allclose(
        np.asarray(g0), np.asarray(g1), atol=1e-6, rtol=1e-5
    )


def test_per_token_loss_chunk_must_divide():
    from distributeddeeplearning_tpu.models.pipelined_transformer import (
        per_token_loss,
    )

    params = init_params(
        jax.random.key(4), num_layers=2, d_model=32, num_heads=2, d_ff=64,
        vocab_size=131, max_len=24,
    )
    toks = jnp.zeros((1, 24), jnp.int32)
    with pytest.raises(ValueError, match="loss_chunk"):
        per_token_loss(params, toks, num_heads=2, loss_chunk=7)


def test_zero3_pipelined_matches_sequential():
    """pipe×fsdp with zero3_axis: stage weights width-sharded over fsdp and
    all-gathered per tick must reproduce the sequential forward AND its
    gradients exactly (the gather reconstructs the full weights)."""
    mesh = create_mesh(MeshSpec(pipe=2, fsdp=2))  # data absorbs the rest
    params = init_params(
        jax.random.key(11), num_layers=4, d_model=32, num_heads=2,
        d_ff=64, vocab_size=64, max_len=16,
    )
    toks = jnp.asarray(
        np.random.default_rng(5).integers(0, 64, (8, 16)), jnp.int32
    )

    def run_pipe(p):
        return forward_pipelined(
            p, toks, num_heads=2, mesh=mesh, num_microbatches=2,
            zero3_axis="fsdp",
        )

    got = run_pipe(params)
    want = forward(params, toks, num_heads=2)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-5
    )

    g_pipe = jax.grad(lambda p: (run_pipe(p) ** 2).mean())(params)
    g_seq = jax.grad(lambda p: (forward(p, toks, num_heads=2) ** 2).mean())(
        params
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        g_pipe,
        g_seq,
    )


def test_zero3_wires_param_partition(monkeypatch):
    """forward_pipelined(zero3_axis=...) must hand pipeline_apply a width
    param_partition (the in-stage ZeRO-3 mechanism) and None without it —
    the wiring a boundary-reshard regression would silently drop."""
    from distributeddeeplearning_tpu.ops import pipeline as pipeline_mod

    captured = {}
    real = pipeline_mod.pipeline_apply

    def spy(*args, **kwargs):
        captured["param_partition"] = kwargs.get("param_partition")
        return real(*args, **kwargs)

    monkeypatch.setattr(pipeline_mod, "pipeline_apply", spy)
    mesh = create_mesh(MeshSpec(pipe=2, fsdp=2))
    params = init_params(
        jax.random.key(0), num_layers=2, d_model=32, num_heads=2, d_ff=64,
        vocab_size=64, max_len=16,
    )
    toks = jnp.zeros((8, 16), jnp.int32)

    forward_pipelined(
        params, toks, num_heads=2, mesh=mesh, num_microbatches=2,
        zero3_axis="fsdp",
    )
    part = captured["param_partition"]
    assert part["qkv"] == (None, None, "fsdp")
    assert part["proj"] == (None, "fsdp", None)
    assert part["w_in"] == (None, None, "fsdp")
    assert part["w_out"] == (None, "fsdp", None)
    assert part["ln1"] is None and part["ln2"] is None

    forward_pipelined(
        params, toks, num_heads=2, mesh=mesh, num_microbatches=2,
    )
    assert captured["param_partition"] is None


def test_zero3_rejects_indivisible_width():
    import pytest

    mesh = create_mesh(MeshSpec(pipe=2, fsdp=4))
    params = init_params(
        jax.random.key(0), num_layers=2, d_model=6, num_heads=2, d_ff=10,
        vocab_size=64, max_len=16,
    )
    with pytest.raises(ValueError, match="must divide"):
        forward_pipelined(
            params, jnp.zeros((8, 16), jnp.int32), num_heads=2, mesh=mesh,
            num_microbatches=2, zero3_axis="fsdp",
        )
