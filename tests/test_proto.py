"""TF-free Example encoder / TFRecord writer (data/proto.py).

Compatibility is pinned in both directions: records written by
``proto.RecordWriter`` + ``encode_example`` must parse with TensorFlow's
own ``tf.io.parse_single_example`` / ``TFRecordDataset`` (the reference
reader's stack) AND with the in-repo C walker (``data/_native.py``), since
the converter schema (``convert_imagenet_to_tf_records.py:111-146``) is the
interchange contract both sides rely on.
"""

import numpy as np
import pytest

from distributeddeeplearning_tpu.data._native import (
    RecordReader,
    example_bytes,
    example_int64,
)
from distributeddeeplearning_tpu.data.proto import (
    RecordWriter,
    encode_example,
)

tf = pytest.importorskip("tensorflow")


FEATURES = {
    "image/encoded": b"\xff\xd8fakejpeg\xff\xd9",
    "image/class/label": 417,
    "image/class/synset": "n02123045",
    "image/format": "JPEG",
    "image/channels": 3,
}


def test_encode_parses_with_tensorflow():
    ex = encode_example(FEATURES)
    parsed = tf.io.parse_single_example(
        ex,
        {
            "image/encoded": tf.io.FixedLenFeature([], tf.string, ""),
            "image/class/label": tf.io.FixedLenFeature([], tf.int64, -1),
            "image/class/synset": tf.io.FixedLenFeature([], tf.string, ""),
            "image/channels": tf.io.FixedLenFeature([], tf.int64, -1),
        },
    )
    assert parsed["image/encoded"].numpy() == FEATURES["image/encoded"]
    assert int(parsed["image/class/label"]) == 417
    assert parsed["image/class/synset"].numpy() == b"n02123045"
    assert int(parsed["image/channels"]) == 3


def test_encode_parses_with_native_walker():
    ex = encode_example(FEATURES)
    assert example_bytes(ex, "image/encoded") == FEATURES["image/encoded"]
    assert example_int64(ex, "image/class/label") == 417
    assert example_bytes(ex, "image/class/synset") == b"n02123045"
    assert example_bytes(ex, "missing/key") is None


def test_negative_and_large_int64():
    ex = encode_example({"a": -5, "b": 2**62})
    parsed = tf.io.parse_single_example(
        ex,
        {
            "a": tf.io.FixedLenFeature([], tf.int64),
            "b": tf.io.FixedLenFeature([], tf.int64),
        },
    )
    assert int(parsed["a"]) == -5
    assert int(parsed["b"]) == 2**62
    assert example_int64(ex, "a") == -5


def test_float_and_multivalue_lists():
    ex = encode_example({"f": [1.5, -2.25], "i": [1, 2, 3], "s": [b"x", b"y"]})
    parsed = tf.io.parse_single_example(
        ex,
        {
            "f": tf.io.FixedLenFeature([2], tf.float32),
            "i": tf.io.FixedLenFeature([3], tf.int64),
            "s": tf.io.FixedLenFeature([2], tf.string),
        },
    )
    np.testing.assert_allclose(parsed["f"].numpy(), [1.5, -2.25])
    assert list(parsed["i"].numpy()) == [1, 2, 3]
    assert list(parsed["s"].numpy()) == [b"x", b"y"]


def test_record_writer_reads_back_with_tf_and_native(tmp_path):
    path = str(tmp_path / "probe.tfrecord")
    payloads = [encode_example({"n": i, "blob": bytes([i]) * i}) for i in range(1, 5)]
    with RecordWriter(path) as w:
        for p in payloads:
            w.write(p)

    # TF reader (CRC-checked by TF itself).
    tf_records = list(tf.data.TFRecordDataset([path]).as_numpy_iterator())
    assert tf_records == payloads

    # Native reader with CRC verification on.
    native_records = list(RecordReader(path, verify=True))
    assert [bytes(r) for r in native_records] == payloads
    assert [example_int64(r, "n") for r in native_records] == [1, 2, 3, 4]


def test_rejects_unsupported_types():
    with pytest.raises(TypeError):
        encode_example({"x": {"nested": 1}})
    with pytest.raises(ValueError):
        encode_example({"x": []})
