"""The transformer-LM workload (workloads/transformer.py) — the CLI-launchable
consumer of the pipe axis (VERDICT r02 item 4): pipe=2 GPipe training through
the standard Trainer, equivalence with the pipe=1 scan-over-layers path, and
the flag surface via the workload runner."""

import numpy as np
import pytest

from distributeddeeplearning_tpu.workloads.transformer import main as lm_main

TINY = dict(
    epochs=1,
    batch_size=2,
    seq_len=16,
    vocab_size=64,
    num_layers=4,
    d_model=32,
    num_heads=2,
    d_ff=64,
    train_examples=64,
    compute_dtype="float32",
    resume=False,
    distributed=False,
)


def test_pipelined_lm_trains_and_evaluates():
    state, fit = lm_main(pipe=2, num_microbatches=2, **TINY)
    # pipe=2 leaves 4 data shards: global batch 2*4=8 -> 8 steps/epoch
    assert int(state.step) == fit.epochs_run * 8
    assert np.isfinite(fit.final_train_metrics["loss"])
    assert fit.final_eval_metrics is not None
    assert {"loss", "top1", "perplexity"} <= set(fit.final_eval_metrics)


def test_pipe2_matches_pipe1_update():
    """One epoch over the same synthetic stream: GPipe over 2 stages must
    produce the same params as the sequential scan (same seed, fp32)."""
    cfg1 = dict(TINY, batch_size=2)   # global batch 2*8 = 16
    cfg2 = dict(TINY, batch_size=4)   # global batch 4*4 = 16 (pipe takes 2)
    s1, _ = lm_main(pipe=1, **cfg1)
    s2, _ = lm_main(pipe=2, num_microbatches=2, **cfg2)
    import jax

    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
        ),
        s1.params,
        s2.params,
    )


def test_microbatch_divisibility_rejected():
    with pytest.raises(ValueError, match="num_microbatches"):
        lm_main(pipe=2, num_microbatches=3, **TINY)


def test_layers_divisibility_rejected():
    bad = dict(TINY)
    bad["num_layers"] = 5
    with pytest.raises(ValueError, match="not divisible by pipe"):
        lm_main(pipe=2, num_microbatches=2, **bad)


def test_runner_flag_surface():
    """The fire-equivalent runner parses --pipe/--num_microbatches."""
    import sys

    from distributeddeeplearning_tpu.workloads._runner import run_from_argv

    argv = sys.argv
    sys.argv = ["transformer"] + [
        f"--{k}={v}" for k, v in TINY.items()
    ] + ["--pipe=2", "--num_microbatches=2"]
    try:
        state, fit = run_from_argv(lm_main)
    finally:
        sys.argv = argv
    assert np.isfinite(fit.final_train_metrics["loss"])


def test_lm_flash_attention_flag_trains():
    """--attention flash routes the workload through the causal Pallas
    kernel (interpret mode on CPU); loss finite, same step count."""
    state, fit = lm_main(attention="flash", **TINY)
    assert int(state.step) == fit.epochs_run * (64 // (2 * 8))
    assert np.isfinite(fit.final_train_metrics["loss"])


@pytest.mark.parametrize("scheme", ["ring", "ulysses", "ulysses-flash"])
def test_lm_sequence_parallel_attention_trains(scheme):
    """--attention ring|ulysses with --seq 2: the causal sequence-parallel
    decoder path (round 4) trains end-to-end on the virtual pod."""
    state, fit = lm_main(attention=scheme, seq=2, **TINY)
    # seq=2 leaves 4 data shards: global batch 2*4=8 -> 8 steps/epoch
    assert int(state.step) == fit.epochs_run * 8
    assert np.isfinite(fit.final_train_metrics["loss"])


def test_lm_seq_parallel_flag_validation():
    with pytest.raises(ValueError, match="mutually exclusive"):
        lm_main(attention="ring", seq=2, pipe=2, **TINY)
    with pytest.raises(ValueError, match="ring"):
        lm_main(attention="dense", seq=2, **TINY)


def test_lm_loss_chunk_trains():
    """--loss_chunk fuses head+CE (no logits materialize); trains end-to-end
    with loss+perplexity metrics (top1 structurally unavailable)."""
    state, fit = lm_main(loss_chunk=5, **TINY)  # seq_len 16 -> s-1 = 15
    assert np.isfinite(fit.final_train_metrics["loss"])
    assert "top1" not in fit.final_train_metrics
    assert "perplexity" in fit.final_train_metrics
    with pytest.raises(ValueError, match="loss_chunk"):
        lm_main(loss_chunk=5, pipe=2, **TINY)


def test_lm_ring_block_k_trains():
    """--sp_block_k engages the ring's blocked inner loop end-to-end."""
    state, fit = lm_main(attention="ring", seq=2, sp_block_k=4, **TINY)
    assert np.isfinite(fit.final_train_metrics["loss"])


def test_lm_all_levers_compose():
    """The flagship long-context composition: causal ring attention (seq
    axis) + per-layer remat + chunked head+CE, all in one training run."""
    state, fit = lm_main(
        attention="ring", seq=2, sp_block_k=4, remat=True, loss_chunk=5,
        **TINY,
    )
    assert np.isfinite(fit.final_train_metrics["loss"])
    assert "perplexity" in fit.final_train_metrics


def test_lm_fsdp_trains():
    """--fsdp 2 shards embed/head (vocab dim) and qkv/FF widths; the run
    must train and validate divisibility."""
    state, fit = lm_main(fsdp=2, **TINY)
    assert np.isfinite(fit.final_train_metrics["loss"])
    with pytest.raises(ValueError, match="fsdp"):
        lm_main(fsdp=2, **dict(TINY, vocab_size=65))


def test_lm_loss_chunk_composes_with_accum():
    """Microbatched gradient accumulation over the fused head+CE path."""
    state, fit = lm_main(loss_chunk=5, accum_steps=2, **TINY)
    assert np.isfinite(fit.final_train_metrics["loss"])


def test_lm_ulysses_flash_all_levers():
    """Ulysses×flash + remat + chunked head+CE in one training run — the
    all-to-all flavor of the flagship long-context composition."""
    state, fit = lm_main(
        attention="ulysses-flash", seq=2, remat=True, loss_chunk=5, **TINY
    )
    assert np.isfinite(fit.final_train_metrics["loss"])


def test_lm_pipe_composes_with_fsdp():
    """pipe=2 x fsdp=2 x data=2 on the 8-device pod: GPipe stages with
    ZeRO-3 width shards living INSIDE the pipeline (the workload wires
    forward_pipelined(zero3_axis='fsdp'): per-tick weight all-gathers via
    param_partition; embed/head vocab shards stay on the GSPMD rules)."""
    state, fit = lm_main(pipe=2, fsdp=2, num_microbatches=2, **TINY)
    assert np.isfinite(fit.final_train_metrics["loss"])


def test_lm_seq_composes_with_fsdp():
    """seq=2 (causal ring) x fsdp=2 x data=2: sequence parallelism over
    ZeRO-sharded params."""
    state, fit = lm_main(attention="ring", seq=2, fsdp=2, **TINY)
    assert np.isfinite(fit.final_train_metrics["loss"])


def test_lm_tensor_parallel_trains():
    """--tensor 2: Megatron-style width sharding (qkv/FF columns, proj/out
    rows); trains end-to-end, divisibility validated."""
    state, fit = lm_main(tensor=2, **TINY)
    assert np.isfinite(fit.final_train_metrics["loss"])
    with pytest.raises(ValueError, match="tensor"):
        lm_main(tensor=4, **dict(TINY, d_model=30))  # 30 % 4 != 0


def test_lm_tensor_composes_with_fsdp():
    """tensor=2 (width) x fsdp=2 (vocab) x data=2 on the 8-device pod."""
    state, fit = lm_main(tensor=2, fsdp=2, **TINY)
    assert np.isfinite(fit.final_train_metrics["loss"])
