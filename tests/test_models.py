"""Model zoo: shapes, registry, parameter-count parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.models import available_models, get_model


def _param_count(model, shape, **init_kwargs):
    v = model.init(jax.random.key(0), jnp.zeros(shape), train=False, **init_kwargs)
    return sum(x.size for x in jax.tree_util.tree_leaves(v["params"]))


def test_registry_has_reference_models():
    names = available_models()
    # resnet_model.py:292-306 depths + tf_cnn_benchmarks inception + BERT config
    for expected in ["resnet18", "resnet34", "resnet50", "resnet101", "resnet152",
                     "resnet200", "inceptionv3", "bert-base"]:
        assert expected in names


def test_unknown_model_raises():
    with pytest.raises(ValueError, match="Unknown model"):
        get_model("alexnet9000")


@pytest.mark.parametrize("depth", [18, 34, 50])
def test_resnet_output_shape(depth):
    model = get_model(f"resnet{depth}", num_classes=13, dtype=jnp.float32)
    v = model.init(jax.random.key(0), jnp.zeros((2, 64, 64, 3)), train=False)
    out = model.apply(v, jnp.zeros((2, 64, 64, 3)), train=False)
    assert out.shape == (2, 13)
    assert out.dtype == jnp.float32


def test_resnet50_param_count_parity():
    """torchvision resnet50 has 25.557M params at 1000 classes; ours at 1001
    (TF convention, defaults.py:11) must land within a whisker."""
    model = get_model("resnet50", num_classes=1001, dtype=jnp.float32)
    n = _param_count(model, (1, 224, 224, 3))
    assert 25.4e6 < n < 25.8e6


def test_resnet18_param_count_parity():
    model = get_model("resnet18", num_classes=1000, dtype=jnp.float32)
    n = _param_count(model, (1, 64, 64, 3))
    assert 11.1e6 < n < 11.9e6  # torchvision: 11.69M


def test_resnet_bf16_activations_fp32_params():
    model = get_model("resnet18", num_classes=5)  # default dtype bf16
    v = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)), train=False)
    for leaf in jax.tree_util.tree_leaves(v["params"]):
        assert leaf.dtype == jnp.float32
    out = model.apply(v, jnp.zeros((1, 32, 32, 3)), train=False)
    assert out.dtype == jnp.float32  # logits cast back for stable loss


def test_bert_forward_and_mask():
    model = get_model(
        "bert-base", num_layers=2, hidden_size=32, num_heads=2,
        intermediate_size=64, vocab_size=100, num_classes=3,
        dropout_rate=0.0, dtype=jnp.float32,
    )
    ids = np.random.default_rng(0).integers(0, 100, (2, 10)).astype(np.int32)
    v = model.init(jax.random.key(0), ids, train=False)
    out = model.apply(v, ids, train=False)
    assert out.shape == (2, 3)
    mask = np.ones((2, 10), np.int32)
    mask[:, 5:] = 0
    masked = model.apply(v, ids, train=False, attention_mask=mask)
    assert masked.shape == (2, 3)
    assert not np.allclose(np.asarray(out), np.asarray(masked))


def test_bert_params_carry_logical_axes():
    """TP/FSDP sharding relies on flax logical axis metadata being present."""
    import flax

    model = get_model(
        "bert-base", num_layers=1, hidden_size=32, num_heads=2,
        intermediate_size=64, vocab_size=100, dtype=jnp.float32,
    )
    ids = np.zeros((1, 8), np.int32)
    v = model.init(jax.random.key(0), ids, train=False)
    specs = flax.linen.get_partition_spec(v["params"])
    flat = jax.tree_util.tree_leaves_with_path(
        specs, is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec)
    )
    named = [s for _, s in flat if any(a is not None for a in s)]
    assert named, "expected logical axis annotations on BERT params"
    all_names = {a for _, s in flat for a in s if a is not None}
    assert {"embed", "mlp", "heads", "kv", "vocab"} <= all_names


@pytest.mark.slow
def test_inception_v3_shape():
    model = get_model("inceptionv3", num_classes=7, dtype=jnp.float32)
    v = model.init(jax.random.key(0), jnp.zeros((1, 299, 299, 3)), train=False)
    out = model.apply(v, jnp.zeros((1, 299, 299, 3)), train=False)
    assert out.shape == (1, 7)


@pytest.mark.slow
def test_inception_v3_aux_logits():
    """tf_cnn_benchmarks' inception3 carries an aux classifier whose loss
    enters weighted 0.4; train-mode forward returns (main, aux), eval-mode
    returns main only, and the combined loss is finite."""
    import numpy as np

    from distributeddeeplearning_tpu.models.inception import inception_aux_loss

    model = get_model(
        "inceptionv3", num_classes=7, dtype=jnp.float32, aux_logits=True
    )
    x = jnp.zeros((2, 299, 299, 3))
    v = model.init(jax.random.key(0), x, train=False)
    assert "InceptionAux_0" in v["params"]
    (main, aux), _ = model.apply(
        v, x, train=True, mutable=["batch_stats"]
    )
    assert main.shape == (2, 7) and aux.shape == (2, 7)
    labels = jnp.array([1, 2])
    loss = inception_aux_loss((main, aux), labels)
    assert np.isfinite(float(loss))
    out_eval = model.apply(v, x, train=False)
    assert out_eval.shape == (2, 7)


def test_vgg11_forward_and_train_step():
    """tf_cnn_benchmarks model-menu parity: the VGG family trains (vgg11 =
    the cheapest config; vgg16/19 registration is covered below)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distributeddeeplearning_tpu.data.synthetic import synthetic_batch
    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.parallel import (
        MeshSpec,
        create_mesh,
        shard_batch,
    )
    from distributeddeeplearning_tpu.train.state import (
        create_train_state,
        sgd_momentum,
    )
    from distributeddeeplearning_tpu.train.step import build_train_step

    mesh = create_mesh(MeshSpec())
    model = get_model("vgg11", num_classes=7, dtype=jnp.float32)
    tx = sgd_momentum(optax.constant_schedule(0.01))
    state = create_train_state(jax.random.key(0), model, (8, 64, 64, 3), tx)
    step = build_train_step(mesh, state, compute_dtype=jnp.float32)
    batch = shard_batch(mesh, synthetic_batch(16, (64, 64, 3), 7))
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_alexnet_forward_shape():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributeddeeplearning_tpu.models import get_model

    model = get_model("alexnet", num_classes=9, dtype=jnp.float32)
    x = np.zeros((2, 128, 128, 3), np.float32)
    v = model.init(jax.random.key(0), jnp.asarray(x), train=False)
    out = model.apply(v, jnp.asarray(x), train=False)
    assert out.shape == (2, 9)
    assert out.dtype == jnp.float32


def test_vgg16_vgg19_register_and_shape():
    """Deeper VGG configs build (abstract eval — no convolutions run)."""
    import jax
    import jax.numpy as jnp

    from distributeddeeplearning_tpu.models import get_model

    for name in ("vgg16", "vgg19"):
        model = get_model(name, num_classes=13, dtype=jnp.float32)
        out = jax.eval_shape(
            lambda m=model: m.init_with_output(
                jax.random.key(0),
                jnp.zeros((2, 64, 64, 3), jnp.float32),
                train=False,
            )[0]
        )
        assert out.shape == (2, 13)
