"""Native TFRecord reader (data/csrc/ddlt_records.c + data/_native.py).

The framework's own native data-plane component — the role TensorFlow's
C++ record reader plays in the reference.  Tests pin: CRC32C known answers,
frame parity with tf.io.TFRecordWriter output, Example feature extraction
against tf.train.Example serialization, corruption detection, and the
pure-Python fallback agreeing with the C path.
"""

from __future__ import annotations

import struct

import numpy as np
import pytest

from distributeddeeplearning_tpu.data import _native
from distributeddeeplearning_tpu.data._native import (
    RecordCorruptionError,
    RecordReader,
    crc32c,
    example_bytes,
    example_int64,
    masked_crc32c,
    native_available,
)


def _write_tfrecords(path, payloads):
    import tensorflow as tf

    with tf.io.TFRecordWriter(str(path)) as w:
        for p in payloads:
            w.write(p)


def _example(jpeg: bytes, label: int) -> bytes:
    import tensorflow as tf

    return tf.train.Example(
        features=tf.train.Features(
            feature={
                "image/encoded": tf.train.Feature(
                    bytes_list=tf.train.BytesList(value=[jpeg])
                ),
                "image/class/label": tf.train.Feature(
                    int64_list=tf.train.Int64List(value=[label])
                ),
                "image/format": tf.train.Feature(
                    bytes_list=tf.train.BytesList(value=[b"JPEG"])
                ),
            }
        )
    ).SerializeToString()


def test_crc32c_known_answers():
    # RFC 3720 test vector + empty string
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"") == 0
    assert crc32c(b"\x00" * 32) == 0x8A9136AA


def test_native_library_compiles_here():
    # This image ships cc; the C path must actually be exercised in CI.
    assert native_available()


def test_reader_matches_tf_writer(tmp_path):
    payloads = [b"alpha", b"b" * 1000, b"", b"\x00\xff" * 7]
    path = tmp_path / "t.tfrecord"
    _write_tfrecords(path, payloads)
    assert list(RecordReader(path)) == payloads
    assert list(RecordReader(path, verify=False)) == payloads


def test_reader_detects_corruption(tmp_path):
    path = tmp_path / "c.tfrecord"
    _write_tfrecords(path, [b"hello world records"])
    raw = bytearray(path.read_bytes())
    raw[14] ^= 0x01  # flip a payload byte
    path.write_bytes(bytes(raw))
    with pytest.raises(RecordCorruptionError):
        list(RecordReader(path))
    # verify=False trusts the frame lengths and yields the (corrupt) payload
    assert len(list(RecordReader(path, verify=False))) == 1


def test_reader_detects_truncation(tmp_path):
    path = tmp_path / "t.tfrecord"
    _write_tfrecords(path, [b"x" * 100])
    raw = path.read_bytes()
    path.write_bytes(raw[:-10])
    with pytest.raises(RecordCorruptionError):
        list(RecordReader(path, verify=False))


def test_example_feature_extraction():
    jpeg = b"\xff\xd8fakejpegdata\xff\xd9"
    rec = _example(jpeg, 37)
    assert example_bytes(rec, "image/encoded") == jpeg
    assert example_bytes(rec, "image/format") == b"JPEG"
    assert example_int64(rec, "image/class/label") == 37
    assert example_bytes(rec, "missing/key") is None
    assert example_int64(rec, "image/encoded") is None  # wrong kind


def test_example_int64_negative_and_large():
    import tensorflow as tf

    for v in (-1, -12345, 2**40, 0):
        rec = tf.train.Example(
            features=tf.train.Features(
                feature={
                    "v": tf.train.Feature(
                        int64_list=tf.train.Int64List(value=[v])
                    )
                }
            )
        ).SerializeToString()
        assert example_int64(rec, "v") == v


def test_overlong_field_lengths_rejected_not_overread():
    """A length-delimited field whose varint length is near 2^64 (or just
    past the buffer) must read as not-found on BOTH paths — never an
    out-of-bounds slice (C) or truncated garbage (Python)."""
    # field 1 (Example.features), wire 2, length = 2^64-1 (10-byte varint)
    huge = bytes([0x0A]) + b"\xff" * 9 + b"\x01"
    assert example_bytes(huge, "image/encoded") is None
    assert example_int64(huge, "image/class/label") is None
    # plausible-but-overlong: claims 100 bytes, buffer has 4
    overlong = bytes([0x0A, 100]) + b"abcd"
    assert example_bytes(overlong, "image/encoded") is None
    # same through the pure-Python walkers
    assert _native._py_find_len_field(huge, 1) is None
    assert _native._py_find_len_field(overlong, 1) is None


def test_python_fallback_agrees_with_native(tmp_path, monkeypatch):
    payloads = [_example(b"data%d" % i, i) for i in range(5)]
    path = tmp_path / "f.tfrecord"
    _write_tfrecords(path, payloads)
    native = list(RecordReader(path))

    # Force the fallback by hiding the loaded library.
    monkeypatch.setattr(_native, "_LIB", None)
    monkeypatch.setattr(_native, "_TRIED", True)
    assert not native_available()
    fallback = list(RecordReader(path))
    assert fallback == native == payloads
    assert crc32c(b"123456789") == 0xE3069283  # pure-python table path
    assert masked_crc32c(b"abc") == (
        ((crc32c(b"abc") >> 15) | (crc32c(b"abc") << 17)) + 0xA282EAD8
    ) & 0xFFFFFFFF
    for i, rec in enumerate(fallback):
        assert example_bytes(rec, "image/encoded") == b"data%d" % i
        assert example_int64(rec, "image/class/label") == i
