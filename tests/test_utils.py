import time

import jax
import jax.numpy as jnp
import numpy as np

from distributeddeeplearning_tpu.utils import (
    AverageMeter,
    ExamplesPerSecondTracker,
    Timer,
    accuracy_topk,
    confidence_interval_95,
    pmean_metrics,
)


def test_timer_context_manager():
    with Timer() as t:
        time.sleep(0.01)
    assert 0.005 < t.elapsed < 1.0
    # elapsed frozen after stop
    e1 = t.elapsed
    time.sleep(0.005)
    assert t.elapsed == e1


def test_timer_decorator_and_report():
    messages = []

    @Timer(report=messages.append, prefix="work")
    def work():
        return 42

    assert work() == 42
    assert len(messages) == 1 and messages[0].startswith("work:")


def test_timer_feeds_obs_histogram():
    """The Timer->obs bridge: every stop() (including via the decorator,
    which must propagate the histogram) records elapsed seconds into the
    given streaming histogram."""
    from distributeddeeplearning_tpu.obs import Histogram

    h = Histogram("timed_phase")
    with Timer(histogram=h):
        time.sleep(0.002)

    @Timer(histogram=h)
    def work():
        time.sleep(0.002)

    work()
    work()
    assert h.count == 3
    assert 0.001 < h.min and h.max < 1.0
    assert h.summary()["p50"] > 0.0


def test_average_meter():
    m = AverageMeter("loss")
    m.update(2.0, n=2)
    m.update(4.0)
    assert m.val == 4.0
    assert abs(m.avg - (2.0 * 2 + 4.0) / 3) < 1e-9


def test_accuracy_topk():
    logits = jnp.array(
        [
            [0.1, 0.9, 0.0, 0.0],  # top1 = 1
            [0.5, 0.1, 0.3, 0.1],  # top1 = 0, label 2 in top-2
        ]
    )
    labels = jnp.array([1, 2])
    acc = accuracy_topk(logits, labels, ks=(1, 2))
    assert float(acc["top1"]) == 0.5
    assert float(acc["top2"]) == 1.0


def test_pmean_metrics_across_devices():
    n = jax.device_count()
    assert n == 8, "conftest must fake 8 devices"

    def body(x):
        return pmean_metrics({"loss": x}, axis_name="dp")

    out = jax.pmap(body, axis_name="dp")(jnp.arange(n, dtype=jnp.float32))
    np.testing.assert_allclose(np.asarray(out["loss"]), np.full(n, (n - 1) / 2.0))


def test_confidence_interval():
    mean, half = confidence_interval_95([1.0, 1.0, 1.0])
    assert mean == 1.0 and half == 0.0
    mean, half = confidence_interval_95([0.0, 2.0])
    assert mean == 1.0 and abs(half - 1.96) < 1e-9


def test_examples_per_second_tracker():
    logs = []
    tr = ExamplesPerSecondTracker(global_batch_size=10, every_n_steps=2, report=logs.append)
    tr.begin()
    time.sleep(0.01)
    tr.after_step()
    tr.after_step()
    assert len(logs) == 1
    assert tr.average_examples_per_sec > 0
    assert tr.summary(total_examples=20) > 0


def test_shipped_logging_confs_load_via_log_config(monkeypatch, tmp_path):
    """The example INI fileConfigs ship in-package and are honored through
    the LOG_CONFIG env contract (reference: control/src/logging.conf role)."""
    import logging
    from pathlib import Path

    import distributeddeeplearning_tpu
    from distributeddeeplearning_tpu.utils.logging_utils import setup_logging

    conf_dir = (
        Path(distributeddeeplearning_tpu.__file__).parent / "config" / "logging"
    )
    for conf in ("control.conf", "workload.conf"):
        path = conf_dir / conf
        assert path.exists(), path
        monkeypatch.setenv("LOG_CONFIG", str(path))
        logger = setup_logging()
        assert logger.name == "ddlt"
        assert logging.getLogger("ddlt").isEnabledFor(logging.INFO)


def test_windowed_benchmark_priming_and_window_count():
    """The overlapped-window core dispatches num_iters+1 windows, measures
    exactly num_iters deltas, and never fetches the priming window into the
    stats (train/benchmark.py)."""
    from distributeddeeplearning_tpu.train.benchmark import (
        _windowed_benchmark,
    )

    calls = {"steps": 0, "batches": 0}

    def step_fn(state, batch):
        calls["steps"] += 1
        return state, {"loss": 0.0}

    def next_batch():
        calls["batches"] += 1
        return None

    result = _windowed_benchmark(
        step_fn,
        state=None,
        next_batch=next_batch,
        model_name="fake",
        batch_size_per_chip=4,
        num_devices=2,
        num_warmup_batches=3,
        num_iters=5,
        num_batches_per_iter=2,
        log=None,
        label="",
    )
    # 3 warmup + (5+1 windows) x 2 batches
    assert calls["steps"] == 3 + 6 * 2 == calls["batches"]
    assert len(result.iter_times_s) == 5  # priming window unmeasured
    assert result.num_devices == 2
    assert result.img_sec_total > 0
