"""`ddlt lint` — the static-analysis subsystem's own test coverage.

Two halves:

- **detection pins** over the seeded-violation corpus
  (``tests/fixtures/lint_violations/``): every checker — host-sync,
  stale-marker, donation, collective-signature, callback-in-jit,
  dtype-audit, sharding-coverage, fault-coverage — must catch exactly its
  planted bug with a file:line finding, and must NOT reproduce the regex
  era's false-positive classes (``float(`` in strings/comments, alias
  renames, ``jnp.asarray`` uploads);
- **clean-tree pins**: both analyzer layers report zero findings over the
  live tree (THE tier-1 gate — ``bench.py --lint`` and ``make lint``
  enforce the same invariant), and the program registry actually covers
  the contracted programs (train step both comm paths, prefill/decode/
  verify on both KV layouts, quantized variants) with non-vacuous
  donation counts.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from distributeddeeplearning_tpu.analysis import format_findings, run_lint
from distributeddeeplearning_tpu.analysis import host_sync
from distributeddeeplearning_tpu.analysis.fault_coverage import (
    check_fault_coverage,
)
from distributeddeeplearning_tpu.analysis.regions import (
    ALL_REGIONS,
    HotRegion,
)
from distributeddeeplearning_tpu.cli.main import main as cli_main

REPO = Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tests" / "fixtures" / "lint_violations"


def _line_of(path: Path, needle: str) -> int:
    for i, line in enumerate(path.read_text().splitlines(), 1):
        if needle in line:
            return i
    raise AssertionError(f"{needle!r} not found in {path}")


def _fixture_region(**overrides) -> HotRegion:
    kw = dict(
        name="fixture-loop",
        module="<fixture>",
        qualname="hot_loop",
        locator="for x in xs",
        landmarks=(),
        sync_budget=0,
    )
    kw.update(overrides)
    return HotRegion(**kw)


# --------------------------------------------------------------------------
# layer 1: host-sync checker detection pins
# --------------------------------------------------------------------------


class TestHostSyncChecker:
    def test_catches_every_planted_sync_with_file_line(self):
        path = FIXTURES / "host_sync_violation.py"
        region = _fixture_region(landmarks=("engine.decode",))
        findings = host_sync.check_region(region, path=str(path))
        syncs = [f for f in findings if f.checker == "host-sync"]
        got = {f.line for f in syncs}
        want = {
            _line_of(path, "float(out)"),
            _line_of(path, "renamed_np.asarray(out)"),
            _line_of(path, "local_asarray(out)"),
            _line_of(path, "renamed_get(out)"),
            _line_of(path, "out.item()"),
            # banned targets passed as bare references (map/key=) sync
            # per element just as hard — the regex caught these as
            # substrings, so the AST checker must too
            _line_of(path, "map(renamed_np.asarray"),
            _line_of(path, "key=renamed_get"),
        }
        assert got == want, format_findings(findings)
        assert all(f.path.endswith("host_sync_violation.py") for f in syncs)
        # alias resolution names the canonical target in the message
        assert any("numpy.asarray" in f.message for f in syncs)
        assert any("jax.device_get" in f.message for f in syncs)
        assert any("reference" in f.message for f in syncs)

    def test_regex_false_positive_classes_stay_clean(self):
        """The known false positives of the old indentation+regex lint:
        banned tokens inside strings and comments, and the jnp.asarray
        device upload — none may produce a finding."""
        path = FIXTURES / "host_sync_violation.py"
        region = _fixture_region(landmarks=("engine.decode",))
        findings = host_sync.check_region(region, path=str(path))
        clean_lines = {
            _line_of(path, "inside a string"),
            _line_of(path, "commented float("),
            _line_of(path, "jnp.asarray(x)"),
        }
        assert not clean_lines & {f.line for f in findings}, (
            format_findings(findings)
        )

    def test_stale_marker_is_a_finding(self):
        """Exactly ONE stale finding: the planted dead waiver — the
        colon-less prose comment mentioning 'sync-ok markers' must not
        register as a (phantom) waiver at all."""
        path = FIXTURES / "stale_marker.py"
        region = _fixture_region(landmarks=("step(x)",), sync_budget=1)
        findings = host_sync.check_region(region, path=str(path))
        assert [f.checker for f in findings] == ["stale-marker"], (
            format_findings(findings)
        )
        assert findings[0].line == _line_of(path, "PLANTED dead waiver")

    def test_live_marker_waives_and_counts_against_budget(self):
        path = FIXTURES / "stale_marker.py"
        # budget 1 satisfied by the live marked float() — no budget
        # finding, no host-sync finding for the marked line
        region = _fixture_region(landmarks=(), sync_budget=1)
        findings = host_sync.check_region(region, path=str(path))
        assert not [f for f in findings if f.checker == "host-sync"]
        assert not [f for f in findings if f.checker == "allowlist-budget"]

    def test_budget_mismatch_is_a_finding(self):
        path = FIXTURES / "stale_marker.py"
        region = _fixture_region(sync_budget=2)  # only 1 live marker
        findings = host_sync.check_region(region, path=str(path))
        budget = [f for f in findings if f.checker == "allowlist-budget"]
        assert len(budget) == 1 and "expects exactly 2" in budget[0].message

    def test_missing_landmark_is_a_finding(self):
        path = FIXTURES / "stale_marker.py"
        region = _fixture_region(
            landmarks=("engine.decode(",), sync_budget=1
        )
        findings = host_sync.check_region(region, path=str(path))
        assert any(f.checker == "landmark" for f in findings)

    def test_moved_region_surfaces_as_finding_not_crash(self):
        path = FIXTURES / "stale_marker.py"
        region = _fixture_region(locator="while nothing matches this")
        findings = host_sync.check_region(region, path=str(path))
        assert [f.checker for f in findings] == ["region"]
        assert "no longer matches" in findings[0].message

    def test_strict_region_ignores_markers(self):
        """Jitted-builder regions: a marked sync is still a finding."""
        path = FIXTURES / "stale_marker.py"
        region = _fixture_region(honor_markers=False)
        findings = host_sync.check_region(region, path=str(path))
        syncs = [f for f in findings if f.checker == "host-sync"]
        assert len(syncs) == 1
        assert "markers are not honored" in syncs[0].message

    def test_goodput_record_float_coercion_is_caught(self):
        """The goodput-ledger seeded fixture: a ledger category recorded
        via a host-syncing ``float(...)`` on the mark()-shaped record
        path — the exact class the real ``obs-goodput-mark`` region bans
        with its zero budget — is caught at file:line (and the decoy
        ``float(`` inside the string is not)."""
        path = FIXTURES / "goodput_violation.py"
        region = _fixture_region(
            qualname="record_goodput",
            locator=None,  # the whole record function is the region
            landmarks=("time.perf_counter()",),
            sync_budget=0,
        )
        findings = host_sync.check_region(region, path=str(path))
        syncs = [f for f in findings if f.checker == "host-sync"]
        assert [f.line for f in syncs] == [
            _line_of(path, "float(seconds)")
        ], format_findings(findings)
        assert _line_of(path, "in this string") not in {
            f.line for f in findings
        }
        # the live-tree region this fixture mirrors is registered with a
        # zero budget — and the real record path stays clean under it
        from distributeddeeplearning_tpu.analysis.regions import get_region

        real = get_region("obs-goodput-mark")
        assert real.sync_budget == 0
        assert not host_sync.check_region(real), format_findings(
            host_sync.check_region(real)
        )


# --------------------------------------------------------------------------
# fault-coverage cross-check
# --------------------------------------------------------------------------


class TestFaultCoverage:
    HOOKS = {
        "covered_kind": ("fire_covered",),
        "orphan_kind": ("fire_orphan",),
        "ckpt_corrupt": ("take_ckpt_corrupt",),
    }

    def test_orphan_kind_is_caught_with_file_line(self):
        faults = FIXTURES / "faultpkg" / "faults.py"
        findings = check_fault_coverage(
            faults_path=str(faults),
            package_root=str(FIXTURES / "faultpkg"),
            kind_hooks=self.HOOKS,
        )
        # orphan_kind and the checkpoint kind below are both uncovered
        orphans = [f for f in findings if "orphan_kind" in f.message]
        assert len(orphans) == 1, format_findings(findings)
        f = orphans[0]
        assert f.checker == "fault-coverage"
        assert f.path.endswith("faults.py")
        assert f.line == _line_of(faults, "KINDS = ")

    def test_orphan_checkpoint_fault_kind_is_caught(self):
        """A checkpoint-durability kind whose injection hook exists but is
        never CALLED (comment/string decoys planted in the fixture) must
        be reported — a renamed ``take_ckpt_corrupt`` call-site would
        silently drop corruption chaos from every bench."""
        findings = check_fault_coverage(
            faults_path=str(FIXTURES / "faultpkg" / "faults.py"),
            package_root=str(FIXTURES / "faultpkg"),
            kind_hooks=self.HOOKS,
        )
        ckpt = [f for f in findings if "ckpt_corrupt" in f.message]
        assert len(ckpt) == 1, format_findings(findings)
        assert "no injection call-site" in ckpt[0].message
        assert "take_ckpt_corrupt" in ckpt[0].message

    def test_renamed_hook_is_caught(self):
        findings = check_fault_coverage(
            faults_path=str(FIXTURES / "faultpkg" / "faults.py"),
            package_root=str(FIXTURES / "faultpkg"),
            kind_hooks={"covered_kind": ("fire_covered_RENAMED",),
                        "orphan_kind": ("fire_orphan",),
                        "ckpt_corrupt": ("take_ckpt_corrupt",)},
        )
        assert any(
            "not a FaultPlan method" in f.message for f in findings
        ), format_findings(findings)

    def test_clean_tree_fault_coverage(self):
        assert check_fault_coverage() == []


# --------------------------------------------------------------------------
# layer 2: program-audit detection pins (seeded bad programs)
# --------------------------------------------------------------------------


class TestProgramAuditDetections:
    @pytest.fixture(scope="class")
    def fixtures(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "lint_violation_programs", FIXTURES / "programs.py"
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_lost_donation_caught(self, fixtures):
        from distributeddeeplearning_tpu.analysis.program_audit import (
            check_program,
        )

        findings = check_program(fixtures.lost_donation())
        assert [f.checker for f in findings] == ["donation"], (
            format_findings(findings)
        )
        assert findings[0].path.endswith("programs.py")
        assert findings[0].line > 0

    def test_callback_in_jit_caught(self, fixtures):
        from distributeddeeplearning_tpu.analysis.program_audit import (
            check_program,
        )

        findings = check_program(fixtures.callback_in_jit())
        assert [f.checker for f in findings] == ["callback-in-jit"], (
            format_findings(findings)
        )
        assert "debug_callback" in findings[0].message

    def test_hoisted_collective_caught(self, fixtures):
        from distributeddeeplearning_tpu.analysis.program_audit import (
            CollectiveContract,
            check_collective_contract,
        )

        jaxpr = fixtures.hoisted_collective()
        findings = check_collective_contract(
            jaxpr, CollectiveContract(in_scan_reduce_scatter_min=1),
            name="fixture.hoisted", path="fixture", line=1,
        )
        msgs = " | ".join(f.message for f in findings)
        assert any(f.checker == "collective-signature" for f in findings)
        assert "INSIDE the accumulation scan" in msgs  # no in-scan RS
        assert "hoisted all-reduce" in msgs  # the post-scan psum

    def test_f32_history_returned_caught(self, fixtures):
        from distributeddeeplearning_tpu.analysis.program_audit import (
            check_program,
        )

        findings = check_program(fixtures.f32_history_returned())
        dtype = [f for f in findings if f.checker == "dtype-audit"]
        assert len(dtype) == 1, format_findings(findings)
        assert "RETURNS" in dtype[0].message

    def test_bf16_history_returned_caught(self, fixtures):
        """Half-width evasion: dequantizing to bf16 instead of f32 is
        the same materialization regression and must still be caught."""
        from distributeddeeplearning_tpu.analysis.program_audit import (
            check_program,
        )

        findings = check_program(fixtures.bf16_history_returned())
        dtype = [f for f in findings if f.checker == "dtype-audit"]
        assert len(dtype) == 1, format_findings(findings)
        assert "RETURNS" in dtype[0].message

    def test_f32_history_intermediate_caught(self, fixtures):
        """The PR-12 extension: a history-granular dequant that never
        reaches an output or a write (reduced away in-program) passes
        the old checks but must fail the strict intermediate audit the
        flash-decode records arm via ``int8_head_dim``."""
        import dataclasses

        from distributeddeeplearning_tpu.analysis.program_audit import (
            check_program,
        )

        rec = fixtures.f32_history_intermediate()
        findings = check_program(rec)
        inter = [f for f in findings if "intermediate" in f.message]
        assert inter, format_findings(findings)
        assert "`mul`" in inter[0].message
        # the SAME program with the strict audit unarmed passes clean —
        # pins that the catch above is the new checker, nothing else
        relaxed = dataclasses.replace(rec, int8_head_dim=None)
        assert not check_program(relaxed), format_findings(
            check_program(relaxed)
        )

    def test_gather_path_fails_strict_intermediate_audit(self):
        """Non-vacuity for the clean-tree gate: arming the strict audit
        on the LEGACY gather int8 decode programs (which the registry
        deliberately registers relaxed) produces findings — so the flash
        programs passing it means the fused read actually differs."""
        import dataclasses

        from distributeddeeplearning_tpu.analysis.program_audit import (
            build_program_records,
            check_program,
        )

        records = {r.name: r for r in build_program_records()}
        for name in (
            "serve.paged.int8_gather.decode",
            "serve.dense.int8_gather.decode",
        ):
            rec = records[name]
            assert rec.int8_head_dim is None, name  # registered relaxed
            armed = dataclasses.replace(rec, int8_head_dim=8)
            inter = [
                f for f in check_program(armed)
                if "intermediate" in f.message
            ]
            assert inter, f"{name}: gather path passed the strict audit"

    def test_f32_history_written_caught(self, fixtures):
        from distributeddeeplearning_tpu.analysis.program_audit import (
            check_program,
        )

        findings = check_program(fixtures.f32_history_written())
        dtype = [f for f in findings if f.checker == "dtype-audit"]
        assert len(dtype) == 1, format_findings(findings)
        assert "WRITES" in dtype[0].message
        assert "dynamic_update_slice" in dtype[0].message

    def test_unsharded_leaf_caught(self, fixtures):
        from distributeddeeplearning_tpu.analysis.program_audit import (
            check_tree_coverage,
        )

        tree_abs, shardings = fixtures.unsharded_leaf()
        findings = check_tree_coverage(
            tree_abs, shardings, name="fixture.cache", path="fixture",
            line=1,
        )
        assert len(findings) == 1, format_findings(findings)
        assert findings[0].checker == "sharding-coverage"
        assert "k_zero_point" in findings[0].message

    def test_rule_table_fallthrough_caught(self, fixtures):
        """The layout-engine sibling of the unsharded-leaf class: a leaf
        name no LAYOUT_RULES pattern matches must surface as a
        sharding-coverage finding at the planted file:line — and the
        matched sibling leaf (qkv) must NOT fire."""
        from distributeddeeplearning_tpu.analysis.program_audit import (
            check_rule_fallthrough,
        )

        path = FIXTURES / "programs.py"
        line = _line_of(path, "wq_lora_adapter")
        findings = check_rule_fallthrough(
            fixtures.rule_fallthrough_tree(), prefix="params",
            name="fixture.params", path=str(path), line=line,
        )
        assert len(findings) == 1, format_findings(findings)
        f = findings[0]
        assert f.checker == "sharding-coverage"
        assert "params/blocks/0/wq_lora_adapter" in f.message
        assert f.path.endswith("programs.py") and f.line == line
        assert "LAYOUT_RULES" in (f.hint or "")

    def test_rule_table_audit_armed_on_live_tree(self):
        """Non-vacuity: the hot-program rule-table sweep inside
        check_sharding_coverage actually consults the layout table — an
        empty rule table must produce fallthrough findings pointing at
        parallel/sharding.py, while the real table stays clean."""
        from unittest import mock

        from distributeddeeplearning_tpu.analysis import program_audit
        from distributeddeeplearning_tpu.parallel import sharding

        assert program_audit.check_sharding_coverage() == []
        with mock.patch.object(sharding, "LAYOUT_RULES", ()):
            findings = program_audit.check_sharding_coverage()
        fallthrough = [
            f for f in findings if "matches NO rule" in f.message
        ]
        assert fallthrough, format_findings(findings)
        assert all(
            f.path.endswith("parallel/sharding.py") and f.line > 0
            for f in fallthrough
        )


# --------------------------------------------------------------------------
# clean-tree gates + registry coverage pins
# --------------------------------------------------------------------------


class TestCleanTree:
    def test_ast_layer_zero_findings(self):
        findings = run_lint(programs=False)
        assert not findings, format_findings(findings, str(REPO))

    def test_program_audits_zero_findings(self):
        """THE acceptance gate: donation + collective signature pinned
        for the train step (both comm paths) and prefill/decode/verify
        on both KV layouts (+ quantized variants), via abstract tracing
        on the CPU platform — zero findings on the clean tree."""
        from distributeddeeplearning_tpu.analysis.program_audit import (
            run_program_audits,
            skipped_audits,
        )

        findings = run_program_audits()
        assert not findings, format_findings(findings, str(REPO))
        # under the test env's 8-device virtual pod NOTHING may skip —
        # a silent skip would make this gate weaker than it reads
        assert skipped_audits() == []

    def test_single_shard_skip_is_reported_not_silent(self):
        """On a REAL 1-device backend (no virtual pod) the implicit-path
        collective audit cannot run — the sweep must still pass clean
        AND report the skip through skipped_audits(), never swallow it
        (a silent skip would make `bench.py --lint` on a 1-device box a
        weaker gate than `make lint` with no indication)."""
        code = (
            "import os\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "os.environ.pop('XLA_FLAGS', None)\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "assert len(jax.devices()) == 1, jax.devices()\n"
            "from distributeddeeplearning_tpu.analysis import "
            "program_audit\n"
            "f = program_audit.run_program_audits()\n"
            "assert not f, [x.message for x in f]\n"
            "skips = program_audit.skipped_audits()\n"
            "assert len(skips) == 1 and 'collective-signature' in "
            "skips[0], skips\n"
            "print('SKIP_REPORTED_OK')\n"
        )
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True,
            text=True, timeout=280, cwd=str(REPO),
        )
        assert "SKIP_REPORTED_OK" in out.stdout, out.stdout + out.stderr

    def test_program_registry_covers_the_contract(self):
        """The zero-findings gate above is only as strong as the
        registry — pin that the contracted programs are actually in it,
        with donation expectations armed."""
        from distributeddeeplearning_tpu.analysis.program_audit import (
            build_program_records,
        )

        records = {r.name: r for r in build_program_records()}
        required = [
            "serve.dense.f32.prefill", "serve.dense.f32.decode",
            "serve.dense.int8.decode", "serve.dense.w_int8.decode",
            "serve.paged.f32.prefill_chunk", "serve.paged.f32.decode",
            "serve.paged.int8.decode", "spec.dense.verify",
            "spec.paged.verify", "spec.dense.rollback",
            "spec.dense.draft",
            # PR 12: flash is the default kernel, and the legacy gather
            # engines stay registered (still selectable end-to-end)
            "serve.dense.int8_gather.decode",
            "serve.paged.int8_gather.decode",
            "serve.paged.int8_gather.prefill_chunk",
        ]
        for name in required:
            assert name in records, sorted(records)
        for name in required:
            if name.endswith((".decode", ".verify", ".rollback")):
                assert records[name].donate_min >= 2, name
        # the quantized variants run the dtype audit
        assert records["serve.dense.int8.decode"].int8_history_len
        assert records["serve.paged.int8.decode"].int8_history_len
        # the default (flash) int8 programs arm the STRICT intermediate
        # audit; the gather variants are relaxed by design
        for name in (
            "serve.dense.int8.decode", "serve.paged.int8.decode",
            "serve.paged.int8.prefill_chunk",
        ):
            assert records[name].int8_head_dim, name
        for name in (
            "serve.dense.int8_gather.decode",
            "serve.paged.int8_gather.decode",
        ):
            assert records[name].int8_head_dim is None, name

    def test_donation_counts_are_exact_not_vacuous(self):
        """The lowered dense decode aliases exactly its cache leaves:
        2 (k, v) for f32, 4 (+scales) for int8 — pins that the alias
        annotation counting measures what it claims."""
        from distributeddeeplearning_tpu.analysis.program_audit import (
            ALIAS_ANNOTATION,
            build_program_records,
        )

        records = {r.name: r for r in build_program_records()}
        for name, expect in (
            ("serve.dense.f32.decode", 2),
            ("serve.dense.int8.decode", 4),
        ):
            rec = records[name]
            text = rec.jitted.trace(*rec.args).lower().as_text()
            assert text.count(ALIAS_ANNOTATION) == expect, name


# --------------------------------------------------------------------------
# entry points
# --------------------------------------------------------------------------


class TestEntryPoints:
    def test_cli_lint_json_clean(self, capsys):
        rc = cli_main(["lint", "--no-programs", "--json"])
        out = capsys.readouterr().out
        assert rc == 0
        assert json.loads(out) == []

    def test_cli_lint_nonzero_on_findings(self, capsys, monkeypatch):
        """Exit-code contract: any finding -> rc 1, file:line printed."""
        import distributeddeeplearning_tpu.analysis as analysis_pkg
        from distributeddeeplearning_tpu.analysis.core import Finding

        monkeypatch.setattr(
            analysis_pkg, "run_lint",
            lambda programs=True: [
                Finding("host-sync", "x.py", 3, "planted", hint="fix it")
            ],
        )
        rc = cli_main(["lint", "--no-programs"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "x.py:3" in out and "planted" in out and "fix it" in out

    def test_bench_lint_preflight_wired(self):
        """`bench.py --lint` exists and gates artifact production (the
        flag parses; the preflight body runs run_lint before any
        benchmark dispatch)."""
        src = (REPO / "bench.py").read_text()
        assert "--lint" in src
        idx_lint = src.index("findings = run_lint()")
        idx_dispatch = src.index("return _run_faults(args)")
        assert idx_lint < idx_dispatch
        help_text = subprocess.run(
            [sys.executable, str(REPO / "bench.py"), "--help"],
            capture_output=True, text=True, timeout=120,
        ).stdout
        assert "--lint" in help_text

    def test_make_lint_target_exists(self):
        mk = (REPO / "Makefile").read_text()
        assert "lint:" in mk and "cli.main lint" in mk


def test_registry_regions_all_resolve():
    """Every registry entry must locate its function+loop in the live
    source (a 'region' finding anywhere means the registry rotted)."""
    for region in ALL_REGIONS:
        findings = host_sync.check_region(region)
        assert not [f for f in findings if f.checker == "region"], (
            region.name
        )
