"""Goyal LR recipe — the accuracy-critical constants from BASELINE.md."""

import numpy as np
import pytest

from distributeddeeplearning_tpu.train.schedule import (
    goyal_lr_schedule,
    scale_base_lr,
)

BASE_LR = 0.0125  # imagenet_pytorch_horovod.py:296-302
SPE = 100  # steps per epoch


def test_linear_scaling():
    assert scale_base_lr(BASE_LR, 32) == pytest.approx(0.4)


def test_warmup_starts_at_base_lr():
    sched = goyal_lr_schedule(BASE_LR, 8, SPE)
    assert float(sched(0)) == pytest.approx(BASE_LR)


def test_warmup_reaches_peak_at_5_epochs():
    sched = goyal_lr_schedule(BASE_LR, 8, SPE)
    assert float(sched(5 * SPE)) == pytest.approx(BASE_LR * 8)


def test_warmup_is_monotonic():
    sched = goyal_lr_schedule(BASE_LR, 8, SPE)
    lrs = [float(sched(s)) for s in range(0, 5 * SPE, 50)]
    assert all(b >= a for a, b in zip(lrs, lrs[1:]))


def test_step_decay_milestones():
    sched = goyal_lr_schedule(BASE_LR, 8, SPE)
    peak = BASE_LR * 8
    assert float(sched(29 * SPE)) == pytest.approx(peak)
    assert float(sched(31 * SPE)) == pytest.approx(peak * 0.1)
    assert float(sched(61 * SPE)) == pytest.approx(peak * 0.01)
    assert float(sched(81 * SPE)) == pytest.approx(peak * 0.001)
    # constant tail
    assert float(sched(200 * SPE)) == pytest.approx(peak * 0.001)


def test_single_replica_has_no_warmup_ramp():
    sched = goyal_lr_schedule(BASE_LR, 1, SPE)
    assert float(sched(0)) == pytest.approx(BASE_LR)
    assert float(sched(3 * SPE)) == pytest.approx(BASE_LR)


def test_custom_milestones():
    sched = goyal_lr_schedule(BASE_LR, 4, SPE, decay_epochs=(10, 20), decay_factor=0.5)
    peak = BASE_LR * 4
    assert float(sched(15 * SPE)) == pytest.approx(peak * 0.5)
    assert float(sched(25 * SPE)) == pytest.approx(peak * 0.25)
