"""Roofline trace analysis (utils/roofline.py).

Runs the full aggregation on a miniature trace written in the xprof
chrome-trace schema (gzip ``*.trace.json.gz``, device HLO events carrying
``bytes accessed`` / ``model flops`` / ``hlo_category`` args — the layout
validated against real v5e traces in round 3/4).  Numbers below are chosen
so every derived quantity is hand-checkable.
"""

import gzip
import json
import os

import numpy as np
import pytest

from distributeddeeplearning_tpu.utils.roofline import (
    analyze_trace,
    device_op_events,
    find_trace_file,
)


def _write_trace(trace_dir: str, events):
    d = os.path.join(trace_dir, "plugins", "profile", "run1")
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, "host.trace.json.gz")
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)
    return path


def _dev_event(name, dur_us, nbytes, flops, category):
    return {
        "ph": "X", "name": name, "ts": 0, "dur": dur_us, "pid": 1, "tid": 1,
        "args": {
            "bytes accessed": str(nbytes),
            "model flops": str(flops),
            "hlo_category": category,
        },
    }


@pytest.fixture()
def mini_trace(tmp_path):
    """2 traced steps: per step one conv fusion at exactly 800 GB/s
    (80 MB / 100 us) and one copy at 100 GB/s (1 MB / 10 us)."""
    events = []
    for _ in range(2):
        events.append(
            _dev_event("fusion.1", 100.0, 80_000_000, 5_000_000_000,
                       "convolution fusion")
        )
        events.append(_dev_event("copy.1", 10.0, 1_000_000, 0, "copy"))
    # host noise the parser must ignore: no byte args / wrong phase
    events.append({"ph": "X", "name": "hostThing", "ts": 0, "dur": 50,
                   "pid": 9, "tid": 9, "args": {}})
    events.append({"ph": "M", "name": "meta", "pid": 1, "args": {}})
    _write_trace(str(tmp_path), events)
    return str(tmp_path)


def test_event_filtering(mini_trace):
    events = device_op_events(find_trace_file(mini_trace))
    assert len(events) == 4  # host noise dropped
    assert {e["category"] for e in events} == {"convolution fusion", "copy"}


def test_aggregation_hand_checked(mini_trace):
    r = analyze_trace(
        mini_trace, steps=2, global_batch=256,
        peak_hbm_gbps=819.0, peak_tflops=394.0,
    )
    # per step: 81 MB, 110 us (gb field rounds to 2 decimals)
    assert r["hbm_gb_per_step"] == pytest.approx(0.08, abs=0.006)
    assert r["device_ms_per_step"] == pytest.approx(0.11)
    assert r["model_gflops_per_step"] == pytest.approx(5.0)
    # conv at 800 GB/s >= 0.6*819 -> bandwidth-bound; copy at 100 GB/s not
    assert r["bw_bound_time_fraction"] == pytest.approx(100 / 110, abs=1e-3)
    assert r["verdict"] == "hbm-bandwidth-bound"
    # ceiling: 81 MB / 819 GB/s = 98.9 us -> vs 110 us measured (the ms
    # fields round to 2 decimals — coarse at mini-trace scale, fine at the
    # real ~95 ms scale; the ratio fields carry the precision)
    assert r["bandwidth_ceiling_ms_per_step"] == pytest.approx(0.0989, abs=0.01)
    assert r["pct_of_bandwidth_ceiling"] == pytest.approx(0.0989 / 0.11, abs=1e-2)
    assert r["implied_ceiling_img_sec"] == pytest.approx(
        256 / 0.0989e-3, rel=0.02
    )
    cat = r["categories"]["convolution fusion"]
    assert cat["sustained_gbps"] == pytest.approx(800.0)
    assert cat["time_fraction"] == pytest.approx(100 / 110, abs=1e-3)
    assert r["top_fusions"][0]["name"] == "fusion.1"


def test_alternate_arg_spellings(tmp_path):
    events = [{
        "ph": "X", "name": "f", "ts": 0, "dur": 10.0, "pid": 1, "tid": 1,
        "args": {"bytes_accessed": 50_000_000, "flops": 500,
                 "category": "fusion"},
    }]
    _write_trace(str(tmp_path), events)
    r = analyze_trace(str(tmp_path), steps=1)
    assert r["hbm_gb_per_step"] == pytest.approx(0.05, abs=0.006)
    assert "fusion" in r["categories"]


def test_empty_trace_raises(tmp_path):
    _write_trace(str(tmp_path), [])
    with pytest.raises(ValueError, match="no device HLO events"):
        analyze_trace(str(tmp_path), steps=1)


def test_missing_trace_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        find_trace_file(str(tmp_path))


def test_even_lane_split_warns(tmp_path, caplog):
    """Busiest-pid sanity check: when the winner holds ~an even 1/n share
    of device time (one device's events possibly split across pids), the
    analyzer says so instead of silently dropping lanes."""
    import logging

    events = []
    for pid in (1, 2):  # one device's step stream split over two pids
        ev = _dev_event("fusion.1", 100.0, 80_000_000, 0, "fusion")
        ev["pid"] = pid
        events.append(ev)
    _write_trace(str(tmp_path), events)
    with caplog.at_level(logging.WARNING, logger="ddlt.roofline"):
        r = analyze_trace(str(tmp_path), steps=1)
    assert r["device_lanes_in_trace"] == 2
    assert r["busiest_lane_share"] == pytest.approx(0.5)
    assert r["lane_warning"] and "even split" in r["lane_warning"]
    assert any("even split" in m for m in caplog.messages)


def test_dominant_lane_does_not_warn(mini_trace):
    r = analyze_trace(mini_trace, steps=2)
    assert r["device_lanes_in_trace"] == 1
    assert r["busiest_lane_share"] == pytest.approx(1.0)
    assert r["lane_warning"] is None


def test_stream_pids_merge_by_device_name(tmp_path):
    """process_name metadata naming two pids as streams of ONE device
    regroups them into a single lane — per-step time/bytes become the SUM,
    not the busiest stream's half."""
    events = [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/device:TPU:0 stream#1"}},
        {"ph": "M", "name": "process_name", "pid": 2,
         "args": {"name": "/device:TPU:0 stream#2"}},
    ]
    for pid, dur in ((1, 100.0), (2, 60.0)):
        ev = _dev_event("fusion.1", dur, 40_000_000, 0, "fusion")
        ev["pid"] = pid
        events.append(ev)
    _write_trace(str(tmp_path), events)
    r = analyze_trace(str(tmp_path), steps=1)
    assert r["device_lanes_in_trace"] == 1  # merged
    assert r["lane_warning"] is None
    assert r["device_ms_per_step"] == pytest.approx(0.16)  # 100+60 us
    assert r["hbm_gb_per_step"] == pytest.approx(0.08, abs=0.006)
