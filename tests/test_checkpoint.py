"""Sharded checkpoint/resume semantics (the protocol the reference only had
in dead code — PyTorch_hvd:62-72,133-144), plus the durable-state layer:
verified manifests, corruption-tolerant fallback restore, the params-only
item split, torn-writer semantics and the async-save/eviction interleave."""

import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearning_tpu.data.synthetic import synthetic_batch
from distributeddeeplearning_tpu.models import get_model
from distributeddeeplearning_tpu.obs.recorder import get_recorder
from distributeddeeplearning_tpu.obs.registry import get_registry
from distributeddeeplearning_tpu.parallel import MeshSpec, create_mesh, shard_batch
from distributeddeeplearning_tpu.train.checkpoint import (
    MANIFEST_NAME,
    CheckpointCorruptionError,
    Checkpointer,
    corrupt_generation,
    latest_verified_step_in_dir,
    load_manifest,
)
from distributeddeeplearning_tpu.train.resilience import PreemptionError
from distributeddeeplearning_tpu.train.state import create_train_state, sgd_momentum
from distributeddeeplearning_tpu.train.step import build_train_step
from distributeddeeplearning_tpu.utils import faults as faults_mod

IMG = (24, 24, 3)
NCLS = 7


@pytest.fixture(autouse=True)
def _clean_fault_plan():
    """Tests install explicit plans; none may leak into the next test."""
    yield
    faults_mod.install_plan("")


@dataclasses.dataclass
class MiniState:
    """Minimal TrainState stand-in: the Checkpointer touches exactly
    these fields (checkpoint-layer tests need no optimizer)."""

    step: object
    params: object
    opt_state: object
    batch_stats: object

    def replace(self, **kw):
        return dataclasses.replace(self, **kw)


def mini_state(step: int = 0, scale: float = 1.0) -> MiniState:
    return MiniState(
        step=jnp.int32(step),
        params={
            "w": scale * jnp.arange(4096, dtype=jnp.float32).reshape(64, 64),
            "b": scale * jnp.ones(64, jnp.float32),
        },
        opt_state={"m": jnp.zeros(64, jnp.float32)},
        batch_stats={},
    )


@pytest.fixture(scope="module")
def setup():
    mesh = create_mesh(MeshSpec())
    model = get_model("resnet18", num_classes=NCLS, dtype=jnp.float32)
    tx = sgd_momentum(optax.constant_schedule(0.05))

    def mk_state():
        return create_train_state(jax.random.key(0), model, (8, *IMG), tx)

    step = build_train_step(mesh, mk_state(), compute_dtype=jnp.float32)
    batch = shard_batch(mesh, synthetic_batch(16, IMG, NCLS))
    return mesh, mk_state, step, batch


def test_save_restore_roundtrip(setup, tmp_path):
    mesh, mk_state, step, batch = setup
    state = mk_state()
    for _ in range(3):
        state, _ = step(state, batch)
    ckpt = Checkpointer(str(tmp_path / "ckpt"))
    assert ckpt.save(3, state)
    ckpt.wait()

    restored, step_no = Checkpointer(str(tmp_path / "ckpt")).restore(mk_state())
    assert step_no == 3
    assert int(restored.step) == 3
    for a, b in zip(
        jax.tree_util.tree_leaves(state.params),
        jax.tree_util.tree_leaves(restored.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # optimizer momentum restored too
    for a, b in zip(
        jax.tree_util.tree_leaves(state.opt_state),
        jax.tree_util.tree_leaves(restored.opt_state),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_restore_empty_dir_returns_template(setup, tmp_path):
    _, mk_state, _, _ = setup
    ckpt = Checkpointer(str(tmp_path / "empty"))
    state, step_no = ckpt.restore(mk_state())
    assert step_no is None
    assert int(state.step) == 0


def test_latest_step_and_max_to_keep(setup, tmp_path):
    _, mk_state, step, batch = setup
    state = mk_state()
    ckpt = Checkpointer(str(tmp_path / "many"), max_to_keep=2)
    for i in range(1, 5):
        state, _ = step(state, batch)
        ckpt.save(i, state)
    ckpt.wait()
    assert ckpt.latest_step() == 4
    steps = sorted(
        int(p.name) for p in (tmp_path / "many").iterdir() if p.name.isdigit()
    )
    assert steps == [3, 4]


def test_resume_training_continues_identically(setup, tmp_path):
    """Deterministic resume: train 2+2 steps with a mid-save must equal 4
    straight steps (the reference never achieved this — broadcast resume was
    dead code)."""
    mesh, mk_state, step, batch = setup

    state_a = mk_state()
    for _ in range(4):
        state_a, ma = step(state_a, batch)

    state_b = mk_state()
    for _ in range(2):
        state_b, _ = step(state_b, batch)
    ckpt = Checkpointer(str(tmp_path / "resume"))
    ckpt.save(2, state_b)
    ckpt.wait()
    resumed, _ = Checkpointer(str(tmp_path / "resume")).restore(mk_state())
    for _ in range(2):
        resumed, mb = step(resumed, batch)

    np.testing.assert_allclose(float(ma["loss"]), float(mb["loss"]), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(state_a.params),
        jax.tree_util.tree_leaves(resumed.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


# --------------------------------------------------------------------------
# durable state: manifests, verified restore, fallback, torn writers
# --------------------------------------------------------------------------


def test_manifest_commits_only_after_wait(tmp_path):
    """A manifest may only ever certify data that has fully landed: with
    one async save in flight the generation has no manifest (not
    restore-eligible to a fresh reader); wait() commits it."""
    ckpt = Checkpointer(str(tmp_path / "d"))
    try:
        ckpt.save(1, mini_state(1))
        # single in-flight async save: its manifest is still pending
        assert load_manifest(tmp_path / "d" / "1") is None
        # a FRESH reader (serve startup racing the writer) must not
        # trust the unfinalized generation
        reader = Checkpointer(str(tmp_path / "d"))
        try:
            assert reader.latest_verified_step() is None
        finally:
            reader._mgr.close()  # close() would commit nothing but waits
        ckpt.wait()
        manifest = load_manifest(tmp_path / "d" / "1")
        assert manifest is not None and manifest["step"] == 1
        assert ckpt.latest_verified_step() == 1
        assert latest_verified_step_in_dir(tmp_path / "d") == 1
    finally:
        ckpt.close()


def test_params_only_item_layout_and_restore(tmp_path):
    """Generations carry a separate ``params`` item, and restore_params
    reads it back exactly (the serve-startup read no longer pays for the
    optimizer state's bytes)."""
    ckpt = Checkpointer(str(tmp_path / "d"))
    try:
        st = mini_state(3, scale=2.5)
        ckpt.save(3, st)
        ckpt.wait()
        assert (tmp_path / "d" / "3" / "params").is_dir()
        assert (tmp_path / "d" / "3" / "state").is_dir()
        params, step = ckpt.restore_params()
        assert step == 3
        for k in ("w", "b"):
            np.testing.assert_array_equal(
                np.asarray(params[k]), np.asarray(st.params[k])
            )
    finally:
        ckpt.close()


@pytest.mark.parametrize("mode", ["flip", "truncate", "unlink", "manifest"])
def test_corrupt_latest_falls_back_to_verified(tmp_path, mode):
    """One corrupt latest generation costs ONE generation of progress:
    restore walks back to the newest verified one, bumps the
    ckpt.verify_failures counter and leaves a flight-recorder dump
    naming the failed generation."""
    reg = get_registry()
    rec = get_recorder()
    rec.drain_dumps()
    before = reg.counter("ckpt.verify_failures").value
    ckpt = Checkpointer(str(tmp_path / "d"))
    try:
        ckpt.save(1, mini_state(1, scale=1.0))
        ckpt.save(2, mini_state(2, scale=7.0))
        ckpt.wait()
        corrupt_generation(tmp_path / "d" / "2", mode)
        state, step = ckpt.restore(mini_state())
        assert step == 1
        assert int(np.asarray(state.step)) == 1
        np.testing.assert_array_equal(
            np.asarray(state.params["b"]), np.ones(64, np.float32)
        )
        # the fallback is observable: counter + dump name the generation
        assert reg.counter("ckpt.verify_failures").value > before
        dumps = rec.drain_dumps()
        assert any(
            d["reason"] == "ckpt_verify_failed" and d.get("generation") == 2
            for d in dumps
        ), [d.get("reason") for d in dumps]
        # restore_params takes the same fallback
        params, pstep = ckpt.restore_params()
        assert pstep == 1
        np.testing.assert_array_equal(
            np.asarray(params["b"]), np.ones(64, np.float32)
        )
    finally:
        ckpt.close()


def test_latest_verified_step_skips_corrupt_manifest(tmp_path):
    ckpt = Checkpointer(str(tmp_path / "d"))
    try:
        ckpt.save(1, mini_state(1))
        ckpt.save(2, mini_state(2))
        ckpt.wait()
        assert ckpt.latest_verified_step() == 2
        corrupt_generation(tmp_path / "d" / "2", "manifest")
        assert ckpt.latest_verified_step() == 1
        assert latest_verified_step_in_dir(tmp_path / "d") == 1
    finally:
        ckpt.close()


def test_ckpt_torn_fault_leaves_generation_ineligible(tmp_path):
    """ckpt_torn models the writer dying mid-generation: data truncated,
    manifest never written — restore must treat the generation as
    incomplete and resume from the previous one."""
    faults_mod.install_plan("ckpt_torn@2")
    ckpt = Checkpointer(str(tmp_path / "d"))
    try:
        ckpt.save(1, mini_state(1))
        ckpt.save(2, mini_state(2))
        ckpt.wait()
        assert load_manifest(tmp_path / "d" / "2") is None
        assert ckpt.latest_verified_step() == 1
        state, step = ckpt.restore(mini_state())
        assert step == 1 and int(np.asarray(state.step)) == 1
        plan = faults_mod.get_plan()
        assert [e.kind for e in plan.events] == ["ckpt_torn"]
    finally:
        ckpt.close()


def test_ckpt_corrupt_fault_fires_at_nth_generation(tmp_path):
    """The @N trigger is generation-opportunity keyed: @2 corrupts the
    SECOND finalized generation (the latest of this run)."""
    faults_mod.install_plan("ckpt_corrupt@2:mode=flip")
    ckpt = Checkpointer(str(tmp_path / "d"))
    try:
        ckpt.save(1, mini_state(1))
        ckpt.save(2, mini_state(2))
        ckpt.wait()
        _, step = ckpt.restore(mini_state())
        assert step == 1  # gen 2 was corrupted after finalize
        plan = faults_mod.get_plan()
        assert [e.kind for e in plan.events] == ["ckpt_corrupt"]
    finally:
        ckpt.close()


def test_every_generation_corrupt_raises_loudly(tmp_path):
    """An all-corrupt store must FAIL, not silently restart from scratch
    (and not restart-loop: CheckpointCorruptionError is deliberately not
    a RestartableError)."""
    from distributeddeeplearning_tpu.train.resilience import RestartableError

    ckpt = Checkpointer(str(tmp_path / "d"))
    try:
        ckpt.save(1, mini_state(1))
        ckpt.wait()
        corrupt_generation(tmp_path / "d" / "1", "flip")
        with pytest.raises(CheckpointCorruptionError):
            ckpt.restore(mini_state())
        assert not issubclass(CheckpointCorruptionError, RestartableError)
    finally:
        ckpt.close()


def test_legacy_manifestless_dir_still_restores(tmp_path):
    """Pre-durability checkpoints (single ``default`` item, no manifest
    anywhere, no marker) keep restoring through the legacy full-read
    path — both restore() and restore_params()."""
    import orbax.checkpoint as ocp

    d = tmp_path / "legacy"
    mgr = ocp.CheckpointManager(
        str(d), options=ocp.CheckpointManagerOptions(create=True)
    )
    st = mini_state(5, scale=3.0)
    mgr.save(
        5,
        args=ocp.args.StandardSave({
            "step": st.step, "params": st.params,
            "opt_state": st.opt_state, "batch_stats": st.batch_stats,
        }),
    )
    mgr.wait_until_finished()
    mgr.close()
    ckpt = Checkpointer(str(d))
    try:
        assert ckpt.latest_verified_step() == 5  # legacy trust + warning
        state, step = ckpt.restore(mini_state())
        assert step == 5
        np.testing.assert_array_equal(
            np.asarray(state.params["w"]), np.asarray(st.params["w"])
        )
        params, pstep = ckpt.restore_params()
        assert pstep == 5
        np.testing.assert_array_equal(
            np.asarray(params["b"]), np.asarray(st.params["b"])
        )
    finally:
        ckpt.close()


# --------------------------------------------------------------------------
# async-save / eviction interleaving (satellite)
# --------------------------------------------------------------------------


def test_eviction_racing_pending_async_save(tmp_path):
    """max_to_keep eviction can delete a generation whose manifest is
    still pending: the pending entry is dropped (no crash, no manifest
    for a ghost dir) and every SURVIVING generation ends verified."""
    ckpt = Checkpointer(str(tmp_path / "d"), max_to_keep=2)
    try:
        for i in range(1, 6):
            ckpt.save(i, mini_state(i))
        ckpt.wait()
        kept = sorted(
            int(p.name) for p in (tmp_path / "d").iterdir()
            if p.name.isdigit()
        )
        assert kept == [4, 5]
        for s in kept:
            assert load_manifest(tmp_path / "d" / str(s)) is not None
        assert ckpt.latest_verified_step() == 5
        # no orphaned pending entries left behind
        assert ckpt._pending_manifests == {}
    finally:
        ckpt.close()


def test_wait_before_restore_contract_same_process(tmp_path):
    """Within one process the writer must wait() before its own restore:
    the freshly-saved generation becomes eligible only after the drain
    (before it, restore sees the older verified generation)."""
    ckpt = Checkpointer(str(tmp_path / "d"))
    try:
        ckpt.save(1, mini_state(1))
        ckpt.wait()
        ckpt.save(2, mini_state(2))
        # gen 2's manifest is pending: restore must land on gen 1
        _, step = ckpt.restore(mini_state())
        assert step == 1
        ckpt.wait()
        _, step = ckpt.restore(mini_state())
        assert step == 2
    finally:
        ckpt.close()


def test_close_on_preemption_error_path_commits_manifest(tmp_path):
    """The PreemptionError unwind: emergency save -> raise -> close() in
    the finally.  close() drains AND commits the manifest, so the
    restart actually gets the emergency generation."""
    ckpt = Checkpointer(str(tmp_path / "d"))
    with pytest.raises(PreemptionError):
        try:
            ckpt.save(7, mini_state(7))
            raise PreemptionError("preempted at step 7", step=7)
        finally:
            ckpt.close()
    reader = Checkpointer(str(tmp_path / "d"))
    try:
        assert reader.latest_verified_step() == 7
        state, step = reader.restore(mini_state())
        assert step == 7 and int(np.asarray(state.step)) == 7
    finally:
        reader.close()


def test_manifest_is_atomic_json(tmp_path):
    """The manifest itself is written tmp+rename: no .tmp residue, valid
    JSON, and it names every leaf of both items with shape/dtype/crc."""
    ckpt = Checkpointer(str(tmp_path / "d"))
    try:
        ckpt.save(1, mini_state(1))
        ckpt.wait()
        step_dir = tmp_path / "d" / "1"
        assert not list(step_dir.glob("*.tmp"))
        manifest = json.loads((step_dir / MANIFEST_NAME).read_text())
        leaves = manifest["leaves"]
        assert any(k.startswith("params/") for k in leaves)
        assert any(k.startswith("state/") for k in leaves)
        for entry in leaves.values():
            assert set(entry) == {"shape", "dtype", "crc32"}
    finally:
        ckpt.close()


def test_async_manifest_checksum_is_donation_safe(setup, tmp_path):
    """The background checksum must hash a PRIVATE host snapshot: the
    donated train step reuses the state buffers in place right after
    save() returns, so hashing a zero-copy view (what device_get hands
    back on CPU) would checksum clobbered bytes and poison every
    generation's manifest — caught live by ``bench.py --ckpt-faults``."""
    reg = get_registry()
    before = reg.counter("ckpt.verify_failures").value
    mesh, mk_state, step, batch = setup
    state = mk_state()
    ckpt = Checkpointer(str(tmp_path / "d"))
    try:
        for i in range(1, 4):
            state, _ = step(state, batch)
            ckpt.save(i, state)  # the next step donates state's buffers
        ckpt.wait()
        restored, s = ckpt.restore(mk_state())
        assert s == 3  # the LATEST generation verified — no fallback
        assert reg.counter("ckpt.verify_failures").value == before
        # ... and a generation whose async write RACED later donated
        # steps still holds its own step's bytes: drop gen 3, restore
        # gen 2, whose saved step value must be exactly 2 (the aliasing
        # bug stored a LATER step's clobbered buffer here)
        corrupt_generation(tmp_path / "d" / "3", "manifest")
        restored2, s2 = ckpt.restore(mk_state())
        assert s2 == 2
        assert int(np.asarray(restored2.step)) == 2
    finally:
        ckpt.close()


# --------------------------------------------------------------------------
# CKPT_DURABLE artifact schema: accept / reject
# --------------------------------------------------------------------------


def _minimal_ckpt_durable_payload():
    return {
        "metric": "ckpt_durable_verify_overhead_pct",
        "value": 1.0, "unit": "%", "bench_revision": 16,
        "platform": "cpu", "virtual_pod": True,
        "faults_spec": "ckpt_corrupt@4:mode=flip",
        "resume": {
            "expected_step": 6, "resumed_step": 6, "exact": True,
            "verify_failures_observed": 1,
        },
        "corrupt_modes": {
            "flip": {"recovered": True},
            "torn": {"recovered": True},
        },
        "reload": {"replicas": 2, "acks": 2, "bit_identical": True},
        "verify_overhead": {
            "save_wall_s": 1.0, "verify_wall_s": 0.01,
            "pct": 1.0, "limit_pct": 10.0,
        },
        "gates": {
            "resume_exact": True, "zero_bricked": True,
            "corrupt_modes_recovered": True,
            "reload_bit_identical": True,
            "verify_overhead_under_limit": True,
            "fallback_observable": True,
        },
    }


def test_ckpt_durable_schema_accepts_minimal_payload():
    from distributeddeeplearning_tpu.obs.schema import (
        validate_ckpt_durable_payload,
    )

    validate_ckpt_durable_payload(_minimal_ckpt_durable_payload())


@pytest.mark.parametrize("breakage", [
    ("resume", None),
    ("corrupt_modes", {}),
    ("reload", {"replicas": 2, "acks": 2}),
    ("gates", {"resume_exact": True}),
    ("verify_overhead", {"pct": 1.0}),
])
def test_ckpt_durable_schema_rejects_drifted_payloads(breakage):
    from distributeddeeplearning_tpu.obs.schema import (
        SchemaError,
        validate_ckpt_durable_payload,
    )

    key, bad = breakage
    payload = _minimal_ckpt_durable_payload()
    if bad is None:
        del payload[key]
    else:
        payload[key] = bad
    with pytest.raises(SchemaError):
        validate_ckpt_durable_payload(payload)


def test_ckpt_durable_schema_rejects_no_chaos_run():
    """An artifact with zero verification failures never exercised the
    fallback — reject it (same principle as OBS_FLEET's no-death rule)."""
    from distributeddeeplearning_tpu.obs.schema import (
        SchemaError,
        validate_ckpt_durable_payload,
    )

    payload = _minimal_ckpt_durable_payload()
    payload["resume"]["verify_failures_observed"] = 0
    with pytest.raises(SchemaError):
        validate_ckpt_durable_payload(payload)


@pytest.mark.slow
@pytest.mark.timeout(560)
def test_bench_ckpt_faults_smoke(tmp_path):
    """``bench.py --ckpt-faults`` end to end: schema-valid CKPT_DURABLE
    artifact, all gates green (corrupt-latest resume exact, every
    corruption mode recovered, fleet reload bit-identical, verify
    overhead in budget)."""
    import os
    import subprocess
    import sys as _sys

    from distributeddeeplearning_tpu.obs.schema import validate_artifact

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    report = tmp_path / "CKPT_DURABLE_r98.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DDLT_FAULTS", None)
    proc = subprocess.run(
        [
            _sys.executable, os.path.join(repo, "bench.py"),
            "--ckpt-faults", "--small",
            "--report", str(report),
        ],
        cwd=repo, env=env, capture_output=True, text=True, timeout=540,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    line = validate_artifact(str(report))
    assert line["bench_revision"] >= 16
    assert all(line["gates"].values()), line["gates"]
    assert line["resume"]["exact"]
    assert line["reload"]["bit_identical"]


def test_policy_skipped_save_keeps_inflight_manifest_pending(tmp_path):
    """A save() the manager's policy skips (save_interval_steps) must not
    drop the still-in-flight previous generation's pending manifest —
    orbax's should_save returns False WITHOUT waiting for the in-flight
    commit, so the final step dir may not exist yet.  The manifest
    commits at the next drain and the generation stays verified."""
    ckpt = Checkpointer(str(tmp_path / "d"), save_interval_steps=2)
    try:
        assert ckpt.save(2, mini_state(2)) is True
        assert ckpt.save(3, mini_state(3)) is False  # policy skip
        ckpt.wait()
        assert ckpt.latest_verified_step() == 2
        assert load_manifest(tmp_path / "d" / "2") is not None
        assert ckpt._pending_manifests == {}
    finally:
        ckpt.close()


def test_fallback_evicts_corrupt_generation_so_step_resaves(tmp_path):
    """The trainer-path restore DELETES a generation that failed
    verification: left in place it would wedge its step forever (orbax
    silently skips re-saving any step <= the latest existing one), so
    the resumed run's recovered progress would never persist."""
    ckpt = Checkpointer(str(tmp_path / "d"))
    try:
        ckpt.save(1, mini_state(1))
        ckpt.save(2, mini_state(2, scale=2.0))
        ckpt.wait()
        corrupt_generation(tmp_path / "d" / "2", "flip")
        _, step = ckpt.restore(mini_state())
        assert step == 1
        assert not (tmp_path / "d" / "2").exists()  # evicted, not wedged
        # the resumed run re-saves the SAME step — and it must stick
        assert ckpt.save(2, mini_state(2, scale=9.0)) is True
        ckpt.wait()
        state, step = ckpt.restore(mini_state())
        assert step == 2
        np.testing.assert_array_equal(
            np.asarray(state.params["b"]), 9.0 * np.ones(64, np.float32)
        )
    finally:
        ckpt.close()


def test_restore_params_never_evicts_the_store(tmp_path):
    """Serving is a read-only consumer: its fallback must leave the
    (trainer-owned) corrupt generation in place."""
    ckpt = Checkpointer(str(tmp_path / "d"))
    try:
        ckpt.save(1, mini_state(1))
        ckpt.save(2, mini_state(2))
        ckpt.wait()
        corrupt_generation(tmp_path / "d" / "2", "flip")
        _, step = ckpt.restore_params()
        assert step == 1
        assert (tmp_path / "d" / "2").exists()  # untouched
    finally:
        ckpt.close()
