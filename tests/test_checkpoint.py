"""Sharded checkpoint/resume semantics (the protocol the reference only had
in dead code — PyTorch_hvd:62-72,133-144)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearning_tpu.data.synthetic import synthetic_batch
from distributeddeeplearning_tpu.models import get_model
from distributeddeeplearning_tpu.parallel import MeshSpec, create_mesh, shard_batch
from distributeddeeplearning_tpu.train.checkpoint import Checkpointer
from distributeddeeplearning_tpu.train.state import create_train_state, sgd_momentum
from distributeddeeplearning_tpu.train.step import build_train_step

IMG = (24, 24, 3)
NCLS = 7


@pytest.fixture(scope="module")
def setup():
    mesh = create_mesh(MeshSpec())
    model = get_model("resnet18", num_classes=NCLS, dtype=jnp.float32)
    tx = sgd_momentum(optax.constant_schedule(0.05))

    def mk_state():
        return create_train_state(jax.random.key(0), model, (8, *IMG), tx)

    step = build_train_step(mesh, mk_state(), compute_dtype=jnp.float32)
    batch = shard_batch(mesh, synthetic_batch(16, IMG, NCLS))
    return mesh, mk_state, step, batch


def test_save_restore_roundtrip(setup, tmp_path):
    mesh, mk_state, step, batch = setup
    state = mk_state()
    for _ in range(3):
        state, _ = step(state, batch)
    ckpt = Checkpointer(str(tmp_path / "ckpt"))
    assert ckpt.save(3, state)
    ckpt.wait()

    restored, step_no = Checkpointer(str(tmp_path / "ckpt")).restore(mk_state())
    assert step_no == 3
    assert int(restored.step) == 3
    for a, b in zip(
        jax.tree_util.tree_leaves(state.params),
        jax.tree_util.tree_leaves(restored.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # optimizer momentum restored too
    for a, b in zip(
        jax.tree_util.tree_leaves(state.opt_state),
        jax.tree_util.tree_leaves(restored.opt_state),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_restore_empty_dir_returns_template(setup, tmp_path):
    _, mk_state, _, _ = setup
    ckpt = Checkpointer(str(tmp_path / "empty"))
    state, step_no = ckpt.restore(mk_state())
    assert step_no is None
    assert int(state.step) == 0


def test_latest_step_and_max_to_keep(setup, tmp_path):
    _, mk_state, step, batch = setup
    state = mk_state()
    ckpt = Checkpointer(str(tmp_path / "many"), max_to_keep=2)
    for i in range(1, 5):
        state, _ = step(state, batch)
        ckpt.save(i, state)
    ckpt.wait()
    assert ckpt.latest_step() == 4
    steps = sorted(
        int(p.name) for p in (tmp_path / "many").iterdir() if p.name.isdigit()
    )
    assert steps == [3, 4]


def test_resume_training_continues_identically(setup, tmp_path):
    """Deterministic resume: train 2+2 steps with a mid-save must equal 4
    straight steps (the reference never achieved this — broadcast resume was
    dead code)."""
    mesh, mk_state, step, batch = setup

    state_a = mk_state()
    for _ in range(4):
        state_a, ma = step(state_a, batch)

    state_b = mk_state()
    for _ in range(2):
        state_b, _ = step(state_b, batch)
    ckpt = Checkpointer(str(tmp_path / "resume"))
    ckpt.save(2, state_b)
    ckpt.wait()
    resumed, _ = Checkpointer(str(tmp_path / "resume")).restore(mk_state())
    for _ in range(2):
        resumed, mb = step(resumed, batch)

    np.testing.assert_allclose(float(ma["loss"]), float(mb["loss"]), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(state_a.params),
        jax.tree_util.tree_leaves(resumed.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)
