"""Int8 quantization subsystem: QTensor math, weight PTQ, int8 KV cache.

The load-bearing guarantees:

- ``quantize``/``dequantize`` round-trip within the 8-bit grid's step and
  ``qdot`` tracks the f32 matmul closely (int8 dot_general + f32 rescale);
- a quantized params pytree flows through the existing forwards (the
  negative-axis QTensor metadata survives the layer scan) and the logits
  stay close to f32;
- the int8 KV cache — dense AND paged — produces the same greedy tokens
  as the f32 cache on serve traffic (the acceptance gate: >= 99% of
  positions), with ``kv_bytes`` (values + scales) <= 55% of the f32
  figure at identical pool geometry;
- byte accounting sums EVERY cache leaf, so scale tensors are charged;
- ``Checkpointer.restore_params(quantize_weights="int8")`` materializes
  the quantized pytree straight from an f32 checkpoint;
- ``bench.py --quant --steps-cap`` runs end-to-end on CPU (fast tier).
"""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.models.pipelined_transformer import (
    forward,
    init_params,
)
from distributeddeeplearning_tpu.quant import (
    QTensor,
    calibrate_params,
    dequantize,
    dequantize_kv,
    params_dtype,
    qdot,
    quantize,
    quantize_kv,
    quantize_params,
)
from distributeddeeplearning_tpu.serve import (
    ContinuousBatchingScheduler,
    InferenceEngine,
    PagedInferenceEngine,
    cache_bytes,
    init_cache,
    init_paged_cache,
    page_bytes,
    synthetic_requests,
)

CFG = dict(num_layers=2, d_model=64, num_heads=4, d_ff=128, vocab_size=61,
           max_len=96)
HEADS = CFG["num_heads"]
HEAD_DIM = CFG["d_model"] // HEADS


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.key(0), **CFG)


# --------------------------------------------------------------------------
# QTensor / qdot
# --------------------------------------------------------------------------

def test_quantize_roundtrip_within_grid_step():
    w = jax.random.normal(jax.random.key(1), (32, 48)) * 0.1
    qt = quantize(w)
    assert qt.values.dtype == jnp.int8
    assert qt.scales.shape == (1, 48)  # keepdims per-output-channel
    # absmax symmetric grid: error bounded by half a step per channel
    step = np.asarray(qt.scales)[0]  # [48]
    err = np.abs(np.asarray(dequantize(qt)) - np.asarray(w))
    assert (err <= step[None, :] * 0.5 + 1e-7).all()


def test_quantize_block_scales_shape_and_roundtrip():
    w = jax.random.normal(jax.random.key(2), (32, 48)) * 0.1
    qb = quantize(w, block=8)
    assert qb.scales.shape == (4, 1, 48)  # 32/8 blocks, keepdims, per-chan
    err = float(jnp.abs(dequantize(qb) - w).max())
    # block scales are never looser than whole-axis absmax scales
    assert err <= float(jnp.abs(dequantize(quantize(w)) - w).max()) + 1e-7


def test_qdot_matches_f32_matmul():
    w = jax.random.normal(jax.random.key(3), (64, 96)) * 0.05
    x = jax.random.normal(jax.random.key(4), (3, 7, 64))
    qt = quantize(w)
    out_q = np.asarray(qdot(x, qt))
    out_f = np.asarray(x @ w)
    rel = np.abs(out_q - out_f).mean() / np.abs(out_f).mean()
    assert rel < 0.02, f"int8 matmul drifted {rel:.3%} from f32"


def test_qdot_lowers_to_int8_dot_general():
    """The compute path really is int8: the jaxpr contains a dot_general
    whose operands are int8 with an int32 accumulator — not a dequantize
    followed by an f32 dot."""
    w = jax.random.normal(jax.random.key(5), (16, 8)) * 0.1
    qt = quantize(w)
    x = jnp.ones((4, 16))
    jaxpr = jax.make_jaxpr(lambda a: qdot(a, qt))(x)
    dots = [e for e in jaxpr.jaxpr.eqns if e.primitive.name == "dot_general"]
    assert dots, "no dot_general in qdot"
    (dot,) = dots
    assert all(str(v.aval.dtype) == "int8" for v in dot.invars)
    assert str(dot.outvars[0].aval.dtype) == "int32"


def test_qtensor_is_a_pytree_and_scan_slices_it():
    """A stacked [L, K, N] QTensor scanned by lax.scan yields per-layer
    [K, N] QTensors whose negative-axis metadata is still valid."""
    w = jax.random.normal(jax.random.key(6), (3, 8, 10)) * 0.1
    qt = quantize(w)  # axis=-2 on the stacked leaf
    leaves, treedef = jax.tree_util.tree_flatten(qt)
    assert len(leaves) == 2
    assert jax.tree_util.tree_unflatten(treedef, leaves).axis == qt.axis

    def body(carry, layer_qt):
        return carry + jnp.sum(dequantize(layer_qt)), None

    total, _ = jax.lax.scan(body, jnp.float32(0), qt)
    assert np.isclose(float(total), float(dequantize(qt).sum()), atol=1e-3)


def test_quantize_kv_per_position_per_head():
    x = jax.random.normal(jax.random.key(7), (5, HEADS, HEAD_DIM))
    vals, scales = quantize_kv(x)
    assert vals.dtype == jnp.int8 and vals.shape == x.shape
    assert scales.shape == (5, HEADS)  # one scale per (position, head)
    err = np.abs(np.asarray(dequantize_kv(vals, scales)) - np.asarray(x))
    assert (err <= np.asarray(scales)[..., None] * 0.5 + 1e-7).all()


# --------------------------------------------------------------------------
# weight PTQ / calibration
# --------------------------------------------------------------------------

def test_quantize_params_leaves_and_passthrough(params):
    qp = quantize_params(params)
    for name in ("qkv", "proj", "w_in", "w_out"):
        assert isinstance(qp["blocks"][name], QTensor)
        assert qp["blocks"][name].shape == params["blocks"][name].shape
    assert isinstance(qp["head"], QTensor)
    # embeddings / position table / layer norms stay f32 (and identical)
    assert qp["embed"] is params["embed"]
    assert qp["pos"] is params["pos"]
    assert qp["blocks"]["ln1"] is params["blocks"]["ln1"]
    assert params_dtype(params) == "float32"
    assert params_dtype(qp) == "int8"
    with pytest.raises(ValueError, match="already quantized"):
        quantize_params(qp)


def test_quantized_forward_tracks_f32(params):
    qp = quantize_params(params)
    toks = jnp.asarray(
        np.random.default_rng(0).integers(1, CFG["vocab_size"], (2, 12))
    )
    lf = forward(params, toks, num_heads=HEADS)
    lq = forward(qp, toks, num_heads=HEADS)
    # the random-init model's logits are nearly flat (spread ~1e-2), so
    # the meaningful gate is MAE against that spread; argmax agreement is
    # only loosely pinned here (near-ties flip on ulp-level noise — the
    # >= 99% greedy gates live in the KV-cache tests, where margins are
    # the serving workload's own)
    spread = float(jnp.abs(lf - lf.mean(-1, keepdims=True)).mean())
    assert float(jnp.abs(lf - lq).mean()) < max(0.05 * spread, 1e-4)
    assert float((lf.argmax(-1) == lq.argmax(-1)).mean()) >= 0.9


def test_calibrate_params_reports_fidelity(params):
    prompts = [[1, 2, 3, 4, 5], [7, 8, 9], [11, 12, 13, 14]]
    qp, rep = calibrate_params(params, prompts, num_heads=HEADS)
    assert params_dtype(qp) == "int8"
    assert rep.num_prompts == 3
    assert rep.num_positions == sum(len(p) for p in prompts)
    assert rep.logit_mae <= rep.logit_mae_max
    assert 0.0 <= rep.greedy_agreement <= 1.0
    assert rep.logit_mae < 1e-3  # tiny vs any usable logit spread
    # percentile observer path (clips outliers; still close)
    qp2, rep2 = calibrate_params(
        params, prompts, num_heads=HEADS, method="percentile",
        percentile=99.0,
    )
    assert rep2.percentile == 99.0
    assert rep2.greedy_agreement >= 0.9


def test_restore_params_materializes_int8(tmp_path, params):
    from distributeddeeplearning_tpu.train.checkpoint import Checkpointer

    class _State:
        step = jnp.int32(7)
        params = None
        opt_state = {"m": jnp.zeros(3)}
        batch_stats = {"n": jnp.zeros(1)}

    st = _State()
    st.params = params
    ckpt = Checkpointer(str(tmp_path / "ckpt"), async_save=False)
    try:
        assert ckpt.save(7, st)
        restored, step = ckpt.restore_params(quantize_weights="int8")
    finally:
        ckpt.close()
    assert step == 7
    assert params_dtype(restored) == "int8"
    assert isinstance(restored["head"], QTensor)
    np.testing.assert_array_equal(restored["embed"], params["embed"])
    with pytest.raises(ValueError, match="unsupported"):
        ckpt2 = Checkpointer(str(tmp_path / "ckpt"), async_save=False)
        try:
            ckpt2.restore_params(quantize_weights="int4")
        finally:
            ckpt2.close()


# --------------------------------------------------------------------------
# int8 KV cache: byte accounting
# --------------------------------------------------------------------------

def test_cache_bytes_counts_scale_leaves():
    kw = dict(num_layers=2, num_heads=HEADS, head_dim=HEAD_DIM)
    f32 = init_cache(batch_slots=2, max_seq=16, dtype=jnp.float32, **kw)
    q = init_cache(batch_slots=2, max_seq=16, dtype=jnp.int8, **kw)
    assert set(q) == {"k", "v", "k_scale", "v_scale"}
    n = 2 * 2 * 16 * HEADS * HEAD_DIM  # elements per leaf (k or v)
    assert cache_bytes(f32) == 2 * n * 4
    assert cache_bytes(q) == 2 * n * 1 + 2 * (n // HEAD_DIM) * 4
    ratio = cache_bytes(q) / cache_bytes(f32)
    assert ratio == (1 + 4 / HEAD_DIM) / 4
    assert ratio <= 0.55


def test_page_bytes_counts_scale_leaves():
    kw = dict(num_layers=2, page_size=4, num_heads=HEADS, head_dim=HEAD_DIM)
    f32 = init_paged_cache(num_pages=6, dtype=jnp.float32, **kw)
    q = init_paged_cache(num_pages=6, dtype=jnp.int8, **kw)
    assert cache_bytes(q) == 7 * page_bytes(q)  # pages + scratch
    per_tok_head = HEAD_DIM * 1 + 4  # int8 vector + one f32 scale
    assert page_bytes(q) == 2 * 2 * 4 * HEADS * per_tok_head
    assert page_bytes(q) / page_bytes(f32) <= 0.55


# --------------------------------------------------------------------------
# int8 KV cache: greedy agreement vs f32, both layouts
# --------------------------------------------------------------------------

def _run_traffic(engine, requests, max_new):
    res, rep = ContinuousBatchingScheduler(
        engine, max_new_tokens=max_new
    ).run(list(requests))
    return {r.uid: r.tokens for r in res}, rep


def _agreement(a, b):
    tot = match = 0
    for uid in a:
        for x, y in zip(a[uid], b[uid]):
            tot += 1
            match += int(x == y)
    return match / tot


def test_int8_dense_cache_matches_f32_greedy(params):
    reqs = synthetic_requests(
        8, vocab_size=CFG["vocab_size"], max_prompt=12, min_prompt=4,
        rng=np.random.default_rng(0),
    )
    kw = dict(num_heads=HEADS, batch_slots=2, max_seq=32,
              prefill_attention="dense", rng=jax.random.key(1))
    tf, rf = _run_traffic(InferenceEngine(params, **kw), reqs, 8)
    tq, rq = _run_traffic(
        InferenceEngine(params, cache_dtype=jnp.int8, **kw), reqs, 8
    )
    assert _agreement(tf, tq) >= 0.99
    assert rq.kv_dtype == "int8" and rf.kv_dtype == "float32"
    assert rq.kv_bytes / rf.kv_bytes <= 0.55


def test_int8_paged_cache_matches_f32_greedy(params):
    reqs = synthetic_requests(
        8, vocab_size=CFG["vocab_size"], max_prompt=24, min_prompt=6,
        rng=np.random.default_rng(0),
    )
    kw = dict(num_heads=HEADS, batch_slots=2, max_seq=48, page_size=8,
              prefill_chunk=16, rng=jax.random.key(1))
    tf, rf = _run_traffic(PagedInferenceEngine(params, **kw), reqs, 12)
    eq = PagedInferenceEngine(params, cache_dtype=jnp.int8, **kw)
    tq, rq = _run_traffic(eq, reqs, 12)
    assert _agreement(tf, tq) >= 0.99
    assert rq.kv_dtype == "int8"
    assert rq.kv_layout == "paged"
    assert rq.kv_bytes / rf.kv_bytes <= 0.55
    assert rq.kv_bytes_peak / rf.kv_bytes_peak <= 0.55
    eq.allocator.check()  # page bookkeeping survived quantized traffic


def test_int8_paged_prefix_sharing_still_exact(params):
    """Prefix-cache hits under the int8 pool: a shared page's int8 values
    AND scales are reused, so a hit decodes identically to a recompute.
    The shared prefix (12 tokens = 3 pages) is deliberately NOT a
    multiple of prefill_chunk (16), so the hit path starts mid-chunk —
    pinning that quantized prefill is chunk-ALIGNMENT-invariant (an
    exact-own-chunk attention window would break exactly this)."""
    reqs = synthetic_requests(
        6, vocab_size=CFG["vocab_size"], max_prompt=12, min_prompt=4,
        shared_prefix_len=12, rng=np.random.default_rng(3),
    )
    kw = dict(num_heads=HEADS, batch_slots=2, max_seq=48, page_size=4,
              prefill_chunk=16, rng=jax.random.key(1),
              cache_dtype=jnp.int8)
    hit = PagedInferenceEngine(params, **kw)
    t_hit, rep_hit = _run_traffic(hit, reqs, 6)
    miss = PagedInferenceEngine(params, prefix_cache=False, **kw)
    t_miss, rep_miss = _run_traffic(miss, reqs, 6)
    assert rep_hit.prefix_hit_rate > 0.0
    assert rep_miss.prefix_hit_rate == 0.0
    assert t_hit == t_miss
    hit.allocator.check()


def test_int8_dense_cache_shards_over_mesh(params):
    """Sharded dense engine with the int8 cache: the scale leaves shard
    like their values (slots over data axes, heads over tensor) and the
    run completes with sharding preserved through donated decode."""
    from distributeddeeplearning_tpu.parallel import MeshSpec, create_mesh
    from distributeddeeplearning_tpu.serve import Request

    mesh = create_mesh(MeshSpec(), devices=jax.devices()[:2])
    engine = InferenceEngine(
        params, num_heads=HEADS, batch_slots=4, max_seq=24, mesh=mesh,
        prefill_attention="dense", cache_dtype=jnp.int8,
    )
    assert engine.cache["k"].dtype == jnp.int8
    assert engine.cache["k_scale"].sharding.spec[0] == ("data", "fsdp")
    reqs = [
        Request(uid=f"r{i}", prompt=[3 + i, 7, 11])
        for i in range(6)
    ]
    results, report = ContinuousBatchingScheduler(
        engine, max_new_tokens=3
    ).run(reqs)
    assert len(results) == 6
    assert report.kv_dtype == "int8"
    assert engine.cache["k_scale"].sharding.spec[0] == ("data", "fsdp")


def test_int8_weights_plus_kv_serve_end_to_end(params):
    qp = quantize_params(params)
    reqs = synthetic_requests(
        4, vocab_size=CFG["vocab_size"], max_prompt=12, min_prompt=4,
        rng=np.random.default_rng(5),
    )
    eng = PagedInferenceEngine(
        qp, num_heads=HEADS, batch_slots=2, max_seq=32, page_size=8,
        prefill_chunk=8, rng=jax.random.key(1), cache_dtype=jnp.int8,
    )
    toks, rep = _run_traffic(eng, reqs, 6)
    assert all(len(t) == 6 for t in toks.values())
    assert rep.weights_dtype == "int8" and rep.kv_dtype == "int8"
    d = rep.to_dict()
    assert d["weights_dtype"] == "int8"  # ServeReport plumbs provenance


# --------------------------------------------------------------------------
# CI smoke: the quant bench path end-to-end through bench.py on CPU
# --------------------------------------------------------------------------

@pytest.mark.timeout(240)
def test_bench_quant_cpu_smoke(tmp_path):
    """Fast tier-1 smoke: bench.py --quant with a hard --steps-cap so the
    five-engine comparison (flash + gather exhibits) + fidelity probe
    can never hang CI."""
    report = tmp_path / "quant.json"
    proc = subprocess.run(
        [
            sys.executable, "bench.py", "--quant", "--small",
            "--seq-len", "12", "--serve-requests", "6",
            "--batch-slots", "2", "--max-new-tokens", "4",
            "--page-size", "4", "--prefill-chunk", "8",
            "--steps-cap", "50", "--report", str(report),
        ],
        capture_output=True, text=True, timeout=220,
        cwd=str(Path(__file__).resolve().parent.parent),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["value"] <= 0.55  # int8 kv bytes ratio, scales included
    assert set(line["configs"]) == {
        "f32", "kv_int8", "kv_w_int8",
        # PR 12: the legacy gather exhibits ride in the same artifact
        "f32_gather", "kv_int8_gather",
    }
    assert line["configs"]["kv_int8"]["kv_dtype"] == "int8"
    assert line["configs"]["kv_w_int8"]["weights_dtype"] == "int8"
    assert line["fidelity_probe"]["kv_int8"]["positions"] > 0
    assert report.exists()
