"""Paged KV cache: allocator invariants, prefix reuse, chunked prefill,
and the dense-vs-paged bit-exactness gate.

The load-bearing guarantee mirrors the dense suite's: decode through the
page pool + block tables must produce the SAME tokens as the dense layout
(and both must match the full-forward oracle) — the paged layout is a
memory-management change, never a math change.  On top of that the
allocator's alloc/free/refcount/prefix-eviction invariants are exercised
directly (``PageAllocator.check``), and admission backpressure is pinned:
an out-of-pages pool queues requests instead of crashing, and a request
that can never fit fails loudly instead of deadlocking.
"""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.models.pipelined_transformer import (
    forward,
    forward_decode_paged,
    forward_prefill,
    forward_prefill_chunk,
    init_params,
)
from distributeddeeplearning_tpu.serve import (
    ContinuousBatchingScheduler,
    InferenceEngine,
    OutOfPages,
    PageAllocator,
    PagedInferenceEngine,
    Request,
    cache_bytes,
    init_paged_cache,
    insert_pages,
    page_bytes,
    pages_for,
    synthetic_requests,
)

CFG = dict(num_layers=3, d_model=32, num_heads=4, d_ff=64, vocab_size=61,
           max_len=64)
HEADS = CFG["num_heads"]
HEAD_DIM = CFG["d_model"] // HEADS


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.key(0), **CFG)


def _naive_greedy(params, prompt, n):
    toks = list(prompt)
    for _ in range(n):
        logits = forward(params, jnp.asarray([toks], jnp.int32),
                         num_heads=HEADS)
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


# --------------------------------------------------------------------------
# allocator
# --------------------------------------------------------------------------

def test_allocator_alloc_free_refcount_invariants():
    a = PageAllocator(6)
    assert a.available == 6 and a.pages_in_use == 0
    pages = a.alloc(4)
    a.check()
    assert len(set(pages)) == 4 and all(1 <= p <= 6 for p in pages)
    assert a.pages_in_use == 4
    assert all(a.refcount(p) == 1 for p in pages)
    a.incref(pages[0])
    a.decref(pages[0])
    assert a.refcount(pages[0]) == 1  # still live after the paired drop
    for p in pages:
        a.decref(p)
    a.check()
    assert a.available == 6  # everything returned
    with pytest.raises(ValueError, match="non-live"):
        a.decref(pages[0])
    with pytest.raises(OutOfPages):
        a.alloc(7)
    a.check()  # a failed alloc must not leak partial allocations
    assert a.available == 6


def test_allocator_prefix_reclaim_and_lru_eviction():
    a = PageAllocator(3)
    pages = a.alloc(3)
    a.register_prefix(("k0",), pages[0])
    a.register_prefix(("k1",), pages[1])
    for p in pages:
        a.decref(p)
    a.check()
    # registered pages are reclaimable (still findable), not freed
    assert a.available == 3
    assert a.lookup_prefix(("k0",)) == pages[0]
    # resurrect k1, then force eviction: k0 is the LRU victim
    a.incref(a.lookup_prefix(("k1",)))
    fresh = a.alloc(2)  # 1 free + must evict k0
    a.check()
    assert a.lookup_prefix(("k0",)) is None, "evicted entry still resolvable"
    assert a.lookup_prefix(("k1",)) == pages[1]
    assert pages[0] in fresh
    with pytest.raises(ValueError, match="non-live"):
        a.incref(pages[0] if pages[0] not in fresh else 99)


def test_allocator_clear_prefix_returns_pages():
    a = PageAllocator(4)
    pages = a.alloc(2)
    a.register_prefix(("x",), pages[0])
    a.decref(pages[0])
    a.decref(pages[1])
    a.clear_prefix()
    a.check()
    assert a.available == 4
    assert a.lookup_prefix(("x",)) is None
    assert a.prefix_entries == 0


# --------------------------------------------------------------------------
# model-level: paged decode / chunked prefill vs the dense oracle
# --------------------------------------------------------------------------

def test_paged_decode_matches_full_forward_every_position(params):
    """Identity block tables: paged decode from an empty pool == full
    forward at every position (the dense suite's acceptance pin, routed
    through pages)."""
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(1, CFG["vocab_size"], (2, 12)),
        jnp.int32,
    )
    b, s = tokens.shape
    page_size = 4
    full = np.asarray(forward(params, tokens, num_heads=HEADS))
    nb = pages_for(16, page_size)
    cache = init_paged_cache(
        num_pages=b * nb, num_layers=CFG["num_layers"], page_size=page_size,
        num_heads=HEADS, head_dim=HEAD_DIM,
    )
    # slot i owns pages [1 + i*nb, 1 + (i+1)*nb)
    tables = jnp.asarray(
        [[1 + i * nb + j for j in range(nb)] for i in range(b)], jnp.int32
    )
    for t in range(s):
        logits, cache = forward_decode_paged(
            params, tokens[:, t], cache, jnp.full((b,), t, jnp.int32),
            tables, num_heads=HEADS, page_size=page_size,
        )
        np.testing.assert_allclose(
            np.asarray(logits), full[:, t], atol=1e-5,
            err_msg=f"paged decode diverged at position {t}",
        )


def test_chunked_prefill_matches_forward(params):
    """Prefill in 4-token chunks == the monolithic forward's logits at
    every chunk's real positions, and the written pages equal
    forward_prefill's K/V."""
    rng = np.random.default_rng(1)
    prompt = rng.integers(1, CFG["vocab_size"], 11).tolist()
    page_size, chunk = 4, 4
    full = np.asarray(
        forward(params, jnp.asarray([prompt], jnp.int32), num_heads=HEADS)
    )
    _, k_ref, v_ref = forward_prefill(
        params, jnp.asarray([prompt], jnp.int32), num_heads=HEADS
    )
    nb = pages_for(16, page_size)
    cache = init_paged_cache(
        num_pages=nb, num_layers=CFG["num_layers"], page_size=page_size,
        num_heads=HEADS, head_dim=HEAD_DIM,
    )
    table = jnp.arange(1, nb + 1, dtype=jnp.int32)
    off = 0
    while off < len(prompt):
        real = min(chunk, len(prompt) - off)
        toks = np.zeros((1, chunk), np.int32)
        toks[0, :real] = prompt[off:off + real]
        logits, cache = forward_prefill_chunk(
            params, jnp.asarray(toks), cache, table, jnp.int32(off),
            num_heads=HEADS, page_size=page_size,
        )
        np.testing.assert_allclose(
            np.asarray(logits)[0, :real], full[0, off:off + real],
            atol=1e-5, err_msg=f"chunk at offset {off} diverged",
        )
        off += real
    # page contents == the monolithic prefill's K/V, page by page
    k_pages = np.asarray(cache["k"])  # [pages, L, ps, h, hd]
    for j in range(len(prompt)):
        np.testing.assert_allclose(
            k_pages[1 + j // page_size, :, j % page_size],
            np.asarray(k_ref)[0, :, j], atol=1e-6,
        )


def test_insert_pages_roundtrip(params):
    """insert_pages scatters [L, P, h, hd] K/V into listed pages."""
    tokens = jnp.asarray([[5, 17, 3, 42, 8, 9, 11, 2]], jnp.int32)
    _, k, v = forward_prefill(params, tokens, num_heads=HEADS)
    cache = init_paged_cache(
        num_pages=4, num_layers=CFG["num_layers"], page_size=4,
        num_heads=HEADS, head_dim=HEAD_DIM,
    )
    cache = insert_pages(
        cache, k[0], v[0], jnp.asarray([2, 3], jnp.int32), page_size=4
    )
    np.testing.assert_allclose(
        np.asarray(cache["k"])[2, :, :, :, :],
        np.asarray(k)[0, :, 0:4], atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(cache["k"])[3, :, 2],
        np.asarray(k)[0, :, 6], atol=1e-6,
    )
    assert page_bytes(cache) == cache_bytes(cache) // 5  # 4 pages + scratch


# --------------------------------------------------------------------------
# engine + scheduler: bit-exactness, prefix reuse, backpressure
# --------------------------------------------------------------------------

def test_paged_engine_greedy_matches_dense_and_oracle(params):
    """THE acceptance gate: identical (seed, request order) greedy runs
    produce bit-identical token sequences under both layouts, across
    mixed prompt lengths that exercise chunking and slot reuse."""
    rng = np.random.default_rng(2)
    prompts = {
        f"r{i}": rng.integers(1, CFG["vocab_size"],
                              rng.integers(2, 21)).tolist()
        for i in range(8)
    }
    reqs = lambda: [Request(uid=u, prompt=p) for u, p in prompts.items()]  # noqa: E731

    dense = InferenceEngine(params, num_heads=HEADS, batch_slots=2,
                            max_seq=32, prefill_attention="dense")
    d_res, _ = ContinuousBatchingScheduler(
        dense, max_new_tokens=4).run(reqs())
    paged = PagedInferenceEngine(params, num_heads=HEADS, batch_slots=2,
                                 max_seq=32, page_size=4, prefill_chunk=8)
    p_res, p_rep = ContinuousBatchingScheduler(
        paged, max_new_tokens=4).run(reqs())

    d_map = {r.uid: r.tokens for r in d_res}
    p_map = {r.uid: r.tokens for r in p_res}
    assert d_map == p_map, "paged diverged from dense"
    for uid, toks in p_map.items():
        assert toks == _naive_greedy(params, prompts[uid], 4), uid
    assert p_rep.kv_layout == "paged"
    assert p_rep.kv_bytes_peak < p_rep.kv_bytes  # never filled the pool
    # every page returned on completion
    paged.allocator.check()
    assert paged.allocator.pages_in_use == 0


def test_prefix_reuse_hit_and_miss(params):
    """Shared system-prompt workload: later requests map the shared full
    pages (nonzero hit rate), outputs still match the oracle; a
    no-prefix engine records zero hits on the same traffic."""
    rng = np.random.default_rng(3)
    prefix = rng.integers(1, CFG["vocab_size"], 12).tolist()
    prompts = {
        f"s{i}": prefix + rng.integers(1, CFG["vocab_size"], 4).tolist()
        for i in range(5)
    }
    reqs = lambda: [Request(uid=u, prompt=p) for u, p in prompts.items()]  # noqa: E731

    eng = PagedInferenceEngine(params, num_heads=HEADS, batch_slots=2,
                               max_seq=32, page_size=4, prefill_chunk=8)
    res, rep = ContinuousBatchingScheduler(eng, max_new_tokens=3).run(reqs())
    assert rep.prefix_hit_rate > 0
    assert eng.prefix_hit_tokens >= 12 * 2  # later requests reuse >= 3 pages
    for r in res:
        assert r.tokens == _naive_greedy(params, prompts[r.uid], 3), r.uid
    eng.allocator.check()

    miss = PagedInferenceEngine(params, num_heads=HEADS, batch_slots=2,
                                max_seq=32, page_size=4, prefill_chunk=8,
                                prefix_cache=False)
    _, mrep = ContinuousBatchingScheduler(miss, max_new_tokens=3).run(reqs())
    assert mrep.prefix_hit_rate == 0.0


def test_prefix_cache_never_shares_decode_written_pages(params):
    """A page only partially covered by the prompt takes decode writes and
    must never be shared: a second request whose prompt extends the first
    one's beyond the last FULL page gets fresh pages for the tail, and
    its outputs stay oracle-exact."""
    base = [7, 3, 11, 9, 2, 5]  # 6 tokens, page_size 4 -> one full page
    # ONE slot: request b admits only after a completes, so a's pages are
    # registered and the share is observable
    eng = PagedInferenceEngine(params, num_heads=HEADS, batch_slots=1,
                               max_seq=32, page_size=4, prefill_chunk=8)
    sched = ContinuousBatchingScheduler(eng, max_new_tokens=4)
    res, rep = sched.run([
        Request(uid="a", prompt=base),
        Request(uid="b", prompt=base),  # same prompt: shares page 0 only
    ])
    for r in res:
        assert r.tokens == _naive_greedy(params, base, 4), r.uid
    # only the single FULL page (4 of 6 prompt tokens) is shareable
    assert eng.prefix_hit_tokens == 4


def test_out_of_pages_backpressure_and_oversized_request(params):
    """A pool smaller than the offered load queues requests (every one
    still completes, oracle-exact); a request larger than the POOL fails
    as an error instead of deadlocking the queue."""
    rng = np.random.default_rng(4)
    prompts = {
        f"r{i}": rng.integers(1, CFG["vocab_size"], 8).tolist()
        for i in range(5)
    }
    eng = PagedInferenceEngine(params, num_heads=HEADS, batch_slots=4,
                               max_seq=32, page_size=4, num_pages=6,
                               prefill_chunk=8)
    res, rep = ContinuousBatchingScheduler(eng, max_new_tokens=4).run(
        [Request(uid=u, prompt=p) for u, p in prompts.items()]
    )
    assert rep.finish_reasons == {"length": 5}
    for r in res:
        assert r.tokens == _naive_greedy(params, prompts[r.uid], 4), r.uid
    # backpressure showed up as queue wait, and occupancy never exceeded
    # what 6 pages admit (3 tokens/page x 6 = 24 < 4 slots x 12 needed)
    assert rep.queue_wait_s["max"] > 0
    eng.allocator.check()
    assert eng.allocator.available == 6

    big = Request(uid="big", prompt=list(range(1, 28)))  # 27 + 4 > 24
    res2, rep2 = ContinuousBatchingScheduler(eng, max_new_tokens=4).run([big])
    assert res2[0].finish_reason == "error"
    assert "pool holds" in res2[0].error
    eng.allocator.check()


def test_engine_prefill_begin_validation_and_release(params):
    eng = PagedInferenceEngine(params, num_heads=HEADS, batch_slots=2,
                               max_seq=16, page_size=4, prefill_chunk=8)
    with pytest.raises(ValueError, match="empty prompt"):
        eng.prefill_begin(0, [], 4)
    with pytest.raises(ValueError, match="no room"):
        eng.prefill_begin(0, list(range(1, 17)), 4)
    with pytest.raises(ValueError, match="slot"):
        eng.prefill_begin(5, [1, 2], 4)
    task = eng.prefill_begin(0, [1, 2, 3], 4)
    with pytest.raises(ValueError, match="still holds pages"):
        eng.prefill_begin(0, [4, 5], 4)
    assert eng.allocator.pages_in_use == pages_for(3 + 4, 4)
    eng.release(0)
    assert eng.allocator.pages_in_use == 0
    assert (eng.block_tables[0] == 0).all()
    # direct OutOfPages from prefill_begin when the pool is exhausted
    tiny = PagedInferenceEngine(params, num_heads=HEADS, batch_slots=2,
                                max_seq=16, page_size=4, num_pages=2,
                                prefill_chunk=8)
    tiny.prefill_begin(0, [1, 2, 3, 4, 5], 3)  # takes both pages
    with pytest.raises(OutOfPages):
        tiny.prefill_begin(1, [1, 2, 3, 4, 5], 3)
    tiny.allocator.check()
    assert task.shared_tokens == 0


def test_chunked_prefill_interleaves_with_decode(params):
    """A long prompt admitted mid-run is prefilled one chunk per loop
    iteration: decode steps for the running request land BETWEEN the
    newcomer's chunks (TTFT jitter capped), and both finish exact."""
    rng = np.random.default_rng(5)
    short = rng.integers(1, CFG["vocab_size"], 3).tolist()
    long = rng.integers(1, CFG["vocab_size"], 24).tolist()
    eng = PagedInferenceEngine(params, num_heads=HEADS, batch_slots=2,
                               max_seq=40, page_size=4, prefill_chunk=8)
    res, rep = ContinuousBatchingScheduler(eng, max_new_tokens=6).run([
        Request(uid="short", prompt=short),
        Request(uid="long", prompt=long),
    ])
    by = {r.uid: r for r in res}
    assert by["short"].tokens == _naive_greedy(params, short, 6)
    assert by["long"].tokens == _naive_greedy(params, long, 6)
    # the long prompt needed 3 chunks; short decoded while they ran, so
    # short finished FIRST despite the long one being, at 24 tokens, the
    # only O(P^2) work in the run
    assert res[0].uid == "short"
    assert rep.decode_steps >= 6


def test_decode_never_writes_mid_prefill_pages(params):
    """Regression: a slot mid-chunked-prefill keeps its shared block-table
    row at SCRATCH, so interleaved decode steps (whose stale lane writes
    unconditionally at pos 0) cannot corrupt the prompt's already-written
    K/V — or a SHARED prefix page another sequence is attending over."""
    rng = np.random.default_rng(7)
    long = rng.integers(1, CFG["vocab_size"], 16).tolist()
    short = rng.integers(1, CFG["vocab_size"], 3).tolist()
    eng = PagedInferenceEngine(params, num_heads=HEADS, batch_slots=2,
                               max_seq=32, page_size=4, prefill_chunk=8)
    # activate slot 0 with a short request so decode has work to do
    first = eng.prefill(0, short, 4)
    # begin the long prompt on slot 1 and run ONE of its two chunks
    task = eng.prefill_begin(1, long, 4)
    assert eng.prefill_step(task) is None  # chunk 1 of 2: mid-prefill
    assert (eng.block_tables[1] == 0).all(), \
        "mid-prefill slot's decode row must stay at SCRATCH"
    before = np.asarray(eng.cache["k"])[task.pages].copy()
    # decode with slot 1's lane stale at pos 0 (the corruption vector)
    eng.decode(np.array([first, 0], np.int32), np.array([3, 0], np.int32))
    after = np.asarray(eng.cache["k"])[task.pages]
    np.testing.assert_array_equal(
        before, after,
        err_msg="decode wrote into a sequence still being prefilled",
    )
    # finishing the prefill installs the row and decodes correctly
    tok = eng.prefill_step(task)
    assert tok is not None
    assert list(eng.block_tables[1][: len(task.pages)]) == task.pages
    assert tok == _naive_greedy(params, long, 1)[0]


def test_step_cap_terminates_run(params):
    eng = PagedInferenceEngine(params, num_heads=HEADS, batch_slots=2,
                               max_seq=32, page_size=4, prefill_chunk=8)
    res, rep = ContinuousBatchingScheduler(
        eng, max_new_tokens=50, step_cap=4
    ).run([Request(uid=f"c{i}", prompt=[1, 2, 3]) for i in range(4)])
    assert rep.decode_steps == 4
    reasons = rep.finish_reasons
    assert reasons.get("step_cap", 0) >= 1
    assert reasons.get("step_cap", 0) + reasons.get("cancelled", 0) == 4
    eng.allocator.check()
    assert eng.allocator.pages_in_use == 0  # cap released everything


def test_report_queue_wait_and_prefill_compiles(params):
    """Satellites: queue_wait is its own percentile block (admission
    latency separated from prefill), and prefill_compiles counts the
    run's distinct compiled shapes — 0 on a re-run of the same shapes."""
    rng = np.random.default_rng(6)
    reqs = lambda: [  # noqa: E731
        Request(uid=f"r{i}",
                prompt=rng.integers(1, CFG["vocab_size"], 6).tolist())
        for i in range(4)
    ]
    eng = PagedInferenceEngine(params, num_heads=HEADS, batch_slots=2,
                               max_seq=32, page_size=4, prefill_chunk=8)
    _, rep1 = ContinuousBatchingScheduler(eng, max_new_tokens=3).run(reqs())
    assert {"p50", "p99", "mean", "max"} <= set(rep1.queue_wait_s)
    assert rep1.prefill_compiles >= 1
    _, rep2 = ContinuousBatchingScheduler(eng, max_new_tokens=3).run(reqs())
    assert rep2.prefill_compiles == 0  # same shapes: nothing new compiled
    assert rep2.queue_wait_s["max"] <= rep1.queue_wait_s["max"] + 1.0

    dense = InferenceEngine(params, num_heads=HEADS, batch_slots=2,
                            max_seq=32, prefill_attention="dense")
    _, drep1 = ContinuousBatchingScheduler(dense, max_new_tokens=3).run(
        reqs())
    assert drep1.prefill_compiles >= 1  # the 8-bucket
    _, drep2 = ContinuousBatchingScheduler(dense, max_new_tokens=3).run(
        reqs())
    assert drep2.prefill_compiles == 0


def test_paged_engine_chunk_shapes_helper(params):
    eng = PagedInferenceEngine(params, num_heads=HEADS, batch_slots=1,
                               max_seq=64, page_size=4, prefill_chunk=16)
    assert eng.chunk_shapes(40) == {16, 8}  # 16+16+8
    assert eng.chunk_shapes(16) == {16}
    assert eng.chunk_shapes(3) == {8}  # bucket floor


def test_synthetic_requests_shared_prefix():
    reqs = synthetic_requests(
        4, vocab_size=61, max_prompt=6, shared_prefix_len=8,
        rng=np.random.default_rng(0),
    )
    first = reqs[0].prompt[:8]
    assert all(r.prompt[:8] == first for r in reqs)
    assert len({tuple(r.prompt) for r in reqs}) > 1  # tails differ


# --------------------------------------------------------------------------
# CI smoke: the paged serve path end-to-end through bench.py on CPU
# --------------------------------------------------------------------------

@pytest.mark.timeout(240)
def test_bench_serve_paged_cpu_smoke():
    """Fast tier-1 smoke: bench.py --serve --kv-layout paged with a hard
    --steps-cap, so a scheduler/allocator regression surfaces on CPU
    (and, via the cap + pytest-timeout, can never hang CI)."""
    proc = subprocess.run(
        [
            sys.executable, "bench.py", "--serve", "--small",
            "--seq-len", "12", "--serve-requests", "6",
            "--batch-slots", "2", "--max-new-tokens", "4",
            "--kv-layout", "paged", "--page-size", "4",
            "--prefill-chunk", "8", "--steps-cap", "50",
        ],
        capture_output=True, text=True, timeout=220,
        cwd=str(Path(__file__).resolve().parent.parent),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["kv_layout"] == "paged"
    assert line["generated_tokens"] > 0
    assert line["kv_bytes_peak"] <= line["kv_bytes"]
    assert line["hbm_bytes_per_admitted_token"] > 0
