"""Vision Transformer (models/vit.py): shapes, training step, sharding.

Beyond-parity model family — the encoder machinery (SelfAttention, logical
axes) is shared with bert, so the same rule sets must shard it."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearning_tpu.models import get_model
from distributeddeeplearning_tpu.parallel import MeshSpec, create_mesh, shard_batch
from distributeddeeplearning_tpu.parallel.sharding import (
    RULES_TP,
    model_logical_axes,
)
from distributeddeeplearning_tpu.train.state import create_train_state
from distributeddeeplearning_tpu.train.step import build_train_step

TINY = dict(
    image_size=32, patch_size=8, hidden_size=32, num_layers=2, num_heads=2,
    intermediate_size=64, num_classes=11, dtype=jnp.float32,
)


def test_forward_shape_and_dtype():
    model = get_model("vit-b16", **TINY)
    imgs = jnp.ones((2, 32, 32, 3), jnp.float32)
    params = model.init(jax.random.key(0), imgs, train=False)
    out = model.apply(params, imgs, train=False)
    assert out.shape == (2, 11)
    assert out.dtype == jnp.float32
    assert np.isfinite(np.asarray(out)).all()


def test_patch_divisibility_rejected():
    model = get_model("vit-b16", **dict(TINY, patch_size=7))
    with pytest.raises(ValueError, match="divisible"):
        model.init(jax.random.key(0), jnp.ones((1, 32, 32, 3)), train=False)


def test_registry_has_both_sizes():
    big = get_model("vit-l16", **dict(TINY, num_layers=1))
    assert big.config.intermediate_size == 64  # override applied
    assert get_model("vit_b16", **TINY).config.patch_size == 8


def test_dp_training_reduces_loss():
    mesh = create_mesh(MeshSpec())
    model = get_model("vit-b16", **TINY)
    tx = optax.adam(1e-3)
    state = create_train_state(
        jax.random.key(0), model, (8, 32, 32, 3), tx
    )
    step = build_train_step(mesh, state, compute_dtype=jnp.float32)
    rng = np.random.default_rng(0)
    batch = shard_batch(
        mesh,
        {
            "image": rng.standard_normal((8, 32, 32, 3)).astype(np.float32),
            "label": rng.integers(0, 11, (8,)).astype(np.int32),
        },
    )
    losses = []
    for _ in range(4):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(v) for v in losses), losses
    assert losses[-1] < losses[0], losses


def test_tp_sharded_step_runs():
    """The bert TP rules shard ViT's qkv/mlp (shared logical axes)."""
    mesh = create_mesh(MeshSpec(tensor=2))
    model = get_model("vit-b16", **TINY)
    tx = optax.sgd(0.1)
    axes = model_logical_axes(
        model, jax.random.key(0), np.zeros((8, 32, 32, 3), np.float32),
        train=False,
    )
    state = create_train_state(jax.random.key(0), model, (8, 32, 32, 3), tx)
    step = build_train_step(
        mesh, state, compute_dtype=jnp.float32, rules=RULES_TP,
        logical_axes=axes,
    )
    rng = np.random.default_rng(1)
    batch = shard_batch(
        mesh,
        {
            "image": rng.standard_normal((8, 32, 32, 3)).astype(np.float32),
            "label": rng.integers(0, 11, (8,)).astype(np.int32),
        },
    )
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_remat_matches_no_remat():
    imgs = jnp.asarray(
        np.random.default_rng(2).standard_normal((2, 32, 32, 3)), jnp.float32
    )
    base = get_model("vit-b16", **TINY)
    params = base.init(jax.random.key(0), imgs, train=False)
    want = base.apply(params, imgs, train=False)
    got = get_model("vit-b16", **dict(TINY, remat="full")).apply(
        params, imgs, train=False
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-6
    )


def test_imagenet_workload_trains_vit():
    """--model vit-b16 rides the ImageNet trainer unchanged (synthetic)."""
    from distributeddeeplearning_tpu.workloads.imagenet import main

    state, fit = main(
        model="vit-b16",
        epochs=1,
        steps_per_epoch=2,
        batch_size=2,
        image_size=32,
        num_classes=11,
        compute_dtype="float32",
        data_format="synthetic",
        resume=False,
        distributed=False,
    )
    assert np.isfinite(fit.final_train_metrics["loss"])


def test_flash_attention_injects_into_vit():
    """The injectable-attention contract: the Pallas kernel (interpret mode
    on CPU) slots into ViT and matches the default dense path."""
    from distributeddeeplearning_tpu.ops.flash_attention import (
        make_flash_attention,
    )

    imgs = jnp.asarray(
        np.random.default_rng(3).standard_normal((2, 32, 32, 3)), jnp.float32
    )
    base = get_model("vit-b16", **TINY)
    params = base.init(jax.random.key(0), imgs, train=False)
    want = base.apply(params, imgs, train=False)
    flash_model = get_model(
        "vit-b16", attention_fn=make_flash_attention(), **TINY
    )
    got = flash_model.apply(params, imgs, train=False)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )
