"""DP train/eval step semantics on the virtual 8-device mesh.

The key correctness property (SURVEY.md §2 "Parallelism strategies"): 8-way
data parallelism must compute the SAME update as single-device training on
the full global batch — that is what Horovod's averaged allreduce guarantees
in the reference, and what XLA's sharding propagation must reproduce here.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearning_tpu.data.synthetic import synthetic_batch
from distributeddeeplearning_tpu.models import get_model
from distributeddeeplearning_tpu.parallel import MeshSpec, create_mesh, shard_batch
from distributeddeeplearning_tpu.train.schedule import goyal_lr_schedule
from distributeddeeplearning_tpu.train.state import create_train_state, sgd_momentum
from distributeddeeplearning_tpu.train.step import (
    build_eval_step,
    build_train_step,
    cross_entropy_loss,
    topk_correct,
)

IMG = (32, 32, 3)
NCLS = 11


def _make_state(lr=0.1, seed=0):
    model = get_model("resnet18", num_classes=NCLS, dtype=jnp.float32)
    tx = sgd_momentum(optax.constant_schedule(lr), weight_decay=5e-5)
    return create_train_state(
        jax.random.key(seed), model, (8, *IMG), tx
    )


@pytest.fixture(scope="module")
def mesh8():
    return create_mesh(MeshSpec())


def test_loss_decreases_on_fixed_batch(mesh8):
    state = _make_state()
    step = build_train_step(mesh8, state, compute_dtype=jnp.float32)
    batch = shard_batch(mesh8, synthetic_batch(16, IMG, NCLS))
    state, first = step(state, batch)
    for _ in range(5):
        state, metrics = step(state, batch)
    assert float(metrics["loss"]) < float(first["loss"])


def test_dp_equals_single_device():
    """The allreduce contract: same batch, 8-way sharded vs 1 device."""
    batch_np = synthetic_batch(16, IMG, NCLS, seed=3)

    mesh8 = create_mesh(MeshSpec())
    state8 = _make_state(seed=1)
    step8 = build_train_step(mesh8, state8, compute_dtype=jnp.float32)
    _, m8 = step8(state8, shard_batch(mesh8, batch_np))

    mesh1 = create_mesh(devices=jax.devices()[:1])
    state1 = _make_state(seed=1)
    step1 = build_train_step(mesh1, state1, compute_dtype=jnp.float32)
    _, m1 = step1(state1, shard_batch(mesh1, batch_np))

    np.testing.assert_allclose(float(m8["loss"]), float(m1["loss"]), rtol=1e-4)
    np.testing.assert_allclose(float(m8["top5"]), float(m1["top5"]), rtol=1e-5)


def test_metrics_shape_and_keys(mesh8):
    state = _make_state()
    sched = goyal_lr_schedule(0.0125, 8, 10)
    step = build_train_step(mesh8, state, schedule=sched, compute_dtype=jnp.float32)
    batch = shard_batch(mesh8, synthetic_batch(16, IMG, NCLS))
    _, metrics = step(state, batch)
    assert set(metrics) == {"loss", "top1", "top5", "lr"}
    for v in metrics.values():
        assert v.shape == ()
        assert jnp.isfinite(v)


def test_state_step_increments(mesh8):
    state = _make_state()
    step = build_train_step(mesh8, state, compute_dtype=jnp.float32)
    batch = shard_batch(mesh8, synthetic_batch(16, IMG, NCLS))
    new_state, _ = step(state, batch)
    assert int(new_state.step) == 1


def test_batch_stats_update(mesh8):
    state = _make_state()
    step = build_train_step(mesh8, state, compute_dtype=jnp.float32)
    batch = shard_batch(mesh8, synthetic_batch(16, IMG, NCLS))
    old = jax.tree_util.tree_leaves(state.batch_stats)[0].copy()
    new_state, _ = step(state, batch)
    new = jax.tree_util.tree_leaves(new_state.batch_stats)[0]
    assert not np.allclose(np.asarray(old), np.asarray(new))


def test_eval_step_does_not_mutate(mesh8):
    state = _make_state()
    ev = build_eval_step(mesh8, state, compute_dtype=jnp.float32)
    batch = shard_batch(mesh8, synthetic_batch(16, IMG, NCLS))
    metrics = ev(state, batch)
    assert set(metrics) == {"loss", "top1", "top5"}


def test_cross_entropy_matches_reference_formula():
    logits = jnp.array([[2.0, 0.0, -1.0], [0.0, 3.0, 0.5]])
    labels = jnp.array([0, 1])
    expected = -np.mean(
        [
            np.log(np.exp(2.0) / np.exp([2.0, 0.0, -1.0]).sum()),
            np.log(np.exp(3.0) / np.exp([0.0, 3.0, 0.5]).sum()),
        ]
    )
    np.testing.assert_allclose(float(cross_entropy_loss(logits, labels)), expected, rtol=1e-6)


def test_topk_accuracy():
    logits = jnp.array([[0.1, 0.9, 0.0], [0.8, 0.1, 0.1]])
    labels = jnp.array([1, 2])
    assert float(topk_correct(logits, labels, 1)) == pytest.approx(0.5)
    assert float(topk_correct(logits, labels, 3)) == pytest.approx(1.0)


def test_bert_with_dropout_trains(mesh8):
    """Dropout RNG plumbing: the default BERT config (dropout 0.1) must train."""
    from distributeddeeplearning_tpu.models import get_model as gm

    model = gm(
        "bert-base", num_layers=1, hidden_size=32, num_heads=2,
        intermediate_size=64, vocab_size=50, num_classes=3,
        max_position_embeddings=16, dtype=jnp.float32,  # dropout_rate=0.1 default
    )
    tx = sgd_momentum(optax.constant_schedule(0.01))
    state = create_train_state(
        jax.random.key(0), model, (2, 8), tx, input_dtype=jnp.int32
    )
    step = build_train_step(mesh8, state, compute_dtype=jnp.float32)
    rng = np.random.default_rng(0)
    batch = shard_batch(
        mesh8,
        {
            "input": rng.integers(0, 50, (16, 8)).astype(np.int32),
            "label": rng.integers(0, 3, (16,)).astype(np.int32),
        },
    )
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_fsdp_opt_state_mirrors_param_sharding():
    """ZeRO contract: momentum buffers shard exactly like their params."""
    from distributeddeeplearning_tpu.models import get_model as gm
    from distributeddeeplearning_tpu.parallel.sharding import (
        RULES_FSDP,
        model_logical_axes,
    )

    mesh = create_mesh(MeshSpec(fsdp=8))
    model = gm(
        "bert-base", num_layers=1, hidden_size=32, num_heads=2,
        intermediate_size=64, vocab_size=50, num_classes=3,
        max_position_embeddings=16, dropout_rate=0.0, dtype=jnp.float32,
    )
    axes = model_logical_axes(
        model, jax.random.key(0), np.zeros((2, 8), np.int32), train=False
    )
    tx = sgd_momentum(optax.constant_schedule(0.01))
    state = create_train_state(
        jax.random.key(0), model, (2, 8), tx, input_dtype=jnp.int32
    )
    step = build_train_step(
        mesh, state, compute_dtype=jnp.float32,
        rules=RULES_FSDP, logical_axes=axes,
    )
    rng = np.random.default_rng(0)
    batch = shard_batch(
        mesh,
        {
            "input": rng.integers(0, 50, (16, 8)).astype(np.int32),
            "label": rng.integers(0, 3, (16,)).astype(np.int32),
        },
    )
    state, _ = step(state, batch)
    kernel = state.params["layer0"]["mlp_in"]["kernel"]
    assert "fsdp" in tuple(kernel.sharding.spec)
    # momentum trace for the same param must carry the same sharding
    momentum_leaves = [
        leaf
        for sub in jax.tree_util.tree_leaves(
            state.opt_state, is_leaf=lambda x: hasattr(x, "sharding")
        )
        if hasattr(sub, "sharding")
        for leaf in [sub]
        if leaf.shape == kernel.shape
    ]
    assert momentum_leaves
    assert any(
        leaf.sharding.is_equivalent_to(kernel.sharding, leaf.ndim)
        for leaf in momentum_leaves
    )


def test_label_smoothing_changes_loss(mesh8):
    # The state fed to a step must share the model/tx objects of the
    # state_example the step was built from (static pytree fields).
    model = get_model("resnet18", num_classes=NCLS, dtype=jnp.float32)
    tx = sgd_momentum(optax.constant_schedule(0.1))

    def mk():
        return create_train_state(jax.random.key(0), model, (8, *IMG), tx)

    batch = shard_batch(mesh8, synthetic_batch(16, IMG, NCLS))
    plain = build_train_step(mesh8, mk(), compute_dtype=jnp.float32)
    smooth = build_train_step(
        mesh8, mk(), compute_dtype=jnp.float32, label_smoothing=0.1
    )
    _, m_plain = plain(mk(), batch)
    _, m_smooth = smooth(mk(), batch)
    assert float(m_plain["loss"]) != float(m_smooth["loss"])


def _bert_state_and_model(seed=0):
    model = get_model(
        "bert-base", num_layers=2, hidden_size=32, num_heads=2,
        intermediate_size=64, vocab_size=50, num_classes=3,
        max_position_embeddings=16, dropout_rate=0.0, dtype=jnp.float32,
    )
    tx = sgd_momentum(optax.constant_schedule(0.05))
    state = create_train_state(
        jax.random.key(seed), model, (2, 8), tx, input_dtype=jnp.int32
    )
    return state, model, tx


def test_grad_accumulation_matches_full_batch(mesh8):
    """accum_steps=4 on the same global batch computes the SAME update as
    one full-batch step (stat-free model; VERDICT r02 item 5 contract)."""
    rng = np.random.default_rng(7)
    batch_np = {
        "input": rng.integers(0, 50, (32, 8)).astype(np.int32),
        "label": rng.integers(0, 3, (32,)).astype(np.int32),
    }
    batch = shard_batch(mesh8, batch_np)

    state_a, _, _ = _bert_state_and_model()
    step_a = build_train_step(mesh8, state_a, compute_dtype=jnp.float32)
    state_a, m_a = step_a(state_a, batch)

    state_b, _, _ = _bert_state_and_model()
    step_b = build_train_step(
        mesh8, state_b, compute_dtype=jnp.float32, accum_steps=4
    )
    state_b, m_b = step_b(state_b, batch)

    np.testing.assert_allclose(float(m_a["loss"]), float(m_b["loss"]), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6
        ),
        state_a.params,
        state_b.params,
    )


def test_grad_accumulation_batchnorm_model_trains(mesh8):
    """BN models train under accumulation (sequential EMA stats updates)."""
    state = _make_state()
    step = build_train_step(
        mesh8, state, compute_dtype=jnp.float32, accum_steps=2
    )
    batch = shard_batch(mesh8, synthetic_batch(16, IMG, NCLS))
    state, first = step(state, batch)
    for _ in range(4):
        state, metrics = step(state, batch)
    assert float(metrics["loss"]) < float(first["loss"])
    assert int(state.step) == 5  # one optimizer update per step call


def test_grad_accumulation_rejects_indivisible_batch(mesh8):
    state = _make_state()
    step = build_train_step(
        mesh8, state, compute_dtype=jnp.float32, accum_steps=3
    )
    batch = shard_batch(mesh8, synthetic_batch(16, IMG, NCLS))
    with pytest.raises(ValueError, match="not divisible"):
        step(state, batch)
