"""Goodput ledger + perf-trajectory tracker (ISSUE 14).

Covers:

- the mark-based ledger partitioning 100% of wall by construction, with
  the compile / step_redone / step_productive classification matching
  the supervisor's redone-steps accounting EXACTLY across restarts
  (preemption with exact resume AND anomaly abort with an older
  checkpoint — the two restart flavors charge differently);
- restart durability: per-incarnation JSONL segments appended through
  the retry layer (surviving an injected ``io_error``), stitched with
  the between-incarnation gap charged to ``recovery``, the residual
  gate catching lost time;
- MFU plumbing: ``utils/hardware.peak_bf16_flops`` returning None (not
  raising) for unknown chips including the virtual test mesh's device
  kind, and the documented ``mfu`` formula;
- the one post-warmup tokens/sec helper shared by the fleet report;
- the trajectory tracker: committed-artifact timeline, sparklines, the
  per-metric tolerance gate passing over real history and failing (rc
  1) on a fixture artifact with an injected regression, list paths
  excluded as positional;
- the GOODPUT schema: acceptance, the categories-don't-sum rejection,
  and the ordered most-specific-first prefix dispatch.
"""

import dataclasses
import json
import os
import subprocess
import sys
import time
from pathlib import Path
from types import SimpleNamespace

import numpy as np
import pytest

from distributeddeeplearning_tpu.obs import goodput
from distributeddeeplearning_tpu.obs import history
from distributeddeeplearning_tpu.obs.goodput import (
    CATEGORIES,
    GoodputLedger,
    post_warmup_tokens_per_sec,
)
from distributeddeeplearning_tpu.obs.schema import (
    SchemaError,
    validate_artifact,
    validate_goodput_payload,
)
from distributeddeeplearning_tpu.utils import faults

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_fault_plan(monkeypatch):
    monkeypatch.delenv(faults.ENV_VAR, raising=False)
    faults.reset()
    yield
    faults.reset()


# --------------------------------------------------------------------------
# ledger unit behavior
# --------------------------------------------------------------------------


def test_marks_partition_the_wall(tmp_path):
    """Every second between begin() and end() lands in exactly one
    category — the mark design makes 100% coverage structural."""
    path = str(tmp_path / "gp.jsonl")
    ledger = GoodputLedger(path)
    ledger.begin()
    time.sleep(0.01)
    ledger.mark("data_wait")
    time.sleep(0.02)
    ledger.mark_step(1)          # first step -> compile
    time.sleep(0.01)
    ledger.mark_step(2)          # -> step_productive
    seg = ledger.end()
    assert seg["counts"] == {"steps": 2, "steps_redone": 0}
    total = sum(seg["seconds"].values())
    assert abs(total - seg["duration_s"]) < 1e-6
    assert seg["seconds"]["compile"] >= 0.02
    assert seg["seconds"]["data_wait"] >= 0.01
    assert seg["seconds"]["step_productive"] >= 0.01
    # the row landed on disk
    rows = goodput.read_rows(path)
    assert len(rows) == 1 and rows[0]["kind"] == "segment"


def test_mark_step_redone_classification(tmp_path):
    """A later incarnation re-executing steps an earlier one completed
    counts them as redone — including a redone FIRST step, whose seconds
    go to compile but whose count stays in steps_redone (the supervisor
    counts it; the ledger must agree)."""
    path = str(tmp_path / "gp.jsonl")
    first = GoodputLedger(path)
    first.begin()
    for s in (1, 2, 3, 4, 5):
        first.mark_step(s)
    first.end()

    second = GoodputLedger(path)
    second.begin(resumed_step=3)
    assert second._redone_until == 5
    for s in (4, 5, 6, 7):
        second.mark_step(s)
    seg = second.end()
    # steps 4 and 5 are redone (<= 5); step 4 is also the segment's
    # compile payer — counted redone, charged compile
    assert seg["counts"] == {"steps": 4, "steps_redone": 2}
    assert seg["seconds"]["compile"] > 0.0
    merged = goodput.stitch(path)
    assert merged["counts"] == {"steps": 9, "steps_redone": 2}
    assert merged["last_step"] == 7


def test_reused_ledger_path_starts_new_run_lineage(tmp_path):
    """A fresh run pointed at a REUSED ledger file must not classify its
    steps as redone against the stale segments, and stitch must not
    charge the gap between unrelated runs to recovery — fresh_start()
    bumps the run lineage and stitch keeps only the newest run."""
    path = str(tmp_path / "gp.jsonl")
    old = GoodputLedger(path)
    old.begin()
    for s in (1, 2, 3):
        old.mark_step(s)
    old.end()

    new = GoodputLedger(path)
    new.begin()
    new.fresh_start()          # the Trainer's resumed-nothing signal
    for s in (1, 2):
        new.mark_step(s)
    seg = new.end()
    assert seg["run"] == 1
    assert seg["counts"] == {"steps": 2, "steps_redone": 0}
    merged = goodput.stitch(path)
    # only the new run's segment is stitched: no phantom recovery gap,
    # no stale steps diluting the counts
    assert merged["segments"] == 1 and merged["runs_in_file"] == 2
    assert merged["counts"]["steps"] == 2
    assert merged["seconds"]["recovery"] == 0.0
    assert merged["total_wall_s"] == pytest.approx(
        seg["duration_s"], abs=1e-6
    )


def test_disabled_ledger_is_inert(tmp_path):
    ledger = GoodputLedger(None)
    assert not ledger.enabled
    ledger.begin()
    ledger.mark("data_wait")
    ledger.mark_step(1)
    ledger.note("x", 1.0)
    assert ledger.end() is None
    assert list(tmp_path.iterdir()) == []


def test_segment_append_survives_injected_io_error(monkeypatch, tmp_path):
    """The JSONL append rides retry_call + the DDLT_FAULTS io_error hook
    (the metrics/checkpoint contract): one injected failure, row lands."""
    monkeypatch.setenv(faults.ENV_VAR, "io_error@1")
    faults.reset()
    path = str(tmp_path / "gp.jsonl")
    ledger = GoodputLedger(path)
    ledger.begin()
    ledger.mark_step(1)
    ledger.end()
    assert len(goodput.read_rows(path)) == 1


def test_stitch_charges_restart_gap_to_recovery():
    base = time.time()

    def seg(i, start, dur, last_step, **seconds):
        body = {c: 0.0 for c in CATEGORIES}
        body.update(seconds)
        # stitch reads seconds/counts/walls only
        return {
            "kind": "segment", "incarnation": i,
            "wall_start": base + start, "wall_end": base + start + dur,
            "duration_s": dur, "seconds": body,
            "counts": {"steps": 1, "steps_redone": 0},
            "last_step": last_step,
        }

    rows = [
        seg(0, 0.0, 10.0, 5, step_productive=10.0),
        {"kind": "restart", "ts": base + 10.5, "attempt": 1,
         "error": "PreemptionError", "step": 5},
        seg(1, 12.0, 8.0, 9, step_productive=7.0, recovery=1.0),
    ]
    merged = goodput.stitch(rows)
    assert merged["segments"] == 2 and merged["restarts"] == 1
    # in-segment recovery (1.0) + the 2.0s inter-incarnation gap
    assert merged["seconds"]["recovery"] == pytest.approx(3.0)
    assert merged["total_wall_s"] == pytest.approx(20.0)
    summary = goodput.summarize_ledger(merged)
    assert summary["goodput_fraction"] == pytest.approx(17.0 / 20.0)
    assert summary["residual_under_limit"]
    assert summary["counts"]["segments"] == 2


def test_residual_gate_catches_lost_time():
    """A merged ledger whose categories do NOT cover the wall (a lost
    segment, marks missing) fails the residual gate instead of reporting
    optimistic goodput."""
    base = time.time()
    merged = goodput.stitch([{
        "kind": "segment", "incarnation": 0,
        "wall_start": base, "wall_end": base + 10.0, "duration_s": 10.0,
        # only 5 of the 10 seconds accounted
        "seconds": {"step_productive": 5.0},
        "counts": {"steps": 1, "steps_redone": 0}, "last_step": 1,
    }])
    summary = goodput.summarize_ledger(merged)
    assert summary["unaccounted_pct"] == pytest.approx(50.0)
    assert not summary["residual_under_limit"]


# --------------------------------------------------------------------------
# MFU / hardware satellites
# --------------------------------------------------------------------------


def test_peak_flops_unknown_chip_returns_none_not_raise():
    from distributeddeeplearning_tpu.utils.hardware import peak_bf16_flops

    import jax

    # the virtual test mesh's fake device kind (CPU backend) is unknown
    assert peak_bf16_flops(jax.devices()[0]) is None
    # an exotic backend whose device_kind ACCESS raises must still
    # answer None (MFU omitted), never propagate
    class _Hostile:
        @property
        def device_kind(self):
            raise RuntimeError("no kind on this backend")

    assert peak_bf16_flops(_Hostile()) is None


def test_mfu_formula_and_omission():
    from distributeddeeplearning_tpu.utils.hardware import mfu

    v4 = SimpleNamespace(device_kind="TPU v4")  # peak 275e12
    # (275e12 * 5 / 10) / (275e12 * 1) = 0.5 — the documented formula
    assert mfu(275e12, 5, 10.0, device=v4, n_chips=1) == pytest.approx(0.5)
    # chips divide the peak
    assert mfu(275e12, 5, 10.0, device=v4, n_chips=2) == pytest.approx(0.25)
    # unknown chip / degenerate inputs omit, never raise
    assert mfu(275e12, 5, 10.0, device=SimpleNamespace(device_kind="cpu"),
               n_chips=1) is None
    assert mfu(0.0, 5, 10.0, device=v4, n_chips=1) is None
    assert mfu(275e12, 0, 10.0, device=v4, n_chips=1) is None


def test_summarize_ledger_omits_mfu_off_tpu():
    base = time.time()
    merged = goodput.stitch([{
        "kind": "segment", "incarnation": 0,
        "wall_start": base, "wall_end": base + 1.0, "duration_s": 1.0,
        "seconds": {"step_productive": 1.0},
        "counts": {"steps": 4, "steps_redone": 0}, "last_step": 4,
        "flops_per_step": 1e9,
    }])
    summary = goodput.summarize_ledger(merged)  # CPU: peak unknown
    assert summary["mfu"] is None
    assert "mfu_omitted_reason" in summary


# --------------------------------------------------------------------------
# the shared post-warmup tokens/sec helper (FleetReport satellite)
# --------------------------------------------------------------------------


def test_post_warmup_tokens_per_sec_excludes_warmup():
    # 100 tokens over 20s of which 10s was spawn/compile -> 10 tok/s,
    # not the 5 tok/s the whole-wall division used to report
    assert post_warmup_tokens_per_sec(100, 20.0, 10.0) == 10.0
    assert post_warmup_tokens_per_sec(100, 20.0, 0.0) == 5.0
    # degenerate windows fall back to the whole wall, never divide by ~0
    assert post_warmup_tokens_per_sec(100, 20.0, 20.0) == 5.0
    assert post_warmup_tokens_per_sec(100, 20.0, 999.0) == 5.0
    assert post_warmup_tokens_per_sec(100, 0.0, 0.0) == 0.0


def test_fleet_report_carries_post_warmup_goodput_fields():
    """The fleet report's goodput rate is the post-warmup definition:
    the warmup window travels with it so readers can reconstruct the
    whole-wall number."""
    from distributeddeeplearning_tpu.serve.fleet import FleetRouter, FleetReport

    names = {f.name for f in dataclasses.fields(FleetReport)}
    assert {"goodput_tokens_per_sec", "warmup_s"} <= names
    # the router routes through the ONE shared helper (no forked math)
    import inspect

    src = inspect.getsource(FleetRouter.serve)
    assert "post_warmup_tokens_per_sec(" in src


# --------------------------------------------------------------------------
# restart-durable stitching against the REAL trainer + supervisor
# --------------------------------------------------------------------------

GLOBAL_BATCH = 16
IMG = (4, 4, 3)
NCLS = 5


@pytest.fixture(scope="module")
def tiny_parts():
    import jax
    import jax.numpy as jnp
    import optax
    from flax import linen as nn

    from distributeddeeplearning_tpu.parallel import MeshSpec, create_mesh
    from distributeddeeplearning_tpu.train.state import (
        create_train_state,
        sgd_momentum,
    )
    from distributeddeeplearning_tpu.train.step import build_train_step

    class _Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(NCLS)(x.reshape((x.shape[0], -1)))

    mesh = create_mesh(MeshSpec())
    model = _Tiny()
    tx = sgd_momentum(optax.constant_schedule(0.05))

    def mk_state():
        return create_train_state(jax.random.key(0), model, (8, *IMG), tx)

    step = build_train_step(
        mesh, mk_state(), compute_dtype=jnp.float32, skip_nonfinite=True
    )
    return mesh, mk_state, step


def _factory(start_step: int):
    def gen():
        i = start_step
        while True:
            rng = np.random.default_rng(1000 + i)
            yield {
                "image": rng.standard_normal(
                    (GLOBAL_BATCH, *IMG)
                ).astype(np.float32),
                "label": rng.integers(0, NCLS, (GLOBAL_BATCH,)).astype(
                    np.int32
                ),
            }
            i += 1

    return gen()


def _supervised_run(mesh, mk_state, step, tmp_path, monkeypatch, spec, *,
                    anomaly_max=3, epochs=2, spe=4, every=2,
                    max_restarts=1):
    """The ``ddlt train --max-restarts`` shape, in-process: supervise()
    around Trainer.fit with the cli's exact redone-steps accounting."""
    from distributeddeeplearning_tpu.train import resilience
    from distributeddeeplearning_tpu.train.checkpoint import (
        latest_verified_step_in_dir,
    )
    from distributeddeeplearning_tpu.train.loop import Trainer, TrainerConfig

    ckpt = str(tmp_path / "ck")
    gp = str(tmp_path / "gp.jsonl")
    monkeypatch.setenv(faults.ENV_VAR, spec)
    faults.reset()
    cfg = TrainerConfig(
        epochs=epochs, steps_per_epoch=spe, global_batch_size=GLOBAL_BATCH,
        prefetch=0, checkpoint_dir=ckpt, checkpoint_every_steps=every,
        anomaly_max_consecutive=anomaly_max, goodput_path=gp,
    )

    def attempt(i):
        return Trainer(mesh, step, config=cfg).fit(mk_state(), _factory)

    redone = {"steps": 0}

    def on_restart(i, exc):
        # the cli supervisor's accounting, verbatim (cli/main.py)
        at = getattr(exc, "step", None)
        if at is None:
            return
        done = at if isinstance(exc, resilience.PreemptionError) else at - 1
        redone["steps"] += max(
            done - (latest_verified_step_in_dir(ckpt) or 0), 0
        )

    (state, fit), restarts = resilience.supervise(
        attempt, max_restarts=max_restarts, on_restart=on_restart,
        ledger_path=gp,
    )
    return state, fit, restarts, redone["steps"], gp


@pytest.mark.timeout(120)
def test_preempt_restart_produces_one_stitched_ledger(
    tiny_parts, tmp_path, monkeypatch
):
    """ISSUE satellite: a ``DDLT_FAULTS preempt@N`` + max-restarts-1 run
    produces ONE merged ledger whose recovery and step_redone categories
    match the supervisor's redone-steps accounting exactly, and whose
    category sum covers total wall within the residual gate."""
    mesh, mk_state, step = tiny_parts
    state, fit, restarts, sup_redone, gp = _supervised_run(
        mesh, mk_state, step, tmp_path, monkeypatch, "preempt@3",
    )
    assert restarts == 1 and int(state.step) == 8
    merged = goodput.stitch(gp)
    assert merged["segments"] == 2 and merged["restarts"] == 1
    # preemption writes the emergency checkpoint at the EXACT step, so
    # the supervisor counts zero redone steps — and so does the ledger
    assert sup_redone == 0
    assert merged["counts"]["steps_redone"] == sup_redone
    assert merged["counts"]["steps"] == 8
    # recovery is nonzero: restore inside incarnation 2 plus the
    # supervisor's restart gap between the segments
    assert merged["seconds"]["recovery"] > 0.0
    summary = goodput.summarize_ledger(merged)
    assert summary["residual_under_limit"], summary
    # the checkpoint layer's save/wait joins fed their detail notes
    assert summary["notes"].get("ckpt_save_block_s", 0.0) > 0.0
    # the supervisor interleaved its restart row
    kinds = [r["kind"] for r in goodput.read_rows(gp)]
    assert kinds == ["segment", "restart", "segment"]


@pytest.mark.timeout(120)
def test_anomaly_restart_redone_matches_supervisor_exactly(
    tiny_parts, tmp_path, monkeypatch
):
    """The other restart flavor: an anomaly abort resumes from an OLDER
    checkpoint, so real work is re-done — the ledger's steps_redone
    count must equal the supervisor's accounting exactly (here: abort at
    step 6, newest verified generation 4, one completed step re-run)."""
    mesh, mk_state, step = tiny_parts
    state, fit, restarts, sup_redone, gp = _supervised_run(
        mesh, mk_state, step, tmp_path, monkeypatch, "nan_loss@5,nan_loss@6",
        anomaly_max=2,
    )
    assert restarts == 1 and int(state.step) == 8
    assert sup_redone == 1  # done=5, newest verified ckpt=4
    merged = goodput.stitch(gp)
    assert merged["counts"]["steps_redone"] == sup_redone
    assert merged["seconds"]["recovery"] > 0.0
    # the redone seconds category is visible whenever a redone step is
    # not the incarnation's compile payer; here step 5 IS the first
    # re-executed step, so its seconds land in compile while the COUNT
    # stays in steps_redone — the supervisor-match contract
    summary = goodput.summarize_ledger(merged)
    assert summary["residual_under_limit"], summary
    assert summary["counts"]["steps_redone"] == 1


@pytest.mark.timeout(120)
def test_inprocess_rollback_segment_carries_anomaly_reason(
    tiny_parts, tmp_path, monkeypatch
):
    """An anomaly handled by the Trainer's own rollback (no supervisor)
    still stamps the aborted attempt's segment reason as AnomalyError —
    a handled exception is invisible to sys.exc_info() in the finally,
    so the except handler records it."""
    from distributeddeeplearning_tpu.train.loop import Trainer, TrainerConfig

    mesh, mk_state, step = tiny_parts
    gp = str(tmp_path / "gp.jsonl")
    monkeypatch.setenv(faults.ENV_VAR, "nan_loss@3,nan_loss@4")
    faults.reset()
    cfg = TrainerConfig(
        epochs=2, steps_per_epoch=3, global_batch_size=GLOBAL_BATCH,
        prefetch=0, anomaly_max_consecutive=2, anomaly_rollback=True,
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every_steps=2,
        goodput_path=gp,
    )
    state, fit = Trainer(mesh, step, config=cfg).fit(mk_state(), _factory)
    assert fit.rollbacks == 1
    segments = [r for r in goodput.read_rows(gp) if r["kind"] == "segment"]
    assert [s["reason"] for s in segments] == ["AnomalyError", "completed"]
    # both attempts belong to one run lineage (the rollback RESUMED)
    assert {s["run"] for s in segments} == {0}


# --------------------------------------------------------------------------
# perf-trajectory tracker
# --------------------------------------------------------------------------


def _write_artifact(dirpath, name, payload):
    path = Path(dirpath) / name
    path.write_text(json.dumps(payload) + "\n")
    return str(path)


def _mini(decode_tps, rev):
    return {
        "metric": "mini_tok_sec",
        "value": decode_tps,
        "unit": "tok/sec",
        "configs": {"f32": {"decode_tokens_per_sec": decode_tps}},
        "bench_revision": rev,
    }


def test_history_timeline_and_green_gate(tmp_path):
    _write_artifact(tmp_path, "MINI_r01.json", _mini(100.0, 1))
    _write_artifact(tmp_path, "MINI_r02.json", _mini(99.0, 2))  # -1%: fine
    rc, out = history.run_history(str(tmp_path), gate=True)
    assert rc == 0
    assert "MINI" in out and "GREEN" in out
    timeline = history.build_timeline(history.load_points(str(tmp_path)))
    series = timeline[("MINI", "configs.f32.decode_tokens_per_sec")]
    assert [p.revision for p in series] == [1, 2]


def test_history_gate_fails_on_injected_regression(tmp_path):
    """ISSUE acceptance: the gate demonstrably fails (rc 1) on a fixture
    artifact with an injected regression — decode tokens/sec down 10%
    against the 5% tolerance."""
    _write_artifact(tmp_path, "MINI_r01.json", _mini(100.0, 1))
    _write_artifact(tmp_path, "MINI_r02.json", _mini(90.0, 2))
    rc, out = history.run_history(str(tmp_path), gate=True)
    assert rc == 1
    assert "REGRESSION" in out and "decode_tokens_per_sec" in out
    # without --gate the same regression is reported but not fatal
    rc2, _ = history.run_history(str(tmp_path), gate=False)
    assert rc2 == 0


def test_history_lower_is_better_metrics_gate_on_rise(tmp_path):
    for rev, pct in ((1, 4.0), (2, 20.0)):  # +16pp past the 5pp budget
        _write_artifact(tmp_path, f"CHAOS_r{rev:02d}.json", {
            "metric": "chaos_overhead", "value": pct, "unit": "%",
            "recovery_overhead_pct": pct, "bench_revision": rev,
        })
    regressions = history.check_gates(
        history.build_timeline(history.load_points(str(tmp_path)))
    )
    assert [r.path for r in regressions] == ["recovery_overhead_pct"]


def test_history_skips_list_paths_as_positional(tmp_path):
    """rows[5] at r01 and r02 can be DIFFERENT configs — list indices
    are not identities, so list-nested metrics never become series."""
    _write_artifact(tmp_path, "ROWS_r01.json", {
        "metric": "m", "value": 1.0, "unit": "x",
        "rows": [{"decode_tokens_per_sec": 100.0}],
    })
    _write_artifact(tmp_path, "ROWS_r02.json", {
        "metric": "m", "value": 1.0, "unit": "x",
        "rows": [{"decode_tokens_per_sec": 10.0}],  # would gate if tracked
    })
    timeline = history.build_timeline(history.load_points(str(tmp_path)))
    assert not [
        key for key in timeline if "decode_tokens_per_sec" in key[1]
    ]
    assert not history.check_gates(timeline)


def test_history_rejects_schema_invalid_artifact(tmp_path):
    # a PARSEABLE artifact the schema sweep would reject (schema drift)
    # fails the history GATE loudly instead of being silently skipped —
    # distinct from malformed/unparseable files, which are warn-and-skip
    # in both modes (tests/test_attrib.py TestHistoryHardening)...
    _write_artifact(tmp_path, "BAD_r01.json", {"metric": "x"})
    _write_artifact(tmp_path, "MINI_r01.json", _mini(100.0, 1))
    rc, out = history.run_history(str(tmp_path), gate=True)
    assert rc == 1 and "schema" in out
    # ...while INSPECTION mode (no --gate) warns and still renders the
    # rest of the timeline (rc-1 semantics belong to the gate)
    rc, out = history.run_history(str(tmp_path), gate=False)
    assert rc == 0
    assert "WARNING" in out and "MINI" in out


def test_sparkline_shape():
    assert history.sparkline([]) == ""
    assert history.sparkline([1.0]) == "▄"
    line = history.sparkline([0.0, 0.5, 1.0])
    assert line[0] == "▁" and line[-1] == "█" and len(line) == 3


def test_history_green_over_committed_artifacts():
    """THE acceptance pin: ``ddlt obs history --gate`` runs green over
    every committed artifact in the repo (tracked metrics may not have
    regressed between adjacent committed revisions)."""
    rc, out = history.run_history(REPO_ROOT, gate=True)
    assert rc == 0, out


def test_cli_obs_history_gate(monkeypatch, capsys):
    from distributeddeeplearning_tpu.cli.main import main as cli_main

    monkeypatch.chdir(REPO_ROOT)
    rc = cli_main(["obs", "history", "--gate"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "GREEN" in out


def test_cli_obs_history_json(monkeypatch, capsys):
    from distributeddeeplearning_tpu.cli.main import main as cli_main

    monkeypatch.chdir(REPO_ROOT)
    rc = cli_main(["obs", "history", "--json"])
    digest = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert digest["green"] is True
    assert digest["tracked_series"] > 0


# --------------------------------------------------------------------------
# GOODPUT schema + sweep dispatch ordering
# --------------------------------------------------------------------------


def _goodput_payload(**overrides):
    seconds = {c: 0.0 for c in CATEGORIES}
    seconds.update(step_productive=6.0, compile=2.0, recovery=1.5,
                   step_redone=0.5)
    payload = {
        "metric": "train_goodput_fraction", "value": 0.6, "unit": "fraction",
        "bench_revision": 17, "platform": "cpu", "virtual_pod": False,
        "faults_spec": "preempt@6",
        "supervisor": {"restarts": 2, "redone_steps": 2},
        "ledger": {
            "total_wall_s": 10.0,
            "seconds": seconds,
            "goodput_fraction": 0.6,
            "unaccounted_pct": 0.0,
            "residual_limit_pct": 2.0,
            "residual_under_limit": True,
            "counts": {"steps": 17, "steps_redone": 2, "segments": 3},
            "mfu": None,
            "mfu_omitted_reason": "off-TPU",
        },
        "trajectory": {"green": True, "tracked_series": 4},
        "gates": {
            "residual_under_limit": True,
            "redone_matches_supervisor": True,
            "recovery_observed": True,
            "completed_exact": True,
            "trajectory_green": True,
        },
    }
    payload.update(overrides)
    return payload


def test_goodput_schema_accepts_valid_payload():
    validate_goodput_payload(_goodput_payload())


def test_goodput_schema_rejects_categories_not_summing_to_wall():
    """ISSUE satellite: a goodput payload whose categories don't sum to
    wall is rejected — lost time must never read as high goodput."""
    payload = _goodput_payload()
    payload["ledger"]["seconds"]["step_productive"] = 1.0  # sum 5 of 10
    with pytest.raises(SchemaError, match="residual gate"):
        validate_goodput_payload(payload)


def test_goodput_schema_rejects_silent_mfu_omission():
    payload = _goodput_payload()
    del payload["ledger"]["mfu_omitted_reason"]
    with pytest.raises(SchemaError, match="mfu"):
        validate_goodput_payload(payload)


def test_goodput_sweep_dispatch_before_generic_fallback(tmp_path):
    """ISSUE satellite: the artifact sweep matches GOODPUT_* to its
    strict validator (ordered prefix table) — a goodput-named artifact
    that only satisfies the generic bench-line checks must FAIL."""
    path = _write_artifact(tmp_path, "GOODPUT_r99.json", {
        "metric": "train_goodput_fraction", "value": 0.9, "unit": "fraction",
    })
    with pytest.raises(SchemaError, match="ledger"):
        validate_artifact(path)
    # a valid payload passes through the same dispatch
    ok = _write_artifact(
        tmp_path, "GOODPUT_r98.json", _goodput_payload()
    )
    validate_artifact(ok)


def test_prefix_dispatch_order_is_most_specific_first():
    from distributeddeeplearning_tpu.obs import schema

    prefixes = [p for p, _ in schema._PREFIX_VALIDATORS]
    # OBS_FLEET_ must dispatch before the OBS_ prefix it also matches
    assert prefixes.index("OBS_FLEET_") < prefixes.index("OBS_")
    # GOODPUT_ is dispatched (not left to the generic fallback)
    assert "GOODPUT_" in prefixes


def test_committed_goodput_artifact_passes_gates():
    """The committed GOODPUT artifact is a real chaos run: schema-valid
    (also covered by the tier-1 sweep), all gates true, recovery and
    redone nonzero and supervisor-matched."""
    import glob

    paths = sorted(glob.glob(os.path.join(REPO_ROOT, "GOODPUT_r*.json")))
    assert paths, "no committed GOODPUT artifact"
    data = validate_artifact(paths[-1])
    assert all(data["gates"].values()), data["gates"]
    assert data["ledger"]["seconds"]["recovery"] > 0.0
    assert data["ledger"]["counts"]["steps_redone"] > 0
    assert (
        data["ledger"]["counts"]["steps_redone"]
        == data["supervisor"]["redone_steps"]
    )
    assert data["trajectory"]["green"] is True


# --------------------------------------------------------------------------
# bench smoke (fast tier, child processes only)
# --------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_bench_goodput_smoke(tmp_path):
    """bench.py --goodput --small end-to-end on CPU (slow tier — ~45s of
    supervised chaos child processes): the stitched ledger, every gate
    green, and the emitted artifact validating against its own schema.
    The fast tier still pins the committed artifact + its gates."""
    report = tmp_path / "GOODPUT_smoke.json"
    proc = subprocess.run(
        [
            sys.executable, "bench.py", "--goodput", "--small",
            "--report", str(report),
        ],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=580,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    data = json.loads(report.read_text())
    validate_goodput_payload(data)
    assert all(data["gates"].values())
    assert data["supervisor"]["restarts"] == 2
    assert data["ledger"]["counts"]["steps_redone"] == (
        data["supervisor"]["redone_steps"]
    )
