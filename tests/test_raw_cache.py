"""Decode-once raw cache (data/raw_cache.py) + bench shard generator.

The cache is the framework's answer to SURVEY §7 hard part (d) on
decode-bound hosts; these tests pin (a) pixel parity with the streaming
native pipeline up to uint8 quantization, (b) true-permutation shuffling
determinism, (c) host-shard geometry, and (d) the on-device normalization
path through the train step's ``input_transform`` hook.
"""

import json
import os

import numpy as np
import pytest

from distributeddeeplearning_tpu.data.bench_data import generate_bench_shards
from distributeddeeplearning_tpu.data.raw_cache import (
    build_raw_cache,
    cache_path_for,
    open_raw_cache,
    raw_cache_input_fn,
    uint8_normalizer,
)

N_IMAGES = 24
IMAGE_SIZE = 32


@pytest.fixture(scope="module")
def shard_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("bench-shards"))
    generate_bench_shards(d, num_images=N_IMAGES, num_shards=2, seed=7)
    return d


@pytest.fixture(scope="module")
def cache_dir(shard_dir):
    c = cache_path_for(shard_dir, True, IMAGE_SIZE)
    build_raw_cache(shard_dir, c, True, image_size=IMAGE_SIZE)
    return c


def test_generator_is_idempotent_and_deterministic(shard_dir, tmp_path):
    import hashlib

    def digest(d):
        h = hashlib.sha256()
        for name in sorted(os.listdir(d)):
            if name.startswith("train-"):
                h.update(open(os.path.join(d, name), "rb").read())
        return h.hexdigest()

    first = digest(shard_dir)
    # Re-generation with a matching manifest is a no-op...
    generate_bench_shards(shard_dir, num_images=N_IMAGES, num_shards=2, seed=7)
    assert digest(shard_dir) == first
    # ...and a fresh directory with the same params is byte-identical.
    other = str(tmp_path / "again")
    generate_bench_shards(other, num_images=N_IMAGES, num_shards=2, seed=7)
    assert digest(other) == first


def test_cache_matches_native_pipeline_up_to_quantization(shard_dir, cache_dir):
    from distributeddeeplearning_tpu.data.native_pipeline import native_input_fn
    from distributeddeeplearning_tpu.data.preprocessing import CHANNEL_MEANS

    manifest, images, labels = open_raw_cache(cache_dir)
    assert manifest["count"] == N_IMAGES
    assert images.shape == (N_IMAGES, IMAGE_SIZE, IMAGE_SIZE, 3)

    # The native train path yields mean-subtracted float32 in record order
    # when shuffling is disabled; the cache stores pre-mean uint8 pixels.
    batch = next(
        native_input_fn(
            shard_dir, True, N_IMAGES, image_size=IMAGE_SIZE,
            shard_count=1, shard_index=0, shuffle_buffer=0, repeat=False,
        )
    )
    means = np.asarray(CHANNEL_MEANS, np.float32)
    # shuffle_buffer=0 still shuffles file order; compare as multisets keyed
    # by label after restoring the mean.
    cached = {
        int(l): images[i].astype(np.float32) for i, l in enumerate(labels)
    }
    for img, label in zip(batch["image"], batch["label"]):
        ref = img + means
        got = cached[int(label)]
        assert np.abs(got - ref).max() <= 0.5 + 1e-3


def test_train_shuffle_is_seeded_permutation(cache_dir):
    def labels_for(seed, batches):
        it = raw_cache_input_fn(
            cache_dir, True, 8, shard_count=1, shard_index=0, seed=seed
        )
        return [next(it)["label"].tolist() for _ in range(batches)]

    a = labels_for(3, 6)
    b = labels_for(3, 6)
    assert a == b  # same seed -> identical epoch streams
    # Epoch 0 (first 3 batches of 8 = 24 images) and epoch 1 cover the same
    # multiset in different orders.
    epoch0 = sum(a[:3], [])
    epoch1 = sum(a[3:], [])
    assert sorted(epoch0) == sorted(epoch1)
    assert epoch0 != epoch1
    assert labels_for(4, 3) != a[:3]  # different seed, different order


def test_eval_order_and_remainder(cache_dir):
    it = raw_cache_input_fn(
        cache_dir, False, 7, shard_count=1, shard_index=0,
        drop_remainder=False,
    )
    batches = list(it)
    sizes = [len(b["label"]) for b in batches]
    assert sizes == [7, 7, 7, 3]
    _, images, labels = open_raw_cache(cache_dir)
    np.testing.assert_array_equal(
        np.concatenate([b["label"] for b in batches]), labels
    )
    np.testing.assert_array_equal(batches[0]["image"], images[:7])


def test_host_sharding_partitions_rows(cache_dir):
    seen = []
    for idx in range(2):
        it = raw_cache_input_fn(
            cache_dir, False, 4, shard_count=2, shard_index=idx,
            drop_remainder=False,
        )
        seen.append(np.concatenate([b["label"] for b in it]))
    _, _, labels = open_raw_cache(cache_dir)
    np.testing.assert_array_equal(np.sort(np.concatenate(seen)), np.sort(labels))
    assert len(seen[0]) == len(seen[1]) == N_IMAGES // 2


def test_build_is_idempotent(shard_dir, cache_dir):
    mtime = os.path.getmtime(os.path.join(cache_dir, "images.u8"))
    manifest = build_raw_cache(
        shard_dir, cache_dir, True, image_size=IMAGE_SIZE
    )
    assert manifest["count"] == N_IMAGES
    assert os.path.getmtime(os.path.join(cache_dir, "images.u8")) == mtime


def test_refuses_random_augmentation(shard_dir, tmp_path):
    with pytest.raises(ValueError, match="cannot be cached"):
        build_raw_cache(
            shard_dir, str(tmp_path / "c"), True, augment="inception"
        )


def test_corrupt_cache_detected(shard_dir, tmp_path):
    c = str(tmp_path / "corrupt")
    build_raw_cache(shard_dir, c, True, image_size=IMAGE_SIZE)
    with open(os.path.join(c, "manifest.json")) as f:
        manifest = json.load(f)
    manifest["count"] += 1
    with open(os.path.join(c, "manifest.json"), "w") as f:
        json.dump(manifest, f)
    with pytest.raises(ValueError, match="corrupt raw cache"):
        open_raw_cache(c)


def test_uint8_batch_trains_via_input_transform(cache_dir):
    """End-to-end: raw uint8 batch + on-device normalization reproduces the
    float-pipeline step (same params, same images) to fp32 tolerance."""
    import jax
    import jax.numpy as jnp

    from distributeddeeplearning_tpu.data.preprocessing import CHANNEL_MEANS
    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.parallel import (
        MeshSpec,
        create_mesh,
        shard_batch,
    )
    from distributeddeeplearning_tpu.train.state import (
        create_train_state,
        sgd_momentum,
    )
    from distributeddeeplearning_tpu.train.step import build_train_step

    mesh = create_mesh(MeshSpec(data=8))
    batch = next(
        raw_cache_input_fn(cache_dir, True, 24, shard_count=1, shard_index=0)
    )
    assert batch["image"].dtype == np.uint8

    model = get_model("resnet18", num_classes=1001, dtype=jnp.float32)
    tx = sgd_momentum(0.1)

    def run(images, transform):
        state = create_train_state(
            jax.random.key(0), model, (24, IMAGE_SIZE, IMAGE_SIZE, 3), tx
        )
        step = build_train_step(
            mesh, state, compute_dtype=jnp.float32, input_transform=transform
        )
        dev_batch = shard_batch(
            mesh, {"image": images, "label": batch["label"]}
        )
        _, metrics = step(state, dev_batch)
        return float(metrics["loss"]), float(metrics["top1"])

    means = np.asarray(CHANNEL_MEANS, np.float32)
    loss_float, top1_float = run(
        batch["image"].astype(np.float32) - means, None
    )
    loss_u8, top1_u8 = run(batch["image"], uint8_normalizer())
    assert np.isfinite(loss_u8)
    assert abs(loss_u8 - loss_float) < 1e-4
    assert top1_u8 == top1_float


def test_imagenet_workload_trains_on_raw_pipeline(shard_dir, tmp_path):
    """Full imagenet driver over the decode-once cache on the CPU mesh:
    cache auto-builds from the shard dir, uint8 batches flow through the
    step's on-device normalization, loss is finite and eval runs."""
    from distributeddeeplearning_tpu.data.bench_data import (
        generate_bench_shards,
    )
    from distributeddeeplearning_tpu.workloads import imagenet

    generate_bench_shards(
        shard_dir, num_images=N_IMAGES, num_shards=2, seed=8,
        split="validation",
    )
    state, result = imagenet.main(
        model="resnet18",
        data_format="tfrecords",
        input_pipeline="raw",
        training_data_path=shard_dir,
        validation_data_path=shard_dir,
        epochs=1,
        steps_per_epoch=2,
        batch_size=1,
        image_size=IMAGE_SIZE,
        num_classes=30,
        train_images=N_IMAGES,
        compute_dtype="float32",
        tensorboard_dir=str(tmp_path / "tb"),
    )
    assert result.epochs_run == 1
    assert np.isfinite(result.final_train_metrics["loss"])
    assert result.final_eval_metrics is not None


def test_start_batch_fast_forward_matches_stream(cache_dir):
    """start_batch=N reproduces exactly the stream's batch N onward —
    the replay-free resume contract (index math only, no decode)."""
    full = raw_cache_input_fn(
        cache_dir, True, 8, shard_count=1, shard_index=0, seed=5
    )
    want = [next(full) for _ in range(7)][4:]  # batches 4,5,6 (epoch 1 starts at 3)
    ff = raw_cache_input_fn(
        cache_dir, True, 8, shard_count=1, shard_index=0, seed=5,
        start_batch=4,
    )
    for expect in want:
        got = next(ff)
        np.testing.assert_array_equal(got["label"], expect["label"])
        np.testing.assert_array_equal(got["image"], expect["image"])


def test_start_batch_rejected_for_eval(cache_dir):
    with pytest.raises(ValueError, match="start_batch"):
        next(raw_cache_input_fn(
            cache_dir, False, 8, shard_count=1, shard_index=0, start_batch=2
        ))
