"""Pallas flash attention (ops/flash_attention.py).

Parity against the plain fused attention (models/bert.py
``dot_product_attention``) on the CPU backend (Pallas interpret mode):
forward values, gradients through the custom VJP, padding-mask handling,
and the BERT encoder end-to-end with the kernel injected.
"""

from __future__ import annotations

import jax
import jax.flatten_util
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.models.bert import dot_product_attention
from distributeddeeplearning_tpu.ops.flash_attention import (
    flash_attention,
    make_flash_attention,
)

B, S, H, D = 2, 64, 4, 32


def _inputs(seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    shape = (B, S, H, D)
    q = jnp.asarray(rng.standard_normal(shape), dtype)
    k = jnp.asarray(rng.standard_normal(shape), dtype)
    v = jnp.asarray(rng.standard_normal(shape), dtype)
    lengths = rng.integers(S // 2, S + 1, B)
    mask = jnp.asarray(
        (np.arange(S)[None, :] < lengths[:, None])[:, None, None, :]
    )
    return q, k, v, mask


def test_forward_matches_reference():
    q, k, v, mask = _inputs()
    got = flash_attention(q, k, v, mask, dtype=jnp.float32, block_q=16, block_k=16)
    want = dot_product_attention(q, k, v, mask, dtype=jnp.float32)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )
    assert np.isfinite(np.asarray(got)).all()


def test_forward_no_mask_single_block():
    q, k, v, _ = _inputs(1)
    got = flash_attention(q, k, v, None, dtype=jnp.float32, block_q=64, block_k=64)
    want = dot_product_attention(q, k, v, None, dtype=jnp.float32)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )


def test_gradients_match_reference():
    q, k, v, mask = _inputs(2)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, mask, dtype=jnp.float32, block_q=16, block_k=16)
        return (o ** 2).sum()

    def loss_ref(q, k, v):
        o = dot_product_attention(q, k, v, mask, dtype=jnp.float32)
        return (o ** 2).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-4, rtol=5e-4
        )


def test_bf16_inputs_supported():
    q, k, v, mask = _inputs(3, jnp.bfloat16)
    got = flash_attention(q, k, v, mask, dtype=jnp.bfloat16, block_q=32, block_k=32)
    want = dot_product_attention(q, k, v, mask, dtype=jnp.bfloat16)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), atol=0.05
    )


def test_indivisible_seq_rejected():
    q, k, v, mask = _inputs()
    with pytest.raises(ValueError, match="not divisible"):
        flash_attention(q, k, v, mask, dtype=jnp.float32, block_q=48, block_k=16)


def test_sharded_flash_matches_reference_on_mesh():
    """make_flash_attention(mesh=...) runs the kernel per-shard under
    shard_map (batch over data axes, heads over tensor) and must agree with
    the unsharded reference."""
    from distributeddeeplearning_tpu.parallel import MeshSpec, create_mesh
    from distributeddeeplearning_tpu.parallel.sharding import batch_sharding

    mesh = create_mesh(MeshSpec(tensor=2))
    # batch must divide the data axes (4-way with tensor=2 on 8 devices)
    rng = np.random.default_rng(5)
    shape = (8, S, H, D)
    q = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    k = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    v = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    lengths = rng.integers(S // 2, S + 1, 8)
    mask = jnp.asarray(
        (np.arange(S)[None, :] < lengths[:, None])[:, None, None, :]
    )
    attn = make_flash_attention(block_q=16, block_k=16, mesh=mesh)

    fn = jax.jit(lambda q, k, v, m: attn(q, k, v, m, dtype=jnp.float32))
    got = fn(q, k, v, mask)
    want = dot_product_attention(q, k, v, mask, dtype=jnp.float32)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )
    # also with explicitly batch-sharded inputs
    q_s = jax.device_put(q, batch_sharding(mesh))
    got_s = fn(q_s, k, v, mask)
    np.testing.assert_allclose(
        np.asarray(got_s), np.asarray(want), atol=2e-5, rtol=2e-5
    )


def test_bert_encoder_with_flash_attention():
    """Full model forward with the kernel injected as attention_fn."""
    from distributeddeeplearning_tpu.models import get_model

    tokens = np.asarray(
        np.random.default_rng(0).integers(0, 97, (2, 32)), np.int32
    )
    kwargs = dict(
        num_layers=2, hidden_size=64, num_heads=4, intermediate_size=128,
        vocab_size=97, num_classes=3, max_position_embeddings=32,
        dropout_rate=0.0, dtype=jnp.float32,
    )
    ref = get_model("bert-base", **kwargs)
    fl = get_model(
        "bert-base", **kwargs,
        attention_fn=make_flash_attention(block_q=16, block_k=16),
    )
    variables = ref.init(jax.random.key(0), tokens, train=False)
    out_ref = ref.apply(variables, tokens, train=False)
    out_fl = fl.apply(variables, tokens, train=False)
    np.testing.assert_allclose(
        np.asarray(out_fl), np.asarray(out_ref), atol=1e-4, rtol=1e-4
    )


def test_gradients_asymmetric_blocks():
    """The Pallas FA2 backward must be block-shape-agnostic (dq pass streams
    k blocks; dk/dv pass streams q blocks — different grids)."""
    q, k, v, mask = _inputs(5)

    def loss_flash(q, k, v):
        o = flash_attention(
            q, k, v, mask, dtype=jnp.float32, block_q=16, block_k=32
        )
        return (o ** 2).sum()

    def loss_ref(q, k, v):
        o = dot_product_attention(q, k, v, mask, dtype=jnp.float32)
        return (o ** 2).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-4, rtol=5e-4
        )


def test_bf16_gradients_finite():
    q, k, v, mask = _inputs(6, jnp.bfloat16)

    def loss(q, k, v):
        o = flash_attention(
            q, k, v, mask, dtype=jnp.bfloat16, block_q=32, block_k=32
        )
        return (o.astype(jnp.float32) ** 2).sum()

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g in grads:
        assert g.dtype == jnp.bfloat16
        assert np.isfinite(np.asarray(g, np.float32)).all()


# ---------------------------------------------------------------------------
# Causal mode (VERDICT r03 #2): in-kernel triangle mask + block skip, exact
# against a dense causal oracle in forward and all three gradients, alone
# and combined with key padding.
# ---------------------------------------------------------------------------


def _dense_causal(q, k, v, mask):
    """Dense causal oracle (the pipelined_transformer block's math)."""
    b, s, h, d = q.shape
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(
        jnp.asarray(d, jnp.float32)
    )
    if mask is not None:
        scores = jnp.where(
            jnp.broadcast_to(mask, (b, 1, 1, s)), scores, -1e30
        )
    tri = jnp.tril(jnp.ones((s, s), bool))
    scores = jnp.where(tri[None, None], scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("block", [16, 32, 64])
def test_causal_forward_matches_dense(block):
    q, k, v, _ = _inputs(3)
    got = flash_attention(
        q, k, v, None, dtype=jnp.float32, block_q=block, block_k=block,
        causal=True,
    )
    want = _dense_causal(q, k, v, None)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )


def test_causal_asymmetric_blocks():
    q, k, v, _ = _inputs(4)
    got = flash_attention(
        q, k, v, None, dtype=jnp.float32, block_q=16, block_k=32, causal=True
    )
    want = _dense_causal(q, k, v, None)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )
    got = flash_attention(
        q, k, v, None, dtype=jnp.float32, block_q=32, block_k=16, causal=True
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )


def test_causal_with_padding_mask():
    q, k, v, mask = _inputs(5)
    got = flash_attention(
        q, k, v, mask, dtype=jnp.float32, block_q=16, block_k=16, causal=True
    )
    want = _dense_causal(q, k, v, mask)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )


def test_causal_gradients_match_dense():
    q, k, v, mask = _inputs(6)

    def loss_flash(q, k, v):
        o = flash_attention(
            q, k, v, mask, dtype=jnp.float32, block_q=16, block_k=16,
            causal=True,
        )
        return (o ** 2).sum()

    def loss_ref(q, k, v):
        return (_dense_causal(q, k, v, mask) ** 2).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-4, rtol=5e-4
        )


def test_causal_first_row_attends_only_itself():
    """Query 0 may see only key 0 — its output must equal v[0] exactly."""
    q, k, v, _ = _inputs(7)
    got = flash_attention(
        q, k, v, None, dtype=jnp.float32, block_q=16, block_k=16, causal=True
    )
    np.testing.assert_allclose(
        np.asarray(got[:, 0]), np.asarray(v[:, 0]), atol=1e-6
    )


def test_pipelined_transformer_flash_matches_dense():
    """The decoder model's attention="flash" path reproduces the dense path
    (logits and parameter gradients) — the VERDICT's 'wired into the decoder'
    requirement, checked end-to-end through forward()."""
    from distributeddeeplearning_tpu.models.pipelined_transformer import (
        forward,
        init_params,
        next_token_loss,
    )

    params = init_params(
        jax.random.key(0), num_layers=2, d_model=64, num_heads=4, d_ff=128,
        vocab_size=97, max_len=32,
    )
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, 97, (2, 32)), jnp.int32
    )
    lg_dense = forward(params, toks, num_heads=4, attention="dense")
    lg_flash = forward(params, toks, num_heads=4, attention="flash")
    np.testing.assert_allclose(
        np.asarray(lg_flash), np.asarray(lg_dense), atol=2e-4, rtol=2e-4
    )

    def loss(p, attention):
        return next_token_loss(
            forward(p, toks, num_heads=4, attention=attention), toks
        )

    g_dense = jax.grad(lambda p: loss(p, "dense"))(params)
    g_flash = jax.grad(lambda p: loss(p, "flash"))(params)
    flat_d, _ = jax.flatten_util.ravel_pytree(g_dense)
    flat_f, _ = jax.flatten_util.ravel_pytree(g_flash)
    np.testing.assert_allclose(
        np.asarray(flat_f), np.asarray(flat_d), atol=5e-4, rtol=5e-4
    )


def test_auto_block_nondivisible_seq():
    """Seq lens divisible by 512 but not 1024 (e.g. 1536) must auto-select
    a smaller block instead of raising — regression for the 1024 default."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributeddeeplearning_tpu.ops.flash_attention import (
        _auto_block,
        flash_attention,
    )

    assert _auto_block(1536) == 512
    assert _auto_block(2048) == 1024
    assert _auto_block(2560) == 512
    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.standard_normal((1, 1536, 1, 8)), jnp.float32)
        for _ in range(3)
    )
    out = flash_attention(q, k, v, None, dtype=jnp.float32, causal=True)
    assert out.shape == (1, 1536, 1, 8)
    assert bool(jnp.isfinite(out).all())


def test_auto_block_floor_falls_back_to_dense():
    """Low-divisibility seq lens (1032 -> block 8, odd -> 1) must not run
    a pathological (S/b)^2 grid: the wrapper warns and takes the dense
    path, matching a plain-XLA reference exactly."""
    import warnings

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributeddeeplearning_tpu.ops.flash_attention import (
        _WARNED_FALLBACKS,
        _auto_block,
        flash_attention,
    )

    assert _auto_block(1032) == 8  # the pathological selection itself

    rng = np.random.default_rng(1)
    s = 1032
    q, k, v = (
        jnp.asarray(rng.standard_normal((1, s, 1, 8)), jnp.float32)
        for _ in range(3)
    )
    _WARNED_FALLBACKS.clear()  # a prior test may have burned this shape
    with pytest.warns(UserWarning, match="below the 128 floor"):
        out = flash_attention(q, k, v, None, dtype=jnp.float32, causal=True)

    # warn-once per shape class: the second identical call must be
    # SILENT (serve loops hit the fallback every step — a per-call
    # warning floods stderr without adding information)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        flash_attention(q, k, v, None, dtype=jnp.float32, causal=True)

    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(8.0)
    scores = jnp.where(jnp.tril(jnp.ones((s, s), bool)), scores, -1e30)
    ref = jnp.einsum(
        "bhqk,bkhd->bqhd", jax.nn.softmax(scores, axis=-1), v
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    # the fallback is differentiable (custom_vjp no longer in the path)
    g = jax.grad(
        lambda q: flash_attention(
            q, k, v, None, dtype=jnp.float32, causal=True
        ).sum()
    )(q)
    assert bool(jnp.isfinite(g).all())

    # seqs at/below the floor keep the kernel: single-tile grids are fine
    q2, k2, v2 = (x[:, :64] for x in (q, k, v))
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        out2 = flash_attention(q2, k2, v2, None, dtype=jnp.float32,
                               causal=True)
    assert out2.shape == (1, 64, 1, 8)

    # explicit tiny blocks are honoured (caller opted in) — no warning
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        out3 = flash_attention(q2, k2, v2, None, dtype=jnp.float32,
                               causal=True, block_q=8, block_k=8)
    np.testing.assert_allclose(np.asarray(out3), np.asarray(out2), atol=2e-5)
