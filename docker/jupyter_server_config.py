# Jupyter config for the control-plane image — role parity with the
# reference's control/Docker/jupyter_notebook_config.py (listen on all
# interfaces inside the container, fixed port mapped by `make docker-run`,
# no browser).  Written for the modern jupyter-server config surface.
c.ServerApp.ip = "0.0.0.0"  # noqa: F821 — `c` is injected by jupyter
c.ServerApp.port = 9999  # noqa: F821
c.ServerApp.open_browser = False  # noqa: F821
c.ServerApp.allow_root = True  # noqa: F821 — the container runs as root
c.ServerApp.root_dir = "/workspace"  # noqa: F821
