# Root lifecycle + smoke-generation Makefile.
#
# Role parity with the reference's two Makefiles:
#   - the root Makefile's non-interactive project smoke-gen + clean
#     (reference Makefile:5-19, `make cookiecutter` / `make clean`), here
#     driven by `ddlt new` instead of cookiecutter;
#   - the {{proj}}/Makefile control-plane lifecycle (build/run/bash/stop,
#     reference {{proj}}/Makefile:27-53), here `docker-build` / `docker-run` /
#     `docker-bash` / `docker-stop` over docker/Dockerfile.control.

PROJECT ?= smoke-test-project
IMAGE ?= ddlt-control
DATA_DIR ?= /data

.PHONY: install test test-fast lint perf-history obs-gate generate clean \
        bench-smoke bench scaling bench-tp bench-tier dryrun docker-build docker-run \
        docker-bash docker-stop

install:
	pip install -e .

test:
	python -m pytest tests/ -x -q

# Tier-1 flow: the hermetic observability gate runs first (attribution
# self-check + perf-trajectory gate, both seconds-cheap on CPU), then
# the fast test tier.
test-fast: obs-gate
	python -m pytest tests/ -x -q -m "not slow"

# Observability gate (obs/attrib.py + obs/history.py), hermetic: the
# attribution self-check builds its own tiny engines on the CPU backend
# and verifies program cost coverage + the HBM-ledger residual gates;
# the history gate re-reads every committed artifact as one metric
# timeline.  Non-zero exit on any gate failure.
obs-gate:
	python -m distributeddeeplearning_tpu.cli.main obs attrib --check
	python -m distributeddeeplearning_tpu.cli.main obs history --gate

# Static analysis (analysis/): AST hot-loop sync lint + jaxpr/HLO program
# audits.  Non-zero exit on any unwaived finding (the CLI pins a virtual
# CPU pod itself, so this works with no TPU attached).
lint:
	python -m distributeddeeplearning_tpu.cli.main lint

# Perf-trajectory gate (obs/history.py): every committed <KIND>_r{NN}.json
# parsed into one metric timeline; non-zero exit when a tracked metric
# regressed past its tolerance between the two newest revisions.
perf-history:
	python -m distributeddeeplearning_tpu.cli.main obs history --gate

# Smoke-generate a project non-interactively (reference Makefile:5-16).
generate:
	python -m distributeddeeplearning_tpu.cli.main new $(PROJECT) \
		--gcp-project smoke-project --gcs-bucket smoke-bucket
	@test -f $(PROJECT)/.env && test -f $(PROJECT)/Makefile \
		&& echo "generated $(PROJECT) OK"

clean:
	rm -rf $(PROJECT)

# Headline benchmark (tiny shapes — CI smoke; drop --small for real numbers).
bench-smoke:
	python bench.py --small

bench:
	python bench.py

# Allreduce scaling-efficiency sweep (BASELINE.json north-star #2).
scaling:
	python bench.py --devices 1,2,4,8 --small

# Tensor-parallel serving benchmark (TP_r{NN}.json): TP=1 vs TP=2 on a
# virtual pod, gated on bit-identical tokens, per-chip param HBM and the
# decode roofline.
bench-tp:
	python bench.py --tp 2

# Host-memory KV page tier benchmark (TIER_r{NN}.json): bit-identical
# spill/restore, prefix-hit rate and admitted-tokens/HBM-byte at 4-10x
# session oversubscription vs the no-tier baseline, decode parity when
# the working set fits in HBM.
bench-tier:
	python bench.py --tier

# Multi-chip sharding dry run on a virtual 8-device pod (the XLA_FLAGS
# hint lets utils/virtual_pod pin the CPU platform without touching the
# hardware plugin, so this works even when the TPU tunnel is down).
dryrun:
	XLA_FLAGS="$$XLA_FLAGS --xla_force_host_platform_device_count=8" python __graft_entry__.py 8

# ---- Control-plane container lifecycle ({{proj}}/Makefile:27-53 parity) ----

docker-build:
	docker build -t $(IMAGE) -f docker/Dockerfile.control .

docker-run:
	docker run -d --name $(IMAGE) \
		-v $(CURDIR):/workspace -v $(DATA_DIR):/data \
		-p 6006:6006 -p 9999:9999 \
		$(IMAGE) sleep infinity
	docker exec -it $(IMAGE) tmux new-session -s control

docker-bash:
	docker exec -it $(IMAGE) tmux attach-session -t control

docker-stop:
	docker rm -f $(IMAGE)
