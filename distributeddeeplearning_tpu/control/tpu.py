"""TPU pod lifecycle: idempotent get-or-create, SSH fan-out, delete.

Capability parity with the reference's AML compute layer:

- get-or-create cluster (``control/src/aml_compute.py:47-71`` — try
  ``ComputeTarget(...)``, create on miss, idempotent on re-run) becomes
  ``gcloud compute tpus tpu-vm describe`` → ``create`` on miss;
- the MPI launcher geometry (``node_count × process_count_per_node``,
  ``aml_compute.py:108-133``) becomes the TPU worker topology: ONE process
  per TPU-VM host driving all its local chips — there is no per-chip rank;
- per-host command fan-out (the mpirun replacement) is
  ``gcloud compute tpus tpu-vm ssh --worker=all --command=...``; the JAX
  runtime performs rendezvous via the TPU metadata service, so no
  coordinator address plumbing is needed on a pod slice;
- ``delete`` parity with ``tasks.py delete`` (resource teardown).

All gcloud calls are composed here and executed through CommandRunner, so
tests assert the exact command lines with no cloud access.
"""

from __future__ import annotations

import json
import logging
import math
import re
from typing import Dict, List, Optional

from distributeddeeplearning_tpu.control.command import CommandRunner

logger = logging.getLogger("ddlt.control.tpu")

# Chips per TPU-VM host by generation; worker (host) count follows from the
# accelerator-type chip count.  Overridable via the TPU_WORKER_COUNT setting.
_CHIPS_PER_HOST = {"v2": 4, "v3": 4, "v4": 4, "v5p": 4, "v5litepod": 8, "v6e": 8}
# Generations whose type suffix counts TensorCores (2 per chip), not chips.
_CORES_SUFFIX = {"v2", "v3", "v4", "v5p"}


def topology_from_type(accelerator_type: str) -> Dict[str, int]:
    """{'chips': N, 'hosts': H} for an accelerator type like ``v5litepod-32``.

    The TPU analogue of the reference's fixed ``process_count_per_node=4``
    GPU geometry (``aml_compute.py:108-109``).
    """
    m = re.fullmatch(r"(v\d+[a-z]*|v5litepod)-(\d+)", accelerator_type)
    if not m:
        raise ValueError(f"unrecognized accelerator type {accelerator_type!r}")
    gen, count = m.group(1), int(m.group(2))
    chips = count // 2 if gen in _CORES_SUFFIX else count
    chips = max(chips, 1)
    per_host = _CHIPS_PER_HOST.get(gen, 4)
    return {"chips": chips, "hosts": max(math.ceil(chips / per_host), 1)}


class TpuPod:
    """Handle to one named TPU pod slice (the reference's ``.cluster``)."""

    def __init__(
        self,
        runner: CommandRunner,
        *,
        name: str,
        zone: str,
        accelerator_type: str,
        runtime_version: str,
        project: Optional[str] = None,
        preemptible: bool = False,
    ):
        self.runner = runner
        self.name = name
        self.zone = zone
        self.accelerator_type = accelerator_type
        self.runtime_version = runtime_version
        self.project = project
        self.preemptible = preemptible

    # -- composed gcloud invocations ------------------------------------

    def _base(self, *verbs: str, surface: str = "tpu-vm") -> List[str]:
        argv = ["gcloud", "compute", "tpus", surface, *verbs]
        if self.project:
            argv += ["--project", self.project]
        return argv

    def _describe_json(
        self, name: str, *, surface: str = "tpu-vm", retries: int = 0
    ):
        """Describe ``name`` on a gcloud surface → dict, or None if absent.

        ``retries`` re-probes transient gcloud failures (idempotent read) —
        the preemption retry loop passes it so one flaky describe does not
        get mistaken for a vanished pod.
        """
        result = self.runner.run(
            self._base("describe", name, surface=surface)
            + ["--zone", self.zone, "--format", "json"],
            check=False,
            retries=retries,
        )
        if self.runner.dry_run:
            # Assume absent so dry-run shows the mutation commands too.
            return None
        if not result.ok:
            return None
        try:
            return json.loads(result.stdout) if result.stdout.strip() else {}
        except json.JSONDecodeError:
            return {}

    def describe(self, *, retries: int = 0):
        """Pod metadata dict, or None when the pod does not exist."""
        return self._describe_json(self.name, retries=retries)

    def exists(self) -> bool:
        return self.describe() is not None

    def state(self, *, retries: int = 0) -> Optional[str]:
        """Lifecycle state from the API (READY, PREEMPTED, TERMINATED, …);
        None when the pod does not exist."""
        meta = self.describe(retries=retries)
        if meta is None:
            return None
        return meta.get("state", "UNKNOWN")

    def recreate(self) -> None:
        """Delete + re-provision — the preemption-recovery primitive.

        Queued-resource-managed pods (a request exists for this pod's
        default request id) cannot be removed with ``tpu-vm delete``; they
        are torn down via the request and RE-QUEUED.  The new request may
        sit in WAITING_FOR_RESOURCES — callers that need the pod
        synchronously (the preemption retry loop) will then see a
        non-READY state and stop cleanly rather than loop on a dead node.
        """
        logger.warning("recreating TPU %s", self.name)
        if self.queued_state() is not None:
            self.delete_queued(force=True)
            self.request_queued()
            return
        self.delete()
        self.create()

    def create(self) -> bool:
        """Get-or-create; returns True when a pod was actually created.

        Idempotency parity with ``_create_cluster`` (``aml_compute.py:55-58``:
        found → reuse, log, return).
        """
        if self.exists():
            logger.info("TPU %s already exists — reusing", self.name)
            return False
        logger.info(
            "creating TPU %s (%s, %s)", self.name, self.accelerator_type, self.zone
        )
        argv = self._base("create", self.name) + [
            "--zone", self.zone,
            "--accelerator-type", self.accelerator_type,
            "--version", self.runtime_version,
        ]
        if self.preemptible:
            argv.append("--preemptible")
        self.runner.run(argv)
        return True

    def delete(self) -> None:
        self.runner.run(
            self._base("delete", self.name) + ["--zone", self.zone, "--quiet"],
            check=False,
        )

    # -- queued resources (how v5e+ capacity is actually obtained) ------

    def request_queued(
        self,
        *,
        request_id: Optional[str] = None,
        spot: bool = False,
        reserved: bool = False,
        valid_until_duration: Optional[str] = None,
    ) -> str:
        """File a queued-resource request for this pod.

        On-demand `create` frequently stockouts for v5e/v5p slices; the
        queued-resources API is how capacity is obtained in practice (the
        role AML's autoscale quota played, ``aml_compute.py:47-71``).  The
        request provisions a node with this pod's name when granted, so
        every other verb (ssh/scp/bootstrap/submit) works unchanged once
        ``queued_state`` reports ACTIVE.  Returns the request id.
        """
        rid = request_id or f"{self.name}-req"
        argv = self._base("create", rid, surface="queued-resources") + [
            "--zone", self.zone,
            "--node-id", self.name,
            "--accelerator-type", self.accelerator_type,
            "--runtime-version", self.runtime_version,
        ]
        if spot or self.preemptible:
            # TPU_PREEMPTIBLE=true means spot semantics everywhere —
            # create() adds --preemptible; the queued surface calls it spot.
            argv.append("--spot")
        if reserved:
            argv.append("--reserved")
        if valid_until_duration:
            argv += ["--valid-until-duration", valid_until_duration]
        self.runner.run(argv)
        return rid

    def queued_state(self, request_id: Optional[str] = None) -> Optional[str]:
        """The request's lifecycle state (WAITING_FOR_RESOURCES,
        PROVISIONING, ACTIVE, FAILED, SUSPENDED, …); None when absent."""
        rid = request_id or f"{self.name}-req"
        meta = self._describe_json(rid, surface="queued-resources")
        if meta is None or not meta:
            # absent OR an empty describe payload: no usable request —
            # treat like absence so tpu-vm-managed pods aren't misclassified
            return None
        state = meta.get("state")
        if isinstance(state, dict):
            return state.get("state", "UNKNOWN")
        return str(state) if state else "UNKNOWN"

    def delete_queued(
        self, request_id: Optional[str] = None, *, force: bool = False
    ) -> bool:
        """Cancel/release the request (also required before re-requesting a
        failed one — the API keeps terminal requests around).

        An ACTIVE request owns a LIVE TPU node; deleting it tears the node
        (and any running job) down, so that path requires ``force=True``.
        Returns False when refused.
        """
        rid = request_id or f"{self.name}-req"
        if not force and self.queued_state(rid) == "ACTIVE":
            logger.error(
                "queued-resource request %s is ACTIVE (owns a live TPU "
                "node); pass force to tear it down", rid,
            )
            return False
        self.runner.run(
            self._base("delete", rid, surface="queued-resources")
            + ["--zone", self.zone, "--quiet", "--force"],
            check=False,
        )
        return True

    def ssh(
        self,
        command: str,
        *,
        worker: str = "all",
        env: Optional[Dict[str, str]] = None,
        check: bool = True,
        stream_to: Optional[str] = None,
    ):
        """Run ``command`` on pod workers — the per-host launcher fan-out
        that replaces ``mpirun`` (``aml_compute.py:128`` distributed_backend).

        ``env`` is injected as ``KEY=VALUE`` exports prefixed to the command,
        the analogue of the estimator's environment-variable injection
        (``DISTRIBUTED=True`` etc., ``aml_compute.py:86-90``).

        ``stream_to`` tees the fan-out's output live to console + log file
        (gcloud multiplexes all workers' stdout onto the one ssh stream).
        """
        if env:
            import shlex

            exports = " ".join(
                f"{k}={shlex.quote(str(v))}" for k, v in sorted(env.items())
            )
            command = f"export {exports} && {command}"
        return self.runner.run(
            self._base("ssh", self.name)
            + ["--zone", self.zone, "--worker", str(worker), "--command", command],
            check=check,
            stream_to=stream_to,
        )

    def interactive(self, *, worker: str = "0"):
        """Open an interactive shell on one worker (``inv interactive``
        parity, ``README.md:271-311``): plain gcloud ssh, no --command."""
        return self.runner.run(
            self._base("ssh", self.name)
            + ["--zone", self.zone, "--worker", str(worker)],
            capture=False,
            check=False,
        )

    def scp(self, src: str, dst: str, *, worker: str = "all"):
        """Copy files to pod workers (code distribution before launch)."""
        return self.runner.run(
            self._base("scp", src, f"{self.name}:{dst}")
            + ["--zone", self.zone, "--worker", str(worker), "--recurse"]
        )

    @property
    def topology(self) -> Dict[str, int]:
        return topology_from_type(self.accelerator_type)


def list_pods(runner: CommandRunner, zone: str, project: Optional[str] = None) -> list:
    argv = ["gcloud", "compute", "tpus", "tpu-vm", "list", "--zone", zone,
            "--format", "json"]
    if project:
        argv += ["--project", project]
    result = runner.run(argv, check=False)
    if not result.ok or not result.stdout.strip():
        return []
    try:
        return json.loads(result.stdout)
    except json.JSONDecodeError:
        return []


def pod_from_settings(settings, runner: CommandRunner) -> TpuPod:
    """Construct the project pod handle from layered config (the reference
    defaults every cluster setting from ``.env`` — ``aml_compute.py:27-44``)."""
    return TpuPod(
        runner,
        name=settings.get("TPU_NAME"),
        zone=settings.get("GCP_ZONE"),
        accelerator_type=settings.get("TPU_TYPE"),
        runtime_version=settings.get("TPU_RUNTIME_VERSION"),
        project=settings.get("GCP_PROJECT") or None,
        preemptible=settings.get_bool("TPU_PREEMPTIBLE", False),
    )
