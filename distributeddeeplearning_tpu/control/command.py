"""Subprocess command runner — the seam between tasks and the cloud CLIs.

The reference's tasks shell out to ``az``/``azcopy`` through invoke's
``c.run`` (``scripts/storage.py``, ``tasks.py``); that context object is what
makes its tasks testable.  Here the same seam is explicit: every gcloud /
gsutil / launcher invocation goes through :class:`CommandRunner`, which

- records every argv it executes (tests assert on composed command lines),
- supports ``dry_run`` (print, don't execute — the operator can copy/paste),
- raises :class:`CommandError` with captured output on failure.
"""

from __future__ import annotations

import dataclasses
import logging
import shlex
import subprocess
from typing import Dict, List, Optional, Sequence

logger = logging.getLogger("ddlt.control")


class CommandError(RuntimeError):
    def __init__(self, argv: Sequence[str], returncode: int, stdout: str, stderr: str):
        self.argv = list(argv)
        self.returncode = returncode
        self.stdout = stdout
        self.stderr = stderr
        super().__init__(
            f"command failed (rc={returncode}): {shlex.join(argv)}\n{stderr or stdout}"
        )


@dataclasses.dataclass
class CommandResult:
    argv: List[str]
    returncode: int
    stdout: str = ""
    stderr: str = ""

    @property
    def ok(self) -> bool:
        return self.returncode == 0


class CommandRunner:
    """Executes external commands; records history; optional dry-run."""

    def __init__(self, dry_run: bool = False):
        self.dry_run = dry_run
        self.history: List[List[str]] = []

    def run(
        self,
        argv: Sequence[str],
        *,
        check: bool = True,
        capture: bool = True,
        env: Optional[Dict[str, str]] = None,
        timeout: Optional[float] = None,
        stream_to: Optional[str] = None,
    ) -> CommandResult:
        """Execute ``argv``.

        ``stream_to`` tees the command's merged stdout/stderr LIVE to both
        the operator's console and the named log file (the reference's
        ``wait_for_completion(show_output=True)`` role,
        ``aml_compute.py:391-392``) — a multi-hour remote run scrolls its
        epochs instead of printing nothing until exit.  The returned
        ``CommandResult.stdout`` carries the tail of the stream so failure
        paths can still report context.
        """
        argv = [str(a) for a in argv]
        self.history.append(argv)
        if self.dry_run:
            print(f"[dry-run] {shlex.join(argv)}")
            return CommandResult(argv=argv, returncode=0)
        logger.debug("exec: %s", shlex.join(argv))
        if stream_to is not None:
            if timeout is not None:
                # The line-by-line tee loop has no read deadline; silently
                # dropping a requested bound would be worse than refusing.
                raise ValueError("timeout is not supported with stream_to")
            result = self._run_streaming(argv, stream_to, env=env)
        else:
            proc = subprocess.run(
                argv,
                capture_output=capture,
                text=True,
                env=env,
                timeout=timeout,
            )
            result = CommandResult(
                argv=argv,
                returncode=proc.returncode,
                stdout=proc.stdout or "",
                stderr=proc.stderr or "",
            )
        if check and not result.ok:
            raise CommandError(argv, result.returncode, result.stdout, result.stderr)
        return result

    _STREAM_TAIL_CHARS = 8192

    def _run_streaming(
        self,
        argv: List[str],
        stream_to: str,
        *,
        env: Optional[Dict[str, str]] = None,
    ) -> CommandResult:
        import sys
        from collections import deque
        from pathlib import Path

        log_path = Path(stream_to)
        log_path.parent.mkdir(parents=True, exist_ok=True)
        tail: deque = deque(maxlen=256)
        with subprocess.Popen(
            argv,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            # Remote fan-out output is not guaranteed UTF-8 (worker locales,
            # binary progress bars); strict decoding would kill the tee loop
            # mid-run and strand the run as 'running'.
            errors="replace",
            env=env,
            bufsize=1,  # line buffered
        ) as proc, open(log_path, "a") as log:
            assert proc.stdout is not None
            for line in proc.stdout:
                sys.stdout.write(line)
                sys.stdout.flush()
                log.write(line)
                log.flush()
                tail.append(line)
            returncode = proc.wait()
        return CommandResult(
            argv=argv,
            returncode=returncode,
            stdout="".join(tail)[-self._STREAM_TAIL_CHARS:],
        )
