"""Subprocess command runner — the seam between tasks and the cloud CLIs.

The reference's tasks shell out to ``az``/``azcopy`` through invoke's
``c.run`` (``scripts/storage.py``, ``tasks.py``); that context object is what
makes its tasks testable.  Here the same seam is explicit: every gcloud /
gsutil / launcher invocation goes through :class:`CommandRunner`, which

- records every argv it executes (tests assert on composed command lines),
- supports ``dry_run`` (print, don't execute — the operator can copy/paste),
- raises :class:`CommandError` with captured output on failure.
"""

from __future__ import annotations

import dataclasses
import logging
import shlex
import subprocess
from typing import Dict, List, Optional, Sequence

logger = logging.getLogger("ddlt.control")


class CommandError(RuntimeError):
    def __init__(self, argv: Sequence[str], returncode: int, stdout: str, stderr: str):
        self.argv = list(argv)
        self.returncode = returncode
        self.stdout = stdout
        self.stderr = stderr
        super().__init__(
            f"command failed (rc={returncode}): {shlex.join(argv)}\n{stderr or stdout}"
        )


@dataclasses.dataclass
class CommandResult:
    argv: List[str]
    returncode: int
    stdout: str = ""
    stderr: str = ""

    @property
    def ok(self) -> bool:
        return self.returncode == 0


class CommandRunner:
    """Executes external commands; records history; optional dry-run."""

    def __init__(self, dry_run: bool = False):
        self.dry_run = dry_run
        self.history: List[List[str]] = []

    def run(
        self,
        argv: Sequence[str],
        *,
        check: bool = True,
        capture: bool = True,
        env: Optional[Dict[str, str]] = None,
        timeout: Optional[float] = None,
    ) -> CommandResult:
        argv = [str(a) for a in argv]
        self.history.append(argv)
        if self.dry_run:
            print(f"[dry-run] {shlex.join(argv)}")
            return CommandResult(argv=argv, returncode=0)
        logger.debug("exec: %s", shlex.join(argv))
        proc = subprocess.run(
            argv,
            capture_output=capture,
            text=True,
            env=env,
            timeout=timeout,
        )
        result = CommandResult(
            argv=argv,
            returncode=proc.returncode,
            stdout=proc.stdout or "",
            stderr=proc.stderr or "",
        )
        if check and not result.ok:
            raise CommandError(argv, proc.returncode, result.stdout, result.stderr)
        return result
