"""Subprocess command runner — the seam between tasks and the cloud CLIs.

The reference's tasks shell out to ``az``/``azcopy`` through invoke's
``c.run`` (``scripts/storage.py``, ``tasks.py``); that context object is what
makes its tasks testable.  Here the same seam is explicit: every gcloud /
gsutil / launcher invocation goes through :class:`CommandRunner`, which

- records every argv it executes (tests assert on composed command lines),
- supports ``dry_run`` (print, don't execute — the operator can copy/paste),
- raises :class:`CommandError` with captured output on failure.
"""

from __future__ import annotations

import dataclasses
import logging
import shlex
import subprocess
import time
from typing import Dict, List, Optional, Sequence

logger = logging.getLogger("ddlt.control")


class CommandError(RuntimeError):
    def __init__(self, argv: Sequence[str], returncode: int, stdout: str, stderr: str):
        self.argv = list(argv)
        self.returncode = returncode
        self.stdout = stdout
        self.stderr = stderr
        super().__init__(
            f"command failed (rc={returncode}): {shlex.join(argv)}\n{stderr or stdout}"
        )


@dataclasses.dataclass
class CommandResult:
    argv: List[str]
    returncode: int
    stdout: str = ""
    stderr: str = ""

    @property
    def ok(self) -> bool:
        return self.returncode == 0


class CommandRunner:
    """Executes external commands; records history; optional dry-run."""

    def __init__(self, dry_run: bool = False):
        self.dry_run = dry_run
        self.history: List[List[str]] = []
        self._sleep = time.sleep  # injectable for tests

    def run(
        self,
        argv: Sequence[str],
        *,
        check: bool = True,
        capture: bool = True,
        env: Optional[Dict[str, str]] = None,
        timeout: Optional[float] = None,
        stream_to: Optional[str] = None,
        retries: int = 0,
    ) -> CommandResult:
        """Execute ``argv``.

        ``stream_to`` tees the command's merged stdout/stderr LIVE to both
        the operator's console and the named log file (the reference's
        ``wait_for_completion(show_output=True)`` role,
        ``aml_compute.py:391-392``) — a multi-hour remote run scrolls its
        epochs instead of printing nothing until exit.  The returned
        ``CommandResult.stdout`` carries the tail of the stream so failure
        paths can still report context.

        ``retries`` re-runs a FAILING command up to that many times with
        jittered exponential backoff (``utils/retry.py``) before the
        check/return decision — for idempotent cloud reads (``gcloud
        describe``, state probes) that fail transiently all the time.
        Every attempt is recorded in ``history``.  Never retry mutating
        verbs that are not idempotent.
        """
        argv = [str(a) for a in argv]
        if self.dry_run:
            self.history.append(argv)
            print(f"[dry-run] {shlex.join(argv)}")
            return CommandResult(argv=argv, returncode=0)
        # Lazy import: pulling utils.retry at module scope executes the
        # utils package __init__, which imports jax — and the control plane
        # must stay importable (and fast) on jax-less operator machines.
        from distributeddeeplearning_tpu.utils.retry import backoff_delays

        delays = backoff_delays(retries, base_delay=0.5, max_delay=10.0)
        attempt = 0
        while True:
            result = self._run_once(
                argv, capture=capture, env=env, timeout=timeout,
                stream_to=stream_to,
            )
            if result.ok or attempt >= retries:
                break
            delay = next(delays)
            attempt += 1
            logger.warning(
                "command failed (rc=%d): %s — retry %d/%d in %.1fs",
                result.returncode, shlex.join(argv), attempt, retries, delay,
            )
            self._sleep(delay)
        if check and not result.ok:
            raise CommandError(argv, result.returncode, result.stdout, result.stderr)
        return result

    def _run_once(
        self,
        argv: List[str],
        *,
        capture: bool,
        env: Optional[Dict[str, str]],
        timeout: Optional[float],
        stream_to: Optional[str],
    ) -> CommandResult:
        self.history.append(argv)
        logger.debug("exec: %s", shlex.join(argv))
        if stream_to is not None:
            if timeout is not None:
                # The line-by-line tee loop has no read deadline; silently
                # dropping a requested bound would be worse than refusing.
                raise ValueError("timeout is not supported with stream_to")
            return self._run_streaming(argv, stream_to, env=env)
        proc = subprocess.run(
            argv,
            capture_output=capture,
            text=True,
            env=env,
            timeout=timeout,
        )
        return CommandResult(
            argv=argv,
            returncode=proc.returncode,
            stdout=proc.stdout or "",
            stderr=proc.stderr or "",
        )

    _STREAM_TAIL_CHARS = 8192

    def _run_streaming(
        self,
        argv: List[str],
        stream_to: str,
        *,
        env: Optional[Dict[str, str]] = None,
    ) -> CommandResult:
        import sys
        from collections import deque
        from pathlib import Path

        log_path = Path(stream_to)
        log_path.parent.mkdir(parents=True, exist_ok=True)
        tail: deque = deque(maxlen=256)
        with subprocess.Popen(
            argv,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            # Remote fan-out output is not guaranteed UTF-8 (worker locales,
            # binary progress bars); strict decoding would kill the tee loop
            # mid-run and strand the run as 'running'.
            errors="replace",
            env=env,
            bufsize=1,  # line buffered
        ) as proc, open(log_path, "a") as log:
            assert proc.stdout is not None
            for line in proc.stdout:
                sys.stdout.write(line)
                sys.stdout.flush()
                log.write(line)
                log.flush()
                tail.append(line)
            returncode = proc.wait()
        return CommandResult(
            argv=argv,
            returncode=returncode,
            stdout="".join(tail)[-self._STREAM_TAIL_CHARS:],
        )
