"""GCS data-plane tasks: bucket lifecycle + image/tfrecord transfer.

Capability parity with the reference's storage scripts, re-keyed for GCS:

- ``create_premium_storage`` / ``create_container`` with idempotency checks
  (``scripts/storage.py:28-112``) → ``ensure_bucket`` (describe → create on
  miss).  GCS has no separate "container" and no harvestable account key —
  authentication is gcloud ADC — so the ``store_key`` → ``.env`` write-back
  contract (``storage.py:74-78``) persists the discovered/created BUCKET
  name instead.
- AzCopy up/down of image trees (``scripts/image.py:7-90``) and tfrecords
  (``scripts/tfrecords.py:13-106``) → ``gcloud storage rsync -r``
  (idempotent re-runs transfer only the delta, like azcopy's resume).
- ``generate_tf_records`` JPEG-count gate (``scripts/tfrecords.py:112-118``)
  → the same guardrail before conversion.

Remote layout (the ``{datastore}`` root):
    gs://<bucket>/images/train , gs://<bucket>/images/validation
    gs://<bucket>/tfrecords/train , gs://<bucket>/tfrecords/validation
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Optional

from distributeddeeplearning_tpu.control.command import CommandRunner

logger = logging.getLogger("ddlt.control.storage")

IMAGE_PREFIX = "images"
TFRECORD_PREFIX = "tfrecords"


class GcsStorage:
    """Bucket handle; all gsutil-equivalent calls via ``gcloud storage``."""

    def __init__(
        self,
        runner: CommandRunner,
        *,
        bucket: str,
        project: Optional[str] = None,
        location: Optional[str] = None,
    ):
        if not bucket:
            raise ValueError("bucket name is required (set GCS_BUCKET)")
        self.runner = runner
        self.bucket = bucket.removeprefix("gs://")
        self.project = project
        self.location = location

    @property
    def url(self) -> str:
        return f"gs://{self.bucket}"

    def exists(self) -> bool:
        result = self.runner.run(
            ["gcloud", "storage", "buckets", "describe", self.url,
             "--format", "json"],
            check=False,
        )
        if self.runner.dry_run:
            # Assume absent so dry-run shows the mutation commands too.
            return False
        return result.ok

    def ensure_bucket(self, settings=None) -> bool:
        """Get-or-create; persists the bucket name to ``.env`` when a
        Settings object is passed (store_key write-back parity).  Returns
        True when the bucket was actually created."""
        created = False
        if self.exists():
            logger.info("bucket %s exists", self.url)
        else:
            argv = ["gcloud", "storage", "buckets", "create", self.url]
            if self.project:
                argv += ["--project", self.project]
            if self.location:
                argv += ["--location", self.location]
            self.runner.run(argv)
            created = True
        if settings is not None and not self.runner.dry_run:
            settings.persist("GCS_BUCKET", self.bucket)
        return created

    def delete_bucket(self) -> None:
        self.runner.run(
            ["gcloud", "storage", "rm", "-r", self.url], check=False
        )

    # -- transfer (azcopy parity) ---------------------------------------

    def _rsync(self, src: str, dst: str):
        # rsync is idempotent, so transient gs:// failures retry safely
        # (utils/retry.py backoff via CommandRunner).
        return self.runner.run(
            ["gcloud", "storage", "rsync", "-r", src, dst], retries=2
        )

    def upload(self, local_dir: str, remote_prefix: str):
        return self._rsync(str(local_dir), f"{self.url}/{remote_prefix}")

    def download(self, remote_prefix: str, local_dir: str):
        Path(local_dir).mkdir(parents=True, exist_ok=True)
        return self._rsync(f"{self.url}/{remote_prefix}", str(local_dir))

    def upload_images(self, data_dir: str):
        """Train + validation image trees (``scripts/image.py:10-14``)."""
        self.upload(Path(data_dir) / "train", f"{IMAGE_PREFIX}/train")
        self.upload(Path(data_dir) / "validation", f"{IMAGE_PREFIX}/validation")

    def download_images(self, data_dir: str):
        self.download(f"{IMAGE_PREFIX}/train", Path(data_dir) / "train")
        self.download(f"{IMAGE_PREFIX}/validation", Path(data_dir) / "validation")

    def upload_tfrecords(self, tfrecords_dir: str):
        self.upload(tfrecords_dir, TFRECORD_PREFIX)

    def download_tfrecords(self, tfrecords_dir: str):
        self.download(TFRECORD_PREFIX, tfrecords_dir)


def count_jpegs(directory: str) -> int:
    """Recursive JPEG count — the conversion gate's input
    (``scripts/tfrecords.py:112-118``)."""
    root = Path(directory)
    if not root.exists():
        return 0
    return sum(
        1
        for p in root.rglob("*")
        if p.suffix.lower() in (".jpeg", ".jpg")
    )


def generate_tfrecords_gated(
    image_dir: str,
    output_dir: str,
    *,
    expected_train: int = 1281167,
    expected_validation: int = 50000,
    force: bool = False,
    **convert_kwargs,
):
    """Convert images → TFRecords only when the JPEG counts look complete.

    The reference refuses to convert partial data (``tfrecords.py:107-127``);
    ``force=True`` overrides for subsets (tests, smoke runs).
    """
    from distributeddeeplearning_tpu.data.convert_tfrecords import convert_imagenet

    train_count = count_jpegs(Path(image_dir) / "train")
    val_count = count_jpegs(Path(image_dir) / "validation")
    if not force and (train_count < expected_train or val_count < expected_validation):
        raise RuntimeError(
            f"refusing to convert: found {train_count} train / {val_count} "
            f"validation JPEGs, expected {expected_train} / {expected_validation} "
            f"(pass --force for subsets)"
        )
    return convert_imagenet(image_dir, output_dir, **convert_kwargs)
