"""Job submission: local debug runs and remote TPU-pod fan-out.

Capability parity with the reference's estimator submit machinery
(``control/src/aml_compute.py:265-536``), TPU-native:

- ``{datastore}`` path templating: any script param containing the
  ``{datastore}`` placeholder is rewritten to the storage root — a GCS
  bucket URL for remote runs, the local data dir for local runs
  (``aml_compute.py:395-403`` rewrote to AML datastore mounts);
- the ``DISTRIBUTED`` environment switch the training scripts key off
  (``aml_compute.py:86-90``): False for local single-host debug, True for
  pod runs;
- local submit = the identical entry module run as a subprocess on this
  host (the reference ran the identical script in a sibling docker
  container — ``aml_compute.py:272-304``; README: "local execution is
  meant for debugging");
- remote submit = get-or-create the pod, then fan the per-host launcher
  out over every TPU-VM worker via SSH (the mpirun replacement;
  ``distributed_backend="mpi"`` at ``aml_compute.py:128``).  JAX's TPU
  runtime handles multi-host rendezvous via the metadata service, so the
  composed command is identical on every worker;
- every submit records a Run in the local registry (AML Run tracking role).
"""

from __future__ import annotations

import logging
import os
import sys
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

from distributeddeeplearning_tpu.control.command import CommandRunner
from distributeddeeplearning_tpu.control.runs import Run, RunRegistry
from distributeddeeplearning_tpu.control.tpu import TpuPod, pod_from_settings

logger = logging.getLogger("ddlt.control.submit")

# The workload runner's resumable exit code (train/resilience.py
# RESUMABLE_EXIT_CODE = 75, EX_TEMPFAIL).  Declared here as a literal
# rather than imported: importing anything under `train` executes
# train/__init__, which pulls the full jax/flax/optax stack into every
# control-plane command on operator machines that only shell out to
# gcloud.  tests/test_resilience.py pins the two values equal.
RESUMABLE_EXIT_CODE = 75

DATASTORE_PLACEHOLDER = "{datastore}"

WORKLOAD_MODULES = {
    "imagenet": "distributeddeeplearning_tpu.workloads.imagenet",
    "benchmark": "distributeddeeplearning_tpu.workloads.benchmark",
    "bert": "distributeddeeplearning_tpu.workloads.bert",
    "transformer": "distributeddeeplearning_tpu.workloads.transformer",
    "experiment": "distributeddeeplearning_tpu.workloads.experiment",
}


def complete_datastore_paths(
    params: Dict[str, Any], datastore_root: str
) -> Dict[str, Any]:
    """Rewrite ``{datastore}``-templated params to the storage root.

    ``_complete_datastore`` parity (``aml_compute.py:395-403``): only string
    params containing the placeholder are touched.
    """
    root = datastore_root.rstrip("/")
    out: Dict[str, Any] = {}
    for key, value in params.items():
        if isinstance(value, str) and DATASTORE_PLACEHOLDER in value:
            out[key] = value.replace(DATASTORE_PLACEHOLDER, root)
        else:
            out[key] = value
    return out


def params_to_flags(params: Dict[str, Any]) -> List[str]:
    """Script-param dict → ``--key value`` argv (the reference passed
    ``script_params`` dicts to the estimator the same way)."""
    flags: List[str] = []
    for key, value in params.items():
        if value is None:
            continue
        flags.append(f"--{key}")
        if not isinstance(value, bool):
            flags.append(str(value))
        else:
            flags.append(str(value).lower())
    return flags


# Pod lifecycle states worth waiting out before resubmitting; stable states
# (READY, PREEMPTED, TERMINATED, absent) return to the caller immediately.
_TRANSITIONAL_POD_STATES = {
    "CREATING", "STARTING", "RESTARTING", "REPAIRING", "PROVISIONING",
    "STOPPING",
}


class Submitter:
    """Composes and executes workload launches, local and remote."""

    def __init__(
        self,
        settings,
        runner: Optional[CommandRunner] = None,
        registry: Optional[RunRegistry] = None,
    ):
        self.settings = settings
        self.runner = runner or CommandRunner()
        self.registry = registry or RunRegistry(
            settings.get("RUNS_DIR", "runs") or "runs"
        )
        self._sleep = time.sleep  # injectable for tests

    def _await_pod_ready(
        self, pod: TpuPod, *, attempts: int = 30, interval_s: float = 10.0
    ) -> Optional[str]:
        """Poll pod state through transitional phases; return the first
        stable state seen (READY, PREEMPTED, TERMINATED, None, ...).

        The preemption retry loop calls this after ``recreate()`` so the
        resubmit doesn't race a pod that is still CREATING; stable non-READY
        states return immediately — deciding what to do about them is the
        caller's policy.
        """
        state = pod.state(retries=2)
        polled = 0
        while state in _TRANSITIONAL_POD_STATES and polled < attempts:
            polled += 1
            logger.info(
                "pod %s state %s — waiting (%d/%d)",
                pod.name, state, polled, attempts,
            )
            self._sleep(interval_s)
            state = pod.state(retries=2)
        return state

    # -- composition helpers --------------------------------------------

    def _resolve_params(self, params: Dict[str, Any], mode: str) -> Dict[str, Any]:
        if mode == "remote":
            bucket = self.settings.get("GCS_BUCKET")
            if any(
                isinstance(v, str) and DATASTORE_PLACEHOLDER in v
                for v in params.values()
            ) and not bucket:
                raise ValueError(
                    "remote submit uses {datastore} paths but GCS_BUCKET is unset"
                )
            root = f"gs://{bucket}"
        else:
            root = self.settings.get("DATA_DIR", "/data")
        return complete_datastore_paths(params, root)

    def _launch_argv(
        self, workload: str, params: Dict[str, Any], python: str = "python3"
    ) -> List[str]:
        module = WORKLOAD_MODULES.get(workload)
        if module is None:
            raise ValueError(
                f"unknown workload {workload!r}; known: {sorted(WORKLOAD_MODULES)}"
            )
        if workload == "experiment" and Path("experiment.py").exists():
            # A generated project carries its own editable scaffold copy
            # (``ddlt new``); the user's file wins over the installed module.
            return [python, "experiment.py", *params_to_flags(params)]
        return [python, "-m", module, *params_to_flags(params)]

    # -- submit verbs ---------------------------------------------------

    def submit_local(
        self,
        workload: str,
        params: Dict[str, Any],
        *,
        experiment: Optional[str] = None,
        distributed: bool = False,
    ) -> Run:
        """Run the workload entry module on this host (debug path).

        ``DISTRIBUTED=False`` single-process semantics unless ``distributed``
        — the exact switch contract of ``aml_compute.py:90,117``.
        """
        params = self._resolve_params(params, "local")
        experiment = experiment or self.settings.get("EXPERIMENT_NAME", "experiment")
        run = self.registry.new_run(experiment, workload, "local", [])
        params.setdefault("tensorboard_dir", str(self.registry.tensorboard_dir(run)))
        params.setdefault("save_filepath", str(self.registry.checkpoint_dir(run)))
        params.setdefault(
            "metrics_path", str(self.registry.run_dir(run) / "metrics.jsonl")
        )
        argv = self._launch_argv(workload, params, python=sys.executable)
        run.argv = argv
        run.extra["tensorboard_dir"] = str(params["tensorboard_dir"])
        run.extra["metrics_path"] = str(params["metrics_path"])
        env = dict(os.environ)
        env["DISTRIBUTED"] = str(distributed)
        log_config = self.settings.get("LOG_CONFIG")
        if log_config:
            env["LOG_CONFIG"] = log_config
        self.registry.update(run, status="running")
        result = self.runner.run(argv, check=False, capture=False, env=env)
        self.registry.update(
            run,
            status="completed" if result.ok else "failed",
            returncode=result.returncode,
        )
        if not result.ok:
            logger.error("local run %s failed (rc=%d)", run.run_id, result.returncode)
        return run

    def submit_remote(
        self,
        workload: str,
        params: Dict[str, Any],
        *,
        experiment: Optional[str] = None,
        pod: Optional[TpuPod] = None,
        python: str = "python3",
        max_retries: Optional[int] = None,
        project_dir: Optional[str] = None,  # default: PROJECT_DIR setting
    ) -> Run:
        """Get-or-create the pod, fan the launcher out over all workers.

        ``max_retries`` (default from ``MAX_RETRIES`` setting, 0) adds the
        preemption handling both the reference and plain Horovod lack
        (SURVEY.md §5 "Failure detection… None in-repo").  Two recovery
        paths share the retry budget:

        - **resumable exit** (rc == 75, the workload runner's
          ``RESUMABLE_EXIT_CODE``): the preemption guard landed an
          emergency checkpoint and asked to be restarted — the identical
          command is resent to the SAME pod, no recreate;
        - **pod loss** (launch failed and the pod is PREEMPTED / gone /
          otherwise not READY): recreate the pod, poll its state until
          READY (``_await_pod_ready``), re-bootstrap, resend.

        Checkpoints live in the run's GCS dir and the workloads default to
        ``resume=True``, so a retried run continues from its last
        checkpointed step rather than restarting.  Every retry decision is
        recorded in the run's ``events`` audit trail.
        """
        params = self._resolve_params(params, "remote")
        experiment = experiment or self.settings.get("EXPERIMENT_NAME", "experiment")
        pod = pod or pod_from_settings(self.settings, self.runner)
        pod.create()  # idempotent get-or-create (aml_compute.py:47-71)

        run = self.registry.new_run(
            experiment,
            workload,
            "remote",
            [],
            tpu_name=pod.name,
            tpu_type=pod.accelerator_type,
        )
        bucket = self.settings.get("GCS_BUCKET")
        if bucket:
            remote_root = f"gs://{bucket}/runs/{experiment}/{run.run_id}"
            params.setdefault("tensorboard_dir", f"{remote_root}/tb")
            params.setdefault("save_filepath", f"{remote_root}/ckpt")
            params.setdefault("metrics_path", f"{remote_root}/metrics.jsonl")
        argv = self._launch_argv(workload, params, python=python)
        run.argv = argv
        if "tensorboard_dir" in params:
            # ``ddlt tensorboard --run ID`` resolves this — a gs:// dir
            # streams a RUNNING remote job's scalars (the reference's
            # azureml.tensorboard streaming role, aml_compute.py:567-635).
            run.extra["tensorboard_dir"] = str(params["tensorboard_dir"])
        if "metrics_path" in params:
            run.extra["metrics_path"] = str(params["metrics_path"])

        env = {"DISTRIBUTED": "True"}
        log_config = self.settings.get("LOG_CONFIG")
        if log_config:
            env["LOG_CONFIG"] = log_config

        import shlex

        command = shlex.join(argv)
        if max_retries is None:
            max_retries = int(self.settings.get("MAX_RETRIES", "0") or 0)
        # Live output: the fan-out's stdout/stderr streams to the operator's
        # console AND <run_dir>/log.txt as the job runs (the reference's
        # wait_for_completion(show_output=True), aml_compute.py:391-392) —
        # retries append to the same log.
        log_path = str(self.registry.run_dir(run) / "log.txt")
        run.extra["log_path"] = log_path
        self.registry.update(run, status="running")
        result = pod.ssh(
            command, worker="all", env=env, check=False, stream_to=log_path
        )
        attempts = 1
        while not result.ok and attempts <= max_retries:
            if result.returncode == RESUMABLE_EXIT_CODE:
                # The workload's preemption guard checkpointed and exited
                # resumable: the pod is (still) usable, the run continues
                # from the emergency checkpoint — resend, don't recreate.
                logger.warning(
                    "run %s attempt %d exited resumable (rc=%d) — "
                    "resubmitting to the same pod (%d/%d)",
                    run.run_id, attempts, result.returncode,
                    attempts, max_retries,
                )
                self.registry.append_event(
                    run,
                    f"attempt {attempts}: resumable exit "
                    f"(rc={RESUMABLE_EXIT_CODE}); resubmitting",
                )
                result = pod.ssh(
                    command, worker="all", env=env, check=False,
                    stream_to=log_path,
                )
                attempts += 1
                continue
            state = pod.state(retries=2)
            if state == "READY":
                # The pod is healthy: the failure is the workload's, not a
                # preemption — retrying the same code would fail the same way.
                logger.error(
                    "run %s failed with pod READY; not retrying", run.run_id
                )
                self.registry.append_event(
                    run, f"attempt {attempts}: failed with pod READY; "
                    "not retrying"
                )
                break
            logger.warning(
                "run %s attempt %d failed (pod state %s) — recreating pod "
                "and resubmitting (%d/%d)",
                run.run_id, attempts, state, attempts, max_retries,
            )
            self.registry.append_event(
                run, f"attempt {attempts}: pod state {state}; recreating"
            )
            ship_dir = project_dir or self.settings.get("PROJECT_DIR", "")
            if not ship_dir or ship_dir == ".":
                # No recorded source tree: shipping the control process's cwd
                # would scp + pip-install whatever happens to be there.
                logger.error(
                    "run %s: cannot re-bootstrap after preemption — "
                    "PROJECT_DIR is unset (run `ddlt tpu bootstrap <dir>` "
                    "first); giving up", run.run_id,
                )
                break
            try:
                pod.recreate()
                ready_state = self._await_pod_ready(pod)
                if ready_state != "READY":
                    # Advisory: a queued-resource recreate may still be
                    # WAITING_FOR_RESOURCES.  Resubmit anyway — the SSH
                    # failure consumes the bounded retry budget, so this
                    # cannot loop forever.
                    logger.warning(
                        "run %s: recreated pod state is %s (not READY); "
                        "resubmitting anyway", run.run_id, ready_state,
                    )
                # Fresh VMs have nothing installed: re-run the bootstrap
                # (scp + pip install) or the identical resubmit dies on
                # import.  PROJECT_DIR names the source tree to ship.
                self.bootstrap_pod(ship_dir, pod=pod)
            except Exception as exc:  # capacity stockout, transient gcloud
                # The run must never be stranded in "running": record the
                # failure and stop retrying.
                logger.error(
                    "run %s: pod recreate/bootstrap failed (%s); giving up",
                    run.run_id, exc,
                )
                self.registry.append_event(
                    run, f"attempt {attempts}: recreate/bootstrap failed "
                    f"({exc}); giving up"
                )
                break
            self.registry.append_event(
                run, f"attempt {attempts}: pod recreated; resubmitting"
            )
            result = pod.ssh(
                command, worker="all", env=env, check=False, stream_to=log_path
            )
            attempts += 1
        if not result.ok:
            tail = (result.stderr or result.stdout or "").strip()[-2000:]
            logger.error(
                "remote run %s failed (rc=%d)%s",
                run.run_id,
                result.returncode,
                f":\n{tail}" if tail else "",
            )
        self.registry.update(
            run,
            status="completed" if result.ok else "failed",
            returncode=result.returncode,
        )
        return run

    def poll_run(
        self,
        experiment: str,
        run_id: str,
        *,
        pod: Optional[TpuPod] = None,
    ) -> Run:
        """Refresh a run's registry status by probing the pod.

        The role of the reference's service-side Run status (AML tracks it;
        ``tasks.py`` ``runs`` lists it).  Here the submit process itself
        normally flips the status when the synchronous fan-out returns — but
        if the control process died (laptop closed, tmux killed), the run is
        stranded in ``running``.  The poll probes EVERY worker for the
        workload's launcher module and decides by quorum: any live launcher
        keeps the run ``running`` (a transiently unreachable worker 0 must
        not fail a healthy pod job), and the flip to ``failed`` requires a
        confirmed-dead majority — per-worker liveness lands in
        ``run.extra['poll_workers']`` either way.  Completed/failed runs are
        returned untouched.
        """
        import re as _re

        run = self.registry.find(experiment, run_id)
        if run is None:
            raise ValueError(f"unknown run {experiment}/{run_id}")
        if run.status != "running" or run.mode != "remote":
            return run
        module = WORKLOAD_MODULES.get(run.workload, run.workload)
        pod = pod or pod_from_settings(self.settings, self.runner)
        state = pod.state()
        if state != "READY":
            run.extra["poll"] = f"pod state {state}"
            self.registry.update(run, status="failed")
            return run
        # Bracket the pattern's first char so pgrep cannot match the probe's
        # own wrapping shell (whose cmdline also contains the module name);
        # ERE-escape the rest — the module path's dots would otherwise match
        # any character and could report an unrelated process as ALIVE.
        pattern = f"[{module[0]}]{_re.escape(module[1:])}"
        probe = pod.ssh(
            f"pgrep -f '{pattern}' >/dev/null && echo ALIVE || echo DEAD",
            worker="all",
            check=False,
        )
        out = probe.stdout or ""
        alive = out.count("ALIVE")
        dead = out.count("DEAD")
        expected = pod.topology["hosts"]
        run.extra["poll_workers"] = {
            "alive": alive, "dead": dead, "expected": expected,
        }
        # Persist the liveness snapshot on EVERY outcome (update() below
        # rewrites the record only on the failed flip).
        self.registry.update(run, status=run.status)
        if alive:
            if alive + dead < expected:
                logger.warning(
                    "run %s: %d/%d workers unreachable during poll; launcher "
                    "alive on %d", run.run_id, expected - alive - dead,
                    expected, alive,
                )
            return run  # genuinely still training somewhere
        if dead * 2 <= expected:
            # No confirmed-dead majority — too few workers answered DEAD
            # (covers the all-probes-failed case, where dead == 0).  A
            # half-blind probe says nothing about the workload; never flip
            # a live run on it.
            logger.warning(
                "run %s: status probe inconclusive (rc=%d, %d/%d workers "
                "answered); leaving status as-is",
                run.run_id, probe.returncode, alive + dead, expected,
            )
            return run
        # Confirmed: a majority of workers (and no minority dissent) report
        # no launcher process.  The run ended without this registry hearing
        # about it.  Without an exit code the safe claim is "failed" — a
        # completed run's submit process would have recorded completion.
        run.extra["poll"] = f"no launcher process on {dead}/{expected} workers"
        self.registry.update(run, status="failed")
        return run

    def bootstrap_pod(
        self,
        project_dir: str = ".",
        *,
        pod: Optional[TpuPod] = None,
        remote_dir: str = "~/ddlt",
    ) -> TpuPod:
        """Distribute the framework to every pod worker and install it.

        The role of the reference's AML environment build (conda spec +
        source_directory upload, ``aml_compute.py:354-393``): get-or-create
        the pod, copy the project, pip-install on each worker.
        """
        pod = pod or pod_from_settings(self.settings, self.runner)
        pod.create()
        # Remember the tree that was shipped: preemption retries re-bootstrap
        # from PROJECT_DIR, which must match what the operator bootstrapped
        # with (not whatever cwd a later submit happens to run from).
        self.settings.persist("PROJECT_DIR", str(Path(project_dir).absolute()))
        pod.scp(str(Path(project_dir)), remote_dir, worker="all")
        install = f"pip install -q -e {remote_dir}"
        if (Path(project_dir) / "envs" / "requirements-tpu.txt").exists():
            # Pin the worker runtime (envs/requirements-tpu.txt — the
            # environment_gpu.yml role) before installing the framework.
            install = (
                f"pip install -q -r {remote_dir}/envs/requirements-tpu.txt"
                f" && {install}"
            )
        pod.ssh(install, worker="all")
        return pod
