"""Control plane: cloud provisioning, storage, job submission, run tracking.

The TPU-native replacement for the reference's L3 cloud-resource layer
(``control/src/aml_compute.py``) and L4 data-plane scripts
(``scripts/{storage,image,tfrecords}.py``).  AML clusters become TPU pods
(gcloud TPU-VM API), blob storage becomes GCS, the MPI launcher becomes a
per-host SSH fan-out with the JAX runtime handling rendezvous, and AML run
tracking becomes a local JSON run registry.

Every cloud interaction goes through :class:`CommandRunner`, so tests (and
``--dry-run``) can observe the exact composed command lines without any cloud
access — the same way the reference shells out to ``az``/``azcopy``.
"""

from distributeddeeplearning_tpu.control.command import (
    CommandError,
    CommandResult,
    CommandRunner,
)
from distributeddeeplearning_tpu.control.runs import RunRegistry
from distributeddeeplearning_tpu.control.storage import GcsStorage
from distributeddeeplearning_tpu.control.submit import Submitter, complete_datastore_paths
from distributeddeeplearning_tpu.control.tpu import TpuPod

__all__ = [
    "CommandError",
    "CommandResult",
    "CommandRunner",
    "GcsStorage",
    "RunRegistry",
    "Submitter",
    "TpuPod",
    "complete_datastore_paths",
]
