"""Run / experiment tracking — a local JSON registry.

The reference delegates run tracking to the AML service: every submit creates
a Run, ``inv runs`` lists the last N per experiment, ``inv experiments``
lists experiments, and ``inv tensorboard`` streams the logs of running jobs
(``tasks.py:120-169``, ``aml_compute.py:567-635``).  There is no managed
service in the loop here, so the registry is a directory tree the operator
owns:

    <runs_root>/<experiment>/<run_id>/run.json   — submit metadata + status
    <runs_root>/<experiment>/<run_id>/tb/        — TensorBoard event files
    <runs_root>/<experiment>/<run_id>/ckpt/      — checkpoints

Both local and remote submits register here; the TensorBoard verb points at
an experiment's (or run's) ``tb`` dirs.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import itertools
import json
import os
from pathlib import Path
from typing import Dict, List, Optional

RUN_FILE = "run.json"


@dataclasses.dataclass
class Run:
    run_id: str
    experiment: str
    workload: str
    mode: str  # local | remote
    argv: List[str]
    status: str = "queued"  # queued | running | completed | failed
    created_at: str = ""
    finished_at: str = ""
    returncode: Optional[int] = None
    extra: Dict[str, str] = dataclasses.field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)


class RunRegistry:
    def __init__(self, root: os.PathLike | str = "runs"):
        self.root = Path(root)

    def _run_dir(self, experiment: str, run_id: str) -> Path:
        return self.root / experiment / run_id

    def new_run(
        self,
        experiment: str,
        workload: str,
        mode: str,
        argv: List[str],
        **extra: str,
    ) -> Run:
        stamp = _dt.datetime.now().strftime("%Y%m%d-%H%M%S")
        run_id = stamp
        for i in itertools.count(1):
            if not self._run_dir(experiment, run_id).exists():
                break
            run_id = f"{stamp}-{i}"
        run = Run(
            run_id=run_id,
            experiment=experiment,
            workload=workload,
            mode=mode,
            argv=[str(a) for a in argv],
            created_at=_dt.datetime.now().isoformat(timespec="seconds"),
            extra=dict(extra),
        )
        self._write(run)
        return run

    def _write(self, run: Run) -> None:
        run_dir = self._run_dir(run.experiment, run.run_id)
        run_dir.mkdir(parents=True, exist_ok=True)
        (run_dir / RUN_FILE).write_text(run.to_json())

    def update(self, run: Run, *, status: str, returncode: Optional[int] = None) -> None:
        run.status = status
        if returncode is not None:
            run.returncode = returncode
        if status in ("completed", "failed"):
            run.finished_at = _dt.datetime.now().isoformat(timespec="seconds")
        self._write(run)

    def append_event(self, run: Run, message: str) -> None:
        """Append a timestamped lifecycle event to ``run.extra['events']``.

        The preemption/restart audit trail: every recreate, resubmit and
        resumable-exit restart lands here so ``ddlt runs --run ID`` can
        answer "what happened to this run" after the fact.
        """
        stamp = _dt.datetime.now().isoformat(timespec="seconds")
        events = run.extra.setdefault("events", [])
        events.append(f"{stamp} {message}")
        self._write(run)

    def run_dir(self, run: Run) -> Path:
        return self._run_dir(run.experiment, run.run_id)

    def run_dir_for(self, experiment: str, run_id: str) -> Path:
        return self._run_dir(experiment, run_id)

    def tensorboard_dir(self, run: Run) -> Path:
        return self.run_dir(run) / "tb"

    def checkpoint_dir(self, run: Run) -> Path:
        return self.run_dir(run) / "ckpt"

    @staticmethod
    def _load(meta: Path) -> Optional[Run]:
        if not meta.exists():
            return None
        try:
            payload = json.loads(meta.read_text())
        except json.JSONDecodeError:
            return None
        known = {f.name for f in dataclasses.fields(Run)}
        return Run(**{k: v for k, v in payload.items() if k in known})

    def find(self, experiment: str, run_id: str) -> Optional[Run]:
        return self._load(self._run_dir(experiment, run_id) / RUN_FILE)

    # -- listing verbs (``inv runs`` / ``inv experiments`` parity) -------

    def experiments(self) -> List[str]:
        if not self.root.exists():
            return []
        return sorted(d.name for d in self.root.iterdir() if d.is_dir())

    def runs(
        self, experiment: str, last: int = 10, status: Optional[str] = None
    ) -> List[Run]:
        exp_dir = self.root / experiment
        if not exp_dir.exists():
            return []
        loaded: List[Run] = []
        for run_dir in sorted(exp_dir.iterdir(), reverse=True):
            run = self._load(run_dir / RUN_FILE)
            if run is None:
                continue
            if status is not None and run.status != status:
                continue
            loaded.append(run)
            if len(loaded) >= last:
                break
        return loaded

    def format_runs(
        self, experiment: str, last: int = 10, status: Optional[str] = None
    ) -> str:
        """Tabulated listing (``az ml run list -o table`` role); ``status``
        filters — ``status="running"`` is the live view (``_select_runs``
        Running-filter role, ``aml_compute.py:603-617``)."""
        rows = self.runs(experiment, last, status=status)
        if not rows:
            if status is not None:
                return f"no {status} runs for experiment {experiment!r}"
            return f"no runs for experiment {experiment!r}"
        header = f"{'RUN_ID':<22}{'WORKLOAD':<14}{'MODE':<8}{'STATUS':<11}{'CREATED':<21}"
        lines = [header, "-" * len(header)]
        for r in rows:
            lines.append(
                f"{r.run_id:<22}{r.workload:<14}{r.mode:<8}{r.status:<11}{r.created_at:<21}"
            )
        return "\n".join(lines)
