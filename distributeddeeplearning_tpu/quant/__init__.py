"""Int8 quantization subsystem: weight PTQ + int8 KV cache for serving.

The serve stack pages KV HBM per token (``serve/kv_cache.py``) but every
byte it holds — weights and KV pages — is full precision, so cache
capacity (and therefore admission, batch occupancy, tokens/HBM-byte) is
the binding constraint on traffic.  This package is the standard next
lever on TPU-class hardware (arxiv 2605.25645, 1909.09756): store int8,
compute the matmuls in int8 with f32 rescale, dequantize KV inside the
fused attention programs.

- :mod:`quant.qtensor` — the :class:`QTensor` registered pytree (int8
  values + f32 per-channel/per-block scales), ``quantize``/``dequantize``,
  and ``qdot``: dynamic per-row activation quantization feeding an int8
  ``lax.dot_general`` (int32 accumulation) with an f32 rescale by the
  product of activation and weight scales; plus the per-position-per-head
  KV quantization helpers the cache layouts use.
- :mod:`quant.calibrate` — post-training weight quantization of the
  ``pipelined_transformer`` param pytree (absmax and percentile
  observers), with an optional calibration pass over a handful of prompts
  that reports logit MAE / greedy agreement vs the f32 model.

Entry points: ``ddlt serve --quantize-kv int8 --quantize-weights int8
--calib-prompts N``, ``Checkpointer.restore_params(quantize_weights=
"int8")``, and ``bench.py --quant`` (the ``QUANT_*.json`` artifact).
"""

from distributeddeeplearning_tpu.quant.qtensor import (
    QTensor,
    dequantize,
    dequantize_kv,
    qdot,
    qmatmul,
    quantize,
    quantize_kv,
)
from distributeddeeplearning_tpu.quant.calibrate import (
    AbsmaxObserver,
    CalibrationReport,
    PercentileObserver,
    calibrate_params,
    params_dtype,
    quantize_params,
)

__all__ = [
    "QTensor",
    "quantize",
    "dequantize",
    "qdot",
    "qmatmul",
    "quantize_kv",
    "dequantize_kv",
    "AbsmaxObserver",
    "PercentileObserver",
    "CalibrationReport",
    "calibrate_params",
    "quantize_params",
    "params_dtype",
]
