"""Post-training weight quantization of the transformer param pytree.

PTQ for serving: the matmul weights of a trained ``pipelined_transformer``
checkpoint (``blocks.{qkv,proj,w_in,w_out}`` and ``head``) become
:class:`~distributeddeeplearning_tpu.quant.qtensor.QTensor` leaves with
per-output-channel f32 scales; embeddings, position table and layer-norm
gains stay f32 (they are lookups/elementwise — no int8 matmul to win, and
they are the quantization-sensitive leaves every production int8 recipe
keeps high-precision).

Two scale observers:

- **absmax** — scale = max|w| per channel: exact range coverage, one
  outlier row can waste the grid;
- **percentile** — scale = P-th percentile of |w| per channel: clips the
  outlier tail (saturating those weights) so the 8-bit grid spends its
  codes on the bulk of the distribution.

``calibrate_params`` additionally runs a handful of calibration prompts
through the f32 AND quantized model and reports per-position logit MAE and
greedy-token agreement — the go/no-go numbers a deployment reads before
flipping traffic to the quantized path (``ddlt serve --quantize-weights
int8 --calib-prompts N`` prints them; ``bench.py --quant`` archives them).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from distributeddeeplearning_tpu.quant.qtensor import QTensor, quantize

PyTree = Any

#: Block-stack matmul leaves that quantize (contraction dim at -2 after
#: the leading [L] stack dim — the negative-axis convention makes the
#: same QTensor metadata valid before and after the layer scan slices L).
BLOCK_MATMUL_LEAVES = ("qkv", "proj", "w_in", "w_out")


class AbsmaxObserver:
    """scale = max|w| per channel — the default, exact-range observer."""

    def __call__(self, x: jax.Array, axis: int) -> jax.Array:
        return jnp.max(jnp.abs(x), axis=axis, keepdims=True)


class PercentileObserver:
    """scale = P-th percentile of |w| per channel: outliers saturate,
    the bulk of the distribution gets the finer grid."""

    def __init__(self, percentile: float = 99.9):
        if not 0.0 < percentile <= 100.0:
            raise ValueError(f"percentile must be in (0, 100], got {percentile}")
        self.percentile = percentile

    def __call__(self, x: jax.Array, axis: int) -> jax.Array:
        return jnp.percentile(
            jnp.abs(x), self.percentile, axis=axis, keepdims=True
        )


def _make_observer(method: str, percentile: float):
    if method == "absmax":
        return AbsmaxObserver()
    if method == "percentile":
        return PercentileObserver(percentile)
    raise ValueError(f"unknown observer method {method!r}")


def quantize_params(
    params: PyTree,
    *,
    method: str = "absmax",
    percentile: float = 99.9,
    block: Optional[int] = None,
) -> PyTree:
    """Quantize the matmul weights of a ``pipelined_transformer`` params
    pytree to int8 QTensors (per-output-channel scales, ``axis=-2``);
    embed/pos/ln leaves pass through untouched.  Idempotent-safe: already-
    quantized leaves raise (re-quantizing int8 codes would double the
    error silently)."""
    observer = _make_observer(method, percentile)

    def q(w):
        if isinstance(w, QTensor):
            raise ValueError("params are already quantized")
        return quantize(w, axis=-2, block=block, observer=observer)

    out = dict(params)
    out["blocks"] = dict(params["blocks"])
    for name in BLOCK_MATMUL_LEAVES:
        out["blocks"][name] = q(params["blocks"][name])
    out["head"] = q(params["head"])
    return out


def abstract_quantized_params(params_abs: PyTree) -> PyTree:
    """ShapeDtypeStruct skeleton of :func:`quantize_params`' output with
    no quantization math run — ``jax.eval_shape`` over the PTQ transform.

    The static-analysis program audit (``analysis/program_audit.py``)
    traces the int8-weight serving programs on exactly this skeleton, so
    the audited QTensor layout (values int8, keepdims f32 scales at the
    negative-axis convention) can never drift from what ``quantize_params``
    actually produces."""
    return jax.eval_shape(quantize_params, params_abs)


def params_dtype(params: PyTree) -> str:
    """``"int8"`` when any matmul leaf is a QTensor, else the param dtype
    name — the ``weights_dtype`` provenance field of ServeReport."""
    leaves = jax.tree_util.tree_leaves(
        params, is_leaf=lambda x: isinstance(x, QTensor)
    )
    if any(isinstance(leaf, QTensor) for leaf in leaves):
        return "int8"
    return str(jax.tree_util.tree_leaves(params)[0].dtype)


@dataclasses.dataclass
class CalibrationReport:
    """Quantized-vs-f32 fidelity over the calibration prompts."""

    num_prompts: int
    num_positions: int  # real (unpadded) positions compared
    logit_mae: float  # mean |logit_q - logit_f32| over real positions
    logit_mae_max: float  # worst single position's mean-abs-error
    greedy_agreement: float  # fraction of positions with equal argmax
    method: str
    percentile: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def calibrate_params(
    params: PyTree,
    prompts: Sequence[Sequence[int]],
    *,
    num_heads: int,
    method: str = "absmax",
    percentile: float = 99.9,
    block: Optional[int] = None,
    attention: str = "dense",
):
    """Quantize the weights, then measure them: run each calibration
    prompt through the f32 and the quantized forward and compare logits
    position-by-position.

    Prompts are padded to one rectangular batch (a single compile) and
    only REAL positions enter the stats.  Returns ``(qparams, report)``.
    """
    from distributeddeeplearning_tpu.models.pipelined_transformer import (
        forward,
    )

    if not prompts:
        raise ValueError("calibration needs at least one prompt")
    if any(len(p) < 1 for p in prompts):
        raise ValueError("empty calibration prompt")
    qparams = quantize_params(
        params, method=method, percentile=percentile, block=block
    )

    lens = [len(p) for p in prompts]
    S = max(lens)
    tokens = np.zeros((len(prompts), S), np.int32)
    for i, p in enumerate(prompts):
        tokens[i, : len(p)] = np.asarray(p, np.int32)
    tokens = jnp.asarray(tokens)

    fwd = jax.jit(
        lambda ps, t: forward(ps, t, num_heads=num_heads, attention=attention)
    )
    logits_f = np.asarray(fwd(params, tokens), np.float32)
    logits_q = np.asarray(fwd(qparams, tokens), np.float32)

    maes: List[float] = []
    agree = 0
    total = 0
    for i, n in enumerate(lens):
        err = np.abs(logits_q[i, :n] - logits_f[i, :n])  # [n, vocab]
        maes.extend(err.mean(axis=-1).tolist())
        agree += int(
            (logits_q[i, :n].argmax(-1) == logits_f[i, :n].argmax(-1)).sum()
        )
        total += n
    report = CalibrationReport(
        num_prompts=len(prompts),
        num_positions=total,
        logit_mae=round(float(np.mean(maes)), 6),
        logit_mae_max=round(float(np.max(maes)), 6),
        greedy_agreement=round(agree / total, 4),
        method=method,
        percentile=percentile if method == "percentile" else None,
    )
    return qparams, report
