"""QTensor: an int8-values + f32-scales pytree, and the int8 matmul.

Symmetric int8 quantization throughout: ``x ≈ values * scales`` with
``values`` in [-127, 127] (the -128 code is left unused so the grid is
symmetric and ``|dequant| <= amax`` exactly).  Scales are stored with
``keepdims`` so dequantization is a plain broadcast multiply, and the
quantized axis is addressed NEGATIVELY (``axis=-2`` for a ``[..., K, N]``
weight contracted over K) so a stacked ``[L, K, N]`` leaf scanned by
``lax.scan`` yields per-layer ``[K, N]`` QTensors whose static metadata
is still correct — the property that lets a quantized params pytree flow
through the existing scan-over-layers forwards unchanged.

``qdot`` is the compute path: activations are quantized dynamically
per-row (per-token absmax over the contraction dim — the W8A8 scheme
hardware int8 units want), the matmul runs as an int8×int8
``lax.dot_general`` with ``preferred_element_type=int32`` (no overflow:
127·127·K fits int32 for any realistic K), and the int32 accumulator is
rescaled once by the OUTER PRODUCT of activation and weight scales.
Block-quantized or non-standard-axis weights fall back to
dequantize-then-matmul (correct, just not int8 compute).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

#: Largest int8 code used; -128 stays unused (symmetric grid).
QMAX = 127.0
#: Floor on scales so an all-zero channel divides cleanly to zeros.
EPS = 1e-12


@dataclasses.dataclass(frozen=True)
class QTensor:
    """Quantized tensor: ``dequant = values.astype(f32) * scales``.

    ``values``: int8; ``scales``: f32 with keepdims shape (broadcastable
    against ``values``); ``axis``: the NEGATIVE index of the reduced
    (contraction) dim the scales were computed over; ``block``: tokens
    per scale block along ``axis`` (None = whole-axis per-channel).
    """

    values: jax.Array
    scales: jax.Array
    axis: int = -2
    block: Optional[int] = None

    # array-protocol conveniences so shape-probing code (engine dim
    # validation, CLI vocab checks) works on quantized leaves unchanged
    @property
    def shape(self) -> Tuple[int, ...]:
        return self.values.shape

    @property
    def ndim(self) -> int:
        return self.values.ndim

    @property
    def dtype(self):
        return self.values.dtype

    def __repr__(self) -> str:  # keep pytree dumps readable
        return (
            f"QTensor(int8{list(self.values.shape)}, "
            f"scales{list(self.scales.shape)}, axis={self.axis}, "
            f"block={self.block})"
        )


def _flatten(qt: QTensor):
    return (qt.values, qt.scales), (qt.axis, qt.block)


def _flatten_with_keys(qt: QTensor):
    # Named child keys (".values" / ".scales") so path-walking consumers —
    # the partition-rule layout engine in ``parallel.sharding`` resolves
    # leaves by name — see readable paths instead of flat indices.
    return (
        (jax.tree_util.GetAttrKey("values"), qt.values),
        (jax.tree_util.GetAttrKey("scales"), qt.scales),
    ), (qt.axis, qt.block)


def _unflatten(aux, children) -> QTensor:
    values, scales = children
    axis, block = aux
    return QTensor(values, scales, axis, block)


jax.tree_util.register_pytree_with_keys(
    QTensor, _flatten_with_keys, _unflatten, flatten_func=_flatten
)


def _amax(x: jax.Array, axis: int, observer=None) -> jax.Array:
    """Per-channel max-abs over ``axis`` (keepdims); ``observer``
    overrides the reduction (``calibrate.PercentileObserver`` clips
    outliers so the grid spends its 8 bits on the bulk)."""
    if observer is not None:
        return observer(x, axis)
    return jnp.max(jnp.abs(x), axis=axis, keepdims=True)


def quantize(
    x: jax.Array,
    *,
    axis: int = -2,
    block: Optional[int] = None,
    observer=None,
) -> QTensor:
    """Quantize ``x`` to int8 with per-channel (or per-block) f32 scales.

    ``axis`` is the reduced dim, addressed negatively (default -2: the
    contraction dim of a ``[..., K, N]`` matmul weight, i.e. per-OUTPUT-
    channel scales).  ``block`` splits that dim into ``block``-sized
    groups with one scale each — finer grid for weights whose channel
    range is dominated by a few rows.
    """
    if axis >= 0:
        axis = axis - x.ndim  # normalize to the negative convention
    x = x.astype(jnp.float32)
    if block is not None:
        K = x.shape[axis]
        if K % block:
            raise ValueError(f"block {block} must divide dim {K} (axis {axis})")
        # [..., K, ...] -> [..., K//block, block, ...]; scale per block
        split = x.ndim + axis
        xb = x.reshape(*x.shape[:split], K // block, block, *x.shape[split + 1:])
        # splitting K -> (K//block, block) leaves the block dim at the
        # same NEGATIVE index `axis` pointed at (the group dim lands one
        # position earlier), so the reduction axis is unchanged
        amax = _amax(xb, axis, observer)
        scales = jnp.maximum(amax, EPS) / QMAX
        values = jnp.clip(jnp.round(xb / scales), -QMAX, QMAX)
        return QTensor(
            values.reshape(x.shape).astype(jnp.int8),
            scales,
            axis,
            block,
        )
    amax = _amax(x, axis, observer)
    scales = jnp.maximum(amax, EPS) / QMAX
    values = jnp.clip(jnp.round(x / scales), -QMAX, QMAX).astype(jnp.int8)
    return QTensor(values, scales, axis, None)


def dequantize(qt: QTensor, dtype=jnp.float32) -> jax.Array:
    """``values * scales`` back to ``dtype`` (exact for the stored grid)."""
    v = qt.values.astype(jnp.float32)
    if qt.block is not None:
        axis = qt.axis
        split = v.ndim + axis
        K = v.shape[axis]
        vb = v.reshape(
            *v.shape[:split], K // qt.block, qt.block, *v.shape[split + 1:]
        )
        return (vb * qt.scales).reshape(v.shape).astype(dtype)
    return (v * qt.scales).astype(dtype)


def qdot(x: jax.Array, qt: QTensor) -> jax.Array:
    """``x @ qt`` with int8 compute: ``x [..., K] @ w [K, N] -> [..., N]``.

    Activations quantize dynamically per row (absmax over K — one scale
    per token, following the separate-activation/weight-scale scheme of
    production int8 serving stacks), the contraction runs int8×int8 with
    int32 accumulation, and ONE f32 multiply applies
    ``a_scale ⊗ w_scale``.  Non-2D / block-quantized / nonstandard-axis
    weights take the dequantize fallback — same math, f32 compute.
    """
    if qt.values.ndim != 2 or qt.axis != -2 or qt.block is not None:
        return x @ dequantize(qt, x.dtype)
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    a_scale = jnp.maximum(amax, EPS) / QMAX  # [..., 1]
    xq = jnp.clip(
        jnp.round(x.astype(jnp.float32) / a_scale), -QMAX, QMAX
    ).astype(jnp.int8)
    acc = jax.lax.dot_general(
        xq,
        qt.values,
        dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [..., N] int32
    w_scale = qt.scales.reshape(-1)  # [N] (keepdims [1, N] flattened)
    return (acc.astype(jnp.float32) * a_scale * w_scale).astype(x.dtype)


def qmatmul(x: jax.Array, w) -> jax.Array:
    """The matmul dispatch the model forwards use: int8 path for QTensor
    weights, plain ``@`` for everything else — ONE call site per matmul,
    so an f32 and a quantized params pytree run the identical program
    structure."""
    if isinstance(w, QTensor):
        return qdot(x, w)
    return x @ w


# --------------------------------------------------------------------------
# KV-cache quantization: per-position-per-head scales.
#
# KV pages are written incrementally (one token per decode step, one chunk
# per prefill step), so the scale granularity must be at most one WRITE:
# a page-granular scale would need requantizing the whole page on every
# token append (growing the scale re-decodes every earlier int8 code to a
# larger value — lossy in exactly the positions attention re-reads).  One
# f32 scale per (position, head) over the head_dim vector keeps every
# write independent: overhead 4 bytes per head-position against head_dim
# int8 bytes (hd=64 → 6.25%; total int8 KV = 26.6% of f32).
# --------------------------------------------------------------------------


def quantized_cache(cache) -> bool:
    """True when a KV-cache pytree carries the int8 layout's scale leaves
    (``{"k", "v", "k_scale", "v_scale"}``) — THE layout predicate, shared
    by the model forwards and the serve cache accounting so the two can
    never disagree about what counts as quantized."""
    return "k_scale" in cache


def quantize_kv(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Quantize K/V vectors ``[..., h, hd] -> (int8 [..., h, hd],
    f32 scales [..., h])`` — one scale per head per position."""
    x = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=-1)
    scale = jnp.maximum(amax, EPS) / QMAX  # [..., h]
    values = jnp.clip(jnp.round(x / scale[..., None]), -QMAX, QMAX)
    return values.astype(jnp.int8), scale


def dequantize_kv(
    values: jax.Array, scale: jax.Array, dtype=jnp.float32
) -> jax.Array:
    """``[..., h, hd] int8 * [..., h] -> [..., h, hd]`` in ``dtype`` —
    the multiply XLA fuses into the attention einsum that consumes it."""
    return (values.astype(jnp.float32) * scale[..., None]).astype(dtype)
