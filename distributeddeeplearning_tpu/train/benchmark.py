"""Synthetic throughput benchmark harness.

Parity with ``PyTorch_benchmark/src/pytorch_synthetic_benchmark.py:51-126``:
N warmup batches, then ``num_iters`` timed iterations of ``num_batches_per_iter``
steps each; report img/sec mean ± 1.96σ per chip and total = world × mean.
Differences are TPU-native, not cosmetic:

- the timed unit is a **jitted train step over the mesh** — the gradient
  all-reduce rides ICI inside the XLA program, so "img/sec" includes the
  collective exactly as the reference's timed ``optimizer.step()`` includes
  the NCCL allreduce;
- each timing window is bounded by a device-to-host fetch of a step's loss
  scalar (JAX dispatch is async; a data-dependent fetch is the sync that
  holds on every PJRT backend, including tunneled remote devices where
  ``block_until_ready`` has been observed to return early) — and the fetch
  for window *i* happens only after window *i+1*'s steps are already
  dispatched, so the device never drains between windows and the D2H
  round-trip latency (~100 ms on a tunneled backend — a 5-10% phantom tax
  on a 2 s window if the device sat idle during it) cancels out of the
  window-to-window deltas.  This is exactly the overlap a real training
  loop gets from reading metrics one step behind the computation;
- one fixed device-resident batch, donated state — steady-state HBM traffic
  only.
"""

from __future__ import annotations

import dataclasses
import statistics
import time
from typing import Callable, Dict, List, Optional

import jax

from distributeddeeplearning_tpu.parallel.mesh import world_size


@dataclasses.dataclass
class BenchmarkResult:
    model: str
    batch_size_per_chip: int
    num_devices: int
    img_sec_per_chip_mean: float
    img_sec_per_chip_ci95: float
    img_sec_total: float
    iter_times_s: List[float]

    def summary_lines(self) -> List[str]:
        # Report shape parity: pytorch_synthetic_benchmark.py:119-126
        return [
            f"Model: {self.model}",
            f"Batch size: {self.batch_size_per_chip} per chip",
            f"Number of chips: {self.num_devices}",
            f"Img/sec per chip: {self.img_sec_per_chip_mean:.1f} "
            f"+-{self.img_sec_per_chip_ci95:.1f}",
            f"Total img/sec on {self.num_devices} chip(s): "
            f"{self.img_sec_total:.1f} "
            f"+-{self.img_sec_per_chip_ci95 * self.num_devices:.1f}",
        ]


def _windowed_benchmark(
    step_fn: Callable,
    state,
    next_batch: Callable[[], object],
    *,
    model_name: str,
    batch_size_per_chip: int,
    num_devices: int,
    num_warmup_batches: int,
    num_iters: int,
    num_batches_per_iter: int,
    log: Optional[Callable[[str], None]],
    label: str,
) -> BenchmarkResult:
    """Shared warmup + overlapped-window timing core.

    Overlapped windows: dispatch window i+1 BEFORE fetching window i's
    sync scalar.  t[i] = host time window i's last step was observed
    complete; successive deltas subtract the (constant) D2H latency away
    and the device stream never drains, so the deltas measure pure device
    throughput — the number a jax.profiler trace reports.
    """
    global_batch = batch_size_per_chip * num_devices

    if log:
        log(f"Running {label}warmup ({num_warmup_batches} batches)...")
    metrics = None
    for _ in range(num_warmup_batches):
        state, metrics = step_fn(state, next_batch())
    if metrics is not None:
        float(metrics["loss"])  # force the dispatched chain to completion

    if log:
        log(
            f"Running {label}benchmark ({num_iters} iters x "
            f"{num_batches_per_iter} batches)..."
        )
    img_secs: List[float] = []
    iter_times: List[float] = []
    # num_iters + 1 windows are dispatched; the FIRST is an unmeasured
    # priming window — the warmup's blocking fetch drained the device, so
    # window 0 uniquely pays the pipeline-refill RTT before the device
    # resumes.  Timestamps start at window 0's fetch-completion; every
    # delta after that is pure device throughput.
    t_prev = None
    pending = None  # window i-1's metrics, fetched after window i dispatches
    for _ in range(num_iters + 1):
        for _ in range(num_batches_per_iter):
            state, metrics = step_fn(state, next_batch())
        if pending is not None:
            float(pending["loss"])
            now = time.perf_counter()
            if t_prev is not None:
                dt = now - t_prev
                iter_times.append(dt)
                img_secs.append(
                    global_batch * num_batches_per_iter / dt / num_devices
                )
            t_prev = now
        pending = metrics
    float(pending["loss"])  # last window drains with nothing queued behind
    dt = time.perf_counter() - t_prev
    iter_times.append(dt)
    img_secs.append(global_batch * num_batches_per_iter / dt / num_devices)

    mean = statistics.fmean(img_secs)
    stdev = statistics.stdev(img_secs) if len(img_secs) > 1 else 0.0
    result = BenchmarkResult(
        model=model_name,
        batch_size_per_chip=batch_size_per_chip,
        num_devices=num_devices,
        img_sec_per_chip_mean=mean,
        img_sec_per_chip_ci95=1.96 * stdev,
        img_sec_total=mean * num_devices,
        iter_times_s=iter_times,
    )
    if log:
        for line in result.summary_lines():
            log(line)
    return result


def run_benchmark(
    step_fn: Callable,
    state,
    batch,
    *,
    model_name: str = "model",
    batch_size_per_chip: int = 64,
    num_devices: Optional[int] = None,
    num_warmup_batches: int = 10,
    num_iters: int = 10,
    num_batches_per_iter: int = 10,
    log: Optional[Callable[[str], None]] = None,
) -> BenchmarkResult:
    """Benchmark ``step_fn(state, batch) -> (state, metrics)``.

    ``batch`` must already be placed on the mesh (global batch). Timings per
    iteration are global-batch steps; per-chip img/sec divides by the device
    count, matching the reference's per-GPU accounting
    (``pytorch_synthetic_benchmark.py:116-122``).
    """
    if num_devices is None:
        # derive from the batch's actual placement, not the global device
        # count — a step built over a subset mesh must not inflate img/sec
        leaves = jax.tree_util.tree_leaves(batch)
        if leaves and hasattr(leaves[0], "sharding"):
            num_devices = leaves[0].sharding.num_devices
        else:
            num_devices = world_size()
    return _windowed_benchmark(
        step_fn,
        state,
        lambda: batch,
        model_name=model_name,
        batch_size_per_chip=batch_size_per_chip,
        num_devices=num_devices,
        num_warmup_batches=num_warmup_batches,
        num_iters=num_iters,
        num_batches_per_iter=num_batches_per_iter,
        log=log,
        label="",
    )


def run_data_benchmark(
    step_fn: Callable,
    state,
    device_batches,
    *,
    model_name: str = "model",
    batch_size_per_chip: int = 64,
    num_devices: Optional[int] = None,
    num_warmup_batches: int = 10,
    num_iters: int = 10,
    num_batches_per_iter: int = 10,
    log: Optional[Callable[[str], None]] = None,
) -> BenchmarkResult:
    """Benchmark the step fed from a REAL input pipeline.

    Identical methodology to :func:`run_benchmark` except each step consumes
    the next batch from ``device_batches`` (an iterator of mesh-placed
    batches, e.g. ``utils.prefetch.prefetch_to_device`` over an input_fn) —
    so the number includes TFRecord read, JPEG decode, host→HBM transfer and
    any pipeline stalls, exactly the end-to-end rate a training run sees.
    The reference never isolates this (its input path is timed only inside
    full training runs); measuring it directly is how the synthetic-vs-fed
    gap in ``BENCH_DATA_*.json`` is produced.

    Raises ``StopIteration`` if the pipeline runs dry before
    ``num_warmup_batches + (num_iters+1)*num_batches_per_iter`` batches
    (one extra unmeasured priming window); size the dataset (or use a
    repeating pipeline) accordingly.
    """
    if num_devices is None:
        num_devices = world_size()
    it = iter(device_batches)
    # Pipeline stalls show up in the window deltas (the next batch is
    # pulled before each dispatch) but the constant D2H fetch latency does
    # not — same methodology as the synthetic path, so the two rates in
    # BENCH_DATA_*.json stay comparable.
    return _windowed_benchmark(
        step_fn,
        state,
        lambda: next(it),
        model_name=model_name,
        batch_size_per_chip=batch_size_per_chip,
        num_devices=num_devices,
        num_warmup_batches=num_warmup_batches,
        num_iters=num_iters,
        num_batches_per_iter=num_batches_per_iter,
        log=log,
        label="data-fed ",
    )
