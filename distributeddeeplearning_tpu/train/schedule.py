"""Learning-rate recipes.

The accuracy-critical recipe from the reference (Goyal et al. 1706.02677,
"Accurate, Large Minibatch SGD"), which BASELINE.md pins as the definition of
"identical top-1":

- base LR scaled linearly by world size: ``lr = base_lr × world_size``
  (``imagenet_pytorch_horovod.py:296-302``, ``resnet_main.py:42``)
- 5-epoch linear warmup from ``base_lr`` up to the scaled LR
  (``imagenet_pytorch_horovod.py:263-289``)
- step decay ÷10 at epochs 30/60/80
  (``imagenet_pytorch_horovod.py:279-289``; vestigial TF variant
  ``resnet_run_loop.py:39-62``)

Expressed as pure step→lr functions (optax schedules) so they live inside the
jitted update — no per-batch host-side ``adjust_learning_rate`` mutation.
"""

from __future__ import annotations

from typing import Sequence

import optax


def scale_base_lr(base_lr: float, world_size: int) -> float:
    """Linear LR scaling (Goyal §2.1): lr = base_lr × number of replicas."""
    return base_lr * world_size


def goyal_lr_schedule(
    base_lr: float,
    world_size: int,
    steps_per_epoch: int,
    *,
    warmup_epochs: int = 5,
    decay_epochs: Sequence[int] = (30, 60, 80),
    decay_factor: float = 0.1,
) -> optax.Schedule:
    """The full reference schedule as one optax schedule.

    Warmup ramps linearly from ``base_lr`` (not zero — matching the
    reference's ``lr_adj = 1/size × (epoch×(size-1)/warmup + 1)`` shape at
    ``imagenet_pytorch_horovod.py:276-278``, which starts at base_lr and ends
    at base_lr×size) and then decays ÷10 at the milestone epochs.
    """
    peak = scale_base_lr(base_lr, world_size)
    warmup_steps = warmup_epochs * steps_per_epoch

    warmup = optax.linear_schedule(
        init_value=base_lr,
        end_value=peak,
        transition_steps=max(warmup_steps, 1),
    )
    plateaus = [
        optax.constant_schedule(peak * decay_factor**i)
        for i in range(len(decay_epochs) + 1)
    ]
    boundaries = [warmup_steps] + [e * steps_per_epoch for e in decay_epochs]
    return optax.join_schedules([warmup] + plateaus, boundaries)


def constant_schedule(lr: float) -> optax.Schedule:
    return optax.constant_schedule(lr)


def warmup_linear_decay_schedule(
    peak_lr: float,
    total_steps: int,
    *,
    warmup_fraction: float = 0.1,
) -> optax.Schedule:
    """BERT fine-tune schedule: linear warmup to ``peak_lr`` over the first
    ``warmup_fraction`` of training, then linear decay to zero (Devlin et
    al. fine-tuning recipe — no reference counterpart to cite; the reference
    trains CNNs only)."""
    warmup_steps = max(int(total_steps * warmup_fraction), 1)
    return optax.join_schedules(
        [
            optax.linear_schedule(0.0, peak_lr, warmup_steps),
            optax.linear_schedule(
                peak_lr, 0.0, max(total_steps - warmup_steps, 1)
            ),
        ],
        [warmup_steps],
    )
