"""Train state: params + optimizer state + step, with bf16 compute policy.

Replaces the reference's mutable (model, optimizer) pair
(``imagenet_pytorch_horovod.py:383-409``) with a single immutable pytree that
``jit`` threads through the step function.  The mixed-precision contract is
TPU-native: **params and optimizer state in float32, activations and
gradients computed in bfloat16** — the role the reference's fp16 gradient
compression knob plays (``pytorch_synthetic_benchmark.py:69``), but without a
loss-scaler because bf16 keeps fp32's exponent range.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from flax import struct
from flax.core import meta

PyTree = Any


class TrainState(struct.PyTreeNode):
    """Immutable training state (flax-style, minimal and orbax-friendly)."""

    step: jax.Array
    params: PyTree
    opt_state: optax.OptState
    batch_stats: PyTree  # BN running stats; {} for stat-free models
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    def apply_gradients(self, grads: PyTree, **kwargs) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(
            step=self.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            **kwargs,
        )


def sgd_momentum(
    schedule: optax.Schedule,
    *,
    momentum: float = 0.9,
    weight_decay: float = 5e-5,
    nesterov: bool = False,
) -> optax.GradientTransformation:
    """The reference optimizer: SGD momentum 0.9, weight decay 5e-5
    (``imagenet_pytorch_horovod.py:42-43,391-395``; TF MomentumOptimizer at
    ``resnet_main.py:139-144``).  Weight decay is coupled (added to the
    gradient) exactly as torch.optim.SGD does, so the recipe transfers."""
    components = []
    if weight_decay:
        components.append(optax.add_decayed_weights(weight_decay))
    components.append(optax.sgd(schedule, momentum=momentum, nesterov=nesterov))
    return optax.chain(*components)


def adamw(
    schedule: optax.Schedule,
    *,
    weight_decay: float = 0.01,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-6,
    grad_clip_norm: float = 1.0,
) -> optax.GradientTransformation:
    """AdamW with global-norm clipping — the standard BERT fine-tune
    optimizer (the reference has no transformer workload; these are the
    Devlin et al. fine-tuning defaults, decoupled weight decay)."""
    components = []
    if grad_clip_norm:
        components.append(optax.clip_by_global_norm(grad_clip_norm))
    components.append(
        optax.adamw(schedule, b1=b1, b2=b2, eps=eps, weight_decay=weight_decay)
    )
    return optax.chain(*components)


def create_train_state(
    rng: jax.Array,
    model,
    input_shape,
    tx: optax.GradientTransformation,
    *,
    input_dtype: jnp.dtype = jnp.float32,
) -> TrainState:
    """Initialize params (fp32) and optimizer state for a flax module."""
    dummy = jnp.zeros(input_shape, input_dtype)
    variables = model.init(rng, dummy, train=False)
    # Unbox flax logical-partitioning metadata: the TrainState holds plain
    # arrays; logical axis specs travel separately (models.logical_axes).
    variables = meta.unbox(variables)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        batch_stats=batch_stats,
        apply_fn=model.apply,
        tx=tx,
    )
