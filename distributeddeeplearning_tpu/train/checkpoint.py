"""Sharded checkpoint / resume with DURABLE, verified generations.

The reference's three partial mechanisms (SURVEY.md §5 "Checkpoint / resume"):
TF Estimator implicit rank-0 checkpoints (``resnet_main.py:140-158``), a buggy
PyTorch rank-0 epoch save (``imagenet_pytorch_horovod.py:257-260`` — NameError
off rank 0), and a full resume protocol stranded in dead code
(``PyTorch_hvd/src/imagenet_pytorch_horovod.py:62-72,133-144``).

TPU-native replacement: orbax ``CheckpointManager`` writes the train-state
pytree **sharded** — every host writes its own param shards in parallel (no
rank-0 gather, no broadcast), and restore places shards directly onto the
mesh from the target state's shardings.

Durability layer (PR 13) — storage is not trusted:

- **verified saves**: every generation gets a content MANIFEST
  (:data:`MANIFEST_NAME` — per-leaf CRC32 + shape + dtype over the saved
  items) written atomically (tmp + rename) only AFTER orbax finalizes the
  generation's data.  A generation without a valid manifest is
  by-construction incomplete (a torn write, a writer killed mid-commit)
  and never restore-eligible;
- **corruption-tolerant restore**: :meth:`Checkpointer.restore` /
  :meth:`Checkpointer.restore_params` walk generations newest-first,
  verify each candidate against its manifest, and FALL BACK past any
  generation that fails to read or to verify — with an obs event, a
  ``ckpt.verify_failures`` counter bump and a flight-recorder dump naming
  the generation and the first failing leaf.  A corrupt latest costs one
  generation of progress, not the run;
- :meth:`Checkpointer.latest_verified_step` replaces the blind
  ``latest_step()`` everywhere a resume decision is made (trainer
  rollback, the ``ddlt train`` supervisor's accounting, serve startup);
- **params-only item**: generations are saved as TWO orbax items —
  ``params`` and ``state`` (step / opt_state / batch_stats) — so
  ``restore_params`` (the ``ddlt serve`` startup path) reads only the
  params bytes instead of ~3x that for an AdamW checkpoint.  Generations
  from before this layout (single ``default`` item, no manifest) keep
  working through the legacy full-read path.

Deterministic chaos for all of it: ``DDLT_FAULTS`` kinds ``ckpt_corrupt``
(flip / truncate / unlink / manifest) and ``ckpt_torn`` fire at generation
finalize (:mod:`..utils.faults`), exercised by ``bench.py --ckpt-faults``
and ``tests/test_checkpoint.py``.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import zlib
from pathlib import Path
from typing import Any, Dict, List, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from distributeddeeplearning_tpu.obs import goodput as _goodput
from distributeddeeplearning_tpu.obs.recorder import get_recorder
from distributeddeeplearning_tpu.obs.registry import get_registry
from distributeddeeplearning_tpu.obs.trace import get_tracer
from distributeddeeplearning_tpu.utils import faults as faults_mod
from distributeddeeplearning_tpu.utils.retry import retry_call

logger = logging.getLogger("ddlt.checkpoint")

PyTree = Any

#: per-generation content manifest, written into the finalized step dir
MANIFEST_NAME = "ddlt_manifest.json"
#: directory-level marker: once ANY manifest has been committed here, a
#: manifest-less generation is incomplete — never "legacy"
DURABLE_MARKER = "ddlt_durable.json"
MANIFEST_FORMAT = 1

CORRUPT_MODES = ("flip", "truncate", "unlink", "manifest")


class CheckpointCorruptionError(RuntimeError):
    """Every manifested generation failed verification — nothing left to
    fall back to.  Deliberately NOT restartable: a supervisor restart
    would re-read the same corrupt store forever."""


# -- manifest construction / verification ----------------------------------


def _leaf_entries(prefix: str, tree: PyTree) -> Dict[str, Dict[str, Any]]:
    """``"<item>/<keypath>" -> {shape, dtype, crc32}`` for every leaf.

    CRC32 over the host bytes: fast enough to stay inside the <10%%
    verify-overhead budget (zlib runs at memory bandwidth next to the
    serialize the save already pays), strong enough to catch the bit-flip
    / truncation / wrong-leaf classes the manifest exists for.
    """
    entries: Dict[str, Dict[str, Any]] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = np.ascontiguousarray(np.asarray(leaf))
        entries[f"{prefix}{jax.tree_util.keystr(path)}"] = {
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
            "crc32": zlib.crc32(arr),
        }
    return entries


def build_manifest(step: int, items: Dict[str, PyTree]) -> Dict[str, Any]:
    """Content manifest over the generation's items (host-side arrays)."""
    leaves: Dict[str, Dict[str, Any]] = {}
    for item_name in sorted(items):
        leaves.update(_leaf_entries(f"{item_name}/", items[item_name]))
    return {
        "format": MANIFEST_FORMAT,
        "step": int(step),
        "created_unix_s": time.time(),
        "items": sorted(items),
        "leaves": leaves,
    }


class _PendingManifest:
    """A generation's manifest being built in the BACKGROUND.

    ``save()`` snapshots the arrays to host synchronously as PRIVATE
    COPIES — ``np.array(copy=True)``, never ``device_get``: on the CPU
    backend device_get returns zero-copy VIEWS of the jax buffers, and
    the very next donated train step reuses that memory in place, so a
    background hash over a view would checksum clobbered bytes (a bug
    the chaos bench caught live) — and hands the checksum work to a
    thread.  The CRC pass rides the same async window the orbax write
    does, so the save path pays one memcpy + thread spawn, not the hash.
    ``wall_s`` records the thread's own CPU-side wall for the artifact's
    accounting; the save-path overhead gate counts only what
    :class:`Checkpointer` adds synchronously (plus any join wait at
    finalize, which a write slower than the hash absorbs to ~0).
    """

    def __init__(self, step: int, host_items: Dict[str, PyTree]):
        self.step = step
        self.manifest: Optional[Dict[str, Any]] = None
        self.wall_s = 0.0
        self._thread = threading.Thread(
            target=self._build, args=(step, host_items),
            name=f"ddlt-ckpt-manifest-{step}", daemon=True,
        )
        self._thread.start()

    def _build(self, step: int, host_items: Dict[str, PyTree]) -> None:
        t0 = time.perf_counter()
        self.manifest = build_manifest(step, host_items)
        self.wall_s = time.perf_counter() - t0

    def join(self) -> Optional[Dict[str, Any]]:
        self._thread.join()
        return self.manifest


def verify_manifest(
    manifest: Dict[str, Any], items: Dict[str, PyTree]
) -> List[str]:
    """Check restored ``items`` against their manifest entries.

    Returns problem strings (empty = verified).  Only the items actually
    restored are checked — a params-only restore verifies the ``params/``
    subset — but a restored item must cover its manifest entries exactly:
    a missing or extra leaf is structural corruption, not a skip.
    """
    problems: List[str] = []
    expected = manifest.get("leaves")
    if not isinstance(expected, dict) or not expected:
        return ["manifest carries no leaf entries"]
    got: Dict[str, Dict[str, Any]] = {}
    for item_name in sorted(items):
        got.update(_leaf_entries(f"{item_name}/", items[item_name]))
    prefixes = tuple(f"{name}/" for name in items)
    for name, entry in sorted(expected.items()):
        if not name.startswith(prefixes):
            continue  # an item this restore did not read
        actual = got.pop(name, None)
        if actual is None:
            problems.append(f"leaf {name} missing from the restored tree")
        elif actual != entry:
            problems.append(
                f"leaf {name} mismatch (manifest {entry}, restored {actual})"
            )
    for name in sorted(got):
        problems.append(f"restored leaf {name} not named by the manifest")
    return problems


def _atomic_write_json(path: Path, payload: Dict[str, Any]) -> None:
    """Write-then-rename so a reader can never observe a torn manifest —
    the manifest's own durability must be at least as good as the
    property it certifies."""
    tmp = path.with_name(path.name + ".tmp")
    with open(tmp, "w") as f:
        json.dump(payload, f)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_manifest(step_dir: Path) -> Optional[Dict[str, Any]]:
    """The generation's manifest, or None when missing/unparseable/
    structurally invalid (all three mean: not restore-eligible)."""
    path = Path(step_dir) / MANIFEST_NAME
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if (
        not isinstance(manifest, dict)
        or manifest.get("format") != MANIFEST_FORMAT
        or not isinstance(manifest.get("leaves"), dict)
        or not manifest["leaves"]
    ):
        return None
    return manifest


def _data_files(step_dir: Path) -> List[Path]:
    """The generation's data files, largest first (path tiebreak) — the
    deterministic corruption targets.  The manifest and orbax's own
    metadata markers are excluded: ``mode=flip`` must hit ARRAY bytes."""
    files = [
        p
        for p in sorted(Path(step_dir).rglob("*"))
        if p.is_file()
        and p.name != MANIFEST_NAME
        and p.parent.name == "d"  # ocdbt data dirs hold the array bytes
    ]
    return sorted(files, key=lambda p: (-p.stat().st_size, str(p)))


def corrupt_generation(step_dir, mode: str = "flip") -> str:
    """Deterministically corrupt one finalized generation (chaos only).

    Returns a description of what was done.  ``flip`` flips one byte in
    the middle of the largest data file, ``truncate`` halves it,
    ``unlink`` deletes it, ``manifest`` deletes the manifest itself (the
    torn-manifest case: data fine, generation still not restore-eligible).
    """
    step_dir = Path(step_dir)
    if mode not in CORRUPT_MODES:
        raise ValueError(
            f"unknown corruption mode {mode!r}; known: {CORRUPT_MODES}"
        )
    if mode == "manifest":
        (step_dir / MANIFEST_NAME).unlink(missing_ok=True)
        return f"unlinked {MANIFEST_NAME}"
    targets = _data_files(step_dir)
    if not targets:
        raise FileNotFoundError(f"no data files under {step_dir}")
    target = targets[0]
    if mode == "unlink":
        target.unlink()
        return f"unlinked {target.name}"
    if mode == "truncate":
        size = target.stat().st_size
        with open(target, "r+b") as f:
            f.truncate(size // 2)
        return f"truncated {target.name} {size} -> {size // 2} bytes"
    size = target.stat().st_size
    with open(target, "r+b") as f:
        f.seek(size // 2)
        byte = f.read(1)
        f.seek(size // 2)
        f.write(bytes([byte[0] ^ 0xFF]))
    return f"flipped byte {size // 2} of {target.name}"


def latest_verified_step_in_dir(directory) -> Optional[int]:
    """Manager-free scan: newest step whose generation carries a valid
    manifest.  Legacy directories (no durability marker AND no manifest
    anywhere) fall back to the newest step dir — pre-manifest checkpoints
    stay usable.  The ``ddlt train`` supervisor's recovery accounting
    uses this (a full ``Checkpointer`` per restart would be waste)."""
    directory = Path(directory)
    if not directory.exists():
        return None
    steps = sorted(
        (int(p.name) for p in directory.iterdir() if p.name.isdigit()),
        reverse=True,
    )
    if not steps:
        return None
    verified = [
        s for s in steps if load_manifest(directory / str(s)) is not None
    ]
    if verified:
        return verified[0]
    if (directory / DURABLE_MARKER).exists():
        return None  # durable dir with zero verified generations
    return steps[0]  # legacy (pre-manifest) directory


class Checkpointer:
    """Epoch/step-granular sharded checkpointing of a ``TrainState``.

    Only array fields travel (step, params, opt_state, batch_stats); static
    fields (apply_fn, tx) are re-supplied by the restore template, which is
    also the source of target shardings.

    Generations are saved as two orbax items — ``params`` and ``state`` —
    and certified by a per-generation manifest (module docstring).
    :attr:`save_wall_s` / :attr:`snapshot_wall_s` / :attr:`verify_wall_s`
    / :attr:`verify_cpu_s` accumulate the save-path wall, the
    donation-safety memcpy any correct async save pays, the wall
    verification proper ADDED (finalize joins + restore-side manifest
    checks), and the background checksum work that overlapped the async
    write — ``bench.py --ckpt-faults`` gates the verification wall at
    < 10% of the persist wall.
    """

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int = 5,
        save_interval_steps: int = 1,
        async_save: bool = True,
    ):
        """``async_save`` (the TPU-native default): ``save()`` snapshots
        the state to PRIVATE host copies synchronously, then orbax
        serializes/writes the snapshot in a background thread — the step
        loop never stalls on storage.  Safe with donated train states
        because the snapshot is a real memcpy, not a view (see
        :meth:`_snapshot_items` for the CPU-backend aliasing bug the
        copy kills).  ``wait()``/``close()`` drain pending writes AND
        commit the drained generations' manifests (a manifest may only
        ever cover data that has fully landed)."""
        self.directory = Path(directory).absolute()
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                create=True,
                enable_async_checkpointing=async_save,
            ),
        )
        # manifests awaiting their generation's async finalize, oldest
        # first: step -> background manifest build over the host snapshot
        # taken at save time (BEFORE donation can touch the buffers)
        self._pending_manifests: Dict[int, _PendingManifest] = {}
        # cumulative walls for the verify-overhead gate:
        # - snapshot_wall_s: the private host memcpy a CORRECT async
        #   save needs with donated states regardless of manifests
        #   (see _snapshot_items — without it the background write
        #   aliases the donated buffer);
        # - verify_wall_s: wall ADDED by verification proper (finalize
        #   joins + restore-side manifest checks);
        # - verify_cpu_s: the background checksum work that overlapped
        #   the async write (CPU cost, not save-path wall).
        self.save_wall_s = 0.0
        self.snapshot_wall_s = 0.0
        self.verify_wall_s = 0.0
        self.verify_cpu_s = 0.0

    @staticmethod
    def _state_items(state) -> Dict[str, PyTree]:
        """The two saved items: ``params`` alone (the serve startup read)
        and ``state`` (everything else a resume needs)."""
        return {
            "params": state.params,
            "state": {
                "step": state.step,
                "opt_state": state.opt_state,
                "batch_stats": state.batch_stats,
            },
        }

    def _step_dir(self, step: int) -> Path:
        return self.directory / str(step)

    def _is_composite(self, step: int) -> bool:
        """Post-PR generations carry a ``params`` item dir; legacy ones
        hold the whole tree under orbax's ``default`` item."""
        return (self._step_dir(step) / "params").exists()

    # -- saving ------------------------------------------------------------

    @staticmethod
    def _snapshot_items(items: Dict[str, PyTree]) -> Optional[Dict[str, PyTree]]:
        """PRIVATE host copies of every leaf (``np.array(copy=True)``),
        or None when a leaf is not fully addressable (a true multi-host
        sharded array — each host holds only its shards, so there is no
        local array to copy).

        The snapshot is what gets handed to orbax AND hashed into the
        manifest.  Two bugs die here, both caught live by the chaos
        bench on the CPU backend, where device→host "copies" of jax
        arrays are zero-copy VIEWS of the device buffer:

        - orbax's async serializer read the view in the background while
          the next DONATED train steps reused the buffer in place — a
          checkpoint labeled step N could contain step N+1's bytes
          (restore "succeeded" with silently wrong state);
        - a manifest hashed over the same view checksummed whatever the
          buffer held by hash time.

        One real memcpy at save time makes the written bytes, the
        manifest bytes and the step-N state the same thing by
        construction.
        """
        leaves = jax.tree_util.tree_leaves(items)
        if not all(
            getattr(leaf, "is_fully_addressable", True) for leaf in leaves
        ):
            return None
        return jax.tree_util.tree_map(
            lambda a: np.array(a, copy=True), items
        )

    def save(self, step: int, state, *, deadline_s: Optional[float] = None) -> bool:
        """Save if the manager's policy wants this step. Returns True if saved.

        Transient storage errors are retried with bounded jittered backoff
        (``utils/retry.py``) before propagating; ``deadline_s`` bounds the
        whole attempt+retry sequence on the wall clock — the emergency-
        checkpoint path passes the preemption grace window's remainder so
        backoff can never sleep past the SIGKILL.  The ``checkpoint.save``
        fault-injection site (``utils/faults.py``) exercises this path.
        """
        items = self._state_items(state)
        t0 = time.perf_counter()
        # snapshot FIRST (donation safety — see _snapshot_items); orbax
        # serializes the snapshot, the manifest hashes the same snapshot
        v0 = time.perf_counter()
        snapshot = self._snapshot_items(items)
        self.snapshot_wall_s += time.perf_counter() - v0
        to_save = snapshot if snapshot is not None else items
        if snapshot is None:
            # true multi-host sharded state: orbax's per-host sharded
            # write takes over; per-host manifests are future work, so
            # the generation ships uncertified (legacy restore semantics)
            logger.warning(
                "step %d: non-addressable sharded state — saving without "
                "a content manifest (multi-host manifests not yet "
                "supported)", step,
            )

        def _save() -> bool:
            faults_mod.get_plan().maybe_io_error("checkpoint.save")
            return self._mgr.save(
                step,
                args=ocp.args.Composite(
                    **{
                        name: ocp.args.StandardSave(tree)
                        for name, tree in to_save.items()
                    }
                ),
            )

        with get_tracer().span("ckpt/save", step=step):
            saved = retry_call(
                _save, retries=2, base_delay=0.2, max_delay=2.0,
                description=f"checkpoint save (step {step})",
                deadline_s=deadline_s,
            )
            if saved and snapshot is not None:
                # checksum in the background over the SAME private
                # snapshot orbax is writing — the hash overlaps the
                # async write, and the manifest WRITE is deferred until
                # the generation's data has landed (_finalize_manifests)
                # so a manifest can never certify a torn generation
                self._pending_manifests[step] = _PendingManifest(
                    step, snapshot
                )
            # orbax serializes async saves: initiating THIS save waited
            # for the previous generation's commit, so every pending
            # manifest except this step's is ready to finalize now
            self._finalize_manifests(exclude_step=step)
        self.save_wall_s += time.perf_counter() - t0
        # goodput detail: the trainer's marks already charge this wall to
        # checkpoint_blocking — the note splits it save-join vs wait-drain
        # for the ledger's notes block (never double-counted in the sum)
        _goodput.get_ledger().note(
            "ckpt_save_block_s", time.perf_counter() - t0
        )
        if saved:
            logger.info("checkpoint saved at step %d -> %s", step, self.directory)
        return saved

    def _finalize_manifests(self, exclude_step: Optional[int] = None) -> None:
        """Commit manifests for every pending generation whose data has
        landed (final step dir present — orbax renames the tmp dir only
        after the commit completes).  Also the injection point for the
        ``ckpt_torn`` / ``ckpt_corrupt`` chaos kinds: both model failures
        that strike exactly here, at generation finalize."""
        plan = faults_mod.get_plan()
        for step in sorted(self._pending_manifests):
            if step == exclude_step:
                continue
            pending = self._pending_manifests.pop(step)
            step_dir = self._step_dir(step)
            if not step_dir.exists():
                if any(
                    self.directory.glob(f"{step}.orbax-checkpoint-tmp-*")
                ):
                    # STILL IN FLIGHT: a policy-skipped save() reaches
                    # here without orbax having waited for the previous
                    # generation's commit — keep the manifest pending for
                    # the next save()/wait() instead of permanently
                    # un-certifying a write that will land fine
                    self._pending_manifests[step] = pending
                    continue
                # evicted (max_to_keep) before its manifest committed, or
                # the write never landed — either way nothing to certify
                logger.debug(
                    "generation %d gone before manifest commit", step
                )
                continue
            # join the background checksum: with a write slower than the
            # hash (the normal case) this is a no-op wait; either way the
            # join wall is charged as verify overhead on the save path
            v0 = time.perf_counter()
            manifest = pending.join()
            self.verify_wall_s += time.perf_counter() - v0
            self.verify_cpu_s += pending.wall_s
            if manifest is None:  # pragma: no cover — build thread died
                logger.warning(
                    "manifest build failed for generation %d — generation "
                    "left uncertified", step,
                )
                continue
            if plan and plan.take_ckpt_torn():
                # writer "dies" mid-generation: data torn, no manifest —
                # the generation must read as incomplete forever
                try:
                    corrupt_generation(step_dir, "truncate")
                except (OSError, FileNotFoundError):  # pragma: no cover
                    pass
                continue
            try:
                _atomic_write_json(step_dir / MANIFEST_NAME, manifest)
                marker = self.directory / DURABLE_MARKER
                if not marker.exists():
                    _atomic_write_json(
                        marker, {"manifest_format": MANIFEST_FORMAT}
                    )
            except OSError as exc:
                # an uncertified-but-complete generation is merely not
                # restore-eligible; failing the RUN over it would invert
                # the durability story
                logger.warning(
                    "manifest write failed for generation %d: %s", step, exc
                )
                continue
            options = plan.take_ckpt_corrupt() if plan else None
            if options is not None:
                what = corrupt_generation(
                    step_dir, str(options.get("mode", "flip"))
                )
                logger.warning(
                    "ckpt_corrupt: generation %d — %s", step, what
                )

    def wait(self, *, deadline_s: Optional[float] = None) -> None:
        """Drain pending async saves, retrying transient storage failures
        (same policy as :meth:`save`), then commit the drained
        generations' manifests.  ``deadline_s`` bounds the retry backoff —
        the emergency-checkpoint path calls this synchronously inside the
        preemption grace window."""

        def _wait() -> None:
            faults_mod.get_plan().maybe_io_error("checkpoint.wait")
            self._mgr.wait_until_finished()

        t0 = time.perf_counter()
        retry_call(
            _wait, retries=2, base_delay=0.2, max_delay=2.0,
            description="checkpoint wait", deadline_s=deadline_s,
        )
        self._finalize_manifests()
        # goodput detail note (see save(): categories come from the
        # trainer's marks, this is the save-join vs wait-drain split)
        _goodput.get_ledger().note(
            "ckpt_wait_block_s", time.perf_counter() - t0
        )

    # -- restore-eligibility ----------------------------------------------

    def latest_step(self) -> Optional[int]:
        """Newest step orbax knows about — storage-trusting; resume
        decisions should use :meth:`latest_verified_step`."""
        return self._mgr.latest_step()

    def all_steps(self) -> List[int]:
        return sorted(int(s) for s in self._mgr.all_steps())

    def _is_legacy_dir(self, steps: List[int]) -> bool:
        """Pre-manifest directory: no durability marker and no manifest on
        any generation — trust the newest step like the old code did."""
        if (self.directory / DURABLE_MARKER).exists():
            return False
        return not any(
            load_manifest(self._step_dir(s)) is not None for s in steps
        )

    def latest_verified_step(self) -> Optional[int]:
        """Newest step whose generation carries a valid manifest — the
        restore-eligibility decision every resume path keys off.  Legacy
        (pre-manifest) directories fall back to ``latest_step`` with a
        warning so old checkpoints stay usable.

        This is a MANIFEST-level probe (cheap: one JSON read per
        generation); full content verification needs the data bytes and
        happens inside the restore walk — a data-corrupt generation
        whose manifest survived intact reads as eligible here and is
        discovered (and, on the trainer path, evicted) at restore time,
        so accounting built on this probe can run one generation ahead
        of where a restart actually lands until that restore runs."""
        steps = sorted(self.all_steps(), reverse=True)
        if not steps:
            return None
        for step in steps:
            if load_manifest(self._step_dir(step)) is not None:
                return step
        if self._is_legacy_dir(steps):
            logger.warning(
                "checkpoint dir %s has no manifests (pre-durability "
                "layout) — trusting latest step %d unverified",
                self.directory, steps[0],
            )
            return steps[0]
        return None

    # -- restore -----------------------------------------------------------

    def _note_verify_failure(
        self, step: int, why: str, leaf: Optional[str]
    ) -> None:
        """One verification failure = one obs event + counter bump + a
        flight-recorder dump naming the generation and leaf — the
        operator-facing answer to "why did resume go backwards?"."""
        logger.error(
            "checkpoint generation %d FAILED verification (%s) — "
            "falling back to the newest older verified generation",
            step, why,
        )
        get_tracer().event(
            "ckpt/verify_failed", cat="ckpt", step=step, why=why, leaf=leaf,
        )
        get_registry().counter("ckpt.verify_failures").inc()
        get_recorder().dump(
            "ckpt_verify_failed", registry=get_registry(),
            generation=step, why=why, leaf=leaf,
            directory=str(self.directory),
        )

    def _restore_items(
        self, step: int, abstract_items: Optional[Dict[str, PyTree]]
    ) -> Dict[str, PyTree]:
        """Read one generation's items (composite or legacy layout) into
        the abstract templates (None = as-saved, host-resident)."""
        if self._is_composite(step):
            names = (
                sorted(abstract_items)
                if abstract_items is not None
                else ["params", "state"]
            )
            restored = self._mgr.restore(
                step,
                args=ocp.args.Composite(
                    **{
                        name: ocp.args.StandardRestore(
                            abstract_items[name]
                            if abstract_items is not None
                            else None
                        )
                        for name in names
                    }
                ),
            )
            return {name: restored[name] for name in names}
        # legacy single-item generation: the whole tree under "default"
        flat = None
        if abstract_items is not None:
            flat = {
                "params": abstract_items["params"],
                **abstract_items["state"],
            }
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(flat)
        )
        return {
            "params": restored["params"],
            "state": {
                "step": restored["step"],
                "opt_state": restored["opt_state"],
                "batch_stats": restored["batch_stats"],
            },
        }

    def _verify_items(
        self, step: int, items: Dict[str, PyTree]
    ) -> bool:
        """True when ``items`` match the generation's manifest; emits the
        failure triplet (event/counter/dump) otherwise."""
        manifest = load_manifest(self._step_dir(step))
        if manifest is None:
            self._note_verify_failure(
                step, "missing or invalid manifest", None
            )
            return False
        with get_tracer().span("ckpt/verify", step=step):
            v0 = time.perf_counter()
            problems = verify_manifest(manifest, items)
            self.verify_wall_s += time.perf_counter() - v0
        if problems:
            first = problems[0]
            leaf = first.split(" ")[1] if first.startswith("leaf ") else None
            self._note_verify_failure(
                step, "; ".join(problems[:3]), leaf
            )
            return False
        return True

    def _verified_candidates(self, steps: List[int]):
        """Newest-first steps whose manifests parse (the restore walk
        order) plus the REJECTED steps.  Manifest-less generations in a
        durable dir are rejected with the failure triplet (they are the
        torn-write signature) — EXCEPT generations this instance knows
        are merely pending their manifest commit (async save not yet
        drained): the writer's own restore racing its own in-flight save
        is the wait()-before-restore contract, not corruption, so those
        skip quietly instead of crying wolf into the verify-failure
        counter."""
        candidates: List[int] = []
        rejected: List[int] = []
        for step in sorted(steps, reverse=True):
            if load_manifest(self._step_dir(step)) is not None:
                candidates.append(step)
            elif step in self._pending_manifests:
                logger.info(
                    "generation %d manifest still pending (async save "
                    "not drained) — not restore-eligible yet", step,
                )
            else:
                self._note_verify_failure(
                    step, "missing or invalid manifest", None
                )
                rejected.append(step)
        return candidates, rejected

    def _delete_generation(self, step: int) -> None:
        """Evict a generation that failed verification.  Leaving the
        corrupt dir in place would WEDGE its step: orbax's ``should_save``
        skips any step <= ``latest_step()``, so after a fallback the
        resumed run's re-save of this very step would silently no-op and
        the recovered progress would never persist — every restart would
        fall back again and re-lose the same work.  (The failure triplet
        already captured the forensics before this runs.)"""
        try:
            self._mgr.delete(step)
            logger.warning(
                "evicted unverifiable generation %d (a corrupt dir left "
                "in place would block its step from ever being re-saved)",
                step,
            )
        except Exception as exc:  # noqa: BLE001 — eviction is best-effort
            logger.warning(
                "could not evict unverifiable generation %d: %s", step, exc
            )

    def _restore_walk(self, steps: List[int], verify: bool):
        """The shared candidate-selection policy of :meth:`restore` and
        :meth:`restore_params`: legacy (pre-manifest) dirs restore the
        newest step unverified; durable dirs walk verified candidates
        newest-first.  Returns ``(candidates, verify, rejected)`` —
        ``rejected`` are manifest-less (torn) generations the caller may
        evict."""
        if self._is_legacy_dir(steps):
            return steps[:1], False, []
        candidates, rejected = self._verified_candidates(steps)
        return candidates, verify, rejected

    def _corruption_error(
        self, steps: List[int]
    ) -> CheckpointCorruptionError:
        return CheckpointCorruptionError(
            f"no generation under {self.directory} verifies "
            f"(steps seen: {steps}) — the store is corrupt beyond the "
            "fallback window; restore from a replica or start fresh"
        )

    def restore(
        self, state_template, *, verify: bool = True,
        evict_failed: bool = True,
    ):
        """Restore the newest VERIFIED checkpoint INTO the template's
        shardings.

        Returns (state, step); (template, None) when nothing to restore.
        A candidate generation that fails to read or fails manifest
        verification is skipped (obs event + flight-recorder dump) and the
        walk falls back to the next older one — a corrupt latest costs one
        generation of progress.  With ``evict_failed`` (the default — this
        is the TRAINER's resume verb, and the trainer owns the store) a
        failed generation is also DELETED: left in place it would wedge
        its step forever, because orbax silently skips re-saving any step
        <= the latest existing one, so the resumed run's recovered
        progress would never persist.  Raises
        :class:`CheckpointCorruptionError` when manifested generations
        exist but none verifies (restart-looping into the same corrupt
        store helps nobody).  Legacy pre-manifest directories restore the
        newest step unverified, exactly as before.
        """
        steps = sorted(self.all_steps(), reverse=True)
        if not steps:
            return state_template, None
        abstract_items = jax.tree_util.tree_map(
            ocp.utils.to_shape_dtype_struct,
            self._state_items(state_template),
        )
        candidates, verify, rejected = self._restore_walk(steps, verify)
        if evict_failed:
            for step in rejected:  # torn generations: same wedge hazard
                self._delete_generation(step)
        for step in candidates:
            try:
                items = self._restore_items(step, abstract_items)
            except Exception as exc:  # noqa: BLE001 — torn data reads raise
                self._note_verify_failure(
                    step, f"restore failed: {type(exc).__name__}: {exc}",
                    None,
                )
                if evict_failed:
                    self._delete_generation(step)
                continue
            if verify and not self._verify_items(step, items):
                if evict_failed:
                    self._delete_generation(step)
                continue
            state = state_template.replace(
                step=items["state"]["step"],
                params=items["params"],
                opt_state=items["state"]["opt_state"],
                batch_stats=items["state"]["batch_stats"],
            )
            logger.info(
                "restored checkpoint step %d from %s%s",
                step, self.directory,
                "" if step == steps[0] else
                f" (fell back past {steps.index(step)} newer generation(s))",
            )
            return state, step
        raise self._corruption_error(steps)

    def restore_params(
        self,
        *,
        quantize_weights: Optional[str] = None,
        verify: bool = True,
    ):
        """Restore only the newest verified generation's ``params``.

        The serving path (``ddlt serve``) needs the weights but neither
        the optimizer state nor a TrainState template.  Post-PR
        generations store params as their own orbax item, so exactly the
        params bytes are read (an AdamW ``state`` item is ~2x the params
        — the old single-item layout forced reading all of it); legacy
        generations keep working through the full read.  Arrays come back
        host-resident (no target shardings); the engine places them onto
        its own mesh.

        ``quantize_weights="int8"`` materializes the quantized serving
        pytree directly from the f32 checkpoint (verification runs on the
        f32 arrays FIRST — quantization of corrupt weights would just
        launder the corruption into plausible-looking scales).

        Returns ``(params, step)``; ``(None, None)`` when no checkpoint.
        Fallback/corruption semantics match :meth:`restore`, minus the
        eviction: serving is a read-only consumer of a store some
        trainer owns.
        """
        if quantize_weights not in (None, "int8"):
            # validate BEFORE the restore: reading the params bytes just
            # to raise on a typo'd mode would waste the startup cost this
            # method exists to bound
            raise ValueError(
                f"unsupported quantize_weights {quantize_weights!r} "
                "(only 'int8')"
            )
        steps = sorted(self.all_steps(), reverse=True)
        if not steps:
            return None, None
        # read-only consumers (serve startup) never mutate the store —
        # eviction of failed generations is the owning trainer's call
        candidates, verify, _rejected = self._restore_walk(steps, verify)
        for step in candidates:
            try:
                if self._is_composite(step):
                    # params item only: the whole point of the split
                    restored = self._mgr.restore(
                        step,
                        args=ocp.args.Composite(
                            params=ocp.args.StandardRestore()
                        ),
                    )
                    items = {"params": restored["params"]}
                else:
                    # legacy: full read, params subtree kept
                    restored = self._mgr.restore(
                        step, args=ocp.args.StandardRestore()
                    )
                    items = {"params": restored["params"]}
            except Exception as exc:  # noqa: BLE001 — torn data reads raise
                self._note_verify_failure(
                    step, f"restore failed: {type(exc).__name__}: {exc}",
                    None,
                )
                continue
            if verify and not self._verify_items(step, items):
                continue
            params = items["params"]
            logger.info(
                "restored params of checkpoint step %d from %s",
                step, self.directory,
            )
            if quantize_weights is not None:
                from distributeddeeplearning_tpu.quant.calibrate import (
                    quantize_params,
                )

                params = quantize_params(params)
                logger.info("quantized restored params to int8 (absmax PTQ)")
            return params, step
        raise self._corruption_error(steps)

    def close(self) -> None:
        """Drain + commit pending manifests, then release the manager.
        Runs on every Trainer exit path (including the PreemptionError
        unwind) — a generation whose manifest never commits is a
        generation a restart cannot use."""
        try:
            self.wait()
        finally:
            self._mgr.close()
