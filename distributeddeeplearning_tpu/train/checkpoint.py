"""Sharded checkpoint / resume — done properly.

The reference's three partial mechanisms (SURVEY.md §5 "Checkpoint / resume"):
TF Estimator implicit rank-0 checkpoints (``resnet_main.py:140-158``), a buggy
PyTorch rank-0 epoch save (``imagenet_pytorch_horovod.py:257-260`` — NameError
off rank 0), and a full resume protocol stranded in dead code
(``PyTorch_hvd/src/imagenet_pytorch_horovod.py:62-72,133-144``: scan
checkpoint files backwards, broadcast resume epoch, load on rank 0, broadcast
state).

TPU-native replacement: orbax ``CheckpointManager`` writes the train-state
pytree **sharded** — every host writes its own param shards in parallel (no
rank-0 gather, no broadcast; the reference's whole protocol exists because
Horovod has no sharded storage), and restore places shards directly onto the
mesh from the target state's shardings.  ``latest_step()`` replaces the
backwards file scan; multihost coordination is orbax's, keyed off
``jax.process_index()``.
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from distributeddeeplearning_tpu.utils import faults as faults_mod
from distributeddeeplearning_tpu.utils.retry import retry_call

logger = logging.getLogger("ddlt.checkpoint")

PyTree = Any


class Checkpointer:
    """Epoch/step-granular sharded checkpointing of a ``TrainState``.

    Only array fields travel (step, params, opt_state, batch_stats); static
    fields (apply_fn, tx) are re-supplied by the restore template, which is
    also the source of target shardings.
    """

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int = 5,
        save_interval_steps: int = 1,
        async_save: bool = True,
    ):
        """``async_save`` (the TPU-native default): ``save()`` copies the
        state to host synchronously, then serializes/writes in a background
        thread — the step loop never stalls on storage.  Safe with donated
        train states because the device→host copy completes before save()
        returns.  ``wait()``/``close()`` drain pending writes."""
        self.directory = Path(directory).absolute()
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                create=True,
                enable_async_checkpointing=async_save,
            ),
        )

    @staticmethod
    def _arrays_of(state) -> PyTree:
        return {
            "step": state.step,
            "params": state.params,
            "opt_state": state.opt_state,
            "batch_stats": state.batch_stats,
        }

    def save(self, step: int, state) -> bool:
        """Save if the manager's policy wants this step. Returns True if saved.

        Transient storage errors are retried with bounded jittered backoff
        (``utils/retry.py``) before propagating — at pod scale a flaky
        gs:// write must not kill a run that could have checkpointed on the
        next attempt.  The ``checkpoint.save`` fault-injection site
        (``utils/faults.py``) exercises this path in tests.
        """
        arrays = self._arrays_of(state)

        def _save() -> bool:
            faults_mod.get_plan().maybe_io_error("checkpoint.save")
            return self._mgr.save(step, args=ocp.args.StandardSave(arrays))

        saved = retry_call(
            _save, retries=2, base_delay=0.2, max_delay=2.0,
            description=f"checkpoint save (step {step})",
        )
        if saved:
            logger.info("checkpoint saved at step %d -> %s", step, self.directory)
        return saved

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, state_template):
        """Restore the latest checkpoint INTO the template's shardings.

        Returns (state, step); (template, None) when nothing to restore —
        the deterministic-resume contract the vestigial reference code
        approximated with hvd.broadcast of the resume epoch.
        """
        step = self.latest_step()
        if step is None:
            return state_template, None
        abstract = jax.tree_util.tree_map(
            ocp.utils.to_shape_dtype_struct, self._arrays_of(state_template)
        )
        restored = self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))
        state = state_template.replace(
            step=restored["step"],
            params=restored["params"],
            opt_state=restored["opt_state"],
            batch_stats=restored["batch_stats"],
        )
        logger.info("restored checkpoint step %d from %s", step, self.directory)
        return state, step

    def restore_params(self, *, quantize_weights: Optional[str] = None):
        """Restore only the latest checkpoint's ``params`` subtree.

        The serving path (``ddlt serve``) needs the weights but neither the
        optimizer state nor a TrainState template — and must not have to
        reconstruct the training-time optimizer just to satisfy
        :meth:`restore`'s template.  Arrays come back host-resident (no
        target shardings); the engine places them onto its own mesh.

        ``quantize_weights="int8"`` materializes the quantized serving
        pytree directly from the f32 checkpoint: the matmul weights come
        back as int8 ``QTensor`` leaves (per-output-channel absmax scales,
        ``quant.calibrate.quantize_params``) without the caller ever
        holding a second full-precision copy past restore.  Use
        ``quant.calibrate.calibrate_params`` instead when a fidelity
        report over calibration prompts is wanted (``ddlt serve
        --quantize-weights int8 --calib-prompts N`` does).

        Cost note: the whole saved tree is read and the non-params subtrees
        dropped — for an AdamW checkpoint ~3x the bytes actually needed.
        A params-only partial restore needs ``ocp.PLACEHOLDER``, which this
        orbax version does not expose; startup-only cost, revisit when the
        pin moves.

        Returns ``(params, step)``; ``(None, None)`` when no checkpoint.
        """
        if quantize_weights not in (None, "int8"):
            # validate BEFORE the restore: reading the whole saved tree
            # (~3x the params bytes) just to raise on a typo'd mode
            # would waste the startup cost this method exists to bound
            raise ValueError(
                f"unsupported quantize_weights {quantize_weights!r} "
                "(only 'int8')"
            )
        step = self.latest_step()
        if step is None:
            return None, None
        # StandardRestore with no template restores as-saved; a bare
        # restore() would need a handler registry in a FRESH process (the
        # serve flow — the saving process's manager has one implicitly).
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore()
        )
        logger.info(
            "restored params of checkpoint step %d from %s",
            step, self.directory,
        )
        params = restored["params"]
        if quantize_weights is not None:
            from distributeddeeplearning_tpu.quant.calibrate import (
                quantize_params,
            )

            params = quantize_params(params)
            logger.info("quantized restored params to int8 (absmax PTQ)")
        return params, step

    def wait(self) -> None:
        """Drain pending async saves, retrying transient storage failures
        (same policy as :meth:`save`; the emergency-checkpoint path calls
        this synchronously inside the preemption grace window)."""

        def _wait() -> None:
            faults_mod.get_plan().maybe_io_error("checkpoint.wait")
            self._mgr.wait_until_finished()

        retry_call(
            _wait, retries=2, base_delay=0.2, max_delay=2.0,
            description="checkpoint wait",
        )

    def close(self) -> None:
        self._mgr.close()
