"""The training loop: epochs, metrics, TensorBoard, checkpoints, resume.

Role parity with both reference drivers — the PyTorch epoch loop
(``imagenet_pytorch_horovod.py:415-441``: train → rank-0 log_row/TB scalars →
validate → rank-0 checkpoint) and the TF Estimator train/evaluate flow
(``resnet_main.py:282-307``) — rebuilt around the jitted sharded step:

- the hot loop is `shard_batch → step_fn` only; metrics come back as
  replicated scalars already reduced across chips inside XLA (the
  reference needed a separate hvd.allreduce Metric class for this);
- primary-process discipline (`jax.process_index()==0`) for logging,
  TensorBoard and throughput reporting, matching the reference's
  ``hvd.rank()==0`` gates;
- checkpoint each epoch + resume-from-latest via orbax (every host
  participates in sharded save/restore — no rank-0 special case);
- end-of-run summary: total images/sec over the train wall-clock
  (``_log_summary`` parity, ``resnet_main.py:184-200``).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Callable, Dict, Iterator, Optional

import jax
import numpy as np

from distributeddeeplearning_tpu.obs import goodput as goodput_mod
from distributeddeeplearning_tpu.obs.goodput import GoodputLedger
from distributeddeeplearning_tpu.obs.registry import get_registry
from distributeddeeplearning_tpu.obs.trace import get_tracer
from distributeddeeplearning_tpu.parallel.distributed import is_primary
from distributeddeeplearning_tpu.parallel.sharding import shard_batch
from distributeddeeplearning_tpu.train.checkpoint import Checkpointer
from distributeddeeplearning_tpu.train.resilience import (
    AnomalyDetector,
    AnomalyError,
    PreemptionError,
    PreemptionGuard,
    StepWatchdog,
)
from distributeddeeplearning_tpu.utils import faults as faults_mod
from distributeddeeplearning_tpu.utils.retry import RateLimitedLogger, retry_call
from distributeddeeplearning_tpu.utils.throughput import ExamplesPerSecondTracker

logger = logging.getLogger("ddlt.train")


def jnp_add(a, b):
    return a + b


# One jitted dispatch per step for the metric accumulation instead of one
# per metric: per-dispatch latency is material on remote backends, and this
# runs every hot-loop step.  Module-level so the compiled executable is
# shared across Trainer instances and epochs.
_acc_add = jax.jit(lambda a, b: jax.tree.map(jnp_add, a, b))

Batch = Dict[str, np.ndarray]


class MetricsLog:
    """Append-only JSONL of per-epoch metric rows (AML ``run.log_row`` role).

    Rank-0 only; best-effort — a failing log write must never kill training.
    Writes go through the bounded-backoff retry helper (``utils/retry.py``)
    so transient storage errors don't silently eat rows; a row dropped after
    exhausting retries is logged once a minute at most (rate-limited), with
    a running ``dropped_rows`` count.
    GCS objects are immutable, so the gs:// path keeps the accumulated rows
    in memory (seeded once from an existing file on resume) and rewrites the
    small object per append — one upload, no per-epoch re-read.
    """

    def __init__(self, path: Optional[str]):
        self.path = path if (path and is_primary()) else None
        self._buffer = ""
        self.dropped_rows = 0
        # At most one "rows are being dropped" line a minute: the log
        # stream that still works must not be flooded by the one that
        # doesn't.
        self._drop_warn = RateLimitedLogger(logger.warning, min_interval_s=60.0)
        if self.path is None:
            return
        if self.path.startswith("gs://"):
            try:
                import tensorflow as tf

                if tf.io.gfile.exists(self.path):  # resume: keep prior rows
                    with tf.io.gfile.GFile(self.path, "r") as f:
                        self._buffer = f.read()
            except Exception as exc:  # pragma: no cover
                logger.warning("metrics log init failed (%s): %s", self.path, exc)
        else:
            import os

            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)

    def _write(self, line: str) -> None:
        faults_mod.get_plan().maybe_io_error("metrics")
        if self.path.startswith("gs://"):
            import tensorflow as tf

            with tf.io.gfile.GFile(self.path, "w") as f:
                f.write(self._buffer + line)
            self._buffer += line  # only on success: a retry resends the row
        else:
            with open(self.path, "a") as f:
                f.write(line)

    def append(self, row: Dict[str, Any]) -> None:
        if self.path is None:
            return
        import json

        line = json.dumps(row) + "\n"
        try:
            retry_call(
                self._write, line,
                retries=3, base_delay=0.05, max_delay=2.0,
                description=f"metrics append ({self.path})",
            )
        except Exception as exc:  # environment-specific storage failures
            self.dropped_rows += 1
            self._drop_warn(
                "metrics row dropped after retries (%s rows dropped so far, "
                "path %s): %s", self.dropped_rows, self.path, exc,
            )


class TensorBoardLogger:
    """Rank-0 TensorBoard scalar writer (tensorboardX parity,
    ``imagenet_pytorch_horovod.py:325-329,426-436``), via tf.summary."""

    def __init__(self, logdir: Optional[str]):
        self._writer = None
        if logdir and is_primary():
            import tensorflow as tf

            self._writer = tf.summary.create_file_writer(logdir)

    def scalars(self, tag_prefix: str, values: Dict[str, float], step: int) -> None:
        if self._writer is None:
            return
        import tensorflow as tf

        with self._writer.as_default():
            for name, value in values.items():
                tf.summary.scalar(f"{tag_prefix}/{name}", value, step=step)

    def flush(self) -> None:
        if self._writer is not None:
            self._writer.flush()


@dataclasses.dataclass
class TrainerConfig:
    epochs: int = 90
    steps_per_epoch: int = 0  # required: total_batches // world (resnet_main.py:246)
    eval_steps: Optional[int] = None  # None = drain the eval iterator
    global_batch_size: int = 0
    log_every: int = 100  # ExamplesPerSecondHook cadence (utils.py:23)
    checkpoint_dir: Optional[str] = None
    # Save inside the step loop every N true steps (in addition to the
    # epoch-end save).  At pod scale an epoch is ~1,250 steps; without this
    # a preemption re-does up to a full epoch.  Resume lands on the EXACT
    # step (see fit's step-indexed factory for replay-free data resume).
    checkpoint_every_steps: Optional[int] = None
    tensorboard_dir: Optional[str] = None
    resume: bool = True
    max_to_keep: int = 5
    # jax.profiler trace of a step window (primary process only): steps
    # [profile_start, profile_start + profile_steps) of the first epoch run.
    profile_dir: Optional[str] = None
    profile_start: int = 10  # skip compile + warmup steps
    profile_steps: int = 10
    # Per-epoch metric rows appended as JSONL (primary process only) — the
    # reference's AML run.log_row channel (imagenet_pytorch_horovod.py:424-435).
    # Local paths and gs:// both work (gs via tf.io.gfile when available).
    metrics_path: Optional[str] = None
    # Host->device input staging depth: a background thread decodes and
    # device_puts the next N train batches while the device executes the
    # current one (utils/prefetch.py).  0 disables (synchronous fetch).
    prefetch: int = 2
    # Multi-host eval buffers the local eval split in host RAM to agree on a
    # common batch count with ONE allgather (see Trainer.evaluate); this caps
    # how many batches may be buffered.  The default comfortably covers
    # ImageNet-val-sized eval splits; raise it deliberately for bigger eval
    # sets (or set eval_steps, which bounds the drain outright).
    eval_buffer_batches: int = 4096
    # ---- resilience knobs (train/resilience.py) ------------------------
    # Preemption guard: SIGTERM/SIGINT set a flag the hot loop checks each
    # step; on the next boundary a SYNCHRONOUS emergency checkpoint is
    # written and PreemptionError raised (exit code 75 — EX_TEMPFAIL —
    # under the workload runner, the signal a supervisor restarts on).
    # None = auto: enabled exactly when a checkpoint_dir is configured.
    preemption_guard: Optional[bool] = None
    # Preemption GRACE WINDOW (seconds from SIGTERM to the platform's
    # SIGKILL).  When set, the emergency-checkpoint path plumbs the
    # window's remainder into the storage retry layer as a hard deadline
    # (retry_call(deadline_s=...)) so backoff can never sleep past the
    # kill — a checkpoint that retries itself into the SIGKILL saves
    # nothing.  None = unknown window, retries stay wall-clock-unbounded.
    preemption_grace_s: Optional[float] = None
    # Host-side anomaly detection: abort (AnomalyError) after this many
    # CONSECUTIVE non-finite loss/grad-norm steps; isolated blips are
    # counted and tolerated.  None = off.  Costs one device sync per step;
    # pair it with build_train_step(skip_nonfinite=True) so the anomalous
    # update is also DISCARDED on device (otherwise detection sees the NaN
    # only after it has already poisoned the params).
    anomaly_max_consecutive: Optional[int] = None
    # On AnomalyError, restore the last checkpoint and keep training (at
    # most anomaly_max_rollbacks times per fit) instead of propagating.
    # Requires a checkpointer with at least one saved step and resume=True;
    # with a plain-iterator data stream the rollback replays from wherever
    # the stream happens to be (the step-indexed factory form is exact).
    anomaly_rollback: bool = False
    anomaly_max_rollbacks: int = 1
    # Hot-loop watchdog: if the gap between completed steps exceeds this
    # many seconds, dump all-thread stacks to stderr and hard-exit 70 (the
    # hung-collective killer on multi-host meshes — one dead host blocks
    # every other host INSIDE an XLA collective with no exception).  Arms
    # after the first step of each epoch (compile excluded) and disarms
    # across eval/checkpoint phases.  None = off.
    step_deadline_s: Optional[float] = None
    # ---- observability (obs/) ------------------------------------------
    # Append a metrics-registry snapshot (counters/gauges/histograms as
    # one JSONL row) here at every epoch boundary, primary process only.
    # Writes go through the retry layer + DDLT_FAULTS io_error hook, same
    # as the metrics log; append-only, so rows survive restarts.
    obs_metrics_path: Optional[str] = None
    # Goodput ledger (obs/goodput.py): classify 100% of the fit's wall
    # into named categories (productive/redone steps, compile, data
    # wait, checkpoint blocking, eval, recovery, other) and append one
    # restart-durable JSONL segment per fit incarnation here — the
    # stitched file is the GOODPUT artifact's evidence.  None (the
    # default) = disabled: the hot-loop mark calls reduce to one
    # attribute check (lint-pinned zero-sync either way).
    goodput_path: Optional[str] = None


def _drain_bounded(batches: Iterator, limit, cap: int) -> list:
    """Buffer up to ``limit`` batches, refusing to exceed ``cap`` — the
    multi-host eval drain's RAM guard (an eval split larger than expected
    must fail loudly, not swap the host)."""
    local: list = []
    for batch in batches:
        local.append(batch)
        if limit is not None and len(local) >= limit:
            break
        if len(local) > cap:
            raise RuntimeError(
                f"multi-host eval buffered more than eval_buffer_batches="
                f"{cap} batches on this host; set TrainerConfig.eval_steps "
                "to bound the eval pass, or raise eval_buffer_batches if "
                "the host has RAM for a larger eval split"
            )
    return local


@dataclasses.dataclass
class FitResult:
    epochs_run: int
    final_train_metrics: Dict[str, float]
    final_eval_metrics: Optional[Dict[str, float]]
    total_images: int
    train_wall_seconds: float
    # resilience accounting: non-finite steps whose update was skipped, and
    # checkpoint rollbacks taken by the anomaly handler during this fit
    anomalous_steps: int = 0
    rollbacks: int = 0

    @property
    def images_per_second(self) -> float:
        return self.total_images / max(self.train_wall_seconds, 1e-9)


class Trainer:
    # class-level fallback so a partially-constructed Trainer (tests
    # drive isolated paths via ``Trainer.__new__``) still has inert
    # ledger marks; __init__ always overrides with the configured one
    goodput = GoodputLedger(enabled=False)
    _flops_probed = True

    def __init__(
        self,
        mesh,
        train_step: Callable,
        *,
        eval_step: Optional[Callable] = None,
        config: TrainerConfig,
    ):
        if config.steps_per_epoch <= 0:
            raise ValueError("steps_per_epoch must be positive")
        self.mesh = mesh
        self.train_step = train_step
        self.eval_step = eval_step
        self.config = config
        self.tb = TensorBoardLogger(config.tensorboard_dir)
        self.metrics_log = MetricsLog(config.metrics_path)
        self.checkpointer = (
            Checkpointer(config.checkpoint_dir, max_to_keep=config.max_to_keep)
            if config.checkpoint_dir
            else None
        )
        # wall-clock goodput accounting (no-op marks unless goodput_path
        # is set); one ledger per Trainer, one SEGMENT per fit attempt
        self.goodput = GoodputLedger(config.goodput_path)
        self._flops_probed = False

    def fit(
        self,
        state,
        train_batches,
        eval_batches_factory: Optional[Callable[[], Iterator[Batch]]] = None,
    ) -> tuple:
        """Run the epoch loop; returns (final_state, FitResult).

        ``train_batches`` is either a batch iterator or a STEP-INDEXED
        factory ``f(start_step) -> Iterator`` (its first yield is the batch
        for true step ``start_step``).  The factory form is what makes
        mid-epoch resume exact: after restoring step k the factory is asked
        for the stream starting at k, so no batch repeats and no batch is
        skipped — replay-free for indexable pipelines (synthetic, raw
        cache).  A plain iterator resumes wherever the stream happens to be
        (the r03 behavior): correct for IID-shuffled repeat streams, but
        not bit-reproducible against an uninterrupted run.

        Resilience wiring (all opt-in via TrainerConfig; see
        ``train/resilience.py``): a PreemptionGuard converting SIGTERM into
        emergency-checkpoint + PreemptionError, an AnomalyDetector over
        per-step loss/grad-norm with optional rollback-to-last-checkpoint,
        a StepWatchdog deadline on hot-loop progress, and the
        ``DDLT_FAULTS`` injection hooks that exercise all of it in tests.
        """
        cfg = self.config
        plan = faults_mod.get_plan()
        factory = (
            train_batches
            if callable(train_batches) and not hasattr(train_batches, "__next__")
            else None
        )
        stream = None if factory is not None else train_batches

        use_guard = cfg.preemption_guard
        if use_guard is None:
            use_guard = self.checkpointer is not None
        guard = (
            PreemptionGuard(grace_s=cfg.preemption_grace_s).install()
            if use_guard
            else None
        )
        if plan and guard is None and any(
            s.kind == "preempt" for s in plan.specs
        ):
            logger.warning(
                "DDLT_FAULTS contains a preempt fault but the preemption "
                "guard is disabled (no checkpoint_dir?) — it will not fire"
            )
        detector = (
            AnomalyDetector(cfg.anomaly_max_consecutive)
            if cfg.anomaly_max_consecutive
            else None
        )
        watchdog = (
            StepWatchdog(cfg.step_deadline_s).start()
            if cfg.step_deadline_s
            else None
        )

        rollbacks = 0
        # HBM attribution (obs/ledger.py): the train state's leaves go on
        # the process ledger by semantic owner — params vs optimizer
        # state vs batch stats — read through ``self._obs_state`` (the
        # hot loop re-points it at the live state each step, so the
        # providers always see the CURRENT buffers, never a donated
        # generation).  Registered once per Trainer; the ledger holds the
        # Trainer weakly, so dropping the Trainer drops the accounting.
        self._obs_state = state
        self._register_hbm_owners()
        # the ledger becomes the PROCESS ledger for the fit so deep
        # layers (Checkpointer save/wait joins) can attach their detail
        # notes without plumbing; restored in the outer finally
        prev_ledger = (
            goodput_mod.set_ledger(self.goodput)
            if self.goodput.enabled else None
        )
        try:
            while True:
                # one ledger segment per fit attempt: begin() re-reads
                # prior segments so redone-step classification survives
                # both in-process rollbacks and cross-process restarts
                self.goodput.begin()
                start_epoch = 0
                start_step_in_epoch = 0
                restored_step = None
                if self.checkpointer is not None and cfg.resume:
                    state, restored_step = self.checkpointer.restore(state)
                    if restored_step is None:
                        # resumed nothing: a NEW run lineage — a reused
                        # ledger file's earlier segments must not mark
                        # this run's steps redone (obs/goodput.py)
                        self.goodput.fresh_start()
                    if restored_step is not None:
                        self.goodput.set_resumed_step(int(restored_step))
                        start_epoch = int(restored_step) // cfg.steps_per_epoch
                        start_step_in_epoch = (
                            int(restored_step) % cfg.steps_per_epoch
                        )
                        if is_primary():
                            logger.info(
                                "resuming from step %d (epoch %d, step %d "
                                "within it)",
                                restored_step, start_epoch,
                                start_step_in_epoch,
                            )
                else:
                    # no checkpointer / resume disabled: by construction
                    # nothing was resumed — new run lineage
                    self.goodput.fresh_start()
                batches = (
                    factory(int(restored_step or 0))
                    if factory is not None
                    else stream
                )
                if plan:
                    batches = plan.wrap_data(
                        batches, start_step=int(restored_step or 0)
                    )

                owned_prefetch = None
                if cfg.prefetch > 0:
                    from distributeddeeplearning_tpu.utils.prefetch import (
                        prefetch_to_device,
                    )

                    batches = owned_prefetch = prefetch_to_device(
                        batches, self.mesh, size=cfg.prefetch
                    )

                attempt_reason = "completed"
                try:
                    state, result = self._fit_inner(
                        state, batches, eval_batches_factory, start_epoch,
                        start_step_in_epoch, guard=guard, detector=detector,
                        watchdog=watchdog, plan=plan,
                    )
                    result.rollbacks = rollbacks
                    return state, result
                except AnomalyError as exc:
                    # the finally below cannot see a HANDLED exception
                    # (Python clears it once this block completes), so
                    # the rolled-back attempt's segment reason is stamped
                    # here, not from sys.exc_info()
                    attempt_reason = type(exc).__name__
                    if watchdog is not None:
                        # the rollback restore below is storage-bound, not
                        # hot-loop progress
                        watchdog.pause()
                    # The live (finite, thanks to the in-jit guard) state is
                    # the restore template for the rollback pass.
                    state = getattr(exc, "state", state)
                    # restore-eligibility is the VERIFIED step: rolling
                    # back into a corrupt generation would trade a
                    # diverging run for a bricked one
                    rollback_to = (
                        self.checkpointer.latest_verified_step()
                        if self.checkpointer is not None
                        else None
                    )
                    can_roll = (
                        cfg.anomaly_rollback
                        and cfg.resume
                        and rollback_to is not None
                        and rollbacks < cfg.anomaly_max_rollbacks
                    )
                    if not can_roll:
                        raise
                    rollbacks += 1
                    detector = AnomalyDetector(cfg.anomaly_max_consecutive)
                    get_tracer().event(
                        "resilience/rollback", cat="resilience",
                        step=exc.step,
                        to_step=rollback_to,
                    )
                    logger.warning(
                        "anomaly abort at step %s — rolling back to "
                        "checkpoint step %s (%d/%d rollbacks)",
                        exc.step, rollback_to,
                        rollbacks, cfg.anomaly_max_rollbacks,
                    )
                finally:
                    if owned_prefetch is not None:
                        # Stop the worker deterministically: without the
                        # close, the thread keeps decoding and device_put-ing
                        # past what fit consumed (and keeps running during
                        # error handling if the loop raised).
                        owned_prefetch.close()
                    if self.checkpointer is not None:
                        # Drain pending async saves even when the loop raised
                        # (data stream died, preemption signal, ...): the
                        # state snapshots were already copied to host, and
                        # finalizing them is the difference between resuming
                        # at the last checkpoint_every_steps boundary and
                        # losing it.
                        self.checkpointer.wait()
                        self.goodput.mark("checkpoint_blocking")
                    # close the attempt's ledger segment whatever happened
                    # — a PreemptionError unwinding here still appends its
                    # segment, which is what makes the ledger restart-
                    # durable (stitching charges the gap to recovery)
                    import sys as _sys

                    exc_type = _sys.exc_info()[0]
                    self.goodput.end(
                        reason=(
                            attempt_reason if exc_type is None
                            else exc_type.__name__
                        )
                    )
        finally:
            if watchdog is not None:
                watchdog.stop()
            if guard is not None:
                guard.uninstall()
            if prev_ledger is not None:
                goodput_mod.set_ledger(prev_ledger)

    def _maybe_measure_flops(self, state, batch) -> None:
        """Best-effort MFU numerator: XLA's own cost model for ONE train
        step (``utils/hardware.step_flops``), fed into the goodput
        ledger.  Only attempted when the ledger is on AND the chip has a
        known peak — off-TPU the MFU column is omitted anyway, so the
        AOT-lowering cost (a second trace) is never paid on the CPU test
        mesh.  The probe stops at ``.lower()`` — the UNOPTIMIZED cost
        analysis, which is what the model-FLOPs numerator wants anyway
        (PaLM MFU counts model FLOPs, not remat re-execution) — because
        ``.lower().compile()`` would run a SECOND full XLA compile that
        the jit dispatch cache never sees, doubling large-model startup.
        Any failure (a step builder without ``.lower``, a backend
        without a cost model) just leaves MFU omitted.
        """
        if self._flops_probed or not self.goodput.enabled:
            return
        self._flops_probed = True
        try:
            from distributeddeeplearning_tpu.utils.hardware import (
                peak_bf16_flops,
                step_flops,
            )

            if peak_bf16_flops() is None:
                return
            lowered = self.train_step.lower(state, batch)
            self.goodput.set_flops_per_step(step_flops(lowered))
        except Exception:  # MFU is an optional column, never a crash
            pass

    def _register_hbm_owners(self) -> None:
        """Register the train state's leaves on the process HBM ledger
        (obs/ledger.py) by semantic owner.  Idempotent per Trainer; the
        providers read ``self._obs_state``, which the hot loop re-points
        at the live state every step."""
        if getattr(self, "_hbm_registered", False):
            return
        self._hbm_registered = True
        from distributeddeeplearning_tpu.obs.ledger import get_ledger

        ledger = get_ledger()
        def _of_state(attr):
            def provider(trainer):
                return getattr(
                    getattr(trainer, "_obs_state", None), attr, None
                )
            return provider

        ledger.register("params", self, _of_state("params"))
        ledger.register("opt_state", self, _of_state("opt_state"))
        ledger.register("batch_stats", self, _of_state("batch_stats"))

    def _emergency_stop(self, step: int, state, watchdog, guard=None) -> None:
        """Preemption noticed at a step boundary: synchronous emergency
        checkpoint, then PreemptionError (→ exit 75 under the runner)."""
        if watchdog is not None:
            watchdog.pause()
        get_tracer().event(
            "resilience/preempted", cat="resilience", step=step
        )
        if self.checkpointer is not None:
            logger.warning(
                "preemption at step %d — writing emergency checkpoint", step
            )
            # save() copies device→host synchronously; wait() drains the
            # background write.  Both must land BEFORE the resumable exit:
            # the grace window is short and the checkpoint IS the recovery
            # — so the window's REMAINDER (re-read before each phase; save
            # may have consumed most of it) deadline-bounds the retry
            # backoff inside both (retry_call(deadline_s=...)).
            self.goodput.mark("other")
            with get_tracer().span(
                "train/emergency_checkpoint", cat="resilience", step=step
            ):
                self.checkpointer.save(
                    step, state,
                    deadline_s=(
                        guard.remaining_grace() if guard is not None else None
                    ),
                )
                self.checkpointer.wait(
                    deadline_s=(
                        guard.remaining_grace() if guard is not None else None
                    ),
                )
            self.goodput.mark("checkpoint_blocking")
            logger.warning("emergency checkpoint at step %d complete", step)
        raise PreemptionError(
            f"preempted at step {step} (emergency checkpoint "
            f"{'written' if self.checkpointer is not None else 'UNAVAILABLE'})",
            step=step,
        )

    def _fit_inner(
        self, state, train_batches, eval_batches_factory, start_epoch,
        start_step_in_epoch=0, *, guard=None, detector=None, watchdog=None,
        plan=None,
    ) -> tuple:
        cfg = self.config
        # one tracer for the whole fit: train-side spans (data wait / step
        # / checkpoint) land on the same timeline as serve and resilience
        # events.  Disabled (the default) = shared no-op spans, no clock
        # reads — the hot-loop lint pins the loop body sync-free either way.
        trace = get_tracer()
        # everything since the segment's begin() — checkpoint restore,
        # stream construction, prefetch spin-up — is restart/recovery
        # work, not training
        self.goodput.mark("recovery")
        tracker = ExamplesPerSecondTracker(
            global_batch_size=cfg.global_batch_size,
            every_n_steps=cfg.log_every,
            report=logger.info if is_primary() else (lambda *_: None),
        )
        tracker.begin()
        train_t0 = time.monotonic()
        total_images = 0
        train_metrics: Dict[str, float] = {}
        eval_metrics: Optional[Dict[str, float]] = None
        epoch = start_epoch
        profile_active = False
        profile_pending = cfg.profile_dir is not None and is_primary()
        total_steps = (
            (cfg.epochs - start_epoch) * cfg.steps_per_epoch
            - start_step_in_epoch
        )
        profile_start = cfg.profile_start
        if profile_pending and total_steps <= cfg.profile_start:
            logger.warning(
                "profile_dir set but the run has only %d steps (< profile_start"
                " %d) — starting the trace at step 0 instead",
                total_steps, cfg.profile_start,
            )
            profile_start = 0
        global_step = 0
        anomalous_total = 0

        for epoch in range(start_epoch, cfg.epochs):
            # Metrics accumulate ON DEVICE (one tiny async add per step);
            # the host only blocks every log_every steps and at epoch end.
            # A per-step float() sync would serialize dispatch and was the
            # gap between Trainer.fit and the benchmark harness throughput.
            acc = None
            epoch_t0 = time.monotonic()
            first_step = start_step_in_epoch if epoch == start_epoch else 0
            steps_this_epoch = cfg.steps_per_epoch - first_step
            anomalous_this_epoch = 0
            for step_i in range(first_step, cfg.steps_per_epoch):
                true_step = epoch * cfg.steps_per_epoch + step_i + 1
                if profile_pending and global_step >= profile_start:
                    jax.profiler.start_trace(cfg.profile_dir)
                    profile_active, profile_pending = True, False
                with trace.span("train/data_wait", step=true_step):
                    host_batch = next(train_batches)
                self.goodput.mark("data_wait")
                if plan:
                    host_batch = plan.poison_batch(true_step, host_batch)
                with trace.span("train/step", step=true_step):
                    batch = shard_batch(self.mesh, host_batch)
                    if global_step == 0:
                        # MFU numerator (no-op off-TPU / ledger-disabled)
                        self._maybe_measure_flops(state, batch)
                    state, metrics = self.train_step(state, batch)
                # re-point the HBM-ledger providers at the LIVE state
                # (the previous generation's buffers were just donated);
                # one attribute store — no sync, no walk
                self._obs_state = state
                anomalous = False
                if detector is not None:
                    # One host sync per step — the price of reacting to a
                    # diverging run before it wastes the rest of the epoch.
                    # (sync-ok markers: the analysis/host_sync.py checker
                    # waives exactly these lines against the trainer
                    # region's sync_budget in analysis/regions.py; any NEW
                    # per-step host sync — or a stale marker — fails
                    # `ddlt lint` and tier-1.)
                    loss_v = float(metrics["loss"])  # sync-ok: anomaly detector
                    gn = metrics.get("grad_norm")
                    flagged = metrics.get("anomalous")
                    try:
                        anomalous = detector.observe(
                            true_step, loss_v,
                            float(gn) if gn is not None else None,  # sync-ok: anomaly detector
                            flagged=(
                                bool(float(flagged))  # sync-ok: anomaly detector
                                if flagged is not None else None
                            ),
                        )
                    except AnomalyError as exc:
                        exc.state = state  # restore template for rollback
                        raise
                if anomalous:
                    # NaN metrics must not poison the epoch accumulator
                    # (the on-device update was already skipped when the
                    # step was built with skip_nonfinite=True).
                    anomalous_this_epoch += 1
                    anomalous_total += 1
                else:
                    acc = metrics if acc is None else _acc_add(acc, metrics)
                if (step_i + 1) % cfg.log_every == 0:
                    jax.block_until_ready(acc)
                # charge the step's wall (dispatch + the detector/log-
                # boundary syncs above) to compile / step_redone /
                # step_productive — the ledger classifies (obs/goodput.py)
                self.goodput.mark_step(true_step)
                tracker.after_step()
                if watchdog is not None:
                    watchdog.tick(true_step)
                total_images += cfg.global_batch_size
                global_step += 1
                if profile_active and global_step >= (
                    profile_start + cfg.profile_steps
                ):
                    jax.block_until_ready(acc)
                    jax.profiler.stop_trace()
                    profile_active = False
                    logger.info("profiler trace written to %s", cfg.profile_dir)
                if (
                    self.checkpointer is not None
                    and cfg.checkpoint_every_steps
                    and true_step % cfg.checkpoint_every_steps == 0
                ):
                    if watchdog is not None:
                        # storage-bound phase: save() can block on the
                        # previous in-flight async write (plus its retry
                        # backoff) — not hot-loop hang evidence.  The next
                        # step's tick re-arms.
                        watchdog.pause()
                    # save() copies device→host synchronously, so the next
                    # step's donation cannot clobber the saved buffers; the
                    # serialize/write happens on orbax's background thread.
                    with trace.span("train/checkpoint", step=true_step):
                        self.checkpointer.save(true_step, state)
                    self.goodput.mark("checkpoint_blocking")
                if guard is not None:
                    if plan:
                        plan.maybe_preempt(true_step, guard)
                    if guard.preempted():
                        self._emergency_stop(
                            true_step, state, watchdog, guard=guard
                        )
            if profile_active:
                # Run shorter than the window: close the trace on step work
                # only — eval/checkpoint/TB below must not pollute it.
                jax.block_until_ready(acc)
                jax.profiler.stop_trace()
                profile_active = False
                logger.info("profiler trace written to %s", cfg.profile_dir)
            if watchdog is not None:
                # Eval, TB, checkpoints below have unbounded (storage-
                # dependent) duration; the deadline re-arms at the next
                # epoch's first completed step.
                watchdog.pause()
            counted_steps = steps_this_epoch - anomalous_this_epoch
            train_metrics = (
                {k: float(v) / counted_steps for k, v in acc.items()}
                if acc is not None and counted_steps > 0
                else {}
            )
            if anomalous_this_epoch:
                train_metrics["anomalous_steps"] = float(anomalous_this_epoch)
            # train-phase wall of THIS epoch (the float() above synced):
            # excludes the eval/checkpoint below, so per-epoch throughput
            # rows are comparable across epochs.
            epoch_train_wall = time.monotonic() - epoch_t0
            if is_primary():
                logger.info(
                    "epoch %d/%d: %s",
                    epoch + 1,
                    cfg.epochs,
                    {k: round(v, 4) for k, v in train_metrics.items()},
                )
            self.tb.scalars("train", train_metrics, epoch)
            # epoch rollup so far (metric readback, logs, TB) is loop
            # bookkeeping, not training
            self.goodput.mark("other")

            if self.eval_step is not None and eval_batches_factory is not None:
                with trace.span("train/eval", epoch=epoch + 1):
                    eval_metrics = self.evaluate(
                        state, eval_batches_factory()
                    )
                self.goodput.mark("eval")
                if is_primary():
                    logger.info(
                        "epoch %d validation: %s",
                        epoch + 1,
                        {k: round(v, 4) for k, v in eval_metrics.items()},
                    )
                self.tb.scalars("val", eval_metrics, epoch)

            # run.log_row parity: one row per epoch with both metric sets
            row: Dict[str, Any] = {"epoch": epoch + 1}
            row.update({f"train_{k}": v for k, v in train_metrics.items()})
            if eval_metrics:
                row.update({f"val_{k}": v for k, v in eval_metrics.items()})
            row["images_per_second"] = (
                steps_this_epoch * cfg.global_batch_size
            ) / max(epoch_train_wall, 1e-9)
            if epoch == start_epoch:
                # The first epoch's wall includes train_step JIT compilation
                # (~20-40s on TPU); flag the row so nobody diffs it against
                # later epochs or the benchmark harness numbers.
                row["includes_compile"] = True
            self.metrics_log.append(row)

            # per-epoch rollup into the obs registry (never per step): the
            # same counters/gauges the serve path feeds, one process view
            reg = get_registry()
            reg.counter("train.steps").inc(steps_this_epoch)
            reg.counter("train.epochs").inc()
            if anomalous_this_epoch:
                reg.counter("train.anomalous_steps").inc(
                    anomalous_this_epoch
                )
            reg.gauge("train.images_per_second").set(
                row["images_per_second"]
            )
            if "loss" in train_metrics:
                reg.gauge("train.loss").set(train_metrics["loss"])
            reg.histogram("train.epoch_train_wall_s").record(
                epoch_train_wall
            )
            if cfg.obs_metrics_path and is_primary():
                reg.write_snapshot(cfg.obs_metrics_path, epoch=epoch + 1)

            if self.checkpointer is not None:
                self.goodput.mark("other")
                with trace.span(
                    "train/checkpoint", step=(epoch + 1) * cfg.steps_per_epoch
                ):
                    self.checkpointer.save(
                        (epoch + 1) * cfg.steps_per_epoch, state
                    )
                self.goodput.mark("checkpoint_blocking")

        wall = time.monotonic() - train_t0
        self.tb.flush()
        if self.checkpointer is not None:
            self.checkpointer.wait()
        result = FitResult(
            epochs_run=max(cfg.epochs - start_epoch, 0),
            final_train_metrics=train_metrics,
            final_eval_metrics=eval_metrics,
            total_images=total_images,
            train_wall_seconds=wall,
            anomalous_steps=anomalous_total,
        )
        if is_primary() and total_images:
            # _log_summary parity (resnet_main.py:184-200)
            logger.info("total images/sec: %.2f", result.images_per_second)
            logger.info("batch size: %d (global)", cfg.global_batch_size)
        return state, result

    def evaluate(self, state, eval_batches: Iterator[Batch]) -> Dict[str, float]:
        """Weighted-average eval metrics over a host-synchronized batch count.

        Per-host eval file shards can yield uneven batch counts; a host with
        extra batches would enter the eval-step collectives alone and hang
        the pod.  Hosts therefore agree ONCE per eval pass on a common batch
        count — each host counts its available batches up front (buffering
        them), the pod takes the minimum, and every host runs exactly that
        many steps with no further host round-trips.  Batches are weighted by
        size so ragged final batches do not bias top-1.
        """
        multi_host = jax.process_count() > 1
        limit = self.config.eval_steps
        if multi_host:
            from jax.experimental import multihost_utils

            # Drain (up to eval_steps) locally first: eval epochs are small
            # (ImageNet val = 50k images / pod) so buffering batch dicts of
            # host numpy arrays is cheap, and it turns N allgathers into 1.
            # The eval_buffer_batches cap keeps an unexpectedly large eval
            # split from silently eating host RAM — fail loudly instead.
            local = _drain_bounded(
                eval_batches, limit, self.config.eval_buffer_batches
            )
            common = int(
                multihost_utils.process_allgather(
                    np.asarray(len(local))
                ).min()
            )
            batches: Iterator[Batch] = iter(local[:common])
            limit = common
        else:
            batches = eval_batches
        # Size-weighted sums accumulate ON DEVICE (batch sizes are known on
        # the host, so the weights add no sync); the only host fetch is the
        # final per-metric float.  A per-batch float(v) here serialized
        # dispatch — ~100 ms/batch on tunneled backends — the same bug the
        # train loop's on-device accumulator fixed (r02).
        sums: Dict[str, jax.Array] = {}
        total_weight = 0
        steps = 0
        while True:
            if limit is not None and steps >= limit:
                break
            batch = next(batches, None)
            if batch is None:
                break
            batch_size = len(next(iter(batch.values())))
            metrics = self.eval_step(state, shard_batch(self.mesh, batch))
            for k, v in metrics.items():
                weighted = v * batch_size
                sums[k] = weighted if k not in sums else sums[k] + weighted
            total_weight += batch_size
            steps += 1
        if not sums or total_weight == 0:
            # zero batches OR only zero-length batches (empty host shards):
            # the old AverageMeter.avg returned 0.0 here; an empty dict is
            # the cleaner "no eval happened" signal callers already handle
            return {}
        return {k: float(v) / total_weight for k, v in sums.items()}
