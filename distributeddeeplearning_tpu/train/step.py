"""Jitted train/eval step builders — the heart of the DP runtime.

The reference's hot loop is `forward → loss → backward → per-gradient Horovod
allreduce (NCCL) → optimizer.step` driven from Python per batch
(``imagenet_pytorch_horovod.py:166-200``; TF Estimator equivalent
``resnet_main.py:282-284``).  TPU-native, the whole thing is ONE compiled XLA
program: the batch arrives sharded over the mesh's data axes, the gradient
all-reduce is inserted by XLA from sharding propagation (riding ICI, no
NCCL/MPI), and metrics reduce in the same program — zero host round-trips
per step beyond feeding data.

Step contract:
    train_step(state, batch) -> (new_state, metrics)   [state donated]
    eval_step(state, batch)  -> metrics
with ``batch = {"image"|"input": ..., "label": ...}`` sharded over (data,fsdp)
and metrics replicated fp32 scalars.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import optax

from distributeddeeplearning_tpu.parallel.sharding import (
    batch_sharding,
    param_shardings,
    replicated,
)

PyTree = Any
Metrics = Dict[str, jax.Array]


def cross_entropy_loss(
    logits: jax.Array, labels: jax.Array, *, label_smoothing: float = 0.0
) -> jax.Array:
    """Mean softmax cross-entropy with integer labels.

    Matches the reference's ``sparse_softmax_cross_entropy``
    (``resnet_main.py:96-101``) / ``nn.CrossEntropyLoss``
    (``imagenet_pytorch_horovod.py:180-182``).  Computed in fp32 regardless of
    the activation dtype.
    """
    logits = logits.astype(jnp.float32)
    if label_smoothing > 0.0:
        num_classes = logits.shape[-1]
        one_hot = optax.smooth_labels(
            jax.nn.one_hot(labels, num_classes), label_smoothing
        )
        return optax.softmax_cross_entropy(logits, one_hot).mean()
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def topk_correct(logits: jax.Array, labels: jax.Array, k: int) -> jax.Array:
    """Fraction of examples whose label is in the top-k logits — parity with
    ``accuracy(output, target, topk=(1,5))`` (``imagenet_pytorch_horovod.py:149-163``)."""
    k = min(k, logits.shape[-1])  # top-5 on a <5-class head degrades gracefully
    _, top = jax.lax.top_k(logits.astype(jnp.float32), k)
    hit = (top == labels[:, None]).any(axis=-1)
    return hit.mean()


def classification_metrics(logits: jax.Array, labels: jax.Array, loss: jax.Array) -> Metrics:
    return {
        "loss": loss.astype(jnp.float32),
        "top1": topk_correct(logits, labels, 1),
        "top5": topk_correct(logits, labels, 5),
    }


# Batch keys forwarded to the model as keyword inputs (transformer models
# take the padding mask alongside the token ids).
EXTRA_INPUT_KEYS = ("attention_mask", "token_type_ids")


def _cast_inputs(inputs: jax.Array, compute_dtype: jnp.dtype) -> jax.Array:
    """Cast float inputs to the compute dtype; integer inputs (token ids)
    pass through — bf16 cannot represent vocab-sized ids exactly."""
    if jnp.issubdtype(inputs.dtype, jnp.integer):
        return inputs
    return inputs.astype(compute_dtype)


def _forward(state, params, inputs, train: bool, rngs=None, extras=None,
             batch_stats=None):
    """Apply the model, handling BN batch_stats models and stat-free models.

    Returns (logits, new_batch_stats, aux_loss) where ``aux_loss`` is the
    summed ``moe_losses`` collection (0.0 for models without MoE layers) —
    the Switch-style load-balance terms sown by ``models.moe.MoeMlp``.

    ``batch_stats`` overrides ``state.batch_stats`` so microbatched callers
    (gradient accumulation) can thread stats updated by earlier microbatches.
    """
    from distributeddeeplearning_tpu.models.moe import MOE_LOSS_COLLECTION

    stats = state.batch_stats if batch_stats is None else batch_stats
    has_stats = bool(jax.tree_util.tree_leaves(stats))
    variables = {"params": params}
    kwargs = dict(extras or {})
    if rngs:
        kwargs["rngs"] = rngs
    if has_stats:
        variables["batch_stats"] = stats
    if train:
        mutable = [MOE_LOSS_COLLECTION] + (["batch_stats"] if has_stats else [])
        logits, new_vars = state.apply_fn(
            variables, inputs, train=True, mutable=mutable, **kwargs
        )
        aux = sum(
            jnp.sum(leaf)
            for leaf in jax.tree_util.tree_leaves(
                new_vars.get(MOE_LOSS_COLLECTION, {})
            )
        )
        new_stats = new_vars.get("batch_stats", stats)
        return logits, new_stats, jnp.asarray(aux, jnp.float32)
    kwargs.pop("rngs", None)
    logits = state.apply_fn(variables, inputs, train=False, **kwargs)
    return logits, stats, jnp.zeros((), jnp.float32)


def _state_shardings(mesh, state_example, rules, logical_axes):
    """Sharding tree matching a TrainState.

    Params follow the logical-axis rules (replicated for pure DP); the
    optimizer state mirrors the param layout wherever optax keeps a
    params-shaped buffer (momentum/Adam moments) — without this, FSDP/TP
    models would replicate fp32 optimizer moments on every chip, forfeiting
    the memory the sharding exists to save.  Scalars (step counts) and
    batch_stats replicate.
    """
    r_shard = replicated(mesh)
    p_shard = param_shardings(mesh, state_example.params, rules, logical_axes)
    p_treedef = jax.tree_util.tree_structure(state_example.params)

    def params_like(subtree) -> bool:
        return jax.tree_util.tree_structure(subtree) == p_treedef

    def opt_leaf(subtree):
        # graft the full param-sharding tree over params-shaped subtrees
        return p_shard if params_like(subtree) else r_shard

    opt_shardings = jax.tree_util.tree_map(
        opt_leaf, state_example.opt_state, is_leaf=params_like
    )
    return state_example.replace(
        step=r_shard,
        params=p_shard,
        opt_state=opt_shardings,
        batch_stats=jax.tree_util.tree_map(lambda _: r_shard, state_example.batch_stats),
    )


def build_train_step(
    mesh,
    state_example,
    *,
    compute_dtype: jnp.dtype = jnp.bfloat16,
    label_smoothing: float = 0.0,
    schedule: Optional[optax.Schedule] = None,
    rules=None,
    logical_axes: Optional[PyTree] = None,
    loss_fn: Callable = cross_entropy_loss,
    metrics_fn: Callable = classification_metrics,
    rng: Optional[jax.Array] = None,
    moe_aux_weight: float = 0.01,  # Switch Transformer's α
    accum_steps: int = 1,
    input_transform: Optional[Callable] = None,
    skip_nonfinite: bool = False,
) -> Callable:
    """Compile the full DP training step over ``mesh``.

    Sharding layout: batch over the (data, fsdp) axes; params via
    ``param_shardings`` (replicated for pure DP — the Horovod contract — or
    rule-sharded for fsdp/tp models).  ``state_example`` supplies the pytree
    structure for sharding construction; the returned function is jitted with
    the state donated, so steady-state HBM holds one copy of params+opt state.

    ``rng`` seeds per-step stochastic layers (dropout); each step folds the
    step counter in, so resume at step k reproduces step k's dropout mask.

    ``input_transform`` runs on the inputs INSIDE the compiled step, before
    the compute-dtype cast — the hook for preprocessing that should ride the
    TPU instead of the host (e.g. ``raw_cache.uint8_normalizer()`` casting
    raw uint8 pixels and subtracting channel means; XLA fuses it into the
    first layer's input chain).

    ``accum_steps`` > 1 microbatches the step: the global batch is split into
    ``accum_steps`` equal slices along the batch axis and a ``lax.scan``
    accumulates the mean gradient before a SINGLE optimizer update — the
    global-batch lever when per-chip memory caps the resident batch (the
    reference's only lever was per-GPU batch × world size).  Activation
    memory scales with the microbatch; parameter/optimizer memory is
    unchanged.  For stat-free models the update is bitwise the same math as
    one big batch (mean of per-microbatch mean-grads == full-batch mean
    grad); BatchNorm models see ``accum_steps`` sequential EMA updates of
    batch statistics over microbatch moments instead of one global-batch
    moment — the standard, documented deviation.

    ``skip_nonfinite`` arms the in-program anomaly guard (the resilience
    layer's device half; ``train/resilience.py`` holds the host half): when
    the loss or the global gradient norm is non-finite, the parameter /
    optimizer / batch-stats update is **discarded inside the compiled step**
    (``step`` still advances, so step accounting and resume stay exact) and
    the metrics gain ``grad_norm`` plus an ``anomalous`` 0/1 flag the
    Trainer's ``AnomalyDetector`` consumes.  Off by default: the extra
    select is cheap but not free, and perf-critical runs should compile the
    identical program they always did.
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    b_shard = batch_sharding(mesh)
    r_shard = replicated(mesh)
    state_shardings = _state_shardings(mesh, state_example, rules or [], logical_axes)
    base_rng = rng if rng is not None else jax.random.key(0)

    def step_fn(state, batch):
        inputs = batch.get("image", batch.get("input"))
        if input_transform is not None:
            inputs = input_transform(inputs)
        labels = batch["label"]
        extras = {k: batch[k] for k in EXTRA_INPUT_KEYS if k in batch}
        step_rng = jax.random.fold_in(base_rng, state.step)

        def compute_loss(params, stats, mb_inputs, mb_labels, mb_extras, rngs):
            logits, new_stats, aux = _forward(
                state,
                params,
                _cast_inputs(mb_inputs, compute_dtype),
                train=True,
                rngs=rngs,
                extras=mb_extras,
                batch_stats=stats,
            )
            loss = loss_fn(logits, mb_labels, label_smoothing=label_smoothing)
            loss = loss + moe_aux_weight * aux
            return loss, (logits, new_stats)

        grad_fn = jax.value_and_grad(compute_loss, has_aux=True)

        def guarded_update(grads, new_stats, loss):
            """Apply the update only when loss and grad norm are finite;
            step advances either way (resume/step accounting stay exact)."""
            grad_norm = optax.global_norm(grads)
            ok = jnp.isfinite(loss) & jnp.isfinite(grad_norm)
            cand = state.apply_gradients(grads, batch_stats=new_stats)
            skipped = state.replace(step=cand.step)
            selected = jax.tree_util.tree_map(
                lambda n, o: jnp.where(ok, n, o), cand, skipped
            )
            guard_metrics = {
                "grad_norm": grad_norm.astype(jnp.float32),
                "anomalous": (1.0 - ok.astype(jnp.float32)),
            }
            return selected, guard_metrics

        if accum_steps == 1:
            (loss, (logits, new_stats)), grads = grad_fn(
                state.params, state.batch_stats, inputs, labels, extras,
                {"dropout": step_rng},
            )
            guard_metrics = {}
            if skip_nonfinite:
                new_state, guard_metrics = guarded_update(
                    grads, new_stats, loss
                )
            else:
                new_state = state.apply_gradients(grads, batch_stats=new_stats)
            # Aux-head models (InceptionV3 aux_logits=True) return (main, aux);
            # metrics report on the main head only.
            main_logits = logits[0] if isinstance(logits, tuple) else logits
            metrics = metrics_fn(main_logits, labels, loss)
            metrics.update(guard_metrics)
        else:
            if inputs.shape[0] % accum_steps:
                raise ValueError(
                    f"global batch {inputs.shape[0]} not divisible by "
                    f"accum_steps={accum_steps}"
                )

            def split(x):
                # Interleaved split (row r -> microbatch r % accum_steps):
                # the batch axis is contiguously sharded over the data mesh
                # axes, so a contiguous [accum, B/accum] reshape would put
                # each microbatch on 1/accum of the devices and force a
                # resharding collective every scan iteration.  The strided
                # assignment keeps every microbatch spread over ALL devices
                # — each device scans over its own resident rows, zero data
                # movement — and the accumulated mean over the global batch
                # is identical either way.
                return x.reshape(
                    (x.shape[0] // accum_steps, accum_steps) + x.shape[1:]
                ).swapaxes(0, 1)

            micro = jax.tree_util.tree_map(
                split, {"inputs": inputs, "labels": labels, "extras": extras}
            )
            zero_grads = jax.tree_util.tree_map(
                lambda p: jnp.zeros_like(p, dtype=jnp.float32), state.params
            )

            def body(carry, xs):
                grads_acc, stats, i = carry
                rngs = {"dropout": jax.random.fold_in(step_rng, i)}
                (loss, (logits, stats)), grads = grad_fn(
                    state.params, stats, xs["inputs"], xs["labels"],
                    xs["extras"], rngs,
                )
                grads_acc = jax.tree_util.tree_map(
                    lambda a, g: a + g.astype(jnp.float32), grads_acc, grads
                )
                main_logits = logits[0] if isinstance(logits, tuple) else logits
                mb_metrics = metrics_fn(main_logits, xs["labels"], loss)
                return (grads_acc, stats, i + 1), mb_metrics

            (grads_sum, new_stats, _), metrics_stack = jax.lax.scan(
                body,
                (zero_grads, state.batch_stats, jnp.zeros((), jnp.int32)),
                micro,
            )
            inv = 1.0 / accum_steps
            grads = jax.tree_util.tree_map(
                lambda g, p: (g * inv).astype(p.dtype), grads_sum, state.params
            )
            metrics = jax.tree_util.tree_map(
                lambda m: m.mean(axis=0), metrics_stack
            )
            if skip_nonfinite:
                new_state, guard_metrics = guarded_update(
                    grads, new_stats, metrics["loss"]
                )
                metrics.update(guard_metrics)
            else:
                new_state = state.apply_gradients(grads, batch_stats=new_stats)
        if schedule is not None:
            metrics["lr"] = schedule(state.step).astype(jnp.float32)
        return new_state, metrics

    return jax.jit(
        step_fn,
        in_shardings=(state_shardings, b_shard),
        out_shardings=(state_shardings, r_shard),
        donate_argnums=(0,),
    )


def build_eval_step(
    mesh,
    state_example,
    *,
    compute_dtype: jnp.dtype = jnp.bfloat16,
    rules=None,
    logical_axes: Optional[PyTree] = None,
    loss_fn: Callable = cross_entropy_loss,
    metrics_fn: Callable = classification_metrics,
    input_transform: Optional[Callable] = None,
) -> Callable:
    """Compile the eval step: forward + loss/top1/top5, no state mutation
    (parity with ``validate`` at ``imagenet_pytorch_horovod.py:203-230`` and
    rank-0 ``model.evaluate`` at ``resnet_main.py:293-307`` — except here
    every chip participates instead of eval running on rank 0 only)."""
    b_shard = batch_sharding(mesh)
    r_shard = replicated(mesh)
    state_shardings = _state_shardings(mesh, state_example, rules or [], logical_axes)

    def step_fn(state, batch):
        inputs = batch.get("image", batch.get("input"))
        if input_transform is not None:
            inputs = input_transform(inputs)
        labels = batch["label"]
        extras = {k: batch[k] for k in EXTRA_INPUT_KEYS if k in batch}
        logits, _, _ = _forward(
            state,
            state.params,
            _cast_inputs(inputs, compute_dtype),
            train=False,
            extras=extras,
        )
        loss = loss_fn(logits, labels)
        return metrics_fn(logits, labels, loss)

    return jax.jit(
        step_fn,
        in_shardings=(state_shardings, b_shard),
        out_shardings=r_shard,
    )
