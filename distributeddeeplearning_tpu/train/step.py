"""Jitted train/eval step builders — the heart of the DP runtime.

The reference's hot loop is `forward → loss → backward → per-gradient Horovod
allreduce (NCCL) → optimizer.step` driven from Python per batch
(``imagenet_pytorch_horovod.py:166-200``; TF Estimator equivalent
``resnet_main.py:282-284``).  TPU-native, the whole thing is ONE compiled XLA
program: the batch arrives sharded over the mesh's data axes, the gradient
all-reduce is inserted by XLA from sharding propagation (riding ICI, no
NCCL/MPI), and metrics reduce in the same program — zero host round-trips
per step beyond feeding data.

Step contract:
    train_step(state, batch) -> (new_state, metrics)   [state donated]
    eval_step(state, batch)  -> metrics
with ``batch = {"image"|"input": ..., "label": ...}`` sharded over (data,fsdp)
and metrics replicated fp32 scalars.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import optax

from distributeddeeplearning_tpu.obs.attrib import tracked_jit as _tracked_jit
from distributeddeeplearning_tpu.parallel.sharding import (
    batch_sharding,
    param_shardings,
    replicated,
)

PyTree = Any
Metrics = Dict[str, jax.Array]

COMM_DTYPES = {None: None, "f32": None, "float32": None,
               "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16}


def cross_entropy_loss(
    logits: jax.Array, labels: jax.Array, *, label_smoothing: float = 0.0
) -> jax.Array:
    """Mean softmax cross-entropy with integer labels.

    Matches the reference's ``sparse_softmax_cross_entropy``
    (``resnet_main.py:96-101``) / ``nn.CrossEntropyLoss``
    (``imagenet_pytorch_horovod.py:180-182``).  Computed in fp32 regardless of
    the activation dtype.
    """
    logits = logits.astype(jnp.float32)
    if label_smoothing > 0.0:
        num_classes = logits.shape[-1]
        one_hot = optax.smooth_labels(
            jax.nn.one_hot(labels, num_classes), label_smoothing
        )
        return optax.softmax_cross_entropy(logits, one_hot).mean()
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def topk_correct(logits: jax.Array, labels: jax.Array, k: int) -> jax.Array:
    """Fraction of examples whose label is in the top-k logits — parity with
    ``accuracy(output, target, topk=(1,5))`` (``imagenet_pytorch_horovod.py:149-163``)."""
    k = min(k, logits.shape[-1])  # top-5 on a <5-class head degrades gracefully
    _, top = jax.lax.top_k(logits.astype(jnp.float32), k)
    hit = (top == labels[:, None]).any(axis=-1)
    return hit.mean()


def classification_metrics(logits: jax.Array, labels: jax.Array, loss: jax.Array) -> Metrics:
    return {
        "loss": loss.astype(jnp.float32),
        "top1": topk_correct(logits, labels, 1),
        "top5": topk_correct(logits, labels, 5),
    }


# Batch keys forwarded to the model as keyword inputs (transformer models
# take the padding mask alongside the token ids).
EXTRA_INPUT_KEYS = ("attention_mask", "token_type_ids")


def _cast_inputs(inputs: jax.Array, compute_dtype: jnp.dtype) -> jax.Array:
    """Cast float inputs to the compute dtype; integer inputs (token ids)
    pass through — bf16 cannot represent vocab-sized ids exactly."""
    if jnp.issubdtype(inputs.dtype, jnp.integer):
        return inputs
    return inputs.astype(compute_dtype)


def _forward(state, params, inputs, train: bool, rngs=None, extras=None,
             batch_stats=None):
    """Apply the model, handling BN batch_stats models and stat-free models.

    Returns (logits, new_batch_stats, aux_loss) where ``aux_loss`` is the
    summed ``moe_losses`` collection (0.0 for models without MoE layers) —
    the Switch-style load-balance terms sown by ``models.moe.MoeMlp``.

    ``batch_stats`` overrides ``state.batch_stats`` so microbatched callers
    (gradient accumulation) can thread stats updated by earlier microbatches.
    """
    from distributeddeeplearning_tpu.models.moe import MOE_LOSS_COLLECTION

    stats = state.batch_stats if batch_stats is None else batch_stats
    has_stats = bool(jax.tree_util.tree_leaves(stats))
    variables = {"params": params}
    kwargs = dict(extras or {})
    if rngs:
        kwargs["rngs"] = rngs
    if has_stats:
        variables["batch_stats"] = stats
    if train:
        mutable = [MOE_LOSS_COLLECTION] + (["batch_stats"] if has_stats else [])
        logits, new_vars = state.apply_fn(
            variables, inputs, train=True, mutable=mutable, **kwargs
        )
        aux = sum(
            jnp.sum(leaf)
            for leaf in jax.tree_util.tree_leaves(
                new_vars.get(MOE_LOSS_COLLECTION, {})
            )
        )
        new_stats = new_vars.get("batch_stats", stats)
        return logits, new_stats, jnp.asarray(aux, jnp.float32)
    kwargs.pop("rngs", None)
    logits = state.apply_fn(variables, inputs, train=False, **kwargs)
    return logits, stats, jnp.zeros((), jnp.float32)


def _state_shardings(mesh, state_example, rules, logical_axes):
    """Sharding tree matching a TrainState.

    Params follow the logical-axis rules (replicated for pure DP); the
    optimizer state mirrors the param layout wherever optax keeps a
    params-shaped buffer (momentum/Adam moments) — without this, FSDP/TP
    models would replicate fp32 optimizer moments on every chip, forfeiting
    the memory the sharding exists to save.  Scalars (step counts) and
    batch_stats replicate.
    """
    r_shard = replicated(mesh)
    p_shard = param_shardings(mesh, state_example.params, rules, logical_axes)
    p_treedef = jax.tree_util.tree_structure(state_example.params)

    def params_like(subtree) -> bool:
        return jax.tree_util.tree_structure(subtree) == p_treedef

    def opt_leaf(subtree):
        # graft the full param-sharding tree over params-shaped subtrees
        return p_shard if params_like(subtree) else r_shard

    opt_example = state_example.opt_state
    if isinstance(opt_example, dict) and set(opt_example) == {"base", "residual"}:
        # comm-overlap layout (parallel/comms.py): per-bucket flat shards
        # (bare tuples of 1-D arrays) stay physically sharded over the
        # data axes — an eval step built from a prepared state must not
        # force-replicate the distributed optimizer buffers it never reads
        opt_shardings = _comm_opt_shardings(mesh, opt_example)
    else:
        opt_shardings = jax.tree_util.tree_map(
            opt_leaf, opt_example, is_leaf=params_like
        )
    return state_example.replace(
        step=r_shard,
        params=p_shard,
        opt_state=opt_shardings,
        batch_stats=jax.tree_util.tree_map(lambda _: r_shard, state_example.batch_stats),
    )


def _comm_opt_shardings(mesh, opt_state):
    """Shardings for a comm-overlap ``{"base", "residual"}`` opt_state:
    per-bucket flat vectors (the WUS optimizer shards and the compression
    residual) over the data axes, everything else replicated — the bucket
    spec comes out of the partition-rule layout table (``comm/`` rules),
    not a hand-wired PartitionSpec."""
    from distributeddeeplearning_tpu.parallel import sharding as _layout

    r = replicated(mesh)
    s = _layout.resolve_shardings(
        mesh, {"bucket": None}, prefix="comm"
    )["bucket"]

    def is_bucket_tuple(x):
        return (
            type(x) is tuple and len(x) > 0
            and all(getattr(e, "ndim", None) == 1 for e in x)
        )

    base = jax.tree_util.tree_map(
        lambda x: tuple(s for _ in x) if is_bucket_tuple(x) else r,
        opt_state["base"], is_leaf=is_bucket_tuple,
    )
    return {
        "base": base,
        "residual": tuple(s for _ in opt_state["residual"]),
    }


def build_train_step(
    mesh,
    state_example,
    *,
    compute_dtype: jnp.dtype = jnp.bfloat16,
    label_smoothing: float = 0.0,
    schedule: Optional[optax.Schedule] = None,
    rules=None,
    logical_axes: Optional[PyTree] = None,
    loss_fn: Callable = cross_entropy_loss,
    metrics_fn: Callable = classification_metrics,
    rng: Optional[jax.Array] = None,
    moe_aux_weight: float = 0.01,  # Switch Transformer's α
    accum_steps: int = 1,
    input_transform: Optional[Callable] = None,
    skip_nonfinite: bool = False,
    comm_overlap: bool = False,
    bucket_mb: float = 4.0,
    comm_dtype: Optional[Any] = None,
    weight_update_sharding: bool = False,
    comm_skip: bool = False,
) -> Callable:
    """Compile the full DP training step over ``mesh``.

    Sharding layout: batch over the (data, fsdp) axes; params via
    ``param_shardings`` (replicated for pure DP — the Horovod contract — or
    rule-sharded for fsdp/tp models).  ``state_example`` supplies the pytree
    structure for sharding construction; the returned function is jitted with
    the state donated, so steady-state HBM holds one copy of params+opt state.

    ``rng`` seeds per-step stochastic layers (dropout); each step folds the
    step counter in, so resume at step k reproduces step k's dropout mask.

    ``input_transform`` runs on the inputs INSIDE the compiled step, before
    the compute-dtype cast — the hook for preprocessing that should ride the
    TPU instead of the host (e.g. ``raw_cache.uint8_normalizer()`` casting
    raw uint8 pixels and subtracting channel means; XLA fuses it into the
    first layer's input chain).

    ``accum_steps`` > 1 microbatches the step: the global batch is split into
    ``accum_steps`` equal slices along the batch axis and a ``lax.scan``
    accumulates the mean gradient before a SINGLE optimizer update — the
    global-batch lever when per-chip memory caps the resident batch (the
    reference's only lever was per-GPU batch × world size).  Activation
    memory scales with the microbatch; parameter/optimizer memory is
    unchanged.  For stat-free models the update is bitwise the same math as
    one big batch (mean of per-microbatch mean-grads == full-batch mean
    grad); BatchNorm models see ``accum_steps`` sequential EMA updates of
    batch statistics over microbatch moments instead of one global-batch
    moment — the standard, documented deviation.

    ``skip_nonfinite`` arms the in-program anomaly guard (the resilience
    layer's device half; ``train/resilience.py`` holds the host half): when
    the loss or the global gradient norm is non-finite, the parameter /
    optimizer / batch-stats update is **discarded inside the compiled step**
    (``step`` still advances, so step accounting and resume stay exact) and
    the metrics gain ``grad_norm`` plus an ``anomalous`` 0/1 flag the
    Trainer's ``AnomalyDetector`` consumes.  Off by default: the extra
    select is cheap but not free, and perf-critical runs should compile the
    identical program they always did.

    ``comm_overlap`` replaces the implicit post-backward GSPMD allreduce
    with the explicit schedule in ``parallel/comms.py``: gradients are
    flattened into fixed-size buckets (``bucket_mb``) and each bucket's
    reduce-scatter over the data axes is issued as soon as that
    microbatch's grads exist inside the accumulation scan — wire time
    overlaps the next microbatch's backward instead of serializing after
    it.  ``weight_update_sharding`` (ZeRO-style distributed optimizer for
    the replicated-params path) applies the optimizer to each chip's 1/N
    gradient shard only and all-gathers the updated params, cutting
    optimizer FLOPs and params-shaped optimizer HBM (momentum, Adam m/v)
    by the data-parallel degree; it assumes the optimizer transform is
    elementwise given (grads, state, params) — SGD/momentum/Adam qualify,
    ``optax.clip_by_global_norm`` does NOT (it would clip by the shard
    norm).  ``comm_dtype="bf16"`` halves wire bytes by compressing the
    reduce-scatter payload, with per-bucket f32 error-feedback residuals
    carried in the train state (and checkpointed) so the rounding error
    re-enters the next step's reduction instead of being lost.

    The comm_overlap path requires replicated params (pure DP — no
    ``rules``/``logical_axes``), and its returned step carries a
    ``prepare_state`` method that converts a fresh ``TrainState`` into the
    comm layout (flat-sharded optimizer buffers + residual slot) — call it
    once before the first step (and use the prepared state as the restore
    template).  ``comm_skip`` is a benchmarking-only debug knob that
    elides the collectives (numerics are garbage) so ``bench.py --comms``
    can price the compute-only step.
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    if comm_overlap:
        if rules or logical_axes is not None:
            raise ValueError(
                "comm_overlap is the explicit replicated-params (pure DP) "
                "schedule; FSDP/TP models keep the implicit GSPMD path "
                "(drop rules/logical_axes or comm_overlap)"
            )
        if comm_dtype not in COMM_DTYPES and comm_dtype is not jnp.bfloat16:
            raise ValueError(
                f"comm_dtype must be one of "
                f"{sorted(k for k in COMM_DTYPES if k)} or None, "
                f"got {comm_dtype!r}"
            )
        return _build_comm_overlap_step(
            mesh,
            state_example,
            compute_dtype=compute_dtype,
            label_smoothing=label_smoothing,
            schedule=schedule,
            loss_fn=loss_fn,
            metrics_fn=metrics_fn,
            rng=rng,
            moe_aux_weight=moe_aux_weight,
            accum_steps=accum_steps,
            input_transform=input_transform,
            skip_nonfinite=skip_nonfinite,
            bucket_mb=bucket_mb,
            comm_dtype=(
                jnp.bfloat16 if comm_dtype is jnp.bfloat16
                else COMM_DTYPES[comm_dtype]
            ),
            weight_update_sharding=weight_update_sharding,
            comm_skip=comm_skip,
        )
    if weight_update_sharding or comm_skip or comm_dtype not in (
        None, "f32", "float32"
    ):
        # silently dropping these would let an A/B run believe it measured
        # the explicit schedule while compiling the implicit one
        raise ValueError(
            "weight_update_sharding/comm_skip/comm_dtype require "
            "comm_overlap=True"
        )
    b_shard = batch_sharding(mesh)
    r_shard = replicated(mesh)
    state_shardings = _state_shardings(mesh, state_example, rules or [], logical_axes)
    base_rng = rng if rng is not None else jax.random.key(0)

    def step_fn(state, batch):
        inputs = batch.get("image", batch.get("input"))
        if input_transform is not None:
            inputs = input_transform(inputs)
        labels = batch["label"]
        extras = {k: batch[k] for k in EXTRA_INPUT_KEYS if k in batch}
        step_rng = jax.random.fold_in(base_rng, state.step)

        def compute_loss(params, stats, mb_inputs, mb_labels, mb_extras, rngs):
            logits, new_stats, aux = _forward(
                state,
                params,
                _cast_inputs(mb_inputs, compute_dtype),
                train=True,
                rngs=rngs,
                extras=mb_extras,
                batch_stats=stats,
            )
            loss = loss_fn(logits, mb_labels, label_smoothing=label_smoothing)
            loss = loss + moe_aux_weight * aux
            return loss, (logits, new_stats)

        grad_fn = jax.value_and_grad(compute_loss, has_aux=True)

        def guarded_update(grads, new_stats, loss):
            """Apply the update only when loss and grad norm are finite;
            step advances either way (resume/step accounting stay exact)."""
            grad_norm = optax.global_norm(grads)
            ok = jnp.isfinite(loss) & jnp.isfinite(grad_norm)
            cand = state.apply_gradients(grads, batch_stats=new_stats)
            skipped = state.replace(step=cand.step)
            selected = jax.tree_util.tree_map(
                lambda n, o: jnp.where(ok, n, o), cand, skipped
            )
            guard_metrics = {
                "grad_norm": grad_norm.astype(jnp.float32),
                "anomalous": (1.0 - ok.astype(jnp.float32)),
            }
            return selected, guard_metrics

        if accum_steps == 1:
            (loss, (logits, new_stats)), grads = grad_fn(
                state.params, state.batch_stats, inputs, labels, extras,
                {"dropout": step_rng},
            )
            guard_metrics = {}
            if skip_nonfinite:
                new_state, guard_metrics = guarded_update(
                    grads, new_stats, loss
                )
            else:
                new_state = state.apply_gradients(grads, batch_stats=new_stats)
            # Aux-head models (InceptionV3 aux_logits=True) return (main, aux);
            # metrics report on the main head only.
            main_logits = logits[0] if isinstance(logits, tuple) else logits
            metrics = metrics_fn(main_logits, labels, loss)
            metrics.update(guard_metrics)
        else:
            if inputs.shape[0] % accum_steps:
                raise ValueError(
                    f"global batch {inputs.shape[0]} not divisible by "
                    f"accum_steps={accum_steps}"
                )

            def split(x):
                # Interleaved split (row r -> microbatch r % accum_steps):
                # the batch axis is contiguously sharded over the data mesh
                # axes, so a contiguous [accum, B/accum] reshape would put
                # each microbatch on 1/accum of the devices and force a
                # resharding collective every scan iteration.  The strided
                # assignment keeps every microbatch spread over ALL devices
                # — each device scans over its own resident rows, zero data
                # movement — and the accumulated mean over the global batch
                # is identical either way.
                return x.reshape(
                    (x.shape[0] // accum_steps, accum_steps) + x.shape[1:]
                ).swapaxes(0, 1)

            micro = jax.tree_util.tree_map(
                split, {"inputs": inputs, "labels": labels, "extras": extras}
            )
            zero_grads = jax.tree_util.tree_map(
                lambda p: jnp.zeros_like(p, dtype=jnp.float32), state.params
            )

            def body(carry, xs):
                grads_acc, stats, i = carry
                rngs = {"dropout": jax.random.fold_in(step_rng, i)}
                (loss, (logits, stats)), grads = grad_fn(
                    state.params, stats, xs["inputs"], xs["labels"],
                    xs["extras"], rngs,
                )
                grads_acc = jax.tree_util.tree_map(
                    lambda a, g: a + g.astype(jnp.float32), grads_acc, grads
                )
                main_logits = logits[0] if isinstance(logits, tuple) else logits
                mb_metrics = metrics_fn(main_logits, xs["labels"], loss)
                return (grads_acc, stats, i + 1), mb_metrics

            (grads_sum, new_stats, _), metrics_stack = jax.lax.scan(
                body,
                (zero_grads, state.batch_stats, jnp.zeros((), jnp.int32)),
                micro,
            )
            inv = 1.0 / accum_steps
            grads = jax.tree_util.tree_map(
                lambda g, p: (g * inv).astype(p.dtype), grads_sum, state.params
            )
            metrics = jax.tree_util.tree_map(
                lambda m: m.mean(axis=0), metrics_stack
            )
            if skip_nonfinite:
                new_state, guard_metrics = guarded_update(
                    grads, new_stats, metrics["loss"]
                )
                metrics.update(guard_metrics)
            else:
                new_state = state.apply_gradients(grads, batch_stats=new_stats)
        if schedule is not None:
            metrics["lr"] = schedule(state.step).astype(jnp.float32)
        return new_state, metrics

    # attribution (obs/attrib.py): the train step's cost_analysis flops/
    # bytes are recorded at first compile and feed the MFU numerator,
    # the roofline denominator and the ATTRIB artifact
    return _tracked_jit("train.step.implicit", jax.jit(
        step_fn,
        in_shardings=(state_shardings, b_shard),
        out_shardings=(state_shardings, r_shard),
        donate_argnums=(0,),
    ))


class CommOverlapStep:
    """The compiled ``comm_overlap`` train step.

    Callable exactly like the plain jitted step (``step(state, batch)``,
    ``step.lower(...)``), plus the comm-layout plumbing callers need:
    ``prepare_state`` converts a fresh ``TrainState`` into the layout this
    step trains and checkpoints (flat-sharded optimizer buffers under
    weight-update sharding, the bf16 error-feedback residual slot), and
    ``wire_bytes()`` reports the analytic per-device bytes-on-wire model
    for the bench artifact.
    """

    def __init__(self, jitted, mesh, layout, *, comm_dtype,
                 weight_update_sharding, accum_steps):
        self._jitted = jitted
        self.mesh = mesh
        self.layout = layout
        self.comm_dtype = comm_dtype
        self.weight_update_sharding = weight_update_sharding
        self.accum_steps = accum_steps
        self.comm_overlap = True

    def __call__(self, state, batch):
        return self._jitted(state, batch)

    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)

    def prepare_state(self, state):
        from distributeddeeplearning_tpu.parallel import comms

        return comms.prepare_comm_state(
            self.mesh, state, self.layout,
            weight_update_sharding=self.weight_update_sharding,
            comm_dtype=self.comm_dtype,
        )

    def wire_bytes(self) -> Dict[str, int]:
        from distributeddeeplearning_tpu.parallel import comms

        return comms.ring_wire_bytes(
            self.layout, comm_dtype=self.comm_dtype,
            weight_update_sharding=self.weight_update_sharding,
            accum_steps=self.accum_steps,
        )


def _build_comm_overlap_step(
    mesh,
    state_example,
    *,
    compute_dtype,
    label_smoothing,
    schedule,
    loss_fn,
    metrics_fn,
    rng,
    moe_aux_weight,
    accum_steps,
    input_transform,
    skip_nonfinite,
    bucket_mb,
    comm_dtype,
    weight_update_sharding,
    comm_skip,
) -> CommOverlapStep:
    """The explicit-comms train step: shard_map over the data axes with
    bucketed reduce-scatter inside the accumulation scan, optional ZeRO
    weight-update sharding, optional bf16 wire compression with error
    feedback.  See ``build_train_step``'s docstring for semantics and
    ``parallel/comms.py`` for the collectives."""
    import types

    from jax import lax
    from jax.experimental.shard_map import shard_map

    from distributeddeeplearning_tpu.parallel import comms
    from distributeddeeplearning_tpu.parallel import sharding as _layout
    from distributeddeeplearning_tpu.parallel.mesh import (
        DATA_AXES,
        data_parallel_size,
    )

    n_shards = data_parallel_size(mesh)
    fsdp_size = mesh.shape["fsdp"]
    layout = comms.BucketLayout.for_tree(
        state_example.params,
        bucket_bytes=max(int(bucket_mb * 2**20), 4),
        shards=n_shards,
    )
    b_shard = batch_sharding(mesh)
    r_shard = replicated(mesh)
    shard_over_data = _layout.resolve_shardings(
        mesh, {"bucket": None}, prefix="comm"
    )["bucket"]
    p_treedef = jax.tree_util.tree_structure(state_example.params)
    base_rng = rng if rng is not None else jax.random.key(0)
    AX = DATA_AXES
    tx = state_example.tx
    apply_fn = state_example.apply_fn
    has_stats = bool(jax.tree_util.tree_leaves(state_example.batch_stats))
    # _forward only touches static attrs (apply_fn) when batch_stats is
    # passed explicitly; a namespace shim keeps the outer traced state out
    # of the shard_map body (its arrays enter as explicit arguments).
    fwd_shim = types.SimpleNamespace(apply_fn=apply_fn, batch_stats={})

    opt_shardings = comms.comm_opt_specs(
        state_example.opt_state, p_treedef, layout,
        weight_update_sharding=weight_update_sharding,
        spec_sharded=shard_over_data, spec_replicated=r_shard,
    )
    opt_specs = comms.comm_opt_specs(
        state_example.opt_state, p_treedef, layout,
        weight_update_sharding=weight_update_sharding,
        spec_sharded=_layout.data_spec(), spec_replicated=_layout.replicated_spec(),
    )
    n_buckets = layout.num_buckets
    residual_shardings = (
        tuple(shard_over_data for _ in range(n_buckets))
        if comm_dtype is not None else ()
    )
    residual_specs = (
        tuple(_layout.data_spec() for _ in range(n_buckets))
        if comm_dtype is not None else ()
    )
    state_shardings = state_example.replace(
        step=r_shard,
        params=jax.tree_util.tree_map(lambda _: r_shard, state_example.params),
        opt_state={"base": opt_shardings, "residual": residual_shardings},
        batch_stats=jax.tree_util.tree_map(
            lambda _: r_shard, state_example.batch_stats
        ),
    )

    def step_fn(state, batch):
        inputs = batch.get("image", batch.get("input"))
        if input_transform is not None:
            inputs = input_transform(inputs)
        labels = batch["label"]
        extras = {k: batch[k] for k in EXTRA_INPUT_KEYS if k in batch}
        if inputs.shape[0] % (n_shards * accum_steps):
            raise ValueError(
                f"global batch {inputs.shape[0]} not divisible by "
                f"data shards x accum_steps = {n_shards} x {accum_steps}"
            )
        step_rng = jax.random.fold_in(base_rng, state.step)
        parts = {"inputs": inputs, "labels": labels, "extras": extras}
        parts_spec = jax.tree_util.tree_map(
            lambda _: _layout.data_spec(), parts
        )

        def inner(params, opt_base, residuals, stats, key, data):
            dev = (
                lax.axis_index("data") * fsdp_size + lax.axis_index("fsdp")
            )

            def compute_loss(p, st, mb_inputs, mb_labels, mb_extras, rngs):
                logits, new_stats, aux = _forward(
                    fwd_shim, p, _cast_inputs(mb_inputs, compute_dtype),
                    train=True, rngs=rngs, extras=mb_extras, batch_stats=st,
                )
                loss = loss_fn(
                    logits, mb_labels, label_smoothing=label_smoothing
                )
                # Sown aux terms are global SUMS in the implicit path; the
                # local partial scales by the shard count so psum/N of the
                # gradients reproduces the same total.
                loss = loss + moe_aux_weight * aux * n_shards
                return loss, (logits, new_stats)

            grad_fn = jax.value_and_grad(compute_loss, has_aux=True)

            def scatter(grads, res):
                buckets = layout.to_buckets(grads)
                if comm_skip:
                    return tuple(
                        layout.shard_slice(b, dev) for b in buckets
                    ), res
                if comm_dtype is None:
                    shards, _ = comms.reduce_scatter_buckets(buckets, AX)
                    return shards, res
                return comms.reduce_scatter_buckets(
                    buckets, AX, comm_dtype=comm_dtype, residuals=res,
                    shards=n_shards,
                )

            def gather(shards):
                if comm_skip:  # timing-only: numerics are garbage
                    return jnp.concatenate(
                        [jnp.tile(s, n_shards) for s in shards]
                    )
                return comms.gather_flat(shards, AX)

            if accum_steps == 1:
                # straight value_and_grad — no scan wrapper, no zero
                # accumulator (same minimal-program contract as the
                # implicit path's accum_steps == 1 special case)
                rngs = {"dropout": jax.random.fold_in(key, dev)}
                (loss, (logits, new_stats)), grads = grad_fn(
                    params, stats, data["inputs"], data["labels"],
                    data["extras"], rngs,
                )
                g_shards, new_residuals = scatter(grads, residuals)
                main_logits = logits[0] if isinstance(logits, tuple) else logits
                local_metrics = metrics_fn(main_logits, data["labels"], loss)
            else:
                def split(x):
                    # strided split of the LOCAL rows: local row l lands in
                    # microbatch l % accum — with the batch contiguously
                    # sharded over devices this reproduces the implicit
                    # path's global strided microbatches device-for-device
                    return x.reshape(
                        (x.shape[0] // accum_steps, accum_steps) + x.shape[1:]
                    ).swapaxes(0, 1)

                micro = jax.tree_util.tree_map(split, data)
                zero_shards = tuple(
                    jnp.zeros((n // n_shards,), jnp.float32)
                    for n in layout.bucket_sizes
                )

                def body(carry, xs):
                    acc, res, st, i = carry
                    rngs = {
                        "dropout": jax.random.fold_in(
                            jax.random.fold_in(key, i), dev
                        )
                    }
                    (loss, (logits, st)), grads = grad_fn(
                        params, st, xs["inputs"], xs["labels"], xs["extras"],
                        rngs,
                    )
                    # the reduce-scatter of THIS microbatch's buckets sits
                    # before the next iteration's backward in the dataflow:
                    # async collective start/done overlaps the wire with
                    # that compute, and the scan accumulates 1/N-sized
                    # scattered shards instead of full gradient trees
                    shards, res = scatter(grads, res)
                    acc = tuple(a + s for a, s in zip(acc, shards))
                    main_logits = (
                        logits[0] if isinstance(logits, tuple) else logits
                    )
                    mb_metrics = metrics_fn(main_logits, xs["labels"], loss)
                    return (acc, res, st, i + 1), mb_metrics

                (g_shards, new_residuals, new_stats, _), mstack = lax.scan(
                    body,
                    (zero_shards, residuals, stats, jnp.zeros((), jnp.int32)),
                    micro,
                )
                local_metrics = jax.tree_util.tree_map(
                    lambda m: m.mean(axis=0), mstack
                )

            # psum_scatter summed over N shards; the implicit path's grads
            # are the global-batch mean — one exact power-of-two rescale
            # (when N and accum are powers of two) recovers it.
            scale = 1.0 / (n_shards * accum_steps)
            g_shards = tuple(s * scale for s in g_shards)

            if weight_update_sharding:
                # ZeRO: this chip updates only its 1/N flat param shard
                # (optimizer buffers live as per-bucket flat shards in
                # opt_base), then all-gathers the updated params.
                p_buckets = layout.to_buckets(params)
                p_shards = tuple(
                    layout.shard_slice(b, dev) for b in p_buckets
                )
                updates, new_opt = tx.update(g_shards, opt_base, p_shards)
                new_p_shards = optax.apply_updates(p_shards, updates)
                new_params = layout.from_flat(gather(new_p_shards))
            else:
                grads_tree = layout.from_flat(gather(g_shards))
                updates, new_opt = tx.update(grads_tree, opt_base, params)
                new_params = optax.apply_updates(params, updates)

            # ONE tree-level collective for metrics (+ BatchNorm stats,
            # which under shard_map are per-device moments — averaged here,
            # the reference's per-GPU-BN semantics rather than GSPMD's
            # global-batch BN).
            payload = {"metrics": local_metrics}
            if has_stats:
                payload["stats"] = new_stats
            reduced = payload if comm_skip else lax.pmean(payload, AX)
            metrics = dict(reduced["metrics"])
            out_stats = reduced["stats"] if has_stats else new_stats

            if skip_nonfinite:
                sq = sum(
                    jnp.sum(jnp.square(s)).astype(jnp.float32)
                    for s in g_shards
                )
                grad_norm = jnp.sqrt(sq if comm_skip else lax.psum(sq, AX))
                ok = jnp.isfinite(metrics["loss"]) & jnp.isfinite(grad_norm)

                def keep(new, old):
                    return jax.tree_util.tree_map(
                        lambda a, b: jnp.where(ok, a, b), new, old
                    )

                new_params = keep(new_params, params)
                new_opt = keep(new_opt, opt_base)
                out_stats = keep(out_stats, stats)
                if comm_dtype is not None:
                    new_residuals = keep(new_residuals, residuals)
                metrics["grad_norm"] = grad_norm.astype(jnp.float32)
                metrics["anomalous"] = 1.0 - ok.astype(jnp.float32)

            return new_params, new_opt, new_residuals, out_stats, metrics

        inner_sm = shard_map(
            inner,
            mesh=mesh,
            in_specs=(
                _layout.replicated_spec(), opt_specs, residual_specs,
                _layout.replicated_spec(), _layout.replicated_spec(),
                parts_spec,
            ),
            out_specs=(
                _layout.replicated_spec(), opt_specs, residual_specs,
                _layout.replicated_spec(), _layout.replicated_spec(),
            ),
            check_rep=False,
        )
        new_params, new_opt, new_res, new_stats, metrics = inner_sm(
            state.params, state.opt_state["base"], state.opt_state["residual"],
            state.batch_stats, step_rng, parts,
        )
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            opt_state={"base": new_opt, "residual": new_res},
            batch_stats=new_stats,
        )
        if schedule is not None:
            metrics["lr"] = schedule(state.step).astype(jnp.float32)
        return new_state, metrics

    jitted = _tracked_jit("train.step.comm_overlap", jax.jit(
        step_fn,
        in_shardings=(state_shardings, b_shard),
        out_shardings=(state_shardings, r_shard),
        donate_argnums=(0,),
    ))
    return CommOverlapStep(
        jitted, mesh, layout, comm_dtype=comm_dtype,
        weight_update_sharding=weight_update_sharding,
        accum_steps=accum_steps,
    )


def build_eval_step(
    mesh,
    state_example,
    *,
    compute_dtype: jnp.dtype = jnp.bfloat16,
    rules=None,
    logical_axes: Optional[PyTree] = None,
    loss_fn: Callable = cross_entropy_loss,
    metrics_fn: Callable = classification_metrics,
    input_transform: Optional[Callable] = None,
) -> Callable:
    """Compile the eval step: forward + loss/top1/top5, no state mutation
    (parity with ``validate`` at ``imagenet_pytorch_horovod.py:203-230`` and
    rank-0 ``model.evaluate`` at ``resnet_main.py:293-307`` — except here
    every chip participates instead of eval running on rank 0 only)."""
    b_shard = batch_sharding(mesh)
    r_shard = replicated(mesh)
    state_shardings = _state_shardings(mesh, state_example, rules or [], logical_axes)

    def step_fn(state, batch):
        inputs = batch.get("image", batch.get("input"))
        if input_transform is not None:
            inputs = input_transform(inputs)
        labels = batch["label"]
        extras = {k: batch[k] for k in EXTRA_INPUT_KEYS if k in batch}
        logits, _, _ = _forward(
            state,
            state.params,
            _cast_inputs(inputs, compute_dtype),
            train=False,
            extras=extras,
        )
        loss = loss_fn(logits, labels)
        return metrics_fn(logits, labels, loss)

    return _tracked_jit("train.step.eval", jax.jit(
        step_fn,
        in_shardings=(state_shardings, b_shard),
        out_shardings=r_shard,
    ))
