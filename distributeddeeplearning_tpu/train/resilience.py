"""Fault detection and reaction: preemption guard, anomaly detector,
step watchdog, and the restart supervisor.

The framework could already *resume* to the exact step (orbax checkpoints +
the Trainer's step-indexed factory contract) and *recreate* a preempted pod
(``TpuPod.recreate``) — but nothing detected a fault or reacted to one.
This module is the reaction layer; :mod:`..utils.faults` is how every path
in it gets exercised on CPU in tier-1 tests.

Exit-code contract (what a supervisor — ``ddlt train --max-restarts``, a
k8s restart policy, the control-plane retry loop — keys off):

- ``RESUMABLE_EXIT_CODE`` (75, BSD ``EX_TEMPFAIL``): the run checkpointed
  its exact step and asks to be restarted — emitted on preemption after
  the emergency checkpoint lands.
- ``WATCHDOG_EXIT_CODE`` (70, ``EX_SOFTWARE``): a hot-loop step blew its
  deadline (hung collective, dead remote host); all-thread stacks were
  dumped to stderr first.  Restarting may help; the stacks say why.
"""

from __future__ import annotations

import faulthandler
import logging
import math
import os
import signal
import sys
import threading
import time
from typing import Callable, Optional, Tuple

from distributeddeeplearning_tpu.obs.recorder import get_recorder
from distributeddeeplearning_tpu.obs.trace import get_tracer

logger = logging.getLogger("ddlt.resilience")

RESUMABLE_EXIT_CODE = 75  # EX_TEMPFAIL: checkpointed, restart me
WATCHDOG_EXIT_CODE = 70   # EX_SOFTWARE: step deadline blown, stacks dumped


class RestartableError(RuntimeError):
    """A failure after which restart-from-latest-checkpoint is the fix."""

    def __init__(self, msg: str, *, step: Optional[int] = None):
        super().__init__(msg)
        self.step = step


class PreemptionError(RestartableError):
    """Raised by the train loop AFTER the emergency checkpoint landed."""


class AnomalyError(RestartableError):
    """Too many consecutive non-finite steps — the model is diverging."""

    def __init__(self, msg: str, *, step: Optional[int] = None,
                 consecutive: int = 0):
        super().__init__(msg, step=step)
        self.consecutive = consecutive


class PreemptionGuard:
    """SIGTERM/SIGINT → a flag the hot loop checks each step.

    TPU preemptions deliver SIGTERM with a short grace window; an unhandled
    one kills the process mid-step and loses everything since the last
    periodic checkpoint.  The guard converts the signal into cooperative
    shutdown: the handler only sets a flag (async-signal-safe), the step
    loop notices it at the next boundary, writes a **synchronous** emergency
    checkpoint, and raises :class:`PreemptionError` so the process can exit
    with :data:`RESUMABLE_EXIT_CODE`.

    A second SIGINT falls through to the previous handler (double Ctrl-C
    still kills an interactive run immediately).

    ``grace_s`` is the preemption GRACE WINDOW: how long after the signal
    the platform waits before SIGKILL.  The guard stamps the signal's
    arrival time, and :meth:`remaining_grace` reports what is left of the
    window — the emergency-checkpoint path plumbs that remainder into the
    storage retry layer (``retry_call(deadline_s=...)``) so backoff can
    never sleep past the kill.  ``None`` = unknown window (no deadline
    plumbed; the old wall-clock-unbounded behavior).
    """

    def __init__(
        self,
        signals: Tuple[int, ...] = (signal.SIGTERM, signal.SIGINT),
        *,
        grace_s: Optional[float] = None,
    ):
        if grace_s is not None and grace_s <= 0:
            raise ValueError(f"grace_s must be > 0, got {grace_s}")
        self.signals = signals
        self.grace_s = grace_s
        self.triggered_at: Optional[float] = None
        self._flag = threading.Event()
        self.reason: Optional[str] = None
        self._previous: dict = {}
        self.installed = False

    def install(self) -> "PreemptionGuard":
        """Install handlers; no-op off the main thread (signal.signal would
        raise there — embedding callers just lose signal coverage, and
        injected preemptions still work via :meth:`trigger`)."""
        if self.installed:
            return self
        if threading.current_thread() is not threading.main_thread():
            logger.warning(
                "PreemptionGuard: not on the main thread; signal handlers "
                "not installed (injected preemptions still honored)"
            )
            return self
        for sig in self.signals:
            self._previous[sig] = signal.signal(sig, self._handle)
        self.installed = True
        return self

    def uninstall(self) -> None:
        for sig, prev in self._previous.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):  # non-main thread / exotic prev
                pass
        self._previous.clear()
        self.installed = False

    def _handle(self, signum, frame) -> None:
        if self._flag.is_set() and signum == signal.SIGINT:
            # Second Ctrl-C: the operator means it.
            prev = self._previous.get(signum)
            if callable(prev):
                prev(signum, frame)
            else:
                raise KeyboardInterrupt
        self.reason = f"signal {signal.Signals(signum).name}"
        if self.triggered_at is None:
            # arm the grace clock at the FIRST signal (time.monotonic is
            # async-signal-safe: a C call, no Python locks)
            self.triggered_at = time.monotonic()
        self._flag.set()
        get_tracer().event(
            "resilience/preemption_signal", cat="resilience",
            reason=self.reason,
        )

    def trigger(self, reason: str = "triggered") -> None:
        """Programmatic preemption (fault injection, tests)."""
        self.reason = reason
        if self.triggered_at is None:
            self.triggered_at = time.monotonic()
        self._flag.set()
        get_tracer().event(
            "resilience/preemption_signal", cat="resilience", reason=reason
        )

    def preempted(self) -> bool:
        return self._flag.is_set()

    def remaining_grace(self) -> Optional[float]:
        """Seconds left of the preemption grace window, floored at 0 —
        the deadline the emergency checkpoint's retries must fit inside.
        ``None`` when no window is configured or no signal has arrived."""
        if self.grace_s is None or self.triggered_at is None:
            return None
        return max(0.0, self.grace_s - (time.monotonic() - self.triggered_at))

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


class AnomalyDetector:
    """Count non-finite loss/grad-norm steps; abort on a consecutive run.

    The jitted step (``build_train_step(skip_nonfinite=True)``) already
    *skips* the poisoned update on-device; this host-side detector decides
    whether the run is still healthy: isolated blips are counted and
    tolerated, ``max_consecutive`` anomalous steps in a row raise
    :class:`AnomalyError` (which the Trainer can answer with a rollback to
    the last checkpoint, or a supervisor with a restart).
    """

    def __init__(self, max_consecutive: int = 3):
        if max_consecutive < 1:
            raise ValueError(
                f"max_consecutive must be >= 1, got {max_consecutive}"
            )
        self.max_consecutive = max_consecutive
        self.total = 0
        self.consecutive = 0

    def observe(
        self,
        step: int,
        loss: float,
        grad_norm: Optional[float] = None,
        flagged: Optional[bool] = None,
    ) -> bool:
        """Record one step's health; returns True when the step is anomalous.

        ``flagged`` is the step's own non-finite verdict when the jitted
        guard computed one; otherwise finiteness of ``loss``/``grad_norm``
        decides.
        """
        anomalous = bool(flagged) if flagged is not None else (
            not math.isfinite(loss)
            or (grad_norm is not None and not math.isfinite(grad_norm))
        )
        if not anomalous:
            self.consecutive = 0
            return False
        self.total += 1
        self.consecutive += 1
        # an instant event on the obs timeline, not just a stderr line:
        # anomaly trips line up against the steps/checkpoints around them
        get_tracer().event(
            "resilience/anomalous_step", cat="resilience", step=step,
            loss=repr(loss), consecutive=self.consecutive,
        )
        logger.warning(
            "anomalous step %d (loss=%s, grad_norm=%s): update skipped "
            "(%d consecutive, %d total)",
            step, loss, grad_norm, self.consecutive, self.total,
        )
        if self.consecutive >= self.max_consecutive:
            get_tracer().event(
                "resilience/anomaly_abort", cat="resilience", step=step,
                consecutive=self.consecutive,
            )
            raise AnomalyError(
                f"{self.consecutive} consecutive non-finite steps "
                f"(last: step {step}, loss={loss})",
                step=step, consecutive=self.consecutive,
            )
        return True


def dump_all_stacks(out=None) -> None:
    """Write every thread's Python stack to ``out`` (default stderr).

    The one artifact that explains a hung collective: which thread sits in
    which blocking call on THIS host when the deadline blew.
    """
    out = out if out is not None else sys.stderr
    try:
        faulthandler.dump_traceback(file=out, all_threads=True)
    except Exception:  # out may be a text-only buffer without fileno
        import traceback

        frames = sys._current_frames()
        for tid, frame in frames.items():
            out.write(f"\n--- thread {tid} ---\n")
            out.write("".join(traceback.format_stack(frame)))
    try:
        out.flush()
    except Exception:
        pass


class StepWatchdog:
    """Background deadline on hot-loop progress — the hung-collective killer.

    On a multi-host mesh one dead host leaves every other host blocked
    *inside* an XLA collective: no exception, no log line, the job burns
    budget until an outer timeout.  The watchdog thread fires when the gap
    between ``tick()`` calls exceeds ``deadline_s``: it dumps all-thread
    stacks and (by default) hard-exits with :data:`WATCHDOG_EXIT_CODE` so a
    supervisor restarts the run — ``on_timeout`` overrides the exit for
    embedding/tests.

    The watchdog arms on the FIRST ``tick()``: step 0 includes XLA
    compilation, whose duration has nothing to do with the steady-state
    deadline.  ``pause()`` disarms across known-slow phases (eval,
    epoch-end checkpoints); the next ``tick()`` re-arms.
    """

    def __init__(
        self,
        deadline_s: float,
        *,
        on_timeout: Optional[Callable[[], None]] = None,
        poll_s: Optional[float] = None,
        stream=None,
    ):
        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        self.deadline_s = deadline_s
        self.on_timeout = on_timeout
        self._poll_s = poll_s if poll_s is not None else min(deadline_s / 4, 1.0)
        self._stream = stream
        self._last_tick: Optional[float] = None
        self._last_step: Optional[int] = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.fired = False

    def start(self) -> "StepWatchdog":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._watch, name="ddlt-step-watchdog", daemon=True
            )
            self._thread.start()
        return self

    def tick(self, step: Optional[int] = None) -> None:
        """A step completed; reset (and arm) the deadline.  ``step`` gives
        the timeout report (and its trace event) the last step that made
        progress — the first thing a hang post-mortem asks."""
        with self._lock:
            self._last_tick = time.monotonic()
            if step is not None:
                self._last_step = step

    def pause(self) -> None:
        """Disarm until the next tick (eval, checkpoint, epoch boundary)."""
        with self._lock:
            self._last_tick = None

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self._poll_s * 4)
            self._thread = None

    def _watch(self) -> None:
        while not self._stop.wait(self._poll_s):
            with self._lock:
                last = self._last_tick
            if last is None:
                continue
            elapsed = time.monotonic() - last
            if elapsed <= self.deadline_s:
                continue
            self.fired = True
            with self._lock:
                last_step = self._last_step
            # timeline first, stderr second: the trace event carries the
            # last-progressed step + timestamps so the hang shows up ON
            # the exported timeline next to whatever it was waiting on
            get_tracer().event(
                "resilience/watchdog_fired", cat="resilience",
                step=last_step, stalled_s=round(elapsed, 3),
                deadline_s=self.deadline_s,
            )
            # freeze the flight recorder BEFORE the stack dump: the ring
            # holds the last spans/events/metric deltas leading into the
            # stall — the first thing the post-mortem wants next to the
            # stacks (a fleet worker's supervisor collects the dump list)
            get_recorder().dump(
                "watchdog_fired", step=last_step,
                stalled_s=round(elapsed, 3), deadline_s=self.deadline_s,
            )
            stream = self._stream if self._stream is not None else sys.stderr
            print(
                f"ddlt watchdog: no step progress for {elapsed:.1f}s "
                f"since step {last_step} "
                f"(deadline {self.deadline_s}s) — dumping all thread stacks",
                file=stream,
            )
            dump_all_stacks(stream)
            if self.on_timeout is not None:
                self.on_timeout()
                # custom handler chose to keep the process: disarm so a
                # still-hung loop doesn't re-fire every poll interval
                with self._lock:
                    self._last_tick = None
                continue
            os._exit(WATCHDOG_EXIT_CODE)

    def __enter__(self) -> "StepWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def supervise(
    fn: Callable[[int], object],
    *,
    max_restarts: int = 0,
    restart_on: Tuple[type, ...] = (RestartableError,),
    on_restart: Optional[Callable[[int, BaseException], None]] = None,
    ledger_path: Optional[str] = None,
):
    """In-process restart loop: call ``fn(attempt)``, restarting on
    restartable failures up to ``max_restarts`` times.

    This is the single-process half of the supervision story (``ddlt train
    --max-restarts``); the cross-process half is the exit-code contract plus
    the control plane's resubmit loop.  ``fn`` must be restartable by
    construction — i.e. resume from its own checkpoints — or the loop just
    re-runs the failure.

    ``ledger_path`` is the goodput ledger's JSONL file (``obs/goodput.py``):
    when set, every restart appends a ``restart`` marker row from the
    SUPERVISOR's side — so the stitched ledger can cross-check that
    segments and restarts interleave (a segment the dying attempt failed
    to write is detectable, not silent) and charge the restart gap to the
    ``recovery`` category.

    Returns ``(result, restarts_used)``.  The final failure propagates.
    """
    restarts = 0
    while True:
        try:
            return fn(restarts), restarts
        except restart_on as exc:
            if restarts >= max_restarts:
                raise
            restarts += 1
            logger.warning(
                "restartable failure (%s: %s) — restart %d/%d from latest "
                "checkpoint", type(exc).__name__, exc, restarts, max_restarts,
            )
            if ledger_path:
                from distributeddeeplearning_tpu.obs import goodput

                goodput.append_row(ledger_path, {
                    "kind": "restart",
                    "ts": time.time(),
                    "attempt": restarts,
                    "error": type(exc).__name__,
                    "step": getattr(exc, "step", None),
                })
            if on_restart is not None:
                on_restart(restarts, exc)
