"""Training core: train state, LR schedules, jitted step functions, loops."""

from distributeddeeplearning_tpu.train.schedule import (
    goyal_lr_schedule,
    scale_base_lr,
)
from distributeddeeplearning_tpu.train.resilience import (
    RESUMABLE_EXIT_CODE,
    WATCHDOG_EXIT_CODE,
    AnomalyDetector,
    AnomalyError,
    PreemptionError,
    PreemptionGuard,
    RestartableError,
    StepWatchdog,
    supervise,
)
from distributeddeeplearning_tpu.train.state import TrainState, create_train_state
from distributeddeeplearning_tpu.train.step import (
    build_eval_step,
    build_train_step,
)

__all__ = [
    "goyal_lr_schedule",
    "scale_base_lr",
    "TrainState",
    "create_train_state",
    "build_train_step",
    "build_eval_step",
    "RESUMABLE_EXIT_CODE",
    "WATCHDOG_EXIT_CODE",
    "AnomalyDetector",
    "AnomalyError",
    "PreemptionError",
    "PreemptionGuard",
    "RestartableError",
    "StepWatchdog",
    "supervise",
]
