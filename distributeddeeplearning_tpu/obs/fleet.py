"""Fleet-scale observability: merge per-worker shards onto one timeline.

PR 6 built ``obs/`` for a single process; PR 7's fleet runs every engine
replica in its own worker process, where each replica's spans, histogram
buckets and counters used to die with the worker.  This module is the
router-side merge layer:

- **trace shards** — every worker exports a Chrome-trace shard with its
  own pid/process_name (:meth:`~.trace.Tracer.to_chrome_trace`);
  :func:`merge_fleet_trace` shifts each shard onto the ROUTER's clock
  via a clock-offset estimate (the same alignment idea as
  :func:`~.profile.merge_host_device`: two timelines, one shared
  reference — there the shared span name, here the shared wall clock /
  the ready-handshake estimate) and unions them into one
  ``fleet.trace.json`` where a failover reads left to right: admit →
  prefill → decode on the dying replica → ``fleet/replica_died`` →
  ``fleet/request_requeued`` → completion on the survivor, all under
  one trace id (:func:`failover_chains` / :func:`check_failover_chain`);

- **mergeable metrics** — workers ship full registry states (histogram
  BUCKETS, not percentile summaries) over the outbox;
  :func:`~.registry.merge_states` folds them bucket-wise so fleet-level
  TTFT/TPOT percentiles are computed from the merged sketch — exactly
  what one process recording every sample would report, which averaging
  per-replica percentiles never is (:func:`fleet_latency`);

- **SLOs** — :class:`SLOSpec` is the declarative service-level gate
  (TTFT p99, TPOT p99, error rate, zero lost requests) evaluated over
  the merged fleet metrics + the fleet report; ``ddlt obs fleet`` and
  ``bench.py --obs-fleet`` (the ``OBS_FLEET_*`` artifact) wire it.

:func:`observe_fleet` is the shared choreography both entry points call,
so the artifact and the CLI can never frame the same run differently.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
from typing import Any, Dict, List, Optional, Sequence

from distributeddeeplearning_tpu.obs.registry import MetricsRegistry

__all__ = [
    "SLOSpec",
    "merge_fleet_trace",
    "load_trace_shards",
    "failover_chains",
    "check_failover_chain",
    "fleet_latency",
    "fleet_latency_per_class",
    "parse_class_slos",
    "evaluate_class_slos",
    "observe_fleet",
]

#: fleet histogram names the SLO layer reads — the scheduler's end-of-run
#: rollup feeds these in every worker (obs/registry names are a contract);
#: per-priority-class splits ride the same names with a ``.<class>``
#: suffix (``serve.ttft_s.premium`` ...), fed per completion by the
#: scheduler's finish path
TTFT_HISTOGRAM = "serve.ttft_s"
TPOT_HISTOGRAM = "serve.tpot_s"


# -- trace shard merge -----------------------------------------------------


def load_trace_shards(trace_dir: str) -> List[Dict[str, Any]]:
    """Every worker shard under ``trace_dir`` (``replica*.trace.json``),
    parse order stable by filename.  Unreadable shards are skipped — a
    worker killed mid-write must not sink the merge of the survivors."""
    shards: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(os.path.join(trace_dir, "replica*.trace.json"))):
        try:
            with open(path) as f:
                shards.append(json.load(f))
        except (OSError, json.JSONDecodeError):
            continue
    return shards


def merge_fleet_trace(
    router_trace: Dict[str, Any],
    shards: Sequence[Dict[str, Any]],
    *,
    offsets_us: Optional[Dict[int, float]] = None,
) -> Dict[str, Any]:
    """One Chrome-trace container: router events + every worker shard,
    all on the ROUTER's clock.

    Per shard the clock offset is ``offsets_us[pid]`` when the caller
    measured one (the router's ready-handshake estimate), else the
    difference of the two tracers' wall-clock epochs
    (``metadata.tracer_epoch_unix_s`` — exact on one host, where every
    process shares the wall clock while ``perf_counter`` epochs differ).
    Shard pids keep their own process rows; a pid that collides with one
    already merged is remapped so two processes can never interleave
    into one track (the bug the derived-pid export fixed).
    """
    merged = {
        "traceEvents": list(router_trace.get("traceEvents", [])),
        "displayTimeUnit": "ms",
        "metadata": dict(router_trace.get("metadata", {})),
    }
    router_epoch = float(
        merged["metadata"].get("tracer_epoch_unix_s", 0.0)
    )
    host_pids = set(merged["metadata"].get("host_pids") or [])
    for ev in merged["traceEvents"]:
        if "pid" in ev:
            host_pids.add(ev["pid"])
    used_pids = set(host_pids)
    shard_meta: List[Dict[str, Any]] = []
    for shard in shards:
        meta = shard.get("metadata", {})
        shard_epoch = float(meta.get("tracer_epoch_unix_s", router_epoch))
        shard_pids = set(meta.get("host_pids") or [])
        for ev in shard.get("traceEvents", []):
            if "pid" in ev:
                shard_pids.add(ev["pid"])
        # handshake offset (keyed by the shard's primary pid) wins over
        # the epoch difference; both express "add this many µs to shard
        # timestamps to land them on the router clock"
        primary = (meta.get("host_pids") or sorted(shard_pids) or [None])[0]
        if offsets_us is not None and primary in offsets_us:
            offset = float(offsets_us[primary])
            offset_source = "handshake"
        else:
            offset = (shard_epoch - router_epoch) * 1e6
            offset_source = "epoch"
        # pid collision remap: keep every process on its own track
        remap: Dict[int, int] = {}
        for pid in sorted(shard_pids):
            if pid in used_pids:
                fresh = max(used_pids | set(remap.values())) + 1
                remap[pid] = fresh
            else:
                remap[pid] = pid
            used_pids.add(remap[pid])
        for ev in shard.get("traceEvents", []):
            ev = dict(ev)
            if "pid" in ev:
                ev["pid"] = remap.get(ev["pid"], ev["pid"])
            if "ts" in ev:
                ev["ts"] = float(ev["ts"]) + offset
            merged["traceEvents"].append(ev)
        mapped = sorted(remap.values())
        host_pids.update(mapped)
        shard_meta.append(
            {
                "process_name": meta.get("process_name"),
                "pids": mapped,
                "offset_us": round(offset, 1),
                "offset_source": offset_source,
            }
        )
    merged["metadata"]["host_pids"] = sorted(host_pids)
    merged["metadata"]["clock"] = "router perf_counter us"
    merged["metadata"]["shards"] = shard_meta
    return merged


# -- failover chains -------------------------------------------------------


def failover_chains(
    merged: Dict[str, Any],
    trace_ids: Optional[Sequence[str]] = None,
) -> Dict[str, List[Dict[str, Any]]]:
    """Group the merged timeline's events by trace id.

    An event belongs to trace ``T`` when its args carry ``trace == T``
    (per-request scheduler spans/events, router requeue/lost events) or
    ``T in args.trace_ids`` (replica-level events like
    ``fleet/replica_died``, which orphan several traces at once).
    Chains come back in router-clock order — which is what makes
    "the failover is visible end-to-end" checkable rather than vibes.
    """
    chains: Dict[str, List[Dict[str, Any]]] = {}
    wanted = set(trace_ids) if trace_ids is not None else None
    for ev in merged.get("traceEvents", []):
        if ev.get("ph") not in ("X", "i"):
            continue
        args = ev.get("args") or {}
        tids = set()
        tid = args.get("trace")
        if tid:
            tids.add(tid)
        for t in args.get("trace_ids") or []:
            tids.add(t)
        for t in tids:
            if wanted is not None and t not in wanted:
                continue
            chains.setdefault(t, []).append(
                {
                    "ts_ms": round(float(ev.get("ts", 0.0)) / 1e3, 3),
                    "name": str(ev.get("name")),
                    "pid": ev.get("pid"),
                    "replica": args.get("replica"),
                }
            )
    for chain in chains.values():
        chain.sort(key=lambda e: e["ts_ms"])
    return chains


def check_failover_chain(chain: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Is this the full failover story?  True when the (time-ordered)
    chain shows serving activity on one process, then the death, then the
    requeue, then a completion on a DIFFERENT process — the acceptance
    shape: admit → ... → ``replica_died`` → ``request_requeued`` →
    completion on the survivor, one trace id throughout."""
    names = [e["name"] for e in chain]
    died_i = names.index("fleet/replica_died") if "fleet/replica_died" in names else -1
    requeued_i = next(
        (
            i
            for i, n in enumerate(names)
            if n == "fleet/request_requeued" and i > died_i
        ),
        -1,
    )
    completes = [
        i for i, n in enumerate(names) if n == "serve/request_complete"
    ]
    served_before_death = (
        [
            e for e in chain[:died_i]
            if e["name"].startswith("serve/")
        ]
        if died_i >= 0
        else []
    )
    dead_pids = {e["pid"] for e in served_before_death}
    complete_i = completes[-1] if completes else -1
    completed_on = chain[complete_i]["pid"] if complete_i >= 0 else None
    ok = (
        died_i >= 0
        and requeued_i > died_i
        and complete_i > requeued_i
        and bool(served_before_death)
        and completed_on is not None
        and completed_on not in dead_pids
    )
    return {
        "ok": ok,
        "events": len(chain),
        "served_on_pid_before_death": sorted(dead_pids),
        "completed_on_pid": completed_on,
        "chain": list(chain),
    }


# -- merged metrics + SLO --------------------------------------------------


def fleet_latency(merged_registry: MetricsRegistry) -> Dict[str, Any]:
    """The fleet-level TTFT/TPOT percentile blocks, read from the
    bucket-merged histograms (never from averaged per-replica
    percentiles — a replica with 10x the traffic must weigh 10x)."""
    ttft = merged_registry.histogram(TTFT_HISTOGRAM)
    tpot = merged_registry.histogram(TPOT_HISTOGRAM)
    return {
        "ttft_s": ttft.summary(),
        "tpot_s": tpot.summary(),
        "ttft_samples": ttft.count,
        "tpot_samples": tpot.count,
    }


def fleet_latency_per_class(
    merged_registry: MetricsRegistry,
) -> Dict[str, Dict[str, Any]]:
    """Per-priority-class TTFT/TPOT blocks from the bucket-merged
    ``serve.ttft_s.<class>`` / ``serve.tpot_s.<class>`` histograms —
    the same never-average-percentiles rule as :func:`fleet_latency`,
    split by SLO class.  Classes are discovered from the metric names
    (a class no worker ever served simply isn't here)."""
    out: Dict[str, Dict[str, Any]] = {}
    ttft_prefix = TTFT_HISTOGRAM + "."
    tpot_prefix = TPOT_HISTOGRAM + "."
    for name, hist in merged_registry._histograms.items():
        if name.startswith(ttft_prefix):
            blk = out.setdefault(name[len(ttft_prefix):], {})
            blk["ttft_s"] = hist.summary()
            blk["ttft_samples"] = hist.count
        elif name.startswith(tpot_prefix):
            blk = out.setdefault(name[len(tpot_prefix):], {})
            blk["tpot_s"] = hist.summary()
            blk["tpot_samples"] = hist.count
    for blk in out.values():
        blk.setdefault("ttft_s", {})
        blk.setdefault("ttft_samples", 0)
        blk.setdefault("tpot_s", {})
        blk.setdefault("tpot_samples", 0)
    return out


@dataclasses.dataclass
class SLOSpec:
    """Declarative service-level objectives over the merged fleet view.

    ``None`` disables a latency criterion; error-rate and lost-request
    bounds always evaluate (the fleet exists to keep them at zero).
    Text form (CLI / bench flags)::

        ttft_p99_s=2.0,tpot_p99_s=0.5,max_error_rate=0,max_lost_requests=0
    """

    ttft_p99_s: Optional[float] = None
    tpot_p99_s: Optional[float] = None
    max_error_rate: float = 0.0
    max_lost_requests: int = 0

    @classmethod
    def parse(cls, text: str) -> "SLOSpec":
        kwargs: Dict[str, Any] = {}
        fields = {f.name for f in dataclasses.fields(cls)}
        for part in (text or "").split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"SLO entry {part!r} is not key=value")
            key, value = part.split("=", 1)
            key = key.strip()
            if key not in fields:
                raise ValueError(
                    f"unknown SLO key {key!r}; known: {sorted(fields)}"
                )
            kwargs[key] = (
                int(value) if key == "max_lost_requests" else float(value)
            )
        return cls(**kwargs)

    def describe(self) -> str:
        parts = []
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if v is not None:
                parts.append(f"{f.name}={v}")
        return ",".join(parts)

    def evaluate(
        self,
        *,
        fleet_report: Dict[str, Any],
        latency: Dict[str, Any],
    ) -> Dict[str, Any]:
        """Per-criterion ``{limit, actual, ok}`` plus the overall
        ``pass`` boolean — the block the OBS_FLEET artifact gates on."""
        criteria: Dict[str, Dict[str, Any]] = {}

        def add(name: str, limit, actual, ok: bool) -> None:
            criteria[name] = {
                "limit": limit,
                "actual": actual,
                "ok": bool(ok),
            }

        if self.ttft_p99_s is not None:
            actual = latency.get("ttft_s", {}).get("p99")
            add(
                "ttft_p99_s", self.ttft_p99_s, actual,
                actual is not None
                and latency.get("ttft_samples", 0) > 0
                and actual <= self.ttft_p99_s,
            )
        if self.tpot_p99_s is not None:
            actual = latency.get("tpot_s", {}).get("p99")
            add(
                "tpot_p99_s", self.tpot_p99_s, actual,
                actual is not None
                and latency.get("tpot_samples", 0) > 0
                and actual <= self.tpot_p99_s,
            )
        requests = int(fleet_report.get("requests", 0)) or 0
        errors = int(fleet_report.get("errors", 0))
        rate = errors / requests if requests else 0.0
        add(
            "max_error_rate", self.max_error_rate, round(rate, 6),
            rate <= self.max_error_rate,
        )
        lost = int(fleet_report.get("lost_requests", 0))
        add(
            "max_lost_requests", self.max_lost_requests, lost,
            lost <= self.max_lost_requests,
        )
        return {
            "spec": self.describe(),
            "criteria": criteria,
            "pass": all(c["ok"] for c in criteria.values()),
        }


def parse_class_slos(entries: Sequence[str]) -> Dict[str, "SLOSpec"]:
    """Parse repeated ``<class>:<key=value,...>`` flags (``ddlt obs
    fleet --slo-per-tenant``) into a class -> :class:`SLOSpec` map.
    Raises on a missing class prefix or a duplicate class — the CLI
    surfaces these at parse time, before any engine builds."""
    out: Dict[str, SLOSpec] = {}
    for entry in entries or []:
        cls, sep, spec_text = entry.partition(":")
        cls = cls.strip()
        if not sep or not cls or any(c.isspace() for c in cls):
            raise ValueError(
                f"per-tenant SLO {entry!r} is not <class>:<key=value,...>"
            )
        if cls in out:
            raise ValueError(f"duplicate per-tenant SLO for class {cls!r}")
        out[cls] = SLOSpec.parse(spec_text)
    return out


def evaluate_class_slos(
    class_slos: Dict[str, "SLOSpec"],
    *,
    fleet_report: Dict[str, Any],
    per_class_latency: Dict[str, Any],
) -> Dict[str, Any]:
    """Evaluate each class's spec against THAT class's bucket-merged
    latency and its slice of the fleet report's ``per_class`` block.
    ``lost_requests`` is fleet-global and charged to every evaluated
    class — a lost request is an SLO violation no matter whose it was.
    A class with an SLO but zero recorded samples FAILS its latency
    criteria (an SLO that cannot be demonstrated is not met)."""
    per: Dict[str, Any] = {}
    report_classes = fleet_report.get("per_class", {}) or {}
    empty = {
        "ttft_s": {}, "tpot_s": {}, "ttft_samples": 0, "tpot_samples": 0,
    }
    for cls, spec in sorted(class_slos.items()):
        blk = report_classes.get(cls, {})
        per[cls] = spec.evaluate(
            fleet_report={
                "requests": blk.get("requests", 0),
                "errors": blk.get("errors", 0),
                "lost_requests": fleet_report.get("lost_requests", 0),
            },
            latency=per_class_latency.get(cls, empty),
        )
    return {
        "per_class": per,
        "pass": all(r["pass"] for r in per.values()),
    }


# -- the shared choreography ----------------------------------------------


def observe_fleet(
    spec,
    requests,
    *,
    replicas: int = 2,
    trace_dir: str,
    faults: Optional[str] = None,
    slo: Optional[SLOSpec] = None,
    class_slos: Optional[Dict[str, SLOSpec]] = None,
    max_restarts: int = 1,
    max_redeliveries: int = 2,
    heartbeat_timeout_s: Optional[float] = None,
) -> Dict[str, Any]:
    """Run a fleet with distributed tracing on and assemble the merged
    view — ONE implementation for ``ddlt obs fleet`` and ``bench.py
    --obs-fleet``, so the artifact and the CLI cannot frame the run
    differently.

    Returns a dict with: ``results``/``fleet_report`` (router truth),
    ``merged_trace_path`` (``<trace_dir>/fleet.trace.json``),
    ``failover`` (per-trace-id chain checks for every requeued request),
    ``fleet_latency`` (bucket-merged TTFT/TPOT), ``per_replica_metrics``
    (the raw shipped states, for exact recomputation), ``slo`` (the
    evaluated spec) and ``flight_recorder_dumps``.
    """
    from distributeddeeplearning_tpu.obs import trace as trace_mod
    from distributeddeeplearning_tpu.obs.profile import summarize_timeline
    from distributeddeeplearning_tpu.serve.fleet import FleetRouter

    os.makedirs(trace_dir, exist_ok=True)
    # a REUSED trace dir (the CLI default is a persistent ./ddlt-obs)
    # still holds the previous run's shards — and trace ids restart at
    # tr0000 every run, so merging stale shards would stitch two
    # unrelated runs into the same chains.  This run's shards only.
    for stale in glob.glob(os.path.join(trace_dir, "replica*.trace.json")):
        os.remove(stale)
    spec = dataclasses.replace(spec, trace_dir=trace_dir)
    prior = trace_mod.get_tracer()
    tracer = trace_mod.set_tracer(
        trace_mod.Tracer(
            enabled=True, annotate=False, process_name="router",
            recorder=trace_mod.PROCESS_RECORDER,
        )
    )
    try:
        router = FleetRouter(
            spec,
            replicas=replicas,
            max_restarts=max_restarts,
            max_redeliveries=max_redeliveries,
            heartbeat_timeout_s=heartbeat_timeout_s,
            faults=faults,
        )
        results, report = router.serve(requests)
    finally:
        trace_mod.set_tracer(prior)

    merged = merge_fleet_trace(
        tracer.to_chrome_trace(),
        load_trace_shards(trace_dir),
        offsets_us=router.clock_offsets_us,
    )
    merged_path = os.path.join(trace_dir, "fleet.trace.json")
    with open(merged_path, "w") as f:
        json.dump(merged, f)
        f.write("\n")

    # failover evidence: one chain per requeued request, checked for the
    # admit -> death -> requeue -> completion-on-survivor shape
    requeued_tids = sorted(
        {
            (ev.get("args") or {}).get("trace")
            for ev in tracer.events
            if ev.get("name") == "fleet/request_requeued"
            and (ev.get("args") or {}).get("trace")
        }
    )
    # no requeues -> no chains to check; skip the full-timeline walk
    chains = (
        failover_chains(merged, requeued_tids) if requeued_tids else {}
    )
    failover = {
        tid: check_failover_chain(chain) for tid, chain in chains.items()
    }

    # the router already merged the shipped states bucket-wise (through
    # fleet_latency above) — read its answer instead of re-merging, so
    # there is exactly ONE computation the artifact can quote
    latency = report.fleet_latency
    slo_result = (
        slo.evaluate(fleet_report=report.to_dict(), latency=latency)
        if slo is not None
        else None
    )
    # per-tenant SLOs (PR 17): each class's spec against that class's
    # bucket-merged latency split — same single-computation rule, the
    # router's fleet_latency_per_class is the one source
    slo_per_tenant = (
        evaluate_class_slos(
            class_slos,
            fleet_report=report.to_dict(),
            per_class_latency=report.fleet_latency_per_class,
        )
        if class_slos
        else None
    )
    return {
        "results": results,
        "fleet_report": report,
        "merged_trace": merged,
        "merged_trace_path": merged_path,
        "timeline": summarize_timeline(merged),
        "failover": failover,
        "fleet_latency": latency,
        "fleet_latency_per_class": report.fleet_latency_per_class,
        "fleet_metrics": report.fleet_metrics,
        "per_replica_metrics": list(report.replica_metric_states),
        "slo": slo_result,
        "slo_per_tenant": slo_per_tenant,
        "flight_recorder_dumps": report.flight_recorder_dumps,
    }
